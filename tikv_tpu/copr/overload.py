"""Overload control plane: per-tenant quotas, adaptive admission, degradation.

The reference survives saturation because admission is explicit: a
``QuotaLimiter`` meters each user's reads, front-end flow control turns a
full scheduler into typed ``ServerIsBusy`` backpressure, and the read pool's
priority lanes are a *server-side* policy, not a client-declared free-for-all
(``src/read_pool.rs``, ``quota_limiter``).  This module is that policy tier
for the device serving plane (docs/robustness.md "Overload control plane"):

* **Tenant identity** — requests carry ``tenant`` in their context
  (:func:`tenant_of`; absent = the ``default`` tenant).  Every admission
  decision, priority clamp, and HBM partition keys on it.
* **Per-tenant token buckets** (:class:`QuotaLimiter`) — requests/s and
  read-bytes/s refill at configured rates (runtime-tunable through POST
  /config ``overload.*``).  Over-quota work is DEFERRED for a bounded wait
  when the bucket refills soon, else SHED as :class:`ServerBusyError` whose
  ``retry_after_s`` is the bucket's ACTUAL refill deficit — clients back off
  proportionally to how far over budget the tenant is, not by a constant.
  Read bytes are charged **post-serve** (response size is unknown at
  admission); the bucket then runs a deficit that defers/sheds the tenant's
  NEXT admissions — the GCRA-style debt shape.
* **Priority clamping** — a tenant's maximum lane is configuration
  (per-tenant ceiling, global default), never the client-declared
  ``priority``; demotions are counted.  The scheduler clamps even with
  overload disabled (``SchedulerConfig.max_priority``).
* **Adaptive admission** (:class:`AdaptiveController`) — samples queue
  depth, lane wait, and the observatory's per-(sig, path) p99 against its
  learned floor each window, and tightens/relaxes one ``scale`` factor in
  ``[min_scale, 1]``.  The scale multiplies every bucket's effective rate
  AND shrinks the scheduler's effective queue cap, turning the static
  ``busy_reject`` boolean into evidence-based shedding.  Every decision is
  counted (``tikv_overload_controller_total{action}``).
* **Memory-pressure degradation** — the region column cache partitions its
  byte budget per tenant (``RegionColumnCache.set_tenant_budgets``; the
  default tenant owns the remainder pool) and degrades an over-budget
  tenant down a ladder: evict ITS coldest images → demote ITS pins to host
  → CPU-fallback ITS device paths for a cooldown — never another tenant's
  warm set.  :meth:`OverloadControl.allow_device` is the serving-path gate.

Bounds: at most ``MAX_TENANTS`` live tenant states (LRU).  The limiter and
controller each own ONE leaf lock; nothing is called under them (the defer
sleep runs outside) — the module is in the lint's ``_SANITIZER_WIRED`` set.

Kill switch: ``OverloadConfig(enabled=False)`` (the default everywhere an
operator has not opted in) makes every admission a no-op.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..analysis.sanitizer import make_lock
from ..util.retry import ServerBusyError

DEFAULT_TENANT = "default"

#: lane order shared with the scheduler (high drains first); rank 0 is the
#: highest priority, so "clamp to ceiling" moves a lane DOWN the table
LANES = ("high", "normal", "low")
_LANE_RANK = {lane: i for i, lane in enumerate(LANES)}

MAX_TENANTS = 64
#: floor under every busy hint: a zero retry_after would collapse the
#: client's hint-dominated backoff to its raw curve (docs/robustness.md)
MIN_RETRY_AFTER_S = 0.001


def tenant_of(context) -> str:
    """The request's tenant identity (``context["tenant"]``; default
    tenant otherwise).  Values are stringified — metric labels and dict
    keys must be stable."""
    t = (context or {}).get("tenant")
    return str(t) if t else DEFAULT_TENANT


def clamp_lane(lane: str, ceiling: str | None) -> str:
    """The effective lane under a ceiling: a request may always ask for a
    LOWER priority than its ceiling, never a higher one."""
    if ceiling is None or ceiling not in _LANE_RANK:
        return lane
    if _LANE_RANK.get(lane, 1) < _LANE_RANK[ceiling]:
        return ceiling
    return lane


def count_demotion(tenant: str, lane: str) -> None:
    """One client-declared priority clamped down to its ceiling."""
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_overload_demote_total",
        "Client-declared priorities clamped to the configured ceiling, "
        "by tenant and effective lane",
    ).inc(tenant=tenant, lane=lane)


@dataclass
class TenantQuota:
    """One tenant's budget.  Rate 0 = unlimited for that resource."""

    requests_per_s: float = 0.0
    read_bytes_per_s: float = 0.0
    #: bucket capacity = rate * burst_s (at least one token): how much a
    #: tenant may burst above its steady rate after an idle period
    burst_s: float = 1.0
    #: per-tenant lane ceiling; None inherits the global default
    max_priority: str | None = None


@dataclass
class OverloadConfig:
    """The control plane's knobs (POST /config ``overload.*`` reconfigures
    the scalar ones online; per-tenant quotas via :meth:`set_quota`)."""

    enabled: bool = True
    default_quota: TenantQuota = field(default_factory=TenantQuota)
    tenants: dict = field(default_factory=dict)  # tenant -> TenantQuota
    #: global lane ceiling for client-declared priorities ("high" = allow)
    max_priority: str = "high"
    #: bounded defer: over-quota work whose bucket refills within this wait
    #: sleeps instead of shedding (the reference's front-end flow control
    #: smooths short bursts the same way)
    max_wait_s: float = 0.02
    adaptive: bool = True
    window_s: float = 1.0
    min_scale: float = 0.1
    #: queue-fullness fractions the controller tightens/relaxes at
    queue_high_frac: float = 0.75
    queue_low_frac: float = 0.25
    #: observatory evidence: a profiled p99 this multiple over its learned
    #: floor is pressure (docs/observatory.md)
    p99_ratio: float = 3.0
    #: cost-router evidence (docs/cost_router.md): chosen-vs-best path
    #: deltas summing past this fraction of the best-path cost in one
    #: window is pressure — serving is persistently off its cheapest path
    route_waste_ratio: float = 0.5
    #: per-tenant HBM partition byte budgets pushed onto the region cache
    tenant_hbm_budgets: dict = field(default_factory=dict)


class _Bucket:
    """Token bucket holding only its level; rates come from the quota at
    every call, so runtime rate changes apply without bucket surgery."""

    __slots__ = ("level", "last", "primed")

    def __init__(self):
        self.level = 0.0
        self.last = 0.0
        self.primed = False

    def _refill(self, rate: float, burst_s: float, now: float) -> None:
        cap = max(rate * burst_s, 1.0)
        if not self.primed:
            # first sight of this bucket: a fresh tenant starts with its
            # full burst allowance, not an empty bucket
            self.level = cap
            self.primed = True
        else:
            self.level = min(cap, self.level + (now - self.last) * rate)
        self.last = now

    def take(self, rate: float, burst_s: float, n: float, now: float) -> float:
        """0.0 = admitted (``n`` tokens debited); else seconds until the
        bucket holds ``n`` tokens at the CURRENT rate — the actual refill
        deficit, which is exactly the honest ``retry_after_s`` hint."""
        if rate <= 0:
            return 0.0  # unlimited resource
        self._refill(rate, burst_s, now)
        if self.level >= n:
            self.level -= n
            return 0.0
        return (n - self.level) / rate

    def charge(self, rate: float, burst_s: float, n: float, now: float) -> None:
        """Post-serve debit (read bytes): the level may go NEGATIVE — the
        debt surfaces as a deficit on the tenant's next admission."""
        if rate <= 0 or n <= 0:
            return
        self._refill(rate, burst_s, now)
        self.level -= n


class _TenantState:
    __slots__ = ("req", "nbytes", "admitted", "deferred", "shed")

    def __init__(self):
        self.req = _Bucket()
        self.nbytes = _Bucket()
        self.admitted = 0
        self.deferred = 0
        self.shed = 0


class QuotaLimiter:
    """Per-tenant token buckets over one leaf lock.  ``probe`` answers in
    refill-deficit seconds; the facade (:class:`OverloadControl`) turns a
    deficit into a bounded defer or a typed shed."""

    def __init__(self, config: OverloadConfig, clock=time.monotonic):
        self.cfg = config
        self.clock = clock
        self._mu = make_lock("copr.overload")
        self._tenants: dict[str, _TenantState] = {}

    def quota_for(self, tenant: str) -> TenantQuota:
        return self.cfg.tenants.get(tenant, self.cfg.default_quota)

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        """Runtime per-tenant override (the POST /config scalars retune the
        DEFAULT quota; named tenants are set here or at construction)."""
        with self._mu:
            self.cfg.tenants[tenant] = quota

    def lane_ceiling(self, tenant: str) -> str:
        q = self.quota_for(tenant)
        return q.max_priority or self.cfg.max_priority

    def probe(self, tenant: str, scale: float = 1.0) -> float:
        """One request admission attempt: 0.0 = admitted, else the refill
        deficit in seconds.  The BYTE bucket is probed first with n=0 (a
        post-serve debt defers before it costs a request token); only an
        admitted probe debits the request bucket."""
        q = self.quota_for(tenant)
        now = self.clock()
        with self._mu:
            st = self._state_locked(tenant)
            wait = st.nbytes.take(q.read_bytes_per_s * scale, q.burst_s, 0.0, now)
            if wait > 0:
                return wait
            return st.req.take(q.requests_per_s * scale, q.burst_s, 1.0, now)

    def charge_bytes(self, tenant: str, n: int, scale: float = 1.0) -> None:
        if n <= 0:
            return
        q = self.quota_for(tenant)
        if q.read_bytes_per_s <= 0:
            return
        now = self.clock()
        with self._mu:
            self._state_locked(tenant).nbytes.charge(
                q.read_bytes_per_s * scale, q.burst_s, float(n), now)

    def note(self, tenant: str, outcome: str) -> None:
        with self._mu:
            st = self._state_locked(tenant)
            if outcome == "admit":
                st.admitted += 1
            elif outcome == "defer":
                st.deferred += 1
            else:
                st.shed += 1

    def _state_locked(self, tenant: str) -> _TenantState:
        st = self._tenants.pop(tenant, None)
        if st is None:
            st = _TenantState()
            while len(self._tenants) >= MAX_TENANTS:
                self._tenants.pop(next(iter(self._tenants)))
        self._tenants[tenant] = st  # reinsert = LRU touch
        return st

    def snapshot(self, scale: float = 1.0) -> dict:
        """Per-tenant bucket levels, effective rates, and admission counts
        (``/debug/overload``, ``ctl.py overload``).  Gauges the bucket
        levels as it goes — the debug surface doubles as the heartbeat."""
        from ..util.metrics import REGISTRY

        level_g = REGISTRY.gauge(
            "tikv_overload_bucket_level",
            "Current token-bucket level, by tenant and resource",
        )
        out = {}
        now = self.clock()
        with self._mu:
            for tenant, st in self._tenants.items():
                q = self.quota_for(tenant)
                # refill-to-now so the reported level is current, not the
                # level at the tenant's last admission
                if q.requests_per_s > 0:
                    st.req._refill(q.requests_per_s * scale, q.burst_s, now)
                if q.read_bytes_per_s > 0:
                    st.nbytes._refill(q.read_bytes_per_s * scale, q.burst_s, now)
                out[tenant] = {
                    "requests_per_s": q.requests_per_s,
                    "read_bytes_per_s": q.read_bytes_per_s,
                    "effective_requests_per_s": round(q.requests_per_s * scale, 3),
                    "effective_read_bytes_per_s": round(
                        q.read_bytes_per_s * scale, 3),
                    "max_priority": q.max_priority or self.cfg.max_priority,
                    "request_tokens": round(st.req.level, 3),
                    "byte_tokens": round(st.nbytes.level, 3),
                    "admitted": st.admitted,
                    "deferred": st.deferred,
                    "shed": st.shed,
                }
                level_g.set(st.req.level, tenant=tenant, resource="requests")
                level_g.set(st.nbytes.level, tenant=tenant, resource="bytes")
        return out


class AdaptiveController:
    """Evidence-based admission tightening (docs/robustness.md).

    Each ``window_s`` the controller folds three signals — mean queue
    fullness, worst sampled lane wait, and the observatory's per-(sig,
    path) p99 against the lowest p99 it has ever seen for that key (the
    learned floor) — into one decision: ``tighten`` halves the scale,
    ``relax`` grows it back toward 1.0, ``hold`` leaves it.  The scale
    multiplies every bucket's effective rate and shrinks the scheduler's
    effective queue cap (:meth:`queue_cap`), so shedding starts when the
    evidence says the store is saturated, not when a static boolean does."""

    def __init__(self, config: OverloadConfig, clock=time.monotonic):
        self.cfg = config
        self.clock = clock
        self._mu = make_lock("copr.overload.controller")
        self.scale = 1.0
        self._q: list[float] = []
        self._w: list[float] = []
        self._last_tick = clock()
        # (sig, path, encoding) -> lowest p99_ms ever profiled: the floor
        # current windows are judged against
        self._p99_floor: dict[tuple, float] = {}
        # cost-router chosen-vs-best evidence accumulated this window:
        # [delta_ms sum, best_ms sum, samples] (docs/cost_router.md)
        self._route = [0.0, 0.0, 0]
        self.actions = {"tighten": 0, "relax": 0, "hold": 0}
        self.last_evidence: dict = {}

    def note_queue(self, depth: int, cap: int) -> None:
        now = self.clock()
        with self._mu:
            self._q.append(depth / max(cap, 1))
            if len(self._q) > 4096:
                del self._q[:-2048]
            due = now - self._last_tick >= self.cfg.window_s
            if due:
                self._last_tick = now
        if due:
            self._tick()

    def note_wait(self, wait_s: float) -> None:
        with self._mu:
            self._w.append(wait_s)
            if len(self._w) > 4096:
                del self._w[:-2048]

    def note_route_delta(self, delta_ms: float, best_ms: float | None) -> None:
        """One cost-router decision's chosen-vs-best gap: overload
        tightening and path choice share evidence — persistent routing
        waste reads as saturation just like tail latency does."""
        with self._mu:
            self._route[0] += max(delta_ms, 0.0)
            self._route[1] += max(best_ms or 0.0, 0.0)
            self._route[2] += 1

    def queue_cap(self, cap: int) -> int:
        """The scheduler's EFFECTIVE queue threshold under pressure: the
        configured cap scaled down with the bucket rates, so backpressure
        starts before the hard queue bound."""
        if self.scale >= 1.0:
            return cap
        return max(1, int(cap * self.scale))

    @property
    def pressure(self) -> bool:
        return self.scale < 1.0

    def _tick(self) -> None:
        # observatory read OUTSIDE the controller lock (its lock is a leaf
        # of its own; nesting ours over it would be fine, but not needed)
        p99_bad, p99_detail = self._obs_pressure()
        with self._mu:
            q, self._q = self._q, []
            w, self._w = self._w, []
            rt, self._route = self._route, [0.0, 0.0, 0]
            q_frac = sum(q) / len(q) if q else 0.0
            wait_bad = bool(w) and max(w) > max(self.cfg.max_wait_s, 0.01) * 4
            # route waste alone signals "wrong path", not saturation — it
            # only contributes evidence when queues back it up, vetoing the
            # relax branch instead of forcing a tighten
            route_bad = (rt[2] >= 8 and rt[1] > 0
                         and rt[0] > self.cfg.route_waste_ratio * rt[1])
            if q_frac >= self.cfg.queue_high_frac or wait_bad or p99_bad:
                action = "tighten"
                self.scale = max(self.cfg.min_scale, self.scale * 0.5)
            elif (q_frac <= self.cfg.queue_low_frac and not p99_bad
                    and not route_bad):
                action = "relax" if self.scale < 1.0 else "hold"
                self.scale = min(1.0, max(self.scale * 1.5, self.scale + 0.05))
            else:
                action = "hold"
            self.actions[action] += 1
            self.last_evidence = {
                "queue_frac": round(q_frac, 3),
                "queue_samples": len(q),
                "wait_pressure": wait_bad,
                "p99_pressure": p99_bad,
                "p99_detail": p99_detail,
                "route_pressure": route_bad,
                "route_waste": (round(rt[0] / rt[1], 3) if rt[1] else 0.0),
                "route_samples": rt[2],
                "scale": round(self.scale, 3),
            }
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_overload_controller_total",
            "Adaptive admission controller decisions, by action",
        ).inc(action=action)
        REGISTRY.gauge(
            "tikv_overload_effective_scale",
            "Adaptive scale applied to bucket rates and the queue cap",
        ).set(self.scale)

    def _obs_pressure(self) -> tuple[bool, dict | None]:
        """Observatory p99-vs-floor evidence: the floor is the lowest p99
        this controller has seen for a (sig, path, encoding); a current
        p99 more than ``p99_ratio`` over it is saturation showing up in
        tail latency (docs/observatory.md)."""
        from . import observatory as _obs

        if not _obs.OBSERVATORY.enabled:
            return False, None
        try:
            rows = _obs.OBSERVATORY.top(8)
        except Exception:  # noqa: BLE001 — evidence, not a dependency
            return False, None
        worst = None
        with self._mu:
            for r in rows:
                if r.get("count", 0) < 8 or not r.get("p99_ms"):
                    continue
                key = (r["sig"], r["path"], r["encoding"])
                floor = self._p99_floor.get(key)
                if floor is None or r["p99_ms"] < floor:
                    if floor is None and len(self._p99_floor) >= MAX_TENANTS:
                        self._p99_floor.pop(next(iter(self._p99_floor)))
                    self._p99_floor[key] = r["p99_ms"]
                elif r["p99_ms"] > self.cfg.p99_ratio * floor:
                    worst = {"sig": r["sig"], "path": r["path"],
                             "p99_ms": r["p99_ms"], "floor_ms": floor}
                    break
        return worst is not None, worst

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "scale": round(self.scale, 3),
                "actions": dict(self.actions),
                "last_evidence": dict(self.last_evidence),
                "p99_floors": {
                    "|".join(map(str, k)): v
                    for k, v in self._p99_floor.items()
                },
            }


class OverloadControl:
    """The facade the serving plane consults: one per endpoint/store,
    wired into the scheduler's admission, the service's read entries, and
    the region cache's tenant partitions."""

    def __init__(self, config: OverloadConfig | None = None,
                 region_cache=None, clock=time.monotonic, sleep=time.sleep):
        self.cfg = config or OverloadConfig()
        self.clock = clock
        self._sleep = sleep
        self.limiter = QuotaLimiter(self.cfg, clock=clock)
        self.controller = AdaptiveController(self.cfg, clock=clock)
        self.region_cache = region_cache
        if region_cache is not None and self.cfg.tenant_hbm_budgets:
            region_cache.set_tenant_budgets(dict(self.cfg.tenant_hbm_budgets))

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def scale(self) -> float:
        return self.controller.scale if self.cfg.adaptive else 1.0

    # -- admission ----------------------------------------------------------

    def admit(self, context: dict | None, *, where: str = "copr",
              wait: bool = True) -> str:
        """Gate one request.  Admitted → returns the tenant (stamping an
        idempotence marker so nested serving layers charge ONE token, not
        one per layer).  Over quota → bounded defer when the bucket refills
        within ``max_wait_s`` (and the request's own deadline), else a
        typed :class:`ServerBusyError` whose ``retry_after_s`` is the
        bucket's actual refill deficit."""
        tenant = tenant_of(context)
        if not self.cfg.enabled:
            return tenant
        if isinstance(context, dict) and context.get("_overload_admitted"):
            # a nested serving layer (service -> scheduler) already charged
            # this request exactly one token
            return tenant
        wait_s = self.limiter.probe(tenant, self.scale())
        if wait_s <= 0:
            self._count(tenant, "admit", where)
            self._stamp(context)
            return tenant
        if wait and wait_s <= self.cfg.max_wait_s \
                and self._deadline_allows(context, wait_s):
            # bounded defer: the bucket refills within the wait budget —
            # smooth the burst instead of bouncing it to the client
            self._count(tenant, "defer", where)
            self.limiter.note(tenant, "defer")
            self._sleep(wait_s)
            wait_s = self.limiter.probe(tenant, self.scale())
            if wait_s <= 0:
                self._stamp(context)
                return tenant
            # racing callers drained the refill: fall through to shed with
            # the NEW deficit (still the honest hint)
        self._count(tenant, "shed", where)
        self.limiter.note(tenant, "shed")
        raise ServerBusyError(
            f"tenant {tenant!r} over quota",
            retry_after_s=max(wait_s, MIN_RETRY_AFTER_S),
        )

    @staticmethod
    def _stamp(context) -> None:
        """Admission idempotence marker: stamped only on SUCCESS, so a
        shed request retried with the same context dict is re-gated."""
        if isinstance(context, dict):
            context["_overload_admitted"] = True

    def note_bytes(self, context: dict | None, nbytes: int) -> None:
        """Post-serve read-byte charge: debits the tenant's byte bucket
        (possibly into debt — the deficit gates its next admission)."""
        if not self.cfg.enabled or nbytes <= 0:
            return
        self.limiter.charge_bytes(tenant_of(context), nbytes, self.scale())

    def _deadline_allows(self, context, wait_s: float) -> bool:
        from ..util.retry import deadline_from_context

        dl = deadline_from_context(context)
        return dl is None or time.monotonic() + wait_s < dl

    def _count(self, tenant: str, outcome: str, where: str) -> None:
        from ..util.metrics import REGISTRY

        if outcome == "admit":
            self.limiter.note(tenant, "admit")
        REGISTRY.counter(
            "tikv_overload_admission_total",
            "Per-tenant quota admission outcomes, by entry point",
        ).inc(tenant=tenant, outcome=outcome, where=where)

    # -- priority clamping ----------------------------------------------------

    def lane_ceiling(self, context: dict | None) -> str | None:
        """The tenant's lane ceiling, or None when overload is disabled
        (the scheduler's global ``max_priority`` still applies then)."""
        if not self.cfg.enabled:
            return None
        return self.limiter.lane_ceiling(tenant_of(context))

    # -- memory-pressure ladder ----------------------------------------------

    def allow_device(self, context: dict | None) -> bool:
        """False while the tenant sits on the degradation ladder's last
        rung (CPU fallback): its HBM partition could not be brought under
        budget by eviction or pin demotion (region_cache.py)."""
        if not self.cfg.enabled or self.region_cache is None:
            return True
        return self.region_cache.device_allowed(tenant_of(context))

    # -- scheduler feedback ----------------------------------------------------

    def note_queue(self, depth: int, cap: int) -> None:
        if self.cfg.enabled and self.cfg.adaptive:
            self.controller.note_queue(depth, cap)

    def note_wait(self, wait_s: float) -> None:
        if self.cfg.enabled and self.cfg.adaptive:
            self.controller.note_wait(wait_s)

    def note_route_delta(self, delta_ms: float, best_ms: float | None) -> None:
        if self.cfg.enabled and self.cfg.adaptive:
            self.controller.note_route_delta(delta_ms, best_ms)

    def queue_cap(self, cap: int) -> int:
        if self.cfg.enabled and self.cfg.adaptive:
            return self.controller.queue_cap(cap)
        return cap

    def pressure_reject(self) -> bool:
        """True when the controller's evidence says shed-with-hint beats
        direct-path serving — the adaptive replacement for the static
        ``busy_reject`` boolean."""
        return self.cfg.enabled and self.cfg.adaptive and self.controller.pressure

    # -- ops ------------------------------------------------------------------

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        self.limiter.set_quota(tenant, quota)

    def reconfigure(self, changed: dict) -> None:
        """Online reconfig (POST /config ``overload.*`` via the
        ConfigController): scalar knobs land here; the default quota's
        rates retune live because buckets read rates per call."""
        dq = self.cfg.default_quota
        for key, value in changed.items():
            if key == "requests_per_s":
                dq.requests_per_s = float(value)
            elif key == "read_bytes_per_s":
                dq.read_bytes_per_s = float(value)
            elif key == "burst_s":
                dq.burst_s = float(value)
            elif key == "enabled":
                self.cfg.enabled = bool(value)
            elif key == "max_wait_s":
                self.cfg.max_wait_s = float(value)
            elif key == "max_priority":
                self.cfg.max_priority = str(value)
            elif key == "adaptive":
                self.cfg.adaptive = bool(value)
            elif key == "min_scale":
                self.cfg.min_scale = float(value)
            elif key == "window_s":
                self.cfg.window_s = float(value)
            elif key == "route_waste_ratio":
                self.cfg.route_waste_ratio = float(value)

    def snapshot(self) -> dict:
        """The ``/debug/overload`` + ``ctl.py overload`` view: per-tenant
        bucket levels and effective rates, shed/defer counts, controller
        state, and HBM partition occupancy."""
        out = {
            "enabled": self.cfg.enabled,
            "adaptive": self.cfg.adaptive,
            "max_wait_s": self.cfg.max_wait_s,
            "max_priority": self.cfg.max_priority,
            "scale": round(self.scale(), 3),
            "tenants": self.limiter.snapshot(self.scale()),
            "controller": self.controller.snapshot(),
        }
        if self.region_cache is not None:
            out["hbm"] = self.region_cache.tenant_occupancy()
        return out
