"""Cost-based path router + online geometry auto-tuner (docs/cost_router.md).

The serving plane has six execution paths (zone full-tile, unary encoded,
fused, xregion-cached, mesh-sharded, CPU fallback) and, since PR 13, a
performance observatory that measures what each path actually costs per
plan signature.  This module closes the loop:

* :class:`CostRouter` — per plan signature, pick the cheapest *eligible*
  path from the observatory's measured profiles (windowed mean latency
  plus compile-ledger amortization) instead of the static rule ladder.
  An explore/exploit guard keeps the profiles honest: a bounded epsilon
  re-probes warm non-best paths, and cold eligible paths are probed at a
  budgeted rate so no path starves and new shapes still get measured.
  When profiles are cold the router falls back to the static order — the
  candidate list callers pass is already in today's ladder order, so a
  cold router IS the old behavior.  Kill switch:
  ``TIKV_TPU_COST_ROUTER=0`` (or ``--no-cost-router``) routes every
  decision to the static head with reason ``kill_switch``.

* :class:`GeometryTuner` — periodically proposes geometry changes
  (``block_rows``, per-lane ``max_wait_s``) from the same measured
  profiles: hill-climb within validated bounds, ONE change in flight at
  a time, judged against the pre-change throughput baseline
  (``Observatory.totals`` deltas — robust to window aging), with
  automatic revert when the change regresses below
  ``revert_ratio`` x baseline.  Changes apply through the same validated
  setters POST /config uses, so out-of-range proposals are rejected, not
  applied.

Every decision is observable: ``tikv_coprocessor_cost_route_total
{path,reason}``, ``tikv_coprocessor_cost_route_delta_ms_total`` (chosen
minus best measured cost — also fed to PR 15's ``AdaptiveController`` so
overload tightening and path choice share evidence),
``tikv_coprocessor_geometry_tune_total{knob,action}``, per-sig decision
records in the observatory, and ``GET /debug/cost_router``.

Locking: ONE leaf lock owned by this module guards the rng / rotation
sequence / decision ring; observatory queries and metric increments
happen outside it (sanitizer-verified, module is in
``_SANITIZER_WIRED``).
"""

from __future__ import annotations

import os
import random
import time

from ..analysis.sanitizer import make_lock
from ..util.metrics import REGISTRY
from .observatory import OBSERVATORY

__all__ = [
    "CostRouter",
    "Decision",
    "GeometryTuner",
    "RouterConfig",
    "TunerConfig",
]

_DECISION_RING = 64
_HISTORY_RING = 32

ROUTE_REASONS = ("measured", "explore", "cold", "static_fallback",
                 "kill_switch")


def _enabled_env() -> bool:
    return os.environ.get("TIKV_TPU_COST_ROUTER", "1") not in ("0", "off", "")


class RouterConfig:
    """Explore/exploit knobs.  ``epsilon`` bounds the share of decisions
    that deliberately pick a warm non-best path; ``cold_probe_rate``
    budgets probes of eligible paths with no warm profile yet;
    ``min_count`` is the windowed serve count below which a profile is
    considered cold; ``compile_amortize_floor`` is the minimum serve count
    the compile ledger's wall time is spread over when pricing a path (a
    freshly compiled path must not price above the interpreter forever
    just because traffic hasn't amortized its one-time compile yet)."""

    __slots__ = ("epsilon", "cold_probe_rate", "min_count", "seed",
                 "compile_amortize_floor")

    def __init__(self, epsilon: float = 0.05, cold_probe_rate: float = 0.02,
                 min_count: int = 5, seed: int | None = None,
                 compile_amortize_floor: int = 64):
        if not 0.0 <= epsilon <= 0.5:
            raise ValueError("costmodel.epsilon must be in [0, 0.5]")
        if not 0.0 <= cold_probe_rate <= 0.5:
            raise ValueError("costmodel.cold_probe_rate must be in [0, 0.5]")
        if min_count < 1:
            raise ValueError("costmodel.min_count must be >= 1")
        if compile_amortize_floor < 1:
            raise ValueError("costmodel.compile_amortize_floor must be >= 1")
        self.epsilon = epsilon
        self.cold_probe_rate = cold_probe_rate
        self.min_count = min_count
        self.seed = seed
        self.compile_amortize_floor = compile_amortize_floor


class Decision:
    """One routing decision: the chosen path, why it won, and the cost
    table it was judged against (``delta_ms`` = chosen minus best measured
    cost; ``None`` when the chosen path has no warm profile yet)."""

    __slots__ = ("path", "reason", "cost_ms", "best_ms", "delta_ms")

    def __init__(self, path: str, reason: str, cost_ms: float | None = None,
                 best_ms: float | None = None):
        self.path = path
        self.reason = reason
        self.cost_ms = cost_ms
        self.best_ms = best_ms
        self.delta_ms = (round(cost_ms - best_ms, 4)
                         if cost_ms is not None and best_ms is not None
                         else None)

    def as_dict(self) -> dict:
        return {"path": self.path, "reason": self.reason,
                "cost_ms": self.cost_ms, "best_ms": self.best_ms,
                "delta_ms": self.delta_ms}


class CostRouter:
    """Pick the cheapest eligible path per plan signature from measured
    profiles, with bounded exploration and strict static fallback."""

    def __init__(self, observatory=None, config: RouterConfig | None = None,
                 enabled: bool | None = None, delta_sink=None):
        self.obs = observatory if observatory is not None else OBSERVATORY
        self.cfg = config or RouterConfig()
        self.enabled = _enabled_env() if enabled is None else enabled
        # chosen-vs-best deltas feed the overload AdaptiveController
        # (PR 15) so path waste and queue pressure share evidence
        self.delta_sink = delta_sink
        # LEAF lock: guards rng / rotation counters / rings only — the
        # observatory query and every metric increment happen outside it
        self._mu = make_lock("copr.costmodel")
        self._rng = random.Random(self.cfg.seed)
        self._seq: dict[str, int] = {}  # sig -> probe rotation counter
        self._recent: list[dict] = []
        self._reasons = dict.fromkeys(ROUTE_REASONS, 0)
        self._started = time.monotonic()

    def route(self, sig: str, candidates: list[str], *, desc: str = "",
              costs: dict[str, dict] | None = None) -> Decision:
        """Route one request.  ``candidates`` MUST be in static-ladder
        order (head = what today's rules would pick); ``costs`` overrides
        the observatory's ``path_costs`` view — the scheduler passes a
        synthetic table when weighing batch vs per-request execution."""
        if not candidates:
            raise ValueError("route() needs at least one candidate path")
        if not self.enabled:
            d = Decision(candidates[0], "kill_switch")
            self._note(sig, d, desc)
            return d
        table = (costs if costs is not None
                 else self.obs.path_costs(
                     sig, amortize_floor=self.cfg.compile_amortize_floor))
        warm = {p: c for p, c in table.items()
                if p in candidates and c.get("count", 0) >= self.cfg.min_count}
        cold = [p for p in candidates if p not in warm]
        if not warm:
            d = Decision(candidates[0], "static_fallback")
            self._note(sig, d, desc)
            return d
        best = min(warm, key=lambda p: warm[p]["cost_ms"])
        best_ms = warm[best]["cost_ms"]
        others = sorted(set(warm) - {best})
        with self._mu:
            r = self._rng.random()
            seq = self._seq[sig] = self._seq.get(sig, -1) + 1
            if len(self._seq) > 4 * _DECISION_RING:
                self._seq.pop(next(iter(self._seq)))
        p_cold = self.cfg.cold_probe_rate if cold else 0.0
        if r < p_cold:
            path = cold[seq % len(cold)]
            d = Decision(path, "cold", None, best_ms)
        elif others and r < p_cold + self.cfg.epsilon:
            path = others[seq % len(others)]
            d = Decision(path, "explore", warm[path]["cost_ms"], best_ms)
        else:
            d = Decision(best, "measured", best_ms, best_ms)
        self._note(sig, d, desc)
        return d

    def _note(self, sig: str, d: Decision, desc: str) -> None:
        with self._mu:
            self._reasons[d.reason] = self._reasons.get(d.reason, 0) + 1
            self._recent.append({"sig": sig, **d.as_dict()})
            if len(self._recent) > _DECISION_RING:
                del self._recent[: len(self._recent) - _DECISION_RING]
        REGISTRY.counter(
            "tikv_coprocessor_cost_route_total",
            "Cost-router path decisions, by chosen path and reason",
        ).inc(path=d.path, reason=d.reason)
        if d.delta_ms is not None and d.delta_ms > 0:
            REGISTRY.counter(
                "tikv_coprocessor_cost_route_delta_ms_total",
                "Chosen-vs-best measured cost gap across route decisions (ms)",
            ).inc(d.delta_ms)
        if sig:
            self.obs.record_route(sig, d.path, d.reason, desc=desc)
        if self.delta_sink is not None and d.delta_ms is not None:
            try:
                self.delta_sink(d.delta_ms, d.best_ms)
            except Exception:  # noqa: BLE001 — evidence feed is best-effort
                pass

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "epsilon": self.cfg.epsilon,
                "cold_probe_rate": self.cfg.cold_probe_rate,
                "min_count": self.cfg.min_count,
                "uptime_s": round(time.monotonic() - self._started, 1),
                "decisions_by_reason": dict(self._reasons),
                "recent": list(self._recent),
            }


class TunerConfig:
    """Geometry auto-tuning knobs.  ``min_serves`` is how many serves the
    in-flight change must accumulate before judging; ``revert_ratio`` is
    the throughput floor — measured rate below ``revert_ratio`` x the
    pre-change baseline triggers automatic revert; ``warmup_ticks`` ticks
    after a change are DISCARDED before measurement starts (a block_rows
    change invalidates warm images, so the first window pays rebuild +
    recompile — judging that transient would revert every good move);
    ``settle_ticks`` bounds how long a change may sit unjudged after
    warmup before it is abandoned (kept) for lack of traffic."""

    __slots__ = ("min_serves", "revert_ratio", "settle_ticks", "warmup_ticks")

    def __init__(self, min_serves: int = 16, revert_ratio: float = 0.7,
                 settle_ticks: int = 4, warmup_ticks: int = 1):
        if min_serves < 1:
            raise ValueError("tuner.min_serves must be >= 1")
        if not 0.0 < revert_ratio < 1.0:
            raise ValueError("tuner.revert_ratio must be in (0, 1)")
        if settle_ticks < 1:
            raise ValueError("tuner.settle_ticks must be >= 1")
        if warmup_ticks < 0:
            raise ValueError("tuner.warmup_ticks must be >= 0")
        self.min_serves = min_serves
        self.revert_ratio = revert_ratio
        self.settle_ticks = settle_ticks
        self.warmup_ticks = warmup_ticks


class _Knob:
    __slots__ = ("name", "get", "apply", "lo", "hi", "direction", "integer")

    def __init__(self, name, get, apply, lo, hi, integer):
        self.name = name
        self.get = get
        self.apply = apply
        self.lo = lo
        self.hi = hi
        # hill-climb direction: -1 halves, +1 doubles; flipped on revert
        # or when a proposal would leave the validated bounds
        self.direction = -1
        self.integer = integer

    def propose(self, cur):
        for _ in range(2):  # current direction, then the flip
            new = cur * 2 if self.direction > 0 else cur / 2
            if self.integer:
                new = int(new)
            if self.lo <= new <= self.hi:
                return new
            self.direction = -self.direction
        return None


class GeometryTuner:
    """Hill-climb serving geometry from measured throughput, one change in
    flight, with automatic revert on floor regression.

    ``tick()`` is the whole control loop: called periodically (the
    standalone server runs it on a background thread; tests and bench call
    it directly).  Idle tick: measure the baseline rate from observatory
    lifetime-total deltas, pick the next knob round-robin, propose a step,
    apply it through the registered setter (the same validated path POST
    /config uses — a rejected proposal counts, nothing is applied).
    In-flight tick: once ``min_serves`` serves have landed on the new
    geometry, judge the measured rate against the baseline and keep or
    revert."""

    def __init__(self, observatory=None, config: TunerConfig | None = None,
                 enabled: bool = True):
        self.obs = observatory if observatory is not None else OBSERVATORY
        self.cfg = config or TunerConfig()
        self.enabled = enabled
        self._mu = make_lock("copr.costmodel.tuner")
        self._knobs: list[_Knob] = []
        self._idx = 0
        self._inflight: dict | None = None
        self._last_totals: dict | None = None
        self._counts = {"propose": 0, "keep": 0, "revert": 0, "reject": 0}
        self._history: list[dict] = []

    def register(self, name: str, get, apply, lo, hi,
                 integer: bool = False) -> None:
        """Register a tunable knob: ``get()`` reads the live value,
        ``apply(v)`` installs one (raising rejects the proposal), and
        ``[lo, hi]`` are the validated bounds the hill-climb stays in."""
        self._knobs.append(_Knob(name, get, apply, lo, hi, integer))

    @staticmethod
    def _rate(before: dict, after: dict) -> tuple[float, int]:
        """(rows per busy-second, serves) accumulated between two
        ``Observatory.totals`` snapshots."""
        serves = after["serves"] - before["serves"]
        rows = after["rows"] - before["rows"]
        busy = after["busy_s"] - before["busy_s"]
        return (rows / busy if busy > 0 else 0.0), serves

    def _count(self, knob: str, action: str, **extra) -> None:
        self._counts[action] = self._counts.get(action, 0) + 1
        self._history.append({"knob": knob, "action": action, **extra})
        if len(self._history) > _HISTORY_RING:
            del self._history[: len(self._history) - _HISTORY_RING]

    def tick(self) -> dict | None:
        """One control-loop step; returns the action taken (or None)."""
        if not self.enabled or not self._knobs:
            return None
        totals = self.obs.totals()
        inflight = self._inflight
        if inflight is not None:
            if inflight["warmup"] < self.cfg.warmup_ticks:
                # discard the post-change transient (image rebuild +
                # recompile): re-anchor the measurement window and wait
                inflight["warmup"] += 1
                inflight["totals"] = totals
                return None
            rate, serves = self._rate(inflight["totals"], totals)
            inflight["ticks"] += 1
            if (serves < self.cfg.min_serves
                    and inflight["ticks"] < self.cfg.settle_ticks):
                return None  # still settling
            knob = inflight["knob"]
            base = inflight["baseline"]
            self._inflight = None
            self._last_totals = totals
            if (serves >= self.cfg.min_serves and base > 0
                    and rate < self.cfg.revert_ratio * base):
                # floor regression: put the old value back, flip direction
                try:
                    knob.apply(inflight["old"])
                except Exception:  # noqa: BLE001 — revert must not raise
                    pass
                knob.direction = -knob.direction
                ev = {"old": inflight["new"], "new": inflight["old"],
                      "rate": round(rate, 1), "baseline": round(base, 1)}
                with self._mu:
                    self._count(knob.name, "revert", **ev)
                self._metric(knob.name, "revert")
                return {"action": "revert", "knob": knob.name, **ev}
            ev = {"value": inflight["new"], "rate": round(rate, 1),
                  "baseline": round(base, 1), "serves": serves}
            with self._mu:
                self._count(knob.name, "keep", **ev)
            self._metric(knob.name, "keep")
            return {"action": "keep", "knob": knob.name, **ev}
        # idle: refresh the baseline window, then propose the next step
        last = self._last_totals
        self._last_totals = totals
        if last is None:
            return None
        rate, serves = self._rate(last, totals)
        if serves < self.cfg.min_serves:
            return None  # not enough traffic to judge anything
        knob = self._knobs[self._idx % len(self._knobs)]
        self._idx += 1
        cur = knob.get()
        new = knob.propose(cur)
        if new is None or new == cur:
            return None
        try:
            knob.apply(new)
        except Exception as exc:  # noqa: BLE001 — validated setter rejected
            with self._mu:
                self._count(knob.name, "reject", value=new, error=str(exc))
            self._metric(knob.name, "reject")
            return {"action": "reject", "knob": knob.name, "value": new}
        self._inflight = {"knob": knob, "old": cur, "new": new,
                          "baseline": rate, "totals": totals, "ticks": 0,
                          "warmup": 0}
        ev = {"old": cur, "new": new, "baseline": round(rate, 1)}
        with self._mu:
            self._count(knob.name, "propose", **ev)
        self._metric(knob.name, "propose")
        return {"action": "propose", "knob": knob.name, **ev}

    @staticmethod
    def _metric(knob: str, action: str) -> None:
        REGISTRY.counter(
            "tikv_coprocessor_geometry_tune_total",
            "Geometry auto-tuner steps, by knob and action",
        ).inc(knob=knob, action=action)

    def snapshot(self) -> dict:
        # knob getters may take their owners' locks — read them OUTSIDE
        # the tuner's leaf lock
        knobs = [
            {"name": k.name, "value": k.get(), "lo": k.lo, "hi": k.hi,
             "direction": k.direction}
            for k in self._knobs
        ]
        f = self._inflight
        inflight = ({"knob": f["knob"].name, "old": f["old"], "new": f["new"],
                     "baseline": round(f["baseline"], 1)}
                    if f is not None else None)
        with self._mu:
            return {
                "enabled": self.enabled,
                "knobs": knobs,
                "in_flight": inflight,
                "counts": dict(self._counts),
                "history": list(self._history),
            }
