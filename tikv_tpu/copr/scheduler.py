"""Unified coprocessor read scheduler: cross-region continuous batching.

The reference serves coprocessor reads through a unified read pool (yatp,
``src/read_pool.rs``): many regions' requests multiplex onto shared workers
with high/normal/low priorities.  This module is the device-serving
re-expression: instead of sharing CPU workers, concurrent device-eligible
DAG requests share **XLA dispatches**.

* Requests are keyed by their **plan signature** (:func:`plan_signature` —
  scalar ops normalized through ``sig_map`` so wire-level ScalarFuncSig
  spellings and kernel names key identically).  Same signature = same
  compiled program shape.
* Requests with the same signature but different regions batch into ONE
  device program: each region's cached column image (PR 1's
  ``region_cache.py``) is padded to a shared block geometry and stacked
  along a new leading region axis (``jax_eval.launch_xregion_cached``),
  with per-region row-count masks so padding never changes results.
* With a multi-device mesh the scheduler is DEVICE-AWARE: the region cache
  places images on owner devices, slots pack per owner, and the batch runs
  as one ``shard_map`` program over device-local shards
  (``jax_eval.launch_xregion_sharded`` → ``parallel/mesh.py``), partial
  aggregate states merging over ICI.  Padding-shed then accounts for the
  (devices × slabs) geometry — the slab axis rounds up to the mesh's
  per-device maximum — and per-device occupancy is reported.  Double-
  buffered prepare fills the NEXT batch's shards on their owner devices
  while the current batch executes.
* Requests over the SAME cached region view with different plans keep the
  old fused path (``jax_eval.run_batch_cached``), now living here instead
  of ``endpoint._try_fused_batch``.
* Everything else — ineligible plans, cold/unresolvable caches, shed
  requests — serves through ``endpoint.handle_request`` unchanged, so the
  scheduler only ever *removes* dispatches, never changes bytes.

Continuous-batching semantics:

* three priority lanes (``high`` / ``normal`` / ``low``, mirroring the
  read-pool priorities) with per-lane max-wait knobs;
* a bounded queue — beyond ``max_queue`` pending requests, admission
  control sheds new arrivals straight to the per-request path;
* ``max_batch`` bounds one program's fan-in; oversize groups chunk;
* a padding budget sheds block-count outliers from a cross-region batch
  (one giant region would otherwise pad every small region up to its
  geometry — the giant serves per-request, where its size already
  amortizes the dispatch);
* double-buffering: batch N executes on device (async dispatch) while the
  host runs batch N+1's cache resolution — the region cache's fill/delta
  pass — and batch N's pull happens only after N+1 is launched.

Metrics: queue depth, batch occupancy, padding waste, per-lane wait — see
``docs/copr_scheduler.md`` and the coprocessor Grafana dashboard.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..analysis.sanitizer import make_condition, make_lock
from ..util import trace
from . import observatory as _obs
from . import overload as _overload
from ..util.retry import DeadlineExceeded, ServerBusyError, deadline_from_context
from . import jax_eval
from .dag import (
    Aggregation,
    DagRequest,
    IndexScan,
    Join,
    Limit,
    Projection,
    Selection,
    TableScan,
    TopN,
)
from .endpoint import (
    REQ_TYPE_DAG,
    CoprRequest,
    CoprResponse,
    resolve_encode_type,
    stale_read_ctx,
)
from .region_cache import _epoch_of, schema_sig
from .rpn import ColumnRef, Constant, FuncCall
from .sig_map import resolve_sig

LANES = ("high", "normal", "low")


@dataclass
class SchedulerConfig:
    """Admission-control knobs (read_pool.rs's pool sizing analog)."""

    max_batch: int = 64            # regions/queries fused into one program
    max_queue: int = 256           # pending cap before admission sheds
    padding_budget: float = 0.5    # max wasted fraction of padded block slots
    max_wait_s: float = 0.004      # normal-lane linger before partial dispatch
    high_max_wait_s: float = 0.001
    low_max_wait_s: float = 0.02
    # busy_reject=True turns queue-full admission into a ServerIsBusy-style
    # REJECTION carrying a retry-after hint (honored by util.retry), instead
    # of silently serving on the caller's thread — rejecting is the right
    # call when the store is saturated: the direct path would add load
    # exactly when there is none to spare
    busy_reject: bool = False
    busy_retry_after_s: float = 0.05
    # lane ceiling for client-declared priorities — SERVER policy, applied
    # even with no overload control wired (docs/robustness.md "Overload"):
    # "high" admits every declared lane (historical behavior); "normal"
    # stops clients from jumping the high lane.  A wired OverloadControl's
    # per-tenant ceilings clamp further.
    max_priority: str = "high"

    def wait_for(self, lane: str) -> float:
        if lane == "high":
            return self.high_max_wait_s
        if lane == "low":
            return self.low_max_wait_s
        return self.max_wait_s


def _lane_of(req: CoprRequest) -> str:
    lane = (req.context or {}).get("priority", "normal")
    return lane if lane in LANES else "normal"


def _clamped_lane(req: CoprRequest, cfg: SchedulerConfig, overload) -> str:
    """The request's EFFECTIVE lane: the client-declared priority clamped
    to the global ceiling (``cfg.max_priority``) and, with an overload
    control wired, the tenant's configured ceiling — the client never
    picks a higher lane than policy grants it.  Demotions are counted per
    tenant (tikv_overload_demote_total)."""
    lane = _lane_of(req)
    ceiling = cfg.max_priority
    if overload is not None:
        tc = overload.lane_ceiling(req.context)
        if tc is not None:
            ceiling = _overload.clamp_lane(ceiling, tc)
    eff = _overload.clamp_lane(lane, ceiling)
    if eff != lane:
        _overload.count_demotion(_overload.tenant_of(req.context), eff)
    return eff


def _expr_sig(e):
    """Canonical, hashable form of a scalar expression tree."""
    if e is None:
        return None
    if isinstance(e, ColumnRef):
        return ("col", e.index)
    if isinstance(e, Constant):
        v = e.value
        if not isinstance(v, (int, float, bytes, str, bool, type(None))):
            v = repr(v)
        return ("const", e.eval_type, e.frac, v)
    if isinstance(e, FuncCall):
        op = e.op
        # wire-format ScalarFuncSig spellings fold onto kernel names, so a
        # tipb-bridged DAG and a natively-built DAG with the same plan key
        # into the same micro-batch (sig_map is the single source of truth)
        mapped = resolve_sig(op)
        if mapped is not None and not mapped.startswith("~"):
            op = mapped
        return ("fn", op, tuple(_expr_sig(c) for c in e.children))
    return ("?", repr(e))


def _exec_sig(ex) -> tuple:
    """One executor descriptor's shape key.  A Join recurses into its
    build chain but deliberately EXCLUDES the build ranges and region
    context — those vary per request without changing the compiled
    program shape, exactly like the probe ranges."""
    if isinstance(ex, TableScan):
        return ("tablescan", ex.table_id, schema_sig(ex.columns_info))
    if isinstance(ex, IndexScan):
        return ("indexscan", ex.table_id, ex.index_id,
                schema_sig(ex.columns_info))
    if isinstance(ex, Selection):
        return ("sel", tuple(_expr_sig(c) for c in ex.conditions))
    if isinstance(ex, Aggregation):
        return ("agg", bool(ex.streamed),
                tuple(_expr_sig(g) for g in ex.group_by),
                tuple((a.op, _expr_sig(a.expr)) for a in ex.agg_funcs))
    if isinstance(ex, TopN):
        return ("topn", ex.limit,
                tuple((_expr_sig(e), bool(d)) for e, d in ex.order_by))
    if isinstance(ex, Limit):
        return ("limit", ex.limit)
    if isinstance(ex, Projection):
        return ("proj", tuple(_expr_sig(e) for e in ex.exprs))
    if isinstance(ex, Join):
        return ("join", ex.join_type, ex.left_key, ex.right_key,
                tuple(_exec_sig(b) for b in ex.build))
    return (type(ex).__name__,)


def plan_signature(dag: DagRequest) -> tuple:
    """The micro-batch key: two DAGs with equal signatures compile to the
    same device program shape, so their executions can share one dispatch
    (over different region images)."""
    parts = [_exec_sig(ex) for ex in dag.executors]
    # encode_type is part of the slot identity: identical requests share one
    # slot's RESPONSE BYTES, and a datum and a chunk request with the same
    # plan must never share those (mirrors the service parse-memo rule)
    parts.append(("out", tuple(dag.output_offsets or ()), dag.chunk_rows,
                  dag.encode_type))
    return tuple(parts)


@dataclass
class _Item:
    req: CoprRequest
    index: int
    lane: str = "normal"
    ticket: "_Ticket | None" = None
    enqueue_t: float = 0.0
    sig: tuple | None = None  # plan signature, set once during grouping
    # absolute monotonic deadline (context "deadline"/"timeout_ms", see
    # util.retry.deadline_from_context); expired items shed BEFORE dispatch
    deadline: float | None = None
    # trace handoff (docs/tracing.md): the submitting thread's span context,
    # so dispatcher-side work lands in the request's own trace; batch_ref
    # names the shared device-dispatch span the item coalesced into
    trace_ctx: dict | None = None
    batch_ref: str | None = None


class _Ticket:
    """One continuous-mode submission: the caller blocks on ``done`` while
    the dispatcher batches and serves.  ``direct`` hands the request back
    to the caller's thread (shed / ineligible work must not serialize the
    whole dispatcher behind one slow per-request execution)."""

    __slots__ = ("done", "resp", "error", "direct")

    def __init__(self):
        self.done = threading.Event()
        self.resp: CoprResponse | None = None
        self.error: BaseException | None = None
        self.direct = False


@dataclass
class _Slot:
    """One distinct (plan, region view) execution slot in a micro-batch.
    Multiple identical requests share the slot (and its response bytes)."""

    items: list = field(default_factory=list)
    cache: object = None
    outcome: str = ""
    # shadow-read sampling (docs/integrity.md): set at resolve time to the
    # slot's snapshot when the sampler picks this warm serve — the finalize
    # pass then byte-compares the device answer against the CPU oracle
    shadow_snap: object = None


class CoprReadScheduler:
    """The unified read scheduler over one :class:`~.endpoint.Endpoint`."""

    def __init__(self, endpoint, config: SchedulerConfig | None = None):
        self.ep = endpoint
        self.cfg = config or SchedulerConfig()
        self._mu = make_condition("copr.scheduler", make_lock("copr.scheduler"))
        self._queues: dict[str, list[_Item]] = {lane: [] for lane in LANES}
        self._running = False
        self._thread: threading.Thread | None = None
        # per-signature memos: device eligibility (supports() re-analyzes the
        # whole plan) and the compiled evaluator (endpoint._evaluator_for
        # keys on serialized plan bytes — ~1ms of wire encoding per lookup
        # that a batch of identical-signature requests should pay once)
        self._memo_mu = make_lock("copr.scheduler.memo")
        self._supports: dict[tuple, bool] = {}
        self._evs: dict[tuple, object] = {}

    def reconfigure(self, changed: dict) -> None:
        """Online scheduler geometry (POST /config ``coprocessor.*`` via
        the ConfigController, and the geometry auto-tuner): the per-lane
        linger windows.  Values were validated by ``TikvConfig.validate``
        before dispatch; lanes read ``cfg.wait_for`` per pass, so changes
        apply on the next dispatch decision."""
        for key, value in changed.items():
            if key == "max_wait_s":
                self.cfg.max_wait_s = float(value)
            elif key == "high_max_wait_s":
                self.cfg.high_max_wait_s = float(value)
            elif key == "low_max_wait_s":
                self.cfg.low_max_wait_s = float(value)

    # -- synchronous entry (endpoint.handle_batch / batch_coprocessor) -----

    def run_batch(self, reqs: list[CoprRequest], *, return_errors: bool = False):
        for r in reqs:
            resolve_encode_type(r)
        tctx = trace.current_context()
        # per-tenant quota admission (docs/robustness.md "Overload"): an
        # over-quota rider fails ITS slot typed (ServerBusyError with the
        # bucket's refill deficit) without deferring — a synchronous batch
        # must not sleep per rider — and siblings keep their responses
        ov = getattr(self.ep, "overload", None)
        results: list[CoprResponse | None] = [None] * len(reqs)
        errors: list[BaseException | None] = [None] * len(reqs)
        live: list[tuple[int, CoprRequest]] = []
        for i, r in enumerate(reqs):
            if ov is not None:
                try:
                    ov.admit(r.context, where="batch", wait=False)
                except ServerBusyError as exc:
                    self._count_shed("tenant_quota")
                    errors[i] = exc
                    continue
            live.append((i, r))
        items = [
            _Item(req=r, index=j, lane=_clamped_lane(r, self.cfg, ov),
                  deadline=deadline_from_context(r.context), trace_ctx=tctx)
            for j, (_i, r) in enumerate(live)
        ]
        sub_results, sub_errors = self._serve(items)
        for (i, _r), res, err in zip(live, sub_results, sub_errors):
            results[i] = res
            errors[i] = err
        if return_errors:
            # per-slot surface (service.coprocessor_batch): computed
            # responses survive a sibling slot's failure — one expired
            # deadline must not discard K-1 finished answers
            return results, errors
        first = next((e for e in errors if e is not None), None)
        if first is not None:
            # the pre-scheduler handle_batch aborted on the first raising
            # request; callers of the raising surface re-serve per slot —
            # keep that contract for the synchronous surface
            raise first
        return results

    # -- continuous entry (unary requests coalescing across clients) -------

    @property
    def running(self) -> bool:
        return self._running

    def start(self) -> None:
        with self._mu:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._dispatch_loop, daemon=True, name="copr-sched"
            )
            self._thread.start()

    def stop(self) -> None:
        with self._mu:
            if not self._running:
                return
            self._running = False
            self._mu.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def execute(self, req: CoprRequest, timeout: float | None = None) -> CoprResponse:
        """Continuous-mode unary entry: enqueue into the request's priority
        lane and wait for the batch that serves it.  Falls back to the
        direct path when the scheduler is stopped, the request is not
        batchable, or admission control sheds it."""
        # encoding negotiation BEFORE admission: an unsupported chunk plan
        # must batch (and key) as its datum twin, never reach an evaluator
        resolve_encode_type(req)
        deadline = deadline_from_context(req.context)
        if deadline is not None and time.monotonic() >= deadline:
            # dead on arrival: admission control sheds it before it costs a
            # queue slot, let alone a device dispatch
            self._count_deadline("admission")
            raise DeadlineExceeded("deadline expired before admission")
        # stale-read admission (docs/stale_reads.md): a read_ts above this
        # replica's RegionReadProgress raises DataNotReady HERE — before a
        # queue slot, a snapshot, or any device dispatch — so the client's
        # watermark-aware backoff starts immediately
        self._check_stale_ready(req)
        # per-tenant quota admission (docs/robustness.md "Overload"): over-
        # quota work defers a bounded wait on THIS caller's thread, then
        # sheds typed with the bucket's refill deficit as retry_after_s —
        # before it can cost a queue slot, a snapshot, or a device dispatch
        ov = getattr(self.ep, "overload", None)
        if ov is not None:
            try:
                ov.admit(req.context, where="sched")
            except ServerBusyError:
                self._count_shed("tenant_quota")
                raise
        if (not self._running or not self.ep._gate_ok("batch")
                or not self._batchable(req)):
            # the BATCH_FUSION gate guards this path exactly like
            # handle_batch: a mixed-version cluster keeps fusion off
            self._count_coalesce("bypass")
            return self.ep.handle_request(req)
        item = _Item(req=req, index=0, lane=_clamped_lane(req, self.cfg, ov),
                     ticket=_Ticket(),
                     enqueue_t=time.perf_counter(), deadline=deadline)
        # queue-lane span (docs/tracing.md): covers enqueue→batch-completion
        # on the submitting thread; the dispatcher stamps dispatcher-side
        # spans into this trace via the captured context
        with trace.span("sched.queue", lane=item.lane) as sp:
            item.trace_ctx = sp.context if sp else None
            depth = 0
            with self._mu:
                # re-check under the lock: a stop() racing this enqueue drains
                # the queues once — anything appended after that drain would
                # never be served and the caller would block forever
                depth = sum(len(q) for q in self._queues.values())
                # under adaptive pressure the EFFECTIVE cap shrinks with the
                # controller's scale, and queue-full becomes a busy-typed
                # rejection even with the static busy_reject off — evidence-
                # based shedding (docs/robustness.md "Overload")
                cap = ov.queue_cap(self.cfg.max_queue) if ov is not None \
                    else self.cfg.max_queue
                busy = False
                if not self._running:
                    do_direct = True
                elif depth >= cap:
                    if self.cfg.busy_reject or (
                            ov is not None and ov.pressure_reject()):
                        # ServerIsBusy with a drain hint: the retry policy
                        # (util.retry) sleeps at least retry_after_s before the
                        # request comes back — backpressure instead of serving
                        # extra work on a saturated store.  Counted under its
                        # own reason: "queue_full" means served on the direct
                        # path, and a rejection is neither served nor direct
                        busy = True
                        do_direct = False
                    else:
                        self._count_shed("queue_full")
                        do_direct = True
                else:
                    do_direct = False
                    self._queues[item.lane].append(item)
                    self._gauge_depth()
                    self._mu.notify_all()
            if ov is not None:
                # controller feed (outside the dispatcher lock): queue
                # fullness is the adaptive controller's primary evidence
                ov.note_queue(depth, self.cfg.max_queue)
            if busy:
                self._count_shed("busy_reject")
                self._count_coalesce("busy_reject")
                sp.tag(outcome="busy_reject")
                # the hint floor keeps the busy class's backoff hint-
                # dominated even when the knob is set to 0
                raise ServerBusyError(
                    "coprocessor scheduler queue is full",
                    retry_after_s=max(self.cfg.busy_retry_after_s, 0.001),
                )
            if do_direct:
                self._count_coalesce("queue_full")
                sp.tag(outcome="queue_full")
                return self.ep.handle_request(req)
            item.ticket.done.wait(timeout)
            if not item.ticket.done.is_set():
                sp.tag(outcome="timeout")
                raise TimeoutError("scheduler did not serve the request in time")
            if item.ticket.direct:
                # the dispatcher shed this request back: serve it on OUR thread
                # so one slow per-request path cannot stall every lane — unless
                # its deadline ran out while it waited
                if deadline is not None and time.monotonic() >= deadline:
                    self._count_deadline("direct")
                    sp.tag(outcome="deadline")
                    raise DeadlineExceeded("deadline expired before direct serve")
                self._count_coalesce("direct")
                sp.tag(outcome="direct")
                return self.ep.handle_request(req)
            if item.ticket.error is not None:
                sp.tag(outcome="error")
                raise item.ticket.error
            # served out of a dispatcher micro-batch: the wire-path coalescing
            # outcome the cluster bench floors on (docs/wire_path.md)
            self._count_coalesce("batched")
            sp.tag(outcome="batched")
            if item.batch_ref is not None:
                sp.link("batched_into", item.batch_ref)
            return item.ticket.resp

    def _dispatch_loop(self) -> None:
        cfg = self.cfg
        while True:
            with self._mu:
                while self._running and not any(self._queues.values()):
                    self._mu.wait(0.5)
                if not self._running:
                    # drain whatever is queued so no caller hangs forever —
                    # but SERVE it below, outside the dispatcher lock: the
                    # drain batch runs engine snapshots and device dispatch,
                    # and holding _mu across those would stall every
                    # execute() caller on a blocked enqueue re-check
                    batch = [it for lane in LANES for it in self._queues[lane]]
                    for lane in LANES:
                        self._queues[lane].clear()
                    self._gauge_depth()
                    stopping = True
                else:
                    stopping = False
                if not stopping:
                    # linger until the oldest item's lane deadline or max_batch
                    now = time.perf_counter()
                    deadline = min(
                        it.enqueue_t + cfg.wait_for(lane)
                        for lane in LANES
                        for it in self._queues[lane]
                    )
                    total = sum(len(q) for q in self._queues.values())
                    if total < cfg.max_batch and now < deadline:
                        self._mu.wait(min(deadline - now, 0.05))
                        continue
                    batch = []
                    for lane in LANES:  # high lane drains first
                        while self._queues[lane] and len(batch) < cfg.max_batch:
                            batch.append(self._queues[lane].pop(0))
                    self._gauge_depth()
            if stopping:
                if batch:
                    self._serve_ticketed(batch)
                return
            if batch:
                for it in batch:
                    self._observe_wait(it)
                self._serve_ticketed(batch)

    def _serve_ticketed(self, batch: list[_Item]) -> None:
        from ..util.failpoint import fail_point

        # chaos/regression hook on the DISPATCHER thread: a seeded sleep
        # here paces batch service so overload tests can saturate the
        # bounded queue deterministically (tests/test_overload.py)
        fail_point("sched_dispatch")
        for i, it in enumerate(batch):
            it.index = i
        try:
            results, errors = self._serve(batch)
        except BaseException as exc:  # noqa: BLE001 — scheduler bug: fail all
            for it in batch:
                it.ticket.error = exc
                it.ticket.done.set()
            return
        # per-ticket delivery: one request's lock conflict or decode error
        # must not poison the riders that coalesced into the same batch
        for it in batch:
            if it.ticket.done.is_set():
                continue  # already handed back to its caller (direct)
            if errors[it.index] is not None:
                it.ticket.error = errors[it.index]
            else:
                it.ticket.resp = results[it.index]
            it.ticket.done.set()

    def _check_stale_ready(self, req: CoprRequest, count: bool = True) -> None:
        """Raise DataNotReady for a stale read this replica cannot admit —
        exactly what the engine's snapshot would raise, but WITHOUT freezing
        the engine (RaftKv.check_read_ready).  No-op on engines without the
        probe (plain local engines) and on non-stale contexts."""
        ctx = stale_read_ctx(req)
        if not ctx or not ctx.get("stale_read"):
            return
        ready = getattr(self.ep.engine, "check_read_ready", None)
        if ready is None:
            return
        try:
            ready(ctx)
        except Exception as exc:
            if count:
                # a witness/non-hosting replica refuses NotLeader — that is
                # a routing problem, not watermark lag; keeping the reasons
                # apart keeps the safe_ts-lag dashboards honest
                if type(exc).__name__ == "DataNotReadyError":
                    self._count_shed("data_not_ready")
                else:
                    self._count_shed("stale_not_leader")
            raise

    # -- the scheduler core -------------------------------------------------

    def _serve(self, items: list[_Item]):
        """Returns (results, errors), index-aligned with ``items``: exactly
        one of results[i] / errors[i] is set per item, so callers deliver
        failures per request instead of poisoning the whole batch."""
        results: list[CoprResponse | None] = [None] * len(items)
        errors: list[BaseException | None] = [None] * len(items)
        # deadline shed FIRST: expired work must never reach grouping, let
        # alone a device dispatch — the client has already given up, and a
        # padded slot spent on it would tax every live rider in the batch
        now = time.monotonic()
        expired = [it for it in items
                   if it.deadline is not None and now >= it.deadline]
        for it in expired:
            self._count_deadline("dispatch")
            self._count_shed("deadline")
            errors[it.index] = DeadlineExceeded("deadline expired in queue")
        if expired:
            items = [it for it in items if errors[it.index] is None]
        # stale-read admission at dispatch: a watermark-lagging item fails
        # typed BEFORE grouping — it must never cost a padded batch slot
        not_ready = []
        for it in items:
            try:
                self._check_stale_ready(it.req)
            except Exception as exc:  # noqa: BLE001 — DataNotReady/NotLeader
                errors[it.index] = exc
                not_ready.append(it)
        if not_ready:
            items = [it for it in items if errors[it.index] is None]
        # group by plan signature, then by distinct region view within a sig
        by_sig: dict[tuple, dict[tuple, _Slot]] = {}
        rest = []
        for it in items:
            sig = self._batchable_sig(it.req)
            if sig is None:
                rest.append(it)
                continue
            it.sig = sig
            rkey = self._region_key(it.req)
            by_sig.setdefault(sig, {}).setdefault(rkey, _Slot()).items.append(it)

        exec_groups: list[tuple] = []  # ("xregion", dag, [slots]) | ("fused", key, [items])
        leftovers: list[_Item] = []
        for sig, slots in by_sig.items():
            if len(slots) >= 2:
                if not self._route_batch(sig):
                    # cost-routed (docs/cost_router.md): the measured
                    # per-request path beats the cross-region batch for
                    # this plan shape — serve the slots directly
                    for slot in slots.values():
                        rest.extend(slot.items)
                    continue
                slot_list = list(slots.values())
                for s in range(0, len(slot_list), self.cfg.max_batch):
                    exec_groups.append(("xregion", sig,
                                        slot_list[s:s + self.cfg.max_batch]))
            else:
                leftovers.extend(next(iter(slots.values())).items)
        # same region view, different plans: the old fused batch shape
        by_cache: dict[tuple, list[_Item]] = {}
        for it in leftovers:
            by_cache.setdefault(self._region_key(it.req), []).append(it)
        for key, group in by_cache.items():
            if len(group) >= 2:
                for s in range(0, len(group), self.cfg.max_batch):
                    exec_groups.append(("fused", key, group[s:s + self.cfg.max_batch]))
            else:
                rest.extend(group)

        # high-priority groups launch first
        lane_rank = {lane: i for i, lane in enumerate(LANES)}
        exec_groups.sort(key=lambda g: min(
            lane_rank[it.lane]
            for it in (sum((s.items for s in g[2]), []) if g[0] == "xregion" else g[2])
        ))

        # double-buffered pipeline: resolve (host fill/delta) group i while
        # group i-1 executes on device; pull i-1 only after i is launched
        pending = None
        for kind, meta, group in exec_groups:
            if kind == "xregion":
                launched = self._launch_xregion(meta, group, results, errors)
            else:
                launched = self._run_fused(meta, group, results, errors)
            if pending is not None:
                pending(results, errors)
            pending = launched
        if pending is not None:
            pending(results, errors)

        for it in rest:
            self._per_request(it, results, errors, kind="direct")
        return results, errors

    def _route_batch(self, sig: tuple) -> bool:
        """Cost-route one sig's micro-batch (docs/cost_router.md):
        measured "xregion" against a synthetic "direct" = the best
        per-request path this sig has profiles for.  True keeps the batch
        (the static choice, and the kill-switch/cold answer); False sends
        the slots to per-request serving."""
        router = getattr(self.ep, "cost_router", None)
        if router is None or not router.enabled:
            return True  # killed router must cost the dispatch loop nothing
        from . import observatory as _obs

        sid = _obs.sig_id(sig)
        costs = router.obs.path_costs(sid)
        table = {}
        if "xregion" in costs:
            table["xregion"] = costs["xregion"]
        direct = [c for p, c in costs.items() if p != "xregion"]
        if direct:
            table["direct"] = min(direct, key=lambda c: c["cost_ms"])
        d = router.route(sid, ["xregion", "direct"], costs=table)
        return d.path != "direct"

    # -- eligibility & keying ----------------------------------------------

    def _batchable(self, req: CoprRequest) -> bool:
        return self._batchable_sig(req) is not None

    def _batchable_sig(self, req: CoprRequest) -> tuple | None:
        """The request's plan signature when it can join a device batch,
        else None.  supports() verdicts memoize per signature."""
        if (req.tp != REQ_TYPE_DAG or req.dag is None
                or not self.ep.device_enabled()
                or not any(isinstance(e, Aggregation) for e in req.dag.executors)):
            return None
        ov = getattr(self.ep, "overload", None)
        if ov is not None and not ov.allow_device(req.context):
            # memory-pressure ladder, last rung (docs/robustness.md): the
            # tenant's HBM partition would not fit even after eviction and
            # pin demotion — its work must not join a device batch (the
            # per-request path CPU-falls-back for the same reason)
            return None
        sig = plan_signature(req.dag)
        ok = self._supports.get(sig)
        if ok is None:
            ok = jax_eval.supports(req.dag)
            # memo mutation under its own lock: _batchable runs on client
            # threads AND the dispatcher; racing evictions of the same key
            # would KeyError
            with self._memo_mu:
                self._supports[sig] = ok
                while len(self._supports) > 256:
                    self._supports.pop(next(iter(self._supports)))
        return sig if ok else None

    def _evaluator_for(self, sig: tuple, dag: DagRequest):
        ev = self._evs.get(sig)
        if ev is None:
            ev = self.ep._evaluator_for(dag)
            with self._memo_mu:
                self._evs[sig] = ev
                while len(self._evs) > 64:
                    self._evs.pop(next(iter(self._evs)))
        return ev

    def _region_key(self, req: CoprRequest) -> tuple:
        ctx = req.context or {}
        return (
            ctx.get("region_id"),
            tuple(req.ranges),
            req.start_ts,
            ctx.get("cache_version"),
            ctx.get("apply_index"),
            _epoch_of(ctx.get("region_epoch")),  # normalizes tuple/list/object
        )

    # -- cache resolution (the host-side fill/delta pass) -------------------

    def _resolve_slot(self, slot: _Slot) -> bool:
        """Resolve a slot's region view to a FILLED block cache, running the
        region cache's build/delta pass if needed.  Returns False when the
        slot must shed to the per-request path."""
        from .tracker import Tracker

        req = slot.items[0].req
        if self.ep.cm is not None:
            # every item in a slot shares (ranges, start_ts) by construction
            # of _region_key — one lock-range scan covers the whole slot
            from ..storage.txn_types import Key

            for start, end in req.ranges:
                self.ep.cm.read_range_check(
                    Key.from_raw(start), Key.from_raw(end), req.start_ts
                )
        snap = self.ep.engine.snapshot(stale_read_ctx(req))
        tracker = Tracker()
        cache, outcome = self.ep._region_cache_for(req, snap, tracker)
        if cache is None:
            cache = self.ep._block_cache_for(req)
            outcome = ""
        if cache is None:
            return False
        if getattr(snap, "stale", False):
            # warm follower device serving: the slot's whole fan-in rides a
            # stale-read snapshot (docs/stale_reads.md)
            self.ep.count_follower_read("batch")
        if not cache.filled:
            # cold block cache: the first request fills it through the
            # normal per-request path (and keeps its own answer); the rest
            # of the slot then serves from the filled blocks
            filler = slot.items[0]
            with trace.attach(filler.trace_ctx):
                resp = self.ep.handle_request(filler.req)
            self._stamp(resp, filler, kind="fill", occupancy=1)
            filler._filled_resp = resp  # type: ignore[attr-defined]
            if not cache.filled or not cache.blocks:
                return False
        slot.cache = cache
        slot.outcome = outcome
        if (outcome in ("hit", "delta", "wt_delta")
                and self.ep.shadow.pick("batch")):
            slot.shadow_snap = snap
        return True

    # -- execution groups ---------------------------------------------------

    def _sharded_mesh(self, ev):
        """The endpoint's mesh when this batch should run the SHARDED warm
        launcher: >1 real device, MESH_SERVING gate open, and every
        aggregate has a mesh merge rule (no rule → the single-device
        xregion program, which needs none)."""
        mesh = self.ep.mesh
        if (mesh is None or getattr(mesh, "size", 1) <= 1
                or getattr(mesh, "devices", None) is None
                or not getattr(self.ep, "shard_cache", True)
                or not self.ep._gate_ok("mesh")):
            return None
        from ..parallel.mesh import mesh_mergeable

        return mesh if mesh_mergeable(ev.device_aggs) else None

    def _launch_xregion(self, sig: tuple, slots: list[_Slot], results, errors):
        """Resolve every slot's cache (host), shed what cannot batch, and
        dispatch ONE cross-region program — over the mesh (one shard_map
        program, slabs on their owner devices) when the endpoint has one,
        else the single-device vmapped program.  Returns the finalize
        closure."""
        live: list[_Slot] = []
        for slot in slots:
            ok = False
            try:
                ok = self._resolve_slot(slot)
            except Exception:  # noqa: BLE001 — resolution must not kill the batch
                ok = False
            if ok:
                live.append(slot)
                # a cold-fill answered the slot's first request already
                for it in slot.items:
                    resp = getattr(it, "_filled_resp", None)
                    if resp is not None:
                        results[it.index] = resp
            else:
                self._shed(slot, "no_cache", results, errors)
        # two slots (different start_ts / apply_index) can resolve to the
        # SAME region image — the region cache keys images on (region_id,
        # ranges, schema) only, and resolving the later slot delta-applies
        # the image IN PLACE, retroactively changing what the earlier slot's
        # resolution saw.  Only the LAST resolution's view is current, so
        # only that slot may batch; earlier aliases shed to the per-request
        # path, where serve() re-resolves them (a now-stale start_ts takes
        # the stale fallback) — snapshot isolation over bytes saved.
        by_image: dict[int, _Slot] = {}
        for slot in live:
            prev = by_image.get(id(slot.cache))
            if prev is not None:
                self._shed(prev, "aliased_image", results, errors)
            by_image[id(slot.cache)] = slot
        live = [s for s in live if by_image.get(id(s.cache)) is s]
        if not live:
            return None
        ev = self._evaluator_for(sig, live[0].items[0].req.dag)
        mesh = self._sharded_mesh(ev)
        breaker = self.ep.breaker
        if mesh is not None and not breaker.allow("mesh"):
            # mesh path tripped: degrade to the single-device cross-region
            # program instead of losing batching entirely
            from .tracker import count_path_fallback

            count_path_fallback("mesh", "breaker_open")
            mesh = None
        if mesh is None and not breaker.allow("xregion"):
            from .tracker import count_path_fallback

            count_path_fallback("xregion", "breaker_open")
            for slot in live:
                self._shed(slot, "breaker_open", results, errors)
            return None
        path = "mesh" if mesh is not None else "xregion"
        if mesh is not None:
            live, device_load, sh_waste = self._shed_for_padding_sharded(
                live, mesh, results, errors)
        else:
            live = self._shed_for_padding(live, results, errors)
            device_load, sh_waste = None, 0.0
        if len(live) < 2:
            breaker.release_probe(path)  # nothing launched on this path
            for slot in live:
                self._shed(slot, "underfull", results, errors, path=path)
            return None
        # cold-fills were answered (and counted) by their own handle_request
        # — the program serves the rest; occupancy counts the whole fan-in.
        # Counted over the FINAL live set: a filled slot shed above (alias /
        # padding) must not deflate this batch's request count.
        n_batch = sum(len(s.items) for s in live)
        n_filled = sum(
            1 for s in live for it in s.items
            if getattr(it, "_filled_resp", None) is not None
        )
        n_reqs = max(n_batch - n_filled, 1)
        kind = "xregion" if mesh is None else "xregion_sharded"
        waste = self._padding_waste(live, ev=ev) if mesh is None else sh_waste
        # fan-in linkage (docs/tracing.md): ONE device-dispatch span — its
        # own one-span trace naming every participating parent trace — and
        # each rider links back to it.  A shared dispatch can't be a child
        # of N parents; this is the honest shape for shared-slot serving.
        riders = [it for s in live for it in s.items]
        bsp = trace.fanin_span(
            "sched.device_dispatch", [it.trace_ctx for it in riders],
            kind=kind, regions=len(live), occupancy=len(riders))
        if bsp:
            ref = f"{bsp.rec.trace_id}:{bsp.span_id}"
            for it in riders:
                it.batch_ref = ref
        t0 = time.perf_counter()
        try:
            # the batch's region images carry their ENCODING DESCRIPTORS on
            # the block caches (copr/encoding.py) alongside the dict
            # radices: the launchers read them to ship encoded HBM payloads
            # when every region agrees on one signature, and decode-ship
            # (counted per-cause) when not — sharded and fused paths stay
            # eligible for compressed-resident regions either way
            with bsp.active():
                if mesh is not None:
                    pending = jax_eval.launch_xregion_sharded(
                        ev, [s.cache for s in live], mesh)
                else:
                    pending = jax_eval.launch_xregion_cached(
                        ev, [s.cache for s in live])
        except ValueError:
            # "not batchable" (empty blocks, unstable dictionaries) is a
            # documented decline, not a device failure — shed without
            # polluting the fallback counter
            breaker.release_probe(path)
            bsp.tag(outcome="ineligible").finish()
            for slot in live:
                self._shed(slot, "ineligible", results, errors, path=path)
            return None
        except Exception as exc:  # noqa: BLE001 — CPU pipeline is the oracle
            self._device_failed(exc, path)
            bsp.tag(outcome="device_error").finish()
            for slot in live:
                self._shed(slot, "device_error", results, errors, path=path)
            return None
        t_launched = time.perf_counter()

        def finalize(results, errors):
            t_fin = time.perf_counter()
            try:
                with bsp.active():
                    resps = pending.finalize()
            except Exception as exc:  # noqa: BLE001
                self._device_failed(exc, path)
                bsp.tag(outcome="device_error").finish()
                for slot in live:
                    self._shed(slot, "device_error", results, errors,
                               path=path)
                return
            self.ep.breaker.record_success(path)
            pull_dt = time.perf_counter() - t_fin
            # latency = this group's own host work (launch) + the blocking
            # pull (residual device time).  The gap between launch and
            # finalize is the NEXT group's prepare pass — double-buffered
            # overlap, not this batch's cost; attributing it here would
            # inflate the device-path percentiles with unrelated host work.
            dt = (t_launched - t0) + pull_dt
            self._batch_metrics(kind, n_reqs, dt, waste, n_batch=n_batch)
            if bsp:
                bsp.tag(outcome="ok", launch_ms=round((t_launched - t0) * 1e3, 3),
                        pull_ms=round(pull_dt * 1e3, 3))
                bsp.finish()
                # each rider's trace gets a span for the shared dispatch it
                # rode, linked to the dispatch span's own trace
                for it in riders:
                    # batch_ref was already stamped at fanin-span creation
                    trace.remote_span(it.trace_ctx, "sched.batched",
                                      start=t0, end=t_fin + pull_dt,
                                      batched_into=ref, kind=kind,
                                      occupancy=n_batch)
            if mesh is not None:
                self._sharded_metrics(device_load, pull_dt)
            # observatory profiles (docs/observatory.md): every rider the
            # program answered records its attributed share on the batch
            # path, with the queue wait it actually paid and the dispatch
            # trace as its exemplar
            obs_path = "mesh" if mesh is not None else "xregion"
            obs_enc = getattr(pending, "obs_encoding", "plain")
            for slot, resp in zip(live, resps):
                rows = slot.cache.total_rows if slot.cache is not None else 0
                for it in slot.items:
                    if results[it.index] is not None:
                        continue  # cold-fill: recorded by its handle_request
                    self._record_obs(
                        it, ev, obs_path, dt / n_reqs, rows=rows,
                        encoding=obs_enc, occupancy=n_batch, waste=waste,
                        dispatch_t=t0, resp=resp)
            for slot, resp in zip(live, resps):
                # per-region chunk payloads: every rider of this slot shares
                # the SAME unjoined column-slab parts, so one multi-response
                # frame gather-writes each region's slabs once
                parts, enc_tp = self.ep._encode_response(resp)
                data = None
                from_device = True
                if slot.shadow_snap is not None:
                    # sampled slot: CPU-oracle byte compare; a mismatch
                    # quarantines the image and this slot serves the oracle
                    fixed = self.ep.shadow_compare(
                        slot.items[0].req, slot.shadow_snap,
                        b"".join(bytes(p) for p in parts), "batch")
                    if fixed is not None:
                        data, parts = fixed, None
                        from_device = False
                from_cache = from_device and slot.outcome not in ("", "miss", "too_big")
                for it in slot.items:
                    if results[it.index] is not None:
                        continue  # the cold-fill already answered this one
                    r = CoprResponse(data, from_device=from_device,
                                     from_cache=from_cache,
                                     data_parts=parts, encode_type=enc_tp)
                    self._stamp(r, it, kind=kind, occupancy=n_batch,
                                waste=waste, total_s=dt / n_reqs)
                    results[it.index] = r

        return finalize

    def _run_fused(self, key, items: list[_Item], results, errors):
        """Same region view, K different plans: the fused batch inherited
        from endpoint._try_fused_batch (run_batch_cached fuses all K into
        one program over the shared cache)."""
        if not self.ep.breaker.allow("fused"):
            from .tracker import count_path_fallback

            count_path_fallback("fused", "breaker_open")
            self._shed(_Slot(items=items), "breaker_open", results, errors,
                       path="fused")
            return None
        slot = _Slot(items=items)
        try:
            ok = self._resolve_slot(slot)
        except Exception:  # noqa: BLE001
            ok = False
        if not ok:
            self.ep.breaker.release_probe("fused")
            self._shed(slot, "no_cache", results, errors, path="fused")
            return None
        cache = slot.cache
        # the filler (cold cache) already answered slot.items[0]
        todo = [it for it in items if getattr(it, "_filled_resp", None) is None]
        for it in items:
            resp = getattr(it, "_filled_resp", None)
            if resp is not None:
                results[it.index] = resp
        if not todo:
            self.ep.breaker.release_probe("fused")  # cold-fill served it all
            return None
        n_reqs = len(todo)
        # identical requests (same signature over this region view) share one
        # query in the fused program — the cross-client dedupe
        uniq: dict[tuple, list[_Item]] = {}
        for it in todo:
            uniq.setdefault(it.sig, []).append(it)
        bsp = trace.fanin_span(
            "sched.device_dispatch", [it.trace_ctx for it in todo],
            kind="fused", plans=len(uniq), occupancy=len(todo))
        t0 = time.perf_counter()
        try:
            evs = [self._evaluator_for(sig, group[0].req.dag)
                   for sig, group in uniq.items()]
            resps = jax_eval.run_batch_cached(evs, cache)
        except ValueError:
            # a documented decline (non-stable group dictionaries, empty
            # cache) — per-request path, no device-failure attribution
            self.ep.breaker.release_probe("fused")
            bsp.tag(outcome="ineligible").finish()
            self._shed(_Slot(items=todo), "ineligible", results, errors,
                       path="fused")
            return None
        except Exception as exc:  # noqa: BLE001
            # _resolve_slot guarantees a filled cache here, so there is no
            # partial fill to clean up (the cold-fill path owns that)
            self._device_failed(exc, "fused")
            bsp.tag(outcome="device_error").finish()
            self._shed(_Slot(items=todo), "device_error", results, errors,
                       path="fused")
            return None
        self.ep.breaker.record_success("fused")
        dt = time.perf_counter() - t0
        if bsp:
            ref = f"{bsp.rec.trace_id}:{bsp.span_id}"
            bsp.tag(outcome="ok").finish()
            for it in todo:
                trace.remote_span(it.trace_ctx, "sched.batched", start=t0,
                                  end=t0 + dt, batched_into=ref,
                                  kind="fused", occupancy=n_reqs)
                it.batch_ref = ref
        self._batch_metrics("fused", n_reqs, dt, 0.0, n_batch=len(items))
        # observatory profiles: each rider's plan records its share of the
        # fused dispatch under its OWN signature (docs/observatory.md).
        # Recorded AFTER the shadow verdict: on a mismatch the non-probe
        # groups re-execute per-request (which records them on the path
        # that actually serves) — recording them here too would double
        # count and skew the fused rows/s floors.
        rows = cache.total_rows if cache is not None else 0

        def _rec_fused(group, g_ev, g_resp=None):
            for it in group:
                self._record_obs(it, g_ev, "fused", dt / n_reqs, rows=rows,
                                 occupancy=n_reqs, dispatch_t=t0, resp=g_resp)

        if slot.shadow_snap is not None:
            groups = list(uniq.values())
            fixed = self.ep.shadow_compare(groups[0][0].req, slot.shadow_snap,
                                           resps[0].encode(), "batch")
            if fixed is not None:
                # the SHARED image is corrupt (and quarantined): the probe's
                # signature group serves the oracle bytes already in hand;
                # the other groups — whose oracle answers were never
                # computed — re-execute per-request over the rebuilt state
                _rec_fused(groups[0], evs[0], resps[0])
                for it in groups[0]:
                    r = CoprResponse(fixed, from_device=False,
                                     encode_type=resps[0].encode_type)
                    self._stamp(r, it, kind="fused", occupancy=n_reqs,
                                total_s=dt / n_reqs)
                    results[it.index] = r
                for group in groups[1:]:
                    for it in group:
                        self._per_request(it, results, errors, kind="shadow")
                return None
        for group, g_ev, g_resp in zip(uniq.values(), evs, resps):
            _rec_fused(group, g_ev, g_resp)
        from_cache = slot.outcome not in ("", "miss", "too_big")
        for group, resp in zip(uniq.values(), resps):
            parts, enc_tp = self.ep._encode_response(resp)
            for it in group:
                r = CoprResponse(None, from_device=True, from_cache=from_cache,
                                 data_parts=parts, encode_type=enc_tp)
                self._stamp(r, it, kind="fused", occupancy=n_reqs,
                            total_s=dt / n_reqs)
                results[it.index] = r
        return None

    # -- admission ----------------------------------------------------------

    @staticmethod
    def _padding_waste(slots: list[_Slot], ev=None) -> float:
        if not slots:
            return 0.0
        counts = [len(s.cache.blocks) for s in slots]
        b = max(counts)
        if ev is not None:
            # zone-aware effective waste (docs/zone_maps.md): a pruned block
            # ships n_valid == 0 and scans as padding, so the batch's useful
            # fraction is its SURVIVOR count — the reported waste says so.
            # The shed predicate stays on raw block counts (no ev): pruning
            # never changes the padded shapes, so shedding can't recover it.
            from . import zone_maps as _zm

            counts = [
                int(keep.sum()) if (keep := _zm.prune_blocks(
                    s.cache, ev.sel_rpns, count=False)) is not None else c
                for s, c in zip(slots, counts)
            ]
        return 1.0 - sum(counts) / (len(counts) * b)

    def _shed_for_padding(self, slots: list[_Slot], results, errors) -> list[_Slot]:
        """Shed block-count outliers until the padded geometry wastes no
        more than the budget.  The LARGEST region sheds (its per-request
        dispatch is already amortized over its rows; keeping it would pad
        every smaller region up to its block count)."""
        live = list(slots)
        while len(live) > 1 and self._padding_waste(live) > self.cfg.padding_budget:
            biggest = max(live, key=lambda s: len(s.cache.blocks))
            live.remove(biggest)
            self._shed(biggest, "padding", results, errors)
        return live

    # -- sharded (mesh) geometry --------------------------------------------

    @staticmethod
    def _device_load(slots: list[_Slot], mesh) -> dict[int, int]:
        """Slabs per device for a prospective batch — the launcher's OWN
        geometry (``parallel.mesh.device_slab_load``), so shed decisions
        and occupancy metrics can never diverge from what launches."""
        from ..parallel.mesh import device_slab_load

        return device_slab_load([s.cache for s in slots], mesh)

    @staticmethod
    def _load_waste(load: dict[int, int]) -> float:
        """Wasted fraction of the (devices × slabs) geometry.  Devices with
        zero load are EXCLUDED: a 3-region batch on an 8-chip mesh leaves 5
        chips idle by region count, which shedding regions can only worsen —
        idle capacity shows in the per-device occupancy series instead.
        Counted waste is slab-count IMBALANCE among loaded devices (the
        regions-axis padding the slab axis rounds up to)."""
        loaded = [v for v in load.values() if v > 0]
        if not loaded:
            return 0.0
        return 1.0 - sum(loaded) / (len(loaded) * max(loaded))

    def _padding_waste_sharded(self, slots: list[_Slot], mesh) -> float:
        return self._load_waste(self._device_load(slots, mesh)) if slots else 0.0

    def _shed_for_padding_sharded(self, slots, mesh, results, errors):
        """Sharded-geometry padding shed: the largest region sheds while
        the loaded-device slab imbalance exceeds the budget.  Returns
        (live slots, final device load, final waste) — one assignment pass
        per iteration, and callers reuse the final geometry instead of
        recomputing it."""
        live = list(slots)
        load = self._device_load(live, mesh)
        waste = self._load_waste(load)
        while len(live) > 1 and waste > self.cfg.padding_budget:
            biggest = max(live, key=lambda s: len(s.cache.blocks))
            live.remove(biggest)
            self._shed(biggest, "padding", results, errors, path="mesh")
            load = self._device_load(live, mesh)
            waste = self._load_waste(load)
        return live, load, waste

    def _sharded_metrics(self, device_load: dict[int, int], pull_dt: float) -> None:
        """Per-device shard occupancy (used slabs / slab-axis size, idle
        devices included) + the collective-merge/pull time of the batch."""
        from ..util.metrics import REGISTRY

        s = max(max(device_load.values()), 1) if device_load else 1
        h = REGISTRY.histogram(
            "tikv_coprocessor_sched_device_occupancy",
            "Per-device slab occupancy of sharded cross-region batches",
            buckets=(0.0, 0.125, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        for did, n in device_load.items():
            h.observe(n / s, device=str(did))
        REGISTRY.histogram(
            "tikv_coprocessor_sharded_merge_seconds",
            "Collective-merge + packed-pull time of sharded batches",
        ).observe(pull_dt)

    def _per_request(self, it: _Item, results, errors, kind: str) -> None:
        """Serve one item on the per-request path, capturing its failure in
        ``errors`` so it stays its own (old unary semantics per request).
        Ticketed (continuous-mode) items are handed back to their caller's
        thread instead — executing them here would serialize every lane
        behind the dispatcher."""
        if results[it.index] is not None or errors[it.index] is not None:
            return
        if it.ticket is not None and not it.ticket.done.is_set():
            it.ticket.direct = True
            it.ticket.done.set()
            return
        try:
            # explicit pool-boundary handoff: the dispatcher serves this on
            # the rider's behalf, so its spans land in the rider's trace
            with trace.attach(it.trace_ctx):
                resp = self.ep.handle_request(it.req)
        except BaseException as exc:  # noqa: BLE001 — delivered per item
            errors[it.index] = exc
            return
        self._stamp(resp, it, kind=kind, occupancy=1)
        results[it.index] = resp

    def _record_obs(self, it: _Item, ev, path: str, latency_s: float, *,
                    rows: int = 0, encoding: str = "plain",
                    occupancy: int = 1, waste: float | None = None,
                    dispatch_t: float | None = None, resp=None) -> None:
        """One batch-served rider into the observatory: attributed latency
        share, the queue wait it actually paid, and its own trace id as the
        profile exemplar (docs/observatory.md)."""
        if not _obs.OBSERVATORY.enabled:
            return
        sig = getattr(ev, "obs_sig", "")
        if not sig and it.sig is not None:
            sig = _obs.sig_id(it.sig)
        qwait = (max(dispatch_t - it.enqueue_t, 0.0)
                 if dispatch_t is not None and it.enqueue_t else 0.0)
        prune = getattr(resp, "_obs_prune", None) or (0, 0)
        _obs.OBSERVATORY.record_serve(
            sig, path, latency_s, rows=rows, encoding=encoding,
            occupancy=occupancy, queue_wait_s=qwait, padding_waste=waste,
            trace_id=(it.trace_ctx or {}).get("trace_id"),
            desc=getattr(ev, "obs_desc", ""),
            blocks_examined=prune[0], blocks_pruned=prune[1])

    def _shed(self, slot: _Slot, reason: str, results, errors,
              path: str = "xregion") -> None:
        self._count_shed(reason)
        it0 = slot.items[0] if slot.items else None
        _obs.OBSERVATORY.record_decline(
            _obs.sig_id(it0.sig) if it0 is not None and it0.sig is not None
            else None,
            path, reason)
        for it in slot.items:
            self._per_request(it, results, errors, kind="shed:" + reason)

    def _device_failed(self, exc: BaseException, path: str) -> None:
        from ..util.metrics import REGISTRY
        from .tracker import count_path_fallback

        self.ep.device_fallbacks += 1
        self.ep.last_device_error = repr(exc)
        self.ep.breaker.record_failure(path)
        count_path_fallback(path, "device_error")
        REGISTRY.counter(
            "tikv_coprocessor_device_fallback_total",
            "Device-path failures that re-ran on the CPU pipeline",
        ).inc()

    # -- metrics ------------------------------------------------------------

    def _stamp(self, resp: CoprResponse, it: _Item, kind: str, occupancy: int,
               waste: float | None = None, total_s: float | None = None) -> None:
        from .tracker import stamp_sched

        resp.metrics = stamp_sched(resp.metrics, it.lane, kind, occupancy,
                                   waste=waste, total_s=total_s)

    def _batch_metrics(self, kind: str, n_reqs: int, dt: float, waste: float,
                       n_batch: int | None = None) -> None:
        """``n_reqs``: requests the device program answered (request_total /
        duration series — exactly-once, so a cold-fill counted by its own
        handle_request is excluded).  ``n_batch``: the batch's whole fan-in
        including the fill (batch/occupancy series)."""
        from ..util.metrics import REGISTRY

        n_batch = n_batch or n_reqs
        # the per-request series stay truthful under batch serving — one
        # duration observation PER REQUEST (each at the per-request share),
        # not a single mean observation, so count-weighted percentiles
        # compare honestly against the unary path
        REGISTRY.counter(
            "tikv_coprocessor_request_total", "Coprocessor requests, by type/path"
        ).inc(n_reqs, tp=str(REQ_TYPE_DAG), path="device")
        h = REGISTRY.histogram(
            "tikv_coprocessor_request_duration_seconds", "Coprocessor latency"
        )
        for _ in range(n_reqs):
            h.observe(dt / n_reqs, tp=str(REQ_TYPE_DAG))
        REGISTRY.counter(
            "tikv_coprocessor_batch_total", "Fused coprocessor batches"
        ).inc()
        REGISTRY.counter(
            "tikv_coprocessor_batch_queries_total", "Queries served fused"
        ).inc(n_batch)
        REGISTRY.counter(
            "tikv_coprocessor_sched_batches_total",
            "Scheduler micro-batches dispatched, by kind",
        ).inc(kind=kind)
        REGISTRY.histogram(
            "tikv_coprocessor_sched_batch_occupancy",
            "Requests per scheduler micro-batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128),
        ).observe(n_batch, kind=kind)
        REGISTRY.histogram(
            "tikv_coprocessor_sched_padding_waste",
            "Wasted fraction of padded block slots per cross-region batch",
            buckets=(0.01, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1.0),
        ).observe(waste, kind=kind)

    def _count_shed(self, reason: str) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_sched_shed_total",
            "Requests shed to the per-request path, by reason",
        ).inc(reason=reason)

    def _count_coalesce(self, outcome: str) -> None:
        """Continuous-mode admission outcomes for wire-coalesced unary
        requests: ``batched`` (served out of a dispatcher micro-batch),
        ``direct`` (handed back to the caller's thread), ``bypass``
        (scheduler off / plan not batchable), ``queue_full`` /
        ``busy_reject`` (admission control)."""
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_wire_coalesce_total",
            "Server-side RPC coalescing admissions, by outcome",
        ).inc(outcome=outcome)

    def _count_deadline(self, at: str) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_deadline_expired_total",
            "Requests shed because their deadline expired, by detection point",
        ).inc(at=at)

    def _gauge_depth(self) -> None:
        from ..util.metrics import REGISTRY

        g = REGISTRY.gauge(
            "tikv_coprocessor_sched_queue_depth",
            "Requests waiting in the scheduler, by priority lane",
        )
        for lane in LANES:
            g.set(len(self._queues[lane]), lane=lane)

    def _observe_wait(self, it: _Item) -> None:
        from ..util.metrics import REGISTRY

        wait = time.perf_counter() - it.enqueue_t
        REGISTRY.histogram(
            "tikv_coprocessor_sched_lane_wait_seconds",
            "Queue wait before dispatch, by priority lane",
        ).observe(wait, lane=it.lane)
        ov = getattr(self.ep, "overload", None)
        if ov is not None:
            # adaptive-controller evidence: sampled lane waits say whether
            # admitted work is actually draining (docs/robustness.md)
            ov.note_wait(wait)
