"""ScalarFuncSig -> kernel-name mapping (single source of truth).

The reference dispatches ~386 `ScalarFuncSig` arms
(tidb_query_expr/src/lib.rs:300); this framework's dtype-generic kernels fold
those families many-to-one.  Used by scripts/catalog_coverage.py to generate
CATALOG.md and by copr.tipb_bridge to translate wire-format sig numbers into
kernel calls.
"""

from __future__ import annotations

import re as _re

ALIASES = {
    # type-variant folds (dtype-generic kernels)
    "AbsInt": "abs", "AbsUInt": "abs", "AbsReal": "abs", "AbsDecimal": "abs",
    "CeilReal": "ceil", "CeilIntToInt": "ceil", "CeilIntToDec": "ceil",
    "CeilDecToInt": "ceil", "CeilDecToDec": "ceil",
    "FloorReal": "floor", "FloorIntToInt": "floor", "FloorIntToDec": "floor",
    "FloorDecToInt": "floor", "FloorDecToDec": "floor",
    "RoundReal": "round_real", "RoundInt": "round_int_frac", "RoundDec": "round_real_frac",
    "RoundWithFracReal": "round_real_frac", "RoundWithFracInt": "round_int_frac",
    "RoundWithFracDec": "round_real_frac",
    "TruncateInt": "truncate_int_frac", "TruncateReal": "truncate_real_frac",
    "TruncateDecimal": "truncate_real_frac", "TruncateUint": "truncate_int_frac",
    "Atan1Arg": "atan", "Atan2Args": "atan2",
    "Log1Arg": "ln", "Log2Args": "log_base", "Log2": "log2", "Log10": "log10",
    "Pow": "pow", "Conv": "conv", "CRC32": "crc32", "Sign": "sign", "Sqrt": "sqrt",
    "Degrees": "degrees", "Radians": "radians", "Exp": "exp",
    "Sin": "sin", "Cos": "cos", "Tan": "tan", "Cot": "cot",
    "Asin": "asin", "Acos": "acos",
    # comparison folds (per-type Lt/Le/...)
    **{f"{op}{t}": op.lower()
       for op in ("Lt", "Le", "Gt", "Ge", "Eq", "Ne")
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    **{f"NullEq{t}": "null_eq"
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    **{f"Coalesce{t}": "coalesce"
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    **{f"Greatest{t}": k for t, k in [
        ("Int", "greatest"), ("Real", "greatest_real"), ("Decimal", "greatest"),
        ("String", "greatest_string"), ("Time", "greatest"), ("Datetime", "greatest"),
        ("Date", "greatest"), ("Duration", "greatest"), ("CmpStringAsTime", "greatest_string"),
        ("CmpStringAsDate", "greatest_string"),
    ]},
    **{f"Least{t}": k for t, k in [
        ("Int", "least"), ("Real", "least_real"), ("Decimal", "least"),
        ("String", "least_string"), ("Time", "least"), ("Datetime", "least"),
        ("Date", "least"), ("Duration", "least"), ("CmpStringAsTime", "least_string"),
        ("CmpStringAsDate", "least_string"),
    ]},
    **{f"Interval{t}": "interval_int" for t in ("Int", "Real")},
    **{f"In{t}": "in" for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    # arithmetic folds
    **{f"{a}{t}": k for a, k in [
        ("Plus", "plus"), ("Minus", "minus"), ("Multiply", "multiply"),
    ] for t in ("Int", "IntUnsigned", "Real", "Decimal",
                "IntUnsignedUnsigned", "IntUnsignedSigned", "IntSignedUnsigned")},
    "DivideReal": "divide_real", "DivideDecimal": "divide_real",
    "IntDivideInt": "int_divide", "IntDivideDecimal": "int_divide",
    "ModInt": "mod", "ModIntUnsignedSigned": "mod", "ModIntSignedUnsigned": "mod",
    "ModIntUnsignedUnsigned": "mod", "ModReal": "mod", "ModDecimal": "mod",
    "UnaryMinusInt": "unary_minus", "UnaryMinusReal": "unary_minus",
    "UnaryMinusDecimal": "unary_minus", "UnaryNot": "not", "UnaryNotInt": "not",
    "UnaryNotReal": "not", "UnaryNotDecimal": "not", "UnaryNotJson": "not",
    # logical / bit
    "LogicalAnd": "and", "LogicalOr": "or", "LogicalXor": "xor",
    "BitAndSig": "bit_and", "BitOrSig": "bit_or", "BitXorSig": "bit_xor",
    "BitNegSig": "bit_neg", "LeftShift": "left_shift", "RightShift": "right_shift",
    # is-null / truth tests
    **{f"{t}IsNull": "is_null"
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    "IntIsTrue": "is_true", "RealIsTrue": "is_true", "DecimalIsTrue": "is_true",
    "IntIsTrueWithNull": "is_true", "RealIsTrueWithNull": "is_true",
    "DecimalIsTrueWithNull": "is_true",
    "IntIsFalse": "is_false", "RealIsFalse": "is_false", "DecimalIsFalse": "is_false",
    "IntIsFalseWithNull": "is_false", "RealIsFalseWithNull": "is_false",
    "DecimalIsFalseWithNull": "is_false",
    # control
    **{f"If{t}": "if" for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    **{f"IfNull{t}": "if_null"
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    **{f"CaseWhen{t}": "case_when"
       for t in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    # casts: 13 source x target families fold onto the cast_* kernels
    **{f"Cast{a}As{b}": f"cast_{a.lower()}_{b.lower()}".replace("time", "datetime")
       for a in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")
       for b in ("Int", "Real", "Decimal", "String", "Time", "Duration", "Json")},
    # string family names
    "Length": "length", "BitLength": "bit_length", "Ascii": "ascii",
    "Reverse": "reverse", "ReverseUtf8": "reverse_utf8",
    "Upper": "upper", "UpperUtf8": "upper", "Lower": "lower", "LowerUtf8": "lower",
    "Left": "left", "LeftUtf8": "left_utf8", "Right": "right", "RightUtf8": "right_utf8",
    "LTrim": "ltrim", "RTrim": "rtrim",
    "Trim1Arg": "trim", "Trim2Args": "trim2", "Trim3Args": "trim2",
    "Repeat": "repeat", "Replace": "replace", "Space": "space",
    "Strcmp": "strcmp", "Instr": "instr", "InstrUtf8": "instr",
    "Locate2Args": "locate", "Locate3Args": "locate3",
    "LocateBinary2Args": "locate", "LocateBinary3Args": "locate3",
    "Concat": "concat", "ConcatWs": "concat_ws", "Elt": "elt", "Field": "field",
    "FieldInt": "field", "FieldReal": "field", "FieldString": "field",
    "FindInSet": "find_in_set", "HexStrArg": "hex", "HexIntArg": "hex",
    "UnHex": "unhex", "Bin": "bin_int", "OctInt": "oct_int", "OctString": "oct_int",
    "CharLength": "char_length", "CharLengthUtf8": "char_length_utf8",
    "ToBase64": "to_base64", "FromBase64": "from_base64",
    "Lpad": "lpad", "LpadUtf8": "lpad", "Rpad": "rpad", "RpadUtf8": "rpad",
    "Substring2Args": "substr2", "Substring3Args": "substr3",
    "Substring2ArgsUtf8": "substr_utf8_2", "Substring3ArgsUtf8": "substr_utf8_3",
    "SubstringIndex": "substring_index", "MakeSet": "make_set",
    "InsertStr": "insert_str", "Insert": "insert_str", "InsertUtf8": "insert_str",
    "Ord": "ord", "Quote": "quote", "FormatWithLocale": "format", "Format": "format",
    "ExportSet3Arg": "export_set3", "ExportSet4Arg": "export_set4",
    "ExportSet5Arg": "export_set5", "CharFunc": "char_fn", "Soundex": "soundex",
    "Mid": "mid", "Position": "position",
    "LikeSig": "like", "RegexpSig": "regexp", "RegexpUtf8Sig": "regexp",
    "RegexpLikeSig": "regexp_like", "RegexpInStrSig": "regexp_instr",
    "RegexpReplaceSig": "regexp_replace", "RegexpSubstrSig": "regexp_substr",
    # encryption
    "Md5": "md5", "Sha1": "sha1", "Sha2": "sha2", "Compress": "compress",
    "Uncompress": "uncompress", "UncompressedLength": "uncompressed_length",
    "Password": "password",
    # time
    "Year": "year", "Month": "month", "DayOfMonth": "day_of_month",
    "DayOfWeek": "day_of_week", "DayOfYear": "day_of_year", "Hour": "hour",
    "Minute": "minute", "Second": "second", "MicroSecond": "micro_second",
    "DayName": "day_name", "MonthName": "month_name", "LastDay": "last_day",
    "WeekDay": "week_day", "WeekOfYear": "week_of_year",
    "WeekWithMode": "week_with_mode", "WeekWithoutMode": "week_of_year",
    "YearWeekWithMode": "year_week", "YearWeekWithoutMode": "year_week",
    "Quarter": "quarter", "ToDays": "to_days", "ToSeconds": "to_seconds",
    "FromDays": "from_days", "MakeDate": "makedate", "MakeTime": "maketime",
    "PeriodAdd": "period_add", "PeriodDiff": "period_diff",
    "DateDiff": "date_diff", "NullTimeDiff": "timediff",
    "TimeToSec": "time_to_sec", "SecToTime": "sec_to_time",
    "AddDatetimeAndDuration": "add_datetime_duration",
    "SubDatetimeAndDuration": "sub_datetime_duration",
    "AddDurationAndDuration": "add_duration",
    "SubDurationAndDuration": "sub_duration",
    "AddDateAndDuration": "add_datetime_duration",
    "SubDateAndDuration": "sub_datetime_duration",
    "ConvertTz": "convert_tz", "GetFormat": "get_format",
    "DateFormatSig": "date_format", "TimeFormat": "time_format",
    "StrToDateDate": "str_to_date", "StrToDateDatetime": "str_to_date",
    "StrToDateDuration": "str_to_date",
    "UnixTimestampInt": "unix_timestamp", "UnixTimestampDec": "unix_timestamp",
    "UnixTimestampCurrent": "~ctx", "FromUnixTime1Arg": "from_unixtime",
    "FromUnixTime2Arg": "from_unixtime", "ExtractDatetime": "extract_datetime",
    "ExtractDatetimeFromString": "extract_datetime", "ExtractDuration": "extract_datetime",
    "AddDateStringInt": "date_add", "AddDateStringString": "date_add",
    "AddDateIntString": "date_add", "AddDateIntInt": "date_add",
    "AddDateDatetimeInt": "date_add", "AddDateDatetimeString": "date_add",
    "SubDateStringInt": "date_sub", "SubDateStringString": "date_sub",
    "SubDateIntString": "date_sub", "SubDateIntInt": "date_sub",
    "SubDateDatetimeInt": "date_sub", "SubDateDatetimeString": "date_sub",
    "Date": "cast_datetime_date", "DurationDurationTimeDiff": "sub_duration",
    "Locate2ArgsUtf8": "locate", "Locate3ArgsUtf8": "locate3",
    "PlusIntSignedSigned": "plus",
    "Pi": "~const-fold", "Rand": "~nondeterministic",
    "RandWithSeedFirstGen": "~nondeterministic", "RandomBytes": "~nondeterministic",
    "AddDateAndString": "add_date_and_string",
    "AddDatetimeAndString": "add_datetime_and_string",
    "AddDurationAndString": "add_duration_and_string",
    "AddStringAndDuration": "add_string_and_duration",
    "SubDatetimeAndString": "sub_datetime_and_string",
    "SubStringAndDuration": "sub_string_and_duration",
    "DurationHour": "duration_hours", "DurationMinute": "minute",
    "DurationSecond": "second", "DurationMicroSecond": "micro_second",
    "TimestampDiff": "timestamp_diff_days", "AddTimeDateTimeNull": "add_datetime_duration",
    "AddTimeDurationNull": "add_duration", "AddTimeStringNull": "add_time_string_null",
    # json
    "JsonArraySig": "json_array", "JsonObjectSig": "json_object",
    "JsonExtractSig": "json_extract", "JsonUnquoteSig": "json_unquote",
    "JsonTypeSig": "json_type", "JsonSetSig": "json_set",
    "JsonInsertSig": "json_insert", "JsonReplaceSig": "json_replace",
    "JsonRemoveSig": "json_remove", "JsonMergeSig": "json_merge",
    "JsonMergePatchSig": "json_merge_patch", "JsonMergePreserveSig": "json_merge",
    "JsonContainsSig": "json_contains", "JsonContainsPathSig": "json_contains_path",
    "JsonLengthSig": "json_length", "JsonDepthSig": "json_depth",
    "JsonKeysSig": "json_keys", "JsonKeys2ArgsSig": "json_keys",
    "JsonValidJsonSig": "json_valid", "JsonValidStringSig": "json_valid",
    "JsonValidOthersSig": "json_valid", "JsonQuoteSig": "json_quote",
    "JsonSearchSig": "json_search", "JsonStorageSizeSig": "json_storage_size",
    "JsonPrettySig": "json_pretty", "JsonArrayAppendSig": "json_array_append",
    "JsonArrayInsertSig": "json_array_insert", "JsonMemberOfSig": "json_member_of",
    "JsonOverlapsSig": "json_overlaps",
    # miscellaneous
    "InetAton": "inet_aton", "InetNtoa": "inet_ntoa",
    "Inet6Aton": "inet6_aton", "Inet6Ntoa": "inet6_ntoa",
    "IsIPv4": "is_ipv4", "IsIPv6": "is_ipv6",
    "IsIPv4Compat": "is_ipv4_compat", "IsIPv4Mapped": "is_ipv4_mapped",
    "AnyValue": "any_value", "UUID": "~nondeterministic", "Uuid": "~nondeterministic",
    "CoalesceBytes": "coalesce", "GreatestCmpStringAsTime": "greatest_string",
    "IntAnyValue": "any_value", "RealAnyValue": "any_value",
    "StringAnyValue": "any_value", "DecimalAnyValue": "any_value",
    "TimeAnyValue": "any_value", "DurationAnyValue": "any_value",
    "JsonAnyValue": "any_value",
}

# sigs deliberately out of scope, with reasons (the honest "no" column)
UNSUPPORTED = {
    "~ctx": "needs evaluation-context wall clock (non-deterministic pushdown)",
    "~const-fold": "constant; folded by the planner before pushdown",
    "~frac": "needs frac-aware bytes plumbing (decimal formatting)",
    "~nondeterministic": "non-deterministic function",
}


def camel_to_snake(name: str) -> str:
    s = _re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return _re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s).lower()


def resolve_sig(sig_name: str, kernels=None) -> str | None:
    """Map a reference ScalarFuncSig name to this framework's kernel name.

    Returns None when unmapped; a "~"-prefixed result means deliberately
    unsupported (see UNSUPPORTED for the reason).
    """
    mapped = ALIASES.get(sig_name)
    if mapped is not None:
        return mapped
    if kernels is None:
        from .kernels import KERNELS as kernels
    snake = camel_to_snake(sig_name)
    return snake if snake in kernels else None
