"""Arrow-like chunk response codec (tipb EncodeType::TypeChunk).

Byte layout per column, re-expressed from the reference's
``tidb_query_datatype/src/codec/chunk/column.rs:938`` (write_chunk_column) and
``:910`` (decode):

    u32le row_count | u32le null_cnt
    | null bitmap ((rows+7)/8 bytes, bit=1 ⇒ NOT null, LSB-first)   iff null_cnt>0
    | (rows+1) × i64le end-offsets                                  iff var-len
    | cell data (fixed_len × rows for fixed-width columns)

Fixed widths follow ``column.rs:47-63`` Column::new: 8 bytes for ints,
doubles, duration and packed times, 4 for float32, 40 for the decimal struct
(``decimal.rs:887`` DECIMAL_STRUCT_SIZE); strings/bytes/json/enum/set are
var-len.  The decimal cell is the reference's in-memory ``Decimal`` struct
(int_cnt, frac_cnt, result_frac_cnt, negative, 9 base-1e9 words); times ride
their packed-u64 wire form and durations are i64 nanoseconds
(``duration.rs:614``).
"""

from __future__ import annotations

import struct

import numpy as np

from .datatypes import EvalType, FieldType, FieldTypeTp

DECIMAL_STRUCT_SIZE = 40
_DIGITS_PER_WORD = 9
_WORD_BUF_LEN = 9

_FIXED_LEN = {
    FieldTypeTp.TINY: 8,
    FieldTypeTp.SHORT: 8,
    FieldTypeTp.INT24: 8,
    FieldTypeTp.LONG: 8,
    FieldTypeTp.LONGLONG: 8,
    FieldTypeTp.DOUBLE: 8,
    FieldTypeTp.FLOAT: 4,
    FieldTypeTp.DURATION: 8,
    FieldTypeTp.DATE: 8,
    FieldTypeTp.DATETIME: 8,
    FieldTypeTp.TIMESTAMP: 8,
    FieldTypeTp.NEW_DECIMAL: DECIMAL_STRUCT_SIZE,
}


def fixed_len(ft: FieldType) -> int:
    """0 means var-len."""
    return _FIXED_LEN.get(ft.tp, 0)


# ---------------------------------------------------------------------------
# decimal struct cells
# ---------------------------------------------------------------------------

def encode_decimal_cell(unscaled: int, frac: int, result_frac: int | None = None) -> bytes:
    """(unscaled, frac) -> the 40-byte Decimal struct."""
    neg = unscaled < 0
    digits = str(-unscaled if neg else unscaled)
    if frac:
        digits = digits.rjust(frac + 1, "0")
        int_part, frac_part = digits[:-frac], digits[-frac:]
    else:
        int_part, frac_part = digits, ""
    int_part = int_part.lstrip("0")
    int_cnt = len(int_part) if (int_part or frac_part) else 1
    words = []
    if int_part:
        first = len(int_part) % _DIGITS_PER_WORD or _DIGITS_PER_WORD
        words.append(int(int_part[:first]))
        for i in range(first, len(int_part), _DIGITS_PER_WORD):
            words.append(int(int_part[i:i + _DIGITS_PER_WORD]))
    for i in range(0, len(frac_part), _DIGITS_PER_WORD):
        words.append(int(frac_part[i:i + _DIGITS_PER_WORD].ljust(_DIGITS_PER_WORD, "0")))
    if len(words) > _WORD_BUF_LEN:
        raise ValueError("decimal exceeds 81 digits")
    words += [0] * (_WORD_BUF_LEN - len(words))
    rf = frac if result_frac is None else result_frac
    return struct.pack("<BBBB9I", int_cnt, frac, rf, 1 if neg else 0, *words)


def decode_decimal_cell(cell: bytes) -> tuple[int, int]:
    """40-byte Decimal struct -> (unscaled, frac)."""
    int_cnt, frac_cnt, _rf, neg, *words = struct.unpack("<BBBB9I", cell)
    int_words = (int_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    frac_words = (frac_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    int_val = 0
    for w in words[:int_words]:
        int_val = int_val * 10**_DIGITS_PER_WORD + w
    frac_str = "".join(
        str(w).rjust(_DIGITS_PER_WORD, "0") for w in words[int_words:int_words + frac_words]
    )[:frac_cnt]
    unscaled = int_val * 10**frac_cnt + int(frac_str or "0")
    return (-unscaled if neg else unscaled), frac_cnt


# ---------------------------------------------------------------------------
# column encode / decode
# ---------------------------------------------------------------------------

class ChunkColumn:
    """Append-oriented builder mirroring column.rs Column."""

    def __init__(self, ft: FieldType):
        self.ft = ft
        self.fixed = fixed_len(ft)
        self.rows = 0
        self.null_cnt = 0
        self.bitmap = bytearray()
        self.offsets = [0]  # var-len only
        self.data = bytearray()

    def _bit(self, on: bool) -> None:
        idx, pos = divmod(self.rows, 8)
        if idx >= len(self.bitmap):
            self.bitmap.append(0)
        if on:
            self.bitmap[idx] |= 1 << pos

    def append_null(self) -> None:
        self._bit(False)
        self.null_cnt += 1
        if self.fixed:
            self.data += b"\x00" * self.fixed
        else:
            self.offsets.append(self.offsets[-1])
        self.rows += 1

    def append_raw(self, cell: bytes) -> None:
        self._bit(True)
        if self.fixed and len(cell) != self.fixed:
            raise ValueError(f"cell width {len(cell)} != {self.fixed}")
        self.data += cell
        if not self.fixed:
            self.offsets.append(len(self.data))
        self.rows += 1

    def append(self, value) -> None:
        """Append a python-domain value for this column's field type."""
        if value is None:
            self.append_null()
            return
        et = self.ft.eval_type
        if et == EvalType.INT:
            self.append_raw(struct.pack("<q", value) if not self.ft.is_unsigned
                            else struct.pack("<Q", value & (1 << 64) - 1))
        elif et == EvalType.REAL:
            self.append_raw(struct.pack("<f" if self.fixed == 4 else "<d", value))
        elif et == EvalType.DECIMAL:
            unscaled, frac = value if isinstance(value, tuple) else (value, self.ft.decimal)
            self.append_raw(encode_decimal_cell(unscaled, frac))
        elif et == EvalType.DATETIME:
            self.append_raw(struct.pack("<Q", value & (1 << 64) - 1))
        elif et == EvalType.DURATION:
            self.append_raw(struct.pack("<q", value))
        elif et == EvalType.ENUM:
            # u64 1-based index + name bytes (TiDB enum chunk layout)
            idx = int(value)
            name = self.ft.elems[idx - 1] if 0 < idx <= len(self.ft.elems) else b""
            self.append_raw(struct.pack("<Q", idx) + name)
        else:  # BYTES / JSON / SET ride their binary payloads
            self.append_raw(bytes(value))

    def extend(self, values: list) -> None:
        """Vectorized bulk append for fixed-width numeric columns (one numpy
        pass instead of a ``struct.pack`` per row); var-len and decimal
        columns fall back to per-value ``append``.  Byte-identical to
        appending each value in order."""
        et = self.ft.eval_type
        vectorizable = (
            et in (EvalType.INT, EvalType.DATETIME, EvalType.DURATION)
            or (et == EvalType.REAL and self.fixed == 8)
        )
        if not vectorizable or len(values) < 16:
            for v in values:
                self.append(v)
            return
        n = len(values)
        nulls = np.fromiter((v is None for v in values), bool, n)
        filled = [0 if v is None else v for v in values]
        if et == EvalType.REAL:
            cells = np.array(filled, dtype="<f8").view(np.uint8).reshape(n, 8)
        elif et == EvalType.INT and self.ft.is_unsigned:
            cells = np.array([v & (1 << 64) - 1 for v in filled],
                             dtype="<u8").view(np.uint8).reshape(n, 8)
        elif et == EvalType.DATETIME:
            cells = np.array([v & (1 << 64) - 1 for v in filled],
                             dtype="<u8").view(np.uint8).reshape(n, 8)
        else:
            cells = np.array(filled, dtype="<i8").view(np.uint8).reshape(n, 8)
        cells[nulls] = 0
        # null bitmap: bit=1 means NOT null, LSB-first within each byte
        start = self.rows
        need = (start + n + 7) // 8 - len(self.bitmap)
        if need > 0:
            self.bitmap += bytes(need)
        bits = np.unpackbits(
            np.frombuffer(bytes(self.bitmap), np.uint8), bitorder="little"
        )[: start + n]
        bits[start:] = ~nulls
        self.bitmap = bytearray(np.packbits(bits, bitorder="little").tobytes())
        self.data += cells.tobytes()
        self.rows += n
        self.null_cnt += int(nulls.sum())

    def encode(self) -> bytes:
        out = bytearray()
        out += struct.pack("<II", self.rows, self.null_cnt)
        if self.null_cnt > 0:
            out += self.bitmap[: (self.rows + 7) // 8]
        if not self.fixed:
            for off in self.offsets:
                out += struct.pack("<q", off)
        out += self.data
        return bytes(out)


def decode_column(buf: bytes, pos: int, ft: FieldType) -> tuple["ChunkColumn", int]:
    rows, null_cnt = struct.unpack_from("<II", buf, pos)
    pos += 8
    col = ChunkColumn(ft)
    col.rows = rows
    col.null_cnt = null_cnt
    nbytes = (rows + 7) // 8
    if null_cnt > 0:
        col.bitmap = bytearray(buf[pos:pos + nbytes])
        pos += nbytes
    else:
        col.bitmap = bytearray(b"\xff" * nbytes)
    if col.fixed:
        dl = col.fixed * rows
        col.offsets = []
    else:
        # one vectorized read of the (rows+1) end-offsets instead of a
        # struct.unpack_from per row
        col.offsets = np.frombuffer(
            bytes(buf[pos:pos + 8 * (rows + 1)]), dtype="<i8"
        ).tolist()
        if len(col.offsets) != rows + 1:
            raise ValueError("truncated chunk column offsets")
        pos += 8 * (rows + 1)
        dl = col.offsets[-1] if col.offsets else 0
    if pos + dl > len(buf):
        raise ValueError("truncated chunk column")
    col.data = bytearray(buf[pos:pos + dl])
    return col, pos + dl


def column_values(col: ChunkColumn) -> list:
    """Decode a column back to python-domain values (None for nulls)."""
    out = []
    ft = col.ft
    et = ft.eval_type
    for i in range(col.rows):
        if not (col.bitmap[i >> 3] >> (i & 7)) & 1:
            out.append(None)
            continue
        if col.fixed:
            cell = bytes(col.data[i * col.fixed:(i + 1) * col.fixed])
        else:
            cell = bytes(col.data[col.offsets[i]:col.offsets[i + 1]])
        if et == EvalType.INT:
            out.append(struct.unpack("<Q" if ft.is_unsigned else "<q", cell)[0])
        elif et == EvalType.REAL:
            out.append(struct.unpack("<f" if col.fixed == 4 else "<d", cell)[0])
        elif et == EvalType.DECIMAL:
            out.append(decode_decimal_cell(cell))
        elif et == EvalType.DATETIME:
            out.append(struct.unpack("<Q", cell)[0])
        elif et == EvalType.DURATION:
            out.append(struct.unpack("<q", cell)[0])
        elif et == EvalType.ENUM:
            out.append(struct.unpack_from("<Q", cell)[0])
        else:
            out.append(cell)
    return out


def encode_chunk(columns: list[ChunkColumn]) -> bytes:
    """chunk.rs:98 write_chunk — columns back to back."""
    return b"".join(c.encode() for c in columns)


def decode_chunk(buf: bytes, field_types: list[FieldType]) -> list[ChunkColumn]:
    pos = 0
    cols = []
    for ft in field_types:
        col, pos = decode_column(buf, pos, ft)
        cols.append(col)
    if pos != len(buf):
        raise ValueError(f"trailing {len(buf) - pos} bytes after chunk")
    return cols
