"""Arrow-like chunk response codec (tipb EncodeType::TypeChunk).

Byte layout per column, re-expressed from the reference's
``tidb_query_datatype/src/codec/chunk/column.rs:938`` (write_chunk_column) and
``:910`` (decode):

    u32le row_count | u32le null_cnt
    | null bitmap ((rows+7)/8 bytes, bit=1 ⇒ NOT null, LSB-first)   iff null_cnt>0
    | (rows+1) × i64le end-offsets                                  iff var-len
    | cell data (fixed_len × rows for fixed-width columns)

Fixed widths follow ``column.rs:47-63`` Column::new: 8 bytes for ints,
doubles, duration and packed times, 4 for float32, 40 for the decimal struct
(``decimal.rs:887`` DECIMAL_STRUCT_SIZE); strings/bytes/json/enum/set are
var-len.  The decimal cell is the reference's in-memory ``Decimal`` struct
(int_cnt, frac_cnt, result_frac_cnt, negative, 9 base-1e9 words); times ride
their packed-u64 wire form and durations are i64 nanoseconds
(``duration.rs:614``).
"""

from __future__ import annotations

import struct

import numpy as np

from .datatypes import EvalType, FieldType, FieldTypeTp

DECIMAL_STRUCT_SIZE = 40
_DIGITS_PER_WORD = 9
_WORD_BUF_LEN = 9

_FIXED_LEN = {
    FieldTypeTp.TINY: 8,
    FieldTypeTp.SHORT: 8,
    FieldTypeTp.INT24: 8,
    FieldTypeTp.LONG: 8,
    FieldTypeTp.LONGLONG: 8,
    FieldTypeTp.DOUBLE: 8,
    FieldTypeTp.FLOAT: 4,
    FieldTypeTp.DURATION: 8,
    FieldTypeTp.DATE: 8,
    FieldTypeTp.DATETIME: 8,
    FieldTypeTp.TIMESTAMP: 8,
    FieldTypeTp.NEW_DECIMAL: DECIMAL_STRUCT_SIZE,
}


def fixed_len(ft: FieldType) -> int:
    """0 means var-len."""
    return _FIXED_LEN.get(ft.tp, 0)


# ---------------------------------------------------------------------------
# decimal struct cells
# ---------------------------------------------------------------------------

def encode_decimal_cell(unscaled: int, frac: int, result_frac: int | None = None) -> bytes:
    """(unscaled, frac) -> the 40-byte Decimal struct."""
    neg = unscaled < 0
    digits = str(-unscaled if neg else unscaled)
    if frac:
        digits = digits.rjust(frac + 1, "0")
        int_part, frac_part = digits[:-frac], digits[-frac:]
    else:
        int_part, frac_part = digits, ""
    int_part = int_part.lstrip("0")
    int_cnt = len(int_part) if (int_part or frac_part) else 1
    words = []
    if int_part:
        first = len(int_part) % _DIGITS_PER_WORD or _DIGITS_PER_WORD
        words.append(int(int_part[:first]))
        for i in range(first, len(int_part), _DIGITS_PER_WORD):
            words.append(int(int_part[i:i + _DIGITS_PER_WORD]))
    for i in range(0, len(frac_part), _DIGITS_PER_WORD):
        words.append(int(frac_part[i:i + _DIGITS_PER_WORD].ljust(_DIGITS_PER_WORD, "0")))
    if len(words) > _WORD_BUF_LEN:
        raise ValueError("decimal exceeds 81 digits")
    words += [0] * (_WORD_BUF_LEN - len(words))
    rf = frac if result_frac is None else result_frac
    return struct.pack("<BBBB9I", int_cnt, frac, rf, 1 if neg else 0, *words)


def decode_decimal_cell(cell: bytes) -> tuple[int, int]:
    """40-byte Decimal struct -> (unscaled, frac)."""
    int_cnt, frac_cnt, _rf, neg, *words = struct.unpack("<BBBB9I", cell)
    int_words = (int_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    frac_words = (frac_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    int_val = 0
    for w in words[:int_words]:
        int_val = int_val * 10**_DIGITS_PER_WORD + w
    frac_str = "".join(
        str(w).rjust(_DIGITS_PER_WORD, "0") for w in words[int_words:int_words + frac_words]
    )[:frac_cnt]
    unscaled = int_val * 10**frac_cnt + int(frac_str or "0")
    return (-unscaled if neg else unscaled), frac_cnt


# ---------------------------------------------------------------------------
# column encode / decode
# ---------------------------------------------------------------------------

class ChunkColumn:
    """Append-oriented builder mirroring column.rs Column."""

    def __init__(self, ft: FieldType):
        self.ft = ft
        self.fixed = fixed_len(ft)
        self.rows = 0
        self.null_cnt = 0
        self.bitmap = bytearray()
        self.offsets = [0]  # var-len only
        self.data = bytearray()

    def _bit(self, on: bool) -> None:
        idx, pos = divmod(self.rows, 8)
        if idx >= len(self.bitmap):
            self.bitmap.append(0)
        if on:
            self.bitmap[idx] |= 1 << pos

    def append_null(self) -> None:
        self._bit(False)
        self.null_cnt += 1
        if self.fixed:
            self.data += b"\x00" * self.fixed
        else:
            self.offsets.append(self.offsets[-1])
        self.rows += 1

    def append_raw(self, cell: bytes) -> None:
        self._bit(True)
        if self.fixed and len(cell) != self.fixed:
            raise ValueError(f"cell width {len(cell)} != {self.fixed}")
        self.data += cell
        if not self.fixed:
            self.offsets.append(len(self.data))
        self.rows += 1

    def append(self, value) -> None:
        """Append a python-domain value for this column's field type."""
        if value is None:
            self.append_null()
            return
        et = self.ft.eval_type
        if et == EvalType.INT:
            self.append_raw(struct.pack("<q", value) if not self.ft.is_unsigned
                            else struct.pack("<Q", value & (1 << 64) - 1))
        elif et == EvalType.REAL:
            self.append_raw(struct.pack("<f" if self.fixed == 4 else "<d", value))
        elif et == EvalType.DECIMAL:
            unscaled, frac = value if isinstance(value, tuple) else (value, self.ft.decimal)
            self.append_raw(encode_decimal_cell(unscaled, frac))
        elif et == EvalType.DATETIME:
            self.append_raw(struct.pack("<Q", value & (1 << 64) - 1))
        elif et == EvalType.DURATION:
            self.append_raw(struct.pack("<q", value))
        elif et == EvalType.ENUM:
            # u64 1-based index + name bytes (TiDB enum chunk layout)
            idx = int(value)
            name = self.ft.elems[idx - 1] if 0 < idx <= len(self.ft.elems) else b""
            self.append_raw(struct.pack("<Q", idx) + name)
        else:  # BYTES / JSON / SET ride their binary payloads
            self.append_raw(bytes(value))

    def extend(self, values: list) -> None:
        """Vectorized bulk append for fixed-width numeric columns (one numpy
        pass instead of a ``struct.pack`` per row); var-len and decimal
        columns fall back to per-value ``append``.  Byte-identical to
        appending each value in order."""
        et = self.ft.eval_type
        vectorizable = (
            et in (EvalType.INT, EvalType.DATETIME, EvalType.DURATION)
            or (et == EvalType.REAL and self.fixed == 8)
        )
        if not vectorizable or len(values) < 16:
            for v in values:
                self.append(v)
            return
        n = len(values)
        nulls = np.fromiter((v is None for v in values), bool, n)
        filled = [0 if v is None else v for v in values]
        if et == EvalType.REAL:
            cells = np.array(filled, dtype="<f8").view(np.uint8).reshape(n, 8)
        elif et == EvalType.INT and self.ft.is_unsigned:
            cells = np.array([v & (1 << 64) - 1 for v in filled],
                             dtype="<u8").view(np.uint8).reshape(n, 8)
        elif et == EvalType.DATETIME:
            cells = np.array([v & (1 << 64) - 1 for v in filled],
                             dtype="<u8").view(np.uint8).reshape(n, 8)
        else:
            cells = np.array(filled, dtype="<i8").view(np.uint8).reshape(n, 8)
        cells[nulls] = 0
        # null bitmap: bit=1 means NOT null, LSB-first within each byte
        start = self.rows
        need = (start + n + 7) // 8 - len(self.bitmap)
        if need > 0:
            self.bitmap += bytes(need)
        bits = np.unpackbits(
            np.frombuffer(bytes(self.bitmap), np.uint8), bitorder="little"
        )[: start + n]
        bits[start:] = ~nulls
        self.bitmap = bytearray(np.packbits(bits, bitorder="little").tobytes())
        self.data += cells.tobytes()
        self.rows += n
        self.null_cnt += int(nulls.sum())

    def encode(self) -> bytes:
        out = bytearray()
        out += struct.pack("<II", self.rows, self.null_cnt)
        if self.null_cnt > 0:
            out += self.bitmap[: (self.rows + 7) // 8]
        if not self.fixed:
            for off in self.offsets:
                out += struct.pack("<q", off)
        out += self.data
        return bytes(out)


def decode_column(buf: bytes, pos: int, ft: FieldType) -> tuple["ChunkColumn", int]:
    rows, null_cnt = struct.unpack_from("<II", buf, pos)
    pos += 8
    col = ChunkColumn(ft)
    col.rows = rows
    col.null_cnt = null_cnt
    nbytes = (rows + 7) // 8
    if null_cnt > 0:
        col.bitmap = bytearray(buf[pos:pos + nbytes])
        pos += nbytes
    else:
        col.bitmap = bytearray(b"\xff" * nbytes)
    if col.fixed:
        dl = col.fixed * rows
        col.offsets = []
    else:
        # one vectorized read of the (rows+1) end-offsets instead of a
        # struct.unpack_from per row
        col.offsets = np.frombuffer(
            bytes(buf[pos:pos + 8 * (rows + 1)]), dtype="<i8"
        ).tolist()
        if len(col.offsets) != rows + 1:
            raise ValueError("truncated chunk column offsets")
        pos += 8 * (rows + 1)
        dl = col.offsets[-1] if col.offsets else 0
    if pos + dl > len(buf):
        raise ValueError("truncated chunk column")
    col.data = bytearray(buf[pos:pos + dl])
    return col, pos + dl


def column_values(col: ChunkColumn) -> list:
    """Decode a column back to python-domain values (None for nulls)."""
    out = []
    ft = col.ft
    et = ft.eval_type
    for i in range(col.rows):
        if not (col.bitmap[i >> 3] >> (i & 7)) & 1:
            out.append(None)
            continue
        if col.fixed:
            cell = bytes(col.data[i * col.fixed:(i + 1) * col.fixed])
        else:
            cell = bytes(col.data[col.offsets[i]:col.offsets[i + 1]])
        if et == EvalType.INT:
            out.append(struct.unpack("<Q" if ft.is_unsigned else "<q", cell)[0])
        elif et == EvalType.REAL:
            out.append(struct.unpack("<f" if col.fixed == 4 else "<d", cell)[0])
        elif et == EvalType.DECIMAL:
            out.append(decode_decimal_cell(cell))
        elif et == EvalType.DATETIME:
            out.append(struct.unpack("<Q", cell)[0])
        elif et == EvalType.DURATION:
            out.append(struct.unpack("<q", cell)[0])
        elif et == EvalType.ENUM:
            out.append(struct.unpack_from("<Q", cell)[0])
        else:
            out.append(cell)
    return out


def encode_chunk(columns: list[ChunkColumn]) -> bytes:
    """chunk.rs:98 write_chunk — columns back to back."""
    return b"".join(c.encode() for c in columns)


# ---------------------------------------------------------------------------
# vectorized column assembly (the serving-plane encoder, docs/wire_path.md)
#
# The append-oriented ChunkColumn above mirrors the reference builder; the
# wire serving plane encodes whole numpy columns at once — null bitmap via
# packbits, fixed cells as one dtype view, end-offsets as one cumsum — with
# bytes identical to appending each value through ChunkColumn (enforced by
# tests/test_chunk_codec.py).
# ---------------------------------------------------------------------------

_POW10 = np.array([10 ** k for k in range(20)], dtype=np.uint64)
_WORD = np.uint64(10 ** _DIGITS_PER_WORD)

#: widest decimal scale the vectorized struct builder covers (two base-1e9
#: frac words); the serving plane declines wider scales to the datum codec
MAX_VEC_DECIMAL_FRAC = 18


def encode_decimal_cells(unscaled: np.ndarray, frac: int) -> np.ndarray:
    """(n,) int64 fixed-point values -> (n, 40) uint8 Decimal structs,
    byte-identical to ``encode_decimal_cell(int(v), frac)`` per row."""
    if not 0 <= frac <= MAX_VEC_DECIMAL_FRAC:
        raise ValueError(f"vectorized decimal frac out of range: {frac}")
    a = np.ascontiguousarray(unscaled, dtype=np.int64)
    n = len(a)
    u = a.view(np.uint64)
    neg = a < 0
    mag = np.where(neg, ~u + np.uint64(1), u)  # |v| (2**63 fits uint64)
    ipart = mag // _POW10[frac]
    fpart = mag - ipart * _POW10[frac]
    # integer digit count: exact uint64 compares, no float log10
    ndig = np.searchsorted(_POW10[1:], ipart, side="right") + (ipart > 0)
    int_cnt = np.maximum(ndig, 1) if frac == 0 else ndig
    # integer words, grouped from the right (≤3 words for 19 digits)
    iw = np.stack([ipart // (_WORD * _WORD),
                   (ipart // _WORD) % _WORD, ipart % _WORD], axis=1)
    nw = (int_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    # frac words, grouped from the left and padded right with zeros
    nfw = (frac + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    if frac == 0:
        fw = np.zeros((n, 0), dtype=np.uint64)
    elif frac <= _DIGITS_PER_WORD:
        fw = (fpart * _POW10[_DIGITS_PER_WORD - frac])[:, None]
    else:
        hi = fpart // _POW10[frac - _DIGITS_PER_WORD]
        lo = (fpart % _POW10[frac - _DIGITS_PER_WORD]) * _POW10[
            2 * _DIGITS_PER_WORD - frac]
        fw = np.stack([hi, lo], axis=1)
    words = np.zeros((n, _WORD_BUF_LEN), dtype="<u4")
    for k in (0, 1, 2, 3):  # nw ∈ {0..3}: bounded cases, not per-row python
        m = nw == k
        if not m.any():
            continue
        if k:
            words[np.ix_(m, range(k))] = iw[m][:, 3 - k:]
        if nfw:
            words[np.ix_(m, range(k, k + nfw))] = fw[m]
    cells = np.empty((n, DECIMAL_STRUCT_SIZE), dtype=np.uint8)
    cells[:, 0] = int_cnt
    cells[:, 1] = frac
    cells[:, 2] = frac
    cells[:, 3] = neg
    cells[:, 4:] = words.view(np.uint8).reshape(n, 4 * _WORD_BUF_LEN)
    return cells


def decode_decimal_cells(cells: np.ndarray, frac: int) -> np.ndarray:
    """(n, 40) uint8 Decimal structs -> (n,) int64 unscaled values — the
    vectorized inverse of :func:`encode_decimal_cells` for the constant
    per-column ``frac`` the serving plane encodes with (cell frac_cnt ==
    column frac).  Value-identical to ``decode_decimal_cell`` per row."""
    if not 0 <= frac <= MAX_VEC_DECIMAL_FRAC:
        raise ValueError(f"vectorized decimal frac out of range: {frac}")
    cells = np.ascontiguousarray(cells, dtype=np.uint8).reshape(
        -1, DECIMAL_STRUCT_SIZE)
    n = len(cells)
    int_cnt = cells[:, 0].astype(np.int64)
    neg = cells[:, 3] != 0
    words = cells[:, 4:].view("<u4").reshape(n, _WORD_BUF_LEN).astype(np.uint64)
    nw = (int_cnt + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    nfw = (frac + _DIGITS_PER_WORD - 1) // _DIGITS_PER_WORD
    ipart = np.zeros(n, dtype=np.uint64)
    fpart = np.zeros(n, dtype=np.uint64)
    for k in (0, 1, 2, 3):  # int-word count ∈ {0..3}: bounded cases
        m = nw == k
        if not m.any():
            continue
        acc = np.zeros(int(m.sum()), dtype=np.uint64)
        for j in range(k):
            acc = acc * _WORD + words[m, j]
        ipart[m] = acc
        if nfw >= 1:
            f0 = words[m, k]
            if frac <= _DIGITS_PER_WORD:
                fpart[m] = f0 // _POW10[_DIGITS_PER_WORD - frac]
            else:
                fpart[m] = (f0 * _POW10[frac - _DIGITS_PER_WORD]
                            + words[m, k + 1]
                            // _POW10[2 * _DIGITS_PER_WORD - frac])
    mag = ipart * _POW10[frac] + fpart
    return np.where(neg, ~mag + np.uint64(1), mag).view(np.int64)


def _null_bitmap(nulls: np.ndarray) -> bytes:
    """LSB-first bitmap, bit=1 ⇒ NOT null — packbits pads the tail with 0
    exactly like the append builder leaves unset bits."""
    return np.packbits(~nulls, bitorder="little").tobytes()


def encode_np_column(ft: FieldType, data: np.ndarray, nulls: np.ndarray,
                     dictionary: np.ndarray | None = None) -> bytes:
    """One whole column -> its chunk wire bytes, vectorized.

    ``data``/``nulls`` are the Column arrays (already row-selected — callers
    late-materialize through ``Column.take`` / ``EncodedColumn.take`` first,
    so encoded-resident columns decode only surviving rows).  Byte-identical
    to a ChunkColumn built by appending ``datum_at``-domain values row by
    row."""
    nulls = np.asarray(nulls, dtype=bool)
    n = len(nulls)
    null_cnt = int(nulls.sum())
    parts = [struct.pack("<II", n, null_cnt)]
    if null_cnt:
        parts.append(_null_bitmap(nulls))
    et = ft.eval_type
    if et in (EvalType.INT, EvalType.DURATION, EvalType.DATETIME):
        cells = np.ascontiguousarray(data, dtype=np.int64)
        if null_cnt:
            cells = np.where(nulls, 0, cells)
        # two's-complement little-endian: identical bytes for the signed
        # (<q) and packed-u64 (<Q) scalar appends
        parts.append(cells.astype("<i8").tobytes())
    elif et == EvalType.REAL:
        dt = "<f4" if fixed_len(ft) == 4 else "<f8"
        cells = np.ascontiguousarray(data, dtype=np.float64)
        if null_cnt:
            cells = np.where(nulls, 0.0, cells)
        parts.append(cells.astype(dt).tobytes())
    elif et == EvalType.DECIMAL:
        vals = np.ascontiguousarray(data, dtype=np.int64)
        if null_cnt:
            vals = np.where(nulls, 0, vals)
        cells = encode_decimal_cells(vals, ft.decimal)
        if null_cnt:
            cells[nulls] = 0  # null struct cells are all-zero padding
        parts.append(cells.tobytes())
    elif et in (EvalType.BYTES, EvalType.JSON):
        vals = data if dictionary is None else dictionary[data]
        if null_cnt:
            lens = np.fromiter(
                (0 if null else len(v) for v, null in zip(vals, nulls)),
                np.int64, n)
            payload = b"".join(
                b"" if null else bytes(v) for v, null in zip(vals, nulls))
        else:
            lens = np.fromiter((len(v) for v in vals), np.int64, n)
            payload = b"".join(bytes(v) for v in vals)
        offsets = np.zeros(n + 1, dtype="<i8")
        np.cumsum(lens, out=offsets[1:])
        parts.append(offsets.tobytes())
        parts.append(payload)
    else:
        raise ValueError(f"chunk wire encode unsupported for {et}")
    return b"".join(parts)


def column_numpy(col: ChunkColumn):
    """Vectorized client-side decode: ``(data, nulls)`` numpy arrays for
    the fixed-width numeric types — decimals decode to their UNSCALED int64
    (the frac is the column's ``ft.decimal``) — and ``(list-of-bytes,
    nulls)`` for var-len.  Value-identical to :func:`column_values` row by
    row (None/tuple substitution is the caller's when needed)."""
    n = col.rows
    nb = (n + 7) // 8
    bits = np.unpackbits(
        np.frombuffer(bytes(col.bitmap[:nb]), np.uint8), bitorder="little")[:n]
    nulls = bits == 0
    et = col.ft.eval_type
    raw = bytes(col.data)
    if et == EvalType.INT:
        return np.frombuffer(raw, "<u8" if col.ft.is_unsigned else "<i8"), nulls
    if et == EvalType.DATETIME:
        return np.frombuffer(raw, "<u8"), nulls
    if et == EvalType.DURATION:
        return np.frombuffer(raw, "<i8"), nulls
    if et == EvalType.REAL:
        return np.frombuffer(raw, "<f4" if col.fixed == 4 else "<f8"), nulls
    if et == EvalType.DECIMAL:
        cells = np.frombuffer(raw, np.uint8).reshape(n, DECIMAL_STRUCT_SIZE)
        return decode_decimal_cells(cells, col.ft.decimal), nulls
    offs = col.offsets
    return [raw[offs[i]:offs[i + 1]] for i in range(n)], nulls


def decode_chunk(buf: bytes, field_types: list[FieldType]) -> list[ChunkColumn]:
    pos = 0
    cols = []
    for ft in field_types:
        col, pos = decode_column(buf, pos, ft)
        cols.append(col)
    if pos != len(buf):
        raise ValueError(f"trailing {len(buf) - pos} bytes after chunk")
    return cols
