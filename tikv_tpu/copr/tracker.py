"""Per-request execution tracker + slow log.

Re-expression of ``src/coprocessor/tracker.rs:46``: each request records its
phase durations (schedule wait, snapshot, handle) and scan statistics; slow
requests (over a threshold) are surfaced to the slow-log sink, and every
response can carry the breakdown back to the client like
``ExecutorExecutionSummary``.
"""

from __future__ import annotations

import threading
import json
import time
from dataclasses import dataclass, field

from ..util import trace


@dataclass
class TrackedMetrics:
    schedule_wait_s: float = 0.0
    snapshot_s: float = 0.0
    handle_s: float = 0.0
    total_s: float = 0.0
    scanned_keys: int = 0
    from_device: bool = False
    # region column cache outcome for this request ("" = cache not consulted;
    # hit / miss / delta / stale / uncacheable / too_big / off) and how many
    # rows the incremental delta apply re-decoded
    region_cache: str = ""
    region_cache_delta_rows: int = 0
    # observatory cross-link (docs/observatory.md): which serving path
    # answered (zone / unary / fused / xregion / mesh / cpu) and the plan
    # signature id — a slow-log entry pivots into ``ctl.py observatory sig
    # <sig>`` the same way its trace_id pivots into ``ctl.py trace show``
    serve_path: str = ""
    plan_sig: str = ""

    def to_dict(self) -> dict:
        d = {
            "schedule_wait_ms": round(self.schedule_wait_s * 1000, 3),
            "snapshot_ms": round(self.snapshot_s * 1000, 3),
            "handle_ms": round(self.handle_s * 1000, 3),
            "total_ms": round(self.total_s * 1000, 3),
            "scanned_keys": self.scanned_keys,
            "from_device": self.from_device,
        }
        if self.region_cache:
            d["region_cache"] = self.region_cache
            d["region_cache_delta_rows"] = self.region_cache_delta_rows
        if self.serve_path:
            d["path"] = self.serve_path
        if self.plan_sig:
            d["plan_sig"] = self.plan_sig
        return d


def count_path_fallback(path: str, cause: str) -> None:
    """Per-cause fast-path miss accounting: any time a serving path (zone /
    mesh / fused / xregion / unary-device) declines or fails onto its
    slower fallback, the reason lands here — ``failed``/``last_error``
    alone can't tell an operator WHY traffic keeps missing the fast path
    (VERDICT weak #6).  Charted on the coprocessor dashboard."""
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_path_fallback_total",
        "Fast-path declines and failures, by serving path and cause",
    ).inc(path=path, cause=cause)


def stamp_sched(md: dict | None, lane: str, kind: str, occupancy: int,
                waste: float | None = None,
                total_s: float | None = None) -> dict:
    """Read-scheduler placement keys for a response-metrics dict (the same
    dict :meth:`TrackedMetrics.to_dict` produces for tracked paths):
    ``sched_lane`` — the priority lane served from; ``sched_batch`` — the
    micro-batch kind (``xregion`` / ``fused`` / ``fill`` / ``direct`` /
    ``shed:<reason>``); ``batch_occupancy`` — requests sharing the
    dispatch; ``padding_waste`` — a cross-region batch's padded-geometry
    waste fraction.  ``total_s`` overrides the tracked total for requests
    whose latency was paid inside a shared batch."""
    d = dict(md or {})
    d["sched_lane"] = lane
    d["sched_batch"] = kind
    d["batch_occupancy"] = occupancy
    if waste is not None:
        d["padding_waste"] = round(waste, 4)
    if total_s is not None:
        d["total_s"] = total_s
        d["from_device"] = True
    return d


class Tracker:
    """Phase stopwatch for one request.  Captures the active trace id at
    construction so slow-log entries pivot straight to their trace
    (docs/tracing.md): ``/debug/traces`` + ``ctl.py trace show`` answer
    "WHERE was this slow request slow" for any logged tag."""

    def __init__(self, req_tag: str = ""):
        self.req_tag = req_tag
        self.trace_id = trace.current_trace_id()
        self.metrics = TrackedMetrics()
        self._created = time.perf_counter()
        self._phase_start = self._created

    def on_schedule(self) -> None:
        now = time.perf_counter()
        self.metrics.schedule_wait_s = now - self._created
        self._phase_start = now

    def on_snapshot_finished(self) -> None:
        now = time.perf_counter()
        self.metrics.snapshot_s = now - self._phase_start
        self._phase_start = now

    def on_finish(self, scanned_keys: int = 0, from_device: bool = False) -> TrackedMetrics:
        now = time.perf_counter()
        self.metrics.handle_s = now - self._phase_start
        self.metrics.total_s = now - self._created
        self.metrics.scanned_keys = scanned_keys
        self.metrics.from_device = from_device
        return self.metrics


class SlowLog:
    """Bounded ring of slow-request records, optionally appended to a
    slow-log FILE as one JSON line per entry (TiKV's slow-log file: a
    separate, grep-able stream from the main log)."""

    def __init__(self, threshold_s: float = 0.3, capacity: int = 256,
                 path: str | None = None):
        self.threshold_s = threshold_s
        self.capacity = capacity
        self.path = path
        self._mu = threading.Lock()
        self.entries: list[dict] = []

    def observe(self, tracker: Tracker) -> bool:
        if tracker.metrics.total_s < self.threshold_s:
            return False
        extra = {}
        if getattr(tracker, "trace_id", None):
            extra["trace_id"] = tracker.trace_id
        return self.record(tracker.req_tag,
                           {**tracker.metrics.to_dict(), **extra})

    def record(self, tag: str, fields: dict) -> bool:
        """Append one slow entry unconditionally — the generic sink the
        txn scheduler's write slow-log shares with the coprocessor path
        (same ring, same JSON-line file format)."""
        entry = {"tag": tag, **fields}
        with self._mu:
            self.entries.append(entry)
            if len(self.entries) > self.capacity:
                del self.entries[: len(self.entries) - self.capacity]
        if self.path is not None:
            # File IO happens outside the ring lock: a slow disk must not
            # serialize other request threads or tail() readers. A single
            # O_APPEND write of one line is atomic at these sizes.
            line = json.dumps({"ts": time.time(), **entry})
            try:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass  # a full disk must not fail the request
        return True

    def tail(self, n: int = 20) -> list[dict]:
        with self._mu:
            return self.entries[-n:]
