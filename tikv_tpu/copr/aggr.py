"""Vectorized aggregate functions with partial states.

Re-expression of ``tidb_query_aggr`` (``src/lib.rs:46,63,232`` and
``impl_{count,sum,avg,first,max_min,bit_op,variance}.rs``).  Like the
reference's pushdown protocol, AVG emits **two** result columns (count, sum)
and VAR_POP emits three (count, sum, sum_sq) — the client (TiDB) finishes the
division, which keeps every state mergeable across partial aggregations (and,
here, across device shards via ``psum``-style reductions).

Updates are segment reductions: ``update(states, group_ids, data, nulls)``
with ``np.add.at``/``np.minimum.at`` on CPU; the JAX path implements the same
states with ``jax.ops.segment_*`` (see jax_eval.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .datatypes import Column, EvalType
from .rpn import Expr, RpnExpression

_I64_MIN = np.iinfo(np.int64).min
_I64_MAX = np.iinfo(np.int64).max
_EXACT_F64 = 1 << 53


def _segment_add(acc: np.ndarray, g: np.ndarray, d: np.ndarray) -> None:
    """acc[g] += d, vectorized.  np.bincount(weights=...) runs ~20x faster
    than np.add.at but sums in float64; it is used only when every partial sum
    is exactly representable (|d|·n below 2^53), else the exact ufunc path."""
    if acc.dtype.kind == "f":
        acc += np.bincount(g, weights=d, minlength=len(acc))
        return
    if len(d):
        # python-int abs: np.abs(INT64_MIN) overflows back to a negative
        amax = max(abs(int(d.max())), abs(int(d.min())))
    else:
        amax = 0
    if amax and amax * len(d) < _EXACT_F64:
        acc += np.bincount(g, weights=d, minlength=len(acc)).astype(np.int64)
    else:
        np.add.at(acc, g, d)


@dataclass
class AggDescriptor:
    """One aggregate call: op over an expression (tipb aggregate Expr)."""

    op: str  # count | sum | avg | min | max | first | bit_and | bit_or | bit_xor | var_pop
    expr: Expr | None  # None for count(1)

    def n_result_columns(self) -> int:
        return {"avg": 2, "var_pop": 3}.get(self.op, 1)


class AggState:
    """Per-group vectorized state for one aggregate over one compiled expr."""

    def __init__(self, op: str, input_type: EvalType, frac: int):
        self.op = op
        self.input_type = input_type
        self.frac = frac
        n0 = 0
        self.count = np.zeros(n0, dtype=np.int64)
        if op in ("sum", "avg", "var_pop"):
            dtype = np.float64 if input_type == EvalType.REAL else np.int64
            self.sum = np.zeros(n0, dtype=dtype)
        if op == "var_pop":
            self.sum_sq = np.zeros(n0, dtype=np.float64)
        if op in ("min", "max", "first"):
            if input_type in (EvalType.BYTES, EvalType.JSON):
                self.value = np.empty(n0, dtype=object)
            else:
                dtype = np.float64 if input_type == EvalType.REAL else np.int64
                self.value = np.zeros(n0, dtype=dtype)
            self.has_value = np.zeros(n0, dtype=bool)
        if op in ("bit_and", "bit_or", "bit_xor"):
            init = -1 if op == "bit_and" else 0
            self.value = np.full(n0, init, dtype=np.int64)

    def grow(self, n_groups: int) -> None:
        cur = len(self.count)
        if n_groups <= cur:
            return
        add = n_groups - cur
        self.count = np.concatenate([self.count, np.zeros(add, dtype=np.int64)])
        if hasattr(self, "sum"):
            self.sum = np.concatenate([self.sum, np.zeros(add, dtype=self.sum.dtype)])
        if hasattr(self, "sum_sq"):
            self.sum_sq = np.concatenate([self.sum_sq, np.zeros(add, dtype=np.float64)])
        if hasattr(self, "value"):
            if self.value.dtype == object:
                ext = np.empty(add, dtype=object)
            elif self.op == "bit_and":
                ext = np.full(add, -1, dtype=np.int64)
            else:
                ext = np.zeros(add, dtype=self.value.dtype)
            self.value = np.concatenate([self.value, ext])
        if hasattr(self, "has_value"):
            self.has_value = np.concatenate([self.has_value, np.zeros(add, dtype=bool)])

    def rebase(self, keep_idx: int | None) -> None:
        """Drop all group state except ``keep_idx`` (which becomes group 0),
        or everything when None — the stream-agg carry.  Owned here so every
        piece of state (including caches like _json_best) moves together."""
        for name in ("count", "sum", "sum_sq", "value", "has_value"):
            if hasattr(self, name):
                arr = getattr(self, name)
                if keep_idx is None:
                    setattr(self, name, arr[:0].copy())
                else:
                    setattr(self, name, arr[keep_idx : keep_idx + 1].copy())
        best = getattr(self, "_json_best", None)
        if best is not None:
            self._json_best = (
                {0: best[keep_idx]} if keep_idx is not None and keep_idx in best else {}
            )

    def update(self, group_ids: np.ndarray, data: np.ndarray | None, nulls: np.ndarray | None) -> None:
        """Accumulate one batch. group_ids: int array, one per logical row."""
        op = self.op
        G = len(self.count)
        if op == "count":
            if nulls is None:  # count(1)
                self.count += np.bincount(group_ids, minlength=G).astype(np.int64)
            else:
                self.count += np.bincount(group_ids[~nulls], minlength=G).astype(np.int64)
            return
        mask = ~nulls
        if not mask.any():
            return
        g = group_ids[mask]
        d = data[mask]
        self.count += np.bincount(g, minlength=G).astype(np.int64)
        if op in ("sum", "avg"):
            _segment_add(self.sum, g, d)
        elif op == "var_pop":
            _segment_add(self.sum, g, d)
            self.sum_sq += np.bincount(g, weights=d.astype(np.float64) ** 2, minlength=len(self.sum_sq))
        elif op == "min":
            self._minmax(g, d, is_min=True)
        elif op == "max":
            self._minmax(g, d, is_min=False)
        elif op == "first":
            # first non-null value per group in stream order: only groups not
            # yet seen can take a value, and np.unique(return_index) yields
            # each new group's earliest row in this batch
            new_mask = ~self.has_value[g]
            if new_mask.any():
                g_new = g[new_mask]
                d_new = d[new_mask]
                uniq, first_idx = np.unique(g_new, return_index=True)
                self.value[uniq] = d_new[first_idx]
                self.has_value[uniq] = True
        elif op == "bit_and":
            np.bitwise_and.at(self.value, g, d)
        elif op == "bit_or":
            np.bitwise_or.at(self.value, g, d)
        elif op == "bit_xor":
            np.bitwise_xor.at(self.value, g, d)
        else:
            raise ValueError(f"unknown aggregate {op}")

    def _minmax(self, g, d, is_min: bool) -> None:
        if self.value.dtype == object:
            if self.input_type == EvalType.JSON:
                # binary-JSON payload bytes do NOT order like the values
                # (little-endian ints, type-code prefixes) — compare by
                # MySQL JSON ordering.  The running best is cached decoded
                # so each incoming row decodes once, not the accumulator
                # again per row.
                from .json_value import json_cmp_values, json_decode

                best = getattr(self, "_json_best", None)
                if best is None:
                    best = self._json_best = {}
                for gi, di in zip(g, d):
                    dv = json_decode(bytes(di))
                    if not self.has_value[gi]:
                        # mark per row, not after the loop: a later row of the
                        # same group IN THIS BATCH must compare, not overwrite
                        self.value[gi] = di
                        self.has_value[gi] = True
                        best[gi] = dv
                    else:
                        if gi not in best:
                            best[gi] = json_decode(bytes(self.value[gi]))
                        c = json_cmp_values(dv, best[gi])
                        if c != 0 and (c < 0) == is_min:
                            self.value[gi] = di
                            best[gi] = dv
                return
            for gi, di in zip(g, d):
                if not self.has_value[gi]:
                    self.value[gi] = di
                    self.has_value[gi] = True
                elif (di < self.value[gi]) == is_min and di != self.value[gi]:
                    self.value[gi] = di
            return
        # seed never-seen groups with the identity sentinel, then accumulate
        if d.dtype.kind == "f":
            sentinel = np.inf if is_min else -np.inf
        else:
            sentinel = _I64_MAX if is_min else _I64_MIN
        unseen = np.unique(g[~self.has_value[g]])
        self.value[unseen] = sentinel
        self.has_value[g] = True
        (np.minimum if is_min else np.maximum).at(self.value, g, d)

    def result_columns(self, n_groups: int) -> list[Column]:
        """Finalize into result columns (count/sum layouts per class docstring)."""
        op = self.op
        zeros = np.zeros(n_groups, dtype=bool)
        if op == "count":
            return [Column(EvalType.INT, self.count[:n_groups], zeros)]
        if op == "sum":
            et = EvalType.REAL if self.input_type == EvalType.REAL else self.input_type
            return [
                Column(et, self.sum[:n_groups], self.count[:n_groups] == 0, self.frac)
            ]
        if op == "avg":
            et = EvalType.REAL if self.input_type == EvalType.REAL else self.input_type
            return [
                Column(EvalType.INT, self.count[:n_groups], zeros),
                Column(et, self.sum[:n_groups], self.count[:n_groups] == 0, self.frac),
            ]
        if op == "var_pop":
            return [
                Column(EvalType.INT, self.count[:n_groups], zeros),
                Column(EvalType.REAL, self.sum[:n_groups].astype(np.float64), self.count[:n_groups] == 0),
                Column(EvalType.REAL, self.sum_sq[:n_groups], self.count[:n_groups] == 0),
            ]
        if op in ("min", "max", "first"):
            return [
                Column(
                    self.input_type,
                    self.value[:n_groups],
                    ~self.has_value[:n_groups],
                    self.frac,
                )
            ]
        if op in ("bit_and", "bit_or", "bit_xor"):
            return [Column(EvalType.INT, self.value[:n_groups], zeros)]
        raise ValueError(op)
