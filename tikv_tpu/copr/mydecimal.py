"""Wide MySQL DECIMAL: exact 81-digit fixed point + memcomparable binary codec.

Re-expression of ``tidb_query_datatype/src/codec/mysql/decimal.rs``.  The
reference stores digits as nine base-10^9 words (WORD_BUF_LEN=9,
DIGITS_PER_WORD=9 → 81-digit capacity, MAX_FRACTION=30) and hand-rolls the
carry chains in Rust.  Here the host-side representation is a Python
arbitrary-precision integer (``unscaled``) plus a fractional-digit count —
exact, branch-free, and trivially convertible to the framework's
TPU-resident form.

TPU-first split:

* **Device path** stays scaled-int64 (`datatypes.Column` DECIMAL) — decimals
  that fit 18 digits ride integer vector lanes on the MXU/VPU unchanged.
* **Host path** (this module) covers the full 81-digit envelope for parsing,
  row-format v2, and the memcomparable binary codec; `to_i64_scaled` bridges
  back to the device form when precision allows.

Binary format parity (``decimal.rs:124-178`` layout constants): digits are
grouped into base-10^9 words of 4 bytes, leading/trailing partial groups use
DIG_2_BYTES, the first byte's MSB is flipped, and negative values are
bitwise-inverted — so ``memcmp`` order equals numeric order, which is what
the reference relies on for index keys.
"""

from __future__ import annotations

WORD_BUF_LEN = 9
DIGITS_PER_WORD = 9
MAX_DIGITS = WORD_BUF_LEN * DIGITS_PER_WORD  # 81
MAX_FRACTION = 30
DIV_FRAC_INCR = 4
# bytes needed to hold 0..9 leftover decimal digits (decimal.rs DIG_2_BYTES)
_DIG_2_BYTES = (0, 1, 1, 2, 2, 3, 3, 4, 4, 4)

# rounding modes (decimal.rs RoundMode; "HalfEven" is MySQL's
# round-half-away-from-zero despite the name)
HALF_EVEN = "half_even"
TRUNCATE = "truncate"
CEILING = "ceiling"


class DecimalOverflow(Exception):
    """Integer part exceeds the 81-digit word buffer."""


class MyDecimal:
    """Immutable exact decimal: ``unscaled * 10^-frac``.

    ``unscaled`` carries the sign (``-0`` has no distinct representation —
    MySQL normalizes it to 0 and Python ints do the same).  ``frac`` ∈ [0, 30].
    """

    __slots__ = ("unscaled", "frac")

    def __init__(self, unscaled: int, frac: int):
        if frac < 0 or frac > MAX_FRACTION:
            raise ValueError(f"frac {frac} out of range")
        self.unscaled = unscaled
        self.frac = frac
        if self.int_digits() + frac > MAX_DIGITS:
            raise DecimalOverflow(f"{self!r} exceeds {MAX_DIGITS} digits")

    # ------------------------------------------------------------- factories
    @classmethod
    def from_int(cls, v: int) -> "MyDecimal":
        return cls(v, 0)

    @classmethod
    def from_str(cls, s: str) -> "MyDecimal":
        """Parse like MySQL: optional sign, digits, '.', digits, exponent."""
        s = s.strip()
        if not s:
            raise ValueError("empty decimal string")
        neg = False
        i = 0
        if s[i] in "+-":
            neg = s[i] == "-"
            i += 1
        int_part = frac_part = ""
        j = i
        while j < len(s) and s[j].isdigit():
            j += 1
        int_part = s[i:j]
        if j < len(s) and s[j] == ".":
            k = j + 1
            while k < len(s) and s[k].isdigit():
                k += 1
            frac_part = s[j + 1 : k]
            j = k
        exp = 0
        if j < len(s) and s[j] in "eE":
            exp = int(s[j + 1 :])
            j = len(s)
        if j != len(s):
            # MySQL truncates trailing garbage with a warning
            pass
        if not int_part and not frac_part:
            raise ValueError(f"bad decimal string {s!r}")
        digits = (int_part + frac_part) or "0"
        frac = len(frac_part) - exp
        unscaled = int(digits)
        if frac < 0:
            unscaled *= 10 ** (-frac)
            frac = 0
        if frac > MAX_FRACTION:
            # round the tail off at 30 fractional digits
            drop = frac - MAX_FRACTION
            unscaled = _round_div(unscaled, 10**drop)
            frac = MAX_FRACTION
        if neg:
            unscaled = -unscaled
        if _int_digits(unscaled, frac) + frac > MAX_DIGITS:
            raise DecimalOverflow(s)
        return cls(unscaled, frac)

    @classmethod
    def from_f64(cls, v: float, frac: int | None = None) -> "MyDecimal":
        if frac is None:
            d = cls.from_str(repr(v))
        else:
            d = cls.from_str(f"{v:.{min(frac, MAX_FRACTION)}f}")
        return d

    @classmethod
    def from_i64_scaled(cls, scaled: int, frac: int) -> "MyDecimal":
        """Lift the framework's device representation (int64 * 10^-frac)."""
        return cls(scaled, frac)

    @classmethod
    def zero(cls, frac: int = 0) -> "MyDecimal":
        return cls(0, frac)

    @classmethod
    def max_value(cls, prec: int, frac: int) -> "MyDecimal":
        return cls(10**prec - 1, frac)

    # ------------------------------------------------------------ inspection
    def int_digits(self) -> int:
        return _int_digits(self.unscaled, self.frac)

    @property
    def precision(self) -> int:
        return self.int_digits() + self.frac

    def is_negative(self) -> bool:
        return self.unscaled < 0

    def is_zero(self) -> bool:
        return self.unscaled == 0

    def to_string(self) -> str:
        mag = abs(self.unscaled)
        sign = "-" if self.unscaled < 0 else ""
        if self.frac == 0:
            return f"{sign}{mag}"
        q, r = divmod(mag, 10**self.frac)
        return f"{sign}{q}.{r:0{self.frac}d}"

    __str__ = to_string

    def __repr__(self):
        return f"MyDecimal({self.to_string()!r})"

    def to_f64(self) -> float:
        return self.unscaled / (10**self.frac)

    def to_int(self, mode: str = HALF_EVEN) -> int:
        return self.round(0, mode).unscaled

    def to_i64_scaled(self) -> tuple[int, int]:
        """(scaled int64, frac) for the device fast path; raises if too wide."""
        if not (-(2**63) <= self.unscaled < 2**63):
            raise DecimalOverflow("does not fit the device int64 form")
        return self.unscaled, self.frac

    # ------------------------------------------------------------ comparison
    def _cmp_key(self) -> int:
        # compare at a common scale without materializing strings
        return self.unscaled * 10 ** (MAX_FRACTION - self.frac)

    def __eq__(self, other):
        return isinstance(other, MyDecimal) and self._cmp_key() == other._cmp_key()

    def __lt__(self, other):
        if not isinstance(other, MyDecimal):
            return NotImplemented
        return self._cmp_key() < other._cmp_key()

    def __le__(self, other):
        if not isinstance(other, MyDecimal):
            return NotImplemented
        return self._cmp_key() <= other._cmp_key()

    def __hash__(self):
        return hash(self._cmp_key())

    # ------------------------------------------------------------ arithmetic
    def round(self, frac: int, mode: str = HALF_EVEN) -> "MyDecimal":
        """Round to ``frac`` fractional digits (decimal.rs round_with_word_buf_len).

        ``frac`` may be negative (rounds into the integer part, frac_cnt
        becomes 0 like the reference)."""
        target = min(frac, MAX_FRACTION)
        if target >= self.frac:
            return MyDecimal(self.unscaled * 10 ** (target - self.frac), target)
        drop = self.frac - target
        base = 10**drop
        if mode == TRUNCATE:
            q = abs(self.unscaled) // base
        elif mode == CEILING:
            if self.unscaled >= 0:
                q = -((-self.unscaled) // base)  # ceil for positives
            else:
                q = abs(self.unscaled) // base  # toward zero for negatives
        else:  # HALF_EVEN == MySQL round-half-away-from-zero
            q = _round_div(abs(self.unscaled), base)
        if self.unscaled < 0:
            q = -q
        if target < 0:
            q *= 10 ** (-target)
            target = 0
        return MyDecimal(q, target)

    def shift(self, by: int) -> "MyDecimal":
        """Multiply by 10^by (decimal.rs shift); adjusts frac first."""
        if by == 0:
            return self
        if by > 0:
            take = min(by, self.frac)
            d = MyDecimal(self.unscaled, self.frac - take)
            rest = by - take
            if rest:
                d = MyDecimal(d.unscaled * 10**rest, d.frac)
            return d
        add = min(-by, MAX_FRACTION - self.frac)
        d = MyDecimal(self.unscaled, self.frac + add)
        rest = -by - add
        if rest:
            # frac is already at MAX_FRACTION: low digits genuinely fall off
            mag = abs(d.unscaled) // 10**rest
            d = MyDecimal(-mag if d.unscaled < 0 else mag, d.frac)
        return d

    def _align(self, other: "MyDecimal") -> tuple[int, int, int]:
        frac = max(self.frac, other.frac)
        a = self.unscaled * 10 ** (frac - self.frac)
        b = other.unscaled * 10 ** (frac - other.frac)
        return a, b, frac

    def __neg__(self):
        return MyDecimal(-self.unscaled, self.frac)

    def __abs__(self):
        return MyDecimal(abs(self.unscaled), self.frac)

    def __add__(self, other: "MyDecimal") -> "MyDecimal":
        a, b, frac = self._align(other)
        return _clamped(a + b, frac)

    def __sub__(self, other: "MyDecimal") -> "MyDecimal":
        a, b, frac = self._align(other)
        return _clamped(a - b, frac)

    def __mul__(self, other: "MyDecimal") -> "MyDecimal":
        raw = self.unscaled * other.unscaled
        frac = self.frac + other.frac
        if frac > MAX_FRACTION:
            # MySQL truncates (not rounds) excess multiplication scale
            mag = abs(raw) // 10 ** (frac - MAX_FRACTION)
            raw = -mag if raw < 0 else mag
            frac = MAX_FRACTION
        return _clamped(raw, frac)

    def div(self, other: "MyDecimal", frac_incr: int = DIV_FRAC_INCR) -> "MyDecimal | None":
        """Division; None on division by zero (decimal.rs do_div_mod)."""
        if other.is_zero():
            return None
        frac = min(self.frac + frac_incr, MAX_FRACTION)
        # numerator scaled so that quotient has `frac` fractional digits
        num = self.unscaled * 10 ** (frac + other.frac - self.frac)
        q = _round_div(abs(num), abs(other.unscaled))
        if (num < 0) != (other.unscaled < 0):
            q = -q
        return _clamped(q, frac)

    __truediv__ = div

    def __mod__(self, other: "MyDecimal") -> "MyDecimal | None":
        if other.is_zero():
            return None
        a, b, frac = self._align(other)
        r = abs(a) % abs(b)
        if a < 0:
            r = -r
        return MyDecimal(r, frac)

    # ---------------------------------------------------------- binary codec
    def encode_bin(self, prec: int, frac: int) -> bytes:
        """MySQL/TiKV binary decimal (decimal.rs write_bin): memcomparable."""
        if frac > prec:
            raise ValueError("frac > prec")
        try:
            d = self.round(frac, HALF_EVEN)
        except DecimalOverflow:
            # widening the scale overran the 81-digit buffer: the value can't
            # fit (prec, frac) anyway — clamp to the max representable
            mag = 10**prec - 1
            d = MyDecimal(-mag if self.unscaled < 0 else mag, frac)
        int_cnt = prec - frac
        mag = abs(d.unscaled)
        ip, fp = divmod(mag, 10**frac) if frac else (mag, 0)
        if ip and _digits(ip) > int_cnt:
            # overflow: clamp to the max representable magnitude
            ip = 10**int_cnt - 1
            fp = 10**frac - 1 if frac else 0
        neg = d.unscaled < 0

        out = bytearray()
        # integer part: leading partial group then full base-10^9 words
        int_full, int_left = divmod(int_cnt, DIGITS_PER_WORD)
        words = []
        rem = ip
        for _ in range(int_full):
            rem, w = divmod(rem, 10**DIGITS_PER_WORD)
            words.append(w)
        lead = rem
        if int_left:
            out += int(lead).to_bytes(_DIG_2_BYTES[int_left], "big")
        for w in reversed(words):
            out += int(w).to_bytes(4, "big")
        # fractional part: full words then trailing partial group
        frac_full, frac_left = divmod(frac, DIGITS_PER_WORD)
        fdigits = f"{fp:0{frac}d}" if frac else ""
        pos = 0
        for _ in range(frac_full):
            out += int(fdigits[pos : pos + DIGITS_PER_WORD]).to_bytes(4, "big")
            pos += DIGITS_PER_WORD
        if frac_left:
            out += int(fdigits[pos:]).to_bytes(_DIG_2_BYTES[frac_left], "big")

        if not out:
            out = bytearray(1)
        out[0] ^= 0x80
        if neg:
            out = bytearray(b ^ 0xFF for b in out)
        return bytes(out)

    @classmethod
    def decode_bin(cls, data: bytes, prec: int, frac: int) -> tuple["MyDecimal", int]:
        """Inverse of encode_bin; returns (decimal, bytes_consumed)."""
        int_cnt = prec - frac
        int_full, int_left = divmod(int_cnt, DIGITS_PER_WORD)
        frac_full, frac_left = divmod(frac, DIGITS_PER_WORD)
        size = (
            int_full * 4
            + _DIG_2_BYTES[int_left]
            + frac_full * 4
            + _DIG_2_BYTES[frac_left]
        )
        buf = bytearray(data[:size])
        if len(buf) < size:
            raise ValueError("decimal bin truncated")
        neg = not (buf[0] & 0x80)
        if neg:
            buf = bytearray(b ^ 0xFF for b in buf)
        buf[0] ^= 0x80
        pos = 0
        ip = 0
        if int_left:
            n = _DIG_2_BYTES[int_left]
            ip = int.from_bytes(buf[pos : pos + n], "big")
            pos += n
        for _ in range(int_full):
            ip = ip * 10**DIGITS_PER_WORD + int.from_bytes(buf[pos : pos + 4], "big")
            pos += 4
        fp = 0
        for _ in range(frac_full):
            fp = fp * 10**DIGITS_PER_WORD + int.from_bytes(buf[pos : pos + 4], "big")
            pos += 4
        if frac_left:
            n = _DIG_2_BYTES[frac_left]
            fp = fp * 10**frac_left + int.from_bytes(buf[pos : pos + n], "big")
            pos += n
        unscaled = ip * 10**frac + fp
        if neg:
            unscaled = -unscaled
        return cls(unscaled, frac), size

    @staticmethod
    def bin_size(prec: int, frac: int) -> int:
        int_cnt = prec - frac
        return (
            (int_cnt // DIGITS_PER_WORD) * 4
            + _DIG_2_BYTES[int_cnt % DIGITS_PER_WORD]
            + (frac // DIGITS_PER_WORD) * 4
            + _DIG_2_BYTES[frac % DIGITS_PER_WORD]
        )


def _digits(v: int) -> int:
    return len(str(abs(v))) if v else 1


def _int_digits(unscaled: int, frac: int) -> int:
    mag = abs(unscaled)
    ip = mag // 10**frac
    return _digits(ip) if ip else 1


def _round_div(num: int, den: int) -> int:
    """Round-half-away-from-zero division of non-negative ints."""
    return (num + den // 2) // den


def _clamped(unscaled: int, frac: int) -> MyDecimal:
    """Clamp the integer part into the 81-digit buffer (Res::Overflow)."""
    if _int_digits(unscaled, frac) + frac > MAX_DIGITS:
        limit = 10 ** (MAX_DIGITS) - 1
        mag = min(abs(unscaled), limit)
        unscaled = -mag if unscaled < 0 else mag
    return MyDecimal(unscaled, frac)
