"""Device-resident equi-join over warm compressed region images.

The join rung (docs/device_join.md) serves a ``[TableScan, Join, ...]``
plan when BOTH region images are warm, without decoding rows that do not
survive the join:

* **rank path** — both key columns are dictionary-encoded.  The probe
  side's codes are remapped into the build side's code space at plan time
  (``np.searchsorted`` over the SORTED build dictionary objects; identity
  when the images share one dictionary object), then the device joins the
  integer code lanes directly with two ``searchsorted`` calls over the
  stable-sorted build codes.  No string ever materializes.
* **hash path** — plain int-family key lanes.  The build side's unique
  keys pack into a power-of-two open-addressing table host-side; the
  table arrays ride as DYNAMIC jit inputs, so compile keys churn only
  with the power-of-two shape buckets, never with table content.  The
  device probes with a vectorized linear-probe ``lax.while_loop``.

Both kernels return per-probe-row ``(start, count)`` group spans into one
stable-sorted build order (ascending key, build-row order within equal
keys — exactly the CPU ``BatchJoinExecutor``'s match order), so pair
expansion and payload gather are one shared host path: surviving row
pairs late-materialize through ``Column.take`` / ``EncodedColumn.take``
only.  Zone maps (docs/zone_maps.md) prune build/probe blocks whose key
ranges cannot intersect BEFORE any key lane decodes.

Everything here is a named decline away from the CPU oracle: any plan or
data shape the device cannot serve raises :class:`JoinDecline`, the
endpoint counts the cause, and the CPU pipeline serves the bytes.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..analysis.sanitizer import note_blocking
from . import jax_eval as _jax_eval  # noqa: F401 — x64 config side effect
from . import zone_maps
from .dag import (
    DagRequest, ExecSummary, Join, TableScan, make_response_encoder, _attach,
)
from .datatypes import Chunk, Column, EvalType
from .executors import (
    BATCH_GROW_FACTOR, BATCH_INITIAL_SIZE, BATCH_MAX_SIZE, ChunkFeedExecutor,
)

# int-family eval types whose decoded lanes are exact int64 join keys; REAL
# and DECIMAL stay on the CPU oracle (bit-cast floats and mixed-frac
# decimals have no lane-equality story worth the risk)
_INT_KEYS = frozenset({EvalType.INT, EvalType.DATETIME, EvalType.DURATION})

_MULT = 0x9E3779B97F4A7C15      # Fibonacci hashing multiplier (mod 2**64)
_EMPTY = -(1 << 63)             # open-addressing empty-slot sentinel
_MISS = np.int64(-1)            # rank-path "no such code" / NULL key

PATHS = ("rank", "hash")

# test/bench hook: force one device path regardless of preference ladder
_PATH_OVERRIDE: str | None = None


def set_path_override(path: str | None) -> None:
    """Force the rank or hash path (tests/bench); None restores routing."""
    assert path in (None, "rank", "hash"), path
    global _PATH_OVERRIDE
    _PATH_OVERRIDE = path


class JoinDecline(Exception):
    """A named reason the device join rung cannot serve this request.

    The endpoint counts ``cause`` under the ``join`` decline path and
    falls to the CPU pipeline — never silent, never wrong bytes."""

    def __init__(self, cause: str):
        super().__init__(cause)
        self.cause = cause


# ---------------------------------------------------------------------------
# plan eligibility
# ---------------------------------------------------------------------------

def analyze_plan(dag: DagRequest):
    """(probe_scan, join, downstream) for a device-joinable plan.

    The rung serves exactly ``[TableScan, Join, *downstream]`` inner
    joins with a bare build-side scan; everything else raises a named
    :class:`JoinDecline` (outer joins decline to the CPU oracle — its
    NULL-extension is the byte contract, per the issue: never silent)."""
    execs = dag.executors
    joins = [i for i, e in enumerate(execs) if isinstance(e, Join)]
    if len(joins) != 1:
        raise JoinDecline("multi_join" if joins else "not_join_plan")
    if not isinstance(execs[0], TableScan):
        raise JoinDecline("leaf_not_table_scan")
    if joins[0] != 1:
        # a Selection (or worse) below the join: the probe lanes served
        # off the image would disagree with the filtered CPU probe stream
        raise JoinDecline("probe_selection")
    join = execs[1]
    if join.join_type != "inner":
        raise JoinDecline("outer_join")
    if len(join.build) != 1:
        raise JoinDecline("build_selection")
    return execs[0], join, list(execs[2:])


# ---------------------------------------------------------------------------
# key lanes
# ---------------------------------------------------------------------------

class _Side:
    """One side's key-lane view over a warm image's blocks."""

    __slots__ = ("blocks", "kind", "dictionary", "keep", "n_rows")

    def __init__(self, cache, key_idx: int, label: str):
        self.blocks = list(cache.blocks)
        if not self.blocks:
            raise JoinDecline(f"{label}_empty_image")
        self.n_rows = sum(b.n_valid for b in self.blocks)
        kcols = []
        for blk in self.blocks:
            if key_idx >= len(blk.cols):
                raise JoinDecline("key_offset")
            kcols.append(blk.cols[key_idx])
        first = kcols[0]
        if first.dictionary is not None:
            if first.eval_type != EvalType.BYTES:
                raise JoinDecline("key_type")  # ENUM/SET code semantics
            if any(c.dictionary is not first.dictionary for c in kcols):
                raise JoinDecline("unstable_dictionary")
            self.kind, self.dictionary = "dict", first.dictionary
        elif first.eval_type in _INT_KEYS:
            if any(c.dictionary is not None for c in kcols):
                raise JoinDecline("unstable_dictionary")
            self.kind, self.dictionary = "int", None
        else:
            raise JoinDecline("key_type")
        self.keep = np.ones(len(self.blocks), dtype=bool)

    def key_lane(self, blk, key_idx: int):
        """(int64 values-or-codes, valid mask) for one block's key column,
        decoding WITHOUT populating the column's resident cache."""
        from . import encoding as _encoding

        col = blk.cols[key_idx]
        nv = blk.n_valid
        data = np.asarray(_encoding.decoded_data(col))[:nv]
        if data.dtype == object:
            raise JoinDecline("key_type")
        nulls = np.asarray(_encoding.decoded_nulls(col))[:nv]
        return data.astype(np.int64, copy=True), ~nulls


def _remap_for(probe: _Side, build: _Side) -> np.ndarray | None:
    """Probe-code → build-code remap array (None = shared dictionary, the
    identity).  Requires a SORTED build dictionary; codes of probe values
    absent from the build side map to ``_MISS``."""
    if probe.dictionary is build.dictionary:
        return None
    from . import encoding as _encoding

    if not _encoding._dict_map_for(build.dictionary)[1]:
        raise JoinDecline("dict_unsorted")
    bd = np.asarray(build.dictionary, dtype=object)
    pd = np.asarray(probe.dictionary, dtype=object)
    if len(bd) == 0:
        return np.full(len(pd), _MISS, dtype=np.int64)
    pos = np.searchsorted(bd, pd)
    posc = np.minimum(pos, len(bd) - 1)
    hit = np.array([bd[p] == v for p, v in zip(posc, pd)], dtype=bool)
    return np.where(hit, posc, _MISS).astype(np.int64)


# ---------------------------------------------------------------------------
# zone-map block pruning (before any key lane decodes)
# ---------------------------------------------------------------------------

def _zone_intervals(side: _Side, key_idx: int):
    """Per-block key interval from the block zones: ``(lo, hi)``,
    ``None`` (unknown — keep, and poison the side's global bound), or
    ``"empty"`` (no live keys: prunable outright for an inner join)."""
    out = []
    for blk in side.blocks:
        z = (blk.zones or {}).get(key_idx)
        if z is None:
            out.append(None)
        elif z.lo is None:
            out.append("empty")
        else:
            out.append((z.lo, z.hi))
    return out


def _map_interval(iv, remap: np.ndarray | None, probe_sorted: bool):
    """A probe-side code interval carried into build code space.  The
    remap is monotone only over a sorted probe dictionary; otherwise the
    interval is unknowable and pruning stands down for it."""
    if iv is None or iv == "empty" or remap is None:
        return iv
    if not probe_sorted:
        return None
    lo, hi = int(iv[0]), int(iv[1])
    live = remap[lo:hi + 1]
    live = live[live >= 0]
    if live.size == 0:
        return "empty"
    return (int(live.min()), int(live.max()))


def _global_bound(ivs):
    """(lo, hi) over kept blocks, or None when any interval is unknown
    (an unknown block could hold anything — no pruning against it)."""
    lo = hi = None
    for iv in ivs:
        if iv == "empty":
            continue
        if iv is None:
            return None
        lo = iv[0] if lo is None else min(lo, iv[0])
        hi = iv[1] if hi is None else max(hi, iv[1])
    return None if lo is None else (lo, hi)


def _prune_side(side: _Side, ivs, other_bound) -> None:
    for i, iv in enumerate(ivs):
        if iv == "empty":
            side.keep[i] = False
        elif (iv is not None and other_bound is not None
                and (iv[1] < other_bound[0] or iv[0] > other_bound[1])):
            side.keep[i] = False


def _zone_prune(probe: _Side, build: _Side, join: Join, remap: np.ndarray | None,
                probe_cache, build_cache) -> tuple[int, int]:
    """Drop blocks whose key ranges cannot intersect the other side.
    Widening-only folds keep stale zones a superset of the data, so a
    non-intersection proof stays a proof.  Returns (examined, pruned)."""
    if not zone_maps.enabled():
        return (0, 0)
    ok_p = zone_maps.ensure_zones(probe_cache)
    ok_b = zone_maps.ensure_zones(build_cache)
    if not (ok_p and ok_b):
        return (0, 0)
    p_ivs = _zone_intervals(probe, join.left_key)
    b_ivs = _zone_intervals(build, join.right_key)
    if remap is not None:
        from . import encoding as _encoding

        p_sorted = _encoding._dict_map_for(probe.dictionary)[1]
        p_ivs = [_map_interval(iv, remap, p_sorted) for iv in p_ivs]
    _prune_side(probe, p_ivs, _global_bound(b_ivs))
    _prune_side(build, b_ivs, _global_bound(p_ivs))
    examined = len(probe.blocks) + len(build.blocks)
    pruned = int((~probe.keep).sum()) + int((~build.keep).sum())
    zone_maps.count_prune("join", "examined", examined)
    zone_maps.count_prune("join", "pruned", pruned)
    return (examined, pruned)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _rank_probe(sorted_keys, probe):
    """Group span per probe key over the stable-sorted build codes.
    ``_MISS`` probes (NULL / unmapped) land before every real code; the
    INT64_MAX shape padding lands after — both span zero rows."""
    lo = jnp.searchsorted(sorted_keys, probe, side="left")
    hi = jnp.searchsorted(sorted_keys, probe, side="right")
    return lo, hi - lo


def _hash_probe(table_keys, table_starts, table_counts, probe):
    """Vectorized linear probe of the open-addressing table.  Table size
    is a power of two, load factor ≤ 0.5 — every probe chain terminates
    at a match or an empty slot.  The table arrays are dynamic inputs;
    only their power-of-two SHAPES key the compile cache."""
    size = table_keys.shape[0]
    shift = jnp.uint64(64 - (int(size).bit_length() - 1))
    h = (probe.astype(jnp.uint64) * jnp.uint64(_MULT)) >> shift
    mask = jnp.int64(size - 1)

    def cond(st):
        return jnp.any(st[3])

    def body(st):
        slot, starts, counts, active = st
        k = table_keys[slot]
        found = active & (k == probe) & (probe != jnp.int64(_EMPTY))
        starts = jnp.where(found, table_starts[slot], starts)
        counts = jnp.where(found, table_counts[slot], counts)
        active = active & ~found & (k != jnp.int64(_EMPTY))
        slot = jnp.where(active, (slot + 1) & mask, slot)
        return slot, starts, counts, active

    n = probe.shape[0]
    init = (h.astype(jnp.int64), jnp.zeros(n, jnp.int64),
            jnp.zeros(n, jnp.int64), jnp.ones(n, jnp.bool_))
    _, starts, counts, _ = jax.lax.while_loop(cond, body, init)
    return starts, counts


_KERNELS: dict[str, object] = {}


def _kernel(path: str):
    fn = _KERNELS.get(path)
    if fn is None:
        from . import observatory as _obs

        # lint: allow(jit-nocache) -- compiled once per path and memoized
        # in _KERNELS; inputs are pow-2 shape buckets so retraces quantize
        raw = jax.jit(_rank_probe if path == "rank" else _hash_probe)
        fn = _obs.timed_jit(raw, f"jax_join.{path}", path)
        _KERNELS[path] = fn
    return fn


def _pow2_pad(a: np.ndarray, fill: int) -> np.ndarray:
    """Shape-bucket padding: compile keys quantize to powers of two."""
    n = len(a)
    m = 1 << max(3, (max(n, 1) - 1).bit_length())
    if m == n:
        return a
    out = np.full(m, fill, dtype=np.int64)
    out[:n] = a
    return out


def _build_hash_table(ukeys, ustarts, ucounts):
    """Pack unique build keys into the open-addressing table host-side.
    Vectorized round-based insertion: each round claims every first
    contender of a free slot, losers step to their next slot.  Slots only
    ever flip empty→occupied, so every slot a key stepped past stays
    occupied — the device's probe-until-empty walk is sound."""
    if np.any(ukeys == _EMPTY):
        raise JoinDecline("sentinel_key")
    size = 8
    while size < 2 * len(ukeys):
        size <<= 1
    shift = np.uint64(64 - (size.bit_length() - 1))
    tk = np.full(size, _EMPTY, dtype=np.int64)
    ts = np.zeros(size, dtype=np.int64)
    tc = np.zeros(size, dtype=np.int64)
    slots = ((ukeys.astype(np.uint64) * np.uint64(_MULT)) >> shift).astype(np.int64)
    pending = np.arange(len(ukeys))
    while pending.size:
        s = slots[pending]
        order = np.argsort(s, kind="stable")
        so = s[order]
        lead = np.ones(so.size, dtype=bool)
        lead[1:] = so[1:] != so[:-1]
        cand = order[lead]
        win = cand[tk[s[cand]] == _EMPTY]
        idx = pending[win]
        tk[s[win]] = ukeys[idx]
        ts[s[win]] = ustarts[idx]
        tc[s[win]] = ucounts[idx]
        placed = np.zeros(pending.size, dtype=bool)
        placed[win] = True
        pending = pending[~placed]
        slots[pending] = (slots[pending] + 1) & (size - 1)
    return tk, ts, tc


# ---------------------------------------------------------------------------
# pair expansion + late materialization
# ---------------------------------------------------------------------------

def _gather_build(build: _Side, bschema, bids: np.ndarray) -> list[Column]:
    """Build-side output columns for the surviving pairs: per-block
    ``take`` decodes ONLY the selected rows (``EncodedColumn.take`` is
    the late-materialize gather); dictionary payloads stay codes when
    every block shares one dictionary object, else survivors decode."""
    k = len(bids)
    sels = []
    gbase = 0
    for blk in build.blocks:
        m = (bids >= gbase) & (bids < gbase + blk.n_valid)
        pos = np.flatnonzero(m)
        if pos.size:
            sels.append((blk, pos, bids[pos] - gbase))
        gbase += blk.n_valid
    out = []
    for j, (et, frac) in enumerate(bschema):
        d0 = build.blocks[0].cols[j].dictionary
        shared = d0 is not None and all(
            b.cols[j].dictionary is d0 for b in build.blocks)
        vals = None
        nulls = np.zeros(k, dtype=bool)
        for blk, pos, local in sels:
            piece = blk.cols[j].take(local)
            if piece.dictionary is not None and not shared:
                piece = piece.decoded()
                if piece.dictionary is not None:
                    raise JoinDecline("payload_dict")
            pdata = np.asarray(piece.data)
            if vals is None:
                vals = np.zeros(k, dtype=pdata.dtype)
            vals[pos] = pdata
            nulls[pos] = np.asarray(piece.nulls)
        if vals is None:
            vals = np.zeros(k, dtype=object if et == EvalType.BYTES else np.int64)
        return_dict = d0 if shared else None
        out.append(Column(et, vals, nulls, frac, dictionary=return_dict))
    return out


def _expand_pairs(starts, counts, sorted_ids):
    """(probe concat index, build global row id) per surviving pair, in
    the CPU oracle's order: probe stream order, build-row order within
    one probe row's matches."""
    counts = counts.astype(np.int64)
    total = int(counts.sum())
    if total == 0:
        return None, None
    pidx = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    offs = (np.arange(total, dtype=np.int64)
            - np.repeat(np.cumsum(counts) - counts, counts))
    bpos = np.repeat(starts.astype(np.int64), counts) + offs
    return pidx, sorted_ids[bpos]


# ---------------------------------------------------------------------------
# serve
# ---------------------------------------------------------------------------

def serve(dag: DagRequest, probe_cache, build_cache,
          prefer: str | None = None):
    """Run a warm two-image join plan on the device.

    Returns ``(SelectResponse, path, stats)`` where ``stats`` carries the
    observatory's build/probe/output row counts and the zone-prune pair;
    raises :class:`JoinDecline` (named cause, CPU serves) on any shape
    the kernels do not cover.  Byte identity with the CPU oracle holds
    because match ORDER is reproduced exactly and the downstream
    descriptors run through the very same executor code over the joined
    chunks."""
    probe_scan, join, downstream = analyze_plan(dag)
    probe = _Side(probe_cache, join.left_key, "probe")
    build = _Side(build_cache, join.right_key, "build")
    if probe.kind != build.kind:
        raise JoinDecline("key_form_mismatch")
    if probe.kind == "int":
        p_et = probe.blocks[0].cols[join.left_key].eval_type
        b_et = build.blocks[0].cols[join.right_key].eval_type
        if p_et != b_et:
            raise JoinDecline("key_form_mismatch")

    remap = _remap_for(probe, build) if probe.kind == "dict" else None
    feasible = ("rank", "hash") if probe.kind == "dict" else ("hash",)
    path = _PATH_OVERRIDE or prefer
    if path not in feasible:
        path = feasible[0]

    examined, pruned = _zone_prune(probe, build, join, remap,
                                   probe_cache, build_cache)

    # build lanes: concat kept blocks, global row ids, stable sort by key
    bkeys, bids = [], []
    gbase = 0
    for i, blk in enumerate(build.blocks):
        if build.keep[i]:
            k, valid = build.key_lane(blk, join.right_key)
            bkeys.append(k[valid])
            bids.append(gbase + np.flatnonzero(valid))
        gbase += blk.n_valid
    bkeys = np.concatenate(bkeys) if bkeys else np.empty(0, dtype=np.int64)
    bids = np.concatenate(bids) if bids else np.empty(0, dtype=np.int64)
    perm = np.argsort(bkeys, kind="stable")
    sorted_keys = bkeys[perm]
    sorted_ids = bids[perm]

    # probe lanes: concat kept blocks in stream order, NULLs to the miss
    # sentinel, dict codes remapped into build code space
    miss = _MISS if path == "rank" else np.int64(_EMPTY)
    parts = []            # (block, concat base, n_valid)
    pkeys = []
    cb = 0
    for i, blk in enumerate(probe.blocks):
        if not probe.keep[i]:
            continue
        k, valid = probe.key_lane(blk, join.left_key)
        if remap is not None:
            if len(remap) == 0:
                valid = np.zeros(len(k), dtype=bool)
            else:
                k = np.where(valid,
                             remap[np.clip(k, 0, len(remap) - 1)], k)
                valid = valid & (k != _MISS)
        k[~valid] = miss
        parts.append((blk, cb, blk.n_valid))
        pkeys.append(k)
        cb += blk.n_valid
    n_probe = cb
    pkeys = np.concatenate(pkeys) if pkeys else np.empty(0, dtype=np.int64)

    stats = {"build_rows": build.n_rows, "probe_rows": probe.n_rows,
             "out_rows": 0, "prune": (examined, pruned)}
    if n_probe and len(sorted_keys):
        probe_dev = _pow2_pad(pkeys, miss)
        if path == "rank":
            starts, counts = _kernel("rank")(
                _pow2_pad(sorted_keys, np.iinfo(np.int64).max), probe_dev)
        else:
            lead = np.ones(len(sorted_keys), dtype=bool)
            lead[1:] = sorted_keys[1:] != sorted_keys[:-1]
            ustarts = np.flatnonzero(lead).astype(np.int64)
            ucounts = np.diff(np.append(ustarts, len(sorted_keys)))
            tk, ts, tc = _build_hash_table(sorted_keys[ustarts], ustarts,
                                           ucounts)
            starts, counts = _kernel("hash")(tk, ts, tc, probe_dev)
        note_blocking("device.join:pull")
        starts = np.asarray(starts)[:n_probe]
        counts = np.asarray(counts)[:n_probe]
        pidx, out_bids = _expand_pairs(starts, counts, sorted_ids)
    else:
        pidx = out_bids = None

    pschema = [(c.ftype.eval_type, c.ftype.decimal)
               for c in probe_scan.columns_info]
    bschema = [(c.ftype.eval_type, c.ftype.decimal)
               for c in join.build[0].columns_info]
    chunks = []
    if pidx is not None:
        stats["out_rows"] = len(pidx)
        for blk, base, nv in parts:
            lo = np.searchsorted(pidx, base, side="left")
            hi = np.searchsorted(pidx, base + nv, side="left")
            if lo == hi:
                continue
            local = pidx[lo:hi] - base
            cols = [c.take(local) for c in blk.cols]
            cols += _gather_build(build, bschema, out_bids[lo:hi])
            chunks.append(Chunk.full(cols))

    # downstream descriptors finish on the SAME CPU executors the oracle
    # runs — shared code is the byte-identity argument, not a twin
    ex = ChunkFeedExecutor(pschema + bschema, chunks)
    for desc in downstream:
        ex = _attach(ex, desc, None)
    enc = make_response_encoder(dag)
    summary = ExecSummary()
    batch = BATCH_INITIAL_SIZE
    while True:
        r = ex.next_batch(batch)
        summary.num_iterations += 1
        if r.chunk.num_rows:
            enc.add_chunk(r.chunk, dag.output_offsets)
            summary.num_produced_rows += r.chunk.num_rows
        if r.is_drained:
            break
        if batch < BATCH_MAX_SIZE:
            batch = min(batch * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
    resp = enc.to_response(exec_summaries=[summary])
    resp._obs_prune = (examined, pruned)
    return resp, path, stats
