"""Vectorized MVCC range resolution — the coprocessor leaf's fast path.

The per-key ``ForwardScanner`` walks cursors in interpreted Python (fine for
the txn layer's point ops, ~µs/row) — far too slow to feed a TPU evaluator at
millions of rows.  This module resolves a whole CF_WRITE range *columnwise*:

  1. slice the snapshot's sorted write-CF range (one bisect, zero copies)
  2. stack the fixed-width keys into an (n, W) byte matrix — record keys of
     one table all encode to the same width, checked in O(n) — and split
     user_key / desc(commit_ts) by slicing
  3. group rows by user key (adjacent-row compare), pick each key's newest
     version with commit_ts <= ts via a segment-min over row indices
  4. parse the chosen Write records vectorized when they share the common
     PUT+short_value layout; anything unusual (rollback/lock/delete/gc-fence,
     large values) falls back to the exact per-key resolver for just those keys

Correctness contract: identical output to ForwardScanner (differentially
tested), including lock checks — locks in range are checked exactly like
``_ScannerBase._check_range_locks``.

This is host-side work feeding the device pipeline, so everything here is
numpy; there is no per-row Python in the common path.
"""

from __future__ import annotations

import numpy as np

from ..storage.engine import CF_LOCK, CF_WRITE, Snapshot
from ..storage.mvcc import ForwardScanner, Statistics
from ..storage.mvcc.reader import _check_lock
from ..storage.txn_types import Key, WriteType
from ..util import codec
from . import datum as datum_mod
from .executors import ScanSource

_TS_W = 8
_PUT = int(WriteType.PUT)
_SHORT_PREFIX = 0x76  # b'v'


def _parse_frames(buf: bytes, n: int) -> list[tuple[bytes, bytes]]:
    from ..native.engine import parse_frames

    return list(parse_frames(buf, n))


def _decode_user_keys(key_rows: np.ndarray) -> list[bytes]:
    """Vectorized memcomparable decode of same-width encoded keys: drop the
    marker byte of each 9-byte group and trim the final group's padding
    (markers verified uniform; per-row fallback otherwise)."""
    n, w = key_rows.shape
    if w % 9 == 0:
        groups = w // 9
        markers = key_rows[:, 8::9]
        if (markers == markers[0]).all():
            raw0, _ = codec.decode_bytes(key_rows[0].tobytes())
            data_cols = np.concatenate(
                [key_rows[:, g * 9 : g * 9 + 8] for g in range(groups)], axis=1
            )[:, : len(raw0)]
            data_cols = np.ascontiguousarray(data_cols)
            return [r.tobytes() for r in data_cols]
    return [codec.decode_bytes(key_rows[i].tobytes())[0] for i in range(n)]


class MvccBatchScanSource(ScanSource):
    """Drop-in ScanSource resolving whole ranges vectorized."""

    def __init__(
        self,
        snapshot: Snapshot,
        ts: int,
        ranges: list[tuple[bytes, bytes]],
        statistics: Statistics | None = None,
        bypass_locks: frozenset[int] = frozenset(),
    ):
        self.snap = snapshot
        self.ts = ts
        self.ranges = ranges
        self.stats = statistics or Statistics()
        self.bypass_locks = bypass_locks
        self._resolved: tuple[list[bytes], list[bytes]] | None = None
        self._pos = 0

    def _resolve_all(self) -> tuple[list[bytes], list[bytes]]:
        keys_out: list[bytes] = []
        vals_out: list[bytes] = []
        for start, end in self.ranges:
            k, v = self._resolve_range(start, end)
            keys_out.extend(k)
            vals_out.extend(v)
        return keys_out, vals_out

    def _resolve_range(self, start: bytes, end: bytes) -> tuple[list[bytes], list[bytes]]:
        enc_start = Key.from_raw(start).encoded
        enc_end = Key.from_raw(end).encoded
        # lock checks, same rule as the scanner
        for k, v in self.snap.scan_cf(CF_LOCK, enc_start, enc_end):
            self.stats.lock.next += 1
            _check_lock(v, Key.from_encoded(k).to_raw(), self.ts, self.bypass_locks)

        native = self._native_range(enc_start, enc_end)
        if native is not None and not isinstance(native, list):
            n, width, arr, values_arr = native
            if n == 0:
                return [], []
            wkeys = None
            pairs = None
        else:
            # native may hand back the already-fetched pairs (variable frames)
            # so the range is never scanned across the FFI twice
            pairs = native if native is not None else list(
                self.snap.scan_cf(CF_WRITE, enc_start, enc_end)
            )
            if not pairs:
                return [], []
            wkeys = [k for k, _ in pairs]
            width = len(wkeys[0])
            if any(len(k) != width for k in wkeys):
                return self._fallback(start, end)
            n = len(wkeys)
            arr = np.frombuffer(b"".join(wkeys), dtype=np.uint8).reshape(n, width)
            values_arr = None
        user = arr[:, : width - _TS_W]
        commit_ts = codec.decode_u64_batch(arr[:, width - _TS_W :]) ^ np.uint64(
            0xFFFFFFFFFFFFFFFF
        )
        # group boundaries: first row of each user key (rows sorted, versions
        # commit_ts-descending within a key)
        first = np.empty(n, dtype=bool)
        first[0] = True
        if n > 1:
            first[1:] = (user[1:] != user[:-1]).any(axis=1)
        gid = np.cumsum(first) - 1
        n_keys = int(gid[-1]) + 1

        visible = commit_ts <= np.uint64(self.ts)
        # newest visible version per key: reversed fancy-store keeps the
        # smallest row index (= highest commit_ts) per group
        pick_arr = np.full(n_keys, -1, dtype=np.int64)
        vis_idx = np.flatnonzero(visible)
        pick_arr[gid[vis_idx][::-1]] = vis_idx[::-1]
        pick = pick_arr[pick_arr >= 0]  # keys with at least one visible version
        if len(pick) == 0:
            return [], []

        if values_arr is not None:
            varr = np.ascontiguousarray(values_arr[pick])
            vw = varr.shape[1]
            simple = self._parse_simple_layout(varr, vw)
            if simple is not None:
                self.stats.write.processed_keys += len(pick)
                key_rows = np.ascontiguousarray(arr[pick, : width - _TS_W])
                out_keys = _decode_user_keys(key_rows)
                return out_keys, simple
            return self._fallback(start, end)

        values = [pairs[i][1] for i in pick]
        # vectorized write-record parse: common layout check
        vlens = np.fromiter((len(v) for v in values), dtype=np.int64, count=len(values))
        if len(values) and (vlens == vlens[0]).all():
            vw = int(vlens[0])
            varr = np.frombuffer(b"".join(values), dtype=np.uint8).reshape(len(values), vw)
            simple = self._parse_simple_layout(varr, vw)
            if simple is not None:
                self.stats.write.processed_keys += len(pick)
                out_keys = [bytes(Key.from_encoded(wkeys[i][: width - _TS_W]).to_raw()) for i in pick]
                return out_keys, simple
        # mixed/unusual records: exact per-key resolution for the whole range
        return self._fallback(start, end)

    def _native_range(self, enc_start: bytes, enc_end: bytes):
        """Fixed-stride zero-copy path over a native snapshot's scan buffer:
        if every (key, value) frame has identical lengths, the whole range
        reshapes into two byte matrices without per-pair Python."""
        scan_raw = getattr(self.snap, "scan_raw", None)
        if scan_raw is None:
            return None
        n, buf = scan_raw(CF_WRITE, enc_start, enc_end)
        if n == 0:
            return 0, 0, None, None
        b = np.frombuffer(buf, dtype=np.uint8)
        klen = int(np.frombuffer(buf[:4], dtype=np.uint32)[0])
        if len(buf) < 8 + klen:
            return None
        vlen = int(np.frombuffer(buf[4 + klen : 8 + klen], dtype=np.uint32)[0])
        stride = 8 + klen + vlen
        if len(buf) != n * stride:
            return _parse_frames(buf, n)  # mixed frame sizes — generic pairs
        mat = b.reshape(n, stride)
        # verify the length headers are constant across rows
        if not (mat[:, :4] == mat[0, :4]).all() or not (
            mat[:, 4 + klen : 8 + klen] == mat[0, 4 + klen : 8 + klen]
        ).all():
            return _parse_frames(buf, n)
        keys_arr = mat[:, 4 : 4 + klen]
        values_arr = mat[:, 8 + klen : stride]
        return n, klen, keys_arr, values_arr

    def _parse_simple_layout(self, varr: np.ndarray, vw: int) -> list[bytes] | None:
        """All records = [P][varint start_ts][v][len][short_value]? Verify the
        constant skeleton and slice out the short values."""
        if not (varr[:, 0] == _PUT).all():
            return None
        # varint start_ts length: find first byte < 0x80 starting at col 1
        off = 1
        while off < vw and (varr[:, off] >= 0x80).any():
            # all rows must agree the byte is a continuation byte
            if not (varr[:, off] >= 0x80).all():
                return None
            off += 1
        off += 1  # the terminating varint byte
        if off + 1 >= vw:
            return None
        if not (varr[:, off] == _SHORT_PREFIX).all():
            return None
        ln = varr[:, off + 1]
        if not (ln == vw - off - 2).all():
            return None
        payload = varr[:, off + 2 :]
        return [p.tobytes() for p in payload]

    def _fallback(self, start: bytes, end: bytes) -> tuple[list[bytes], list[bytes]]:
        ks, vs = [], []
        for k, v in ForwardScanner(
            self.snap,
            self.ts,
            Key.from_raw(start),
            Key.from_raw(end),
            bypass_locks=self.bypass_locks,
            statistics=self.stats,
        ):
            ks.append(k)
            vs.append(v)
        return ks, vs

    def next_batch(self, n: int) -> tuple[list[bytes], list[bytes], bool]:
        if self._resolved is None:
            self._resolved = self._resolve_all()
        keys, vals = self._resolved
        lo = self._pos
        hi = min(lo + n, len(keys))
        self._pos = hi
        return keys[lo:hi], vals[lo:hi], hi >= len(keys)
