"""Vectorized MVCC range resolution — the coprocessor leaf's fast path.

The per-key ``ForwardScanner`` walks cursors in interpreted Python (fine for
the txn layer's point ops, ~µs/row) — far too slow to feed a TPU evaluator at
millions of rows.  This module resolves a whole CF_WRITE range *columnwise*:

  1. slice the snapshot's sorted write-CF range (one bisect, zero copies)
  2. stack the fixed-width keys into an (n, W) byte matrix — record keys of
     one table all encode to the same width, checked in O(n) — and split
     user_key / desc(commit_ts) by slicing
  3. group rows by user key (adjacent-row compare), pick each key's newest
     version with commit_ts <= ts via a segment-min over row indices
  4. parse the chosen Write records vectorized when they share the common
     PUT+short_value layout; anything unusual (rollback/lock/delete/gc-fence,
     large values) falls back to the exact per-key resolver for just those keys

Correctness contract: identical output to ForwardScanner (differentially
tested), including lock checks — locks in range are checked exactly like
``_ScannerBase._check_range_locks``.

This is host-side work feeding the device pipeline, so everything here is
numpy; there is no per-row Python in the common path.
"""

from __future__ import annotations

import numpy as np

from ..storage.engine import CF_LOCK, CF_WRITE, Snapshot
from ..storage.mvcc import ForwardScanner, Statistics
from ..storage.mvcc.reader import _check_lock
from ..storage.txn_types import Key, WriteType
from ..util import codec
from . import datum as datum_mod
from .executors import ScanSource

_TS_W = 8
_PUT = int(WriteType.PUT)
_SHORT_PREFIX = 0x76  # b'v'


def _parse_frames(buf: bytes, n: int) -> list[tuple[bytes, bytes]]:
    from ..native.engine import parse_frames

    return list(parse_frames(buf, n))


def _decode_user_keys(key_rows: np.ndarray) -> list[bytes]:
    """Vectorized memcomparable decode of same-width encoded keys: drop the
    marker byte of each 9-byte group and trim the final group's padding
    (markers verified uniform; per-row fallback otherwise)."""
    n, w = key_rows.shape
    if w % 9 == 0:
        groups = w // 9
        markers = key_rows[:, 8::9]
        if (markers == markers[0]).all():
            raw0, _ = codec.decode_bytes(key_rows[0].tobytes())
            data_cols = np.concatenate(
                [key_rows[:, g * 9 : g * 9 + 8] for g in range(groups)], axis=1
            )[:, : len(raw0)]
            data_cols = np.ascontiguousarray(data_cols)
            return [r.tobytes() for r in data_cols]
    return [codec.decode_bytes(key_rows[i].tobytes())[0] for i in range(n)]


class MvccBatchScanSource(ScanSource):
    """Drop-in ScanSource resolving whole ranges vectorized.

    With ``record_versions=True`` the vectorized paths additionally record a
    per-output-row version fingerprint (the commit_ts of the newest CF_WRITE
    entry at or below ``ts``) plus the range's overall max commit_ts — the
    raw material the region column cache needs to detect deltas later.  A
    range that takes the exact per-key fallback clears ``versions_exact``;
    callers wanting version info must then decline (the cache simply does
    not form).
    """

    def __init__(
        self,
        snapshot: Snapshot,
        ts: int,
        ranges: list[tuple[bytes, bytes]],
        statistics: Statistics | None = None,
        bypass_locks: frozenset[int] = frozenset(),
        record_versions: bool = False,
    ):
        self.snap = snapshot
        self.ts = ts
        self.ranges = ranges
        self.stats = statistics or Statistics()
        self.bypass_locks = bypass_locks
        self.record_versions = record_versions
        self.versions_exact = True
        self.row_commit_ts: np.ndarray | None = None
        self.max_commit_ts = 0
        self._resolved: tuple[list[bytes], list[bytes]] | None = None
        self._pos = 0

    def fork(self, ranges: list[tuple[bytes, bytes]]) -> "MvccBatchScanSource":
        # join build-side sibling: same snapshot/ts, own ranges; version
        # recording stays off — only the probe side's image is delta-tracked
        return MvccBatchScanSource(self.snap, self.ts, ranges,
                                   statistics=self.stats,
                                   bypass_locks=self.bypass_locks)

    def _resolve_all(self) -> tuple[list[bytes], list[bytes]]:
        keys_out: list[bytes] = []
        vals_out: list[bytes] = []
        cts_out: list[np.ndarray] = []
        for start, end in self.ranges:
            k, v = self._resolve_range(start, end)
            keys_out.extend(k)
            vals_out.extend(v)
            if self.record_versions:
                if self._range_cts is None:
                    self.versions_exact = False
                else:
                    cts_out.append(self._range_cts)
                    self.max_commit_ts = max(self.max_commit_ts, self._range_max_ct)
        if self.record_versions and self.versions_exact:
            self.row_commit_ts = (
                np.concatenate(cts_out) if cts_out else np.empty(0, dtype=np.int64)
            )
        return keys_out, vals_out

    def _resolve_range(self, start: bytes, end: bytes) -> tuple[list[bytes], list[bytes]]:
        # version info for the range just resolved (record_versions bookkeeping)
        self._range_cts: np.ndarray | None = None
        self._range_max_ct = 0
        enc_start = Key.from_raw(start).encoded
        enc_end = Key.from_raw(end).encoded
        # lock checks, same rule as the scanner
        for k, v in self.snap.scan_cf(CF_LOCK, enc_start, enc_end):
            self.stats.lock.next += 1
            _check_lock(v, Key.from_encoded(k).to_raw(), self.ts, self.bypass_locks)

        native = self._native_range(enc_start, enc_end)
        if native is not None and not isinstance(native, list):
            n, width, arr, values_arr = native
            if n == 0:
                self._range_cts = np.empty(0, dtype=np.int64)
                return [], []
            wkeys = None
            pairs = None
        else:
            # native may hand back the already-fetched pairs (variable frames)
            # so the range is never scanned across the FFI twice
            pairs = native if native is not None else list(
                self.snap.scan_cf(CF_WRITE, enc_start, enc_end)
            )
            if not pairs:
                self._range_cts = np.empty(0, dtype=np.int64)
                return [], []
            wkeys = [k for k, _ in pairs]
            width = len(wkeys[0])
            if any(len(k) != width for k in wkeys):
                return self._fallback(start, end)
            n = len(wkeys)
            arr = np.frombuffer(b"".join(wkeys), dtype=np.uint8).reshape(n, width)
            values_arr = None
        user = arr[:, : width - _TS_W]
        commit_ts = codec.decode_u64_batch(arr[:, width - _TS_W :]) ^ np.uint64(
            0xFFFFFFFFFFFFFFFF
        )
        # group boundaries: first row of each user key (rows sorted, versions
        # commit_ts-descending within a key)
        first = np.empty(n, dtype=bool)
        first[0] = True
        if n > 1:
            first[1:] = (user[1:] != user[:-1]).any(axis=1)
        gid = np.cumsum(first) - 1
        n_keys = int(gid[-1]) + 1

        visible = commit_ts <= np.uint64(self.ts)
        # newest visible version per key: reversed fancy-store keeps the
        # smallest row index (= highest commit_ts) per group
        pick_arr = np.full(n_keys, -1, dtype=np.int64)
        vis_idx = np.flatnonzero(visible)
        pick_arr[gid[vis_idx][::-1]] = vis_idx[::-1]
        pick = pick_arr[pick_arr >= 0]  # keys with at least one visible version
        self._range_max_ct = int(commit_ts.max())
        if len(pick) == 0:
            self._range_cts = np.empty(0, dtype=np.int64)
            return [], []
        pick_cts = commit_ts[pick].astype(np.int64)

        if values_arr is not None:
            varr = np.ascontiguousarray(values_arr[pick])
            vw = varr.shape[1]
            simple = self._parse_simple_layout(varr, vw)
            if simple is not None:
                self.stats.write.processed_keys += len(pick)
                key_rows = np.ascontiguousarray(arr[pick, : width - _TS_W])
                out_keys = _decode_user_keys(key_rows)
                self._range_cts = pick_cts
                return out_keys, simple
            if self.record_versions:
                return self._exact_picked(
                    pick, pick_cts, arr, width,
                    lambda j: varr[j].tobytes(),
                )
            return self._fallback(start, end)

        values = [pairs[i][1] for i in pick]
        # vectorized write-record parse: common layout check
        vlens = np.fromiter((len(v) for v in values), dtype=np.int64, count=len(values))
        if len(values) and (vlens == vlens[0]).all():
            vw = int(vlens[0])
            varr = np.frombuffer(b"".join(values), dtype=np.uint8).reshape(len(values), vw)
            simple = self._parse_simple_layout(varr, vw)
            if simple is not None:
                self.stats.write.processed_keys += len(pick)
                out_keys = [bytes(Key.from_encoded(wkeys[i][: width - _TS_W]).to_raw()) for i in pick]
                self._range_cts = pick_cts
                return out_keys, simple
        if self.record_versions:
            return self._exact_picked(
                pick, pick_cts, arr, width, lambda j: values[j]
            )
        # mixed/unusual records: exact per-key resolution for the whole range
        return self._fallback(start, end)

    def _exact_picked(self, pick, pick_cts, arr, width, rec_of):
        """Record-versions build path for ranges whose picked records don't
        share one layout: the key-space work stays vectorized, and only the
        picked (newest-visible) record of each key parses exactly — PUTs
        yield their value, DELETEs drop the key, LOCK/ROLLBACK re-resolve
        through older versions.  Version fingerprints stay the picked
        entry's commit_ts, matching ``scan_delta``."""
        from ..storage.engine import CF_DEFAULT
        from ..storage.txn_types import Write, append_ts

        key_rows = np.ascontiguousarray(arr[pick, : width - _TS_W])
        raw_keys = _decode_user_keys(key_rows)
        keep: list[int] = []
        vals: list[bytes] = []
        for j in range(len(pick)):
            w = Write.from_bytes(rec_of(j))
            if w.write_type == WriteType.PUT:
                v = w.short_value
                if v is None:
                    enc = Key.from_raw(raw_keys[j]).encoded
                    self.stats.data.get += 1
                    v = self.snap.get_cf(CF_DEFAULT, append_ts(enc, w.start_ts))
                    if v is None:
                        raise ValueError(f"default value missing for {raw_keys[j]!r}")
            elif w.write_type == WriteType.DELETE:
                continue
            else:  # LOCK / ROLLBACK records: an older version decides
                enc = Key.from_raw(raw_keys[j]).encoded
                v = _resolve_one(self.snap, enc, self.ts, self.stats)
                if v is None:
                    continue
            keep.append(j)
            vals.append(v)
        self.stats.write.processed_keys += len(keep)
        self._range_cts = pick_cts[np.array(keep, dtype=np.int64)] if keep else np.empty(0, dtype=np.int64)
        return [raw_keys[j] for j in keep], vals

    def _native_range(self, enc_start: bytes, enc_end: bytes):
        """Fixed-stride zero-copy path over a native snapshot's scan buffer:
        if every (key, value) frame has identical lengths, the whole range
        reshapes into two byte matrices without per-pair Python."""
        scan_raw = getattr(self.snap, "scan_raw", None)
        if scan_raw is None:
            return None
        n, buf = scan_raw(CF_WRITE, enc_start, enc_end)
        if n == 0:
            return 0, 0, None, None
        b = np.frombuffer(buf, dtype=np.uint8)
        klen = int(np.frombuffer(buf[:4], dtype=np.uint32)[0])
        if len(buf) < 8 + klen:
            return None
        vlen = int(np.frombuffer(buf[4 + klen : 8 + klen], dtype=np.uint32)[0])
        stride = 8 + klen + vlen
        if len(buf) != n * stride:
            return _parse_frames(buf, n)  # mixed frame sizes — generic pairs
        mat = b.reshape(n, stride)
        # verify the length headers are constant across rows
        if not (mat[:, :4] == mat[0, :4]).all() or not (
            mat[:, 4 + klen : 8 + klen] == mat[0, 4 + klen : 8 + klen]
        ).all():
            return _parse_frames(buf, n)
        keys_arr = mat[:, 4 : 4 + klen]
        values_arr = mat[:, 8 + klen : stride]
        return n, klen, keys_arr, values_arr

    def _parse_simple_layout(self, varr: np.ndarray, vw: int) -> list[bytes] | None:
        """All records = [P][varint start_ts][v][len][short_value]? Verify the
        constant skeleton and slice out the short values."""
        if not (varr[:, 0] == _PUT).all():
            return None
        # varint start_ts length: find first byte < 0x80 starting at col 1
        off = 1
        while off < vw and (varr[:, off] >= 0x80).any():
            # all rows must agree the byte is a continuation byte
            if not (varr[:, off] >= 0x80).all():
                return None
            off += 1
        off += 1  # the terminating varint byte
        if off + 1 >= vw:
            return None
        if not (varr[:, off] == _SHORT_PREFIX).all():
            return None
        ln = varr[:, off + 1]
        if not (ln == vw - off - 2).all():
            return None
        payload = varr[:, off + 2 :]
        return [p.tobytes() for p in payload]

    def _fallback(self, start: bytes, end: bytes) -> tuple[list[bytes], list[bytes]]:
        ks, vs = [], []
        for k, v in ForwardScanner(
            self.snap,
            self.ts,
            Key.from_raw(start),
            Key.from_raw(end),
            bypass_locks=self.bypass_locks,
            statistics=self.stats,
        ):
            ks.append(k)
            vs.append(v)
        return ks, vs

    def next_batch(self, n: int) -> tuple[list[bytes], list[bytes], bool]:
        if self._resolved is None:
            self._resolved = self._resolve_all()
        keys, vals = self._resolved
        lo = self._pos
        hi = min(lo + n, len(keys))
        self._pos = hi
        return keys[lo:hi], vals[lo:hi], hi >= len(keys)


# ---------------------------------------------------------------------------
# Delta resolution against a cached region image (region_cache.py)
# ---------------------------------------------------------------------------


def _resolve_one(snap: Snapshot, enc_user_key: bytes, ts: int, stats: Statistics) -> bytes | None:
    """Exact visible value of one key at ``ts`` — PointGetter under RC (no
    per-key lock check: callers lock-check the whole range once)."""
    from ..storage.mvcc.reader import IsolationLevel, PointGetter

    return PointGetter(
        snap, ts, isolation=IsolationLevel.RC, statistics=stats
    ).get(Key.from_encoded(enc_user_key))


def scan_delta(
    snap: Snapshot,
    ts: int,
    ranges: list[tuple[bytes, bytes]],
    image_handles: np.ndarray,
    image_commit_ts: np.ndarray,
    statistics: Statistics | None = None,
    bypass_locks: frozenset[int] = frozenset(),
):
    """Diff the engine's newest-visible versions against a cached image.

    One vectorized pass over the CF_WRITE keys of ``ranges`` (no value
    parsing, no row decode) finds the keys whose version fingerprint — the
    commit_ts of the newest entry at or below ``ts`` — differs from the
    image's; only those are resolved exactly.  Returns None when the ranges
    are not vectorizable (non-uniform key widths or non-record keys), else::

        {"changed_handles", "changed_values", "changed_commit_ts",
         "deleted_handles", "max_commit_ts", "n_visible"}

    ``deleted_handles`` are image rows with no visible version anymore;
    ``changed_values`` align with ``changed_handles`` and are the exact MVCC
    values (a changed key that resolves to nothing joins the deleted set
    instead).  Lock checks run over each whole range, like the scanners.
    """
    from .table import decode_record_handles

    stats = statistics or Statistics()
    vis_handles: list[np.ndarray] = []
    vis_cts: list[np.ndarray] = []
    vis_enc_keys: list[np.ndarray] = []  # (k, keylen) byte matrix per range
    vis_pick_vals: list[list] = []  # lazily-fetched picked record values
    max_ct = 0
    for start, end in ranges:
        enc_start = Key.from_raw(start).encoded
        enc_end = Key.from_raw(end).encoded
        for k, v in snap.scan_cf(CF_LOCK, enc_start, enc_end):
            stats.lock.next += 1
            _check_lock(v, Key.from_encoded(k).to_raw(), ts, bypass_locks)
        pairs = list(snap.scan_cf(CF_WRITE, enc_start, enc_end))
        if not pairs:
            continue
        wkeys = [k for k, _ in pairs]
        width = len(wkeys[0])
        if any(len(k) != width for k in wkeys):
            return None
        n = len(wkeys)
        arr = np.frombuffer(b"".join(wkeys), dtype=np.uint8).reshape(n, width)
        user = arr[:, : width - _TS_W]
        commit_ts = codec.decode_u64_batch(arr[:, width - _TS_W :]) ^ np.uint64(
            0xFFFFFFFFFFFFFFFF
        )
        max_ct = max(max_ct, int(commit_ts.max()))
        first = np.empty(n, dtype=bool)
        first[0] = True
        if n > 1:
            first[1:] = (user[1:] != user[:-1]).any(axis=1)
        gid = np.cumsum(first) - 1
        n_keys = int(gid[-1]) + 1
        visible = commit_ts <= np.uint64(ts)
        pick_arr = np.full(n_keys, -1, dtype=np.int64)
        vis_idx = np.flatnonzero(visible)
        pick_arr[gid[vis_idx][::-1]] = vis_idx[::-1]
        has_vis = pick_arr >= 0
        first_idx = np.flatnonzero(first)
        key_rows = np.ascontiguousarray(arr[first_idx[has_vis], : width - _TS_W])
        raw_keys = _decode_user_keys(key_rows)
        lens = {len(rk) for rk in raw_keys}
        if lens and lens != {19}:
            return None  # not record keys — the cache only images tables
        handles = decode_record_handles(raw_keys)
        if len(handles) > 1 and not (handles[1:] > handles[:-1]).all():
            return None
        vis_handles.append(handles)
        vis_cts.append(commit_ts[pick_arr[has_vis]].astype(np.int64))
        vis_enc_keys.append(key_rows)
        vis_pick_vals.append([pairs[i][1] for i in pick_arr[has_vis]])

    if vis_handles:
        handles = np.concatenate(vis_handles)
        cts = np.concatenate(vis_cts)
    else:
        handles = np.empty(0, dtype=np.int64)
        cts = np.empty(0, dtype=np.int64)
    if len(handles) > 1 and not (handles[1:] > handles[:-1]).all():
        return None  # ranges out of handle order — images are handle-sorted

    # changed = visible keys whose fingerprint disagrees with the image
    pos = np.searchsorted(image_handles, handles)
    pos_c = np.minimum(pos, max(len(image_handles) - 1, 0))
    if len(image_handles):
        present = image_handles[pos_c] == handles
        same = present & (image_commit_ts[pos_c] == cts)
    else:
        present = np.zeros(len(handles), dtype=bool)
        same = present
    changed_idx = np.flatnonzero(~same)

    # deleted = image rows whose handle no longer has a visible version
    gone = np.ones(len(image_handles), dtype=bool)
    if len(handles):
        ipos = np.searchsorted(handles, image_handles)
        ipos_c = np.minimum(ipos, len(handles) - 1)
        gone = handles[ipos_c] != image_handles
    deleted = set(image_handles[gone].tolist())

    changed_handles: list[int] = []
    changed_values: list[bytes] = []
    changed_cts: list[int] = []
    # re-encode only the changed keys (tiny): raw record key -> encoded form
    offsets = np.cumsum([0] + [len(h) for h in vis_handles])
    for ci in changed_idx:
        ri = int(np.searchsorted(offsets, ci, side="right") - 1)
        local = int(ci - offsets[ri])
        # vis_enc_keys rows ARE the memcomparable-encoded user keys (sliced
        # straight off the CF_WRITE key matrix) — use them as-is
        enc_user = vis_enc_keys[ri][local].tobytes()
        # fast path: the picked record is a plain PUT with a short value
        val = None
        rec = vis_pick_vals[ri][local]
        if rec and rec[0] == _PUT:
            try:
                w = _parse_write_short(rec)
            except ValueError:
                w = None
            if w is not None:
                val = w
        if val is None:
            val = _resolve_one(snap, enc_user, ts, stats)
        h = int(handles[ci])
        if val is None:
            if bool(present[ci]):
                deleted.add(h)
            continue
        changed_handles.append(h)
        changed_values.append(val)
        changed_cts.append(int(cts[ci]))

    return {
        "changed_handles": np.array(changed_handles, dtype=np.int64),
        "changed_values": changed_values,
        "changed_commit_ts": np.array(changed_cts, dtype=np.int64),
        "deleted_handles": np.array(sorted(deleted), dtype=np.int64),
        "max_commit_ts": max_ct,
        "n_visible": int(len(handles)),
    }


def _parse_write_short(rec: bytes) -> bytes | None:
    """Short-value payload of a PUT write record, or None when the record
    carries flags/indirection the fast path must not guess about."""
    from ..storage.txn_types import Write

    w = Write.from_bytes(rec)
    if w.write_type != WriteType.PUT or w.gc_fence is not None:
        return None
    return w.short_value  # None ⇒ CF_DEFAULT value: exact path handles it
