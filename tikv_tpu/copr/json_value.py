"""MySQL JSON: binary codec, path expressions, and value operations.

Re-expression of ``tidb_query_datatype/src/codec/mysql/json`` (mod.rs type
codes, binary.rs layout, path_expr.rs legs, json_extract.rs /
json_modify.rs / json_merge.rs semantics).  Values round-trip through the
TiDB binary JSON layout:

    datum  = type_code(1B) + value
    object = elem_count(u32le) size(u32le) key_entries value_entries keys vals
             key_entry   = key_offset(u32le) key_len(u16le)
             value_entry = type_code(1B) + offset_or_inlined_literal(u32le)
    array  = elem_count(u32le) size(u32le) value_entries vals
    string = leb128 length + utf8 bytes ;  i64/u64/f64 = 8B little-endian
    literal= 0x00 NULL | 0x01 TRUE | 0x02 FALSE

Python-side values: None, bool, int, float, str, list, dict (a thin
``JsonU64`` wrapper marks explicit u64).  Object keys sort MySQL-style:
shorter first, then byte order.
"""

from __future__ import annotations

import json as _pyjson
import struct

TYPE_OBJECT = 0x01
TYPE_ARRAY = 0x03
TYPE_LITERAL = 0x04
TYPE_I64 = 0x09
TYPE_U64 = 0x0A
TYPE_F64 = 0x0B
TYPE_STRING = 0x0C

LIT_NULL = 0x00
LIT_TRUE = 0x01
LIT_FALSE = 0x02

_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")
_I64 = struct.Struct("<q")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")


class JsonU64(int):
    """Marks an int as an explicit UNSIGNED INTEGER json value."""


def _key_sort(k: bytes):
    return (len(k), k)  # MySQL: shorter keys first, then binary order


def _leb128(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_leb128(b: bytes, off: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        c = b[off]
        off += 1
        n |= (c & 0x7F) << shift
        if not c & 0x80:
            return n, off
        shift += 7


def _type_of(v) -> int:
    if v is None or isinstance(v, bool):
        return TYPE_LITERAL
    if isinstance(v, JsonU64):
        return TYPE_U64
    if isinstance(v, int):
        return TYPE_U64 if v >= 2**63 else TYPE_I64
    if isinstance(v, float):
        return TYPE_F64
    if isinstance(v, str):
        return TYPE_STRING
    if isinstance(v, list):
        return TYPE_ARRAY
    if isinstance(v, dict):
        return TYPE_OBJECT
    raise TypeError(f"not a json value: {type(v)}")


def _encode_value(v) -> bytes:
    t = _type_of(v)
    if t == TYPE_LITERAL:
        return bytes([LIT_NULL if v is None else (LIT_TRUE if v else LIT_FALSE)])
    if t == TYPE_I64:
        return _I64.pack(v)
    if t == TYPE_U64:
        return _U64.pack(v)
    if t == TYPE_F64:
        return _F64.pack(v)
    if t == TYPE_STRING:
        raw = v.encode("utf-8")
        return _leb128(len(raw)) + raw
    if t == TYPE_ARRAY:
        entries = bytearray()
        data = bytearray()
        header = 8 + 5 * len(v)
        for el in v:
            et = _type_of(el)
            if et == TYPE_LITERAL:
                entries.append(et)
                entries += _U32.pack(_encode_value(el)[0])
            else:
                entries.append(et)
                entries += _U32.pack(header + len(data))
                data += _encode_value(el)
        total = header + len(data)
        return _U32.pack(len(v)) + _U32.pack(total) + bytes(entries) + bytes(data)
    # object
    items = sorted(((k.encode("utf-8"), val) for k, val in v.items()), key=lambda kv: _key_sort(kv[0]))
    header = 8 + 6 * len(items) + 5 * len(items)
    key_entries = bytearray()
    value_entries = bytearray()
    keys = bytearray()
    data = bytearray()
    for k, _val in items:
        key_entries += _U32.pack(header + len(keys))
        key_entries += _U16.pack(len(k))
        keys += k
    for _k, val in items:
        vt = _type_of(val)
        if vt == TYPE_LITERAL:
            value_entries.append(vt)
            value_entries += _U32.pack(_encode_value(val)[0])
        else:
            value_entries.append(vt)
            value_entries += _U32.pack(header + len(keys) + len(data))
            data += _encode_value(val)
    total = header + len(keys) + len(data)
    return (
        _U32.pack(len(items)) + _U32.pack(total)
        + bytes(key_entries) + bytes(value_entries) + bytes(keys) + bytes(data)
    )


def json_encode(v) -> bytes:
    """Python value → binary JSON datum (type byte + value)."""
    return bytes([_type_of(v)]) + _encode_value(v)


def _decode_value(t: int, b: bytes, off: int):
    if t == TYPE_LITERAL:
        lit = b[off]
        return None if lit == LIT_NULL else (lit == LIT_TRUE)
    if t == TYPE_I64:
        return _I64.unpack_from(b, off)[0]
    if t == TYPE_U64:
        u = _U64.unpack_from(b, off)[0]
        return JsonU64(u) if u >= 2**63 else u
    if t == TYPE_F64:
        return _F64.unpack_from(b, off)[0]
    if t == TYPE_STRING:
        n, p = _read_leb128(b, off)
        return b[p : p + n].decode("utf-8")
    count = _U32.unpack_from(b, off)[0]
    if t == TYPE_ARRAY:
        out = []
        for i in range(count):
            et = b[off + 8 + 5 * i]
            val_off = _U32.unpack_from(b, off + 8 + 5 * i + 1)[0]
            if et == TYPE_LITERAL:
                out.append(None if (val_off & 0xFF) == LIT_NULL else ((val_off & 0xFF) == LIT_TRUE))
            else:
                out.append(_decode_value(et, b, off + val_off))
        return out
    if t == TYPE_OBJECT:
        obj = {}
        ve_base = off + 8 + 6 * count
        for i in range(count):
            key_off = _U32.unpack_from(b, off + 8 + 6 * i)[0]
            key_len = _U16.unpack_from(b, off + 8 + 6 * i + 4)[0]
            key = b[off + key_off : off + key_off + key_len].decode("utf-8")
            et = b[ve_base + 5 * i]
            val_off = _U32.unpack_from(b, ve_base + 5 * i + 1)[0]
            if et == TYPE_LITERAL:
                obj[key] = None if (val_off & 0xFF) == LIT_NULL else ((val_off & 0xFF) == LIT_TRUE)
            else:
                obj[key] = _decode_value(et, b, off + val_off)
        return obj
    raise ValueError(f"bad json type code {t:#x}")


def json_decode(b: bytes):
    """Binary JSON datum → Python value."""
    return _decode_value(b[0], b, 1)


def json_binary_len(b: bytes, off: int) -> int:
    """Length of the binary JSON datum starting at ``off`` (for the datum
    codec: JSON payloads are self-delimiting)."""
    t = b[off]
    p = off + 1
    if t == TYPE_LITERAL:
        return 2
    if t in (TYPE_I64, TYPE_U64, TYPE_F64):
        return 9
    if t == TYPE_STRING:
        n, q = _read_leb128(b, p)
        return (q - off) + n
    if t in (TYPE_ARRAY, TYPE_OBJECT):
        return 1 + _U32.unpack_from(b, p + 4)[0]
    raise ValueError(f"bad json type code {t:#x}")


# ---------------------------------------------------------------------------
# text form (MySQL serialization: ", " / ": " separators)
# ---------------------------------------------------------------------------


def json_to_text(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
            return repr(v)
        return _pyjson.dumps(v)
    if isinstance(v, str):
        return _pyjson.dumps(v, ensure_ascii=False)
    if isinstance(v, list):
        return "[" + ", ".join(json_to_text(e) for e in v) + "]"
    items = sorted(((k.encode(), k, val) for k, val in v.items()), key=lambda kv: _key_sort(kv[0]))
    return "{" + ", ".join(f"{_pyjson.dumps(k, ensure_ascii=False)}: {json_to_text(val)}" for _kb, k, val in items) + "}"


def json_parse_text(s: str):
    """JSON text → Python value (cast_string_json / JSON_VALID)."""
    return _pyjson.loads(s)


# ---------------------------------------------------------------------------
# path expressions (path_expr.rs): $, .key, ."quoted", [N], [*], .*, **
# ---------------------------------------------------------------------------

MEMBER, INDEX, WILD_MEMBER, WILD_INDEX, DOUBLE_WILD = "m", "i", "wm", "wi", "**"


def parse_path(path: str) -> list[tuple]:
    s = path.strip()
    if not s.startswith("$"):
        raise ValueError(f"invalid json path {path!r}")
    i = 1
    legs: list[tuple] = []
    while i < len(s):
        c = s[i]
        if c.isspace():
            i += 1
        elif c == ".":
            i += 1
            while i < len(s) and s[i].isspace():
                i += 1
            if i < len(s) and s[i] == "*":
                legs.append((WILD_MEMBER,))
                i += 1
            elif i < len(s) and s[i] == '"':
                j = i + 1
                buf = []
                while j < len(s) and s[j] != '"':
                    if s[j] == "\\":
                        j += 1
                        if j >= len(s):
                            raise ValueError(f"invalid json path {path!r}")
                    buf.append(s[j])
                    j += 1
                if j >= len(s):
                    raise ValueError(f"invalid json path {path!r}")
                legs.append((MEMBER, "".join(buf)))
                i = j + 1
            else:
                j = i
                while j < len(s) and (s[j].isalnum() or s[j] in "_$"):
                    j += 1
                if j == i:
                    raise ValueError(f"invalid json path {path!r}")
                legs.append((MEMBER, s[i:j]))
                i = j
        elif c == "[":
            j = s.index("]", i)
            inner = s[i + 1 : j].strip()
            if inner == "*":
                legs.append((WILD_INDEX,))
            else:
                idx = int(inner)
                if idx < 0:
                    raise ValueError(f"invalid json path {path!r} (negative index)")
                legs.append((INDEX, idx))
            i = j + 1
        elif c == "*" and s[i : i + 2] == "**":
            legs.append((DOUBLE_WILD,))
            i += 2
        else:
            raise ValueError(f"invalid json path {path!r}")
    if legs and legs[-1][0] == DOUBLE_WILD:
        raise ValueError(f"path {path!r} must not end with **")
    return legs


def path_has_wildcard(legs: list[tuple]) -> bool:
    return any(leg[0] in (WILD_MEMBER, WILD_INDEX, DOUBLE_WILD) for leg in legs)


def _match(v, legs: list[tuple], out: list) -> None:
    if not legs:
        out.append(v)
        return
    leg, rest = legs[0], legs[1:]
    kind = leg[0]
    if kind == MEMBER:
        if isinstance(v, dict) and leg[1] in v:
            _match(v[leg[1]], rest, out)
    elif kind == INDEX:
        if isinstance(v, list):
            if 0 <= leg[1] < len(v):
                _match(v[leg[1]], rest, out)
        elif leg[1] == 0:
            _match(v, rest, out)  # scalar acts as single-element array
    elif kind == WILD_MEMBER:
        if isinstance(v, dict):
            for val in v.values():
                _match(val, rest, out)
    elif kind == WILD_INDEX:
        if isinstance(v, list):
            for el in v:
                _match(el, rest, out)
    elif kind == DOUBLE_WILD:
        # ** : any depth ≥ 1 below the current value
        def walk(node):
            if isinstance(node, dict):
                for val in node.values():
                    _match(val, rest, out)
                    walk(val)
            elif isinstance(node, list):
                for el in node:
                    _match(el, rest, out)
                    walk(el)

        walk(v)


def extract(v, paths: list[str]):
    """JSON_EXTRACT semantics: one non-wildcard path → the value itself;
    otherwise an array of every match; no matches → None sentinel."""
    all_legs = [parse_path(p) for p in paths]
    matches: list = []
    for legs in all_legs:
        _match(v, legs, matches)
    if not matches:
        return _NO_MATCH
    if len(paths) == 1 and not path_has_wildcard(all_legs[0]):
        return matches[0]
    return matches


_NO_MATCH = object()


def modify(v, updates: list[tuple[str, object]], mode: str):
    """JSON_SET / JSON_INSERT / JSON_REPLACE (json_modify.rs).  Wildcards are
    rejected, matching MySQL."""
    for path, new in updates:
        legs = parse_path(path)
        if path_has_wildcard(legs):
            raise ValueError("wildcards not allowed in this json function")
        v = _modify_one(v, legs, new, mode)
    return v


def _modify_one(v, legs, new, mode):
    if not legs:
        return new if mode in ("set", "replace") else v
    leg, rest = legs[0], legs[1:]
    if leg[0] == MEMBER and isinstance(v, dict):
        key = leg[1]
        if key in v:
            out = dict(v)
            out[key] = _modify_one(v[key], rest, new, mode)
            return out
        if not rest and mode in ("set", "insert"):
            out = dict(v)
            out[key] = new
            return out
        return v
    if leg[0] == INDEX:
        arr = v if isinstance(v, list) else [v]
        idx = leg[1]
        if 0 <= idx < len(arr):
            out = list(arr)
            out[idx] = _modify_one(arr[idx], rest, new, mode)
            return out if isinstance(v, list) else (out[0] if len(out) == 1 else out)
        if not rest and mode in ("set", "insert"):
            return list(arr) + [new]  # append past the end, MySQL-style
        return v
    return v


def remove(v, paths: list[str]):
    """JSON_REMOVE.  Wildcards and '$' itself are rejected."""
    for path in paths:
        legs = parse_path(path)
        if not legs:
            raise ValueError("cannot remove the document root")
        if path_has_wildcard(legs):
            raise ValueError("wildcards not allowed in json_remove")
        v = _remove_one(v, legs)
    return v


def _remove_one(v, legs):
    leg, rest = legs[0], legs[1:]
    if leg[0] == MEMBER and isinstance(v, dict) and leg[1] in v:
        out = dict(v)
        if rest:
            out[leg[1]] = _remove_one(v[leg[1]], rest)
        else:
            del out[leg[1]]
        return out
    if leg[0] == INDEX and isinstance(v, list) and 0 <= leg[1] < len(v):
        out = list(v)
        if rest:
            out[leg[1]] = _remove_one(v[leg[1]], rest)
        else:
            del out[leg[1]]
        return out
    return v


# ---------------------------------------------------------------------------
# value operations
# ---------------------------------------------------------------------------


def json_type_name(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, JsonU64):
        return "UNSIGNED INTEGER"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def depth(v) -> int:
    if isinstance(v, dict):
        return 1 + max((depth(x) for x in v.values()), default=0)
    if isinstance(v, list):
        return 1 + max((depth(x) for x in v), default=0)
    return 1


def length(v) -> int:
    if isinstance(v, dict):
        return len(v)
    if isinstance(v, list):
        return len(v)
    return 1


def merge(values: list):
    """JSON_MERGE (merge-preserving, json_merge.rs): arrays concatenate,
    objects union with recursive merge, scalars wrap into arrays."""
    out = values[0]
    for nxt in values[1:]:
        out = _merge2(out, nxt)
    return out


def _merge2(a, b):
    a_arr, b_arr = isinstance(a, list), isinstance(b, list)
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _merge2(out[k], v) if k in out else v
        return out
    left = a if a_arr else [a]
    right = b if b_arr else [b]
    return left + right


def _json_eq(a, b) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    if type(a) is not type(b) and not (isinstance(a, type(b)) or isinstance(b, type(a))):
        return False
    return a == b


def contains(target, candidate) -> bool:
    """JSON_CONTAINS containment rules (json_contains.rs)."""
    if isinstance(target, dict):
        if not isinstance(candidate, dict):
            return False
        return all(k in target and contains(target[k], v) for k, v in candidate.items())
    if isinstance(target, list):
        if isinstance(candidate, list):
            return all(contains(target, el) for el in candidate)
        return any(contains(el, candidate) for el in target)
    if isinstance(candidate, (dict, list)):
        return False
    return _json_eq(target, candidate)


def quote(raw: bytes) -> bytes:
    """JSON_QUOTE: utf8 text → JSON string literal text."""
    return _pyjson.dumps(raw.decode("utf-8"), ensure_ascii=False).encode("utf-8")


def unquote(v) -> bytes:
    """JSON_UNQUOTE: string values yield their text; other values their
    serialization."""
    if isinstance(v, str):
        return v.encode("utf-8")
    return json_to_text(v).encode("utf-8")


# ---------------------------------------------------------------------------
# ordering (json/comparer: precedence groups, then within-group rules)
# ---------------------------------------------------------------------------

def _precedence(v) -> int:
    if v is None:
        return 0
    if isinstance(v, bool):
        return 5
    if isinstance(v, (int, float)):
        return 1
    if isinstance(v, str):
        return 2
    if isinstance(v, dict):
        return 3
    return 4  # array


def json_cmp_values(a, b) -> int:
    """Total order over decoded JSON values: precedence NULL < NUMBER <
    STRING < OBJECT < ARRAY < BOOLEAN; numbers numeric, strings byte order,
    arrays elementwise then length, objects by size then sorted pairs."""
    pa, pb = _precedence(a), _precedence(b)
    if pa != pb:
        return -1 if pa < pb else 1
    if pa == 0:
        return 0
    if pa == 5:
        return (a > b) - (a < b)
    if pa == 1:
        if isinstance(a, int) and isinstance(b, int):
            return (a > b) - (a < b)  # exact: floats lose ints above 2^53
        fa, fb = float(a), float(b)
        return (fa > fb) - (fa < fb)
    if pa == 2:
        ab, bb = a.encode("utf-8"), b.encode("utf-8")
        return (ab > bb) - (ab < bb)
    if pa == 4:
        for x, y in zip(a, b):
            c = json_cmp_values(x, y)
            if c:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    # objects: size, then MySQL-sorted (key, value) pairs
    if len(a) != len(b):
        return -1 if len(a) < len(b) else 1
    ka = sorted(a, key=lambda k: _key_sort(k.encode()))
    kb = sorted(b, key=lambda k: _key_sort(k.encode()))
    for x, y in zip(ka, kb):
        xb, yb = x.encode(), y.encode()
        if xb != yb:
            return -1 if _key_sort(xb) < _key_sort(yb) else 1
        c = json_cmp_values(a[x], b[y])
        if c:
            return c
    return 0


def json_cmp(a: bytes, b: bytes) -> int:
    """Compare two binary JSON payloads by value."""
    return json_cmp_values(json_decode(a), json_decode(b))
