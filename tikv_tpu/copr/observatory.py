"""Performance observatory: per-plan-signature path cost profiles.

The serving plane has six execution paths (zone full-tile, unary encoded,
fused, xregion-cached, mesh-sharded, CPU fallback) chosen by static
eligibility rules — and until now nobody *measured* what each path costs
per plan shape.  This module is the always-on, bounded, queryable
cost-measurement plane (docs/observatory.md):

* **Path cost profiles** — per (plan signature, path, encoding) streaming
  profiles over ring-buffered time windows: latency histogram with
  p50/p95/p99 accessors (the bucket-interpolation core is shared with
  ``util.metrics.Histogram.percentile``), rows/s, batch occupancy,
  padding-waste share, queue wait, decline/fallback causes, and exemplar
  trace ids from the tracing plane (docs/tracing.md) so "this sig's p99
  regressed" pivots straight to the exact slow trace.
* **Device-cost ledger** — every compile event at the jit boundary
  (``timed_jit`` wraps the jitted callables in jax_eval / jax_zone /
  parallel.mesh): wall time, plan sig, path, per-site executable cache
  size, and XLA ``cost_analysis()`` flops / bytes when the backend exposes
  them (gated behind ``TIKV_TPU_OBS_XLA_ANALYSIS=1`` — the AOT analysis
  pass costs a second lowering).  Recompile storms become a visible
  series instead of a latency mystery.
* **Pinned-HBM watermarks** — per pin-kind current bytes + high-water
  marks, fed by ``ColumnBlockCache.device_arrays`` build/evict deltas.
* **Regression floors** — ``write_floor``/``floor_diff`` snapshot per-sig
  baselines to disk; ``scripts/obs_diff.py`` gates any sig whose measured
  rows/s dropped more than the ratio (default 2x) against the stored
  floor.

Bounds: at most ``max_sigs`` signature entries (LRU, evictions counted),
``N_WINDOWS`` time windows per profile, ``_MAX_EXEMPLARS`` exemplars per
window, ``_LEDGER_CAP`` compile events.  The report hot path takes ONE
leaf lock owned by this module and calls nothing under it — it shares no
lock with serving (sanitizer-verified; the module is in
``_SANITIZER_WIRED``).

Kill switch: ``TIKV_TPU_OBSERVATORY=0`` turns every record call into a
no-op (the surfaces then report ``enabled: false``).
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..analysis.sanitizer import make_lock
from ..util.metrics import REGISTRY, percentile_from_buckets

__all__ = [
    "OBSERVATORY",
    "Observatory",
    "dag_sig",
    "floor_diff",
    "timed_jit",
]

# latency buckets (seconds) — finer than the metrics default at the fast
# end: warm device serves sit well under a millisecond
BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
           0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

N_WINDOWS = 8
_MAX_EXEMPLARS = 4
_LEDGER_CAP = 256
_MAX_DECLINE_CAUSES = 16

# pin-signature kind → watermark path label (docs/observatory.md): the
# stacked/nvoff pins are shared by the unary warm path and the xregion
# launcher, so they gauge under one "stacked" family label
PIN_PATHS = {
    "zone_layout": "zone",
    "shardslab": "mesh",
    "blockenc": "unary",
    "stackedenc": "stacked",
    "nvoff": "stacked",
}


def _enabled_env() -> bool:
    return os.environ.get("TIKV_TPU_OBSERVATORY", "1") not in ("0", "off", "")


def sig_id(sig: tuple) -> str:
    """Stable short id of a raw plan-signature tuple (the scheduler's
    grouping key) — what profiles, slow-log entries, and the compile
    ledger key on."""
    return hashlib.blake2b(repr(sig).encode(), digest_size=6).hexdigest()


def dag_sig(dag) -> tuple[str, str]:
    """(sig id, human description) for a DAG: the observatory's profile
    key.  The id hashes the scheduler's :func:`plan_signature` — the same
    normalization that decides micro-batch sharing, so two requests that
    can share a dispatch profile under one sig by construction."""
    from .scheduler import plan_signature  # lazy: scheduler imports jax_eval

    sig = plan_signature(dag)
    return sig_id(sig), _describe(sig)


def _describe(sig: tuple) -> str:
    """Compact plan string for operator displays (``ctl.py observatory``)."""
    parts = []
    for p in sig:
        k = p[0]
        if k == "tablescan":
            parts.append(f"scan(t{p[1]})")
        elif k == "indexscan":
            parts.append(f"iscan(t{p[1]}.i{p[2]})")
        elif k == "sel":
            parts.append(f"sel[{len(p[1])}]")
        elif k == "agg":
            ops = ",".join(str(a[0]) for a in p[3]) or "-"
            parts.append(f"agg({ops};g{len(p[2])})")
        elif k == "topn":
            parts.append(f"topn({p[1]})")
        elif k == "limit":
            parts.append(f"limit({p[1]})")
        elif k == "proj":
            parts.append(f"proj[{len(p[1])}]")
        elif k == "join":
            parts.append(f"join({p[1]};k{p[2]}=k{p[3]};b={_describe(p[4])})")
        elif k != "out":
            parts.append(str(k))
    return "|".join(parts)


class _Window:
    """One time window of a profile: non-cumulative latency buckets plus
    the secondary cost axes.  Exemplars keep the ``_MAX_EXEMPLARS`` slowest
    sampled trace ids of the window."""

    __slots__ = ("start", "count", "lat_sum", "rows", "occ_sum", "waste_sum",
                 "waste_n", "qwait_sum", "blk_exam", "blk_pruned",
                 "join_build", "join_probe", "join_out", "buckets",
                 "exemplars")

    def __init__(self, start: float):
        self.start = start
        self.count = 0
        self.lat_sum = 0.0
        self.rows = 0
        self.occ_sum = 0
        self.waste_sum = 0.0
        self.waste_n = 0
        self.qwait_sum = 0.0
        self.blk_exam = 0
        self.blk_pruned = 0
        self.join_build = 0
        self.join_probe = 0
        self.join_out = 0
        self.buckets = [0] * (len(BUCKETS) + 1)
        self.exemplars: list[tuple[float, str]] = []

    def add(self, latency_s, rows, occupancy, queue_wait_s, padding_waste,
            trace_id, blocks_examined=0, blocks_pruned=0,
            join_build_rows=0, join_probe_rows=0, join_out_rows=0) -> None:
        self.count += 1
        self.lat_sum += latency_s
        self.rows += rows
        self.occ_sum += occupancy
        self.qwait_sum += queue_wait_s
        self.blk_exam += blocks_examined
        self.blk_pruned += blocks_pruned
        self.join_build += join_build_rows
        self.join_probe += join_probe_rows
        self.join_out += join_out_rows
        if padding_waste is not None:
            self.waste_sum += padding_waste
            self.waste_n += 1
        for i, b in enumerate(BUCKETS):
            if latency_s <= b:
                self.buckets[i] += 1
                break
        else:
            self.buckets[-1] += 1
        if trace_id:
            ex = self.exemplars
            if len(ex) < _MAX_EXEMPLARS:
                ex.append((latency_s, trace_id))
            else:
                mi = min(range(len(ex)), key=lambda i: ex[i][0])
                if latency_s > ex[mi][0]:
                    ex[mi] = (latency_s, trace_id)


class _Profile:
    """Streaming cost profile for one (sig, path, encoding) key: a ring of
    time windows plus lifetime totals (the `top` sort key is lifetime time
    spent, like a profiler's cumulative column)."""

    __slots__ = ("window_s", "windows", "total_count", "total_lat",
                 "total_rows", "declines")

    def __init__(self, window_s: float, now: float):
        self.window_s = window_s
        self.windows: list[_Window] = [_Window(now)]
        self.total_count = 0
        self.total_lat = 0.0
        self.total_rows = 0
        self.declines: dict[str, int] = {}

    def _current(self, now: float) -> _Window:
        w = self.windows[-1]
        if now - w.start >= self.window_s:
            w = _Window(now)
            self.windows.append(w)
            if len(self.windows) > N_WINDOWS:
                del self.windows[: len(self.windows) - N_WINDOWS]
        return w

    def add(self, now, latency_s, rows, occupancy, queue_wait_s,
            padding_waste, trace_id, blocks_examined=0,
            blocks_pruned=0, join_build_rows=0, join_probe_rows=0,
            join_out_rows=0) -> None:
        self.total_count += 1
        self.total_lat += latency_s
        self.total_rows += rows
        self._current(now).add(latency_s, rows, occupancy, queue_wait_s,
                               padding_waste, trace_id,
                               blocks_examined, blocks_pruned,
                               join_build_rows, join_probe_rows,
                               join_out_rows)

    def decline(self, cause: str) -> None:
        if cause in self.declines or len(self.declines) < _MAX_DECLINE_CAUSES:
            self.declines[cause] = self.declines.get(cause, 0) + 1
        else:
            self.declines["other"] = self.declines.get("other", 0) + 1

    def view(self) -> dict:
        """Aggregate the retained windows into the reportable profile."""
        counts = [0] * (len(BUCKETS) + 1)
        n = lat = rows = occ = qwait = waste = 0.0
        waste_n = blk_exam = blk_pruned = 0
        j_build = j_probe = j_out = 0
        exemplars: list[tuple[float, str]] = []
        for w in self.windows:
            for i, c in enumerate(w.buckets):
                counts[i] += c
            n += w.count
            lat += w.lat_sum
            rows += w.rows
            occ += w.occ_sum
            qwait += w.qwait_sum
            waste += w.waste_sum
            waste_n += w.waste_n
            blk_exam += w.blk_exam
            blk_pruned += w.blk_pruned
            j_build += w.join_build
            j_probe += w.join_probe
            j_out += w.join_out
            exemplars.extend(w.exemplars)
        exemplars.sort(reverse=True)
        pct = lambda q: percentile_from_buckets(BUCKETS, counts, int(n), q)
        return {
            "count": int(n),
            "total_count": self.total_count,
            "time_spent_s": round(self.total_lat, 6),
            "window_count": int(n),
            "window_time_s": round(lat, 6),
            "rows": int(rows),
            "rows_per_s": round(rows / lat, 3) if lat > 0 else 0.0,
            "p50_ms": round(pct(0.50) * 1e3, 4),
            "p95_ms": round(pct(0.95) * 1e3, 4),
            "p99_ms": round(pct(0.99) * 1e3, 4),
            "mean_ms": round(lat / n * 1e3, 4) if n else 0.0,
            "mean_occupancy": round(occ / n, 3) if n else 0.0,
            "padding_waste": round(waste / waste_n, 4) if waste_n else None,
            # zone-map pruning effectiveness (docs/zone_maps.md): blocks the
            # serve paths examined vs proved empty and skipped/masked
            "blocks_examined": blk_exam,
            "blocks_pruned": blk_pruned,
            "pruned_fraction": (round(blk_pruned / blk_exam, 4)
                                if blk_exam else None),
            # device join profile (docs/device_join.md): per-sig build and
            # probe magnitudes plus output selectivity (out rows per probe
            # row) — what the cost router's join pricing keys on
            "join_build_rows": j_build,
            "join_probe_rows": j_probe,
            "join_out_rows": j_out,
            "join_selectivity": (round(j_out / j_probe, 4)
                                 if j_probe else None),
            "queue_wait_ms_mean": round(qwait / n * 1e3, 4) if n else 0.0,
            "declines": dict(self.declines),
            "exemplar_traces": [tid for _lat, tid in exemplars[:_MAX_EXEMPLARS]],
        }


class _SigEntry:
    __slots__ = ("desc", "paths", "last_used", "routes")

    def __init__(self, desc: str, now: float):
        self.desc = desc
        self.paths: dict[tuple[str, str], _Profile] = {}
        self.last_used = now
        # cost-router decisions for this sig: (path, reason) -> count
        self.routes: dict[tuple[str, str], int] = {}


class Observatory:
    """The bounded in-memory flight recorder every serve path reports into.

    One process-global instance (``OBSERVATORY``) serves the status
    server's ``/debug/observatory``, the ``debug_observatory`` RPC, and
    ``ctl.py observatory`` — mirroring how the tracer is surfaced."""

    def __init__(self, window_s: float | None = None,
                 max_sigs: int | None = None, enabled: bool | None = None):
        self.enabled = _enabled_env() if enabled is None else enabled
        self.window_s = window_s if window_s is not None else float(
            os.environ.get("TIKV_TPU_OBS_WINDOW_S", "15"))
        self.max_sigs = max_sigs if max_sigs is not None else int(
            os.environ.get("TIKV_TPU_OBS_MAX_SIGS", "64"))
        self.xla_analysis = os.environ.get(
            "TIKV_TPU_OBS_XLA_ANALYSIS", "0") == "1"
        # LEAF lock by construction: nothing is called while holding it —
        # the report hot path shares no lock with serving
        self._mu = make_lock("copr.observatory")
        self._sigs: dict[str, _SigEntry] = {}
        self._evicted = 0
        self._started = time.monotonic()
        # compile ledger: bounded event ring + per-(sig, path) aggregates +
        # per-site executable cache sizes
        self._compiles: list[dict] = []
        self._compile_agg: dict[tuple[str, str], dict] = {}
        self._cache_sizes: dict[str, int] = {}
        # pinned-HBM accounting by pin kind (PIN_PATHS): current + watermark
        self._hbm: dict[str, list[float]] = {}  # path -> [current, watermark]

    # -- report hot path ----------------------------------------------------

    def record_serve(self, sig: str, path: str, latency_s: float, *,
                     rows: int = 0, encoding: str = "plain",
                     occupancy: int = 1, queue_wait_s: float = 0.0,
                     padding_waste: float | None = None,
                     trace_id: str | None = None, desc: str = "",
                     blocks_examined: int = 0,
                     blocks_pruned: int = 0,
                     join_build_rows: int = 0,
                     join_probe_rows: int = 0,
                     join_out_rows: int = 0) -> None:
        """One served request on ``path`` under plan signature ``sig``.
        ``latency_s`` is the request's attributed share for batch-served
        riders (the scheduler's per-request share), the tracked total for
        unary serves."""
        if not self.enabled or not sig:
            return
        now = time.monotonic()
        with self._mu:
            entry = self._touch_locked(sig, desc, now)
            prof = entry.paths.get((path, encoding))
            if prof is None:
                prof = entry.paths[(path, encoding)] = _Profile(self.window_s, now)
            prof.add(now, latency_s, rows, occupancy, queue_wait_s,
                     padding_waste, trace_id, blocks_examined, blocks_pruned,
                     join_build_rows, join_probe_rows, join_out_rows)
        REGISTRY.counter(
            "tikv_observatory_serve_total",
            "Requests recorded by the performance observatory, by path",
        ).inc(path=path)
        REGISTRY.gauge(
            "tikv_observatory_evicted_sigs",
            "Profile signatures evicted by the observatory's LRU bound",
        ).set(self._evicted)
        REGISTRY.histogram(
            "tikv_observatory_serve_seconds",
            "Per-request attributed latency recorded by the observatory",
            buckets=BUCKETS,
        ).observe(latency_s, path=path)
        if rows:
            REGISTRY.counter(
                "tikv_observatory_rows_total",
                "Rows processed by recorded serves, by path",
            ).inc(rows, path=path)

    def record_decline(self, sig: str | None, path: str, cause: str) -> None:
        """A decline/fallback/shed on ``path`` — the per-sig half of the
        global ``tikv_coprocessor_path_fallback_total`` story: WHY does
        *this plan shape* keep missing its fast path."""
        if not self.enabled:
            return
        if sig:
            now = time.monotonic()
            with self._mu:
                entry = self._touch_locked(sig, "", now)
                prof = None
                for (p, _e), pr in entry.paths.items():
                    # attach to the path's existing encoding profile
                    if p == path:
                        prof = pr
                        break
                if prof is None:
                    prof = entry.paths[(path, "plain")] = _Profile(
                        self.window_s, now)
                prof.decline(cause)
        REGISTRY.counter(
            "tikv_observatory_decline_total",
            "Path declines/sheds recorded by the observatory, by path and cause",
        ).inc(path=path, cause=cause)

    def record_route(self, sig: str, path: str, reason: str,
                     desc: str = "") -> None:
        """One cost-router decision for ``sig`` (docs/cost_router.md):
        which path won and why (measured / explore / cold / static_fallback
        / kill_switch).  Kept per-sig so ``format_sig`` shows decisions next
        to the measured profiles they came from."""
        if not self.enabled or not sig:
            return
        now = time.monotonic()
        with self._mu:
            entry = self._touch_locked(sig, desc, now)
            key = (path, reason)
            entry.routes[key] = entry.routes.get(key, 0) + 1

    def path_costs(self, sig: str, amortize_floor: int = 1) -> dict[str, dict]:
        """Per-path cost view for the router: merge this sig's encodings
        per path label (highest window count wins — the encoding actually
        serving now), and fold the compile ledger's amortized cost in.
        ``cost_ms`` is the router's scalar: windowed p50 latency (the
        median is robust to the compile-laden first serve, which would
        otherwise double-count compile — it is already in the ledger) plus
        the sig's compile wall time amortized over its lifetime serves —
        ``amortize_floor`` caps the penalty for freshly compiled paths by
        assuming at least that many serves will share the compile (without
        it a just-compiled device path prices above the CPU pipeline until
        enough traffic has drained, and explore-rate trickle never
        un-sticks it)."""
        with self._mu:
            entry = self._sigs.get(sig)
            views: dict[str, dict] = {}
            if entry is not None:
                for (p, _e), prof in entry.paths.items():
                    v = prof.view()
                    if p in views and views[p]["count"] >= v["count"]:
                        continue
                    views[p] = v
            agg = {p: dict(a) for (s, p), a in self._compile_agg.items()
                   if s == sig}
        out: dict[str, dict] = {}
        for p, v in views.items():
            compile_ms = 0.0
            a = agg.get(p)
            if a and v["total_count"]:
                compile_ms = (a["wall_s"] * 1e3
                              / max(v["total_count"], amortize_floor))
            out[p] = {
                "count": v["count"],
                "total_count": v["total_count"],
                "mean_ms": v["mean_ms"],
                "p50_ms": v["p50_ms"],
                "p95_ms": v["p95_ms"],
                "rows_per_s": v["rows_per_s"],
                "queue_wait_ms_mean": v["queue_wait_ms_mean"],
                "mean_occupancy": v["mean_occupancy"],
                "compile_amortized_ms": round(compile_ms, 4),
                "cost_ms": round(v["p50_ms"] + compile_ms, 4),
            }
        return out

    def totals(self) -> dict:
        """Lifetime aggregate across every live sig/path — the geometry
        tuner's throughput probe: deltas of (rows, busy seconds, serves)
        between ticks are robust to window aging, unlike windowed rates."""
        with self._mu:
            count = rows = 0
            lat = 0.0
            for entry in self._sigs.values():
                for prof in entry.paths.values():
                    count += prof.total_count
                    rows += prof.total_rows
                    lat += prof.total_lat
        return {"serves": count, "rows": rows, "busy_s": round(lat, 6)}

    def _touch_locked(self, sig: str, desc: str, now: float) -> _SigEntry:
        entry = self._sigs.pop(sig, None)
        if entry is None:
            entry = _SigEntry(desc, now)
            while len(self._sigs) >= self.max_sigs:
                self._sigs.pop(next(iter(self._sigs)))
                self._evicted += 1
        else:
            if desc and not entry.desc:
                entry.desc = desc
            entry.last_used = now
        self._sigs[sig] = entry  # reinsert = LRU touch
        return entry

    # -- device-cost ledger -------------------------------------------------

    def record_compile(self, site: str, path: str, wall_s: float, *,
                       sig: str = "", cache_size: int | None = None,
                       flops: float | None = None,
                       bytes_accessed: float | None = None) -> None:
        """One compile event at the jit boundary: ``wall_s`` is the
        first-call wall time (trace + XLA compile + the first execute —
        the cost a request actually pays when it triggers the compile)."""
        if not self.enabled:
            return
        ev = {
            "t": round(time.monotonic() - self._started, 3),
            "site": site,
            "path": path,
            "sig": sig,
            "wall_s": round(wall_s, 6),
        }
        if cache_size is not None:
            ev["cache_size"] = cache_size
        if flops is not None:
            ev["flops"] = flops
        if bytes_accessed is not None:
            ev["bytes_accessed"] = bytes_accessed
        with self._mu:
            self._compiles.append(ev)
            if len(self._compiles) > _LEDGER_CAP:
                del self._compiles[: len(self._compiles) - _LEDGER_CAP]
            agg = self._compile_agg.setdefault(
                (sig, path), {"count": 0, "wall_s": 0.0})
            agg["count"] += 1
            agg["wall_s"] += wall_s
            while len(self._compile_agg) > self.max_sigs * 4:
                self._compile_agg.pop(next(iter(self._compile_agg)))
            if cache_size is not None:
                self._cache_sizes[site] = cache_size
                while len(self._cache_sizes) > 64:
                    self._cache_sizes.pop(next(iter(self._cache_sizes)))
        REGISTRY.counter(
            "tikv_observatory_compile_total",
            "XLA compile events at the jit boundary, by path",
        ).inc(path=path)
        REGISTRY.histogram(
            "tikv_observatory_compile_seconds",
            "First-call wall time of compile events (trace+compile+execute)",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30),
        ).observe(wall_s, path=path)

    def note_pin(self, kind: str, delta_bytes: int) -> None:
        """Pinned-HBM delta for one pin-signature kind (fed by
        ``ColumnBlockCache.device_arrays``): maintains the current bytes
        and the high-water mark per path label."""
        if not self.enabled or not delta_bytes:
            return
        path = PIN_PATHS.get(kind, "stacked")
        with self._mu:
            cur = self._hbm.setdefault(path, [0.0, 0.0])
            cur[0] = max(cur[0] + delta_bytes, 0.0)
            cur[1] = max(cur[1], cur[0])
            snap_cur, snap_max = cur
        g = REGISTRY.gauge(
            "tikv_observatory_pinned_hbm_bytes",
            "Bytes currently pinned on devices, by pin path",
        )
        g.set(snap_cur, path=path)
        REGISTRY.gauge(
            "tikv_observatory_pinned_hbm_watermark_bytes",
            "High-water mark of device-pinned bytes, by pin path",
        ).set(snap_max, path=path)

    # -- queryable surfaces -------------------------------------------------

    def snapshot(self, sig: str | None = None) -> dict:
        """The full observatory view (``/debug/observatory``,
        ``debug_observatory``): per-sig path profiles, the compile ledger,
        and the HBM watermarks.  ``sig`` narrows to one signature."""
        with self._mu:
            sigs = {}
            for s, entry in self._sigs.items():
                if sig is not None and s != sig:
                    continue
                sigs[s] = {
                    "desc": entry.desc,
                    "paths": {
                        f"{p}|{e}": prof.view()
                        for (p, e), prof in entry.paths.items()
                    },
                }
                if entry.routes:
                    sigs[s]["routes"] = {
                        f"{p}|{r}": n for (p, r), n in entry.routes.items()
                    }
            compiles = list(self._compiles) if sig is None else [
                ev for ev in self._compiles if ev.get("sig") == sig]
            compile_agg = {
                f"{s or '-'}|{p}": dict(agg)
                for (s, p), agg in self._compile_agg.items()
                if sig is None or s == sig
            }
            out = {
                "enabled": self.enabled,
                "window_s": self.window_s,
                "n_windows": N_WINDOWS,
                "max_sigs": self.max_sigs,
                "live_sigs": len(self._sigs),
                "evicted_sigs": self._evicted,
                "uptime_s": round(time.monotonic() - self._started, 1),
                "sigs": sigs,
                "compiles": {
                    "events": compiles,
                    "by_sig_path": compile_agg,
                    "executable_cache_sizes": dict(self._cache_sizes),
                },
                "hbm": {
                    p: {"bytes": int(v[0]), "watermark_bytes": int(v[1])}
                    for p, v in self._hbm.items()
                },
            }
        REGISTRY.gauge(
            "tikv_observatory_sigs",
            "Plan signatures currently profiled by the observatory",
        ).set(out["live_sigs"])
        return out

    def top(self, n: int = 20) -> list[dict]:
        """(sig, path) rows sorted by lifetime time spent — a live
        profiler's cumulative-time top for the serving plane."""
        with self._mu:
            rows = []
            for s, entry in self._sigs.items():
                for (p, e), prof in entry.paths.items():
                    v = prof.view()
                    rows.append({
                        "sig": s,
                        "desc": entry.desc,
                        "path": p,
                        "encoding": e,
                        **{k: v[k] for k in (
                            "time_spent_s", "total_count", "count",
                            "rows_per_s", "p50_ms", "p95_ms", "p99_ms",
                            "mean_occupancy")},
                    })
        rows.sort(key=lambda r: r["time_spent_s"], reverse=True)
        return rows[:n]

    # -- regression floors --------------------------------------------------

    def floor(self, min_count: int = 3) -> dict:
        """Per-(sig, path) rows/s baselines from the current windows —
        what ``write_floor`` persists and ``scripts/obs_diff.py`` gates
        against."""
        snap = self.snapshot()
        sigs = {}
        for s, entry in snap["sigs"].items():
            paths = {}
            for pk, v in entry["paths"].items():
                if v["count"] >= min_count and v["rows_per_s"] > 0:
                    paths[pk] = {
                        "rows_per_s": v["rows_per_s"],
                        "p95_ms": v["p95_ms"],
                        "count": v["count"],
                        "desc": entry["desc"],
                    }
                    if v.get("pruned_fraction") is not None:
                        # zone-map effectiveness floor (docs/zone_maps.md)
                        paths[pk]["pruned_fraction"] = v["pruned_fraction"]
            if paths:
                sigs[s] = paths
        return {"version": 1, "written_at": time.time(), "sigs": sigs}

    def write_floor(self, path: str, min_count: int = 3) -> dict:
        fl = self.floor(min_count=min_count)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(fl, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return fl

    def reset(self) -> None:
        with self._mu:
            self._sigs.clear()
            self._compiles.clear()
            self._compile_agg.clear()
            self._cache_sizes.clear()
            self._hbm.clear()
            self._evicted = 0
            self._started = time.monotonic()


def floor_diff(floor: dict, current: dict, ratio: float = 2.0,
               min_count: int = 3) -> dict:
    """Compare a live/current observatory snapshot against a stored floor:
    any (sig, path) whose measured rows/s dropped more than ``ratio``
    below the floor is a regression.  ``current`` may be a full
    ``snapshot()`` dict or another ``floor()`` dict — both carry
    ``sigs``."""
    regressions = []
    checked = 0
    missing = []
    for s, paths in (floor.get("sigs") or {}).items():
        cur_entry = (current.get("sigs") or {}).get(s)
        for pk, base in paths.items():
            if cur_entry is None:
                missing.append(f"{s}/{pk}")
                continue
            cur = cur_entry.get("paths", cur_entry).get(pk)
            if isinstance(cur, dict) and "paths" in cur:  # defensive
                cur = None
            if cur is None:
                missing.append(f"{s}/{pk}")
                continue
            if cur.get("count", 0) < min_count:
                missing.append(f"{s}/{pk}")
                continue
            checked += 1
            base_r = float(base["rows_per_s"])
            cur_r = float(cur.get("rows_per_s") or 0.0)
            if cur_r <= 0 or base_r / max(cur_r, 1e-12) > ratio:
                regressions.append({
                    "sig": s,
                    "path": pk,
                    "desc": base.get("desc", ""),
                    "floor_rows_per_s": base_r,
                    "rows_per_s": cur_r,
                    "drop": round(base_r / max(cur_r, 1e-12), 2),
                })
            # zone-map pruning regression (docs/zone_maps.md): a plan whose
            # floor recorded meaningful pruning must keep pruning — a sharp
            # drop means zones stopped proving emptiness (a maintenance bug
            # or an eligibility regression), even when rows/s still passes
            # because the serve got cheaper elsewhere
            base_pf = base.get("pruned_fraction")
            cur_pf = cur.get("pruned_fraction")
            if (base_pf is not None and base_pf >= 0.05
                    and (cur_pf or 0.0) < base_pf / ratio):
                regressions.append({
                    "sig": s,
                    "path": pk,
                    "desc": base.get("desc", ""),
                    "kind": "pruning",
                    "floor_pruned_fraction": base_pf,
                    "pruned_fraction": cur_pf or 0.0,
                })
    return {
        "ok": not regressions,
        "checked": checked,
        "ratio": ratio,
        "regressions": regressions,
        "missing": missing,
    }


# ---------------------------------------------------------------------------
# jit-boundary hook
# ---------------------------------------------------------------------------


class _TimedJit:
    """Wraps an ALREADY-jitted callable: steady-state calls pay one C-level
    ``_cache_size()`` probe and an int compare; a call that grew the
    executable cache records a compile event (wall = that call's whole
    duration).  XLA cost/memory analysis is attempted only under
    ``TIKV_TPU_OBS_XLA_ANALYSIS=1`` (it costs a second lowering, and
    donated buffers can make it impossible after the fact — failures are
    silently skipped)."""

    __slots__ = ("fn", "site", "path", "sig", "_seen")

    def __init__(self, fn, site: str, path: str, sig: str = ""):
        self.fn = fn
        self.site = site
        self.path = path
        self.sig = sig or ""
        self._seen = -1

    def _cache_size(self):
        try:
            return self.fn._cache_size()
        except Exception:  # noqa: BLE001 — non-pjit callable: no ledger
            return None

    def __call__(self, *args):
        t0 = time.perf_counter()
        out = self.fn(*args)
        # the post-call probe is the only reliable compile detector: a new
        # argument SHAPE compiles even when the cache was already warm, so
        # a pre-call fast path would miss every recompile after the first
        after = self._cache_size()
        if after is not None and after != self._seen:
            wall = time.perf_counter() - t0
            flops = nbytes = None
            if OBSERVATORY.xla_analysis:
                try:
                    compiled = self.fn.lower(*args).compile()
                    ca = compiled.cost_analysis()
                    if isinstance(ca, (list, tuple)):
                        ca = ca[0] if ca else {}
                    flops = float(ca.get("flops", 0.0)) or None
                    nbytes = float(ca.get("bytes accessed", 0.0)) or None
                except Exception:  # noqa: BLE001 — analysis is best-effort
                    pass
            OBSERVATORY.record_compile(
                self.site, self.path, wall, sig=self.sig,
                cache_size=after, flops=flops, bytes_accessed=nbytes)
            self._seen = after
        return out


def timed_jit(fn, site: str, path: str, sig: str = ""):
    """Hook a jitted callable into the device-cost ledger.  Call sites keep
    their literal ``jax.jit(...)`` (the static-analysis jit rules still see
    it) and wrap the result: ``timed_jit(jax.jit(f), "jax_eval.scan",
    "unary", sig=self.obs_sig)``."""
    if not OBSERVATORY.enabled:
        return fn
    return _TimedJit(fn, site, path, sig)


def format_top(rows: list[dict]) -> str:
    """Aligned text table for ``ctl.py observatory top`` and the status
    server's ``/debug/observatory`` — a live profiler top sorted by time
    spent."""
    hdr = (f"{'SIG':>12} {'PATH':>8} {'ENC':>7} {'SPENT_S':>9} {'REQS':>7} "
           f"{'ROWS/S':>12} {'P50_MS':>9} {'P95_MS':>9} {'P99_MS':>9} "
           f"{'OCC':>5}  DESC")
    lines = [hdr]
    for r in rows:
        lines.append(
            f"{r['sig']:>12} {r['path']:>8} {r['encoding']:>7} "
            f"{r['time_spent_s']:>9.3f} {r['total_count']:>7} "
            f"{r['rows_per_s']:>12.1f} {r['p50_ms']:>9.3f} "
            f"{r['p95_ms']:>9.3f} {r['p99_ms']:>9.3f} "
            f"{r['mean_occupancy']:>5.1f}  {r['desc']}")
    return "\n".join(lines)


def format_sig(sig: str, entry: dict) -> str:
    """One signature's full profile as text (``ctl.py observatory sig``)."""
    lines = [f"sig {sig}  {entry.get('desc', '')}"]
    for pk, v in sorted(entry.get("paths", {}).items()):
        lines.append(
            f"  {pk}: n={v['count']} (lifetime {v['total_count']}) "
            f"rows/s={v['rows_per_s']} p50={v['p50_ms']}ms "
            f"p95={v['p95_ms']}ms p99={v['p99_ms']}ms "
            f"occ={v['mean_occupancy']} qwait={v['queue_wait_ms_mean']}ms"
            + (f" waste={v['padding_waste']}"
               if v.get("padding_waste") is not None else ""))
        if v.get("join_probe_rows"):
            lines.append(
                f"    join: build={v['join_build_rows']} "
                f"probe={v['join_probe_rows']} out={v['join_out_rows']} "
                f"selectivity={v['join_selectivity']}")
        if v.get("declines"):
            lines.append(f"    declines: {v['declines']}")
        if v.get("exemplar_traces"):
            lines.append(f"    exemplars: {', '.join(v['exemplar_traces'])}")
    routes = entry.get("routes")
    if routes:
        pairs = ", ".join(f"{k}={n}" for k, n in sorted(routes.items()))
        lines.append(f"  routes: {pairs}")
    return "\n".join(lines)


def count_backend_probe(verdict: str) -> None:
    """Bench backend-probe verdicts (ok / timeout / error): the counter
    that makes an attested-accelerator bench run distinguishable from a
    wedged probe (ROADMAP bench-attestation gap)."""
    REGISTRY.counter(
        "tikv_observatory_backend_probe_total",
        "Bench backend-probe verdicts (docs/observatory.md)",
    ).inc(verdict=verdict)


OBSERVATORY = Observatory()
