"""Vectorized group-dictionary encoding.

Group-by keys are mapped to dense ids in **first-occurrence stream order**
(the CPU hash-agg's insertion order and the device path's group order both
come from here, which is what keeps their outputs byte-identical).

The per-block work is numpy: ``np.unique(return_inverse)`` gives block-local
codes, and only the (small) set of block-local uniques goes through the Python
dictionary, so cost per block is O(n log u) vectorized + O(u) interpreted —
not O(n) interpreted like a per-row dict loop.
"""

from __future__ import annotations

import numpy as np


class GroupDict:
    """Incremental key→dense-id dictionary over column batches."""

    def __init__(self):
        self.index: dict = {}
        self.rows: list[tuple] = []  # gid -> key tuple (python values, None=NULL)

    def __len__(self) -> int:
        return len(self.rows)

    def assign(self, parts: list[tuple[np.ndarray, np.ndarray]]) -> np.ndarray:
        """parts: per group-expr (data, nulls) arrays over the SAME rows.
        Returns int64 gids aligned with those rows."""
        n = len(parts[0][0]) if parts else 0
        if n == 0:
            return np.empty(0, dtype=np.int64)
        if len(parts) == 1:
            return self._assign_single(*parts[0])
        return self._assign_tuple(parts)

    # -- single key, fully vectorized --------------------------------------

    def _assign_single(self, data: np.ndarray, nulls: np.ndarray) -> np.ndarray:
        if data.dtype == object:
            # NOTE: numpy 'S' arrays strip trailing NUL bytes (b"a" == b"a\x00"),
            # so bytes keys must stay object dtype; np.unique compares them as
            # python objects — slower, but exact
            arr = data
            if nulls.any():
                arr = data.copy()
                arr[nulls] = b""
        else:
            arr = data
        # block-local code: null rows get the dedicated slot len(uniq)
        uniq, inverse = np.unique(arr, return_inverse=True)
        codes = np.where(nulls, len(uniq), inverse)
        # map local code -> global gid, creating new gids in first-occurrence
        # order: the first row of each local code via one reversed fancy-store
        # (last write wins ⇒ smallest row index; avoids the slow .at ufuncs)
        n_local = len(uniq) + 1
        first_row = np.full(n_local, -1, dtype=np.int64)
        n = len(codes)
        first_row[codes[::-1]] = np.arange(n - 1, -1, -1, dtype=np.int64)
        present = np.flatnonzero(first_row >= 0)
        order = present[np.argsort(first_row[present], kind="stable")]
        local_to_global = np.empty(n_local, dtype=np.int64)
        for lc in order:
            if lc == len(uniq):
                key = None
            else:
                v = uniq[lc]
                key = bytes(v) if isinstance(v, (bytes, np.bytes_)) else v.item()
            gid = self.index.get(key)
            if gid is None:
                gid = len(self.rows)
                self.index[key] = gid
                self.rows.append((key,))
            local_to_global[lc] = gid
        return local_to_global[codes]

    def assign_coded(
        self, codes: np.ndarray, nulls: np.ndarray, dictionary: np.ndarray
    ) -> np.ndarray:
        """Fast path for an already dictionary-encoded group column: codes are
        dense in [0, D), so no np.unique pass is needed — first-occurrence
        rows come from one reversed fancy-store (O(n), no .at ufuncs)."""
        n = len(codes)
        d = len(dictionary)
        local = np.where(nulls, d, codes).astype(np.int64)
        first_row = np.full(d + 1, -1, dtype=np.int64)
        # reversed store: the last write per slot is the smallest row index
        first_row[local[::-1]] = np.arange(n - 1, -1, -1, dtype=np.int64)
        present = np.flatnonzero(first_row >= 0)
        order = present[np.argsort(first_row[present], kind="stable")]
        local_to_global = np.empty(d + 1, dtype=np.int64)
        for lc in order:
            key = None if lc == d else bytes(dictionary[lc])
            gid = self.index.get(key)
            if gid is None:
                gid = len(self.rows)
                self.index[key] = gid
                self.rows.append((key,))
            local_to_global[lc] = gid
        return local_to_global[local]

    def assign_coded_multi(
        self, parts: list[tuple[np.ndarray, np.ndarray, np.ndarray]]
    ) -> np.ndarray:
        """Composite key over multiple dictionary-encoded columns: fold the
        per-column codes into one dense product code (null gets a dedicated
        slot per column), then the single-code path.  Capacity is the product
        of dictionary sizes — callers gate on it staying small."""
        n = len(parts[0][0])
        local = np.zeros(n, dtype=np.int64)
        cap = 1
        for codes, nulls, dictionary in parts:
            d = len(dictionary)
            local = local * (d + 1) + np.where(nulls, d, codes)
            cap *= d + 1
        first_row = np.full(cap, -1, dtype=np.int64)
        first_row[local[::-1]] = np.arange(n - 1, -1, -1, dtype=np.int64)
        present = np.flatnonzero(first_row >= 0)
        order = present[np.argsort(first_row[present], kind="stable")]
        local_to_global = np.zeros(cap, dtype=np.int64)
        for lc in order:
            parts_key = []
            rem = int(lc)
            for codes, nulls, dictionary in reversed(parts):
                d = len(dictionary)
                c = rem % (d + 1)
                rem //= d + 1
                parts_key.append(None if c == d else bytes(dictionary[c]))
            key = tuple(reversed(parts_key))
            gid = self.index.get(key)
            if gid is None:
                gid = len(self.rows)
                self.index[key] = gid
                self.rows.append(key)
            local_to_global[lc] = gid
        return local_to_global[local]

    # -- composite key fallback --------------------------------------------

    def _assign_tuple(self, parts) -> np.ndarray:
        n = len(parts[0][0])
        gids = np.empty(n, dtype=np.int64)
        index = self.index
        rows = self.rows
        for i in range(n):
            key = tuple(
                None if nl[i] else (bytes(d[i]) if d.dtype == object else d[i].item())
                for d, nl in parts
            )
            gid = index.get(key)
            if gid is None:
                gid = len(rows)
                index[key] = gid
                rows.append(key)
            gids[i] = gid
        return gids
