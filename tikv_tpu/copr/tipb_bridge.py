"""tipb wire <-> internal plan/response bridge.

Decodes a protobuf ``tipb.DAGRequest`` (the bytes TiDB puts in
``coprocessor.Request.data``, src/coprocessor/mod.rs parse path) into this
framework's internal ``DagRequest``, and encodes internal ``SelectResponse``s
back into protobuf ``tipb.SelectResponse`` bytes in either encode type:

* ``TypeDefault`` — datum-encoded rows.  Internal chunks already hold
  reference-format datums except decimals (internally a compact
  frac+i64-scaled pair); those are re-encoded as MySQL binary decimals
  (decimal.rs write_bin) so the wire bytes follow the reference contract.
* ``TypeChunk`` — the Arrow-like column layout (chunk_codec), which needs the
  output schema's field types.

Expression trees translate through the ScalarFuncSig tables: wire sig number
-> CamelCase name (proto.tipb_pb.SIG_NAME) -> kernel (copr.sig_map).
"""

from __future__ import annotations

from ..proto import tipb_pb as tp
from ..util import codec
from . import datum as datum_mod
from .aggr import AggDescriptor
from .chunk_codec import ChunkColumn, encode_chunk
from .dag import (
    Aggregation,
    DagRequest,
    IndexScan,
    Limit,
    Selection,
    SelectResponse,
    TableScan,
    TopN,
)
from .datatypes import ColumnInfo, FieldType, FieldTypeTp
from .mydecimal import MyDecimal
from .rpn import FuncCall, call, col, const_bytes, const_decimal, const_int, const_real
from .sig_map import resolve_sig

from .collation import collation_name

_AGG_OPS = {
    tp.ExprType.Count: "count",
    tp.ExprType.Sum: "sum",
    tp.ExprType.Avg: "avg",
    tp.ExprType.Min: "min",
    tp.ExprType.Max: "max",
    tp.ExprType.First: "first",
    tp.ExprType.AggBitAnd: "bit_and",
    tp.ExprType.AggBitOr: "bit_or",
    tp.ExprType.AggBitXor: "bit_xor",
    tp.ExprType.VarPop: "var_pop",
}


class TipbError(ValueError):
    pass


def field_type_from_pb(ci: tp.ColumnInfoPb) -> FieldType:
    collation = collation_name(getattr(ci, "collation", 0) or 0)
    return FieldType(
        tp=FieldTypeTp(ci.tp),
        flag=getattr(ci, "flag", 0) or 0,
        flen=getattr(ci, "column_len", -1) or -1,
        decimal=getattr(ci, "decimal", 0) or 0,
        collation=collation,
    )


def column_info_from_pb(ci: tp.ColumnInfoPb) -> ColumnInfo:
    return ColumnInfo(
        col_id=ci.column_id,
        ftype=field_type_from_pb(ci),
        is_pk_handle=bool(getattr(ci, "pk_handle", False)),
    )


def expr_from_pb(e: tp.Expr):
    """tipb Expr tree -> internal expression (rpn builders)."""
    t = e.tp
    val = e.val or b""
    if t == tp.ExprType.ColumnRef:
        return col(codec.decode_i64(val, 0))
    if t == tp.ExprType.Int64:
        return const_int(codec.decode_i64(val, 0))
    if t == tp.ExprType.Uint64:
        return const_int(codec.decode_u64(val, 0))
    if t == tp.ExprType.Null:
        return const_int(None)
    if t in (tp.ExprType.Float64, tp.ExprType.Float32):
        return const_real(codec.decode_f64(val, 0))
    if t in (tp.ExprType.String, tp.ExprType.Bytes):
        return const_bytes(val)
    if t == tp.ExprType.MysqlDecimal:
        prec, frac = val[0], val[1]
        d, _ = MyDecimal.decode_bin(val[2:], prec, frac)
        scaled, dfrac = d.to_i64_scaled()
        return const_decimal(scaled, dfrac)
    if t == tp.ExprType.MysqlDuration:
        from .rpn import Constant
        from .datatypes import EvalType

        return Constant(codec.decode_i64(val, 0), EvalType.DURATION)
    if t == tp.ExprType.MysqlTime:
        from .rpn import Constant
        from .datatypes import EvalType

        return Constant(codec.decode_u64(val, 0), EvalType.DATETIME)
    if t == tp.ExprType.MysqlJson:
        from .rpn import const_json
        from .json_value import decode_json_binary

        return const_json(decode_json_binary(val))
    if t == tp.ExprType.ScalarFunc:
        name = tp.SIG_NAME.get(e.sig)
        if name is None:
            raise TipbError(f"unknown ScalarFuncSig {e.sig}")
        kernel = resolve_sig(name)
        if kernel is None or kernel.startswith("~"):
            raise TipbError(f"unsupported sig {name}")
        return call(kernel, *[expr_from_pb(c) for c in e.children])
    raise TipbError(f"unsupported ExprType {t}")


def agg_from_pb(e: tp.Expr) -> AggDescriptor:
    op = _AGG_OPS.get(e.tp)
    if op is None:
        raise TipbError(f"unsupported aggregate ExprType {e.tp}")
    arg = None
    if e.children:
        arg = expr_from_pb(e.children[0])
        if op == "count" and not isinstance(arg, FuncCall) and getattr(arg, "value", 1) is not None \
                and not hasattr(arg, "index"):
            arg = None  # count(const) == count(1) == count(*)
    return AggDescriptor(op, arg)


def dag_from_pb(pb: tp.DAGRequest) -> DagRequest:
    execs = []
    for ex in pb.executors:
        t = ex.tp
        if t == tp.ExecType.TypeTableScan:
            s = ex.tbl_scan
            execs.append(TableScan(s.table_id, [column_info_from_pb(c) for c in s.columns]))
        elif t == tp.ExecType.TypeIndexScan:
            s = ex.idx_scan
            execs.append(IndexScan(s.table_id, s.index_id,
                                   [column_info_from_pb(c) for c in s.columns]))
        elif t == tp.ExecType.TypeSelection:
            execs.append(Selection([expr_from_pb(c) for c in ex.selection.conditions]))
        elif t in (tp.ExecType.TypeAggregation, tp.ExecType.TypeStreamAgg):
            a = ex.aggregation
            execs.append(Aggregation(
                [expr_from_pb(g) for g in a.group_by],
                [agg_from_pb(f) for f in a.agg_func],
                streamed=(t == tp.ExecType.TypeStreamAgg),
            ))
        elif t == tp.ExecType.TypeTopN:
            n = ex.top_n
            execs.append(TopN([(expr_from_pb(b.expr), bool(b.desc)) for b in n.order_by],
                              n.limit))
        elif t == tp.ExecType.TypeLimit:
            execs.append(Limit(ex.limit.limit))
        else:
            raise TipbError(f"unsupported ExecType {t}")
    offsets = list(pb.output_offsets) or None
    return DagRequest(executors=execs, output_offsets=offsets)


def decode_dag_request(data: bytes) -> tuple[DagRequest, tp.DAGRequest]:
    pb = tp.DAGRequest.decode(data)
    return dag_from_pb(pb), pb


# ---------------------------------------------------------------------------
# responses
# ---------------------------------------------------------------------------

def _reencode_rows_data(chunk: bytes) -> bytes:
    """Internal chunk (ncols-prefixed rows of datums) -> reference rows_data.

    Datums are copied verbatim except decimals, whose internal compact form
    (flag 6, frac u8, i64 scaled) becomes the reference's
    flag+prec+frac+write_bin form (codec/datum.rs).
    """
    out = bytearray()
    off = 0
    n = len(chunk)
    while off < n:
        ncols, off = codec.decode_var_u64(chunk, off)
        for _ in range(ncols):
            start = off
            d, off = datum_mod.decode_datum(chunk, start)
            if d.flag == datum_mod.DECIMAL_FLAG:
                scaled, frac = d.value
                dec = MyDecimal(scaled, frac)
                prec = max(dec.precision, frac + 1)
                out.append(datum_mod.DECIMAL_FLAG)
                out.append(prec)
                out.append(frac)
                out += dec.encode_bin(prec, frac)
            else:
                out += chunk[start:off]
    return bytes(out)


def _chunk_columns(chunk: bytes, field_types: list[FieldType]) -> bytes:
    """Internal chunk -> TypeChunk column block."""
    cols = [ChunkColumn(ft) for ft in field_types]
    # accumulate per column, then one bulk ``extend`` each: fixed-width
    # numeric columns append in a single numpy pass instead of a
    # struct.pack per row (byte-identical either way)
    vals: list[list] = [[] for _ in field_types]
    off = 0
    n = len(chunk)
    while off < n:
        ncols, off = codec.decode_var_u64(chunk, off)
        if ncols != len(field_types):
            raise TipbError(f"row has {ncols} cols, schema has {len(field_types)}")
        for vl in vals:
            d, off = datum_mod.decode_datum(chunk, off)
            vl.append(d.value if d.flag != datum_mod.NIL_FLAG else None)
    for c, vl in zip(cols, vals):
        c.extend(vl)
    return encode_chunk(cols)


def encode_select_response(
    resp: SelectResponse,
    encode_type: int = tp.EncodeType.TypeDefault,
    field_types: list[FieldType] | None = None,
    output_counts: list[int] | None = None,
) -> bytes:
    """Internal SelectResponse -> protobuf tipb.SelectResponse bytes."""
    pb = tp.SelectResponse()
    if encode_type == tp.EncodeType.TypeChunk:
        if field_types is None:
            raise TipbError("TypeChunk needs the output schema's field types")
        pb.chunks = [tp.ChunkPb(rows_data=_chunk_columns(c, field_types))
                     for c in resp.chunks]
    else:
        pb.chunks = [tp.ChunkPb(rows_data=_reencode_rows_data(c))
                     for c in resp.chunks]
    pb.encode_type = encode_type
    if resp.warnings:
        pb.warnings = [tp.ErrorPb(code=1105, msg=w) for w in resp.warnings]
        pb.warning_count = len(resp.warnings)
    if output_counts:
        pb.output_counts = list(output_counts)
    if resp.exec_summaries:
        pb.execution_summaries = [
            tp.ExecutorExecutionSummary(
                num_produced_rows=s.num_produced_rows,
                num_iterations=s.num_iterations,
            )
            for s in resp.exec_summaries
        ]
    return pb.encode()


def internal_response_to_tipb(data: bytes, encode_type: int = tp.EncodeType.TypeDefault,
                              field_types: list[FieldType] | None = None) -> bytes:
    """Re-frame an internal SelectResponse.encode() payload as tipb bytes.

    The internal framing is var_u64 chunk count, then len-prefixed chunks,
    then len-prefixed warning strings (dag.py SelectResponse.encode)."""
    from .dag import SelectResponse as InternalResp

    off = 0
    nchunks, off = codec.decode_var_u64(data, off)
    chunks = []
    for _ in range(nchunks):
        ln, off = codec.decode_var_u64(data, off)
        chunks.append(data[off:off + ln])
        off += ln
    warnings = []
    if off < len(data):
        nw, off = codec.decode_var_u64(data, off)
        for _ in range(nw):
            ln, off = codec.decode_var_u64(data, off)
            warnings.append(data[off:off + ln].decode())
            off += ln
    resp = InternalResp(chunks=chunks, warnings=warnings)
    return encode_select_response(resp, encode_type, field_types)


def decode_ref_datum(buf: bytes, off: int = 0):
    """Decode one reference-format datum (codec/datum.rs) — like the internal
    decoder except decimals carry prec+frac+write_bin payloads."""
    flag = buf[off]
    if flag == datum_mod.DECIMAL_FLAG:
        prec, frac = buf[off + 1], buf[off + 2]
        d, used = MyDecimal.decode_bin(buf[off + 3:], prec, frac)
        scaled, dfrac = d.to_i64_scaled()
        return datum_mod.Datum(flag, (scaled, dfrac)), off + 3 + used
    return datum_mod.decode_datum(buf, off)


def error_response(msg: str, code: int = 1105) -> bytes:
    pb = tp.SelectResponse(error=tp.ErrorPb(code=code, msg=msg))
    return pb.encode()
