"""Device-resident per-region column cache with incremental delta apply.

The coprocessor's existing block cache (``cache.py``) is keyed by
``(region, ranges, start_ts, data version)`` — ANY write produces a new key
and the whole region re-decodes from KV bytes.  That leaves scan/selection
DAGs (cost-dominated by rowv2 decode + MVCC resolution) on the 1.0× floor:
the device never helps because every request rebuilds the columns on host.

This module keeps ONE decoded image per ``(region, ranges, schema)``, keyed
for freshness by ``(region_epoch, apply_index)`` — the TCR/Taurus near-data
shape: base data stays resident in the accelerator-friendly format and only
deltas move.

* build: vectorized MVCC range resolve (``MvccBatchScanSource``) + the
  NumPy-batched row decoder materialize the region's visible rows into
  fixed-width column blocks; the evaluators pin them on device on first use.
* hit: same ``apply_index`` ⇒ the engine cannot have changed; serve the
  resident blocks as-is (zero scan, zero decode, zero transfer).
* delta: a newer ``apply_index`` (or a later ``start_ts`` while future
  versions exist) triggers ``mvcc_batch.scan_delta``: one vectorized pass
  over the CF_WRITE *keys* finds rows whose version fingerprint moved; only
  those rows re-resolve and re-decode.  Pure in-place updates patch the
  pinned device arrays with ``.at[].set`` scatters; inserts/deletes repack
  the host blocks (still no KV decode) and drop the pins to rebuild lazily.
* fallback: a read below the image's snapshot ts, a non-vectorizable range,
  or an over-budget region serves through the existing per-request path —
  the cache only ever degrades to current behavior.

Invalidation: ``raft/store.py`` calls :func:`notify_region_epoch_change` on
split / merge / conf change; the epoch in the key catches anything missed.
Memory: LRU over images + a byte budget bound host AND device residency (a
device pin costs about one host copy per pinned plan signature).

Concurrency: cache resolution (lookup / build / delta apply) serializes
under the manager lock, but the evaluator reads the image's blocks after
``serve`` returns — a delta applying concurrently with another request's
read of the SAME image could tear that read.  Deltas only arrive with a
newer ``apply_index``, so this needs a reader still in flight when the next
raft apply's read lands; endpoints that serve a region from multiple
threads should serialize per region (the raft apply path itself already
is).  The wire paths currently pass no ``apply_index``, making the cache
opt-in per deployment.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from ..storage.engine import CF_LOCK
from ..storage.mvcc import Statistics
from ..storage.mvcc.reader import _check_lock
from ..storage.txn_types import Key
from .cache import ColumnBlockCache
from .datatypes import Column, EvalType
from .mvcc_batch import MvccBatchScanSource, scan_delta
from .table import RowBatchDecoder, decode_record_handles

DEFAULT_BYTE_BUDGET = 256 << 20
DEFAULT_MAX_REGIONS = 64
_REBUILD_FRACTION = 0.25  # delta bigger than this fraction of rows ⇒ rebuild

_CACHES: "weakref.WeakSet[RegionColumnCache]" = weakref.WeakSet()


def notify_region_epoch_change(region_id: int, reason: str = "epoch") -> None:
    """Raft-side invalidation hook: a region's epoch moved (split / merge /
    conf change) — every live cache drops its images of that region."""
    for c in list(_CACHES):
        c.invalidate_region(region_id, reason=reason)


def _epoch_of(ctx_epoch) -> tuple[int, int] | None:
    if ctx_epoch is None:
        return None
    if isinstance(ctx_epoch, (tuple, list)) and len(ctx_epoch) == 2:
        return (int(ctx_epoch[0]), int(ctx_epoch[1]))
    conf_ver = getattr(ctx_epoch, "conf_ver", None)
    version = getattr(ctx_epoch, "version", None)
    if conf_ver is None or version is None:
        return None
    return (int(conf_ver), int(version))


def schema_sig(columns_info) -> tuple:
    return tuple(
        (
            c.col_id,
            c.ftype.eval_type,
            c.ftype.decimal,
            c.ftype.flag,
            bool(c.ftype.is_unsigned),
            bool(c.is_pk_handle),
            c.default_value,
        )
        for c in columns_info
    )


class RegionImage:
    """One region's decoded, device-pinnable columnar state."""

    def __init__(self, key, epoch, schema, block_rows: int):
        self.key = key
        self.epoch = epoch
        self.schema = schema
        self.block_rows = block_rows
        self.apply_index = -1
        self.snapshot_ts = -1
        self.max_commit_ts = 0
        self.handles = np.empty(0, dtype=np.int64)
        self.row_commit_ts = np.empty(0, dtype=np.int64)
        self.block_cache = ColumnBlockCache(key=key)
        self.decoder = RowBatchDecoder(schema)
        self.nbytes = 0
        # bytes->code maps for dict-encoded columns, built on first delta
        self._dict_maps: dict[int, dict] = {}

    @property
    def n_rows(self) -> int:
        return len(self.handles)

    def _offsets(self) -> np.ndarray:
        nv = np.array([b.n_valid for b in self.block_cache.blocks], dtype=np.int64)
        return np.concatenate([[0], np.cumsum(nv)])

    def _recount(self) -> None:
        self.nbytes = (
            self.block_cache.nbytes() + self.handles.nbytes + self.row_commit_ts.nbytes
        )

    # -- build -------------------------------------------------------------

    def fill(self, handles: np.ndarray, values: list[bytes], cts: np.ndarray,
             max_commit_ts: int, apply_index: int, start_ts: int) -> None:
        self.handles = handles
        self.row_commit_ts = cts
        cache = self.block_cache
        cache.blocks.clear()
        br = self.block_rows
        for s in range(0, len(values), br):
            e = min(s + br, len(values))
            cols = self.decoder.decode(handles[s:e], values[s:e])
            cache.add(cols, e - s)
        cache.filled = True
        self.apply_index = apply_index
        self.snapshot_ts = start_ts
        self.max_commit_ts = max_commit_ts
        self._recount()

    # -- delta -------------------------------------------------------------

    def apply_delta(self, delta: dict, apply_index: int, start_ts: int) -> int:
        """Apply a ``mvcc_batch.scan_delta`` result; returns rows touched."""
        ch = delta["changed_handles"]
        dh = delta["deleted_handles"]
        n_touched = len(ch) + len(dh)
        if n_touched:
            pos = np.searchsorted(self.handles, ch)
            pos_c = np.minimum(pos, max(self.n_rows - 1, 0))
            in_place = (
                len(dh) == 0
                and self.n_rows > 0
                and bool((self.handles[pos_c] == ch).all())
            )
            cols = (
                self.decoder.decode(ch, delta["changed_values"]) if len(ch) else None
            )
            if in_place:
                self._apply_updates(pos, cols, ch, delta["changed_commit_ts"])
            else:
                self._apply_structural(ch, cols, delta["changed_commit_ts"], dh)
        self.apply_index = apply_index
        self.snapshot_ts = start_ts
        self.max_commit_ts = delta["max_commit_ts"]
        self._recount()
        return n_touched

    def _code_of(self, ci: int, blocks, value: bytes) -> int:
        """Image dictionary code for ``value`` on column ``ci``, appending a
        new entry (shared across every block) when unseen."""
        dmap = self._dict_maps.get(ci)
        dictionary = blocks[0].cols[ci].dictionary
        if dmap is None:
            dmap = self._dict_maps[ci] = {bytes(v): j for j, v in enumerate(dictionary)}
        code = dmap.get(value)
        if code is None:
            code = len(dmap)
            dmap[value] = code
            grown = np.empty(code + 1, dtype=object)
            grown[:code] = dictionary
            grown[code] = value
            for b in blocks:
                b.cols[ci].dictionary = grown
        return code

    def _delta_cell(self, ci: int, blocks, col: Column, r: int):
        """(value, is_null) of delta row ``r`` in the image's representation."""
        nl = bool(np.asarray(col.nulls)[r])
        image_col = blocks[0].cols[ci] if blocks else None
        dict_encoded = image_col is not None and image_col.is_dict_encoded
        obj_col = (
            image_col.data.dtype == object
            if image_col is not None and isinstance(image_col.data, np.ndarray)
            else self.schema[ci].ftype.eval_type in (EvalType.BYTES, EvalType.JSON)
            and not dict_encoded
        )
        if nl:
            return (b"" if obj_col and not dict_encoded else 0), True
        v = col.decoded().data[r] if col.is_dict_encoded else col.data[r]
        if dict_encoded:
            return self._code_of(ci, blocks, bytes(v)), False
        return v, False

    def _apply_updates(self, pos: np.ndarray, cols, ch: np.ndarray, cts: np.ndarray) -> None:
        """In-place row updates: mutate host arrays, scatter device pins."""
        blocks = self.block_cache.blocks
        offsets = self._offsets()
        bi_arr = np.searchsorted(offsets, pos, side="right") - 1
        updates: dict[int, tuple[np.ndarray, dict]] = {}
        for bi in np.unique(bi_arr):
            sel = np.flatnonzero(bi_arr == bi)
            rows = (pos[sel] - offsets[bi]).astype(np.int64)
            per_col: dict[int, tuple[np.ndarray, np.ndarray]] = {}
            for ci, col in enumerate(cols):
                if self.schema[ci].is_pk_handle:
                    continue  # handles are the row identity — never change
                image_col = blocks[int(bi)].cols[ci]
                vals = np.empty(len(sel), dtype=np.asarray(image_col.data).dtype)
                nls = np.zeros(len(sel), dtype=bool)
                for j, si in enumerate(sel):
                    v, nl = self._delta_cell(ci, blocks, col, int(si))
                    vals[j] = v
                    nls[j] = nl
                image_col.data[rows] = vals
                image_col.nulls[rows] = nls
                per_col[ci] = (vals, nls)
            updates[int(bi)] = (rows, per_col)
        self.row_commit_ts[pos] = cts
        self.block_cache.scatter_update(updates)

    def _apply_structural(self, ch: np.ndarray, cols, cts: np.ndarray, dh: np.ndarray) -> None:
        """Inserts and/or deletes: repack host blocks from the resident
        columns (no KV decode) and drop device pins to rebuild lazily."""
        blocks = self.block_cache.blocks
        n_old = self.n_rows
        # global view of each column, preserving dictionary codes
        gdata, gnulls = [], []
        for ci in range(len(self.schema)):
            if blocks:
                gdata.append(np.concatenate([np.asarray(b.cols[ci].data) for b in blocks]))
                gnulls.append(np.concatenate([np.asarray(b.cols[ci].nulls) for b in blocks]))
            else:
                et = self.schema[ci].ftype.eval_type
                dtype = (
                    object if et in (EvalType.BYTES, EvalType.JSON)
                    else np.float64 if et == EvalType.REAL
                    else np.int64
                )
                gdata.append(np.empty(0, dtype=dtype))
                gnulls.append(np.empty(0, dtype=bool))
        handles = self.handles
        row_cts = self.row_commit_ts
        if len(dh) and n_old:
            keep = np.ones(n_old, dtype=bool)
            dpos = np.searchsorted(handles, dh)
            ok = dpos < n_old
            ok &= handles[np.minimum(dpos, n_old - 1)] == dh
            keep[dpos[ok]] = False
            sel = np.flatnonzero(keep)
            handles = handles[sel]
            row_cts = row_cts[sel]
            gdata = [d[sel] for d in gdata]
            gnulls = [nl[sel] for nl in gnulls]
        if len(ch):
            # split changed rows into updates of surviving rows vs inserts
            pos = np.searchsorted(handles, ch)
            pos_c = np.minimum(pos, max(len(handles) - 1, 0))
            is_upd = (len(handles) > 0) & (handles[pos_c] == ch) if len(handles) else (
                np.zeros(len(ch), dtype=bool)
            )
            new_vals: list[list] = [[] for _ in self.schema]
            new_nulls: list[list] = [[] for _ in self.schema]
            for r in range(len(ch)):
                for ci, col in enumerate(cols):
                    if self.schema[ci].is_pk_handle:
                        v, nl = int(ch[r]), False
                    else:
                        v, nl = self._delta_cell(ci, blocks, col, r)
                    new_vals[ci].append(v)
                    new_nulls[ci].append(nl)
            upd_idx = np.flatnonzero(np.asarray(is_upd))
            for ci in range(len(self.schema)):
                if len(upd_idx) and not self.schema[ci].is_pk_handle:
                    gdata[ci][pos_c[upd_idx]] = np.array(
                        [new_vals[ci][int(i)] for i in upd_idx], dtype=gdata[ci].dtype
                    )
                    gnulls[ci][pos_c[upd_idx]] = np.array(
                        [new_nulls[ci][int(i)] for i in upd_idx], dtype=bool
                    )
            if len(upd_idx):
                row_cts = row_cts.copy()
                row_cts[pos_c[upd_idx]] = cts[upd_idx]
            ins_idx = np.flatnonzero(~np.asarray(is_upd))
            if len(ins_idx):
                ins_h = ch[ins_idx]
                ins_at = np.searchsorted(handles, ins_h)
                handles = np.insert(handles, ins_at, ins_h)
                row_cts = np.insert(row_cts, ins_at, cts[ins_idx])
                for ci in range(len(self.schema)):
                    ivals = np.array(
                        [new_vals[ci][int(i)] for i in ins_idx], dtype=gdata[ci].dtype
                    )
                    gdata[ci] = np.insert(gdata[ci], ins_at, ivals)
                    gnulls[ci] = np.insert(
                        gnulls[ci], ins_at, np.array([new_nulls[ci][int(i)] for i in ins_idx], dtype=bool)
                    )
        self.handles = handles
        self.row_commit_ts = row_cts
        # re-chunk into blocks (views over the global arrays) and drop pins
        templates = [blocks[0].cols[ci] if blocks else None for ci in range(len(self.schema))]
        self.block_cache.blocks.clear()
        br = self.block_rows
        n = len(handles)
        for s in range(0, n, br):
            e = min(s + br, n)
            bcols = []
            for ci in range(len(self.schema)):
                t = templates[ci]
                bcols.append(Column(
                    t.eval_type if t is not None else self.schema[ci].ftype.eval_type,
                    gdata[ci][s:e],
                    gnulls[ci][s:e],
                    t.frac if t is not None else self.schema[ci].ftype.decimal,
                    t.dictionary if t is not None else None,
                ))
            self.block_cache.add(bcols, e - s)
        self.block_cache.filled = True
        self.block_cache.drop_device()


class RegionCacheStats:
    __slots__ = ("hits", "misses", "deltas", "delta_rows", "stale", "uncacheable",
                 "evictions", "invalidations", "bytes_pinned")

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.deltas = 0
        self.delta_rows = 0
        self.stale = 0
        self.uncacheable = 0
        self.evictions = 0
        self.invalidations = 0
        self.bytes_pinned = 0

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class RegionColumnCache:
    """LRU of :class:`RegionImage` under a byte budget.

    **Sharded mode** (``mesh`` with >1 device): every image is assigned an
    OWNER device under a per-device byte budget — the whole image on the
    least-loaded device normally, block-level round-robin for a single huge
    region (one region bigger than a device's budget share).  The placement
    is written onto each image's block cache as ``owner_devices`` (device id
    per block); the mesh-sharded warm launcher
    (``parallel.mesh.launch_xregion_sharded``) pins the slab stacks there, so
    a cross-region batch runs with zero re-sharding — each device already
    holds its shard.  Eviction/invalidation rebalances: images migrate from
    the most- to the least-loaded device (pins rebuild lazily on the new
    owner)."""

    def __init__(
        self,
        byte_budget: int = DEFAULT_BYTE_BUDGET,
        max_regions: int = DEFAULT_MAX_REGIONS,
        block_rows: int | None = None,
        mesh=None,
        per_device_budget: int | None = None,
    ):
        from .jax_eval import DEFAULT_BLOCK_ROWS

        self.byte_budget = byte_budget
        self.max_regions = max_regions
        self.block_rows = block_rows or DEFAULT_BLOCK_ROWS
        self._images: dict = {}  # key -> RegionImage, insertion = LRU order
        self._mu = threading.RLock()
        self.stats = RegionCacheStats()
        self.devices: list = []
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            try:
                devs = list(np.asarray(mesh.devices).reshape(-1))
            except Exception:  # noqa: BLE001 — a fake/broken mesh: unsharded
                devs = []
            if len(devs) > 1:
                self.devices = devs
        self.per_device_budget = (
            per_device_budget
            if per_device_budget is not None
            else byte_budget // max(len(self.devices), 1)
        )
        self._device_bytes: dict[int, int] = {d.id: 0 for d in self.devices}
        _CACHES.add(self)

    @property
    def sharded(self) -> bool:
        return bool(self.devices)

    # -- public ------------------------------------------------------------

    def serve(self, snap, context: dict, columns_info, ranges, start_ts: int,
              statistics: Statistics | None = None):
        """Resolve a request against the cache.

        Returns ``(block_cache | None, outcome, delta_rows)``; a None block
        cache means "serve through the normal path" (outcome says why)."""
        region_id = (context or {}).get("region_id")
        epoch = _epoch_of((context or {}).get("region_epoch"))
        apply_index = (context or {}).get("apply_index")
        if region_id is None or epoch is None or apply_index is None:
            return None, "off", 0
        key = (region_id, tuple(ranges), schema_sig(columns_info))
        stats = statistics or Statistics()
        with self._mu:
            img = self._images.get(key)
            if img is not None and img.epoch != epoch:
                self._drop(key, reason="epoch")
                img = None
            if img is not None:
                # LRU touch
                self._images.pop(key)
                self._images[key] = img
        if img is None:
            # build OUTSIDE the manager lock: a cold build of a large region
            # (full MVCC resolve + decode) must not stall hits on warm
            # regions.  A concurrent build of the same key wastes one build;
            # the insert below keeps whichever image is newest.
            return self._build(key, epoch, snap, columns_info, ranges,
                               start_ts, apply_index, stats)
        with self._mu:
            if self._images.get(key) is not img or img.epoch != epoch:
                # raced with an invalidation between lookup and here
                self.stats.uncacheable += 1
                self._count("uncacheable")
                return None, "uncacheable", 0
            if start_ts < img.snapshot_ts:
                self.stats.stale += 1
                self._count("stale")
                return None, "stale", 0
            fresh = apply_index == img.apply_index and (
                start_ts == img.snapshot_ts or img.max_commit_ts <= img.snapshot_ts
            )
            if fresh:
                if start_ts > img.snapshot_ts:
                    self._check_locks(snap, ranges, start_ts, stats)
                    img.snapshot_ts = start_ts
                self.stats.hits += 1
                self._count("hit")
                return img.block_cache, "hit", 0
            delta = scan_delta(snap, start_ts, ranges, img.handles,
                               img.row_commit_ts, statistics=stats)
            if delta is None:
                self.stats.uncacheable += 1
                self._count("uncacheable")
                self._drop(key, reason="unvectorizable")
                return None, "uncacheable", 0
            n_touch = len(delta["changed_handles"]) + len(delta["deleted_handles"])
            if img.n_rows and n_touch > _REBUILD_FRACTION * img.n_rows:
                self._drop(key, reason="delta_too_big")
                return self._build(key, epoch, snap, columns_info, ranges,
                                   start_ts, apply_index, stats)
            n = img.apply_delta(delta, apply_index, start_ts)
            if self.devices:
                # a structural repack can change the block count and bytes:
                # refresh the placement so owner_devices stays block-aligned
                self._unplace(img)
                self._place(img)
            self.stats.deltas += 1
            self.stats.delta_rows += n
            self._count("delta")
            self._count_delta_rows(n)
            self._enforce_budget(keep=key)
            self._gauge_bytes()
            return img.block_cache, "delta", n

    def invalidate_region(self, region_id: int, reason: str = "epoch") -> None:
        with self._mu:
            for key in [k for k in self._images if k[0] == region_id]:
                self._drop(key, reason=reason)
            self._rebalance()

    def total_bytes(self) -> int:
        with self._mu:
            return sum(img.nbytes for img in self._images.values())

    def placement(self) -> dict[int, int]:
        """{device_id: pinned bytes} placement metadata (sharded mode)."""
        with self._mu:
            return dict(self._device_bytes)

    def resident_block_caches(self) -> list:
        """The resident images' block caches (benches / introspection —
        feed to ``parallel.mesh.slab_assignment`` for the slab geometry)."""
        with self._mu:
            return [img.block_cache for img in self._images.values()]

    def __len__(self) -> int:
        return len(self._images)

    # -- sharded placement ---------------------------------------------------

    def _place(self, img) -> None:
        """Assign owner devices to a freshly built/repacked image: whole
        image to the least-loaded device, block-level round-robin when the
        image alone exceeds the per-device budget (a single huge region must
        spread, or one chip serves it while the rest idle)."""
        if not self.devices:
            return
        bc = img.block_cache
        n_blocks = len(bc.blocks)
        if n_blocks == 0:
            bc.owner_devices = []
            img.placement_bytes = {}
            return
        per_block = img.nbytes // n_blocks
        if img.nbytes > self.per_device_budget and n_blocks > 1:
            order = sorted(self.devices, key=lambda d: self._device_bytes[d.id])
            owners = [order[b % len(order)].id for b in range(n_blocks)]
        else:
            dev = min(self.devices, key=lambda d: self._device_bytes[d.id])
            owners = [dev.id] * n_blocks
        bc.owner_devices = owners
        pb: dict[int, int] = {}
        for did in owners:
            pb[did] = pb.get(did, 0) + per_block
        img.placement_bytes = pb
        for did, b in pb.items():
            self._device_bytes[did] += b

    def _unplace(self, img) -> None:
        for did, b in getattr(img, "placement_bytes", {}).items():
            self._device_bytes[did] = max(0, self._device_bytes.get(did, 0) - b)
        img.placement_bytes = {}
        img.block_cache.owner_devices = None

    def _rebalance(self) -> None:
        """Shrink the device-load spread after an eviction/invalidation:
        move the best-fitting whole image from the most- to the least-loaded
        device while that strictly narrows the gap.  Only the placement
        metadata moves — device pins drop and rebuild lazily on the new
        owner at the next warm batch."""
        if not self.devices or len(self._images) < 2:
            return
        for _ in range(len(self._images)):
            hi = max(self.devices, key=lambda d: self._device_bytes[d.id])
            lo = min(self.devices, key=lambda d: self._device_bytes[d.id])
            gap = self._device_bytes[hi.id] - self._device_bytes[lo.id]
            if gap <= 0:
                return
            cand = [
                i for i in self._images.values()
                if set(getattr(i, "placement_bytes", {})) == {hi.id}
                and 0 < i.nbytes < gap
            ]
            if not cand:
                return
            img = min(cand, key=lambda i: abs(gap - 2 * i.nbytes))
            self._unplace(img)
            img.block_cache.drop_device()
            img.block_cache.owner_devices = [lo.id] * len(img.block_cache.blocks)
            img.placement_bytes = {lo.id: img.nbytes}
            self._device_bytes[lo.id] += img.nbytes
            # the migration moved placement bytes AFTER the drop path's
            # last refresh — keep the per-device gauge truthful
            self._gauge_bytes()
        return

    # -- internals ---------------------------------------------------------

    def _build(self, key, epoch, snap, columns_info, ranges, start_ts,
               apply_index, stats):
        """Build an image for ``key`` (expensive part lock-free) and insert
        it.  Safe to call with or without the manager lock held (the lock is
        reentrant); a racing build of the same key keeps whichever image
        reflects the newer apply index — this request serves its own blocks
        either way."""
        src = MvccBatchScanSource(snap, start_ts, ranges, statistics=stats,
                                  record_versions=True)
        keys, values = src._resolve_all()
        if not src.versions_exact:
            self.stats.uncacheable += 1
            self._count("uncacheable")
            return None, "uncacheable", 0
        handles = decode_record_handles(keys)
        if len(handles) > 1 and not (handles[1:] > handles[:-1]).all():
            self.stats.uncacheable += 1
            self._count("uncacheable")
            return None, "uncacheable", 0
        img = RegionImage(key, epoch, list(columns_info), self.block_rows)
        img.fill(handles, values, src.row_commit_ts, src.max_commit_ts,
                 apply_index, start_ts)
        if img.nbytes > self.byte_budget:
            self.stats.uncacheable += 1
            self._count("too_big")
            # serve this request from the just-built blocks, but don't keep
            # them resident — the budget is the OOM guard
            return img.block_cache, "too_big", 0
        with self._mu:
            existing = self._images.get(key)
            if (existing is None or existing.epoch != epoch
                    or existing.apply_index <= apply_index):
                if existing is not None:
                    self._unplace(existing)
                self._images[key] = img
                self._place(img)
                self._enforce_budget(keep=key)
            self.stats.misses += 1
            self._count("miss")
            self._gauge_bytes()
        return img.block_cache, "miss", 0

    def _check_locks(self, snap, ranges, ts, stats) -> None:
        for start, end in ranges:
            enc_start = Key.from_raw(start).encoded
            enc_end = Key.from_raw(end).encoded
            for k, v in snap.scan_cf(CF_LOCK, enc_start, enc_end):
                stats.lock.next += 1
                _check_lock(v, Key.from_encoded(k).to_raw(), ts, frozenset())

    def _drop(self, key, reason: str) -> None:
        img = self._images.pop(key, None)
        if img is None:
            return
        self._unplace(img)
        img.block_cache.drop_device()
        img.block_cache.blocks.clear()
        img.block_cache.filled = False
        self.stats.invalidations += 1
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_invalidate_total",
            "Region column cache invalidations, by reason",
        ).inc(reason=reason)
        self._gauge_bytes()

    def _enforce_budget(self, keep) -> None:
        while len(self._images) > self.max_regions or (
            sum(i.nbytes for i in self._images.values()) > self.byte_budget
            and len(self._images) > 1
        ):
            victim = next((k for k in self._images if k != keep), None)
            if victim is None:
                break
            img = self._images.pop(victim)
            self._unplace(img)
            img.block_cache.drop_device()
            img.block_cache.blocks.clear()
            img.block_cache.filled = False
            self.stats.evictions += 1
            from ..util.metrics import REGISTRY

            REGISTRY.counter(
                "tikv_coprocessor_region_cache_evict_total",
                "Region column cache LRU/budget evictions",
            ).inc()
        self._rebalance()

    def _count(self, outcome: str) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_total",
            "Region column cache lookups, by outcome",
        ).inc(outcome=outcome)

    def _count_delta_rows(self, n: int) -> None:
        if not n:
            return
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_delta_rows_total",
            "Rows re-decoded by incremental delta applies",
        ).inc(n)

    def _gauge_bytes(self) -> None:
        total = sum(i.nbytes for i in self._images.values())
        self.stats.bytes_pinned = total
        from ..util.metrics import REGISTRY

        REGISTRY.gauge(
            "tikv_coprocessor_region_cache_bytes",
            "Host bytes held by resident region images",
        ).set(total)
        if self.devices:
            g = REGISTRY.gauge(
                "tikv_coprocessor_region_cache_device_bytes",
                "Bytes pinned per owner device (sharded placement)",
            )
            for d in self.devices:
                g.set(self._device_bytes.get(d.id, 0), device=str(d.id))
