"""Device-resident per-region column cache with incremental delta apply.

The coprocessor's existing block cache (``cache.py``) is keyed by
``(region, ranges, start_ts, data version)`` — ANY write produces a new key
and the whole region re-decodes from KV bytes.  That leaves scan/selection
DAGs (cost-dominated by rowv2 decode + MVCC resolution) on the 1.0× floor:
the device never helps because every request rebuilds the columns on host.

This module keeps ONE decoded image per ``(region, ranges, schema)``, keyed
for freshness by ``(region_epoch, apply_index)`` — the TCR/Taurus near-data
shape: base data stays resident in the accelerator-friendly format and only
deltas move.

* build: vectorized MVCC range resolve (``MvccBatchScanSource``) + the
  NumPy-batched row decoder materialize the region's visible rows into
  fixed-width column blocks; the evaluators pin them on device on first use.
* hit: same ``apply_index`` ⇒ the engine cannot have changed; serve the
  resident blocks as-is (zero scan, zero decode, zero transfer).
* delta: a newer ``apply_index`` (or a later ``start_ts`` while future
  versions exist) triggers ``mvcc_batch.scan_delta``: one vectorized pass
  over the CF_WRITE *keys* finds rows whose version fingerprint moved; only
  those rows re-resolve and re-decode.  Pure in-place updates patch the
  pinned device arrays with ``.at[].set`` scatters; inserts/deletes repack
  the host blocks (still no KV decode) and drop the pins to rebuild lazily.
* fallback: a read below the image's snapshot ts, a non-vectorizable range,
  or an over-budget region serves through the existing per-request path —
  the cache only ever degrades to current behavior.

Follower stale serving (docs/stale_reads.md): images built off STALE-read
snapshots need no special handling — a stale snapshot's ``apply_index`` is
guaranteed at/above the RegionReadProgress pair's required index and its
reads sit at/below the paired watermark (``raftkv`` refuses otherwise, and
``endpoint._region_cache_for`` asserts the pairing), so the
``(region_id, epoch, apply_index)`` key already identifies exactly the data
version the watermark covers.  Leader and follower images of one region
therefore never alias to different bytes under one key.

Invalidation: ``raft/store.py`` calls :func:`notify_region_epoch_change` on
split / merge / conf change; the epoch in the key catches anything missed.
Memory: LRU over images + a byte budget bound host AND device residency (a
device pin costs about one host copy per pinned plan signature).

Write-through deltas: the raft apply path
(``raft/store.py`` ``_apply_run`` / ``_exec_data_cmd``) calls
:func:`notify_region_write` with every committed data batch's ops and the
entry's apply index.  The parsed delta (changed handles/values/commit_ts,
deleted handles, lock touches) is buffered on the image as a PENDING delta;
the next warm read folds it in under the manager lock and serves WITHOUT
any CF_WRITE scan — ``scan_delta`` stays as the fallback whenever emission
is off (``apply_emit_write_delta`` failpoint, config), an op is not
vectorizable, or the pending chain has a gap (detected via the per-region
notify watermark; see docs/write_path.md for the contract).

Concurrency: cache resolution (lookup / build / delta apply) serializes
under the manager lock, but the evaluator reads the image's blocks after
``serve`` returns — a delta applying concurrently with another request's
read of the SAME image could tear that read.  Deltas mutate blocks only on
the serve path (write-through emission merely buffers pending rows), so
this needs a reader still in flight when a LATER read's fold-in lands;
endpoints that serve a region from multiple threads should serialize per
region.  ``apply_index`` is propagated end-to-end: ``RegionSnapshot``
carries the peer's applied index, and the endpoint reads region identity,
epoch and apply index straight off the snapshot — raft-backed deployments
need no context plumbing (explicit context still wins for tests and
embedded engines).
"""

from __future__ import annotations

import time
import weakref

import numpy as np

from ..analysis import bufsan as _bufsan
from ..analysis.sanitizer import make_rlock
from ..storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE
from ..storage.mvcc import Statistics
from ..storage.mvcc.reader import _check_lock
from ..storage.txn_types import Key, Write, WriteType, append_ts, split_ts
from . import encoding as _encoding
from . import integrity as _integrity
from .cache import ColumnBlockCache
from .datatypes import Column, EvalType
from .mvcc_batch import MvccBatchScanSource, scan_delta
from .table import RowBatchDecoder, decode_record_handles, decode_record_key, record_key

DEFAULT_BYTE_BUDGET = 256 << 20
DEFAULT_MAX_REGIONS = 64
_REBUILD_FRACTION = 0.25  # delta bigger than this fraction of rows ⇒ rebuild
_TOKEN_UNSET = object()  # cache not yet bound to an engine's data_token

_CACHES: "weakref.WeakSet[RegionColumnCache]" = weakref.WeakSet()


def notify_region_epoch_change(region_id: int, reason: str = "epoch") -> None:
    """Raft-side invalidation hook: a region's epoch moved (split / merge /
    conf change) — every live cache drops its images of that region."""
    for c in list(_CACHES):
        c.invalidate_region(region_id, reason=reason)


def notify_region_write(region_id: int, ops, apply_index: int,
                        get_default=None, token=None) -> None:
    """Write-through hook: a committed data batch applied to ``region_id``
    at ``apply_index``.  ``ops`` are the batch's ``(op, cf, key, val)``
    tuples in MVCC key space (pre data-prefix); ``get_default`` resolves a
    ``CF_DEFAULT`` key for PUT records whose value is not inline;
    ``token`` identifies the emitting engine (region ids are not
    process-unique — each cache only accepts deltas from the engine it
    serves).  Interested caches buffer the parsed delta on their images of
    the region; warm reads fold it in without re-scanning CF_WRITE.  The
    parse (which may read CF_DEFAULT) runs at most ONCE per notify and
    outside every cache lock."""
    memo: list = []

    def parse_once():
        if not memo:
            memo.append(_parse_write_ops(ops, get_default))
        return memo[0]

    for c in list(_CACHES):
        c.apply_write(region_id, parse_once, apply_index, token=token)


def notify_region_write_lost(region_id: int, apply_index: int,
                             token=None) -> None:
    """Write-through hook for a data change of UNKNOWN content (emission
    disabled, snapshot apply, merge catch-up): pending deltas are dropped
    and the notify watermark advances, so reads fall back to ``scan_delta``
    until a read's snapshot catches up past ``apply_index``."""
    for c in list(_CACHES):
        c.note_write_lost(region_id, apply_index, token=token)


def _parse_write_ops(ops, get_default):
    """Parse a committed batch's ops into ``(writes, lock_keys)`` —
    ``writes`` = [(raw_key, commit_ts, value | None-for-delete)] in batch
    order, ``lock_keys`` = raw keys whose CF_LOCK state changed.  Returns
    None when any CF_WRITE op is not expressible as an incremental row
    change (delete/delete_range on CF_WRITE, exotic records, a missing
    CF_DEFAULT value) — the caller then degrades to the scan_delta path."""
    writes: list[tuple[bytes, int, bytes | None]] = []
    lock_keys: list[bytes] = []
    for op, cf, key, val in ops:
        if cf == CF_LOCK:
            try:
                lock_keys.append(Key.from_encoded(key).to_raw())
            except Exception:  # noqa: BLE001 — undecodable lock key
                return None
            continue
        if cf != CF_WRITE:
            continue  # CF_DEFAULT rides along with its CF_WRITE record
        if op != "put":
            return None  # GC / collapse deletes: not an incremental change
        try:
            enc_user, cts = split_ts(key)
            w = Write.from_bytes(val)
            raw = Key.from_encoded(enc_user).to_raw()
        except Exception:  # noqa: BLE001 — malformed record
            return None
        if w.write_type == WriteType.PUT:
            if w.gc_fence is not None:
                return None
            v = w.short_value
            if v is None:
                try:
                    v = get_default(append_ts(enc_user, w.start_ts)) if get_default else None
                except Exception:  # noqa: BLE001 — a faulting engine read
                    v = None  # must degrade, not propagate into apply
                if v is None:
                    return None
            writes.append((raw, int(cts), v))
        elif w.write_type == WriteType.DELETE:
            writes.append((raw, int(cts), None))
        # LOCK / ROLLBACK records change no visible row data: skip.  Their
        # fingerprint drift is repaired by the scan_delta fallback if a
        # reader ever diffs this range again.
    return writes, lock_keys


def _in_ranges(raw: bytes, ranges) -> bool:
    for start, end in ranges:
        if start <= raw < end:
            return True
    return False


def _epoch_of(ctx_epoch) -> tuple[int, int] | None:
    if ctx_epoch is None:
        return None
    if isinstance(ctx_epoch, (tuple, list)) and len(ctx_epoch) == 2:
        return (int(ctx_epoch[0]), int(ctx_epoch[1]))
    conf_ver = getattr(ctx_epoch, "conf_ver", None)
    version = getattr(ctx_epoch, "version", None)
    if conf_ver is None or version is None:
        return None
    return (int(conf_ver), int(version))


def schema_sig(columns_info) -> tuple:
    return tuple(
        (
            c.col_id,
            c.ftype.eval_type,
            c.ftype.decimal,
            c.ftype.flag,
            bool(c.ftype.is_unsigned),
            bool(c.is_pk_handle),
            c.default_value,
        )
        for c in columns_info
    )


class RegionImage:
    """One region's decoded, device-pinnable columnar state."""

    def __init__(self, key, epoch, schema, block_rows: int):
        self.key = key
        self.epoch = epoch
        self.schema = schema
        self.block_rows = block_rows
        # overload plane (docs/robustness.md "Overload"): the tenant whose
        # request built this image — HBM partition accounting and the
        # memory-pressure ladder key on it
        self.tenant = "default"
        self.apply_index = -1
        self.snapshot_ts = -1
        self.max_commit_ts = 0
        self.handles = np.empty(0, dtype=np.int64)
        self.row_commit_ts = np.empty(0, dtype=np.int64)
        self.block_cache = ColumnBlockCache(key=key)
        self.decoder = RowBatchDecoder(schema)
        self.nbytes = 0
        # compressed residency (docs/compressed_columns.md): whether fill
        # ran the encoding stats pass, and which columns it encoded
        self.encode_enabled = False
        self.encodings: dict[int, str] = {}
        # bytes->code maps for dict-encoded columns, built on first delta
        self._dict_maps: dict[int, dict] = {}
        # write-through pending delta (apply_write buffers; serve folds in):
        # {"base", "apply_index", "changed": {handle: (value, cts)},
        #  "deleted": set[handle], "max_ct"} or None
        self.wt_pending: dict | None = None
        # a write-through batch touched CF_LOCK in range: the next warm
        # serve must re-scan locks even at an unchanged start_ts.  Cleared
        # only when a lock-free scan ran on a snapshot at/after the batch
        # that dirtied it (locks_dirty_at) — an older snapshot proves
        # nothing about that batch's lock.
        self.locks_dirty = False
        self.locks_dirty_at = 0
        # integrity fingerprint (docs/integrity.md): one crc64 per row over
        # the RAW (key, value) chain — byte-identical to the coprocessor
        # Checksum entry — plus a commit_ts-mixed variant, both folded
        # incrementally by every delta apply.  fp_valid=False (multi-table
        # ranges, unhashable delta keys) disables the whole plane for this
        # image: the scrubber reports it unverifiable, checksum serves cold.
        self.fp_valid = False
        self.table_id: int | None = None
        self.row_fp = np.empty(0, dtype=np.uint64)
        self.row_nbytes = np.empty(0, dtype=np.int64)
        self.fp_value = 0      # fold(row_fp): the warm Checksum answer
        self.fp_integrity = 0  # fold(mix_fp(row_fp, row_commit_ts))

    @property
    def n_rows(self) -> int:
        return len(self.handles)

    def _offsets(self) -> np.ndarray:
        nv = np.array([b.n_valid for b in self.block_cache.blocks], dtype=np.int64)
        return np.concatenate([[0], np.cumsum(nv)])

    def _recount(self) -> None:
        self.nbytes = (
            self.block_cache.nbytes() + self.handles.nbytes + self.row_commit_ts.nbytes
        )

    # -- build -------------------------------------------------------------

    def fill(self, handles: np.ndarray, values: list[bytes], cts: np.ndarray,
             max_commit_ts: int, apply_index: int, start_ts: int,
             raw_keys: list[bytes] | None = None, encode: bool = False) -> None:
        self.handles = handles
        self.row_commit_ts = cts
        self._init_fingerprint(handles, values, raw_keys)
        cache = self.block_cache
        cache.clear_blocks()
        br = self.block_rows
        for s in range(0, len(values), br):
            e = min(s + br, len(values))
            cols = self.decoder.decode(handles[s:e], values[s:e])
            cache.add(cols, e - s)
        cache.filled = True
        # fill-time stats pass (docs/compressed_columns.md): eligible
        # columns become ENCODED residents — dict codes narrowed, runs
        # collapsed to RLE, narrow ranges bitpacked — and the recount below
        # accounts the budget in ENCODED bytes, which is what multiplies
        # warm capacity.  Fingerprints above hash the LOGICAL rows, so the
        # integrity plane cross-checks encoded and decoded images alike.
        self.encode_enabled = bool(encode)
        if encode:
            self.encodings = _encoding.encode_blocks(cache, self.schema)
        self.apply_index = apply_index
        self.snapshot_ts = start_ts
        self.max_commit_ts = max_commit_ts
        self.wt_pending = None  # a rebuild reflects the engine directly
        self._recount()

    # -- integrity fingerprint ---------------------------------------------

    def _init_fingerprint(self, handles, values, raw_keys) -> None:
        """Compute the per-row integrity hashes at build time.  Delta folds
        reconstruct raw keys from (table_id, handle), so a single-table
        range is required — raw record keys ARE (table_id, handle) encoded,
        making the reconstruction exact."""
        self.fp_valid = False
        self.table_id = None
        try:
            if raw_keys is None:
                self.table_id = self._table_id_from_ranges()
                if self.table_id is None:
                    return
                raw_keys = [record_key(self.table_id, int(h)) for h in handles]
            elif len(raw_keys):
                tid_first = decode_record_key(raw_keys[0])[0]
                # keys are sorted: same first/last table prefix = one table
                if decode_record_key(raw_keys[-1])[0] != tid_first:
                    return
                self.table_id = tid_first
            else:
                self.table_id = self._table_id_from_ranges()
            self.row_fp = _integrity.row_checksums(raw_keys, values)
            self.row_nbytes = np.fromiter(
                (len(k) + len(v) for k, v in zip(raw_keys, values)),
                dtype=np.int64, count=len(values),
            )
        except Exception:  # noqa: BLE001 — exotic keys: plane off, serve on
            self.row_fp = np.empty(0, dtype=np.uint64)
            self.row_nbytes = np.empty(0, dtype=np.int64)
            self.fp_value = self.fp_integrity = 0
            return
        self.fp_valid = True
        self._refold()

    def _table_id_from_ranges(self) -> int | None:
        from ..util import codec as _codec

        tids = set()
        for start, _end in self.key[1]:
            if len(start) < 9 or start[:1] != b"t":
                return None
            tids.add(_codec.decode_i64(start, 1))
        return tids.pop() if len(tids) == 1 else None

    def _refold(self) -> None:
        self.fp_value = _integrity.fold(self.row_fp)
        self.fp_integrity = _integrity.fold(
            _integrity.mix_fp(self.row_fp, self.row_commit_ts)
        )

    def _invalidate_fp(self) -> None:
        """An unhashable delta landed: the fingerprint plane turns off for
        this image (it would otherwise drift silently)."""
        self.fp_valid = False
        self.row_fp = np.empty(0, dtype=np.uint64)
        self.row_nbytes = np.empty(0, dtype=np.int64)
        self.fp_value = self.fp_integrity = 0

    def checksum_parts(self) -> tuple[int, int, int] | None:
        """(checksum, total_kvs, total_bytes) exactly as the CPU-oracle
        Checksum scan would answer over this image's rows, or None when the
        fingerprint plane is off for this image."""
        if not self.fp_valid:
            return None
        return self.fp_value, self.n_rows, int(self.row_nbytes.sum())

    # -- delta -------------------------------------------------------------

    def apply_delta(self, delta: dict, apply_index: int, start_ts: int) -> int:
        """Apply a ``mvcc_batch.scan_delta`` result; returns rows touched."""
        ch = delta["changed_handles"]
        dh = delta["deleted_handles"]
        n_touched = len(ch) + len(dh)
        if n_touched:
            pos = np.searchsorted(self.handles, ch)
            pos_c = np.minimum(pos, max(self.n_rows - 1, 0))
            in_place = (
                len(dh) == 0
                and self.n_rows > 0
                and bool((self.handles[pos_c] == ch).all())
            )
            cols = (
                self.decoder.decode(ch, delta["changed_values"]) if len(ch) else None
            )
            # fingerprint fold (docs/integrity.md): hash the delta rows off
            # the RAW value chain before decode touches them — the fold
            # tracks what the image will CONTAIN, the scrubber's oracle says
            # what it SHOULD contain
            new_fp = new_nb = None
            if self.fp_valid:
                try:
                    dkeys = [record_key(self.table_id, int(h)) for h in ch]
                    new_fp = _integrity.row_checksums(dkeys, delta["changed_values"])
                    new_nb = np.fromiter(
                        (len(k) + len(v)
                         for k, v in zip(dkeys, delta["changed_values"])),
                        dtype=np.int64, count=len(ch),
                    )
                except Exception:  # noqa: BLE001 — unhashable: plane off
                    self._invalidate_fp()
            if in_place:
                if self.fp_valid:
                    cts_new = np.asarray(delta["changed_commit_ts"], dtype=np.int64)
                    old_fp = self.row_fp[pos]
                    old_mix = _integrity.mix_fp(old_fp, self.row_commit_ts[pos])
                    self.fp_value ^= _integrity.fold(old_fp) ^ _integrity.fold(new_fp)
                    self.fp_integrity ^= _integrity.fold(old_mix) ^ _integrity.fold(
                        _integrity.mix_fp(new_fp, cts_new)
                    )
                    self.row_fp[pos] = new_fp
                    self.row_nbytes[pos] = new_nb
                self._apply_updates(pos, cols, ch, delta["changed_commit_ts"])
            else:
                self._apply_structural(ch, cols, delta["changed_commit_ts"], dh,
                                       new_fp, new_nb)
        self.apply_index = apply_index
        self.snapshot_ts = start_ts
        self.max_commit_ts = delta["max_commit_ts"]
        self._recount()
        return n_touched

    def _code_of(self, ci: int, blocks, value: bytes) -> int:
        """Image dictionary code for ``value`` on column ``ci``, appending a
        new entry (shared across every block) when unseen."""
        dmap = self._dict_maps.get(ci)
        dictionary = blocks[0].cols[ci].dictionary
        if dmap is None:
            dmap = self._dict_maps[ci] = {bytes(v): j for j, v in enumerate(dictionary)}
        code = dmap.get(value)
        if code is None:
            code = len(dmap)
            dmap[value] = code
            grown = np.empty(code + 1, dtype=object)
            grown[:code] = dictionary
            grown[code] = value
            for b in blocks:
                b.cols[ci].dictionary = grown
        return code

    def _delta_cell(self, ci: int, blocks, col: Column, r: int):
        """(value, is_null) of delta row ``r`` in the image's representation."""
        nl = bool(np.asarray(col.nulls)[r])
        image_col = blocks[0].cols[ci] if blocks else None
        dict_encoded = image_col is not None and image_col.is_dict_encoded
        if isinstance(image_col, _encoding.EncodedColumn):
            # int-family lanes by construction — and the ``.data`` probe
            # below would permanently cache a full decode the encoded byte
            # budget never accounted for
            obj_col = False
        else:
            obj_col = (
                image_col.data.dtype == object
                if image_col is not None and isinstance(image_col.data, np.ndarray)
                else self.schema[ci].ftype.eval_type in (EvalType.BYTES, EvalType.JSON)
                and not dict_encoded
            )
        if nl:
            return (b"" if obj_col and not dict_encoded else 0), True
        v = col.decoded().data[r] if col.is_dict_encoded else col.data[r]
        if dict_encoded:
            return self._code_of(ci, blocks, bytes(v)), False
        return v, False

    def _apply_updates(self, pos: np.ndarray, cols, ch: np.ndarray, cts: np.ndarray) -> None:
        """In-place row updates: mutate host arrays (patching encoded
        payloads where the encoding survives — docs/compressed_columns.md),
        scatter device pins."""
        blocks = self.block_cache.blocks
        offsets = self._offsets()
        bi_arr = np.searchsorted(offsets, pos, side="right") - 1
        if _bufsan.enabled():
            # mutation choke point: the fold is about to write these host
            # arrays in place — any of them still exposed (wire part mid
            # sendmsg, shadow-read snapshot) is a violation.  Encoded
            # columns list their payload arrays, never ``.data`` (the
            # property would cache a full decode).
            bufs: list = [self.row_commit_ts]
            for bi in np.unique(bi_arr):
                for col in blocks[int(bi)].cols:
                    if isinstance(col, _encoding.EncodedColumn):
                        bufs.extend(a for a in (col.packed, col.run_values,
                                                col.run_ends, col.run_nulls)
                                    if a is not None)
                    else:
                        bufs.append(col.data)
                        bufs.append(col.nulls)
            _bufsan.note_mutation(bufs, site="region_cache._apply_updates")
        # any in-place update to an RLE column breaks its runs: demote it
        # image-wide up front (decode-on-next-serve), so the assignments
        # below land on plain decoded arrays
        for ci in range(len(self.schema)):
            if self.schema[ci].is_pk_handle:
                continue
            c0 = blocks[0].cols[ci] if blocks else None
            if isinstance(c0, _encoding.EncodedColumn) and c0.kind == "rle":
                _encoding.demote_column(self.block_cache, ci, "inplace_update")
        updates: dict[int, tuple[np.ndarray, dict]] = {}
        for bi in np.unique(bi_arr):
            sel = np.flatnonzero(bi_arr == bi)
            rows = (pos[sel] - offsets[bi]).astype(np.int64)
            per_col: dict[int, tuple[np.ndarray, np.ndarray]] = {}
            for ci, col in enumerate(cols):
                if self.schema[ci].is_pk_handle:
                    continue  # handles are the row identity — never change
                image_col = blocks[int(bi)].cols[ci]
                vals = np.empty(len(sel), dtype=_encoding.host_dtype(image_col))
                nls = np.zeros(len(sel), dtype=bool)
                for j, si in enumerate(sel):
                    v, nl = self._delta_cell(ci, blocks, col, int(si))
                    vals[j] = v
                    nls[j] = nl
                if isinstance(image_col, _encoding.EncodedColumn):
                    if not image_col.try_patch(rows, vals, nls):
                        # the new values don't fit the narrow lanes: demote
                        # the column image-wide and write decoded
                        _encoding.demote_column(
                            self.block_cache, ci, "value_range")
                        image_col = blocks[int(bi)].cols[ci]
                        image_col.data[rows] = vals.astype(
                            image_col.data.dtype, copy=False)
                        image_col.nulls[rows] = nls
                else:
                    d = np.asarray(image_col.data)
                    if (image_col.dictionary is not None and d.dtype != object
                            and d.dtype.kind in "iu" and d.dtype.itemsize < 8
                            and len(vals)
                            and _encoding.ensure_code_capacity(
                                blocks, ci, int(vals.max()))):
                        # narrowed code lanes widened (a delta grew the
                        # dictionary past them) — pins rebuild from host
                        self.block_cache.enc_version += 1
                        self.block_cache.drop_device()
                        image_col = blocks[int(bi)].cols[ci]
                    image_col.data[rows] = vals.astype(
                        np.asarray(image_col.data).dtype, copy=False)
                    image_col.nulls[rows] = nls
                per_col[ci] = (vals, nls)
            updates[int(bi)] = (rows, per_col)
        self.row_commit_ts[pos] = cts
        self.block_cache.scatter_update(updates)

    def _apply_structural(self, ch: np.ndarray, cols, cts: np.ndarray, dh: np.ndarray,
                          new_fp: np.ndarray | None = None,
                          new_nb: np.ndarray | None = None) -> None:
        """Inserts and/or deletes: repack host blocks from the resident
        columns (no KV decode) and drop device pins to rebuild lazily.
        ``new_fp``/``new_nb`` are the changed rows' integrity hashes/sizes —
        mirrored through the same delete/update/insert index math as
        ``row_commit_ts`` so the fingerprint arrays stay row-aligned."""
        # repacks build NEW arrays (concatenate copies) so exposed buffers
        # are never written — but the old image is about to be replaced, so
        # sweep the ledger once: anything already corrupted reports here
        # with its export stack instead of at a far-away release
        _bufsan.verify_all(site="region_cache._apply_structural")
        if self.fp_valid and new_fp is None and len(ch):
            self._invalidate_fp()
        fp = self.row_fp if self.fp_valid else None
        nb = self.row_nbytes if self.fp_valid else None
        blocks = self.block_cache.blocks
        n_old = self.n_rows
        # global view of each column, preserving dictionary codes
        gdata, gnulls = [], []
        for ci in range(len(self.schema)):
            if blocks:
                g = np.concatenate([np.asarray(b.cols[ci].data) for b in blocks])
                if (blocks[0].cols[ci].dictionary is not None
                        and g.dtype != object and g.dtype.kind in "iu"
                        and g.dtype.itemsize < 8):
                    # narrowed code lanes widen for the repack math (new
                    # codes may exceed them); re-encode below re-narrows
                    g = g.astype(np.int64)
                gdata.append(g)
                gnulls.append(np.concatenate([np.asarray(b.cols[ci].nulls) for b in blocks]))
            else:
                et = self.schema[ci].ftype.eval_type
                dtype = (
                    object if et in (EvalType.BYTES, EvalType.JSON)
                    else np.float64 if et == EvalType.REAL
                    else np.int64
                )
                gdata.append(np.empty(0, dtype=dtype))
                gnulls.append(np.empty(0, dtype=bool))
        handles = self.handles
        row_cts = self.row_commit_ts
        if len(dh) and n_old:
            keep = np.ones(n_old, dtype=bool)
            dpos = np.searchsorted(handles, dh)
            ok = dpos < n_old
            ok &= handles[np.minimum(dpos, n_old - 1)] == dh
            keep[dpos[ok]] = False
            sel = np.flatnonzero(keep)
            handles = handles[sel]
            row_cts = row_cts[sel]
            if fp is not None:
                fp = fp[sel]
                nb = nb[sel]
            gdata = [d[sel] for d in gdata]
            gnulls = [nl[sel] for nl in gnulls]
        if len(ch):
            # split changed rows into updates of surviving rows vs inserts
            pos = np.searchsorted(handles, ch)
            pos_c = np.minimum(pos, max(len(handles) - 1, 0))
            is_upd = (len(handles) > 0) & (handles[pos_c] == ch) if len(handles) else (
                np.zeros(len(ch), dtype=bool)
            )
            new_vals: list[list] = [[] for _ in self.schema]
            new_nulls: list[list] = [[] for _ in self.schema]
            for r in range(len(ch)):
                for ci, col in enumerate(cols):
                    if self.schema[ci].is_pk_handle:
                        v, nl = int(ch[r]), False
                    else:
                        v, nl = self._delta_cell(ci, blocks, col, r)
                    new_vals[ci].append(v)
                    new_nulls[ci].append(nl)
            upd_idx = np.flatnonzero(np.asarray(is_upd))
            for ci in range(len(self.schema)):
                if len(upd_idx) and not self.schema[ci].is_pk_handle:
                    gdata[ci][pos_c[upd_idx]] = np.array(
                        [new_vals[ci][int(i)] for i in upd_idx], dtype=gdata[ci].dtype
                    )
                    gnulls[ci][pos_c[upd_idx]] = np.array(
                        [new_nulls[ci][int(i)] for i in upd_idx], dtype=bool
                    )
            if len(upd_idx):
                row_cts = row_cts.copy()
                row_cts[pos_c[upd_idx]] = cts[upd_idx]
                if fp is not None:
                    fp = fp.copy()
                    nb = nb.copy()
                    fp[pos_c[upd_idx]] = new_fp[upd_idx]
                    nb[pos_c[upd_idx]] = new_nb[upd_idx]
            ins_idx = np.flatnonzero(~np.asarray(is_upd))
            if len(ins_idx):
                ins_h = ch[ins_idx]
                ins_at = np.searchsorted(handles, ins_h)
                handles = np.insert(handles, ins_at, ins_h)
                row_cts = np.insert(row_cts, ins_at, cts[ins_idx])
                if fp is not None:
                    fp = np.insert(fp, ins_at, new_fp[ins_idx])
                    nb = np.insert(nb, ins_at, new_nb[ins_idx])
                for ci in range(len(self.schema)):
                    ivals = np.array(
                        [new_vals[ci][int(i)] for i in ins_idx], dtype=gdata[ci].dtype
                    )
                    gdata[ci] = np.insert(gdata[ci], ins_at, ivals)
                    gnulls[ci] = np.insert(
                        gnulls[ci], ins_at, np.array([new_nulls[ci][int(i)] for i in ins_idx], dtype=bool)
                    )
        self.handles = handles
        self.row_commit_ts = row_cts
        if fp is not None:
            self.row_fp = fp
            self.row_nbytes = nb
            # the repack is already O(n): a vectorized re-fold is simpler
            # than incrementally retiring the deleted rows' contributions
            self._refold()
        # re-chunk into blocks (views over the global arrays) and drop pins
        templates = [blocks[0].cols[ci] if blocks else None for ci in range(len(self.schema))]
        self.block_cache.clear_blocks()  # drops pins WITH accounting
        br = self.block_rows
        n = len(handles)
        for s in range(0, n, br):
            e = min(s + br, n)
            bcols = []
            for ci in range(len(self.schema)):
                t = templates[ci]
                bcols.append(Column(
                    t.eval_type if t is not None else self.schema[ci].ftype.eval_type,
                    gdata[ci][s:e],
                    gnulls[ci][s:e],
                    t.frac if t is not None else self.schema[ci].ftype.decimal,
                    t.dictionary if t is not None else None,
                ))
            self.block_cache.add(bcols, e - s)
        self.block_cache.filled = True
        if self.encode_enabled:
            # structural repacks re-run the stats pass: the rebuilt plain
            # blocks re-encode from fresh value ranges/runs (no KV decode —
            # the repack above already stayed on resident columns)
            self.encodings = _encoding.encode_blocks(self.block_cache, self.schema)
        self.block_cache.drop_device()


class RegionCacheStats:
    __slots__ = ("hits", "misses", "deltas", "delta_rows", "stale", "uncacheable",
                 "evictions", "invalidations", "bytes_pinned",
                 "wt_deltas", "wt_rows", "wt_lost")

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.deltas = 0      # scan_delta-path serves (CF_WRITE re-scans)
        self.delta_rows = 0
        self.stale = 0
        self.uncacheable = 0
        self.evictions = 0
        self.invalidations = 0
        self.bytes_pinned = 0
        self.wt_deltas = 0   # write-through folds (zero CF_WRITE scans)
        self.wt_rows = 0
        self.wt_lost = 0     # emission gaps forcing a scan_delta repair

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class RegionColumnCache:
    """LRU of :class:`RegionImage` under a byte budget.

    **Sharded mode** (``mesh`` with >1 device): every image is assigned an
    OWNER device under a per-device byte budget — the whole image on the
    least-loaded device normally, block-level round-robin for a single huge
    region (one region bigger than a device's budget share).  The placement
    is written onto each image's block cache as ``owner_devices`` (device id
    per block); the mesh-sharded warm launcher
    (``parallel.mesh.launch_xregion_sharded``) pins the slab stacks there, so
    a cross-region batch runs with zero re-sharding — each device already
    holds its shard.  Eviction/invalidation rebalances: images migrate from
    the most- to the least-loaded device (pins rebuild lazily on the new
    owner)."""

    def __init__(
        self,
        byte_budget: int = DEFAULT_BYTE_BUDGET,
        max_regions: int = DEFAULT_MAX_REGIONS,
        block_rows: int | None = None,
        mesh=None,
        per_device_budget: int | None = None,
        write_through: bool = True,
        data_token: object = _TOKEN_UNSET,
        encode_columns: bool = True,
    ):
        from .jax_eval import DEFAULT_BLOCK_ROWS

        self.byte_budget = byte_budget
        self.max_regions = max_regions
        self.block_rows = block_rows or DEFAULT_BLOCK_ROWS
        # compressed residency (docs/compressed_columns.md): fill runs the
        # encoding stats pass and the byte budget accounts ENCODED bytes —
        # encode_columns=False is the kill switch (decoded residency, PR-9
        # behavior exactly)
        self.encode_columns = encode_columns
        self._images: dict = {}  # key -> RegionImage, insertion = LRU order
        self._mu = make_rlock("copr.region_cache")
        self.stats = RegionCacheStats()
        # quarantine ledger (docs/integrity.md): every image invalidated by
        # an integrity mismatch leaves an entry here — the operator's
        # forensic trail behind tikv_coprocessor_integrity_quarantine_total
        self.quarantine_ledger: list[dict] = []
        # write-through delta intake (docs/write_path.md): per-region
        # watermark of the highest apply index whose data change this cache
        # has SEEN (as a parsed delta or a lost marker).  Pending deltas may
        # only start on an image whose apply_index has caught up to the
        # watermark — anything else means a missed batch, and missed batches
        # must repair through scan_delta, never through a gapped pending.
        self.write_through = write_through
        self._wt_seen: dict[int, int] = {}
        # engine identity this cache serves: notifies from any OTHER engine
        # are dropped — region ids alone don't identify data in a process
        # that hosts several stores or embedded endpoints.  Bound at
        # construction when the owner knows its engine (Endpoint passes the
        # engine's data_token; None for plain local engines); otherwise
        # learned from the first served snapshot — late binding silently
        # drops any notify racing the early serves (the watermark cannot
        # see them), so a late-bound cache additionally refuses to START a
        # pending chain for a region until one notify has been observed
        # and a read has repaired past it (_merge_pending's prev>=0 gate).
        self._wt_token = data_token
        self._wt_late_bound = False
        # per-tenant HBM partitions (docs/robustness.md "Overload"): byte
        # budgets splitting the global budget per tenant; the default
        # tenant owns the remainder pool.  An over-budget tenant degrades
        # down the pressure ladder (_enforce_tenant_budgets): evict ITS
        # coldest images → demote ITS pins to host → CPU-fallback ITS
        # device paths for a cooldown — never another tenant's warm set.
        self._tenant_budgets: dict[str, int] = {}
        self._device_blocked: dict[str, float] = {}
        self.device_block_cooldown_s = 2.0
        self._clock = time.monotonic
        self.devices: list = []
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            try:
                devs = list(np.asarray(mesh.devices).reshape(-1))
            except Exception:  # noqa: BLE001 — a fake/broken mesh: unsharded
                devs = []
            if len(devs) > 1:
                self.devices = devs
        self.per_device_budget = (
            per_device_budget
            if per_device_budget is not None
            else byte_budget // max(len(self.devices), 1)
        )
        self._device_bytes: dict[int, int] = {d.id: 0 for d in self.devices}
        _CACHES.add(self)

    @property
    def sharded(self) -> bool:
        return bool(self.devices)

    # -- public ------------------------------------------------------------

    def serve(self, snap, context: dict, columns_info, ranges, start_ts: int,
              statistics: Statistics | None = None):
        """Resolve a request against the cache.

        Returns ``(block_cache | None, outcome, delta_rows)``; a None block
        cache means "serve through the normal path" (outcome says why)."""
        region_id = (context or {}).get("region_id")
        epoch = _epoch_of((context or {}).get("region_epoch"))
        apply_index = (context or {}).get("apply_index")
        if region_id is None or epoch is None or apply_index is None:
            return None, "off", 0
        tenant = str((context or {}).get("tenant") or "default")
        key = (region_id, tuple(ranges), schema_sig(columns_info))
        stats = statistics or Statistics()
        with self._mu:
            if self._wt_token is _TOKEN_UNSET:
                # bind to the engine behind the first served snapshot —
                # from here on, only ITS write-through notifies are accepted.
                # Notifies BEFORE this bind were dropped unseen, so pending
                # creation stays gated until the stream re-anchors.
                self._wt_token = getattr(snap, "data_token", None)
                self._wt_late_bound = True
            img = self._images.get(key)
            if img is not None and img.epoch != epoch:
                self._drop(key, reason="epoch")
                img = None
            if img is not None:
                # LRU touch
                self._images.pop(key)
                self._images[key] = img
        if img is None:
            # build OUTSIDE the manager lock: a cold build of a large region
            # (full MVCC resolve + decode) must not stall hits on warm
            # regions.  A concurrent build of the same key wastes one build;
            # the insert below keeps whichever image is newest.
            return self._build(key, epoch, snap, columns_info, ranges,
                               start_ts, apply_index, stats, tenant=tenant)
        with self._mu:
            if self._images.get(key) is not img or img.epoch != epoch:
                # raced with an invalidation between lookup and here
                self.stats.uncacheable += 1
                self._count("uncacheable")
                return None, "uncacheable", 0
            if start_ts < img.snapshot_ts:
                self.stats.stale += 1
                self._count("stale")
                return None, "stale", 0
            if self._hit_fresh_locked(img, apply_index, start_ts, snap,
                                      ranges, stats):
                self.stats.hits += 1
                self._count("hit")
                return img.block_cache, "hit", 0
            pend = img.wt_pending
            if (pend is not None
                    and img.apply_index > apply_index):
                # reader's snapshot predates the image: the scan_delta below
                # would rewind the image under the pending chain's base —
                # keep the pending for current readers, serve this one cold
                self.stats.stale += 1
                self._count("stale")
                return None, "stale", 0
            if (pend is not None
                    and apply_index >= pend["apply_index"]
                    and img.apply_index >= pend["base"]
                    and img.max_commit_ts <= img.snapshot_ts
                    and start_ts >= pend["max_ct"]):
                # write-through fast path: every data batch between the
                # image's state and the reader's snapshot is buffered here —
                # fold it in and serve with ZERO CF_WRITE scans.  Locks are
                # the one thing a buffered batch cannot prove absent, so a
                # dirty lock state re-scans CF_LOCK (tiny) first.
                if img.locks_dirty or start_ts > img.snapshot_ts:
                    seen = self._check_locks(snap, ranges, start_ts, stats)
                    if seen == 0 and apply_index >= img.locks_dirty_at:
                        img.locks_dirty = False
                n_touch = len(pend["changed"]) + len(pend["deleted"])
                if n_touch == 0:
                    # the batches touched nothing in this image's ranges
                    # (another table/index in the region, lock-only traffic):
                    # advance the version bookkeeping and serve a plain HIT —
                    # no fold, no device re-placement churn
                    img.apply_index = apply_index
                    img.snapshot_ts = max(img.snapshot_ts, start_ts)
                    img.max_commit_ts = max(img.max_commit_ts, pend["max_ct"])
                    img.wt_pending = None
                    self.stats.hits += 1
                    self._count("hit")
                    return img.block_cache, "hit", 0
                if img.n_rows and n_touch > _REBUILD_FRACTION * img.n_rows:
                    self._drop(key, reason="delta_too_big")
                    return self._build(key, epoch, snap, columns_info, ranges,
                                       start_ts, apply_index, stats,
                                       tenant=tenant)
                handles = np.array(sorted(pend["changed"]), dtype=np.int64)
                delta = {
                    "changed_handles": handles,
                    "changed_values": [pend["changed"][int(h)][0] for h in handles],
                    "changed_commit_ts": np.array(
                        [pend["changed"][int(h)][1] for h in handles], dtype=np.int64),
                    "deleted_handles": np.array(sorted(pend["deleted"]), dtype=np.int64),
                    "max_commit_ts": max(img.max_commit_ts, pend["max_ct"]),
                }
                n = img.apply_delta(delta, apply_index, start_ts)
                img.wt_pending = None
                if self.devices:
                    self._unplace(img)
                    self._place(img)
                self.stats.wt_deltas += 1
                self.stats.wt_rows += n
                self._count("wt_delta")
                self._count_delta_rows(n)
                self._enforce_budget(keep=key)
                self._gauge_bytes(full=False)
                return img.block_cache, "wt_delta", n
            # lint: allow(lock-blocking-call) -- the fold-in must be atomic
            # with the image version bump (docs: Concurrency); the scan is
            # bounded by the delta size, and cold BUILDS run outside the lock
            delta = scan_delta(snap, start_ts, ranges, img.handles,
                               img.row_commit_ts, statistics=stats)
            if delta is None:
                self.stats.uncacheable += 1
                self._count("uncacheable")
                self._drop(key, reason="unvectorizable")
                return None, "uncacheable", 0
            n_touch = len(delta["changed_handles"]) + len(delta["deleted_handles"])
            if img.n_rows and n_touch > _REBUILD_FRACTION * img.n_rows:
                self._drop(key, reason="delta_too_big")
                return self._build(key, epoch, snap, columns_info, ranges,
                                   start_ts, apply_index, stats,
                                   tenant=tenant)
            n = img.apply_delta(delta, apply_index, start_ts)
            if apply_index >= img.locks_dirty_at:
                # scan_delta lock-checked the ranges on a snapshot that
                # contains the dirtying batch
                img.locks_dirty = False
            pend = img.wt_pending
            if pend is not None and (pend["apply_index"] <= img.apply_index
                                     or img.apply_index < pend["base"]):
                # the scan repaired past the pending chain (or rewound under
                # its base): replaying it would regress rows — drop it
                img.wt_pending = None
            if self.devices:
                # a structural repack can change the block count and bytes:
                # refresh the placement so owner_devices stays block-aligned
                self._unplace(img)
                self._place(img)
            self.stats.deltas += 1
            self.stats.delta_rows += n
            self._count("delta")
            self._count_delta_rows(n)
            self._enforce_budget(keep=key)
            self._gauge_bytes(full=False)
            return img.block_cache, "delta", n

    # -- integrity plane (docs/integrity.md) ---------------------------------

    def quarantine_image(self, key, stage: str, detail: dict | None = None):
        """Quarantine ONE image: ledger entry + invalidation (counted under
        its own reason so dashboards separate corruption from churn).  The
        rebuild happens on the next serve — or eagerly by the scrubber.
        Safe to call with the manager lock held (it is reentrant)."""
        import time as _time

        with self._mu:
            img = self._images.get(key)
            if img is None:
                return None
            entry = {
                "time": _time.time(),
                "region_id": key[0],
                "key_id": _integrity.image_key_id(key),
                "ranges": [(s.hex(), e.hex()) for s, e in key[1]],
                "stage": stage,
                "epoch": list(img.epoch),
                "apply_index": img.apply_index,
                "snapshot_ts": img.snapshot_ts,
                "rows": img.n_rows,
                "fingerprint": img.fp_integrity if img.fp_valid else None,
            }
            if detail:
                entry.update(detail)
            self.quarantine_ledger.append(entry)
            del self.quarantine_ledger[:-256]
            self._drop(key, reason="quarantine")
        _integrity.count_quarantine(stage)
        return entry

    def quarantine_region(self, region_id: int, ranges=None, stage: str = "scrub",
                          detail: dict | None = None) -> list:
        """Quarantine every image of ``region_id`` (narrowed to one range
        set when ``ranges`` is given) — the shadow-read mismatch path."""
        with self._mu:
            keys = [
                k for k in self._images
                if k[0] == region_id and (ranges is None or k[1] == tuple(ranges))
            ]
            return [self.quarantine_image(k, stage, detail) for k in keys]

    def image_fingerprints(self) -> list[dict]:
        """Per-image integrity view for the debug surface: fingerprint,
        apply point, and write-through pending state of every resident
        image."""
        with self._mu:
            out = []
            for key, img in self._images.items():
                out.append({
                    "region_id": key[0],
                    "key_id": _integrity.image_key_id(key),
                    "epoch": list(img.epoch),
                    "apply_index": img.apply_index,
                    "snapshot_ts": img.snapshot_ts,
                    "rows": img.n_rows,
                    "fp_valid": img.fp_valid,
                    "fingerprint": img.fp_integrity if img.fp_valid else None,
                    "checksum": img.fp_value if img.fp_valid else None,
                    "pending": img.wt_pending is not None,
                })
            return out

    def checksum_serve(self, snap, context: dict, ranges, start_ts: int):
        """Answer a coprocessor Checksum (tp=105) off a warm image
        fingerprint: returns (checksum, total_kvs, total_bytes) when an
        image of exactly these ranges is fresh for (apply_index, start_ts),
        else None (the CPU-oracle scan serves).  The per-row hash is the
        checksum_range entry by construction, so warm and cold answers are
        byte-identical.  Locks are the one thing the fingerprint cannot
        prove absent — a dirty/newer-ts serve re-scans CF_LOCK exactly like
        the hit path (and raises KeyIsLocked exactly like the oracle scan
        would)."""
        region_id = (context or {}).get("region_id")
        epoch = _epoch_of((context or {}).get("region_epoch"))
        apply_index = (context or {}).get("apply_index")
        if region_id is None or epoch is None or apply_index is None:
            return None
        rkey = tuple(ranges)
        stats = Statistics()
        with self._mu:
            for key, img in self._images.items():
                if key[0] != region_id or key[1] != rkey:
                    continue
                if img.epoch != epoch or not img.fp_valid:
                    continue
                # the hit path's exact freshness + stale-guard + lock rules
                # (ONE definition — _hit_fresh_locked — so the warm
                # Checksum path can never drift from what a served hit
                # would have answered)
                if self._hit_fresh_locked(img, apply_index, start_ts, snap,
                                          ranges, stats):
                    return img.checksum_parts()
        return None

    def invalidate_region(self, region_id: int, reason: str = "epoch") -> None:
        with self._mu:
            for key in [k for k in self._images if k[0] == region_id]:
                self._drop(key, reason=reason)
            # the notify watermark dies with the images (dead region ids —
            # merge sources, destroyed peers — must not leak an entry each);
            # a live region's next notify re-seeds it before any new image
            # can finish building
            self._wt_seen.pop(region_id, None)
            self._rebalance()

    # -- write-through intake (raft apply -> pending deltas) -----------------

    def apply_write(self, region_id: int, parse_once, apply_index: int,
                    token=None) -> None:
        """Buffer a committed batch's row changes on every resident image of
        ``region_id``.  Raft applies a region's entries in order on one
        worker, so notifies arrive in apply-index order per region; an index
        at or below the watermark is a replica's replay of a batch already
        merged (identical ops by raft) and is skipped.  ``parse_once`` is
        the notify's memoized op parser — invoked OUTSIDE the manager lock
        (it may read CF_DEFAULT), at most once across every live cache."""
        with self._mu:
            if self._wt_token is _TOKEN_UNSET or token != self._wt_token:
                return  # not this cache's engine (or cache never served yet)
            prev = self._wt_seen.get(region_id, -1)
            if apply_index <= prev:
                return
            # the watermark advances even with write_through off: flipping
            # it back on must not let a pending start across unseen batches
            self._wt_seen[region_id] = apply_index
            if not self.write_through:
                # an unbuffered batch gaps any surviving chain — drop it,
                # or re-enabling would merge later batches into the gap
                self._drop_pendings_locked(region_id)
                return
            if not any(k[0] == region_id for k in self._images):
                return
        parsed = parse_once()
        with self._mu:
            # images may have churned while parsing: re-list.  A freshly
            # built image already containing this batch just replays it
            # idempotently; the ``prev`` creation check below still blocks
            # any image whose snapshot predates an unbuffered notify.
            imgs = [img for k, img in self._images.items() if k[0] == region_id]
            if not imgs:
                return
            if parsed is None:
                # not expressible as row changes: pendings are now gapped
                for img in imgs:
                    img.wt_pending = None
                self.stats.wt_lost += 1
                self._count_wt_lost()
                return
            writes, lock_keys = parsed
            for img in imgs:
                self._merge_pending(img, writes, lock_keys, prev, apply_index)

    def note_write_lost(self, region_id: int, apply_index: int,
                        token=None) -> None:
        """A data change of unknown content landed (emission off, raft
        snapshot apply, merge catch-up, OR a notify that faulted after the
        watermark already advanced): drop pendings unconditionally — a
        dropped chain only costs a scan_delta repair, while a chain kept
        across an unbuffered batch serves wrong rows forever — and advance
        the watermark so no pending restarts until a read catches the image
        up past ``apply_index``."""
        with self._mu:
            if self._wt_token is _TOKEN_UNSET or token != self._wt_token:
                return
            if apply_index > self._wt_seen.get(region_id, -1):
                self._wt_seen[region_id] = apply_index
            self._drop_pendings_locked(region_id)

    def _drop_pendings_locked(self, region_id: int) -> None:
        dropped = False
        for k, img in self._images.items():
            if k[0] == region_id and img.wt_pending is not None:
                img.wt_pending = None
                dropped = True
        if dropped:
            self.stats.wt_lost += 1
            self._count_wt_lost()

    def _merge_pending(self, img, writes, lock_keys, prev: int,
                       apply_index: int) -> None:
        ranges = img.key[1]
        if any(_in_ranges(rk, ranges) for rk in lock_keys):
            img.locks_dirty = True
            img.locks_dirty_at = max(img.locks_dirty_at, apply_index)
        pend = img.wt_pending
        if pend is None:
            if prev > img.apply_index or apply_index <= img.apply_index:
                # a batch between the image's state and this one was never
                # buffered (image built mid-stream, or emission was off):
                # this image repairs through scan_delta, not a gapped chain
                return
            if self._wt_late_bound and prev < 0:
                # first observed notify for this region on a LATE-bound
                # cache: earlier notifies may have been dropped unseen
                # while unbound, so this chain cannot anchor — the next
                # read repairs via scan_delta, re-anchoring the stream
                return
            pend = img.wt_pending = {
                "base": img.apply_index, "apply_index": apply_index,
                "changed": {}, "deleted": set(), "max_ct": 0,
            }
        else:
            pend["apply_index"] = apply_index
        for raw, cts, v in writes:
            if not _in_ranges(raw, ranges):
                continue
            if len(raw) != 19:
                # non-record key inside a record range: not foldable
                self._drop_pending_img(img)
                return
            try:
                h = int(decode_record_handles([raw])[0])
            except Exception:  # noqa: BLE001
                self._drop_pending_img(img)
                return
            if v is None:
                pend["changed"].pop(h, None)
                pend["deleted"].add(h)
            else:
                pend["deleted"].discard(h)
                pend["changed"][h] = (v, cts)
            pend["max_ct"] = max(pend["max_ct"], cts)
        if len(pend["changed"]) + len(pend["deleted"]) > max(1024, img.n_rows):
            # pending outgrew the image: a rebuild will beat replaying it
            self._drop_pending_img(img)

    def _drop_pending_img(self, img) -> None:
        """Drop ONE image's pending chain, keeping the wt_lost accounting in
        step with every other drop path (the Grafana emission-gap series
        must see these, or a rising scan_delta rate is undiagnosable)."""
        if img.wt_pending is not None:
            img.wt_pending = None
            self.stats.wt_lost += 1
            self._count_wt_lost()

    # -- per-tenant HBM partitions (docs/robustness.md "Overload") -----------

    def set_tenant_budgets(self, budgets: dict[str, int]) -> None:
        """Partition the byte budget per tenant.  Tenants absent from the
        map share the remainder pool with the default tenant (explicitly
        listing ``default`` pins its pool too).  Enforcement runs now —
        shrinking a partition degrades its tenant immediately."""
        with self._mu:
            self._tenant_budgets = {str(t): int(b) for t, b in budgets.items()}
            self._enforce_tenant_budgets(keep=None)
            self._gauge_bytes()

    def resize_budget(self, byte_budget: int) -> None:
        """Online global-budget change (``Nemesis.memory_squeeze`` and ops
        reconfig): enforcement runs immediately under the new bound."""
        with self._mu:
            self.byte_budget = int(byte_budget)
            self._enforce_budget(keep=None)
            self._gauge_bytes()

    def tenant_budget(self, tenant: str) -> int | None:
        """The tenant's partition bytes, or None = unbounded (only the
        global budget applies).  The default tenant's implicit budget is
        the remainder after every explicit partition."""
        b = self._tenant_budgets.get(tenant)
        if b is not None:
            return b
        if tenant == "default" and self._tenant_budgets:
            explicit = sum(v for t, v in self._tenant_budgets.items()
                           if t != "default")
            return max(self.byte_budget - explicit, 0)
        return None

    def device_allowed(self, tenant: str) -> bool:
        """False while the tenant sits on the pressure ladder's last rung
        (CPU fallback); the block lifts itself after the cooldown."""
        with self._mu:
            until = self._device_blocked.get(tenant)
            if until is None:
                return True
            if self._clock() >= until:
                self._device_blocked.pop(tenant, None)
                return True
            return False

    def tenant_occupancy(self) -> dict:
        """Per-tenant partition view for ``/debug/overload``: resident
        bytes vs budget, image count, and any active device block."""
        with self._mu:
            per: dict[str, dict] = {}
            now = self._clock()
            for img in self._images.values():
                e = per.setdefault(img.tenant, {"bytes": 0, "images": 0})
                e["bytes"] += img.nbytes
                e["images"] += 1
            for tenant in set(per) | set(self._tenant_budgets) \
                    | set(self._device_blocked):
                e = per.setdefault(tenant, {"bytes": 0, "images": 0})
                e["budget"] = self.tenant_budget(tenant)
                until = self._device_blocked.get(tenant)
                e["device_blocked_s"] = (
                    round(max(until - now, 0.0), 3) if until is not None
                    and until > now else 0.0)
            return per

    def _tenant_bytes_locked(self, tenant: str) -> int:
        return sum(img.nbytes for img in self._images.values()
                   if img.tenant == tenant)

    def _enforce_tenant_budgets(self, keep) -> None:
        """The memory-pressure degradation ladder, per over-budget tenant
        (caller holds the manager lock):

        1. evict the tenant's COLDEST images (LRU order) — never another
           tenant's, never the image being served (``keep``);
        2. still over (only ``keep`` / a single over-sized image remains):
           demote the tenant's device pins to host — HBM frees, the host
           copy keeps serving through a rebuild-on-demand pin;
        3. still over: CPU-fallback the tenant's device paths for a
           cooldown (``device_allowed``), so it stops re-pinning what its
           partition cannot hold.  Other tenants' warm sets are untouched
           at every rung."""
        if not self._tenant_budgets:
            return
        from ..util.metrics import REGISTRY

        evict_c = REGISTRY.counter(
            "tikv_overload_hbm_evict_total",
            "Per-tenant HBM-partition pressure actions, by ladder step",
        )
        tenants = {img.tenant for img in self._images.values()}
        for tenant in sorted(tenants):
            budget = self.tenant_budget(tenant)
            if budget is None:
                continue
            if self._tenant_bytes_locked(tenant) <= budget:
                continue
            # rung 1: evict the tenant's own coldest images — sparing its
            # HOTTEST one (and the image being served): a tenant keeps one
            # warm image and the later rungs handle the case where that
            # single image alone exceeds the partition
            mine = [k for k, img in self._images.items()
                    if img.tenant == tenant]
            hottest = mine[-1] if mine else None
            for key in mine:
                if key == keep or key == hottest:
                    continue
                if self._tenant_bytes_locked(tenant) <= budget:
                    break
                self._drop(key, reason="tenant_budget")
                evict_c.inc(tenant=tenant, step="evict")
            if self._tenant_bytes_locked(tenant) <= budget:
                continue
            # rung 2: demote remaining device pins to host
            demoted = False
            for img in self._images.values():
                if img.tenant == tenant:
                    img.block_cache.drop_device()
                    demoted = True
            if demoted:
                evict_c.inc(tenant=tenant, step="demote")
            # rung 3: the host-resident set alone is over the partition —
            # block the tenant's device serving for a cooldown so it stops
            # rebuilding pins its budget cannot hold
            self._device_blocked[tenant] = (
                self._clock() + self.device_block_cooldown_s)
            evict_c.inc(tenant=tenant, step="cpu_block")
            REGISTRY.counter(
                "tikv_overload_device_block_total",
                "Tenants pushed to the pressure ladder's CPU-fallback rung",
            ).inc(tenant=tenant)
        self._rebalance()

    def warm_region_ids(self) -> list[int]:
        """Region ids with a resident device image — the placement this
        store advertises to PD each heartbeat so peers can forward
        device-eligible DAGs to the owner (docs/wire_path.md).  Doubles as
        the byte-gauge heartbeat: pure-hit traffic never re-gauges on the
        serve path, so the pinned-HBM/compression gauges refresh here."""
        with self._mu:
            self._gauge_bytes()
            return sorted({k[0] for k in self._images})

    def has_warm_region(self, region_id: int) -> bool:
        with self._mu:
            return any(k[0] == region_id for k in self._images)

    def total_bytes(self) -> int:
        with self._mu:
            return sum(img.nbytes for img in self._images.values())

    def placement(self) -> dict[int, int]:
        """{device_id: pinned bytes} placement metadata (sharded mode)."""
        with self._mu:
            return dict(self._device_bytes)

    def resident_block_caches(self) -> list:
        """The resident images' block caches (benches / introspection —
        feed to ``parallel.mesh.slab_assignment`` for the slab geometry)."""
        with self._mu:
            return [img.block_cache for img in self._images.values()]

    def __len__(self) -> int:
        return len(self._images)

    # -- sharded placement ---------------------------------------------------

    def _place(self, img) -> None:
        """Assign owner devices to a freshly built/repacked image: whole
        image to the least-loaded device, block-level round-robin when the
        image alone exceeds the per-device budget (a single huge region must
        spread, or one chip serves it while the rest idle)."""
        if not self.devices:
            return
        bc = img.block_cache
        n_blocks = len(bc.blocks)
        if n_blocks == 0:
            bc.owner_devices = []
            img.placement_bytes = {}
            return
        per_block = img.nbytes // n_blocks
        if img.nbytes > self.per_device_budget and n_blocks > 1:
            order = sorted(self.devices, key=lambda d: self._device_bytes[d.id])
            owners = [order[b % len(order)].id for b in range(n_blocks)]
        else:
            dev = min(self.devices, key=lambda d: self._device_bytes[d.id])
            owners = [dev.id] * n_blocks
        bc.owner_devices = owners
        pb: dict[int, int] = {}
        for did in owners:
            pb[did] = pb.get(did, 0) + per_block
        img.placement_bytes = pb
        for did, b in pb.items():
            self._device_bytes[did] += b

    def _unplace(self, img) -> None:
        for did, b in getattr(img, "placement_bytes", {}).items():
            self._device_bytes[did] = max(0, self._device_bytes.get(did, 0) - b)
        img.placement_bytes = {}
        img.block_cache.owner_devices = None

    def _rebalance(self) -> None:
        """Shrink the device-load spread after an eviction/invalidation:
        move the best-fitting whole image from the most- to the least-loaded
        device while that strictly narrows the gap.  Only the placement
        metadata moves — device pins drop and rebuild lazily on the new
        owner at the next warm batch."""
        if not self.devices or len(self._images) < 2:
            return
        for _ in range(len(self._images)):
            hi = max(self.devices, key=lambda d: self._device_bytes[d.id])
            lo = min(self.devices, key=lambda d: self._device_bytes[d.id])
            gap = self._device_bytes[hi.id] - self._device_bytes[lo.id]
            if gap <= 0:
                return
            cand = [
                i for i in self._images.values()
                if set(getattr(i, "placement_bytes", {})) == {hi.id}
                and 0 < i.nbytes < gap
            ]
            if not cand:
                return
            img = min(cand, key=lambda i: abs(gap - 2 * i.nbytes))
            self._unplace(img)
            img.block_cache.drop_device()
            img.block_cache.owner_devices = [lo.id] * len(img.block_cache.blocks)
            img.placement_bytes = {lo.id: img.nbytes}
            self._device_bytes[lo.id] += img.nbytes
            # the migration moved placement bytes AFTER the drop path's
            # last refresh — keep the per-device gauge truthful
            self._gauge_bytes(full=False)
        return

    # -- internals ---------------------------------------------------------

    def _build(self, key, epoch, snap, columns_info, ranges, start_ts,
               apply_index, stats, tenant: str = "default"):
        """Build an image for ``key`` (expensive part lock-free) and insert
        it.  Safe to call with or without the manager lock held (the lock is
        reentrant); a racing build of the same key keeps whichever image
        reflects the newer apply index — this request serves its own blocks
        either way."""
        src = MvccBatchScanSource(snap, start_ts, ranges, statistics=stats,
                                  record_versions=True)
        keys, values = src._resolve_all()
        if not src.versions_exact:
            self.stats.uncacheable += 1
            self._count("uncacheable")
            return None, "uncacheable", 0
        handles = decode_record_handles(keys)
        if len(handles) > 1 and not (handles[1:] > handles[:-1]).all():
            self.stats.uncacheable += 1
            self._count("uncacheable")
            return None, "uncacheable", 0
        img = RegionImage(key, epoch, list(columns_info), self.block_rows)
        img.tenant = tenant
        img.fill(handles, values, src.row_commit_ts, src.max_commit_ts,
                 apply_index, start_ts, raw_keys=keys,
                 encode=self.encode_columns)
        if img.nbytes > self.byte_budget:
            self.stats.uncacheable += 1
            self._count("too_big")
            # serve this request from the just-built blocks, but don't keep
            # them resident — the budget is the OOM guard
            return img.block_cache, "too_big", 0
        with self._mu:
            existing = self._images.get(key)
            if (existing is None or existing.epoch != epoch
                    or existing.apply_index <= apply_index):
                if existing is not None:
                    self._unplace(existing)
                self._images[key] = img
                self._place(img)
                self._enforce_budget(keep=key)
            self.stats.misses += 1
            self._count("miss")
            self._gauge_bytes()
        return img.block_cache, "miss", 0

    def _hit_fresh_locked(self, img, apply_index, start_ts, snap, ranges,
                          stats) -> bool:
        """ONE definition of hit-path freshness (serve()'s hits AND the
        warm Checksum path): True iff the image may serve ``start_ts``
        as-is at ``apply_index``.  Re-scans CF_LOCK when it must (raising
        on a blocking lock, exactly like the oracle scan would) and
        maintains ``locks_dirty`` / ``snapshot_ts`` like a served hit.
        Caller holds the manager lock."""
        if start_ts < img.snapshot_ts:
            # the image may contain rows committed above this reader's ts —
            # only a fresh scan can answer below the image's snapshot
            return False
        if not (apply_index == img.apply_index and (
                start_ts == img.snapshot_ts
                or img.max_commit_ts <= img.snapshot_ts)):
            return False
        if start_ts > img.snapshot_ts or img.locks_dirty:
            seen = self._check_locks(snap, ranges, start_ts, stats)
            if seen == 0 and apply_index >= img.locks_dirty_at:
                # this snapshot contains the dirtying batch and the range is
                # lock-free — safe to stop re-scanning.  An OLDER snapshot
                # seeing no locks proves nothing.
                img.locks_dirty = False
            img.snapshot_ts = max(img.snapshot_ts, start_ts)
        return True

    def _check_locks(self, snap, ranges, ts, stats) -> int:
        """Raise on a blocking lock; return how many locks the ranges hold
        (0 lets callers clear a dirty-lock flag)."""
        seen = 0
        for start, end in ranges:
            enc_start = Key.from_raw(start).encoded
            enc_end = Key.from_raw(end).encoded
            for k, v in snap.scan_cf(CF_LOCK, enc_start, enc_end):
                stats.lock.next += 1
                seen += 1
                _check_lock(v, Key.from_encoded(k).to_raw(), ts, frozenset())
        return seen

    def _drop(self, key, reason: str) -> None:
        img = self._images.pop(key, None)
        if img is None:
            return
        self._unplace(img)
        img.block_cache.clear_blocks()
        img.block_cache.filled = False
        self.stats.invalidations += 1
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_invalidate_total",
            "Region column cache invalidations, by reason",
        ).inc(reason=reason)
        self._gauge_bytes()

    def _enforce_budget(self, keep) -> None:
        # per-tenant partitions first: an over-budget tenant degrades down
        # its own ladder before global pressure evicts ANYONE
        self._enforce_tenant_budgets(keep)
        while len(self._images) > self.max_regions or (
            sum(i.nbytes for i in self._images.values()) > self.byte_budget
            and len(self._images) > 1
        ):
            victim = self._pick_victim_locked(keep)
            if victim is None:
                break
            img = self._images.pop(victim)
            self._unplace(img)
            img.block_cache.clear_blocks()
            img.block_cache.filled = False
            self.stats.evictions += 1
            from ..util.metrics import REGISTRY

            REGISTRY.counter(
                "tikv_coprocessor_region_cache_evict_total",
                "Region column cache LRU/budget evictions",
            ).inc()
        self._rebalance()

    def _pick_victim_locked(self, keep):
        """Global-budget eviction victim: prefer images of tenants over
        their OWN partition (a hot tenant's global pressure must land on
        its warm set, not a well-behaved sibling's), else plain LRU."""
        if self._tenant_budgets:
            for k, img in self._images.items():
                if k == keep:
                    continue
                budget = self.tenant_budget(img.tenant)
                if budget is not None \
                        and self._tenant_bytes_locked(img.tenant) > budget:
                    return k
        return next((k for k in self._images if k != keep), None)

    def _count(self, outcome: str) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_total",
            "Region column cache lookups, by outcome",
        ).inc(outcome=outcome)

    def _count_wt_lost(self) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_wt_lost_total",
            "Write-through emission gaps (pendings dropped; scan_delta repairs)",
        ).inc()

    def _count_delta_rows(self, n: int) -> None:
        if not n:
            return
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_region_cache_delta_rows_total",
            "Rows re-decoded by incremental delta applies",
        ).inc(n)

    def _gauge_bytes(self, full: bool = True) -> None:
        total = sum(i.nbytes for i in self._images.values())
        self.stats.bytes_pinned = total
        from ..util.metrics import REGISTRY

        REGISTRY.gauge(
            "tikv_coprocessor_region_cache_bytes",
            "Resident (encoded) bytes held by region images",
        ).set(total)
        # compressed-residency observability (docs/compressed_columns.md):
        # the ratio the budget win rides on, and the TRUE bytes pinned in
        # HBM right now (summed over every image's device signatures — with
        # encoded residency these are the narrow/encoded payloads, not a
        # host-side proxy).  These walk every image's columns and pin trees,
        # so delta/wt_delta applies (the write hot path, under this lock)
        # pass full=False and the heartbeat/build/drop paths refresh them.
        if full:
            decoded = sum(
                i.block_cache.nbytes_decoded() for i in self._images.values()
            )
            resident = sum(
                i.block_cache.nbytes() for i in self._images.values()
            )
            REGISTRY.gauge(
                "tikv_coprocessor_region_cache_compression_ratio",
                "Decoded-vs-resident byte ratio of the warm column blocks",
            ).set(decoded / resident if resident else 1.0)
            REGISTRY.gauge(
                "tikv_coprocessor_region_cache_device_pinned_bytes",
                "True bytes currently pinned on devices by region images",
            ).set(sum(
                i.block_cache.device_nbytes() for i in self._images.values()
            ))
            if self._tenant_budgets:
                per: dict[str, int] = {}
                for img in self._images.values():
                    per[img.tenant] = per.get(img.tenant, 0) + img.nbytes
                g = REGISTRY.gauge(
                    "tikv_overload_hbm_bytes",
                    "Resident bytes per tenant HBM partition",
                )
                for tenant in set(per) | set(self._tenant_budgets):
                    g.set(per.get(tenant, 0), tenant=tenant)
        if self.devices:
            g = REGISTRY.gauge(
                "tikv_coprocessor_region_cache_device_bytes",
                "Bytes pinned per owner device (sharded placement)",
            )
            for d in self.devices:
                g.set(self._device_bytes.get(d.id, 0), device=str(d.id))
