"""Datum v1 row codec.

Re-expression of the reference's ``tidb_query_datatype/src/codec/datum.rs``:
each value is a one-byte flag followed by a flag-specific payload.  Flag values
match the reference so key/value material is interoperable in spirit:

  NIL=0, BYTES=1, COMPACT_BYTES=2, INT=3, UINT=4, FLOAT=5, DECIMAL=6,
  DURATION=7, VARINT=8, UVARINT=9, JSON=10, MAX=250

Decimals here are this framework's TPU-friendly representation: a scaled
int64 (``value * 10^frac``) encoded as (frac: u8, varint scaled) — exact
fixed-point arithmetic that maps directly onto integer vector lanes, instead
of the reference's base-10^9 word array (``codec/mysql/decimal.rs``).
"""

from __future__ import annotations

from ..util import codec

NIL_FLAG = 0
BYTES_FLAG = 1
COMPACT_BYTES_FLAG = 2
INT_FLAG = 3
UINT_FLAG = 4
FLOAT_FLAG = 5
DECIMAL_FLAG = 6
DURATION_FLAG = 7
VARINT_FLAG = 8
UVARINT_FLAG = 9
JSON_FLAG = 10
MAX_FLAG = 250


class Datum:
    """Tagged scalar. value is None | int | float | bytes | (scaled, frac)."""

    __slots__ = ("flag", "value")

    def __init__(self, flag: int, value):
        self.flag = flag
        self.value = value

    def __repr__(self):
        return f"Datum({self.flag}, {self.value!r})"

    def __eq__(self, other):
        return isinstance(other, Datum) and self.flag == other.flag and self.value == other.value


def encode_datum(out: bytearray, flag: int, value, for_key: bool = False) -> None:
    """Append one datum. ``for_key`` selects memcomparable encodings."""
    if flag == NIL_FLAG:
        out.append(NIL_FLAG)
    elif flag == INT_FLAG:
        if for_key:
            out.append(INT_FLAG)
            out += codec.encode_i64(value)
        else:
            out.append(VARINT_FLAG)
            out += codec.encode_var_i64(value)
    elif flag == UINT_FLAG:
        if for_key:
            out.append(UINT_FLAG)
            out += codec.encode_u64(value)
        else:
            out.append(UVARINT_FLAG)
            out += codec.encode_var_u64(value)
    elif flag == FLOAT_FLAG:
        out.append(FLOAT_FLAG)
        out += codec.encode_f64(value)
    elif flag == BYTES_FLAG:
        if for_key:
            out.append(BYTES_FLAG)
            out += codec.encode_bytes(value)
        else:
            out.append(COMPACT_BYTES_FLAG)
            out += codec.encode_compact_bytes(value)
    elif flag == DECIMAL_FLAG:
        scaled, frac = value
        out.append(DECIMAL_FLAG)
        out.append(frac)
        # fixed 8-byte memcomparable i64: decimals stay fixed-width so row
        # blocks batch-decode as a reshape, and key encodings order correctly
        out += codec.encode_i64(scaled)
    elif flag == DURATION_FLAG:
        out.append(DURATION_FLAG)
        out += codec.encode_i64(value)
    elif flag == JSON_FLAG:
        # value is the self-delimiting binary JSON payload (type byte + body)
        out.append(JSON_FLAG)
        out += value
    elif flag == MAX_FLAG:
        out.append(MAX_FLAG)
    else:
        raise ValueError(f"unsupported datum flag {flag}")


def decode_datum(b: bytes, offset: int = 0) -> tuple[Datum, int]:
    flag = b[offset]
    offset += 1
    if flag == NIL_FLAG:
        return Datum(NIL_FLAG, None), offset
    if flag == INT_FLAG:
        return Datum(INT_FLAG, codec.decode_i64(b, offset)), offset + 8
    if flag == UINT_FLAG:
        return Datum(UINT_FLAG, codec.decode_u64(b, offset)), offset + 8
    if flag == VARINT_FLAG:
        v, offset = codec.decode_var_i64(b, offset)
        return Datum(INT_FLAG, v), offset
    if flag == UVARINT_FLAG:
        v, offset = codec.decode_var_u64(b, offset)
        return Datum(UINT_FLAG, v), offset
    if flag == FLOAT_FLAG:
        return Datum(FLOAT_FLAG, codec.decode_f64(b, offset)), offset + 8
    if flag == BYTES_FLAG:
        v, consumed = codec.decode_bytes(b[offset:])
        return Datum(BYTES_FLAG, v), offset + consumed
    if flag == COMPACT_BYTES_FLAG:
        v, offset = codec.decode_compact_bytes(b, offset)
        return Datum(BYTES_FLAG, v), offset
    if flag == DECIMAL_FLAG:
        frac = b[offset]
        scaled = codec.decode_i64(b, offset + 1)
        return Datum(DECIMAL_FLAG, (scaled, frac)), offset + 9
    if flag == DURATION_FLAG:
        return Datum(DURATION_FLAG, codec.decode_i64(b, offset)), offset + 8
    if flag == JSON_FLAG:
        from .json_value import json_binary_len

        n = json_binary_len(b, offset)
        return Datum(JSON_FLAG, b[offset : offset + n]), offset + n
    if flag == MAX_FLAG:
        return Datum(MAX_FLAG, None), offset
    raise ValueError(f"unknown datum flag {flag}")


def decode_datums(b: bytes) -> list[Datum]:
    out = []
    off = 0
    while off < len(b):
        d, off = decode_datum(b, off)
        out.append(d)
    return out


def encode_row_value(col_ids: list[int], datums: list[tuple[int, object]]) -> bytes:
    """Row value: alternating (col_id as varint-int datum, value datum) pairs —
    the reference's datum-v1 row layout (codec/table.rs)."""
    out = bytearray()
    for cid, (flag, value) in zip(col_ids, datums):
        encode_datum(out, INT_FLAG, cid)
        encode_datum(out, flag, value)
    return bytes(out)


def decode_row_value(b: bytes) -> dict[int, Datum]:
    ds = decode_datums(b)
    if len(ds) % 2 != 0:
        raise ValueError("odd number of datums in row")
    out = {}
    for i in range(0, len(ds), 2):
        if ds[i].flag != INT_FLAG:
            raise ValueError("row col id must be int datum")
        out[ds[i].value] = ds[i + 1]
    return out
