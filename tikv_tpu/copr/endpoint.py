"""Coprocessor endpoint: parse, route, execute.

Re-expression of ``src/coprocessor/endpoint.rs`` (:45 Endpoint, :144
parse_request_and_check_memory_locks, :392/:459/:486 unary path): takes a
coprocessor request (DAG over key ranges at a start_ts), obtains a snapshot
from the engine, and runs the plan — on the **device path** when the DAG is
eligible (the plugin-boundary gating from BASELINE.json), else the CPU batch
pipeline.  A response cache keyed by (region, data version) serves repeated
requests and backs the columnar block cache.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..analysis import bufsan as _bufsan
from ..storage.kv import Engine
from ..storage.mvcc import Statistics
from ..util import trace
from . import jax_eval
from .cache import ColumnBlockCache, CopCache
from .dag import (
    ENC_TYPE_CHUNK,
    BatchExecutorsRunner,
    DagRequest,
    SelectResponse,
    negotiate_encode_type,
)
from .executors import MvccScanSource
from .mvcc_batch import MvccBatchScanSource

REQ_TYPE_DAG = 103
REQ_TYPE_ANALYZE = 104
REQ_TYPE_CHECKSUM = 105

_MESH_UNCHECKED = object()  # sentinel: DAG not yet probed for mesh eligibility

# server.py's wire-stage buckets (tikv_wire_stage_seconds): the coprocessor
# response-encode observation below must create the series with the SAME
# bucket layout when the endpoint runs before the TCP server imports
_WIRE_STAGE_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1,
                      0.5, 1, 5)


@dataclass
class CoprRequest:
    """coppb.Request equivalent."""

    tp: int
    dag: DagRequest
    ranges: list[tuple[bytes, bytes]]
    start_ts: int
    context: dict = field(default_factory=dict)  # region_id, epoch...


class CoprResponse:
    """coppb.Response equivalent.

    ``data`` is the canonical payload bytes (every in-process consumer and
    byte-identity compare).  TypeChunk responses additionally carry
    ``data_parts`` — the unjoined buffer list from
    ``SelectResponse.encode_parts`` — and ``data`` joins LAZILY, so the
    wire path ships each large column slab as its own ``sendmsg`` iovec
    without ever paying the join (docs/wire_path.md)."""

    __slots__ = ("_data", "data_parts", "encode_type", "from_device",
                 "from_cache", "metrics")

    def __init__(self, data: bytes | None = None, from_device: bool = False,
                 from_cache: bool = False, metrics: dict | None = None,
                 data_parts: list | None = None, encode_type: int = 0):
        assert data is not None or data_parts is not None
        self._data = data
        self.data_parts = data_parts
        self.encode_type = encode_type
        self.from_device = from_device
        self.from_cache = from_cache
        self.metrics = metrics if metrics is not None else {}

    @property
    def data(self) -> bytes:
        if self._data is None:
            self._data = b"".join(bytes(p) for p in self.data_parts)
        return self._data


def resolve_encode_type(req: CoprRequest) -> None:
    """Entry-gate encoding negotiation: a TypeChunk request whose plan
    cannot chunk-encode downgrades IN PLACE to its datum twin — a datum
    response with a counted cause, never an error.  Idempotent (the twin's
    encode_type is datum), called at every serving entry (service parse,
    endpoint unary/batch, scheduler admission) so no path can reach an
    evaluator with an unsupported chunk plan."""
    dag = req.dag
    if dag is None or dag.encode_type != ENC_TYPE_CHUNK:
        return
    eff, cause = negotiate_encode_type(dag)
    if cause is None:
        return
    req.dag = eff
    ctx = req.context if req.context is not None else {}
    req.context = ctx
    if "chunk_declined" not in ctx:
        ctx["chunk_declined"] = cause
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_wire_chunk_total",
            "TypeChunk response negotiation, by outcome (cause on declines)",
        ).inc(outcome="decline", cause=cause)


def stale_read_ctx(req: CoprRequest) -> dict | None:
    """Effective stale-read context for admission and snapshotting: the DAG
    executes its MVCC read at ``req.start_ts``, so the watermark check must
    cover start_ts even when the client declared a lower ``read_ts`` —
    otherwise a lagging replica would admit a request whose scan then reads
    above the watermark (a typed DataNotReady here, not a tripped pairing
    invariant in the region cache)."""
    ctx = req.context or None
    if not ctx or not ctx.get("stale_read"):
        return ctx
    read_ts = ctx.get("read_ts")
    if read_ts is None or int(read_ts) < req.start_ts:
        ctx = dict(ctx, read_ts=req.start_ts)
    return ctx


class Endpoint:
    def __init__(
        self,
        engine: Engine,
        enable_device: bool = True,
        block_cache: CopCache | None = None,
        concurrency_manager=None,
        slow_log=None,
        mesh=None,
        feature_gate=None,
        enable_region_cache: bool = True,
        region_cache=None,
        sched_config=None,
        block_rows: int | None = None,
        shard_cache: bool = True,
        write_through: bool = True,
        encode_columns: bool = True,
        breaker=None,
        breaker_config=None,
        shadow_sample: int | None = None,
        overload=None,
        overload_config=None,
        cost_router=None,
    ):
        from .breaker import DeviceCircuitBreaker
        from .tracker import SlowLog

        self.engine = engine
        self.enable_device = enable_device
        # device block geometry: evaluators pad every block to this row
        # count, so small-region deployments (many regions per store) should
        # size it near the region row count — a 4k-row region padded to the
        # 64k default wastes 16x the compute on every backend.  None keeps
        # the jax_eval default.
        self.block_rows = block_rows
        # device-resident per-region column cache with delta apply (region
        # requests carrying region_epoch + apply_index in the context skip
        # scan+decode entirely on repeat reads); None = disabled.  With a
        # multi-device mesh the cache runs SHARDED: images placed on owner
        # devices so warm serving uses every chip (docs/mesh_serving.md).
        # shard_cache=False is the kill switch: no sharded placement AND no
        # sharded warm routing (unary or scheduler) — PR-2 behavior exactly
        self.shard_cache = shard_cache
        if region_cache is not None:
            self.region_cache = region_cache
        elif enable_region_cache:
            from .region_cache import RegionColumnCache

            # write_through=False is the kill switch for the raft-apply
            # delta intake (docs/write_path.md): warm reads under writes
            # then always repair through scan_delta
            self.region_cache = RegionColumnCache(
                block_rows=block_rows,
                mesh=mesh if shard_cache else None,
                write_through=write_through,
                # compressed residency (docs/compressed_columns.md): images
                # encode at fill and the budget counts ENCODED bytes —
                # encode_columns=False is the kill switch
                encode_columns=encode_columns,
                # bind the cache to THIS engine's write-through stream now —
                # a raft engine exposes its store engine's identity; a plain
                # local engine binds None (direct notify callers, tests)
                data_token=getattr(engine, "data_token", None),
            )
        else:
            self.region_cache = None
        # version-gated rollout (feature_gate.rs:14): the gate is the hard
        # floor under the enable_device/mesh/batch-fusion switches — a
        # mixed-version cluster keeps device serving off until every store
        # can speak it.  None = ungated (tests, embedded use).
        self.feature_gate = feature_gate
        self.cop_cache = block_cache or CopCache()
        self.cm = concurrency_manager
        self.slow_log = slow_log or SlowLog()
        self._evaluators: dict = {}
        # multi-device serving: a (regions × groups) jax.sharding.Mesh shards
        # eligible aggregation DAGs' row blocks across devices (scale-out
        # analog of region sharding); single-device when None or 1 device
        self.mesh = mesh
        self._mesh_runners: dict = {}
        # device-path failures observed (CPU fallback taken): a permanently
        # broken device shows up here instead of only as from_device=False
        self.device_fallbacks = 0
        self.last_device_error: str | None = None
        # device-path circuit breaker (docs/robustness.md): repeated faults
        # on a serving path (unary/zone/fused/xregion/mesh) trip THAT path
        # to its fallback for a cooldown, with half-open probes — one flaky
        # path stops re-paying its failure latency on every request.  The
        # scheduler and the zone evaluator consult the same instance.
        self.breaker = breaker or DeviceCircuitBreaker(breaker_config)
        # unified read scheduler (scheduler.py): cross-region continuous
        # batching over the region column cache.  handle_batch always routes
        # through it; start() turns on the continuous unary lanes.
        from .scheduler import CoprReadScheduler

        self.scheduler = CoprReadScheduler(self, sched_config)
        # integrity plane (docs/integrity.md): deterministic shadow-read
        # sampling of warm device serves (default 1/256, TIKV_TPU_SHADOW_SAMPLE
        # env; 0 = off, 1 = verify every warm serve) + the SDC scrubber —
        # constructed unstarted; standalone servers start the cadence
        from .integrity import IntegrityScrubber, ShadowSampler

        self.shadow = ShadowSampler(shadow_sample)
        self.scrubber = (
            IntegrityScrubber(self.region_cache, engine)
            if self.region_cache is not None else None
        )
        # overload control plane (docs/robustness.md "Overload"): per-tenant
        # quota admission + lane clamping in the scheduler, HBM partitions
        # in the region cache, CPU fallback on the memory-pressure ladder's
        # last rung.  None = no admission policy (historical behavior).
        if overload is not None:
            self.overload = overload
        elif overload_config is not None:
            from .overload import OverloadControl

            self.overload = OverloadControl(
                overload_config, region_cache=self.region_cache)
        else:
            self.overload = None
        # cost-based path router (docs/cost_router.md): picks the cheapest
        # measured path per plan signature, bounded explore, strict static
        # fallback.  None (the library default) means the static ladder
        # stands untouched; the standalone server wires a default-on router
        # (kill switch: TIKV_TPU_COST_ROUTER=0 / --no-cost-router — the
        # router still answers, with reason="kill_switch" and the static
        # head, byte- and path-identical to the pre-router ladder).
        self.cost_router = cost_router
        if cost_router is not None and cost_router.delta_sink is None:
            # chosen-vs-best deltas feed the overload AdaptiveController so
            # admission tightening and path choice share evidence (PR 15)
            cost_router.delta_sink = self._note_route_delta
        # geometry auto-tuner attach point: the standalone server parks its
        # GeometryTuner here so /debug/cost_router shows tuner state next
        # to the decisions it reacted to
        self.geometry_tuner = None

    def _encode_response(self, resp: SelectResponse):
        """SelectResponse -> (frame parts, encode_type): the one response
        serialization point of the device/CPU unary paths, timed into the
        wire-stage histogram (stage=copr_encode) so response assembly stays
        attributable next to decode/route/execute/encode
        (docs/wire_path.md)."""
        import time as _time

        from ..util.metrics import REGISTRY

        t0 = _time.perf_counter()
        parts = resp.encode_parts()
        REGISTRY.histogram(
            "tikv_wire_stage_seconds",
            "Wire-path time per served frame, by stage",
            buckets=_WIRE_STAGE_BUCKETS,
        ).observe(_time.perf_counter() - t0, stage="copr_encode")
        return parts, resp.encode_type

    def handle_request(self, req: CoprRequest) -> CoprResponse:
        """Instrumented entry: every path (device, CPU fallback, analyze,
        checksum) lands in tikv_coprocessor_request_* exactly once."""
        import time as _time

        from ..util.metrics import REGISTRY
        from ..util.retry import DeadlineExceeded, deadline_from_context

        resolve_encode_type(req)

        # shed expired work at the LAST entry gate: every fallback route
        # (scheduler direct serve, per-slot batch re-serve, scheduler-off
        # unary service) funnels through here, so an expired request can
        # never reach a snapshot or a device dispatch
        dl = deadline_from_context(req.context)
        if dl is not None and _time.monotonic() >= dl:
            REGISTRY.counter(
                "tikv_coprocessor_deadline_expired_total",
                "Requests shed because their deadline expired, by detection point",
            ).inc(at="endpoint")
            raise DeadlineExceeded("deadline expired before serving")

        t0 = _time.perf_counter()
        with trace.span("copr.handle", tp=req.tp,
                        region=(req.context or {}).get("region_id")) as sp:
            resp = self._handle_request_inner(req)
            md = resp.metrics or {}
            if sp:
                # the tracker's phase breakdown rides the request's span so
                # the slow log and the trace tell one story (docs/tracing.md)
                sp.tag(from_device=resp.from_device,
                       from_cache=resp.from_cache,
                       **{k: md[k] for k in
                          ("schedule_wait_ms", "snapshot_ms", "handle_ms",
                           "total_ms", "scanned_keys", "region_cache")
                          if k in md})
        REGISTRY.counter(
            "tikv_coprocessor_request_total", "Coprocessor requests, by type/path"
        ).inc(tp=str(req.tp), path="device" if resp.from_device else "cpu")
        REGISTRY.histogram(
            "tikv_coprocessor_request_duration_seconds", "Coprocessor latency"
        ).observe(md.get("total_s", _time.perf_counter() - t0), tp=str(req.tp))
        if resp.from_cache:
            REGISTRY.counter(
                "tikv_coprocessor_cache_hit_total",
                "Requests answered from the HBM-pinned block cache",
            ).inc()
        return resp

    def _handle_request_inner(self, req: CoprRequest) -> CoprResponse:
        from .tracker import Tracker

        from ..util.failpoint import fail_point

        fail_point("coprocessor_parse_request")
        tracker = Tracker(f"copr tp={req.tp} region={req.context.get('region_id') if req.context else None}")
        if req.tp == REQ_TYPE_ANALYZE:
            return self._tracked(tracker, self._handle_analyze, req)
        if req.tp == REQ_TYPE_CHECKSUM:
            return self._tracked(tracker, self._handle_checksum, req)
        if req.tp != REQ_TYPE_DAG:
            raise ValueError(f"unsupported coprocessor request type {req.tp}")
        if self.cm is not None:
            from ..storage.txn_types import Key

            for start, end in req.ranges:
                self.cm.read_range_check(Key.from_raw(start), Key.from_raw(end), req.start_ts)
        tracker.on_schedule()
        # chaos/regression hook INSIDE the tracked window (the parse
        # failpoint above fires before the tracker starts): a seeded
        # sleep here inflates measured serve latency — what the
        # observatory floor gate's regression test injects
        fail_point("coprocessor_serve")
        with trace.span("copr.snapshot"):
            snap = self.engine.snapshot(stale_read_ctx(req))
        tracker.on_snapshot_finished()
        # follower stale serving (docs/stale_reads.md): the snapshot itself
        # says whether it came off the stale path — counted per serving
        # path below so operators see read traffic scale with replicas
        stale_snap = bool(getattr(snap, "stale", False))
        use_device = False
        if self.device_enabled():
            decline = jax_eval.decline_cause(req.dag)
            use_device = decline is None
            if decline is not None:
                from .dag import Join, Limit, Projection, TopN

                if any(isinstance(e, (Limit, TopN, Join, Projection))
                       for e in req.dag.executors[1:]):
                    # Limit/TopN plans never fall to the CPU silently: the
                    # early-exit tiling work (docs/zone_maps.md) made them
                    # device-eligible, so a decline is a named, counted
                    # event; Join/Projection plans likewise (the join rung
                    # below may still serve them — docs/device_join.md)
                    from . import encoding as _encoding

                    _encoding.count_decline("device_plan", decline)
        if use_device and self.overload is not None \
                and not self.overload.allow_device(req.context):
            # memory-pressure degradation ladder, last rung (overload.py):
            # this tenant's HBM partition would not fit even after eviction
            # and pin demotion — serve its work on the CPU pipeline until
            # the cooldown lifts, leaving other tenants' warm sets alone
            from .tracker import count_path_fallback

            count_path_fallback("unary", "tenant_pressure")
            use_device = False
        if use_device and not self.breaker.allow("unary"):
            # tripped: repeated unary device faults — serve straight off the
            # CPU pipeline until a half-open probe restores the path
            from .tracker import count_path_fallback

            count_path_fallback("unary", "breaker_open")
            use_device = False
        # cost-based routing (docs/cost_router.md) AFTER the admission
        # gates: overload and breaker verdicts are overrides, not cost
        # preferences — the router only picks among paths admission allows
        route = None
        if use_device:
            route = self._route_for(req)
            if route is not None and route.path == "cpu":
                # measured: the host wins this plan shape (Tailwind-style
                # routing around the accelerator), or a budgeted cold
                # probe keeping the CPU profile fresh
                from .tracker import count_path_fallback

                count_path_fallback("unary", "cost_route")
                use_device = False
        if use_device:
            cache = None
            ev = None
            try:
                cache, rc_outcome = self._region_cache_for(req, snap, tracker)
                if cache is None:
                    cache = self._block_cache_for(req)
                # cold path with a mesh: MeshServingRunner shards the MVCC
                # scan's super-blocks; warm path with a mesh: the cache is
                # ALREADY sharded (RegionColumnCache places images on owner
                # devices), so cached serving routes through the sharded
                # cross-region launcher below — the PR-2 "mesh bypass due to
                # filled cache" is gone
                ev = None
                if cache is None:
                    ev = self._mesh_evaluator_for(req.dag)
                if ev is None:
                    ev = self._evaluator_for(req.dag)
                src = None
                if cache is None or not cache.filled:
                    src = MvccBatchScanSource(snap, req.start_ts, req.ranges)
                resp = None
                want_mesh = route is None or route.path == "mesh"
                if src is None and want_mesh and self._mesh_would_serve(req.dag):
                    resp = self._run_sharded_cached(ev, cache)
                if resp is None:
                    # routed zone/unary steer the evaluator's rung choice;
                    # set/cleared around run — a concurrent mis-read only
                    # picks a different byte-identical warm rung
                    ev.route_hint = (route.path if route is not None
                                     and route.path in ("zone", "unary")
                                     else None)
                    try:
                        resp = ev.run(src, cache=cache)
                    finally:
                        ev.route_hint = None
                parts, enc_tp = self._encode_response(resp)
                data = None
                from_device = True
                # shadow-read verification (docs/integrity.md): a sampled
                # warm image-backed serve re-executes on the CPU oracle and
                # byte-compares — a mismatch quarantines the image and the
                # CPU bytes serve, so a sampled request never returns
                # corrupted derived state.  The oracle runs the SAME
                # negotiated encoding (req.dag carries it), so chunk
                # responses byte-compare chunk bytes.
                if (rc_outcome in ("hit", "delta", "wt_delta")
                        and self.shadow.pick("unary")):
                    fixed = self.shadow_compare(
                        req, snap, b"".join(bytes(p) for p in parts), "unary")
                    if fixed is not None:
                        data, parts = fixed, None
                        from_device = False
                scanned = src.stats.write.processed_keys if src is not None else 0
                m = tracker.on_finish(scanned_keys=scanned, from_device=from_device)
                rows = (cache.total_rows
                        if cache is not None and cache.filled and src is None
                        else scanned)
                self._record_obs(req, tracker,
                                 getattr(resp, "_obs_path", "unary"),
                                 getattr(resp, "_obs_encoding", "plain"),
                                 rows, ev=ev, resp=resp)
                self.slow_log.observe(tracker)
                from_cache = (from_device
                              and cache is not None and cache.filled and src is None
                              and rc_outcome not in ("miss", "too_big"))
                self.breaker.record_success("unary")
                if stale_snap:
                    self.count_follower_read("device" if from_device else "cpu")
                return CoprResponse(
                    data, from_device=from_device,
                    from_cache=from_cache,
                    metrics=m.to_dict(),
                    data_parts=parts, encode_type=enc_tp,
                )
            except Exception as exc:
                from .integrity import IntegrityMismatch

                if isinstance(exc, IntegrityMismatch):
                    raise  # TIKV_TPU_INTEGRITY_FATAL: surface, never mask
                # device/runtime failure (compiler, tunnel, OOM): the CPU
                # pipeline is the correctness oracle and always available —
                # re-run there off the same immutable snapshot rather than
                # surfacing an accelerator error to the client
                if cache is not None and not cache.filled:
                    # a partially-filled block cache would double-append on
                    # the next request and serve wrong data forever; the
                    # failed run may have pinned arrays — clear WITH the
                    # observatory's pin accounting
                    cache.clear_blocks()
                self.device_fallbacks += 1
                self.last_device_error = repr(exc)
                self.breaker.record_failure("unary")
                cur = trace.current()
                if cur is not None:
                    cur.tag(device_fallback=repr(exc))
                from ..util.metrics import REGISTRY

                from . import observatory as _obs
                from .tracker import count_path_fallback

                count_path_fallback("unary", "device_error")
                _obs.OBSERVATORY.record_decline(
                    getattr(ev, "obs_sig", None), "unary", "device_error")
                REGISTRY.counter(
                    "tikv_coprocessor_device_fallback_total",
                    "Device-path failures that re-ran on the CPU pipeline",
                ).inc()
        resp = self._try_device_join(req, snap, tracker, stale_snap)
        if resp is not None:
            return resp
        resp = self._try_dict_rewrite(req, snap, tracker, stale_snap)
        if resp is not None:
            return resp
        stats = Statistics()
        src = MvccScanSource(snap, req.start_ts, req.ranges, statistics=stats)
        with trace.span("copr.cpu"):
            resp = BatchExecutorsRunner(req.dag, src).handle_request()
        m = tracker.on_finish(scanned_keys=stats.write.processed_keys, from_device=False)
        self._record_obs(req, tracker, "cpu", "plain",
                         stats.write.processed_keys)
        self.slow_log.observe(tracker)
        if stale_snap:
            self.count_follower_read("cpu")
        parts, enc_tp = self._encode_response(resp)
        return CoprResponse(None, from_device=False, metrics=m.to_dict(),
                            data_parts=parts, encode_type=enc_tp)

    def _build_cache_for(self, req: CoprRequest, snap, join):
        """Resolve a Join's build-side region image.  The build context
        (region id / epoch / apply index) rides the Join descriptor — the
        probe snapshot cannot vouch for a DIFFERENT region's identity, so
        a missing context is a named decline, never a guess."""
        ctx = join.build_context
        if ctx is None:
            return None, "no_build_context"
        context = dict(ctx)
        if req.context and "tenant" in req.context:
            # one request, one tenant: the build image bills the same
            # HBM partition as the probe's
            context.setdefault("tenant", req.context["tenant"])
        cache, outcome, _delta = self.region_cache.serve(
            snap, context, join.build[0].columns_info, join.build_ranges,
            req.start_ts)
        return cache, outcome

    def _try_device_join(self, req: CoprRequest, snap, tracker, stale_snap):
        """Device join rung (docs/device_join.md): a ``[TableScan, Join,
        ...]`` plan whose probe AND build region images are warm serves as
        ONE dispatch over both images — rank-space joins over shared
        sorted dictionaries, radix-hash joins over int key lanes — with
        payload columns late-materialized only for surviving row pairs.
        Every shape the kernels cannot cover (outer joins, filtered probe
        sides, unsorted dictionaries, exotic key types) is a per-cause
        counted decline to the CPU oracle, never a silent fallback."""
        from . import encoding as _encoding
        from . import observatory as _obs
        from .dag import Join

        dag = req.dag
        if (self.region_cache is None or not self.device_enabled()
                or dag is None
                or not any(isinstance(e, Join) for e in dag.executors)):
            return None

        def declined(cause: str):
            _encoding.count_join("device", "declined")
            _encoding.count_decline("join", cause)
            try:
                sig, _desc = _obs.dag_sig(dag)
            except Exception:  # noqa: BLE001 — profiling must not fail serving
                sig = None
            _obs.OBSERVATORY.record_decline(sig, "join", cause)
            return None

        from . import jax_join as _jax_join

        try:
            _probe_scan, join, _rest = _jax_join.analyze_plan(dag)
        except _jax_join.JoinDecline as d:
            return declined(d.cause)
        if self.overload is not None \
                and not self.overload.allow_device(req.context):
            from .tracker import count_path_fallback

            count_path_fallback("unary", "tenant_pressure")
            return None
        if not self.breaker.allow("unary"):
            from .tracker import count_path_fallback

            count_path_fallback("unary", "breaker_open")
            return None
        # cost routing among the join ladder (docs/cost_router.md):
        # candidate_paths declares rank/hash/cpu for join plans, so the
        # router prices the measured rank vs hash vs CPU profiles
        route = self._route_for(req)
        prefer = (route.path if route is not None
                  and route.path in ("rank", "hash", "cpu") else None)
        if prefer == "cpu":
            from .tracker import count_path_fallback

            count_path_fallback("unary", "cost_route")
            _encoding.count_join("cpu", "routed")
            self.breaker.release_probe("unary")
            return None
        try:
            probe_cache, rc_outcome = self._region_cache_for(req, snap, tracker)
            if (probe_cache is None or not probe_cache.filled
                    or not probe_cache.blocks):
                self.breaker.release_probe("unary")
                return declined("probe_cold")
            build_cache, b_outcome = self._build_cache_for(req, snap, join)
            if b_outcome == "no_build_context":
                self.breaker.release_probe("unary")
                return declined("no_build_context")
            if (build_cache is None or not build_cache.filled
                    or not build_cache.blocks):
                self.breaker.release_probe("unary")
                return declined("build_cold")
            try:
                resp, path, stats = _jax_join.serve(
                    dag, probe_cache, build_cache, prefer=prefer)
            except _jax_join.JoinDecline as d:
                self.breaker.release_probe("unary")
                return declined(d.cause)
            parts, enc_tp = self._encode_response(resp)
            data = None
            from_device = True
            warm = ("hit", "delta", "wt_delta")
            if ((rc_outcome in warm or b_outcome in warm)
                    and self.shadow.pick("unary")):
                fixed = self.shadow_compare(
                    req, snap, b"".join(bytes(p) for p in parts), "unary")
                if fixed is not None:
                    data, parts = fixed, None
                    from_device = False
            _encoding.count_join(path, "served")
            m = tracker.on_finish(scanned_keys=0, from_device=from_device)
            resp._obs_join = (stats["build_rows"], stats["probe_rows"],
                              stats["out_rows"])
            self._record_obs(req, tracker, path, "encoded",
                             stats["probe_rows"] + stats["build_rows"],
                             resp=resp)
            self.slow_log.observe(tracker)
            self.breaker.record_success("unary")
            if stale_snap:
                self.count_follower_read("device" if from_device else "cpu")
            cold = ("miss", "too_big")
            return CoprResponse(
                data, from_device=from_device,
                from_cache=(from_device and rc_outcome not in cold
                            and b_outcome not in cold),
                metrics=m.to_dict(), data_parts=parts, encode_type=enc_tp)
        except Exception as exc:  # noqa: BLE001 — CPU pipeline always serves
            from .integrity import IntegrityMismatch

            if isinstance(exc, IntegrityMismatch):
                raise  # TIKV_TPU_INTEGRITY_FATAL: surface, never mask
            self.device_fallbacks += 1
            self.last_device_error = repr(exc)
            self.breaker.record_failure("unary")
            from .tracker import count_path_fallback

            count_path_fallback("unary", "device_error")
            _encoding.count_join("device", "error")
            return None

    def _try_dict_rewrite(self, req: CoprRequest, snap, tracker, stale_snap):
        """Dictionary code-space serving rung (docs/compressed_columns.md):
        a DAG whose ONLY device blocker is bytes predicates over
        dictionary-resident columns rewrites those predicates into the warm
        image's code space (equality/IN through the bytes→code map, ranges
        through searchsorted ranks on a SORTED dictionary) and serves on
        the device — no string ever materializes.  Declines — cold region,
        unstable/unsorted dictionary, a plan shape the rewrite can't cover —
        are counted per-cause and fall to the CPU pipeline; served bytes
        ride the same shadow-read sampling as every warm device serve."""
        from . import encoding as _encoding

        if (self.region_cache is None or not self.device_enabled()
                or not _encoding.dict_rewrite_probe(req.dag)):
            return None
        if req.dag.encode_type == ENC_TYPE_CHUNK:
            # the rewrite rung is DATUM-ONLY: the rewritten plan's schema
            # declares a dict column LONGLONG while the served column still
            # carries bytes, and the schema-driven chunk encoder would emit
            # raw dictionary codes a client decoding against its own plan
            # cannot read (the oracle would then false-quarantine a healthy
            # image on the shadow mismatch).  The CPU pipeline below serves
            # the chunk bytes correctly.
            _encoding.count_decline("rewrite", "chunk_encoding")
            return None
        if not self.breaker.allow("unary"):
            from .tracker import count_path_fallback

            count_path_fallback("unary", "breaker_open")
            return None
        try:
            cache, rc_outcome = self._region_cache_for(req, snap, tracker)
            if cache is None or not cache.filled or not cache.blocks:
                _encoding.count_rewrite("cold")
                _encoding.count_decline("rewrite", "cold_region")
                self.breaker.release_probe("unary")
                return None
            new_dag, info = _encoding.rewrite_dag_for_dict(req.dag, cache.blocks)
            if new_dag is None or not jax_eval.supports(new_dag):
                _encoding.count_rewrite("declined")
                _encoding.count_decline(
                    "rewrite",
                    info if isinstance(info, str) else "unsupported_plan")
                self.breaker.release_probe("unary")
                return None
            ev = self._evaluator_for(new_dag)
            resp = ev.run(None, cache=cache)
            parts, enc_tp = self._encode_response(resp)
            data = None
            from_device = True
            if (rc_outcome in ("hit", "delta", "wt_delta")
                    and self.shadow.pick("unary")):
                fixed = self.shadow_compare(
                    req, snap, b"".join(bytes(p) for p in parts), "unary")
                if fixed is not None:
                    data, parts = fixed, None
                    from_device = False
            _encoding.count_rewrite("served")
            m = tracker.on_finish(scanned_keys=0, from_device=from_device)
            # the rewrite rung serves over resident code lanes — encoded by
            # construction; the sig recorded is the ORIGINAL plan's (what
            # the client sent), not the rewritten one
            self._record_obs(req, tracker, "unary", "encoded",
                             cache.total_rows, resp=resp)
            self.slow_log.observe(tracker)
            self.breaker.record_success("unary")
            if stale_snap:
                self.count_follower_read("device" if from_device else "cpu")
            return CoprResponse(
                data, from_device=from_device,
                # first-touch builds are NOT cache hits — same rule as the
                # main unary path's from_cache accounting
                from_cache=from_device and rc_outcome not in ("miss", "too_big"),
                metrics=m.to_dict(), data_parts=parts, encode_type=enc_tp)
        except Exception as exc:  # noqa: BLE001 — CPU pipeline always serves
            from .integrity import IntegrityMismatch

            if isinstance(exc, IntegrityMismatch):
                raise  # TIKV_TPU_INTEGRITY_FATAL: surface, never mask
            self.device_fallbacks += 1
            self.last_device_error = repr(exc)
            self.breaker.record_failure("unary")
            from .tracker import count_path_fallback

            count_path_fallback("unary", "device_error")
            _encoding.count_rewrite("error")
            return None

    def _record_obs(self, req: CoprRequest, tracker, path: str,
                    encoding: str, rows: int, ev=None, resp=None) -> None:
        """Report one served request into the performance observatory
        (docs/observatory.md) and stamp the serving path + plan sig onto
        the tracker so the slow log pivots into ``ctl.py observatory sig``.
        Must run BEFORE ``slow_log.observe``."""
        from . import observatory as _obs

        if not _obs.OBSERVATORY.enabled:
            # kill switch: skip even the dag_sig walk — a disabled
            # observatory must cost the hot path nothing
            return
        sig = getattr(ev, "obs_sig", "") if ev is not None else ""
        desc = getattr(ev, "obs_desc", "") if ev is not None else ""
        if not sig:
            try:
                sig, desc = _obs.dag_sig(req.dag)
            except Exception:  # noqa: BLE001 — profiling must not fail serving
                return
        tracker.metrics.serve_path = path
        tracker.metrics.plan_sig = sig
        m = tracker.metrics
        # zone-map pruning effectiveness rides the profile (docs/zone_maps.md)
        prune = getattr(resp, "_obs_prune", None) or (0, 0)
        # device-join magnitudes ride it too (docs/device_join.md)
        jn = getattr(resp, "_obs_join", None) or (0, 0, 0)
        _obs.OBSERVATORY.record_serve(
            sig, path, m.total_s, rows=rows, encoding=encoding,
            queue_wait_s=m.schedule_wait_s, trace_id=tracker.trace_id,
            desc=desc, blocks_examined=prune[0], blocks_pruned=prune[1],
            join_build_rows=jn[0], join_probe_rows=jn[1],
            join_out_rows=jn[2])

    def _cpu_bytes(self, req: CoprRequest, snap) -> bytes:
        """The CPU-oracle answer to ``req`` off ``snap`` — the byte-identity
        ground truth every device path is held to."""
        stats = Statistics()
        src = MvccScanSource(snap, req.start_ts, req.ranges, statistics=stats)
        return BatchExecutorsRunner(req.dag, src).handle_request().encode()

    def shadow_compare(self, req: CoprRequest, snap, device_data: bytes,
                       path: str) -> bytes | None:
        """Shadow-read verification core (docs/integrity.md): re-execute a
        sampled warm serve on the CPU oracle off the SAME snapshot and byte
        compare.  Returns None on a match (or an inconclusive oracle error);
        on mismatch the backing image is quarantined, the mismatch counts
        under stage=shadow_read, and the CPU bytes return for the caller to
        serve — zero wrong bytes reach the sampled client."""
        from .integrity import IntegrityMismatch, count_mismatch, integrity_fatal

        # the device answer is an exposure: it is held across the oracle
        # re-execution, and a concurrent fold mutating its backing buffer
        # would turn a true mismatch into a phantom (or mask one)
        _bufsan.export("shadow_read", device_data, site="endpoint.shadow_compare")
        try:
            try:
                cpu = self._cpu_bytes(req, snap)
            except Exception:  # noqa: BLE001 — locks/races: inconclusive, not bad
                self.shadow.note(path, "error")
                return None
        finally:
            _bufsan.release(device_data, site="endpoint.shadow_compare")
        if cpu == device_data:
            self.shadow.note(path, "ok")
            return None
        self.shadow.note(path, "mismatch")
        count_mismatch("shadow_read")
        region_id = (req.context or {}).get("region_id")
        if region_id is None:
            region = getattr(snap, "region", None)
            region_id = getattr(region, "id", None)
        if self.region_cache is not None and region_id is not None:
            self.region_cache.quarantine_region(
                region_id, ranges=req.ranges, stage="shadow_read",
                detail={"path": path},
            )
        if integrity_fatal():
            raise IntegrityMismatch(
                f"shadow read mismatch on region {region_id} path={path}"
            )
        return cpu

    def overload_snapshot(self) -> dict:
        """The /debug/overload + ``ctl.py overload`` view: per-tenant
        bucket levels and effective rates, shed/defer counts, adaptive
        controller state, and HBM partition occupancy."""
        if self.overload is None:
            return {"enabled": False, "wired": False}
        return self.overload.snapshot()

    def integrity_snapshot(self) -> dict:
        """The /debug/integrity + ``ctl.py integrity`` view: per-image
        fingerprints, the quarantine ledger, scrubber cadence/progress, and
        shadow-read sample/mismatch counts."""
        rc = self.region_cache
        out = {
            "enabled": rc is not None,
            "shadow": self.shadow.snapshot(),
            "scrubber": self.scrubber.snapshot() if self.scrubber is not None else None,
        }
        if rc is not None:
            out["fingerprints"] = rc.image_fingerprints()
            out["quarantine"] = list(rc.quarantine_ledger)
        return out

    @staticmethod
    def count_follower_read(path: str) -> None:
        """Follower/stale-served DAG requests, by serving path — the series
        that shows coprocessor read traffic scaling with replica count
        instead of leader count (docs/stale_reads.md)."""
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_follower_read_total",
            "DAG requests served off a stale-read (follower-eligible) "
            "snapshot, by serving path",
        ).inc(path=path)

    def _tracked(self, tracker, handler, req: CoprRequest) -> CoprResponse:
        resp = handler(req, tracker)
        resp.metrics = tracker.on_finish(scanned_keys=tracker.metrics.scanned_keys).to_dict()
        self.slow_log.observe(tracker)
        return resp

    def handle_streaming_request(self, req: CoprRequest, rows_per_stream: int = 1024):
        """Yield CoprResponse frames (endpoint.rs streaming path — always the
        CPU pipeline; the device path answers whole queries)."""
        if req.tp != REQ_TYPE_DAG:
            raise ValueError("streaming supports DAG requests only")
        resolve_encode_type(req)
        snap = self.engine.snapshot(stale_read_ctx(req))
        src = MvccScanSource(snap, req.start_ts, req.ranges, statistics=Statistics())
        # frames flush at whole response chunks — align the chunk size so
        # streams actually split at the requested granularity (on a copy:
        # the caller's DagRequest framing must not change).  The copy keeps
        # the negotiated encoding: large TypeChunk results stream as
        # column-slab frames on the same flush cadence.
        dag = DagRequest(
            executors=req.dag.executors,
            output_offsets=req.dag.output_offsets,
            chunk_rows=min(req.dag.chunk_rows, rows_per_stream),
            encode_type=req.dag.encode_type,
        )
        runner = BatchExecutorsRunner(dag, src)
        for resp in runner.handle_streaming_request(rows_per_stream):
            parts, enc_tp = self._encode_response(resp)
            yield CoprResponse(None, from_device=False, data_parts=parts,
                               encode_type=enc_tp)

    def _handle_analyze(self, req: CoprRequest, tracker=None) -> CoprResponse:
        from . import analyze as az
        from .dag import build_executors
        from .tracker import Tracker

        tracker = tracker or Tracker()
        tracker.on_schedule()
        snap = self.engine.snapshot(stale_read_ctx(req))
        tracker.on_snapshot_finished()
        src = MvccBatchScanSource(snap, req.start_ts, req.ranges)
        executor = build_executors(req.dag, src)
        n_cols = len(executor.schema())
        params = req.context.get("analyze", {}) if req.context else {}
        result = az.analyze_columns(
            executor,
            n_cols,
            sample_size=params.get("sample_size", 10000),
            max_buckets=params.get("max_buckets", 256),
        )
        tracker.metrics.scanned_keys = result.sampled_rows
        out = bytearray()
        from ..util import codec as c

        out += c.encode_var_u64(result.sampled_rows)
        out += c.encode_var_u64(n_cols)
        for ci in range(n_cols):
            h = result.histograms[ci]
            out += c.encode_var_u64(h.ndv)
            out += c.encode_var_u64(len(h.buckets))
            for b in h.buckets:
                out += c.encode_compact_bytes(b.lower)
                out += c.encode_compact_bytes(b.upper)
                out += c.encode_var_u64(b.count)
                out += c.encode_var_u64(b.repeats)
            out += c.encode_var_u64(result.fm_sketches[ci].ndv())
            out += c.encode_var_u64(result.cm_sketches[ci].count)
        return CoprResponse(bytes(out))

    def _handle_checksum(self, req: CoprRequest, tracker=None) -> CoprResponse:
        """MVCC-consistent checksum: the logical rows visible at start_ts
        (checksum.rs scans through the snapshot store), so large values in
        CF_DEFAULT are covered and replicas with different physical version
        histories but identical logical data agree.

        Warm path (docs/integrity.md): a resident region image of exactly
        these ranges carries the XOR-folded per-row crc64 — byte-identical
        to this scan's answer by construction — so ADMIN CHECKSUM over warm
        data costs zero engine reads; anything else falls back to the
        CPU-oracle scan."""
        from . import analyze as az
        from ..storage.mvcc import ForwardScanner
        from ..storage.txn_types import Key
        from ..util.metrics import REGISTRY
        from .tracker import Tracker

        tracker = tracker or Tracker()
        tracker.on_schedule()
        snap = self.engine.snapshot(stale_read_ctx(req))
        tracker.on_snapshot_finished()
        warm = None
        if self.region_cache is not None:
            warm = self.region_cache.checksum_serve(
                snap, self._snap_context(req, snap), req.ranges, req.start_ts
            )
        if warm is not None:
            checksum, total_kvs, total_bytes = warm
            r = {"checksum": checksum, "total_kvs": total_kvs,
                 "total_bytes": total_bytes}
        else:
            kvs = []
            for start, end in req.ranges:
                kvs.extend(
                    ForwardScanner(snap, req.start_ts, Key.from_raw(start), Key.from_raw(end))
                )
            r = az.checksum_range(kvs)
            tracker.metrics.scanned_keys = r["total_kvs"]
        REGISTRY.counter(
            "tikv_coprocessor_checksum_total",
            "Coprocessor Checksum (tp=105) requests, by serving path",
        ).inc(path="warm" if warm is not None else "cold")
        from ..util import codec as c

        out = (
            c.encode_u64(r["checksum"])
            + c.encode_var_u64(r["total_kvs"])
            + c.encode_var_u64(r["total_bytes"])
        )
        return CoprResponse(out, from_cache=warm is not None)

    def handle_batch(self, reqs: list[CoprRequest]) -> list["CoprResponse"]:
        """K coprocessor requests answered together (the batch_coprocessor /
        batch_commands serving shape, kv.rs:891), routed through the unified
        read scheduler (scheduler.py): device-eligible aggregation DAGs fuse
        into as few XLA dispatches as their plan signatures allow — same
        plan across regions stacks into ONE cross-region program over the
        cached region images; different plans over the same region view fuse
        the old way (jax_eval.run_batch_cached).  Anything ineligible falls
        back to per-request handling; responses are byte-identical either
        way."""
        for r in reqs:
            resolve_encode_type(r)
        if len(reqs) >= 2 and self.device_enabled() and self._gate_ok("batch"):
            from ..util.failpoint import fail_point

            fail_point("coprocessor_parse_request")
            return self.scheduler.run_batch(reqs)
        return [self.handle_request(r) for r in reqs]

    def handle_batch_errors(
        self, reqs: list[CoprRequest]
    ) -> tuple[list["CoprResponse | None"], list[BaseException | None]]:
        """``handle_batch`` with per-slot error isolation: returns parallel
        (results, errors) lists instead of raising on the first bad slot, so
        the service layer keeps every computed response when one rider's
        deadline expires in the queue (re-serving the whole batch would
        double the device work the shed was meant to save)."""
        for r in reqs:
            resolve_encode_type(r)
        if len(reqs) >= 2 and self.device_enabled() and self._gate_ok("batch"):
            from ..util.failpoint import fail_point

            fail_point("coprocessor_parse_request")
            return self.scheduler.run_batch(reqs, return_errors=True)
        results: list[CoprResponse | None] = [None] * len(reqs)
        errors: list[BaseException | None] = [None] * len(reqs)
        for i, r in enumerate(reqs):
            try:
                results[i] = self.handle_request(r)
            except Exception as e:  # noqa: BLE001 — per-slot isolation
                errors[i] = e
        return results, errors

    def _evaluator_for(self, dag: DagRequest) -> "jax_eval.JaxDagEvaluator":
        """Reuse compiled evaluators across requests, keyed by plan bytes
        (each holds its jit caches — recompiling per request throws away the
        warm XLA programs)."""
        from ..server import wire
        from .dag_wire import dag_to_wire

        key = wire.dumps(dag_to_wire(dag))
        ev = self._evaluators.get(key)
        if ev is None:
            if self.block_rows is not None:
                ev = jax_eval.JaxDagEvaluator(dag, block_rows=self.block_rows,
                                              breaker=self.breaker)
            else:
                ev = jax_eval.JaxDagEvaluator(dag, breaker=self.breaker)
            self._evaluators[key] = ev
            while len(self._evaluators) > 64:
                self._evaluators.pop(next(iter(self._evaluators)))
        return ev

    def device_enabled(self) -> bool:
        return self.enable_device and self._gate_ok("device")

    def set_enable_device(self, on: bool) -> None:
        """Online toggle (POST /config coprocessor.enable_device)."""
        self.enable_device = bool(on)

    def set_block_rows(self, n: int) -> None:
        """Online geometry change (POST /config coprocessor.block_rows /
        the auto-tuner).  Evaluators pad every block to block_rows and warm
        images were built at the old geometry, so both are dropped: the
        next serve rebuilds at the new size.  Bounds are enforced by
        TikvConfig.validate before this is ever called."""
        n = int(n)
        if n == self.block_rows:
            return
        self.block_rows = n
        self._evaluators.clear()
        self._mesh_runners.clear()
        if self.region_cache is not None:
            self.region_cache.block_rows = n
            for rid in list(self.region_cache.warm_region_ids()):
                self.region_cache.invalidate_region(rid, reason="geometry")

    def _route_for(self, req: CoprRequest):
        """Consult the cost router for this request's execution path
        (docs/cost_router.md).  None means routing is unavailable (sig
        walk failed) — the static ladder stands."""
        router = self.cost_router
        if router is None:
            return None
        from . import encoding as _encoding
        from . import observatory as _obs

        try:
            sig, desc = _obs.dag_sig(req.dag)
        except Exception:  # noqa: BLE001 — routing must not fail serving
            return None
        cands = _encoding.candidate_paths(
            req.dag, device_ok=True,
            mesh_ok=self._mesh_would_serve(req.dag))
        return router.route(sig, cands, desc=desc)

    def _note_route_delta(self, delta_ms: float, best_ms: float | None) -> None:
        if self.overload is not None:
            self.overload.note_route_delta(delta_ms, best_ms)

    def cost_router_snapshot(self) -> dict:
        """The ``/debug/cost_router`` + ``ctl.py cost-router`` view: router
        decision counts/ring and the geometry tuner's knobs, in-flight
        change, and keep/revert history."""
        if self.cost_router is None:
            return {"enabled": False, "wired": False}
        out = {"router": self.cost_router.snapshot()}
        if self.geometry_tuner is not None:
            out["tuner"] = self.geometry_tuner.snapshot()
        return out

    def _gate_ok(self, what: str) -> bool:
        if self.feature_gate is None:
            return True
        from ..pd.feature_gate import BATCH_FUSION, DEVICE_COPROCESSOR, MESH_SERVING

        feat = {"device": DEVICE_COPROCESSOR, "mesh": MESH_SERVING,
                "batch": BATCH_FUSION}[what]
        return self.feature_gate.can_enable(feat)

    def _run_sharded_cached(self, ev, cache):
        """Warm cached serving THROUGH the mesh: run the plan over the
        image's device-local shards via the sharded cross-region launcher
        (one region = one slot; a block-spread huge region uses every chip).
        Returns the SelectResponse, or None on a documented decline — an
        aggregate with no mesh merge rule, unstable group dictionaries —
        which serves per-request on the single-device warm path.  Real
        device failures count against the MESH breaker path and decline to
        the single-device warm path (which can still serve the bytes) —
        tripping every unary request to CPU for one bad collective would
        throw away a working single-device fallback."""
        from ..parallel.mesh import mesh_mergeable
        from ..util.metrics import REGISTRY
        from . import jax_eval as _je
        from .tracker import count_path_fallback

        if not self.shard_cache:
            return None
        if ev.plan.agg is None or not mesh_mergeable(ev.device_aggs):
            count_path_fallback("mesh", "no_merge_rule")
            return None
        if not self.breaker.allow("mesh"):
            count_path_fallback("mesh", "breaker_open")
            return None
        # A single-owner image still routes here on purpose: SPMD means the
        # other devices scan only zero-pad slabs (same wall time as the
        # owner) plus a tiny-carry collective — while the single-device
        # warm path would REBUILD a full default-device pin, paying the
        # whole-image transfer the owner placement exists to avoid.
        try:
            pending = _je.launch_xregion_sharded(ev, [cache], self.mesh)
            resp = pending.finalize()[0]
        except ValueError:
            # documented decline (no merge rule surfaced late, empty blocks)
            self.breaker.release_probe("mesh")
            count_path_fallback("mesh", "ineligible")
            return None
        except Exception as exc:  # noqa: BLE001 — single-device path serves
            self.breaker.record_failure("mesh")
            self.device_fallbacks += 1
            self.last_device_error = repr(exc)
            count_path_fallback("mesh", "device_error")
            return None
        self.breaker.record_success("mesh")
        resp._obs_path = "mesh"  # observatory path marker
        REGISTRY.counter(
            "tikv_coprocessor_mesh_cache_hit_total",
            "Warm cached requests served mesh-sharded (replaces the PR-2 "
            "mesh_bypass{reason=cache})",
        ).inc()
        return resp

    def _mesh_would_serve(self, dag: DagRequest) -> bool:
        """True only when the mesh path would actually take this DAG (mesh
        present with real devices, gate open, AND the plan is mesh-runnable)
        — the sharded warm route must not probe plans the mesh would have
        declined anyway."""
        if (self.mesh is None or getattr(self.mesh, "size", 1) <= 1
                or getattr(self.mesh, "devices", None) is None):
            return False
        from .dag import Aggregation

        # cheap pre-filter: the mesh runner only takes aggregation DAGs, so
        # cached scan/selection traffic (the common warm path) never pays
        # the runner-construction probe below
        if not any(isinstance(e, Aggregation) for e in dag.executors):
            return False
        try:
            return self._mesh_evaluator_for(dag) is not None
        except Exception:  # noqa: BLE001 — a broken mesh backend is "no"
            return False

    def _mesh_evaluator_for(self, dag: DagRequest):
        """A MeshServingRunner when the mesh has >1 device and the DAG is an
        eligible aggregation; None routes to the single-device evaluator."""
        if self.mesh is None or self.mesh.size <= 1 or not self._gate_ok("mesh"):
            return None
        from ..parallel.mesh import MeshServingRunner
        from ..server import wire
        from .dag_wire import dag_to_wire

        key = wire.dumps(dag_to_wire(dag))
        runner = self._mesh_runners.get(key, _MESH_UNCHECKED)
        if runner is _MESH_UNCHECKED:
            try:
                runner = MeshServingRunner(dag, self.mesh)
            except ValueError:
                runner = None  # not an aggregation DAG — cached so repeat
                # requests skip re-probing (single-device path)
            self._mesh_runners[key] = runner
            while len(self._mesh_runners) > 16:
                self._mesh_runners.pop(next(iter(self._mesh_runners)))
        return runner

    def _region_cache_for(self, req: CoprRequest, snap, tracker):
        """Resolve the request against the region column cache.  Returns
        (filled block cache | None, outcome) and stamps the tracker with the
        outcome + delta size so responses carry the cache behavior."""
        if self.region_cache is None:
            return None, ""
        from .dag import TableScan

        execs = req.dag.executors if req.dag is not None else []
        if not execs or type(execs[0]) is not TableScan:
            return None, ""
        context = self._snap_context(req, snap)
        apply_index = context.get("apply_index")
        rp = getattr(snap, "read_progress", None)
        if rp is not None:
            # RegionReadProgress pairing invariant (docs/stale_reads.md): a
            # stale snapshot's claimed apply_index sits at/above the pair's
            # required index (raftkv refuses otherwise) and the DAG reads
            # at/below the paired watermark — which is exactly why the
            # (region_id, epoch, apply_index) image key stays correct for
            # follower warm serving: the image can never claim data the
            # watermark hasn't covered
            assert apply_index is not None and apply_index >= rp[1], \
                f"stale snapshot apply_index {apply_index} below required {rp[1]}"
            assert req.start_ts <= rp[0], \
                f"stale DAG read at {req.start_ts} above resolved ts {rp[0]}"
        cache, outcome, delta_rows = self.region_cache.serve(
            snap, context, execs[0].columns_info, req.ranges, req.start_ts
        )
        if outcome != "off":
            tracker.metrics.region_cache = outcome
            tracker.metrics.region_cache_delta_rows = delta_rows
        return cache, outcome

    @staticmethod
    def _snap_context(req: CoprRequest, snap) -> dict:
        """The request context enriched from the snapshot: a raft
        RegionSnapshot carries its own identity and data version — serving
        paths need no context plumbing; explicit context still wins (tests,
        embedded use over plain engines)."""
        context = dict(req.context or {})
        region = getattr(snap, "region", None)
        if region is not None:
            context.setdefault("region_id", region.id)
            context.setdefault(
                "region_epoch", (region.epoch.conf_ver, region.epoch.version)
            )
        apply_index = getattr(snap, "apply_index", None)
        if apply_index is not None:
            context.setdefault("apply_index", apply_index)
        return context

    def _block_cache_for(self, req: CoprRequest):
        """Decoded-block cache, valid only while the region data is unchanged:
        the caller must supply a data version (apply index / resolved ts) in
        context["cache_version"]; without one, every request is cold (the
        reference's cop-cache likewise keys on region apply version,
        cache.rs:10).  Deliberately NOT defaulted from the snapshot's
        apply_index: every ad-hoc start_ts would mint a fresh entry and
        churn warm ones out of the shared LRU — the region column cache is
        the apply_index-keyed layer (docs/write_path.md)."""
        version = (req.context or {}).get("cache_version")
        if version is None:
            return None
        key = (
            req.context.get("region_id"),
            tuple(req.ranges),
            req.start_ts,
            version,
        )
        return self.cop_cache.get_or_create(key)
