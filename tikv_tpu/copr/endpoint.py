"""Coprocessor endpoint: parse, route, execute.

Re-expression of ``src/coprocessor/endpoint.rs`` (:45 Endpoint, :144
parse_request_and_check_memory_locks, :392/:459/:486 unary path): takes a
coprocessor request (DAG over key ranges at a start_ts), obtains a snapshot
from the engine, and runs the plan — on the **device path** when the DAG is
eligible (the plugin-boundary gating from BASELINE.json), else the CPU batch
pipeline.  A response cache keyed by (region, data version) serves repeated
requests and backs the columnar block cache.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..storage.kv import Engine
from ..storage.mvcc import Statistics
from . import jax_eval
from .cache import ColumnBlockCache, CopCache
from .dag import BatchExecutorsRunner, DagRequest, SelectResponse
from .executors import MvccScanSource
from .mvcc_batch import MvccBatchScanSource

REQ_TYPE_DAG = 103
REQ_TYPE_ANALYZE = 104
REQ_TYPE_CHECKSUM = 105


@dataclass
class CoprRequest:
    """coppb.Request equivalent."""

    tp: int
    dag: DagRequest
    ranges: list[tuple[bytes, bytes]]
    start_ts: int
    context: dict = field(default_factory=dict)  # region_id, epoch...


@dataclass
class CoprResponse:
    data: bytes
    from_device: bool = False
    from_cache: bool = False


class Endpoint:
    def __init__(
        self,
        engine: Engine,
        enable_device: bool = True,
        block_cache: CopCache | None = None,
        concurrency_manager=None,
    ):
        self.engine = engine
        self.enable_device = enable_device
        self.cop_cache = block_cache or CopCache()
        self.cm = concurrency_manager
        self._evaluators: dict = {}

    def handle_request(self, req: CoprRequest) -> CoprResponse:
        if req.tp != REQ_TYPE_DAG:
            raise ValueError(f"unsupported coprocessor request type {req.tp}")
        if self.cm is not None:
            from ..storage.txn_types import Key

            for start, end in req.ranges:
                self.cm.read_range_check(Key.from_raw(start), Key.from_raw(end), req.start_ts)
        snap = self.engine.snapshot(req.context or None)
        use_device = self.enable_device and jax_eval.supports(req.dag)
        if use_device:
            ev = self._evaluator_for(req.dag)
            cache = self._block_cache_for(req)
            src = None
            if cache is None or not cache.filled:
                src = MvccBatchScanSource(snap, req.start_ts, req.ranges)
            resp = ev.run(src, cache=cache)
            return CoprResponse(
                resp.encode(), from_device=True,
                from_cache=cache is not None and cache.filled and src is None,
            )
        src = MvccScanSource(snap, req.start_ts, req.ranges, statistics=Statistics())
        resp = BatchExecutorsRunner(req.dag, src).handle_request()
        return CoprResponse(resp.encode(), from_device=False)

    def _evaluator_for(self, dag: DagRequest) -> "jax_eval.JaxDagEvaluator":
        """Reuse compiled evaluators across requests, keyed by plan bytes
        (each holds its jit caches — recompiling per request throws away the
        warm XLA programs)."""
        from ..server import wire
        from .dag_wire import dag_to_wire

        key = wire.dumps(dag_to_wire(dag))
        ev = self._evaluators.get(key)
        if ev is None:
            ev = jax_eval.JaxDagEvaluator(dag)
            self._evaluators[key] = ev
            while len(self._evaluators) > 64:
                self._evaluators.pop(next(iter(self._evaluators)))
        return ev

    def _block_cache_for(self, req: CoprRequest):
        """Decoded-block cache, valid only while the region data is unchanged:
        the caller must supply a data version (apply index / resolved ts) in
        context["cache_version"]; without one, every request is cold (the
        reference's cop-cache likewise keys on region apply version,
        cache.rs:10)."""
        version = (req.context or {}).get("cache_version")
        if version is None:
            return None
        key = (
            req.context.get("region_id"),
            tuple(req.ranges),
            req.start_ts,
            version,
        )
        return self.cop_cache.get_or_create(key)
