"""Integrity plane for the derived device serving plane (docs/integrity.md).

Everything the warm path serves since PR 1 is *derived* state — decoded
region column images, wt_delta folds, mesh shards — and until this module
nothing ever verified that derived state against ground truth: the raft
mvcc consistency check covers engine CFs only and the native engine's
CRC32c stops at the WAL.  A silent decode bug, a bad delta fold, or
device-side bit corruption would serve wrong bytes to every warm read
forever.  This module closes that loop with three always-on nets:

1. **Image fingerprints** — every :class:`~.region_cache.RegionImage`
   carries an order-independent content hash computed at build time and
   folded incrementally on every delta apply (write-through or scan_delta),
   so a fingerprint is available at any ``(region_id, epoch, apply_index)``
   without re-reading the image, let alone the engine.  The per-row hash is
   ``crc64(compact(key) + compact(value))`` — byte-for-byte the entry of
   ``analyze.checksum_range`` — so the XOR fold doubles as the coprocessor
   Checksum (tp=105) answer for warm ranges.  A second fold mixes each
   row's ``commit_ts`` through splitmix64 so version drift is visible too.

2. **Background scrubber** — :class:`IntegrityScrubber` walks warm images
   on a cadence, recomputes the oracle hash from an engine snapshot at the
   image's apply point, and on mismatch **quarantines** the image
   (invalidate + ledger entry + ``tikv_coprocessor_integrity_mismatch_total``)
   and eagerly rebuilds it from the engine.  ``deep=True`` additionally
   re-decodes the oracle rows and compares the decoded block columns — the
   net that catches post-decode bit flips the raw-chain hash cannot see.
   The scrubber also rides the raft ``schedule_consistency_check`` round
   (:func:`scrub_region_on_consistency_check`), so every replica verifies
   its derived plane at the exact apply index the mvcc hash is taken at,
   and the leader's ``verify_hash`` entry carries its image fingerprints
   for a literal replica cross-check (:func:`cross_check_image_fps`).

3. **Shadow-read sampling** — :class:`ShadowSampler` deterministically
   picks a configurable fraction of warm device serves (default 1/256,
   ``TIKV_TPU_SHADOW_SAMPLE``) for re-execution on the CPU fallback
   executor and byte comparison (``Endpoint.shadow_compare``).  A mismatch
   quarantines the image and the CPU result serves — a sampled request can
   never return wrong bytes.

``TIKV_TPU_INTEGRITY_FATAL=1`` turns any detected mismatch into a raised
:class:`IntegrityMismatch` (tests, canary stores); the default is
quarantine + rebuild + count, because serving correct bytes off a rebuilt
image beats crashing the store.
"""

from __future__ import annotations

import hashlib
import os
import time

import numpy as np

from ..analysis.sanitizer import make_lock
from ..util import codec
from .analyze import _crc64_table

_CRC64_TABLE = np.array(_crc64_table, dtype=np.uint64)
_MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)
_PHI = np.uint64(0x9E3779B97F4A7C15)

DEFAULT_SHADOW_EVERY = 256


class IntegrityMismatch(Exception):
    """Raised instead of quarantining when TIKV_TPU_INTEGRITY_FATAL=1."""


def integrity_fatal() -> bool:
    return os.environ.get("TIKV_TPU_INTEGRITY_FATAL", "") == "1"


# ---------------------------------------------------------------------------
# row hashing (vectorized crc64-ECMA, identical to analyze.checksum_range)
# ---------------------------------------------------------------------------

# crc64_batch padding bounds: a row longer than _JUMBO_ROW hashes scalar
# (a dense matrix padded to one huge blob's length would multiply EVERY
# row's footprint by it), and the padded matrix is processed in slices of
# at most _MATRIX_BYTES so the transient never scales with the row count
_JUMBO_ROW = 4096
_MATRIX_BYTES = 16 << 20


def crc64_batch(rows: list[bytes]) -> np.ndarray:
    """crc64-ECMA of every byte string, vectorized ACROSS rows: the carry
    chain is sequential within a row, so the loop runs over byte positions
    while each step advances every row at once.  Bit-identical to
    :func:`..analyze.crc64` per row.  Memory-bounded: jumbo rows fall back
    to the scalar loop and the padded matrix is sliced, so a skewed value
    distribution cannot balloon the transient footprint."""
    n = len(rows)
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    lens = np.fromiter(map(len, rows), dtype=np.int64, count=n)
    out = np.empty(n, dtype=np.uint64)
    jumbo = np.flatnonzero(lens > _JUMBO_ROW)
    if len(jumbo):
        from .analyze import crc64

        for i in jumbo:
            out[i] = crc64(rows[int(i)])
    small = np.flatnonzero(lens <= _JUMBO_ROW) if len(jumbo) else None
    order = small if small is not None else np.arange(n, dtype=np.int64)
    step = len(order)
    if len(order):
        step = max(1, _MATRIX_BYTES // max(int(lens[order].max()), 1))
    eight = np.uint64(8)
    for s in range(0, len(order), step):
        sel = order[s:s + step]
        slens = lens[sel]
        k = len(sel)
        crc = np.full(k, 0xFFFFFFFFFFFFFFFF, dtype=np.uint64)
        m = int(slens.max()) if k else 0
        if m:
            chunk = [rows[int(i)] for i in sel]
            flat = np.frombuffer(b"".join(chunk), dtype=np.uint8)
            buf = np.zeros((k, m), dtype=np.uint8)
            row_idx = np.repeat(np.arange(k, dtype=np.int64), slens)
            col_idx = np.arange(int(slens.sum()), dtype=np.int64) - np.repeat(
                np.cumsum(slens) - slens, slens
            )
            buf[row_idx, col_idx] = flat
            for j in range(m):
                active = slens > j
                idx = ((crc ^ buf[:, j]) & np.uint64(0xFF)).astype(np.int64)
                crc = np.where(active, _CRC64_TABLE[idx] ^ (crc >> eight), crc)
        out[sel] = crc ^ _MASK64
    return out


def row_checksums(raw_keys: list[bytes], values: list[bytes]) -> np.ndarray:
    """Per-row ``crc64(compact(key) + compact(value))`` — EXACTLY the entry
    ``analyze.checksum_range`` folds, so ``fold(row_checksums(...))`` equals
    the coprocessor Checksum of the same rows."""
    ecb = codec.encode_compact_bytes
    return crc64_batch([ecb(k) + ecb(v) for k, v in zip(raw_keys, values)])


def mix_fp(row_fp: np.ndarray, commit_ts) -> np.ndarray:
    """Mix each row's content hash with its commit_ts (splitmix64): the
    version-aware fingerprint — XOR-foldable like the content hash, but
    sensitive to a corrupted ``row_commit_ts`` too."""
    x = np.asarray(row_fp, dtype=np.uint64) ^ (
        np.asarray(commit_ts).astype(np.uint64) * _PHI
    )
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def fold(fps) -> int:
    """Order-independent combine (XOR): rows are unique by handle, so the
    fold identifies the row SET regardless of block layout or apply order."""
    a = np.asarray(fps, dtype=np.uint64)
    return int(np.bitwise_xor.reduce(a)) if a.size else 0


def image_key_id(key) -> str:
    """Stable, wire-safe identifier of an image key's (ranges, schema) —
    what replicas use to pair up images for the consistency cross-check
    (the raw key contains bytes and nested tuples; a digest travels)."""
    return hashlib.blake2b(repr((key[1], key[2])).encode(), digest_size=8).hexdigest()


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def count_mismatch(stage: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_integrity_mismatch_total",
        "Derived-state integrity mismatches detected, by detection stage",
    ).inc(stage=stage)


def count_quarantine(stage: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_integrity_quarantine_total",
        "Region images quarantined (invalidated + ledgered) after an "
        "integrity mismatch, by detection stage",
    ).inc(stage=stage)


def count_scrub(outcome: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_integrity_scrub_total",
        "Scrubber image verifications, by outcome",
    ).inc(outcome=outcome)


# ---------------------------------------------------------------------------
# shadow-read sampling
# ---------------------------------------------------------------------------

class ShadowSampler:
    """Deterministic 1-in-N pick of warm device serves for CPU shadow
    re-execution.  Counter-based (not hashed off request identity) so a hot
    identical request cannot land on a permanently-sampled bucket and pay
    the CPU re-execution on EVERY serve; the N-th warm serve per path
    samples, making the steady-state overhead exactly cpu_cost/N.

    ``every=0`` disables sampling; ``every=1`` verifies every warm serve
    (the chaos suite's zero-wrong-bytes mode)."""

    def __init__(self, every: int | None = None):
        if every is None:
            env = os.environ.get("TIKV_TPU_SHADOW_SAMPLE", "")
            every = int(env) if env else DEFAULT_SHADOW_EVERY
        self.every = max(int(every), 0)
        self._mu = make_lock("copr.integrity")
        self._n: dict[str, int] = {}
        self.results: dict[tuple, int] = {}

    def pick(self, path: str) -> bool:
        """Count one warm device serve on ``path``; True when it samples."""
        if self.every == 0:
            return False
        with self._mu:
            n = self._n.get(path, 0) + 1
            self._n[path] = n
        return n % self.every == 0

    def note(self, path: str, result: str) -> None:
        from ..util.metrics import REGISTRY

        with self._mu:
            k = (path, result)
            self.results[k] = self.results.get(k, 0) + 1
        REGISTRY.counter(
            "tikv_coprocessor_shadow_read_total",
            "Warm device serves re-executed on the CPU oracle, by serving "
            "path and comparison result",
        ).inc(path=path, result=result)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "every": self.every,
                "warm_serves": dict(self._n),
                "results": {f"{p}:{r}": n for (p, r), n in self.results.items()},
            }


# ---------------------------------------------------------------------------
# oracle verification
# ---------------------------------------------------------------------------

def verify_image(cache, key, snap, deep: bool = True, stage: str = "scrub") -> dict:
    """Verify ONE resident image against the engine oracle.

    Recomputes the visible row set of ``key``'s ranges at the image's
    snapshot_ts from ``snap`` and compares: the incremental fingerprint
    folds against their own row arrays (fold drift), the row arrays against
    the oracle (content/version corruption), and — with ``deep`` — the
    decoded block columns against a fresh decode of the oracle rows (the
    post-decode plane that actually serves).  On mismatch the image is
    quarantined through the cache's ledger; callers rebuild.

    Validity: the oracle is only meaningful when the image has folded every
    data batch the snapshot contains — enforced via the snapshot's
    apply_index and the cache's write-through watermark; anything else
    returns ``stale`` and the image is retried on a later round."""
    region_id = key[0]
    with cache._mu:
        img = cache._images.get(key)
        if img is None:
            return {"outcome": "missing"}
        if not img.fp_valid:
            return {"outcome": "unverifiable"}
        a_idx = img.apply_index
        ts = img.snapshot_ts
        schema = list(img.schema)
        wt_seen = cache._wt_seen.get(region_id, -1)
    snap_idx = getattr(snap, "apply_index", None)
    if snap_idx is not None and snap_idx < a_idx:
        return {"outcome": "stale"}  # snapshot predates the image
    if snap_idx is not None and snap_idx != a_idx and a_idx < wt_seen:
        # the engine holds data batches the image has not folded yet — the
        # next warm serve folds them; verify then
        return {"outcome": "stale"}
    from .mvcc_batch import MvccBatchScanSource
    from .table import RowBatchDecoder, decode_record_handles

    src = MvccBatchScanSource(snap, ts, list(key[1]), record_versions=True)
    try:
        keys_raw, values = src._resolve_all()
    except Exception as exc:  # noqa: BLE001 — locks, faulting engine
        return {"outcome": "error", "error": repr(exc)}
    if not src.versions_exact:
        return {"outcome": "unverifiable"}
    o_fp = row_checksums(keys_raw, values)
    o_cts = src.row_commit_ts
    # the deep compare's expensive half — handle decode + a full row decode
    # of the oracle values — runs OUTSIDE the manager lock (it touches only
    # oracle-side locals); under the lock only vectorized compares remain,
    # so concurrent warm serves and the raft apply loop never stall on a
    # scrub's decode
    o_handles = o_cols = None
    if deep:
        try:
            o_handles = decode_record_handles(keys_raw)
            if len(o_handles):
                o_cols = RowBatchDecoder(schema).decode(o_handles, values)
        except Exception as exc:  # noqa: BLE001 — exotic rows: cannot judge
            return {"outcome": "error", "error": repr(exc)}
    with cache._mu:
        if cache._images.get(key) is not img or img.apply_index != a_idx:
            return {"outcome": "raced"}
        failed: list[str] = []
        if img.fp_value != fold(img.row_fp) or img.fp_integrity != fold(
            mix_fp(img.row_fp, img.row_commit_ts)
        ):
            # the incremental fold diverged from its own arrays: a fold bug
            # or bookkeeping corruption — as quarantine-worthy as content
            failed.append("fold_drift")
        if img.fp_value != fold(o_fp):
            failed.append("content")
        if img.fp_integrity != fold(mix_fp(o_fp, o_cts)):
            failed.append("versions")
        if deep and not failed:
            failed.extend(_deep_compare(img, o_handles, o_cols, o_cts))
        info = {
            "region_id": region_id,
            "key_id": image_key_id(key),
            "epoch": img.epoch,
            "apply_index": a_idx,
            "snapshot_ts": ts,
            "rows": img.n_rows,
            "fingerprint": img.fp_integrity,
        }
        if not failed:
            return {"outcome": "ok", **info}
        schema = list(img.schema)
        cache.quarantine_image(
            key, stage=stage,
            detail={"failed": failed, "oracle_fingerprint": fold(mix_fp(o_fp, o_cts)),
                    "oracle_rows": len(keys_raw)},
        )
    count_mismatch(stage)
    if integrity_fatal():
        raise IntegrityMismatch(
            f"integrity mismatch ({stage}) on region {region_id} "
            f"apply_index {a_idx}: {failed}"
        )
    return {"outcome": "mismatch", "failed": failed, "schema": schema, **info}


def _deep_compare(img, o_handles, o_cols, o_cts) -> list[str]:
    """Compare the image's DECODED plane (what serves) against the
    pre-decoded oracle rows.  Caller holds the cache lock; the oracle-side
    decode already happened outside it — only vectorized compares (plus,
    for compressed-resident columns, a fresh vectorized decode of the
    ENCODED payload: materialized decode caches are purged first, so a
    bit flip in the encoded bytes — the form the device actually serves —
    can never hide behind a stale host decode;
    docs/compressed_columns.md)."""
    from .encoding import EncodedColumn

    def _purge():
        for b in img.block_cache.blocks:
            for c in b.cols:
                if isinstance(c, EncodedColumn):
                    c.purge_decoded()

    _purge()
    try:
        return _deep_compare_inner(img, o_handles, o_cols, o_cts)
    finally:
        # the compare itself re-materialized the caches: drop them again so
        # a scrubbed image resumes costing its ENCODED bytes
        _purge()


def _deep_compare_inner(img, o_handles, o_cols, o_cts) -> list[str]:
    if not np.array_equal(o_handles, img.handles):
        return ["handles"]
    if o_cts is not None and not np.array_equal(
        np.asarray(o_cts, dtype=np.int64), img.row_commit_ts
    ):
        return ["commit_ts"]
    blocks = img.block_cache.blocks
    if sum(b.n_valid for b in blocks) != img.n_rows:
        return ["blocks"]
    if img.n_rows == 0 or o_cols is None:
        return []
    cols = o_cols
    for ci in range(len(img.schema)):
        parts_d, parts_n = [], []
        for b in blocks:
            c = b.cols[ci].decoded()
            parts_d.append(np.asarray(c.data)[: b.n_valid])
            parts_n.append(np.asarray(c.nulls)[: b.n_valid])
        idata = np.concatenate(parts_d)
        inulls = np.concatenate(parts_n)
        oc = cols[ci].decoded()
        odata = np.asarray(oc.data)
        onulls = np.asarray(oc.nulls)
        if not np.array_equal(inulls, onulls):
            return [f"nulls:{ci}"]
        live = ~inulls
        a, b_ = idata[live], odata[live]
        if a.dtype.kind == "f" or b_.dtype.kind == "f":
            same = np.array_equal(a.astype(np.float64), b_.astype(np.float64),
                                  equal_nan=True)
        else:
            same = bool(np.asarray(a == b_).all()) if len(a) else True
        if not same:
            return [f"column:{ci}"]
    return []


# ---------------------------------------------------------------------------
# background scrubber
# ---------------------------------------------------------------------------

class IntegrityScrubber:
    """Cadenced oracle verification of warm images (SDC scrubber).

    ``scrub_once()`` is the synchronous core — a round-robin cursor over
    the cache's resident images verifies up to ``per_round`` of them
    against engine snapshots; mismatches quarantine AND eagerly rebuild
    (the repaired image serves the next warm read with zero cold cost).
    ``start(interval_s)`` runs rounds on a ``util.worker.Worker`` timer —
    the standalone server's always-on mode."""

    def __init__(self, cache, engine, per_round: int = 8, deep: bool = True):
        self.cache = cache
        self.engine = engine
        self.per_round = per_round
        self.deep = deep
        self.interval_s: float | None = None
        self._mu = make_lock("copr.integrity.scrub")
        self._worker = None
        self._cursor = 0
        # TIKV_TPU_INTEGRITY_FATAL on the cadenced path: the Worker timer
        # swallows exceptions, so the fatal raise is recorded here instead
        # (and further rounds stop) — surfaced via snapshot()/debug RPC
        self.fatal_error: str | None = None
        self.stats = {
            "rounds": 0, "checked": 0, "ok": 0, "mismatch": 0,
            "skipped": 0, "errors": 0, "last_round_unix": 0.0,
        }

    # -- snapshots -----------------------------------------------------------

    def _snapshot_for(self, key):
        """An engine snapshot to verify ``key`` against.  RaftKv exposes a
        protocol-free local snapshot (scrubbing needs a pinned LOCAL apply
        point, not linearizability); plain engines snapshot directly."""
        local = getattr(self.engine, "local_snapshot", None)
        if local is not None:
            return local(key[0])
        return self.engine.snapshot({"region_id": key[0]})

    # -- the scrub core ------------------------------------------------------

    def scrub_once(self, limit: int | None = None) -> list[dict]:
        cache = self.cache
        if cache is None:
            return []
        with cache._mu:
            all_keys = list(cache._images.keys())
        if not all_keys:
            return []
        k = min(limit or self.per_round, len(all_keys))
        with self._mu:
            start = self._cursor % len(all_keys)
            self._cursor = start + k
        picked = [all_keys[(start + i) % len(all_keys)] for i in range(k)]
        out = []
        fatal: IntegrityMismatch | None = None
        for key in picked:
            try:
                snap = self._snapshot_for(key)
            except Exception as exc:  # noqa: BLE001 — peer gone, engine closed
                res = {"outcome": "error", "error": repr(exc)}
            else:
                try:
                    res = verify_image(cache, key, snap, deep=self.deep,
                                       stage="scrub")
                except IntegrityMismatch as exc:
                    # fatal mode: the quarantine + mismatch counts already
                    # happened inside verify_image — finish this round's
                    # bookkeeping (metrics, stats, remaining images) and
                    # re-raise at the end, so fatal never UNDER-reports
                    res = {"outcome": "mismatch", "fatal": True}
                    fatal = fatal or exc
                if res["outcome"] == "mismatch" and "schema" in res:
                    self._rebuild(key, snap, res)
            count_scrub(res["outcome"])
            with self._mu:
                self.stats["checked"] += 1
                if res["outcome"] == "ok":
                    self.stats["ok"] += 1
                elif res["outcome"] == "mismatch":
                    self.stats["mismatch"] += 1
                elif res["outcome"] == "error":
                    self.stats["errors"] += 1
                else:
                    self.stats["skipped"] += 1
            out.append({"region_id": key[0], **res})
        with self._mu:
            self.stats["rounds"] += 1
            self.stats["last_round_unix"] = time.time()
        if fatal is not None:
            raise fatal
        return out

    def _rebuild(self, key, snap, res: dict) -> None:
        """Eager repair: rebuild the quarantined image from the engine so
        the next warm read serves a verified image, not a cold miss."""
        schema = res.get("schema")
        if schema is None:
            return
        ctx = {
            "region_id": key[0],
            "region_epoch": res["epoch"],
            "apply_index": getattr(snap, "apply_index", None) or res["apply_index"],
        }
        try:
            self.cache.serve(snap, ctx, schema, list(key[1]), res["snapshot_ts"])
        except Exception:  # noqa: BLE001 — locks etc: the next read rebuilds
            pass

    # -- cadence -------------------------------------------------------------

    def start(self, interval_s: float = 10.0) -> None:
        if self._worker is not None:
            return
        from ..util.worker import Runnable, Worker

        self.interval_s = interval_s
        scrubber = self

        class _ScrubRunnable(Runnable):
            def _round(self) -> None:
                if scrubber.fatal_error is not None:
                    return  # fatal mode already fired: no further rounds
                try:
                    scrubber.scrub_once()
                except IntegrityMismatch as exc:
                    # the Worker swallows exceptions, so the fatal raise
                    # would otherwise vanish: record + log it loudly and
                    # stop scrubbing (snapshot()/debug_integrity surface it)
                    scrubber.fatal_error = repr(exc)
                    from ..util import logger as _slog

                    _slog.get_logger("integrity").error(
                        "fatal integrity mismatch (scrubber halted)",
                        error=repr(exc),
                    )

            def run(self, task) -> None:
                self._round()

            def on_timeout(self) -> None:
                self._round()

        w = Worker("integrity-scrub", timer_interval=interval_s)
        w.start(_ScrubRunnable())
        self._worker = w

    def stop(self) -> None:
        w, self._worker = self._worker, None
        if w is not None:
            w.stop()

    @property
    def running(self) -> bool:
        return self._worker is not None

    def snapshot(self) -> dict:
        with self._mu:
            st = dict(self.stats)
        return {
            "running": self.running,
            "interval_s": self.interval_s,
            "per_round": self.per_round,
            "deep": self.deep,
            "fatal_error": self.fatal_error,
            **st,
        }


# ---------------------------------------------------------------------------
# raft consistency-check ride-along
# ---------------------------------------------------------------------------

def _caches_for(token):
    from .region_cache import _CACHES, _TOKEN_UNSET

    out = []
    for c in list(_CACHES):
        t = c._wt_token
        if t is not _TOKEN_UNSET and t == token:
            out.append(c)
    return out


def scrub_region_on_consistency_check(region_id: int, token, snap,
                                      limit: int = 4) -> list[dict]:
    """Every replica applying a compute_hash entry verifies its OWN derived
    images of the region against its OWN engine at that exact apply point —
    the mvcc hash then cross-checks the engines replica-to-replica, so the
    derived planes are transitively cross-checked too.

    This runs INLINE on the raft apply thread, so the work is bounded:
    hash-level only (``deep=False`` — no full row decode; the decoded
    plane is the budgeted background scrubber's and the shadow reads' job)
    and at most ``limit`` images per apply — comparable to the
    ``_region_hash`` scan the round already pays, never a multiple of it."""
    results = []
    checked = 0
    for cache in _caches_for(token):
        with cache._mu:
            keys = [k for k in cache._images if k[0] == region_id]
        for key in keys:
            if checked >= limit:
                return results
            res = verify_image(cache, key, snap, deep=False,
                               stage="consistency_check")
            results.append(res)
            checked += 1
    return results


def region_image_fingerprints(region_id: int, token) -> dict:
    """{key_id: {"apply_index", "snapshot_ts", "max_commit_ts",
    "fingerprint"}} of this store's verified images of the region — the
    payload the leader attaches to verify_hash so replicas can literally
    compare device-image hashes.  snapshot_ts/max_commit_ts travel so the
    receiver can prove the row sets identical before comparing (see
    :func:`cross_check_image_fps`)."""
    out: dict = {}
    for cache in _caches_for(token):
        with cache._mu:
            for key, img in cache._images.items():
                if key[0] != region_id or not img.fp_valid:
                    continue
                out[image_key_id(key)] = {
                    "apply_index": img.apply_index,
                    "snapshot_ts": img.snapshot_ts,
                    "max_commit_ts": img.max_commit_ts,
                    "fingerprint": img.fp_integrity,
                }
    return out


def cross_check_image_fps(region_id: int, token, leader_fps: dict) -> list[dict]:
    """verify_hash-side replica cross-check: compare local image
    fingerprints against the leader's — but ONLY when the two images
    provably hold the same row set.  Equal apply_index alone is not enough:
    two healthy replicas may have built the same (ranges, schema) image at
    different read timestamps (PR-7 stale reads), seeing different MVCC
    versions.  The row sets are identical iff the apply state is pinned
    equal AND neither image contains a version the other's read point
    missed: ``leader.max_commit_ts <= local.snapshot_ts`` and
    ``local.max_commit_ts <= leader.snapshot_ts`` (a separating version
    with cts between the two read points would raise the later image's
    max_commit_ts above the earlier one's snapshot).  Anything else is
    incomparable and skipped — the local-engine scrub at the compute point
    already covered those images.  Divergence quarantines the LOCAL image:
    the engine mvcc hash decides who is wrong at the region level; the
    derived plane simply rebuilds."""
    quarantined = []
    for cache in _caches_for(token):
        with cache._mu:
            keys = [k for k in cache._images if k[0] == region_id]
            for key in keys:
                img = cache._images.get(key)
                if img is None or not img.fp_valid:
                    continue
                rec = leader_fps.get(image_key_id(key))
                if rec is None or int(rec["apply_index"]) != img.apply_index:
                    continue
                if not (int(rec["max_commit_ts"]) <= img.snapshot_ts
                        and img.max_commit_ts <= int(rec["snapshot_ts"])):
                    continue  # read points may see different version sets
                if int(rec["fingerprint"]) != img.fp_integrity:
                    entry = cache.quarantine_image(
                        key, stage="replica_divergence",
                        detail={"leader_fingerprint": int(rec["fingerprint"])},
                    )
                    quarantined.append(entry)
    for _ in quarantined:
        count_mismatch("replica_divergence")
    return quarantined
