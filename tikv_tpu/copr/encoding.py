"""Compressed device-resident columns — encodings, eligibility, rewrites.

HBM capacity is the ceiling on warm serving: the region column cache keeps
ONE decoded image per region under a per-device byte budget, so the number
of regions that stay warm — and therefore hit the vectorized wire path — is
bounded by DECODED size.  Following "GPU Acceleration of SQL Analytics on
Compressed Data" (PAPERS.md), this module makes ENCODED blocks the resident
form and pushes evaluation through the encodings, so the budget buys 3-5×
more warm regions for the same bytes:

* **bitpack** — int-family columns whose value range fits narrow signed
  lanes store ``value - ref`` in int8/int16/int32 (frame-of-reference +
  power-of-two lane widths; numpy has no sub-byte arrays, so 8 bits is the
  floor).  The device program widens in-register (``x.astype(i64) + ref``)
  — HBM holds the narrow lanes, compute sees exact int64.
* **rle** — columns dominated by runs store (run_values, run_ends,
  run_nulls); the device expands rows in-kernel with one ``searchsorted``
  gather per column, so HBM holds runs while predicates/aggregates see the
  logical rows.
* **dict** — BYTES columns already arrive dictionary-coded from the row
  decoders; the codes are additionally NARROWED to the smallest lane that
  holds the dictionary, and equality/IN/range predicates over such columns
  are REWRITTEN into the code space (:func:`rewrite_dag_for_dict`) so
  warm bytes-predicate DAGs run on the device without materializing a
  single string.

Eligibility is centralized HERE (plan-sig × encoding → path decision) so
the serving paths can never disagree about what ships encoded, and every
decline is counted per-cause — never silent:

======== ============ ========== ====== ====== ========= ==========
encoding unary-stacked per-block  zone   fused  xregion   mesh-shard
======== ============ ========== ====== ====== ========= ==========
plain     ✓            ✓          ✓      ✓      ✓         ✓
dict/code ✓ (narrow)   ✓          ✓      ✓      ✓ sig=    ✓ sig=
bitpack   ✓            ✓          (own)  ✓      ✓ sig=    ✓ sig=
rle       ✓            ✓          (own)  ✓      ✓ sig=    decode-ship
======== ============ ========== ====== ====== ========= ==========

"sig=": cross-region programs (vmapped / shard_map) stack per-region pinned
arrays, so every region in the batch must carry the SAME encoding signature
(lane widths, run capacities); a mismatch decode-ships the batch (cause
``enc_mismatch``).  The mesh launcher additionally declines RLE (slab
stacks mix blocks of several regions on one device; run capacities would
have to unify across the whole batch — cause ``rle_sharded``).  "(own)":
the zone-tiled layout re-clusters and re-narrows from the logical rows —
it is its own compressed resident form, not a decline.

Delta semantics (docs/compressed_columns.md): in-place write-through folds
PATCH bitpacked lanes (and dict codes) when the new value still fits;
anything that breaks an encoding — an out-of-range value, any in-place
update to an RLE column — DEMOTES that column image-wide to plain decoded
(counted ``tikv_coprocessor_encoding_demote_total{kind,cause}``), dropping
device pins so the next serve re-pins the decoded form; structural repacks
re-encode from fresh stats.  Byte-identity is non-negotiable: decode() is
exact, null slots normalize to the canonical 0 filler, and the integrity
plane (fingerprints over the LOGICAL rows, deep scrub, shadow reads)
cross-checks encoded and decoded images of the same data.
"""

from __future__ import annotations

import numpy as np

from .datatypes import Column, EvalType

# minimum win before a column trades decode work for bytes: bitpack must
# shed at least half the lanes, RLE must shed at least 3/4 of the slots
_RLE_MAX_RUN_FRACTION = 0.25
_NARROW_DTYPES = (np.int8, np.int16, np.int32)

# device-plan memo: (id(cache), enc_version, ship, nullable) → plan
_PLAN_MEMO: dict = {}
_PLAN_MEMO_MAX = 256

# dictionary → code-map memo for predicate rewrites (id-keyed, bounded; the
# dictionary object is held so the id cannot be recycled under the entry)
_DICT_MAPS: dict = {}
_DICT_MAPS_MAX = 64


# ---------------------------------------------------------------------------
# metrics (every decision observable; declines NEVER silent)
# ---------------------------------------------------------------------------

def count_encoded(kind: str, n: int = 1) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_encoding_total",
        "Columns made device-resident in encoded form at fill, by kind",
    ).inc(n, kind=kind)


def count_demote(kind: str, cause: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_encoding_demote_total",
        "Encoded columns demoted to plain decoded (encoding broken), by cause",
    ).inc(kind=kind, cause=cause)


def count_path(path: str, decision: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_encoded_path_total",
        "Device-path consumption decisions for encoded-resident images",
    ).inc(path=path, decision=decision)


def count_decline(path: str, cause: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_encoded_decline_total",
        "Encoded-path declines (decode-ship / CPU), by path and cause",
    ).inc(path=path, cause=cause)


def count_rewrite(outcome: str) -> None:
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_encoded_rewrite_total",
        "Dict-code-space predicate rewrites of bytes-predicate DAGs",
    ).inc(outcome=outcome)


def count_join(path: str, outcome: str) -> None:
    """Join serving outcomes by path (rank / hash / cpu): served, declined,
    error — the device-join twin of count_rewrite (docs/device_join.md);
    per-cause decline detail rides count_decline(path="join", cause)."""
    from ..util.metrics import REGISTRY

    REGISTRY.counter(
        "tikv_coprocessor_join_total",
        "Coprocessor join serves by path and outcome",
    ).inc(path=path, outcome=outcome)


# ---------------------------------------------------------------------------
# eligibility → candidate set (the cost router's input)
# ---------------------------------------------------------------------------

def candidate_paths(dag, *, device_ok: bool, mesh_ok: bool) -> list[str]:
    """The eligible execution paths for ``dag``, in STATIC-LADDER order —
    head = what today's rules pick, so a cold/killed cost router choosing
    ``candidates[0]`` IS the pre-router behavior (docs/cost_router.md).

    ``device_ok`` is the admission verdict (plan eligibility AND overload
    AND breaker — the endpoint computes it before routing); ``mesh_ok`` is
    whether the sharded mesh launcher would serve this request.  Zone
    stays a *candidate* for any aggregation plan: its evaluator still
    probes data-shape eligibility at run time and falls through to unary,
    so routing to "zone" means "try the zone rung", exactly like the
    static ladder does."""
    if not device_ok:
        return ["cpu"]
    from .dag import Aggregation, Join

    if any(isinstance(e, Join) for e in dag.executors):
        # join plans route among the device-join rung's two kernels and the
        # CPU oracle (docs/device_join.md): rank (sorted-dict code space)
        # leads the static ladder, hash (open-addressing over int lanes)
        # second — each is "try the rung", with per-cause counted declines
        # falling through to the next, exactly like zone/unary
        return ["rank", "hash", "cpu"]
    paths: list[str] = []
    if mesh_ok:
        paths.append("mesh")
    if any(isinstance(e, Aggregation) for e in dag.executors):
        paths.append("zone")
    paths.extend(("unary", "cpu"))
    return paths


# ---------------------------------------------------------------------------
# EncodedColumn — a lazy-decoding Column variant
# ---------------------------------------------------------------------------

class EncodedColumn(Column):
    """A :class:`Column` whose resident payload is encoded.

    ``data``/``nulls`` are PROPERTIES that materialize (and cache) the
    decoded arrays on first touch, so every generic consumer — the CPU
    executors, the response encoder, the deep scrub, the zone layout —
    stays correct without knowing about encodings; the device paths read
    the payload directly and decode in-kernel.  ``take`` is the
    late-materialize gather: only the selected rows decompress."""

    __slots__ = ("kind", "packed", "ref", "run_values", "run_ends",
                 "run_nulls", "k_cap", "n", "_data", "_nulls")

    def __init__(self, eval_type, frac, kind, n, *, packed=None, ref=0,
                 run_values=None, run_ends=None, run_nulls=None, k_cap=0,
                 nulls=None):
        # NOTE: deliberately no super().__init__ — the base slots `data` /
        # `nulls` are shadowed by the properties below
        self.eval_type = eval_type
        self.frac = frac
        self.dictionary = None
        self.kind = kind  # "bp" | "rle"
        self.n = n
        self.packed = packed
        self.ref = int(ref)
        self.run_values = run_values
        self.run_ends = run_ends
        self.run_nulls = run_nulls
        self.k_cap = int(k_cap)
        self._data = None
        self._nulls = nulls  # bp keeps plain bool nulls; rle expands lazily

    # -- logical view -------------------------------------------------------

    def __len__(self) -> int:
        return self.n

    @property
    def data(self):
        if self._data is None:
            self._data = self._decode_rows(None)
        return self._data

    @property
    def nulls(self):
        if self._nulls is None:  # rle only
            idx = self._run_index(np.arange(self.n))
            self._nulls = self.run_nulls[idx]
        return self._nulls

    def purge_decoded(self) -> None:
        """Drop materialized caches so the next touch decodes from the
        payload — the scrub path uses this to verify the ENCODED bytes, not
        a stale decode."""
        self._data = None
        if self.kind == "rle":
            self._nulls = None

    def _run_index(self, rows: np.ndarray) -> np.ndarray:
        return np.searchsorted(self.run_ends, rows, side="right")

    def _decode_rows(self, rows):
        """Decode all rows (rows=None) or just the selected ones.  Null
        slots normalize to the canonical 0 filler (what the row decoders
        and delta cells write), so decode is byte-stable."""
        if self.kind == "bp":
            if rows is None:
                out = self.packed.astype(np.int64)
                out += self.ref
                out[self._nulls] = 0
            else:
                out = self.packed[rows].astype(np.int64)
                out += self.ref
                out[self._nulls[rows]] = 0
            return out
        idx = self._run_index(np.arange(self.n) if rows is None else rows)
        out = self.run_values[idx].astype(np.int64, copy=True)
        out[self.run_nulls[idx]] = 0
        return out

    def take(self, indices: np.ndarray) -> Column:
        """Late materialization: decompress ONLY the surviving rows."""
        indices = np.asarray(indices)
        data = self._decode_rows(indices)
        if self.kind == "bp":
            nulls = self._nulls[indices]
        else:
            nulls = self.run_nulls[self._run_index(indices)]
        return Column(self.eval_type, data, nulls.copy(), self.frac)

    def slice(self, start: int, stop: int) -> Column:
        return self.take(np.arange(start, stop))

    # -- payload accounting / mutation ---------------------------------------

    def encoded_nbytes(self) -> int:
        if self.kind == "bp":
            return self.packed.nbytes + self._nulls.nbytes
        return (self.run_values.nbytes + self.run_ends.nbytes
                + self.run_nulls.nbytes)

    def try_patch(self, rows: np.ndarray, vals: np.ndarray,
                  nls: np.ndarray) -> bool:
        """In-place update of the encoded payload; False = encoding broken
        (caller demotes).  Any in-place write to an RLE column breaks its
        runs; a bitpacked write survives while the new values fit the
        lanes."""
        if self.kind != "bp":
            return False
        info = np.iinfo(self.packed.dtype)
        v = np.asarray(vals, dtype=np.int64)
        live = ~np.asarray(nls, dtype=bool)
        rel = v - self.ref
        if live.any() and (int(rel[live].min()) < info.min
                           or int(rel[live].max()) > info.max):
            return False
        self.packed[rows] = np.where(live, rel, 0).astype(self.packed.dtype)
        self._nulls[rows] = nls
        if self._data is not None:
            self._data[rows] = np.where(live, v, 0)
        return True


def decoded_data(col: Column):
    """The decoded data array WITHOUT populating the column's decode cache
    — decode-ship pin builds (and the zone layout) must not leave a
    permanent host copy the encoded byte budget never accounted for."""
    if isinstance(col, EncodedColumn):
        return col._data if col._data is not None else col._decode_rows(None)
    return col.data


def decoded_nulls(col: Column):
    """Expanded null mask without populating the RLE null cache."""
    if (isinstance(col, EncodedColumn) and col.kind == "rle"
            and col._nulls is None):
        return col.run_nulls[col._run_index(np.arange(col.n))]
    return col.nulls


def decode_column(col: Column) -> Column:
    """A plain decoded Column for ``col`` (identity for unencoded ones)."""
    if isinstance(col, EncodedColumn):
        return Column(col.eval_type, np.asarray(col.data),
                      np.asarray(col.nulls).copy(), col.frac)
    return col


def host_dtype(col: Column):
    """The DECODED host dtype of a column (what delta cells compute in)."""
    if isinstance(col, EncodedColumn):
        return np.dtype(np.int64)
    d = np.asarray(col.data)
    if col.is_dict_encoded and d.dtype != object:
        return np.dtype(np.int64)  # codes widen before delta math
    return d.dtype


# ---------------------------------------------------------------------------
# stats pass + encode / demote / re-encode
# ---------------------------------------------------------------------------

def _narrow_lane(lo: int, hi: int, ref: int):
    for dt in _NARROW_DTYPES:
        info = np.iinfo(dt)
        if info.min <= lo - ref and hi - ref <= info.max:
            return dt
    return None


def _encode_one(col: Column, n_valid: int):
    """Choose and build the encoded form of ONE block column, or None to
    keep it as-is.  Int-family lanes only; REAL/object columns stay plain
    (float ranges don't narrow exactly; BYTES rides the dict path)."""
    data = col.data if not isinstance(col, EncodedColumn) else None
    if data is None or not isinstance(data, np.ndarray) or data.dtype == object:
        return None
    if col.eval_type == EvalType.REAL or data.dtype.kind not in "iu":
        return None
    if col.is_dict_encoded:
        return None  # dict codes narrow through narrow_dict_codes instead
    n = len(data)
    if n == 0:
        return None
    nulls = np.asarray(col.nulls, dtype=bool)
    a = data.astype(np.int64, copy=False)
    # RLE probe: runs over (value, null) pairs
    change = np.empty(n, dtype=bool)
    change[0] = True
    np.not_equal(a[1:], a[:-1], out=change[1:])
    change[1:] |= nulls[1:] != nulls[:-1]
    run_starts = np.flatnonzero(change)
    r = len(run_starts)
    if r <= max(1, int(n * _RLE_MAX_RUN_FRACTION)):
        run_ends = np.empty(r, dtype=np.int64)
        run_ends[:-1] = run_starts[1:]
        run_ends[-1] = n
        return EncodedColumn(
            col.eval_type, col.frac, "rle", n,
            run_values=a[run_starts].copy(), run_ends=run_ends,
            run_nulls=nulls[run_starts].copy(),
        )
    live = ~nulls
    if not live.any():
        lo = hi = 0
    else:
        lo, hi = int(a[live].min()), int(a[live].max())
    ref = lo
    dt = _narrow_lane(lo, hi, ref)
    if dt is None or np.dtype(dt).itemsize * 2 > a.dtype.itemsize:
        return None  # no lane at least halves the bytes
    packed = np.where(live, a - ref, 0).astype(dt)
    return EncodedColumn(col.eval_type, col.frac, "bp", n, packed=packed,
                         ref=ref, nulls=nulls.copy())


def narrow_dict_codes(col: Column) -> Column:
    """Narrow a dictionary-coded column's code lanes in place (int64 codes
    → the smallest lane holding the dictionary, with growth headroom)."""
    d = np.asarray(col.data)
    if (col.dictionary is None or d.dtype == object
            or col.eval_type in (EvalType.ENUM, EvalType.SET)):
        return col
    hi = max(len(col.dictionary), 1)
    dt = _narrow_lane(0, 2 * hi, 0)
    if dt is None or np.dtype(dt).itemsize >= d.dtype.itemsize:
        return col
    col.data = d.astype(dt)
    return col


def ensure_code_capacity(blocks, ci: int, max_code: int) -> bool:
    """Widen a narrowed dict-code column (image-wide) so ``max_code`` fits;
    returns True when lanes changed (callers drop device pins)."""
    c0 = blocks[0].cols[ci]
    d0 = np.asarray(c0.data)
    if d0.dtype == object or d0.dtype.kind not in "iu":
        return False
    if max_code <= np.iinfo(d0.dtype).max:
        return False
    dt = _narrow_lane(0, 2 * max_code, 0) or np.int64
    for b in blocks:
        b.cols[ci].data = np.asarray(b.cols[ci].data).astype(dt)
    if np.dtype(dt).itemsize >= 8:
        # only the widen-to-int64 case ENDS the encoding; int8→int16/32
        # stays a narrowed 'code' resident and must not read as a demotion
        count_demote("code", "code_overflow")
    return True


def encode_blocks(cache, schema) -> dict:
    """The fill-time stats pass: choose ONE encoding per column for the
    whole image (uniform across blocks — cross-block device stacking
    requires one signature) and swap the block columns for their encoded
    forms.  Returns {col_idx: kind} for the columns that changed."""
    blocks = cache.blocks
    if not blocks:
        return {}
    n_cols = len(blocks[0].cols)
    changed: dict[int, str] = {}
    for ci in range(n_cols):
        cols = [b.cols[ci] for b in blocks]
        if any(isinstance(c, EncodedColumn) for c in cols):
            continue
        if cols[0].is_dict_encoded:
            for b in blocks:
                narrow_dict_codes(b.cols[ci])
            d = np.asarray(blocks[0].cols[ci].data)
            if d.dtype != object and d.dtype.itemsize < 8:
                changed[ci] = "code"
                count_encoded("code")
            continue
        d0 = np.asarray(cols[0].data)
        if d0.dtype == object and cols[0].eval_type == EvalType.BYTES:
            # low-cardinality strings become dictionary residents with a
            # SORTED dictionary (order-preserving codes — what lets range
            # predicates rewrite into the code space) shared across blocks
            if _dict_encode_blocks(blocks, ci):
                changed[ci] = "dict"
                count_encoded("dict")
            continue
        encoded = [_encode_one(c, b.n_valid) for c, b in zip(cols, blocks)]
        if any(e is None for e in encoded):
            continue
        kinds = {e.kind for e in encoded}
        kind = kinds.pop() if len(kinds) == 1 else "bp"
        if kind == "bp":
            # bitpack everywhere (also the tie-break for mixed per-block
            # choices) under ONE shared frame of reference — cross-block
            # device stacks ship one dynamic ref per column
            encoded = _unify_bitpack(cols)
            if encoded is None:
                continue
        else:
            k_cap = 1
            while k_cap < max(len(e.run_values) for e in encoded):
                k_cap *= 2
            for e in encoded:
                e.k_cap = k_cap
        for b, e in zip(blocks, encoded):
            b.cols[ci] = e
        changed[ci] = kind
        count_encoded(kind)
    if changed:
        cache.enc_version = getattr(cache, "enc_version", 0) + 1
    # fill/repack-time zone maps (docs/zone_maps.md): the stats pass above
    # already bounded every encoded column, so attaching the prunable
    # per-block zones here is nearly free — and fresh (non-stale) by
    # construction.  Plain images build theirs lazily on first prune.
    from . import zone_maps as _zm

    for b in blocks:
        b.zones = _zm.build_block_zones(b.cols, b.n_valid)
    return changed


_DICT_MAX_CARDINALITY = 65536


def _dict_encode_blocks(blocks, ci: int) -> bool:
    """Dictionary-encode an object BYTES column image-wide: one SORTED
    dictionary object shared by every block (identity-shared — the stable-
    dictionary group paths and the predicate rewrite both key on it),
    narrow code lanes, null slots coded 0 (consumers mask)."""
    parts = [np.asarray(b.cols[ci].data) for b in blocks]
    nullp = [np.asarray(b.cols[ci].nulls) for b in blocks]
    n = sum(len(p) for p in parts)
    if n == 0:
        return False
    cap = min(max(n // 4, 1), _DICT_MAX_CARDINALITY)
    values = set()
    try:
        for p, nl in zip(parts, nullp):
            for v, isnull in zip(p, nl):
                if not isnull:
                    values.add(bytes(v))
            if len(values) > cap:
                # high-cardinality column: stop scanning the moment the cap
                # is exceeded — this runs on the fill/repack path
                return False
    except TypeError:
        return False  # non-bytes payloads: not dictionary material
    if not values or len(values) > cap:
        return False
    uniq = sorted(values)
    dictionary = np.empty(len(uniq), dtype=object)
    for j, v in enumerate(uniq):
        dictionary[j] = v
    dt = _narrow_lane(0, 2 * len(uniq), 0) or np.int64
    for b, p, nl in zip(blocks, parts, nullp):
        codes = np.searchsorted(dictionary, p).astype(dt)
        codes[nl] = 0
        c = b.cols[ci]
        b.cols[ci] = Column(c.eval_type, codes, np.asarray(c.nulls),
                            c.frac, dictionary)
    return True


def _unify_bitpack(cols):
    """Bitpack every block of a column under ONE shared (ref, lane)."""
    lo = hi = None
    for c in cols:
        a = np.asarray(c.data).astype(np.int64, copy=False)
        live = ~np.asarray(c.nulls, dtype=bool)
        if not live.any():
            continue
        clo, chi = int(a[live].min()), int(a[live].max())
        lo = clo if lo is None else min(lo, clo)
        hi = chi if hi is None else max(hi, chi)
    if lo is None:
        lo = hi = 0
    ref = lo
    dt = _narrow_lane(lo, hi, ref)
    if dt is None or np.dtype(dt).itemsize * 2 > 8:
        return None
    out = []
    for c in cols:
        a = np.asarray(c.data).astype(np.int64, copy=False)
        nulls = np.asarray(c.nulls, dtype=bool)
        packed = np.where(~nulls, a - ref, 0).astype(dt)
        out.append(EncodedColumn(c.eval_type, c.frac, "bp", len(a),
                                 packed=packed, ref=ref, nulls=nulls.copy()))
    return out


def demote_column(cache, ci: int, cause: str) -> None:
    """Replace an encoded column with its plain decoded form IMAGE-WIDE
    (every block — cross-block signatures must stay uniform) and drop
    device pins; the next serve re-pins decoded.  This is the
    "decode-on-next-serve" rung for updates that break an encoding."""
    kind = None
    for b in cache.blocks:
        c = b.cols[ci]
        if isinstance(c, EncodedColumn):
            kind = c.kind
            b.cols[ci] = decode_column(c)
    if kind is not None:
        count_demote(kind, cause)
        cache.enc_version = getattr(cache, "enc_version", 0) + 1
        cache.drop_device()


# ---------------------------------------------------------------------------
# byte accounting
# ---------------------------------------------------------------------------

def column_nbytes(col: Column) -> int:
    """Resident (encoded) bytes of one block column — THE figure budgets
    and gauges use.  Matches the historical formula exactly for plain
    columns so unencoded caches account identically to before."""
    if isinstance(col, EncodedColumn):
        return col.encoded_nbytes()
    data = np.asarray(col.data)
    total = data.nbytes if data.dtype != object else 32 * len(data)
    total += np.asarray(col.nulls).nbytes
    if col.dictionary is not None:
        total += 64 * len(col.dictionary)
    return total


def column_decoded_nbytes(col: Column) -> int:
    """What the column WOULD cost decoded (int64 lanes + bool nulls) — the
    numerator of the compression-ratio gauge."""
    if isinstance(col, EncodedColumn):
        return col.n * 8 + col.n * 1
    data = np.asarray(col.data)
    if col.dictionary is not None and data.dtype != object and data.dtype.kind in "iu":
        return len(data) * 8 + np.asarray(col.nulls).nbytes + 64 * len(col.dictionary)
    return column_nbytes(col)


# ---------------------------------------------------------------------------
# device consumption plans (per path, per-cause declines)
# ---------------------------------------------------------------------------

class DevicePlan:
    """How one image's columns ship to the device for a (ship, nullable)
    set: per-slot static descriptors (the jit/pin cache key), the dynamic
    frame-of-reference vector, and payload builders."""

    __slots__ = ("sig", "null_sig", "refs")

    def __init__(self, sig, null_sig, refs):
        self.sig = sig            # tuple per ship col (static, hashable)
        self.null_sig = null_sig  # tuple per nullable col
        self.refs = refs          # np.ndarray (n_ship,) int64

    @property
    def encoded(self) -> bool:
        return any(d[0] != "plain" for d in self.sig)


def _col_desc(col: Column):
    if isinstance(col, EncodedColumn):
        if col.kind == "bp":
            return ("bp", col.packed.dtype.str), col.ref
        return ("rle", col.k_cap, col.run_values.dtype.str), 0
    d = np.asarray(col.data)
    if (col.dictionary is not None and d.dtype != object
            and d.dtype.kind in "iu" and d.dtype.itemsize < 8):
        return ("code", d.dtype.str), 0
    return ("plain",), 0


def device_plan(cache, ship_cols, nullable_cols) -> "DevicePlan | None":
    """The consumption plan for ``cache``'s blocks, or None when every
    shipped column is plain (callers keep the legacy pin signatures — an
    unencoded image behaves bit-for-bit as before this module existed).
    Memoized per (cache, enc_version, ship, nullable)."""
    blocks = cache.blocks
    if not blocks:
        return None
    import weakref

    key = (id(cache), getattr(cache, "enc_version", 0),
           tuple(ship_cols), tuple(nullable_cols))
    hit = _PLAN_MEMO.get(key)
    if hit is not None and hit[0]() is cache:
        # the weakref guards id reuse: a dead cache's id may be recycled,
        # but its entry's referent is gone, so a recycled id recomputes
        return hit[1]
    sig, refs = [], []
    for i in ship_cols:
        desc, ref = _col_desc(blocks[0].cols[i])
        sig.append(desc)
        refs.append(ref)
    null_sig = []
    for i in nullable_cols:
        c = blocks[0].cols[i]
        null_sig.append(("rle", c.k_cap) if isinstance(c, EncodedColumn)
                        and c.kind == "rle" else ("plain",))
    plan = DevicePlan(tuple(sig), tuple(null_sig),
                      np.asarray(refs, dtype=np.int64))
    if not plan.encoded:
        plan = None
    _PLAN_MEMO[key] = (weakref.ref(cache), plan)
    while len(_PLAN_MEMO) > _PLAN_MEMO_MAX:
        _PLAN_MEMO.pop(next(iter(_PLAN_MEMO)))
    return plan


def block_payload(col: Column, pad_rows: int, k_cap_pad: int | None = None):
    """The host array(s) to pin for one block column under its descriptor:
    plain/bp/code → the (narrow) row array padded to ``pad_rows``; rle →
    (run_values, run_ends) padded to the column's k_cap (ends padded with
    ``pad_rows`` so padding rows land in an inert pad run)."""
    if isinstance(col, EncodedColumn) and col.kind == "rle":
        k = k_cap_pad or col.k_cap
        rv = np.zeros(k, dtype=col.run_values.dtype)
        rv[: len(col.run_values)] = col.run_values
        re_ = np.full(k, pad_rows, dtype=np.int64)
        re_[: len(col.run_ends)] = col.run_ends
        return rv, re_
    arr = col.packed if isinstance(col, EncodedColumn) else col.data
    arr = np.asarray(arr)
    if len(arr) == pad_rows:
        return arr
    if arr.dtype == object:
        ext = np.empty(pad_rows - len(arr), dtype=object)
        ext[:] = b""
        return np.concatenate([arr, ext])
    return np.concatenate([arr, np.zeros(pad_rows - len(arr), dtype=arr.dtype)])


def block_null_payload(col: Column, pad_rows: int):
    """Null payload: run-shaped for rle columns, padded bool otherwise."""
    if isinstance(col, EncodedColumn) and col.kind == "rle":
        rn = np.ones(col.k_cap, dtype=bool)
        rn[: len(col.run_nulls)] = col.run_nulls
        return rn
    nulls = np.asarray(col.nulls if not isinstance(col, EncodedColumn)
                       else col._nulls)
    if len(nulls) == pad_rows:
        return nulls
    return np.concatenate([nulls, np.ones(pad_rows - len(nulls), dtype=bool)])


def stack_block_payloads(blocks, ship_cols, nullable_cols, plan,
                         pad_rows: int):
    """THE stacked payload assembly shared by every multi-block pin builder
    (``jax_eval._stacked_device`` and the mesh slab pins): per ship col a
    ``(B, rows)`` narrow array — or an ``((B, k), (B, k))`` run pair for
    rle — plus padded null payloads and the frame-of-reference vector.
    Host-side numpy; callers move the leaves to their device."""
    data = []
    for j, i in enumerate(ship_cols):
        payloads = [block_payload(b.cols[i], pad_rows) for b in blocks]
        if plan.sig[j][0] == "rle":
            data.append((np.stack([p[0] for p in payloads]),
                         np.stack([p[1] for p in payloads])))
        else:
            data.append(np.stack([np.asarray(p) for p in payloads]))
    nulls = [
        np.stack([block_null_payload(b.cols[i], pad_rows) for b in blocks])
        for i in nullable_cols
    ]
    return data, nulls, np.asarray(plan.refs)


def batch_plan(caches, ship_cols, nullable_cols, path: str,
               allow_rle: bool = True):
    """Cross-region consumption decision: ONE plan for every cache in the
    batch, or None to decode-ship (counted per-cause — a batch is only as
    encodable as its least compatible region)."""
    plans = [device_plan(c, ship_cols, nullable_cols) for c in caches]
    if all(p is None for p in plans):
        return None  # nothing encoded anywhere: legacy path, not a decline
    if any(p is None for p in plans):
        count_decline(path, "enc_mismatch")
        count_path(path, "decoded_ship")
        return None
    sigs = {(p.sig, p.null_sig) for p in plans}
    if len(sigs) != 1:
        count_decline(path, "enc_mismatch")
        count_path(path, "decoded_ship")
        return None
    if not allow_rle and any(d[0] == "rle" for d in plans[0].sig):
        count_decline(path, "rle_sharded")
        count_path(path, "decoded_ship")
        return None
    count_path(path, "encoded")
    return plans


def late_materialize_chunk(columns, logical):
    """Selection-output late materialization: when any output column is
    encoded, gather the surviving rows THROUGH the encodings (each
    EncodedColumn decodes only its selected rows) instead of letting the
    response encoder materialize whole columns.  Returns (columns,
    logical_rows) — unchanged for fully-plain blocks."""
    if not any(isinstance(c, EncodedColumn) for c in columns):
        return columns, logical
    taken = [c.take(logical) for c in columns]
    return taken, np.arange(len(logical))


# ---------------------------------------------------------------------------
# dictionary code-space predicate rewriting (unary warm path)
# ---------------------------------------------------------------------------

_CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


def dict_rewrite_probe(dag) -> bool:
    """Cheap pre-filter: a TableScan DAG whose selection compares BYTES
    columns against bytes constants MIGHT rewrite into code space.  No
    dictionary inspection here — the endpoint calls this on every CPU-bound
    request, so it must stay allocation-light."""
    from .dag import Aggregation, Selection, TableScan, TopN

    execs = list(getattr(dag, "executors", ()) or ())
    if not execs or type(execs[0]) is not TableScan:
        return False
    sel = next((e for e in execs[1:] if isinstance(e, Selection)), None)
    if sel is None:
        return False
    has_agg = any(isinstance(e, Aggregation) for e in execs[1:])
    if not has_agg and any(isinstance(e, TopN) for e in execs[1:]):
        return False  # raw TopN ships every column as payload (typed)
    bytes_cols = {
        i for i, c in enumerate(execs[0].columns_info)
        if c.ftype.eval_type == EvalType.BYTES
    }
    if not bytes_cols:
        return False
    return any(_rewritable_cond(c, bytes_cols) is not None
               for c in sel.conditions)


def _rewritable_cond(cond, bytes_cols):
    """(col_index, op, consts, flipped) for ``cmp(col, const)`` /
    ``cmp(const, col)`` / ``in(col, consts...)`` over a BYTES column."""
    from .rpn import ColumnRef, Constant, FuncCall

    if not isinstance(cond, FuncCall):
        return None
    ch = cond.children
    def _bytes_const(c):
        return (isinstance(c, Constant)
                and (c.value is None or c.eval_type == EvalType.BYTES))

    if cond.op == "in" and len(ch) >= 2 and isinstance(ch[0], ColumnRef) \
            and ch[0].index in bytes_cols \
            and all(_bytes_const(c) for c in ch[1:]):
        return ch[0].index, "in", [c.value for c in ch[1:]], False
    if cond.op in _CMP_OPS and len(ch) == 2:
        a, b = ch
        if isinstance(a, ColumnRef) and _bytes_const(b) \
                and a.index in bytes_cols:
            return a.index, cond.op, [b.value], False
        if _bytes_const(a) and isinstance(b, ColumnRef) \
                and b.index in bytes_cols:
            return b.index, _FLIP[cond.op], [a.value], True
    return None


def _expr_refs(expr, out: set) -> None:
    """Collect every column index referenced anywhere in an expression."""
    from .rpn import ColumnRef, FuncCall

    if isinstance(expr, ColumnRef):
        out.add(expr.index)
    elif isinstance(expr, FuncCall):
        for c in expr.children:
            _expr_refs(c, out)


def _dict_map_for(dictionary) -> tuple[dict, bool]:
    """(bytes→code map, is_sorted) for a dictionary object, memoized by
    identity (``_code_of`` mutation replaces the object, so a stale entry
    can never serve)."""
    key = id(dictionary)
    hit = _DICT_MAPS.get(key)
    if hit is not None and hit[0] is dictionary:
        return hit[1], hit[2]
    m = {bytes(v): j for j, v in enumerate(dictionary)}
    vals = [bytes(v) for v in dictionary]
    is_sorted = all(vals[j] < vals[j + 1] for j in range(len(vals) - 1))
    _DICT_MAPS[key] = (dictionary, m, is_sorted)
    while len(_DICT_MAPS) > _DICT_MAPS_MAX:
        _DICT_MAPS.pop(next(iter(_DICT_MAPS)))
    return m, is_sorted


def rewrite_dag_for_dict(dag, blocks):
    """Rewrite ``dag``'s bytes predicates into the dictionary code space of
    a WARM image's blocks: the BYTES column's schema entry becomes INT (the
    evaluator then ships codes — already resident — and compares integer
    lanes), equality/IN constants map through the dictionary (absent value
    → code -1, which no row carries), and range constants become
    ``searchsorted`` ranks when the dictionary is SORTED (an unsorted or
    delta-grown dictionary declines range ops — cause ``dict_unsorted``).

    Returns (rewritten DagRequest, rewritten col set) or (None, cause)."""
    from .dag import DagRequest, Selection, TableScan
    from .datatypes import ColumnInfo, FieldType, FieldTypeTp
    from .rpn import ColumnRef, Constant, FuncCall

    from .dag import Aggregation, TopN

    execs = list(dag.executors)
    scan = execs[0]
    bytes_cols = {
        i for i, c in enumerate(scan.columns_info)
        if c.ftype.eval_type == EvalType.BYTES
    }
    sel_pos = next((k for k, e in enumerate(execs) if isinstance(e, Selection)), None)
    if sel_pos is None:
        return None, "no_selection"
    sel = execs[sel_pos]
    if (any(isinstance(e, TopN) for e in execs[1:])
            and not any(isinstance(e, Aggregation) for e in execs[1:])):
        # raw TopN ships EVERY schema column as typed payload — a rewritten
        # column would finalize as integers (probe blocks this too; kept
        # here so direct callers can't serve codes)
        return None, "topn_payload"

    candidates: set[int] = set()
    for cond in sel.conditions:
        rec = _rewritable_cond(cond, bytes_cols)
        if rec is not None:
            candidates.add(rec[0])
    if not candidates:
        return None, "no_rewritable_predicate"

    # a rewritten column's schema entry becomes INT, so ANY reference to it
    # outside its rewritten conjuncts — an aggregate argument, a group-by
    # key, a TopN order, an unrewritable condition — would evaluate (and
    # SERVE) raw dictionary codes instead of the strings.  Those references
    # type-check fine after the flip, so jax_eval.supports cannot catch
    # them: decline here, before any evaluator exists.
    outside: set[int] = set()
    for cond in sel.conditions:
        rec = _rewritable_cond(cond, bytes_cols)
        if rec is None or rec[0] not in candidates:
            _expr_refs(cond, outside)
    for e in execs[1:]:
        if isinstance(e, Aggregation):
            for g in e.group_by:
                _expr_refs(g, outside)
            for a in e.agg_funcs:
                if getattr(a, "expr", None) is not None:
                    _expr_refs(a.expr, outside)
        elif isinstance(e, TopN):
            for expr, _desc in e.order_by:
                _expr_refs(expr, outside)
    candidates -= outside
    if not candidates:
        return None, "outside_reference"

    new_conds = []
    rewritten: set[int] = set()
    for cond in sel.conditions:
        rec = _rewritable_cond(cond, bytes_cols)
        if rec is None or rec[0] not in candidates:
            new_conds.append(cond)
            continue
        ci, op, consts, _flipped = rec
        col0 = blocks[0].cols[ci]
        if col0.dictionary is None or np.asarray(col0.data).dtype == object:
            return None, "not_dict_resident"
        for b in blocks[1:]:
            if b.cols[ci].dictionary is not col0.dictionary:
                return None, "unstable_dictionary"
        cmap, is_sorted = _dict_map_for(col0.dictionary)
        if op in ("eq", "ne"):
            c = consts[0]
            code = None if c is None else cmap.get(bytes(c), -1)
            new_conds.append(FuncCall(op, [ColumnRef(ci),
                                           Constant(code, EvalType.INT)]))
        elif op == "in":
            kept: list[int] = []
            has_null_literal = False
            for orig in consts:
                if orig is None:
                    has_null_literal = True  # keeps IN three-valued
                    continue
                code = cmap.get(bytes(orig))
                if code is not None:
                    kept.append(code)
            if not kept:
                kept.append(-1)  # no row carries code -1
            in_args = [Constant(c, EvalType.INT) for c in kept]
            if has_null_literal:
                in_args.append(Constant(None, EvalType.INT))
            new_conds.append(FuncCall("in", [ColumnRef(ci)] + in_args))
        else:  # lt / le / gt / ge need an ORDER-preserving code space
            if not is_sorted:
                # the endpoint counts every decline once from the returned
                # cause — counting here too would double this one cause
                return None, "dict_unsorted"
            c = consts[0]
            if c is None:
                new_conds.append(FuncCall(op, [ColumnRef(ci),
                                               Constant(None, EvalType.INT)]))
            else:
                vals = [bytes(v) for v in col0.dictionary]
                p_left = int(np.searchsorted(np.array(vals, dtype=object), bytes(c), side="left"))
                p_right = int(np.searchsorted(np.array(vals, dtype=object), bytes(c), side="right"))
                if op == "lt":
                    node = FuncCall("lt", [ColumnRef(ci), Constant(p_left, EvalType.INT)])
                elif op == "le":
                    node = FuncCall("lt", [ColumnRef(ci), Constant(p_right, EvalType.INT)])
                elif op == "gt":
                    node = FuncCall("ge", [ColumnRef(ci), Constant(p_right, EvalType.INT)])
                else:  # ge
                    node = FuncCall("ge", [ColumnRef(ci), Constant(p_left, EvalType.INT)])
                new_conds.append(node)
        rewritten.add(ci)

    new_cols = []
    for i, info in enumerate(scan.columns_info):
        if i in rewritten:
            ft = FieldType(FieldTypeTp.LONGLONG, info.ftype.flag)
            new_cols.append(ColumnInfo(info.col_id, ft, info.is_pk_handle,
                                       info.default_value))
        else:
            new_cols.append(info)
    new_scan = TableScan(scan.table_id, new_cols)
    new_execs = [new_scan] + execs[1:]
    new_execs[sel_pos] = Selection(new_conds)
    # deliberately NOT propagating encode_type: the rewritten plan's STATIC
    # schema lies about the runtime columns (a rewritten bytes column is
    # declared LONGLONG while the served column still materializes bytes
    # through its dictionary), which the value-driven datum encoder never
    # reads but the schema-driven chunk encoder would — so the rewrite rung
    # is datum-only and the endpoint declines it for chunk-negotiated
    # requests (endpoint._try_dict_rewrite)
    return DagRequest(
        executors=new_execs,
        output_offsets=dag.output_offsets,
        chunk_rows=dag.chunk_rows,
    ), rewritten
