"""ANALYZE + CHECKSUM support: table statistics and integrity checksums.

Re-expression of ``src/coprocessor/statistics/{histogram,cmsketch,fmsketch}.rs``
and ``checksum.rs``:

* Histogram — equi-depth buckets over sorted sampled values (lower/upper/
  count/repeats per bucket), the optimizer's selectivity backbone
* CMSketch — count-min sketch (d×w counters) for point-frequency estimates
* FMSketch — Flajolet-Martin distinct-count estimator (mask doubling)
* checksum — crc64-ECMA over the raw kv pairs of a range

Sampling is reservoir-based like analyze.rs; the DAG table-scan leaf feeds
decoded columns in, so device-decoded blocks can be analyzed too.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from . import datum as datum_mod
from ..util import codec

# ---------------------------------------------------------------------------
# crc64-ECMA (checksum.rs uses crc64fast; table-driven here)
# ---------------------------------------------------------------------------

_CRC64_POLY = 0xC96C5795D7870F42
_crc64_table: list[int] = []


def _crc64_init() -> None:
    for i in range(256):
        crc = i
        for _ in range(8):
            if crc & 1:
                crc = (crc >> 1) ^ _CRC64_POLY
            else:
                crc >>= 1
        _crc64_table.append(crc)


_crc64_init()


def crc64(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFFFFFFFFFF
    for b in data:
        crc = _crc64_table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFFFFFFFFFF


def checksum_range(kvs) -> dict:
    """Order-independent crc64 over kv pairs (XOR-combined like the
    reference's checksum so region splits can be merged)."""
    total = 0
    total_kvs = 0
    total_bytes = 0
    for k, v in kvs:
        entry = crc64(codec.encode_compact_bytes(k) + codec.encode_compact_bytes(v))
        total ^= entry
        total_kvs += 1
        total_bytes += len(k) + len(v)
    return {"checksum": total, "total_kvs": total_kvs, "total_bytes": total_bytes}


# ---------------------------------------------------------------------------
# FMSketch (fmsketch.rs)
# ---------------------------------------------------------------------------

class FmSketch:
    def __init__(self, max_size: int = 10000):
        self.mask = 0
        self.max_size = max_size
        self.hash_set: set[int] = set()

    def insert(self, value: bytes) -> None:
        h = crc64(value)
        if (h & self.mask) != 0:
            return
        self.hash_set.add(h)
        while len(self.hash_set) > self.max_size:
            self.mask = (self.mask << 1) | 1
            self.hash_set = {x for x in self.hash_set if (x & self.mask) == 0}

    def ndv(self) -> int:
        return (self.mask + 1) * len(self.hash_set)


# ---------------------------------------------------------------------------
# CMSketch (cmsketch.rs)
# ---------------------------------------------------------------------------

class CmSketch:
    def __init__(self, depth: int = 5, width: int = 2048):
        self.depth = depth
        self.width = width
        self.count = 0
        self.table = [[0] * width for _ in range(depth)]

    def insert(self, value: bytes) -> None:
        self.count += 1
        h = crc64(value)
        h1, h2 = h & 0xFFFFFFFF, h >> 32
        for i in range(self.depth):
            j = (h1 + i * h2) % self.width
            self.table[i][j] += 1

    def query(self, value: bytes) -> int:
        h = crc64(value)
        h1, h2 = h & 0xFFFFFFFF, h >> 32
        return min(self.table[i][(h1 + i * h2) % self.width] for i in range(self.depth))


# ---------------------------------------------------------------------------
# Histogram (histogram.rs)
# ---------------------------------------------------------------------------

@dataclass
class Bucket:
    lower: bytes
    upper: bytes
    count: int  # cumulative
    repeats: int


@dataclass
class Histogram:
    ndv: int = 0
    buckets: list[Bucket] = field(default_factory=list)

    @classmethod
    def build(cls, sorted_values: list[bytes], max_buckets: int = 256) -> "Histogram":
        """Equi-depth histogram from sorted (possibly repeated) values."""
        h = cls()
        n = len(sorted_values)
        if n == 0:
            return h
        per_bucket = max(1, (n + max_buckets - 1) // max_buckets)
        cum = 0
        for v in sorted_values:
            cum += 1
            if h.buckets and h.buckets[-1].upper == v:
                h.buckets[-1].count = cum
                h.buckets[-1].repeats += 1
            elif h.buckets and (h.buckets[-1].count - (h.buckets[-2].count if len(h.buckets) > 1 else 0)) < per_bucket:
                b = h.buckets[-1]
                b.upper = v
                b.count = cum
                b.repeats = 1
                h.ndv += 1
            else:
                h.buckets.append(Bucket(v, v, cum, 1))
                h.ndv += 1
        return h

    def total_count(self) -> int:
        return self.buckets[-1].count if self.buckets else 0


# ---------------------------------------------------------------------------
# Analyze runner (statistics/analyze.rs)
# ---------------------------------------------------------------------------

@dataclass
class AnalyzeColumnsResult:
    histograms: list[Histogram]
    cm_sketches: list[CmSketch]
    fm_sketches: list[FmSketch]
    sampled_rows: int


def analyze_columns(
    executor,
    n_columns: int,
    sample_size: int = 10000,
    max_buckets: int = 256,
    seed: int = 0,
) -> AnalyzeColumnsResult:
    """Drive a batch executor, reservoir-sample rows, build per-column stats."""
    rng = random.Random(seed)
    samples: list[list[bytes]] = [[] for _ in range(n_columns)]
    cms = [CmSketch() for _ in range(n_columns)]
    fms = [FmSketch() for _ in range(n_columns)]
    seen = 0
    while True:
        r = executor.next_batch(1024)
        chunk = r.chunk
        for row in chunk.logical_rows:
            row = int(row)
            encoded = []
            for ci in range(n_columns):
                c = chunk.columns[ci]
                flag, value = c.datum_at(row)
                out = bytearray()
                # memcomparable (for_key) encoding: histogram bucket bounds
                # sort by VALUE order, not varint byte order
                datum_mod.encode_datum(out, flag, value, for_key=True)
                encoded.append(bytes(out))
            for ci in range(n_columns):
                cms[ci].insert(encoded[ci])
                fms[ci].insert(encoded[ci])
                if len(samples[ci]) < sample_size:
                    samples[ci].append(encoded[ci])
                else:
                    j = rng.randrange(seen + 1)
                    if j < sample_size:
                        samples[ci][j] = encoded[ci]
            seen += 1
        if r.is_drained:
            break
    hists = [Histogram.build(sorted(s), max_buckets) for s in samples]
    return AnalyzeColumnsResult(hists, cms, fms, seen)
