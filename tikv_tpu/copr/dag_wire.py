"""DagRequest ⇄ wire dict conversion (the tipb-protobuf role for our RPC)."""

from __future__ import annotations

from .aggr import AggDescriptor
from .dag import (
    Aggregation, DagRequest, IndexScan, Join, Limit, Projection, Selection,
    TableScan, TopN,
)
from .datatypes import ColumnInfo, EvalType, FieldType, FieldTypeTp
from .rpn import ColumnRef, Constant, FuncCall


def expr_to_wire(e) -> dict:
    if isinstance(e, ColumnRef):
        return {"t": "col", "i": e.index}
    if isinstance(e, Constant):
        return {"t": "const", "v": e.value, "et": e.eval_type.value, "frac": e.frac}
    if isinstance(e, FuncCall):
        return {"t": "call", "op": e.op, "args": [expr_to_wire(c) for c in e.children]}
    raise TypeError(e)


def expr_from_wire(d: dict):
    if d["t"] == "col":
        return ColumnRef(d["i"])
    if d["t"] == "const":
        return Constant(d["v"], EvalType(d["et"]), d.get("frac", 0))
    if d["t"] == "call":
        return FuncCall(d["op"], [expr_from_wire(a) for a in d["args"]])
    raise ValueError(d)


def _col_info_to_wire(c: ColumnInfo) -> dict:
    return {
        "id": c.col_id,
        "tp": int(c.ftype.tp),
        "flag": c.ftype.flag,
        "dec": c.ftype.decimal,
        "pk": c.is_pk_handle,
    }


def _col_info_from_wire(d: dict) -> ColumnInfo:
    return ColumnInfo(
        d["id"],
        FieldType(FieldTypeTp(d["tp"]), d.get("flag", 0), decimal=d.get("dec", 0)),
        is_pk_handle=d.get("pk", False),
    )


def _exec_to_wire(e) -> dict:
    if isinstance(e, TableScan):
        return {"t": "table_scan", "table_id": e.table_id,
                "cols": [_col_info_to_wire(c) for c in e.columns_info]}
    if isinstance(e, IndexScan):
        return {"t": "index_scan", "table_id": e.table_id, "index_id": e.index_id,
                "cols": [_col_info_to_wire(c) for c in e.columns_info]}
    if isinstance(e, Selection):
        return {"t": "selection", "conds": [expr_to_wire(c) for c in e.conditions]}
    if isinstance(e, Aggregation):
        return {
            "t": "agg",
            "group_by": [expr_to_wire(g) for g in e.group_by],
            "aggs": [{"op": a.op, "expr": expr_to_wire(a.expr) if a.expr else None} for a in e.agg_funcs],
            "streamed": e.streamed,
        }
    if isinstance(e, TopN):
        return {"t": "topn", "limit": e.limit,
                "order_by": [[expr_to_wire(x), desc] for x, desc in e.order_by]}
    if isinstance(e, Limit):
        return {"t": "limit", "limit": e.limit}
    if isinstance(e, Projection):
        return {"t": "projection", "exprs": [expr_to_wire(x) for x in e.exprs]}
    if isinstance(e, Join):
        d = {"t": "join", "join_type": e.join_type,
             "left_key": e.left_key, "right_key": e.right_key,
             "build": [_exec_to_wire(b) for b in e.build],
             "build_ranges": [[s, x] for s, x in e.build_ranges]}
        if e.build_context is not None:
            d["build_context"] = dict(e.build_context)
        return d
    raise TypeError(e)


def dag_to_wire(dag: DagRequest) -> dict:
    execs = [_exec_to_wire(e) for e in dag.executors]
    d = {"executors": execs, "output_offsets": dag.output_offsets, "chunk_rows": dag.chunk_rows}
    if dag.encode_type:
        # emitted only when non-default so pre-chunk plan bytes (and every
        # memo/evaluator key derived from them) are unchanged
        d["encode_type"] = dag.encode_type
    return d


def _exec_from_wire(e: dict):
    t = e["t"]
    if t == "table_scan":
        return TableScan(e["table_id"], [_col_info_from_wire(c) for c in e["cols"]])
    if t == "index_scan":
        return IndexScan(e["table_id"], e["index_id"], [_col_info_from_wire(c) for c in e["cols"]])
    if t == "selection":
        return Selection([expr_from_wire(c) for c in e["conds"]])
    if t == "agg":
        return Aggregation(
            [expr_from_wire(g) for g in e["group_by"]],
            [AggDescriptor(a["op"], expr_from_wire(a["expr"]) if a["expr"] else None) for a in e["aggs"]],
            streamed=e.get("streamed", False),
        )
    if t == "topn":
        return TopN([(expr_from_wire(x), desc) for x, desc in e["order_by"]], e["limit"])
    if t == "limit":
        return Limit(e["limit"])
    if t == "projection":
        return Projection([expr_from_wire(x) for x in e["exprs"]])
    if t == "join":
        ctx = e.get("build_context")
        if ctx is not None and "region_epoch" in ctx:
            ctx = dict(ctx, region_epoch=tuple(ctx["region_epoch"]))
        return Join(
            [_exec_from_wire(b) for b in e["build"]],
            [(s, x) for s, x in e["build_ranges"]],
            e["left_key"], e["right_key"],
            join_type=e.get("join_type", "inner"),
            build_context=ctx,
        )
    raise ValueError(t)


def dag_from_wire(d: dict) -> DagRequest:
    execs = [_exec_from_wire(e) for e in d["executors"]]
    return DagRequest(executors=execs, output_offsets=d.get("output_offsets"),
                      chunk_rows=d.get("chunk_rows", 1024),
                      encode_type=d.get("encode_type", 0))
