"""DagRequest ⇄ wire dict conversion (the tipb-protobuf role for our RPC)."""

from __future__ import annotations

from .aggr import AggDescriptor
from .dag import Aggregation, DagRequest, IndexScan, Limit, Selection, TableScan, TopN
from .datatypes import ColumnInfo, EvalType, FieldType, FieldTypeTp
from .rpn import ColumnRef, Constant, FuncCall


def expr_to_wire(e) -> dict:
    if isinstance(e, ColumnRef):
        return {"t": "col", "i": e.index}
    if isinstance(e, Constant):
        return {"t": "const", "v": e.value, "et": e.eval_type.value, "frac": e.frac}
    if isinstance(e, FuncCall):
        return {"t": "call", "op": e.op, "args": [expr_to_wire(c) for c in e.children]}
    raise TypeError(e)


def expr_from_wire(d: dict):
    if d["t"] == "col":
        return ColumnRef(d["i"])
    if d["t"] == "const":
        return Constant(d["v"], EvalType(d["et"]), d.get("frac", 0))
    if d["t"] == "call":
        return FuncCall(d["op"], [expr_from_wire(a) for a in d["args"]])
    raise ValueError(d)


def _col_info_to_wire(c: ColumnInfo) -> dict:
    return {
        "id": c.col_id,
        "tp": int(c.ftype.tp),
        "flag": c.ftype.flag,
        "dec": c.ftype.decimal,
        "pk": c.is_pk_handle,
    }


def _col_info_from_wire(d: dict) -> ColumnInfo:
    return ColumnInfo(
        d["id"],
        FieldType(FieldTypeTp(d["tp"]), d.get("flag", 0), decimal=d.get("dec", 0)),
        is_pk_handle=d.get("pk", False),
    )


def dag_to_wire(dag: DagRequest) -> dict:
    execs = []
    for e in dag.executors:
        if isinstance(e, TableScan):
            execs.append({"t": "table_scan", "table_id": e.table_id,
                          "cols": [_col_info_to_wire(c) for c in e.columns_info]})
        elif isinstance(e, IndexScan):
            execs.append({"t": "index_scan", "table_id": e.table_id, "index_id": e.index_id,
                          "cols": [_col_info_to_wire(c) for c in e.columns_info]})
        elif isinstance(e, Selection):
            execs.append({"t": "selection", "conds": [expr_to_wire(c) for c in e.conditions]})
        elif isinstance(e, Aggregation):
            execs.append({
                "t": "agg",
                "group_by": [expr_to_wire(g) for g in e.group_by],
                "aggs": [{"op": a.op, "expr": expr_to_wire(a.expr) if a.expr else None} for a in e.agg_funcs],
                "streamed": e.streamed,
            })
        elif isinstance(e, TopN):
            execs.append({"t": "topn", "limit": e.limit,
                          "order_by": [[expr_to_wire(x), desc] for x, desc in e.order_by]})
        elif isinstance(e, Limit):
            execs.append({"t": "limit", "limit": e.limit})
        else:
            raise TypeError(e)
    d = {"executors": execs, "output_offsets": dag.output_offsets, "chunk_rows": dag.chunk_rows}
    if dag.encode_type:
        # emitted only when non-default so pre-chunk plan bytes (and every
        # memo/evaluator key derived from them) are unchanged
        d["encode_type"] = dag.encode_type
    return d


def dag_from_wire(d: dict) -> DagRequest:
    execs = []
    for e in d["executors"]:
        t = e["t"]
        if t == "table_scan":
            execs.append(TableScan(e["table_id"], [_col_info_from_wire(c) for c in e["cols"]]))
        elif t == "index_scan":
            execs.append(IndexScan(e["table_id"], e["index_id"], [_col_info_from_wire(c) for c in e["cols"]]))
        elif t == "selection":
            execs.append(Selection([expr_from_wire(c) for c in e["conds"]]))
        elif t == "agg":
            execs.append(
                Aggregation(
                    [expr_from_wire(g) for g in e["group_by"]],
                    [AggDescriptor(a["op"], expr_from_wire(a["expr"]) if a["expr"] else None) for a in e["aggs"]],
                    streamed=e.get("streamed", False),
                )
            )
        elif t == "topn":
            execs.append(TopN([(expr_from_wire(x), desc) for x, desc in e["order_by"]], e["limit"]))
        elif t == "limit":
            execs.append(Limit(e["limit"]))
        else:
            raise ValueError(t)
    return DagRequest(executors=execs, output_offsets=d.get("output_offsets"),
                      chunk_rows=d.get("chunk_rows", 1024),
                      encode_type=d.get("encode_type", 0))
