"""String collations: binary, utf8mb4_bin, utf8mb4_general_ci, utf8mb4_unicode_ci.

Re-expression of ``tidb_query_datatype/src/codec/collation`` (collator/mod.rs
+ collator/{binary,utf8mb4_binary,utf8mb4_general_ci,unicode_ci}.rs): each
collation produces a **sort key** such that bytewise comparison of sort keys
equals collated comparison of the strings.  That shape is deliberately
TPU-friendly: collation happens once per value on the host (sort keys are
just bytes), and everything downstream — comparisons, group-by dictionaries,
min/max — stays the byte machinery it already was.

Semantics mirrored from the reference:
* ``binary``: raw bytes, NO PAD.
* ``utf8mb4_bin``: codepoint order with PAD SPACE (trailing spaces ignored,
  like the reference's trimmed utf8mb4_bin).
* ``utf8mb4_general_ci``: per-BMP-character weight = uppercased codepoint
  (supplementary planes collapse to 0xFFFD), PAD SPACE — the same
  plane-table outcome as general_ci for the common cases.
* ``utf8mb4_unicode_ci``: UCA primary-weight comparison (case- AND
  accent-insensitive), PAD SPACE.  The reference ships MySQL's UCA 4.0.0
  weight table (collator/unicode_ci_data.rs); this framework derives the
  primary weights algorithmically from the Unicode database shipped with
  CPython — NFKD decomposition drops combining marks (accents), casefold
  collapses case and ß→ss-style expansions, and supplementary-plane
  characters collapse to 0xFFFD exactly like MySQL's old unicode_ci.  The
  outcome matches the reference for the case/accent/expansion families its
  tests exercise; exotic tailorings may order differently (documented
  deviation, not silent).
"""

from __future__ import annotations

import unicodedata

PADDING_SPACE = ord(" ")


def _general_ci_weight(ch: str) -> int:
    cp = ord(ch)
    if cp > 0xFFFF:
        return 0xFFFD
    up = ch.upper()
    # multi-char expansions (ß→SS) collapse to their first char, matching
    # general_ci's single-weight-per-character model
    return ord(up[0]) if up else cp


class Collator:
    name = "binary"
    is_ci = False

    def sort_key(self, raw: bytes) -> bytes:
        return raw

    def compare(self, a: bytes, b: bytes) -> int:
        ka, kb = self.sort_key(a), self.sort_key(b)
        return (ka > kb) - (ka < kb)

    def eq(self, a: bytes, b: bytes) -> bool:
        return self.sort_key(a) == self.sort_key(b)


class BinaryCollator(Collator):
    name = "binary"


class Utf8Mb4BinCollator(Collator):
    name = "utf8mb4_bin"

    def sort_key(self, raw: bytes) -> bytes:
        # PAD SPACE: trailing spaces carry no weight
        text = raw.decode("utf-8", "replace").rstrip(" ")
        out = bytearray()
        for ch in text:
            out += ord(ch).to_bytes(3, "big")
        return bytes(out)


class Utf8Mb4GeneralCiCollator(Collator):
    name = "utf8mb4_general_ci"
    is_ci = True

    def sort_key(self, raw: bytes) -> bytes:
        text = raw.decode("utf-8", "replace").rstrip(" ")
        out = bytearray()
        for ch in text:
            out += _general_ci_weight(ch).to_bytes(2, "big")
        return bytes(out)


def _unicode_primary(text: str) -> list[int]:
    """Primary UCA-style weights: accents and case carry no weight."""
    out: list[int] = []
    for ch in text:
        # decompose, drop combining marks, fold case (ß→ss, ﬁ→fi, …)
        for d in unicodedata.normalize("NFKD", ch):
            if unicodedata.combining(d):
                continue
            for f in d.casefold():
                cp = ord(f)
                if unicodedata.combining(f):
                    continue
                out.append(0xFFFD if cp > 0xFFFF else cp)
    return out


class Utf8Mb4UnicodeCiCollator(Collator):
    name = "utf8mb4_unicode_ci"
    is_ci = True

    def sort_key(self, raw: bytes) -> bytes:
        text = raw.decode("utf-8", "replace").rstrip(" ")
        out = bytearray()
        for w in _unicode_primary(text):
            out += w.to_bytes(2, "big")
        return bytes(out)


_COLLATORS: dict[str, Collator] = {
    c.name: c
    for c in (
        BinaryCollator(),
        Utf8Mb4BinCollator(),
        Utf8Mb4GeneralCiCollator(),
        Utf8Mb4UnicodeCiCollator(),
    )
}
# TiDB collation ids (mysql/consts: 63 binary, 46 utf8mb4_bin, 45 general_ci,
# 224 unicode_ci); negative ids are how tipb marks "new collation enabled"
_BY_ID = {
    63: "binary",
    46: "utf8mb4_bin",
    45: "utf8mb4_general_ci",
    224: "utf8mb4_unicode_ci",
    # utf8 ids fold onto their utf8mb4 collators (same ordering rules here)
    33: "utf8mb4_general_ci",
    83: "utf8mb4_bin",
    192: "utf8mb4_unicode_ci",
}


def collation_name(coll_id: int, default: str = "binary") -> str:
    """MySQL collation id (negative = new-collation namespace) -> collator
    name; unknown ids fall back to ``default``."""
    return _BY_ID.get(abs(coll_id), default)


def get_collator(name_or_id) -> Collator:
    if isinstance(name_or_id, int):
        name = _BY_ID.get(abs(name_or_id))
        if name is None:
            raise ValueError(f"unsupported collation id {name_or_id}")
        return _COLLATORS[name]
    c = _COLLATORS.get(name_or_id)
    if c is None:
        raise ValueError(f"unsupported collation {name_or_id!r}")
    return c
