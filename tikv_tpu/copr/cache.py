"""Columnar block cache — the TPU-first re-expression of the coprocessor cache.

The reference caches *response bytes* keyed by region version
(``src/coprocessor/cache.rs:10``): a repeated identical request on an
unchanged region skips execution.  A TPU evaluator wants a deeper cache: the
expensive shared work is MVCC scan + row→column decode + host→device
transfer, and it is the same for EVERY query over that data.  So this cache
holds decoded column blocks keyed by (region/range, data-version ts):

* any query shape over the cached range skips scan+decode (CPU and TPU both)
* the device path additionally pins each block's arrays in HBM on first use,
  so steady-state queries are pure on-device compute — no PCIe/tunnel traffic

Invalidation follows the reference's rule: the key includes the region's data
version (apply index / max commit ts), so any write produces a new key.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..analysis import bufsan as _bufsan
from ..analysis.sanitizer import make_lock


# per-block pinned signatures: stacked + nvoff + zone layout + sharded slab
# stacks must coexist on a warm image without evicting each other
_MAX_DEVICE_SIGS = 6


def _pin_kind(sig: tuple) -> str:
    """Pin-signature family for the observatory's HBM watermarks: named
    kinds lead their sig tuple ("stackedenc", "blockenc", "nvoff",
    "zone_layout", "shardslab"); the plain stacked pin leads with its
    column tuple."""
    return sig[0] if sig and isinstance(sig[0], str) else "stacked"


def _entry_nbytes(entry) -> int:
    """Device bytes of one pinned entry (zone layouts report their ``dev``
    tree) — the same figure device_nbytes() sums."""
    import jax

    tree = getattr(entry, "dev", entry)
    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree.leaves(tree))


@dataclass
class _Block:
    cols: list  # list[Column] (host)
    n_valid: int
    device: dict = field(default_factory=dict)  # (cols-sig) -> (data, nulls) jnp lists
    # per-column prune statistics, built lazily by zone_maps.ensure_zones;
    # None = not built yet (fresh fills / structural repacks start here)
    zones: dict | None = None


class ColumnBlockCache:
    """Decoded blocks for one (range, version) — build once, evaluate many."""

    def __init__(self, key=None):
        self.key = key
        self.blocks: list[_Block] = []
        self.filled = False
        # sharded placement metadata (RegionColumnCache in mesh mode): one
        # owner device id per block; None = single-device (default-device
        # pins).  parallel.mesh.launch_xregion_sharded reads this to pin
        # each slab on its owner.
        self.owner_devices: list[int] | None = None
        # bumped whenever column encodings change (fill-time encode, delta
        # demotion, code-lane widening) — the device-plan memo and the
        # encoded pin signatures key on it (copr/encoding.py)
        self.enc_version = 0
        self._mu = make_lock("copr.block_cache")

    def add(self, cols, n_valid: int) -> None:
        self.blocks.append(_Block(cols, n_valid))

    def __iter__(self):
        return iter((b.cols, b.n_valid) for b in self.blocks)

    @property
    def total_rows(self) -> int:
        return sum(b.n_valid for b in self.blocks)

    def device_arrays(self, block: _Block, sig: tuple, build) -> tuple:
        """Per-block device arrays for a plan signature, pinned on first use.
        Bounded per block: each distinct signature pins a full copy, so old
        signatures are dropped LRU-style once _MAX_DEVICE_SIGS accumulate.
        Pin/unpin byte deltas feed the observatory's per-path HBM
        watermarks (docs/observatory.md)."""
        with self._mu:
            hit = block.device.get(sig)
            if hit is not None:
                # touch for LRU order
                block.device.pop(sig)
                block.device[sig] = hit
                return hit
        built = build(block)
        with self._mu:
            added = sig not in block.device
            block.device.setdefault(sig, built)
            dropped = []
            while len(block.device) > _MAX_DEVICE_SIGS:
                old_sig = next(iter(block.device))
                dropped.append((old_sig, block.device.pop(old_sig)))
            out = block.device[sig]
        if added or dropped:
            from .observatory import OBSERVATORY

            if added:
                OBSERVATORY.note_pin(_pin_kind(sig), _entry_nbytes(built))
                # pins are exposures: the host arrays behind them must only
                # change through scatter_update (which re-registers); a pin
                # whose sample fails at drop took a bypass write
                _bufsan.export("device_pin", built, site="cache.device_arrays")
            for old_sig, entry in dropped:
                OBSERVATORY.note_pin(_pin_kind(old_sig), -_entry_nbytes(entry))
                _bufsan.release(entry, site="cache.device_arrays.lru")
        return out

    def nbytes(self) -> int:
        """RESIDENT byte footprint of the blocks — encoded bytes for
        encoded columns (docs/compressed_columns.md), the decoded-array
        footprint otherwise.  Budgets and gauges use this figure: encoded
        images cost what their payload costs, which is what multiplies
        warm capacity under a fixed byte budget."""
        from .encoding import column_nbytes

        return sum(column_nbytes(c) for b in self.blocks for c in b.cols)

    def nbytes_decoded(self) -> int:
        """What the blocks WOULD cost fully decoded — the numerator of the
        compression-ratio gauge."""
        from .encoding import column_decoded_nbytes

        return sum(column_decoded_nbytes(c) for b in self.blocks for c in b.cols)

    def device_nbytes(self) -> int:
        """TRUE bytes currently pinned on devices for this cache, summed
        over every pinned signature's arrays (zone layouts report their
        ``dev`` tree).  This is the figure behind
        ``tikv_coprocessor_region_cache_device_pinned_bytes`` — with
        encoded residency it reflects the narrow/encoded payloads actually
        in HBM, not a host-side proxy."""
        import jax

        total = 0
        with self._mu:
            for b in self.blocks:
                for entry in b.device.values():
                    tree = getattr(entry, "dev", entry)
                    for leaf in jax.tree.leaves(tree):
                        total += int(getattr(leaf, "nbytes", 0) or 0)
        return total

    def clear_blocks(self) -> None:
        """Drop every block AND its pinned device copies.  The one correct
        way to discard blocks: a raw ``blocks.clear()`` would strand the
        pinned entries' bytes in the observatory's HBM gauges forever
        (the arrays themselves are freed by GC; the accounting is not)."""
        self.drop_device()
        self.blocks.clear()

    def drop_device(self) -> None:
        """Unpin every device copy; host blocks stay.  The next query
        re-transfers from host (no decode)."""
        with self._mu:
            dropped = [
                (sig, entry)
                for b in self.blocks
                for sig, entry in b.device.items()
            ]
            for b in self.blocks:
                b.device.clear()
        if dropped:
            from .observatory import OBSERVATORY

            for sig, entry in dropped:
                OBSERVATORY.note_pin(_pin_kind(sig), -_entry_nbytes(entry))
                _bufsan.release(entry, site="cache.drop_device")

    def scatter_update(self, updates: dict) -> None:
        """Patch pinned device arrays in place after an in-place host update.

        ``updates``: block_idx -> (row_positions int array, {col_idx:
        (values ndarray, nulls ndarray)}).  Host column arrays must already
        hold the new values.  Understands the two pinned layouts the
        evaluators build — the per-cache stacked arrays and per-block column
        lists — and patches them with ``.at[].set`` scatters (a device-side
        op; the base arrays never round-trip to host).  Any other signature
        (zone layouts, mesh ``shardslab`` stacks; nvoff is kept — row counts
        are unchanged) is dropped so it rebuilds from the updated host
        blocks on its owner device."""
        from . import zone_maps as _zm

        released, repinned = [], []
        with self._mu:
            for bi, blk in enumerate(self.blocks):
                upd = updates.get(bi)
                if upd is not None and blk.zones is not None:
                    # widen the block's zone map with the incoming values —
                    # stale-but-sound maintenance (docs/zone_maps.md); the
                    # host columns already hold these values
                    _zm.fold_update(blk.zones, upd[1])
                for sig in list(blk.device):
                    kind = sig[0]
                    if kind == "nvoff":
                        continue  # in-place updates never change row counts
                    if kind in ("stackedenc", "blockenc"):
                        # encoded pins hold narrow/run payloads: a decoded-
                        # domain scatter cannot patch them in place (the
                        # ref/run structure lives in the encoding) — drop,
                        # and the next serve re-pins from the updated host
                        # payload (which try_patch/demote kept truthful)
                        released.append(blk.device.pop(sig))
                    elif kind == "stacked":
                        old = blk.device[sig]
                        blk.device[sig] = self._patch_stacked(old, sig, updates)
                        repinned.append((old, blk.device[sig]))
                    elif isinstance(kind, tuple):
                        if upd is None:
                            continue
                        old = blk.device[sig]
                        blk.device[sig] = self._patch_block(old, sig, upd)
                        repinned.append((old, blk.device[sig]))
                    else:
                        released.append(blk.device.pop(sig))
        # the mutation choke point for pins: scatter IS the coordinated
        # host-mutate-then-patch path, so patched pins re-register (new
        # sample) and dropped pins release-verify (docs/static_analysis.md)
        for entry in released:
            _bufsan.release(entry, site="cache.scatter_update")
        for old, new in repinned:
            _bufsan.release(old, site="cache.scatter_update")
            _bufsan.export("device_pin", new, site="cache.scatter_update")

    @staticmethod
    def _patch_stacked(entry, sig, updates):
        """sig = ("stacked", ship_cols, nullable, block_rows); entry =
        (data_tuple[(B, rows)] per ship col, nulls_tuple per nullable col)."""
        _, ship_cols, nullable, _rows = sig
        data, nulls = entry
        data = list(data)
        nulls = list(nulls)
        for bi, (pos, cols) in updates.items():
            for ci, (vals, nl) in cols.items():
                if ci in ship_cols:
                    j = ship_cols.index(ci)
                    vals = np.asarray(vals).astype(data[j].dtype, copy=False)
                    data[j] = data[j].at[bi, pos].set(vals)
                if ci in nullable:
                    j = nullable.index(ci)
                    nulls[j] = nulls[j].at[bi, pos].set(np.asarray(nl))
        return tuple(data), tuple(nulls)

    @staticmethod
    def _patch_block(entry, sig, upd):
        """sig = (device_cols, nullable_cols, block_rows); entry =
        ([data per device col], [nulls per nullable col]) for ONE block."""
        dev_cols, nullable, _rows = sig
        pos, cols = upd
        data, nulls = list(entry[0]), list(entry[1])
        for ci, (vals, nl) in cols.items():
            if ci in dev_cols:
                j = dev_cols.index(ci)
                data[j] = data[j].at[pos].set(np.asarray(vals))
            if ci in nullable:
                j = nullable.index(ci)
                nulls[j] = nulls[j].at[pos].set(np.asarray(nl))
        return data, nulls


class CopCache:
    """Top-level cache registry keyed by (region_id, range, version)."""

    def __init__(self, max_entries: int = 64):
        self.max_entries = max_entries
        self._entries: dict = {}
        self._order: list = []
        self._mu = make_lock("copr.cop_cache")

    def get_or_create(self, key) -> ColumnBlockCache:
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                e = ColumnBlockCache(key)
                self._entries[key] = e
                self._order.append(key)
                while len(self._order) > self.max_entries:
                    old = self._order.pop(0)
                    del self._entries[old]
            else:
                # LRU touch so hot entries survive cold churn
                self._order.remove(key)
                self._order.append(key)
            return e
