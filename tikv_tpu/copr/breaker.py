"""Device-path circuit breaker: trip a faulting serving path to its fallback.

The coprocessor has five serving paths — zone full-tile, fused batch,
cross-region (``xregion``), mesh-sharded, and the per-request unary device
path — each with a slower-but-always-correct fallback (generic warm path,
per-request serving, single-device launch, CPU pipeline).  A single device
fault already falls back per request; what that does NOT protect against is
a *persistently* wedged path (bad driver state, a compiler regression on one
program shape, a flaky interconnect) re-paying the failure latency on every
request forever.

Classic breaker states per path (docs/robustness.md):

* **closed** — healthy; failures below the threshold just count.
* **open** — ``threshold`` consecutive failures tripped the path: every
  ``allow()`` is refused (callers take their fallback immediately) until the
  cooldown elapses.  Repeated trips grow the cooldown exponentially up to a
  ceiling.
* **half-open** — cooldown elapsed: exactly ONE caller is admitted as a
  probe.  Success restores the path (closed, counters reset); failure
  re-opens with a longer cooldown.

Metrics: ``tikv_coprocessor_breaker_event_total{path,event}`` with
``event ∈ {trip, probe, restore}`` and the state gauge
``tikv_coprocessor_breaker_state{path}`` (0 closed / 1 open / 2 half-open).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..analysis.sanitizer import make_lock

PATHS = ("unary", "zone", "fused", "xregion", "mesh")

_STATE_VALUE = {"closed": 0, "open": 1, "half_open": 2}


@dataclass
class _PathState:
    state: str = "closed"
    failures: int = 0       # consecutive failures while closed
    trips: int = 0          # consecutive trips (drives cooldown growth)
    open_until: float = 0.0
    probing: bool = False   # a half-open probe is in flight


@dataclass(frozen=True)
class BreakerConfig:
    threshold: int = 3          # consecutive failures that trip a path
    cooldown_s: float = 5.0     # first-trip cooldown
    cooldown_multiplier: float = 2.0
    max_cooldown_s: float = 60.0


class DeviceCircuitBreaker:
    """Thread-safe per-path breaker shared by the endpoint, the read
    scheduler, and the zone evaluator.  ``clock`` is injectable for tests."""

    def __init__(self, config: BreakerConfig | None = None, clock=time.monotonic):
        self.cfg = config or BreakerConfig()
        self.clock = clock
        self._mu = make_lock("copr.breaker")
        self._paths: dict[str, _PathState] = {}

    def _st(self, path: str) -> _PathState:
        st = self._paths.get(path)
        if st is None:
            st = self._paths[path] = _PathState()
        return st

    def allow(self, path: str) -> bool:
        """May this path serve now?  False = take the fallback.  When an
        open path's cooldown has elapsed, the FIRST caller through becomes
        the half-open probe (exactly one in flight)."""
        with self._mu:
            st = self._st(path)
            if st.state == "closed":
                return True
            if st.state == "open" and self.clock() >= st.open_until:
                st.state = "half_open"
                self._gauge(path, st)
            if st.state == "half_open" and not st.probing:
                st.probing = True
                self._event(path, "probe")
                return True
            return False

    def record_success(self, path: str) -> None:
        with self._mu:
            st = self._st(path)
            if st.state != "closed":
                self._event(path, "restore")
            st.state = "closed"
            st.failures = 0
            st.trips = 0
            st.probing = False
            self._gauge(path, st)

    def release_probe(self, path: str) -> None:
        """The admitted caller neither succeeded nor failed (a documented
        decline took its fallback before the path actually ran): free the
        half-open probe slot so the next caller can probe.  No-op when the
        path is closed."""
        with self._mu:
            self._st(path).probing = False

    def record_failure(self, path: str) -> None:
        with self._mu:
            st = self._st(path)
            if st.state == "half_open":
                # the probe failed: straight back to open, longer cooldown
                st.probing = False
                self._trip(path, st)
                return
            if st.state == "open":
                return  # late failure from a pre-trip launch: already open
            st.failures += 1
            if st.failures >= self.cfg.threshold:
                self._trip(path, st)
            else:
                self._gauge(path, st)

    def state_of(self, path: str) -> str:
        with self._mu:
            st = self._st(path)
            if st.state == "open" and self.clock() >= st.open_until:
                return "half_open"
            return st.state

    def _trip(self, path: str, st: _PathState) -> None:
        st.trips += 1
        cooldown = min(
            self.cfg.cooldown_s * (self.cfg.cooldown_multiplier ** (st.trips - 1)),
            self.cfg.max_cooldown_s,
        )
        st.state = "open"
        st.open_until = self.clock() + cooldown
        st.failures = 0
        self._event(path, "trip")
        self._gauge(path, st)

    # -- metrics (called under _mu: REGISTRY ops are lock-free-ish counters)

    def _event(self, path: str, event: str) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.counter(
            "tikv_coprocessor_breaker_event_total",
            "Device-path circuit breaker transitions, by path and event",
        ).inc(path=path, event=event)

    def _gauge(self, path: str, st: _PathState) -> None:
        from ..util.metrics import REGISTRY

        REGISTRY.gauge(
            "tikv_coprocessor_breaker_state",
            "Breaker state per device path (0 closed / 1 open / 2 half-open)",
        ).set(_STATE_VALUE[st.state], path=path)
