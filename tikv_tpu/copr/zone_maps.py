"""Per-block zone maps: min/max/null-count pruning statistics.

The compressed-resident stats pass (``copr/encoding.py``) already bounds
every encoded block — frame-of-reference bitpack carries its frame, RLE its
run values, dictionary columns their code range.  This module turns those
bounds (plus a cheap masked min/max for plain numeric columns) into
*prunable* per-block zone maps, and evaluates a served DAG's selection
conjuncts against them so the device paths skip blocks that provably hold
no qualifying row (docs/zone_maps.md).

Soundness contract — the only invariant pruning relies on:

* every NON-NULL value ``v`` of the column in the block satisfies
  ``lo <= v <= hi`` (``lo is None`` means the block never held a non-null
  value for this column);
* the block's null count lies within ``[null_lo, null_hi]``.

Bounds may be WIDER than the true range ("stale-but-sound"): an in-place
write-through fold widens ``lo``/``hi`` with the incoming values and flags
the zone stale, because an overwrite may have removed the extremal row —
rescanning would defeat the point of a fold.  Structural deltas (inserts /
deletes) repack blocks into fresh ``_Block`` objects, so their zones simply
rebuild lazily from the new data.

Dictionary columns are tracked in CODE (rank) space: the serve-time
conjuncts arriving here were produced by ``rewrite_dag_for_dict``
(docs/compressed_columns.md), whose constants are codes/ranks too, so the
comparison needs no value-space translation.  Plain BYTES/JSON columns are
untracked — blocks always survive predicates over them.
"""

from __future__ import annotations

import os

import numpy as np

from .rpn import RpnExpression
from ..util.metrics import REGISTRY

__all__ = [
    "ColumnZone", "build_block_zones", "ensure_zones", "fold_update",
    "prune_blocks", "count_prune", "enabled", "set_enabled", "PruneStats",
]


def _env_enabled() -> bool:
    return os.environ.get("TIKV_TPU_ZONE_PRUNE", "1") != "0"


_ENABLED: bool | None = None  # None = follow the environment


def enabled() -> bool:
    return _env_enabled() if _ENABLED is None else _ENABLED


def set_enabled(on: bool | None) -> None:
    """Test/bench kill switch (None = defer to TIKV_TPU_ZONE_PRUNE)."""
    global _ENABLED
    _ENABLED = on


def count_prune(path: str, outcome: str, n: int = 1) -> None:
    if n:
        REGISTRY.counter(
            "tikv_coprocessor_zone_prune_total",
            "Zone-map prune decisions by serving path and outcome",
        ).inc(n, path=path, outcome=outcome)


class ColumnZone:
    """Value/null bounds for ONE column of ONE block (see module contract)."""

    __slots__ = ("lo", "hi", "null_lo", "null_hi", "n", "stale")

    def __init__(self, lo, hi, null_lo: int, null_hi: int, n: int,
                 stale: bool = False):
        self.lo = lo
        self.hi = hi
        self.null_lo = int(null_lo)
        self.null_hi = int(null_hi)
        self.n = int(n)
        self.stale = stale

    def __repr__(self) -> str:  # debugging / test output only
        return (f"ColumnZone(lo={self.lo}, hi={self.hi}, "
                f"nulls=[{self.null_lo},{self.null_hi}]/{self.n}"
                f"{', stale' if self.stale else ''})")


def _scalar(v):
    """Numpy scalar → exact Python number (int64 math must not wrap when a
    decimal alignment factor multiplies it later)."""
    return v.item() if hasattr(v, "item") else v


def _zone_of_column(col, n_valid: int) -> ColumnZone | None:
    """Zone for one column, reading the ENCODED payload where one is
    resident (no decode).  None = untracked (object payloads)."""
    from .encoding import EncodedColumn

    if isinstance(col, EncodedColumn):
        if col.kind == "bp":
            nulls = np.asarray(col._nulls[:n_valid])
            live = ~nulls
            nn = int(nulls.sum())
            if not live.any():
                return ColumnZone(None, None, nn, nn, n_valid)
            pk = np.asarray(col.packed[:n_valid])[live]
            return ColumnZone(_scalar(pk.min()) + col.ref,
                              _scalar(pk.max()) + col.ref, nn, nn, n_valid)
        # rle: only runs intersecting the valid prefix count
        ends = np.asarray(col.run_ends)
        starts = np.concatenate([[0], ends[:-1]])
        sel = starts < n_valid
        rv = np.asarray(col.run_values)[sel]
        rn = np.asarray(col.run_nulls)[sel]
        spans = np.minimum(ends[sel], n_valid) - starts[sel]
        nn = int(spans[rn].sum())
        live = rv[~rn]
        if len(live) == 0:
            return ColumnZone(None, None, nn, nn, n_valid)
        return ColumnZone(_scalar(live.min()), _scalar(live.max()),
                          nn, nn, n_valid)
    data = np.asarray(col.data)
    if data.dtype == object:
        return None  # raw BYTES/JSON: untracked
    nulls = np.asarray(col.nulls[:n_valid])
    nn = int(nulls.sum())
    live = ~nulls
    if not live.any():
        return ColumnZone(None, None, nn, nn, n_valid)
    d = data[:n_valid][live]
    return ColumnZone(_scalar(d.min()), _scalar(d.max()), nn, nn, n_valid)


def build_block_zones(cols, n_valid: int) -> dict[int, ColumnZone]:
    """Zones for every trackable column of one block."""
    zones: dict[int, ColumnZone] = {}
    if n_valid <= 0:
        return zones
    for ci, col in enumerate(cols):
        try:
            z = _zone_of_column(col, n_valid)
        except Exception:  # noqa: BLE001 — stats must never break serving
            z = None
        if z is not None:
            zones[ci] = z
    return zones


def ensure_zones(cache) -> bool:
    """Lazily attach zones to every block of a filled cache (fill and
    structural repacks create fresh ``_Block`` objects with ``zones=None``,
    so this is also how rebuilds happen).  Returns False when the cache
    cannot carry zones."""
    blocks = getattr(cache, "blocks", None)
    if not blocks:
        return False
    for blk in blocks:
        if blk.zones is None:
            blk.zones = build_block_zones(blk.cols, blk.n_valid)
    return True


def fold_update(zones: dict[int, ColumnZone] | None, col_updates: dict) -> None:
    """Fold one in-place write-through delta into a block's zones
    (``cache.scatter_update`` calls this — the single host mutation funnel
    for in-place updates).  Widening only: incoming non-null values widen
    ``lo``/``hi``; the null bounds widen by how many written rows could
    have flipped null-ness either way.  The zone goes stale because an
    overwrite may have removed the old extremal row."""
    if not zones:
        return
    for ci, (vals, nls) in col_updates.items():
        z = zones.get(ci)
        if z is None:
            continue
        nls = np.asarray(nls, dtype=bool)
        k = int(len(nls))
        k_null = int(nls.sum())
        live = ~nls
        if live.any():
            v = np.asarray(vals)[live]
            if v.dtype == object:
                zones.pop(ci, None)  # decoded-object write: stop tracking
                continue
            lo, hi = _scalar(v.min()), _scalar(v.max())
            z.lo = lo if z.lo is None else min(z.lo, lo)
            z.hi = hi if z.hi is None else max(z.hi, hi)
        z.null_hi = min(z.n, z.null_hi + k_null)
        z.null_lo = max(0, z.null_lo - (k - k_null))
        z.stale = True


# ---------------------------------------------------------------------------
# Conjunct recognition + per-block emptiness tests
# ---------------------------------------------------------------------------

_CMP_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
             "eq": "eq", "ne": "ne"}


def _recognize(rpn: RpnExpression):
    """Recognize the prunable conjunct shapes:

    * ``cmp(col, const)`` / ``cmp(const, col)`` → ("cmp", ci, op, cscale, c)
      — the same 3-node shape ``jax_zone._recognize_conjunct`` classifies
      tiles with, decimal alignment pre-multiplied (exact Python ints);
    * ``in(col, const...)``                      → ("in", ci, cscale, consts)
    * ``is_null(col)``                           → ("is_null", ci)

    None for anything else: unrecognized conjuncts never prune."""
    nodes = rpn.nodes
    if len(nodes) == 2 and nodes[1].kind == "fn" and nodes[1].op == "is_null" \
            and nodes[0].kind == "col":
        return ("is_null", nodes[0].index)
    if len(nodes) == 3 and nodes[2].kind == "fn":
        op = nodes[2].op
        if op not in _CMP_FLIP:
            return None
        a, b, sb = nodes[0], nodes[1], nodes[2].scale_by
        if a.kind == "col" and b.kind == "const":
            c = None if b.value is None else b.value * sb[1]
            return ("cmp", a.index, op, sb[0], c)
        if a.kind == "const" and b.kind == "col":
            c = None if a.value is None else a.value * sb[0]
            return ("cmp", b.index, _CMP_FLIP[op], sb[1], c)
        return None
    if (len(nodes) >= 3 and nodes[-1].kind == "fn" and nodes[-1].op == "in"
            and nodes[0].kind == "col"
            and all(n.kind == "const" for n in nodes[1:-1])):
        sb = nodes[-1].scale_by
        if any(isinstance(n.value, (bytes, bytearray)) for n in nodes[1:-1]):
            return None  # bytes IN-lists never reach zones untranslated
        consts = tuple(
            None if n.value is None else n.value * m
            for n, m in zip(nodes[1:-1], sb[1:])
        )
        return ("in", nodes[0].index, sb[0], consts)
    return None


def _cmp_empty(op: str, lo, hi, c) -> bool:
    """True iff NO value in [lo, hi] can satisfy ``col op c`` — the same
    interval tests ``jax_zone._classify_tiles`` uses for empty tiles."""
    if op == "lt":
        return lo >= c
    if op == "le":
        return lo > c
    if op == "gt":
        return hi <= c
    if op == "ge":
        return hi < c
    if op == "eq":
        return c < lo or c > hi
    # ne: only empty when every non-null value IS the constant
    return lo == c and hi == c


def _conjunct_prunes(rec, zones: dict[int, ColumnZone]) -> bool:
    """True iff the recognized conjunct proves the block yields NO row.
    NULL three-valued logic: a NULL comparison never satisfies a filter,
    so value predicates also prune blocks with no non-null values."""
    kind = rec[0]
    if kind == "is_null":
        z = zones.get(rec[1])
        return z is not None and z.null_hi == 0
    if kind == "cmp":
        _, ci, op, cscale, c = rec
        z = zones.get(ci)
        if z is None:
            return False
        if c is None:
            return True  # cmp(col, NULL) is NULL on every row
        if z.lo is None:
            return True  # no non-null value in the block
        return _cmp_empty(op, z.lo * cscale, z.hi * cscale, c)
    # "in"
    _, ci, cscale, consts = rec
    z = zones.get(ci)
    if z is None:
        return False
    if z.lo is None:
        return True
    lo, hi = z.lo * cscale, z.hi * cscale
    return all(c is None or c < lo or c > hi for c in consts)


class PruneStats:
    __slots__ = ("examined", "pruned")

    def __init__(self, examined: int = 0, pruned: int = 0):
        self.examined = examined
        self.pruned = pruned


def prune_blocks(cache, sel_rpns, path: str = "unary",
                 stats: PruneStats | None = None,
                 count: bool = True) -> np.ndarray | None:
    """Per-block keep mask for a filled cache under the plan's selection
    conjuncts (AND semantics: any conjunct that proves a block empty prunes
    it).  Returns None when pruning is off / inapplicable / proves nothing
    — callers then keep their exact pre-zone-map code path."""
    if not enabled() or not sel_rpns:
        return None
    recs = [r for r in (_recognize(rpn) for rpn in sel_rpns) if r is not None]
    if not recs:
        return None
    if not ensure_zones(cache):
        return None
    blocks = cache.blocks
    keep = np.ones(len(blocks), dtype=bool)
    for bi, blk in enumerate(blocks):
        zones = blk.zones
        if not zones:
            continue
        for rec in recs:
            if _conjunct_prunes(rec, zones):
                keep[bi] = False
                break
    n_pruned = int((~keep).sum())
    if stats is not None:
        stats.examined += len(blocks)
        stats.pruned += n_pruned
    if count:  # advisory probes (scheduler waste accounting) don't count
        count_prune(path, "examined", len(blocks))
        count_prune(path, "pruned", n_pruned)
    if n_pruned == 0:
        return None
    return keep


# ---------------------------------------------------------------------------
# TopN zone-order early exit
# ---------------------------------------------------------------------------

def topn_cutoff_order(blocks, keep, order_col: int, desc: bool, k: int):
    """Host-only TopN early exit: among the SURVIVING blocks (iterated in
    stream order for byte-identical tie-breaks), find which can still
    contribute to the top-``k`` (docs/zone_maps.md).

    Ascending: sort candidate blocks by ``hi``; once the accumulated row
    count reaches ``k`` the threshold ``T`` is that prefix's max ``hi`` —
    ≥k rows sort at or below ``T`` (nulls sort first, so null rows count
    toward the prefix too).  A remaining block with ``lo > T`` and no nulls
    holds only rows STRICTLY above the eventual kth value: even losing
    every tie, none can enter the top-k, so it is skipped.  Descending is
    symmetric on ``lo`` with the guaranteed count shrunk by ``null_hi``
    (nulls sort last under desc).  Returns an updated keep mask, or None
    when the bound is not satisfiable from zone order (untracked order
    column, too few bounded rows, stale zones are fine — wider bounds only
    weaken the exit, never break it)."""
    cand = []
    for bi, blk in enumerate(blocks):
        if not keep[bi]:
            continue
        z = (blk.zones or {}).get(order_col)
        if z is None:
            return None  # untracked order column: no sound bound
        cand.append((bi, z))
    if not cand:
        return None
    if desc:
        # guaranteed non-null rows with value >= lo
        ordered = sorted(cand, key=lambda t: _neg_key(t[1].lo))
        got = 0
        thresh = None
        for _bi, z in ordered:
            if z.lo is None:
                break  # all-null blocks bound nothing under desc
            got += max(0, z.n - z.null_hi)
            if got >= k:
                thresh = z.lo
                break
        if thresh is None:
            return None
        out = keep.copy()
        for bi, z in cand:
            if z.hi is not None and z.hi < thresh and z.null_hi == 0:
                out[bi] = False
        return out
    ordered = sorted(cand, key=lambda t: _pos_key(t[1].hi))
    got = 0
    thresh = None
    for _bi, z in ordered:
        # nulls sort FIRST ascending: every row of the block sorts <= hi
        got += z.n
        if z.lo is None:
            continue  # all-null: rows count toward the prefix, no threshold
        if got >= k:
            thresh = z.hi
            break
    if thresh is None:
        return None
    out = keep.copy()
    for bi, z in cand:
        if z.lo is not None and z.lo > thresh and z.null_hi == 0:
            out[bi] = False
    return out


def _pos_key(v):
    # all-null blocks (hi None) sort FIRST: their rows sort before any value
    return (v is not None, v if v is not None else 0)


def _neg_key(v):
    # sort descending by lo with None (all-null) last
    return (v is None, -(v if v is not None else 0))
