"""Vectorized scalar-function kernels, backend-parameterized.

The reference implements ~400 MySQL scalar functions as per-type vectorized
fns (``tidb_query_expr/src/impl_*.rs``).  Here each kernel is written ONCE
against the array-API module ``xp`` — ``numpy`` for the CPU oracle path,
``jax.numpy`` inside ``jit`` for the TPU path — so CPU and TPU semantics can
not drift apart.  A kernel maps (data, null) operand pairs to a (data, null)
result; SQL three-valued logic lives in the null masks.

Conventions:
* data arrays: int64 / float64 / bool promoted to int64 on output
* null mask: bool array, True = NULL
* comparisons/logical return INT (0/1) like MySQL
* decimal values are scaled int64; frac bookkeeping happens in rpn.py
"""

from __future__ import annotations

import operator

# Each entry: name -> (arity, result_kind, fn(xp, *operand_pairs) -> (data, nulls))
# result_kind: "int" | "real" | "decimal" | "same" (same as first operand) | "bytes"

KERNELS: dict[str, tuple[int, str, object]] = {}


def _reg(name: str, arity: int, rkind: str):
    def deco(fn):
        KERNELS[name] = (arity, rkind, fn)
        return fn

    return deco


def _binop_nulls(xp, an, bn):
    return an | bn


# -- comparisons ------------------------------------------------------------

def _cmp(pyop):
    def fn(xp, a, b):
        (ad, an), (bd, bn) = a, b
        return pyop(ad, bd).astype("int64"), _binop_nulls(xp, an, bn)

    return fn


for _name, _op in [
    ("lt", operator.lt),
    ("le", operator.le),
    ("gt", operator.gt),
    ("ge", operator.ge),
    ("eq", operator.eq),
    ("ne", operator.ne),
]:
    KERNELS[_name] = (2, "int", _cmp(_op))


# -- logical (MySQL three-valued) ------------------------------------------

@_reg("and", 2, "int")
def _and(xp, a, b):
    (ad, an), (bd, bn) = a, b
    at = (ad != 0) & ~an
    bt = (bd != 0) & ~bn
    af = (ad == 0) & ~an
    bf = (bd == 0) & ~bn
    data = (at & bt).astype("int64")
    # false AND anything = false (not null); null only if neither side false
    nulls = (an | bn) & ~af & ~bf
    return data, nulls


@_reg("or", 2, "int")
def _or(xp, a, b):
    (ad, an), (bd, bn) = a, b
    at = (ad != 0) & ~an
    bt = (bd != 0) & ~bn
    data = (at | bt).astype("int64")
    nulls = (an | bn) & ~at & ~bt
    return data, nulls


@_reg("xor", 2, "int")
def _xor(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ((ad != 0) ^ (bd != 0)).astype("int64"), an | bn


@_reg("not", 1, "int")
def _not(xp, a):
    ad, an = a
    return (ad == 0).astype("int64"), an


# -- null predicates --------------------------------------------------------

@_reg("is_null", 1, "int")
def _is_null(xp, a):
    ad, an = a
    return an.astype("int64"), xp.zeros_like(an)


@_reg("is_true", 1, "int")
def _is_true(xp, a):
    ad, an = a
    return ((ad != 0) & ~an).astype("int64"), xp.zeros_like(an)


@_reg("is_false", 1, "int")
def _is_false(xp, a):
    ad, an = a
    return ((ad == 0) & ~an).astype("int64"), xp.zeros_like(an)


# -- arithmetic -------------------------------------------------------------

@_reg("plus", 2, "same")
def _plus(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad + bd, an | bn


@_reg("minus", 2, "same")
def _minus(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad - bd, an | bn


@_reg("multiply", 2, "same")
def _multiply(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad * bd, an | bn


@_reg("divide_real", 2, "real")
def _divide_real(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    return ad / safe, an | bn | zero  # MySQL: x/0 = NULL


@_reg("int_divide", 2, "int")
def _int_divide(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    # MySQL DIV truncates toward zero; _trunc_div corrects python's floor
    return _trunc_div(xp, ad, safe), an | bn | zero


@_reg("mod", 2, "same")
def _mod(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    r = ad - (ad / safe if ad.dtype.kind == "f" else _trunc_div(xp, ad, safe)) * safe
    if ad.dtype.kind == "f":
        r = xp.fmod(ad, safe)
    return r, an | bn | zero


def _trunc_div(xp, a, b):
    q = a // b
    r = a - q * b
    return xp.where((r != 0) & ((a < 0) ^ (b < 0)), q + 1, q)


@_reg("unary_minus", 1, "same")
def _unary_minus(xp, a):
    ad, an = a
    return -ad, an


@_reg("abs", 1, "same")
def _abs(xp, a):
    ad, an = a
    return xp.abs(ad), an


# -- real math --------------------------------------------------------------

def _realfn(name, f):
    @_reg(name, 1, "real")
    def fn(xp, a, _f=f):
        ad, an = a
        return _f(xp)(ad), an

    return fn


_realfn("sqrt", lambda xp: xp.sqrt)
_realfn("exp", lambda xp: xp.exp)
_realfn("sin", lambda xp: xp.sin)
_realfn("cos", lambda xp: xp.cos)
_realfn("tan", lambda xp: xp.tan)


@_reg("ln", 1, "real")
def _ln(xp, a):
    ad, an = a
    bad = ad <= 0
    safe = xp.where(bad, xp.ones_like(ad), ad)
    return xp.log(safe), an | bad


@_reg("ceil", 1, "same")
def _ceil(xp, a):
    ad, an = a
    return (xp.ceil(ad) if ad.dtype.kind == "f" else ad), an


@_reg("floor", 1, "same")
def _floor(xp, a):
    ad, an = a
    return (xp.floor(ad) if ad.dtype.kind == "f" else ad), an


@_reg("pow", 2, "real")
def _pow(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad**bd, an | bn


# -- control ----------------------------------------------------------------

@_reg("if", 3, "same_2")
def _if(xp, c, t, f):
    (cd, cn), (td, tn), (fd, fn_) = c, t, f
    cond = (cd != 0) & ~cn
    return xp.where(cond, td, fd), xp.where(cond, tn, fn_)


@_reg("if_null", 2, "same")
def _if_null(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return xp.where(an, bd, ad), xp.where(an, bn, xp.zeros_like(an))


@_reg("coalesce2", 2, "same")
def _coalesce2(xp, a, b):
    return _if_null(xp, a, b)
