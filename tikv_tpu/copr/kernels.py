"""Vectorized scalar-function kernels, backend-parameterized.

Arity -1 marks variadic kernels (concat, coalesce, in, case_when) — the RPN
compiler records the actual child count per call site.

The reference implements ~400 MySQL scalar functions as per-type vectorized
fns (``tidb_query_expr/src/impl_*.rs``).  Here each kernel is written ONCE
against the array-API module ``xp`` — ``numpy`` for the CPU oracle path,
``jax.numpy`` inside ``jit`` for the TPU path — so CPU and TPU semantics can
not drift apart.  A kernel maps (data, null) operand pairs to a (data, null)
result; SQL three-valued logic lives in the null masks.

Conventions:
* data arrays: int64 / float64 / bool promoted to int64 on output
* null mask: bool array, True = NULL
* comparisons/logical return INT (0/1) like MySQL
* decimal values are scaled int64; frac bookkeeping happens in rpn.py
"""

from __future__ import annotations

import operator

# Each entry: name -> (arity, result_kind, fn(xp, *operand_pairs) -> (data, nulls))
# result_kind: "int" | "real" | "decimal" | "same" (same as first operand) | "bytes"

KERNELS: dict[str, tuple[int, str, object]] = {}


def _reg(name: str, arity: int, rkind: str):
    def deco(fn):
        KERNELS[name] = (arity, rkind, fn)
        return fn

    return deco


def _binop_nulls(xp, an, bn):
    return an | bn


# -- comparisons ------------------------------------------------------------

def _cmp(pyop):
    def fn(xp, a, b):
        (ad, an), (bd, bn) = a, b
        return pyop(ad, bd).astype("int64"), _binop_nulls(xp, an, bn)

    return fn


for _name, _op in [
    ("lt", operator.lt),
    ("le", operator.le),
    ("gt", operator.gt),
    ("ge", operator.ge),
    ("eq", operator.eq),
    ("ne", operator.ne),
]:
    KERNELS[_name] = (2, "int", _cmp(_op))


# -- logical (MySQL three-valued) ------------------------------------------

@_reg("and", 2, "int")
def _and(xp, a, b):
    (ad, an), (bd, bn) = a, b
    at = (ad != 0) & ~an
    bt = (bd != 0) & ~bn
    af = (ad == 0) & ~an
    bf = (bd == 0) & ~bn
    data = (at & bt).astype("int64")
    # false AND anything = false (not null); null only if neither side false
    nulls = (an | bn) & ~af & ~bf
    return data, nulls


@_reg("or", 2, "int")
def _or(xp, a, b):
    (ad, an), (bd, bn) = a, b
    at = (ad != 0) & ~an
    bt = (bd != 0) & ~bn
    data = (at | bt).astype("int64")
    nulls = (an | bn) & ~at & ~bt
    return data, nulls


@_reg("xor", 2, "int")
def _xor(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ((ad != 0) ^ (bd != 0)).astype("int64"), an | bn


@_reg("not", 1, "int")
def _not(xp, a):
    ad, an = a
    return (ad == 0).astype("int64"), an


# -- null predicates --------------------------------------------------------

@_reg("is_null", 1, "int")
def _is_null(xp, a):
    ad, an = a
    return an.astype("int64"), xp.zeros_like(an)


@_reg("is_true", 1, "int")
def _is_true(xp, a):
    ad, an = a
    return ((ad != 0) & ~an).astype("int64"), xp.zeros_like(an)


@_reg("is_false", 1, "int")
def _is_false(xp, a):
    ad, an = a
    return ((ad == 0) & ~an).astype("int64"), xp.zeros_like(an)


# -- arithmetic -------------------------------------------------------------

@_reg("plus", 2, "same")
def _plus(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad + bd, an | bn


@_reg("minus", 2, "same")
def _minus(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad - bd, an | bn


@_reg("multiply", 2, "same")
def _multiply(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad * bd, an | bn


@_reg("divide_real", 2, "real")
def _divide_real(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    return ad / safe, an | bn | zero  # MySQL: x/0 = NULL


@_reg("int_divide", 2, "int")
def _int_divide(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    # MySQL DIV truncates toward zero; _trunc_div corrects python's floor
    return _trunc_div(xp, ad, safe), an | bn | zero


@_reg("mod", 2, "same")
def _mod(xp, a, b):
    (ad, an), (bd, bn) = a, b
    zero = bd == 0
    safe = xp.where(zero, xp.ones_like(bd), bd)
    r = ad - (ad / safe if ad.dtype.kind == "f" else _trunc_div(xp, ad, safe)) * safe
    if ad.dtype.kind == "f":
        r = xp.fmod(ad, safe)
    return r, an | bn | zero


def _trunc_div(xp, a, b):
    q = a // b
    r = a - q * b
    return xp.where((r != 0) & ((a < 0) ^ (b < 0)), q + 1, q)


@_reg("unary_minus", 1, "same")
def _unary_minus(xp, a):
    ad, an = a
    return -ad, an


@_reg("abs", 1, "same")
def _abs(xp, a):
    ad, an = a
    return xp.abs(ad), an


# -- real math --------------------------------------------------------------

def _realfn(name, f):
    @_reg(name, 1, "real")
    def fn(xp, a, _f=f):
        ad, an = a
        return _f(xp)(ad), an

    return fn


_realfn("sqrt", lambda xp: xp.sqrt)
_realfn("exp", lambda xp: xp.exp)
_realfn("sin", lambda xp: xp.sin)
_realfn("cos", lambda xp: xp.cos)
_realfn("tan", lambda xp: xp.tan)


@_reg("ln", 1, "real")
def _ln(xp, a):
    ad, an = a
    bad = ad <= 0
    safe = xp.where(bad, xp.ones_like(ad), ad)
    return xp.log(safe), an | bad


@_reg("ceil", 1, "same")
def _ceil(xp, a):
    ad, an = a
    return (xp.ceil(ad) if ad.dtype.kind == "f" else ad), an


@_reg("floor", 1, "same")
def _floor(xp, a):
    ad, an = a
    return (xp.floor(ad) if ad.dtype.kind == "f" else ad), an


@_reg("pow", 2, "real")
def _pow(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad**bd, an | bn


# -- control ----------------------------------------------------------------

@_reg("if", 3, "same_2")
def _if(xp, c, t, f):
    (cd, cn), (td, tn), (fd, fn_) = c, t, f
    cond = (cd != 0) & ~cn
    return xp.where(cond, td, fd), xp.where(cond, tn, fn_)


@_reg("if_null", 2, "same")
def _if_null(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return xp.where(an, bd, ad), xp.where(an, bn, xp.zeros_like(an))


@_reg("coalesce2", 2, "same")
def _coalesce2(xp, a, b):
    return _if_null(xp, a, b)


@_reg("coalesce", -1, "same")
def _coalesce(xp, *args):
    data, nulls = args[0]
    for d, nl in args[1:]:
        data = xp.where(nulls, d, data)
        nulls = nulls & nl
    return data, nulls


@_reg("case_when", -1, "same_case")
def _case_when(xp, *args):
    """case_when(c1, r1, c2, r2, ..., [else]) — first true condition wins."""
    has_else = len(args) % 2 == 1
    pairs = args[: len(args) - 1] if has_else else args
    if has_else:
        data, nulls = args[-1]
    else:
        d0 = pairs[1][0]
        data = xp.zeros_like(d0)
        nulls = xp.ones_like(pairs[1][1])
    # apply in reverse so earlier conditions take precedence
    for i in range(len(pairs) - 2, -1, -2):
        cd, cn = pairs[i]
        rd, rn = pairs[i + 1]
        cond = (cd != 0) & ~cn
        data = xp.where(cond, rd, data)
        nulls = xp.where(cond, rn, nulls)
    return data, nulls


@_reg("in", -1, "int")
def _in(xp, *args):
    """a IN (v1, v2, ...) with MySQL NULL semantics: NULL if no match and
    any operand NULL."""
    (ad, an) = args[0]
    found = None
    any_null = an
    for vd, vn in args[1:]:
        eq = (ad == vd) & ~vn & ~an
        found = eq if found is None else (found | eq)
        any_null = any_null | vn
    data = found.astype("int64")
    nulls = ~found & any_null
    return data, nulls


# -- casts ------------------------------------------------------------------

@_reg("cast_int_real", 1, "real")
def _cast_int_real(xp, a):
    ad, an = a
    return ad.astype("float64"), an


@_reg("cast_real_int", 1, "int")
def _cast_real_int(xp, a):
    ad, an = a
    # MySQL rounds half away from zero
    return xp.where(ad >= 0, xp.floor(ad + 0.5), xp.ceil(ad - 0.5)).astype("int64"), an


@_reg("cast_decimal_real", 1, "real")
def _cast_decimal_real(xp, a):
    # decimal operands reach real-kind kernels already unscaled (rpn planning)
    ad, an = a
    return ad * 1.0, an


@_reg("truncate_int", 1, "int")
def _truncate_int(xp, a):
    ad, an = a
    return xp.trunc(ad).astype("int64") if ad.dtype.kind == "f" else ad, an


# -- bytes/string family (CPU-only: BYTES exprs never route to the device) --

import numpy as _np


def _bytes_op(name, arity, rkind):
    def deco(fn):
        def wrapped(xp, *args):
            datas = [a[0] for a in args]
            nulls = args[0][1]
            for _, nl in args[1:]:
                nulls = nulls | nl
            n = len(datas[0])
            out = _np.empty(n, dtype=object)
            rnull = _np.asarray(nulls).copy()
            for i in range(n):
                r = b"" if rnull[i] else fn(*[d[i] for d in datas])
                if r is None:  # per-row SQL NULL (e.g. invalid input)
                    rnull[i] = True
                    r = b""
                out[i] = r
            return out, rnull

        KERNELS[name] = (arity, rkind, wrapped)
        return fn

    return deco


def _int_bytes_op(name, arity):
    """bytes-input kernels returning INT."""

    def deco(fn):
        def wrapped(xp, *args):
            datas = [a[0] for a in args]
            nulls = args[0][1]
            for _, nl in args[1:]:
                nulls = nulls | nl
            n = len(datas[0])
            out = _np.fromiter((fn(*[d[i] for d in datas]) for i in range(n)), dtype=_np.int64, count=n)
            return out, nulls

        KERNELS[name] = (arity, "int", wrapped)
        return fn

    return deco


_int_bytes_op("length", 1)(lambda s: len(s))
_int_bytes_op("bit_length", 1)(lambda s: len(s) * 8)
_int_bytes_op("ascii", 1)(lambda s: s[0] if s else 0)
_int_bytes_op("locate", 2)(lambda sub, s: s.find(sub) + 1)
_bytes_op("upper", 1, "bytes")(lambda s: s.upper())
_bytes_op("lower", 1, "bytes")(lambda s: s.lower())
_bytes_op("reverse", 1, "bytes")(lambda s: s[::-1])
_bytes_op("ltrim", 1, "bytes")(lambda s: s.lstrip(b" "))
_bytes_op("rtrim", 1, "bytes")(lambda s: s.rstrip(b" "))
_bytes_op("trim", 1, "bytes")(lambda s: s.strip(b" "))
_bytes_op("hex", 1, "bytes")(lambda s: s.hex().upper().encode())
_bytes_op("replace", 3, "bytes")(lambda s, frm, to: s.replace(frm, to) if frm else s)
_bytes_op("concat", -1, "bytes")(lambda *parts: b"".join(parts))
_bytes_op("left", 2, "bytes")(lambda s, n: s[: max(int(n), 0)])
_bytes_op("right", 2, "bytes")(lambda s, n: s[max(len(s) - int(n), 0):] if int(n) > 0 else b"")


def _substr(s, pos, length=None):
    pos = int(pos)
    if pos == 0:
        return b""
    if pos < 0:
        pos = len(s) + pos
        if pos < 0:
            return b""
    else:
        pos -= 1
    if length is None:
        return s[pos:]
    length = int(length)
    if length <= 0:
        return b""
    return s[pos : pos + length]


_bytes_op("substr2", 2, "bytes")(lambda s, p: _substr(s, p))
_bytes_op("substr3", 3, "bytes")(lambda s, p, l: _substr(s, p, l))

# MySQL LIKE: % any run, _ single char, backslash escape; pattern regexes cached
import re as _re

_like_cache: dict[bytes, "_re.Pattern"] = {}


def _like_regex(pattern: bytes):
    rx = _like_cache.get(pattern)
    if rx is None:
        out = []
        i = 0
        while i < len(pattern):
            ch = pattern[i : i + 1]
            if ch == b"\\" and i + 1 < len(pattern):
                out.append(_re.escape(pattern[i + 1 : i + 2]))
                i += 2
                continue
            if ch == b"%":
                out.append(b".*")
            elif ch == b"_":
                out.append(b".")
            else:
                out.append(_re.escape(ch))
            i += 1
        rx = _re.compile(rb"\A" + b"".join(out) + rb"\Z", _re.DOTALL)
        if len(_like_cache) > 1024:
            _like_cache.clear()
        _like_cache[pattern] = rx
    return rx


_int_bytes_op("like", 2)(lambda s, pat: 1 if _like_regex(pat).match(s) else 0)


# -- math catalog (impl_math.rs / impl_op.rs) ------------------------------

def _realfn_dom(name, f):
    """Real function with a restricted domain: any non-finite result becomes
    SQL NULL (the reference's f64_to_real is_finite gate — LOG2(0) must be
    NULL, not −inf, and NaN likewise)."""

    @_reg(name, 1, "real")
    def fn(xp, a, _f=f):
        ad, an = a
        r = _f(xp)(ad)
        return r, an | ~xp.isfinite(r)

    return fn


_realfn_dom("log2", lambda xp: xp.log2)
_realfn_dom("log10", lambda xp: xp.log10)
_realfn_dom("asin", lambda xp: xp.arcsin)
_realfn_dom("acos", lambda xp: xp.arccos)
_realfn("atan", lambda xp: xp.arctan)


@_reg("atan2", 2, "real")
def _atan2(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return xp.arctan2(ad, bd), an | bn


@_reg("cot", 1, "real")
def _cot(xp, a):
    ad, an = a
    t = xp.tan(ad)
    zero = t == 0
    safe = xp.where(zero, xp.ones_like(t), t)
    return 1.0 / safe, an | zero


@_reg("radians", 1, "real")
def _radians(xp, a):
    ad, an = a
    return ad * (3.141592653589793 / 180.0), an


@_reg("degrees", 1, "real")
def _degrees(xp, a):
    ad, an = a
    return ad * (180.0 / 3.141592653589793), an


@_reg("sign", 1, "int")
def _sign(xp, a):
    ad, an = a
    return xp.sign(ad).astype("int64"), an


def _round_half_away(xp, v):
    # MySQL/Rust f64::round: half away from zero — floor(v+0.5) is WRONG at
    # e.g. 0.49999999999999994 (v+0.5 rounds up to 1.0); use banker's round
    # for non-halves and fix the exact halves
    t = xp.trunc(v)
    is_half = xp.abs(v - t) == 0.5
    return xp.where(is_half, t + xp.sign(v), xp.round(v))


@_reg("round_real", 1, "real")
def _round_real(xp, a):
    ad, an = a
    return _round_half_away(xp, ad), an


@_reg("round_real_frac", 2, "real")
def _round_real_frac(xp, a, b):
    (ad, an), (bd, bn) = a, b
    # the reference divides by 10^-d (round_with_frac_real) — multiplying by
    # 10^d rounds differently in f64 (0.35*10 = 3.5 but 0.35/0.1 = 3.4999…)
    p = xp.power(10.0, -bd.astype("float64"))
    return _round_half_away(xp, ad / p) * p, an | bn


@_reg("truncate_real_frac", 2, "real")
def _truncate_real_frac(xp, a, b):
    (ad, an), (bd, bn) = a, b
    # unlike ROUND, the reference's truncate MULTIPLIES by 10^d
    # (impl_math.rs truncate_real): overflowed scaling passes x through,
    # but an underflow to 0 returns 0.0
    m = xp.power(10.0, bd.astype("float64"))
    tmp = ad * m
    out = xp.where(
        xp.isfinite(tmp),
        xp.where(tmp == 0, xp.zeros_like(ad), xp.trunc(tmp) / m),
        ad,
    )
    return out, an | bn


# -- bit operators (impl_op.rs: results are u64 in MySQL; kept as the i64
# bit pattern on 64-bit lanes) ----------------------------------------------

@_reg("bit_and", 2, "int")
def _bit_and(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad & bd, an | bn


@_reg("bit_or", 2, "int")
def _bit_or(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad | bd, an | bn


@_reg("bit_xor", 2, "int")
def _bit_xor(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad ^ bd, an | bn


@_reg("bit_neg", 1, "int")
def _bit_neg(xp, a):
    ad, an = a
    return ~ad, an


@_reg("left_shift", 2, "int")
def _left_shift(xp, a, b):
    (ad, an), (bd, bn) = a, b
    big = (bd >= 64) | (bd < 0)  # MySQL: shift ≥64 yields 0
    safe = xp.where(big, xp.zeros_like(bd), bd)
    return xp.where(big, xp.zeros_like(ad), ad << safe), an | bn


@_reg("right_shift", 2, "int")
def _right_shift(xp, a, b):
    (ad, an), (bd, bn) = a, b
    big = (bd >= 64) | (bd < 0)
    safe = xp.where(big, xp.zeros_like(bd), bd)
    # logical shift on the u64 bit pattern, like MySQL >>
    shifted = (ad.astype("uint64") >> safe.astype("uint64")).astype("int64")
    return xp.where(big, xp.zeros_like(ad), shifted), an | bn


# -- greatest/least (impl_compare.rs; variadic, null if ANY operand null) ---

def _extreme(is_max):
    def fn(xp, *args):
        data, nulls = args[0]
        for d, nl in args[1:]:
            data = xp.maximum(data, d) if is_max else xp.minimum(data, d)
            nulls = nulls | nl
        return data, nulls

    return fn


KERNELS["greatest"] = (-1, "same", _extreme(True))
KERNELS["least"] = (-1, "same", _extreme(False))


# -- string catalog additions (impl_string.rs; CPU-only) --------------------

import base64 as _b64
import hashlib as _hashlib
import zlib as _zlib


_MAX_BLOB_WIDTH = 16 * 1024 * 1024  # validate_target_len_for_pad / space cap


def _pad(left):
    def fn(s_, n, pad):
        n = int(n)
        # NULL on negative/oversize target or empty pad that would be needed
        if n < 0 or n > _MAX_BLOB_WIDTH or (len(s_) < n and not pad):
            return None
        if n <= len(s_):
            return s_[:n]
        fill = (pad * ((n - len(s_)) // len(pad) + 1))[: n - len(s_)]
        return fill + s_ if left else s_ + fill

    return fn


_bytes_op("lpad", 3, "bytes")(_pad(True))
_bytes_op("rpad", 3, "bytes")(_pad(False))
# repeat: the reference has no blob cap (clamps count to i32::MAX and
# allocates); we keep a 64MB max_allowed_packet-style NULL guard — a
# deliberate deviation so one request cannot allocate unbounded memory
_bytes_op("repeat", 2, "bytes")(
    lambda s_, n: None if len(s_) * max(int(n), 0) > 4 * _MAX_BLOB_WIDTH else s_ * max(int(n), 0)
)
_bytes_op("space", 1, "bytes")(
    lambda n: None if int(n) > _MAX_BLOB_WIDTH else b" " * max(int(n), 0)
)
_int_bytes_op("strcmp", 2)(lambda a, b: (a > b) - (a < b))
_int_bytes_op("instr", 2)(lambda s_, sub: s_.find(sub) + 1)
# the reference has TWO signatures: char_length over binary strings is byte
# length; char_length_utf8 counts characters (impl_string.rs:880)
_int_bytes_op("char_length", 1)(lambda s_: len(s_))
_int_bytes_op("char_length_utf8", 1)(lambda s_: len(s_.decode("utf-8", "replace")))
_int_bytes_op("crc32", 1)(lambda s_: _zlib.crc32(s_))
_int_bytes_op("find_in_set", 2)(
    lambda s_, set_: 0 if (b"," in s_ or not set_)  # empty list -> 0
    else (set_.split(b",").index(s_) + 1 if s_ in set_.split(b",") else 0)
)
_bytes_op("oct_int", 1, "bytes")(lambda n: oct(int(n) & (2**64 - 1))[2:].encode())
_bytes_op("bin_int", 1, "bytes")(lambda n: bin(int(n) & (2**64 - 1))[2:].encode())
def _unhex(s_):
    try:
        t = s_.decode()
        return bytes.fromhex(t if len(t) % 2 == 0 else "0" + t)
    except (ValueError, UnicodeDecodeError):
        return None  # MySQL: invalid hex -> NULL


_bytes_op("unhex", 1, "bytes")(_unhex)
_bytes_op("to_base64", 1, "bytes")(lambda s_: _b64.b64encode(s_))


def _from_base64(s_):
    # reference semantics (impl_string.rs from_base64): whitespace stripped
    # first; bad length -> empty string; invalid characters -> NULL
    t = bytes(c for c in s_ if c not in b" \t\r\n\x0b\x0c")
    if len(t) % 4 != 0:
        return b""
    try:
        return _b64.b64decode(t, validate=True)
    except Exception:
        return None


_bytes_op("from_base64", 1, "bytes")(_from_base64)
_bytes_op("md5", 1, "bytes")(lambda s_: _hashlib.md5(s_).hexdigest().encode())
_bytes_op("sha1", 1, "bytes")(lambda s_: _hashlib.sha1(s_).hexdigest().encode())
_bytes_op("sha2", 2, "bytes")(
    lambda s_, n: {
        0: _hashlib.sha256, 224: _hashlib.sha224, 256: _hashlib.sha256,
        384: _hashlib.sha384, 512: _hashlib.sha512,
    }[int(n)](s_).hexdigest().encode()
    if int(n) in (0, 224, 256, 384, 512)
    else None
)


def _substring_index(s_, delim, count):
    count = int(count)
    if not delim or count == 0:
        return b""
    parts = s_.split(delim)
    if count > 0:
        return delim.join(parts[:count])
    return delim.join(parts[count:])


_bytes_op("substring_index", 3, "bytes")(_substring_index)


def _elt_kernel(xp, *args):
    """ELT(n, s1, s2, ...): only the SELECTED candidate's null matters
    (impl_string.rs elt) — a NULL in an unselected slot must not null the
    row, so this kernel handles its own masks."""
    nd, nn = args[0]
    cnt = len(args) - 1
    n = len(nd)
    out = _np.empty(n, dtype=object)
    rnull = _np.zeros(n, dtype=bool)
    for i in range(n):
        out[i] = b""
        if nn[i]:
            rnull[i] = True
            continue
        k = int(nd[i])
        if not 1 <= k <= cnt:
            rnull[i] = True
            continue
        cd, cn = args[k]
        if cn[i]:
            rnull[i] = True
        else:
            out[i] = cd[i]
    return out, rnull


KERNELS["elt"] = (-1, "bytes", _elt_kernel)


def _field_kernel(xp, *args):
    """FIELD(s, c1, c2, ...) never returns NULL: a NULL subject yields 0 and
    NULL candidates are skipped (impl_string.rs field_bytes)."""
    sd, sn = args[0]
    n = len(sd)
    out = _np.zeros(n, dtype=_np.int64)
    for i in range(n):
        if sn[i]:
            continue
        for j in range(1, len(args)):
            cd, cn = args[j]
            if not cn[i] and cd[i] == sd[i]:
                out[i] = j
                break
    return out, _np.zeros(n, dtype=bool)


KERNELS["field"] = (-1, "int", _field_kernel)

# inet helpers (impl_misc.rs)
import ipaddress as _ip


def _inet_aton(s_):
    # strictly digits and dots (impl_miscellaneous.rs inet_aton): '+1.2.3.4',
    # ' 1.2.3.4', '1_0.0.0.1' are invalid; empty MIDDLE groups mean 0
    # ('1..2' = 16777218) but a trailing dot is invalid
    try:
        t = s_.decode()
    except UnicodeDecodeError:
        return None
    if not t or t.endswith(".") or any(c not in "0123456789." for c in t):
        return None
    parts = t.split(".")
    if len(parts) > 4:
        return None
    nums = [int(x) if x else 0 for x in parts]
    if any(x > 255 for x in nums):
        return None
    # short forms: a.b -> a<<24|b, a.b.c -> a<<24|b<<16|c (MySQL rule)
    nums = nums[:-1] + [0] * (4 - len(parts)) + [nums[-1]]
    return (nums[0] << 24) | (nums[1] << 16) | (nums[2] << 8) | nums[3]


def _reg_nullable_int(name, arity, fn):
    """bytes-input kernel returning INT where a per-row None result means
    SQL NULL (unlike _int_bytes_op, which cannot signal new nulls)."""

    def wrapped(xp, *args):
        datas = [a[0] for a in args]
        nulls = args[0][1]
        for _, nl in args[1:]:
            nulls = nulls | nl
        n = len(datas[0])
        out = _np.zeros(n, dtype=_np.int64)
        rnull = _np.asarray(nulls).copy()
        for i in range(n):
            if rnull[i]:
                continue
            r = fn(*[d[i] for d in datas])
            if r is None:
                rnull[i] = True
            else:
                out[i] = r
        return out, rnull

    KERNELS[name] = (arity, "int", wrapped)


_reg_nullable_int("inet_aton", 1, _inet_aton)
_bytes_op("inet_ntoa", 1, "bytes")(
    lambda n: str(_ip.IPv4Address(int(n))).encode() if 0 <= int(n) <= 0xFFFFFFFF else None
)


# -- collation-aware string kernels (collation.py sort keys) ---------------

from .collation import get_collator as _get_collator

for _coll in ("binary", "utf8mb4_bin", "utf8mb4_general_ci"):
    _c = _get_collator(_coll)
    # sort_key_<collation>: bytes → sort-key bytes; comparisons, group-bys,
    # and min/max over the result behave as collated operations on the input
    _bytes_op(f"sort_key_{_coll}", 1, "bytes")(_c.sort_key)
    _int_bytes_op(f"eq_{_coll}", 2)(
        lambda a, b, _c=_c: 1 if _c.eq(a, b) else 0
    )

def _utf8_fold(b):
    # case folding must be unicode-aware: bytes.lower() is ASCII-only and
    # would disagree with general_ci on any non-ASCII letter
    return b.decode("utf-8", "replace").lower().encode("utf-8")


_int_bytes_op("like_ci", 2)(
    lambda s_, pat: 1 if _like_regex(_utf8_fold(pat)).match(_utf8_fold(s_)) else 0
)


# -- MySQL JSON family (CPU-only like the bytes family; the reference's
# impl_json.rs — values travel as binary JSON payloads in object arrays) ----

from . import json_value as _jv  # noqa: E402


def _json_op(name, arity, rkind):
    """Per-row JSON kernel: each fn receives raw per-row operand values
    (binary-JSON payloads for JSON operands, bytes for paths/text, numbers
    for numerics); result re-encoded by rkind ("json" payload, "bytes" raw,
    "int"/"real" numeric).  A per-row result of None means SQL NULL."""

    def deco(fn):
        def wrapped(xp, *args):
            datas = [a[0] for a in args]
            nulls = args[0][1].copy()
            for _, nl in args[1:]:
                nulls = nulls | nl
            n = len(datas[0])
            out = _np.empty(n, dtype=object)
            rnull = _np.asarray(nulls).copy()
            for i in range(n):
                if rnull[i]:
                    out[i] = b"" if rkind in ("json", "bytes") else 0
                    continue
                r = fn(*[d[i] for d in datas])
                if r is None:
                    rnull[i] = True
                    out[i] = b"" if rkind in ("json", "bytes") else 0
                else:
                    out[i] = r
            if rkind == "int":
                return out.astype(_np.int64), rnull
            if rkind == "real":
                return out.astype(_np.float64), rnull
            return out, rnull

        KERNELS[name] = (arity, rkind, wrapped)
        return fn

    return deco


def _jd(b):
    return _jv.json_decode(bytes(b))


@_json_op("json_extract", -1, "json")
def _json_extract(doc, *paths):
    r = _jv.extract(_jd(doc), [p.decode() for p in paths])
    return None if r is _jv._NO_MATCH else _jv.json_encode(r)


@_json_op("json_unquote", 1, "bytes")
def _json_unquote(doc):
    return _jv.unquote(_jd(doc))


@_json_op("json_type", 1, "bytes")
def _json_type(doc):
    return _jv.json_type_name(_jd(doc)).encode()


@_json_op("json_length", -1, "int")
def _json_length(doc, *path):
    v = _jd(doc)
    if path:
        v = _jv.extract(v, [path[0].decode()])
        if v is _jv._NO_MATCH:
            return None
    return _jv.length(v)


@_json_op("json_depth", 1, "int")
def _json_depth(doc):
    return _jv.depth(_jd(doc))


@_json_op("json_valid", 1, "int")
def _json_valid(raw):
    try:
        _jv.json_parse_text(raw.decode("utf-8"))
        return 1
    except (ValueError, UnicodeDecodeError):
        return 0


@_json_op("json_keys", -1, "json")
def _json_keys(doc, *path):
    v = _jd(doc)
    if path:
        v = _jv.extract(v, [path[0].decode()])
        if v is _jv._NO_MATCH:
            return None
    if not isinstance(v, dict):
        return None
    return _jv.json_encode(sorted(v.keys(), key=lambda k: _jv._key_sort(k.encode())))


@_json_op("json_array", -1, "json")
def _json_array(*elems):
    return _jv.json_encode([_jd(e) for e in elems])


@_json_op("json_object", -1, "json")
def _json_object(*kv):
    if len(kv) % 2:
        raise ValueError("json_object: incorrect parameter count (key/value pairs)")
    obj = {}
    for i in range(0, len(kv), 2):
        obj[bytes(kv[i]).decode("utf-8")] = _jd(kv[i + 1])
    return _jv.json_encode(obj)


@_json_op("json_merge", -1, "json")
def _json_merge(*docs):
    return _jv.json_encode(_jv.merge([_jd(d) for d in docs]))


@_json_op("json_contains", 2, "int")
def _json_contains(target, candidate):
    return 1 if _jv.contains(_jd(target), _jd(candidate)) else 0


def _json_modify_fn(mode):
    def fn(doc, *rest):
        if len(rest) % 2:
            raise ValueError(f"json_{mode}: incorrect parameter count (path/value pairs)")
        updates = [
            (rest[i].decode(), _jd(rest[i + 1])) for i in range(0, len(rest), 2)
        ]
        return _jv.json_encode(_jv.modify(_jd(doc), updates, mode))

    return fn


_json_op("json_set", -1, "json")(_json_modify_fn("set"))
_json_op("json_insert", -1, "json")(_json_modify_fn("insert"))
_json_op("json_replace", -1, "json")(_json_modify_fn("replace"))


@_json_op("json_remove", -1, "json")
def _json_remove(doc, *paths):
    return _jv.json_encode(_jv.remove(_jd(doc), [p.decode() for p in paths]))


@_json_op("json_quote", 1, "bytes")
def _json_quote(raw):
    return _jv.quote(bytes(raw))


# casts between JSON and base types (impl_cast.rs json arms)
@_json_op("cast_int_json", 1, "json")
def _cast_int_json(v):
    return _jv.json_encode(int(v))


@_json_op("cast_real_json", 1, "json")
def _cast_real_json(v):
    return _jv.json_encode(float(v))


@_json_op("cast_string_json", 1, "json")
def _cast_string_json(raw):
    try:
        return _jv.json_encode(_jv.json_parse_text(bytes(raw).decode("utf-8")))
    except (ValueError, UnicodeDecodeError):
        return None


@_json_op("cast_json_string", 1, "bytes")
def _cast_json_string(doc):
    return _jv.json_to_text(_jd(doc)).encode("utf-8")


@_json_op("cast_json_int", 1, "int")
def _cast_json_int(doc):
    import math

    def _round(f):  # MySQL rounds half away from zero
        return int(math.floor(f + 0.5)) if f >= 0 else int(math.ceil(f - 0.5))

    def _sat(n):  # saturate to i64 (MySQL CAST semantics; u64 values clamp)
        return max(-(2**63), min(2**63 - 1, n))

    v = _jd(doc)
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        return _sat(int(v))
    if isinstance(v, float):
        return _sat(_round(v))
    if isinstance(v, str):
        try:
            return _sat(_round(float(v)))
        except (ValueError, OverflowError):
            return 0
    return 0


@_json_op("cast_json_real", 1, "real")
def _cast_json_real(doc):
    v = _jd(doc)
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return 0.0
    return 0.0


# -- regexp family (impl_regexp.rs; MySQL uses ICU — python `re` covers the
# common POSIX-ish subset; case-sensitivity follows the binary collation,
# with _ci variants for case-insensitive columns) ---------------------------

_rx_cache: dict = {}


def _rx(pat: bytes, flags: int = 0):
    key = (pat, flags)
    rx = _rx_cache.get(key)
    if rx is None:
        if len(_rx_cache) > 512:
            _rx_cache.clear()
        rx = _rx_cache[key] = _re.compile(pat, flags)
    return rx


def _reg_regexp(name, flags):
    def fn(s_, pat):
        try:
            return 1 if _rx(pat, flags).search(s_) else 0
        except _re.error:
            return None

    _reg_nullable_int(name, 2, fn)


_reg_regexp("regexp", 0)
_reg_regexp("regexp_like", 0)
_reg_regexp("regexp_like_ci", _re.IGNORECASE)


def _regexp_substr(s_, pat):
    try:
        m = _rx(pat).search(s_)
    except _re.error:
        return None
    return m.group(0) if m else None


_bytes_op("regexp_substr", 2, "bytes")(_regexp_substr)


def _regexp_instr(s_, pat):
    try:
        m = _rx(pat).search(s_)
    except _re.error:
        return None
    return (m.start() + 1) if m else 0


_reg_nullable_int("regexp_instr", 2, _regexp_instr)


def _icu_repl_to_py(repl: bytes, n_groups: int) -> bytes:
    """MySQL/ICU replacement syntax → python re replacement: $N consumes
    the LONGEST digit run that is still a valid group number (ICU rule:
    "$12" with one group means group 1 + literal '2'), backslash escapes
    the next character literally, and everything else (incl. python-special
    backslashes) is literal.  Cached per (replacement, group count) — this
    runs on the per-row hot path."""
    cached = _repl_cache.get((repl, n_groups))
    if cached is not None:
        return cached
    out = bytearray()
    i = 0
    while i < len(repl):
        c = repl[i]
        if c == 0x5C and i + 1 < len(repl):  # backslash: next char literal
            nxt = repl[i + 1]
            out += b"\\\\" if nxt == 0x5C else bytes([nxt])
            i += 2
        elif c == 0x24 and i + 1 < len(repl) and 0x30 <= repl[i + 1] <= 0x39:
            j = i + 1
            while j < len(repl) and 0x30 <= repl[j] <= 0x39:
                j += 1
            digits = repl[i + 1 : j]
            # trim trailing digits until the group number is valid
            while len(digits) > 1 and int(digits) > n_groups:
                digits = digits[:-1]
            out += b"\\g<" + digits + b">"
            i = i + 1 + len(digits)
        elif c == 0x5C:
            out += b"\\\\"  # trailing backslash: literal
            i += 1
        else:
            out += bytes([c])
            i += 1
    result = bytes(out)
    if len(_repl_cache) > 512:
        _repl_cache.clear()
    _repl_cache[repl, n_groups] = result
    return result


_repl_cache: dict = {}


def _regexp_replace(s_, pat, repl):
    try:
        rx = _rx(pat)
        return rx.sub(_icu_repl_to_py(repl, rx.groups), s_)
    except _re.error:
        return None


_bytes_op("regexp_replace", 3, "bytes")(_regexp_replace)


# time-type kernels register themselves into KERNELS on import
from . import mysql_time as _mysql_time  # noqa: E402,F401

# catalog extension (conversion / control / string / time / json / misc
# breadth) — also self-registering
from . import kernels_ext as _kernels_ext  # noqa: E402,F401


# ---------------------------------------------------------------------------
# encoded-column device decode (docs/compressed_columns.md)
# ---------------------------------------------------------------------------
# The region column cache keeps blocks device-resident in ENCODED form
# (copr/encoding.py: bitpacked narrow lanes, RLE runs, narrowed dictionary
# codes).  These helpers are the ONE in-kernel decode used by every device
# program (jax_eval._build_cols, the mesh slab step): HBM holds the encoded
# payload, the first ops of the compiled program widen/expand in registers,
# and everything downstream (RPN kernels above, segment reductions) sees
# exact int64/f64 lanes — byte-identical to evaluating the decoded image.


def decode_device_column(xp, desc, payload, nulls, ref, n_rows: int):
    """(data, nulls) int64/f64 lanes for ONE shipped column.

    ``desc`` is the static encoding descriptor baked into the compiled
    program's cache key; ``ref`` is the DYNAMIC frame-of-reference scalar
    (bitpack), so images whose value ranges differ still share one
    executable; ``payload`` is the pinned array — narrow lanes for
    plain/bp/code, an (run_values, run_ends) pair for rle."""
    kind = desc[0]
    if kind == "plain":
        return payload, nulls
    if kind == "bp":
        data = payload.astype(xp.int64)
        if ref is not None:
            data = data + ref
        return data, nulls
    if kind == "code":
        return payload.astype(xp.int64), nulls
    if kind == "rle":
        run_values, run_ends = payload
        k_cap = desc[1]
        rows = xp.arange(n_rows, dtype=xp.int64)
        idx = xp.clip(
            xp.searchsorted(run_ends, rows, side="right"), 0, k_cap - 1
        )
        data = run_values[idx].astype(xp.int64)
        if nulls.shape[0] != n_rows:  # run-shaped null payload
            nulls = nulls[idx]
        return data, nulls
    raise AssertionError(f"unknown encoding descriptor {desc!r}")
