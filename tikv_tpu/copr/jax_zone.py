"""Zone-tiled clustered warm evaluator — the device aggregation fast path.

The generic warm path (jax_eval's scan over stacked blocks) spends its time in
per-row masked reductions: XLA-CPU devectorizes a reduction whose input is a
select or a widening cast, and TPU scatter is off the table entirely.  This
module removes per-row masking from the hot loop with a classic columnar
storage layout (the reference has no equivalent inside a region scan — TiKV's
coprocessor filters row-by-row, `src/coprocessor/endpoint.rs`; the layout here
plays the role TiFlash's rough index / Parquet page statistics play in the
columnar siblings):

* rows are PERMUTED so each group-by slot's rows are contiguous (cluster by
  the stable dictionary codes), padded per run to a tile multiple, and
  secondary-sorted inside each run by a range-predicate column;
* referenced columns are pinned NARROWED (int8/int16/int32 chosen from the
  actual value range) with per-tile min/max zone statistics kept host-side;
* each query classifies every tile against its selection conjuncts using
  interval arithmetic: **full** (provably all rows pass), **empty** (provably
  none), or **partial**;
* full tiles aggregate with PURE same-dtype staged tile reductions — no mask,
  no select, no widening in the reduction, so XLA emits clean SIMD loops (and
  on TPU, clean VPU/MXU reductions with no scatter);
* partial tiles (predicate boundaries, tiles containing NULLs in referenced
  columns, pad tiles) are gathered whole — a contiguous DMA-friendly gather —
  and evaluated row-by-row through the same RPN machinery as the generic
  path, over a power-of-two tile-count bucket so shapes stay static;
* per-group results merge through tiny T-sized segment ops (T = n/TILE_ROWS).

Scope: zone layouts are built and keyed PER CACHE (one region image), so
they serve the per-request warm path and the same-region fused batch
(jax_eval.run_batch_cached probes them first).  The read scheduler's
cross-region batches (scheduler.py → jax_eval.launch_xregion_cached) and
the mesh-sharded warm launcher (parallel/mesh.py launch_xregion_sharded,
docs/mesh_serving.md) bypass zones: a cross-region/sharded program needs
one shared geometry across images whose cluster permutations and tile
statistics differ per region — batching zone-tiled execution across
regions (or tiling it per device shard) would need a shared tile
classification pass and is future work; the scheduler's padding-budget
shed keeps the bypass bounded to batches that actually profit from
stacking.

Exactness contract: REAL (f64) aggregate arguments are rejected (summation
order would differ from the CPU oracle beyond the last ulp); everything else
is int64-lane arithmetic, so responses stay byte-identical to the CPU
pipeline, including group output order (tracked as the minimum original row
index among each group's active rows — the CPU hash-agg's insertion order,
matching jax_eval's `_fused_step` semantics).  One carve-out shared with the
generic device path: var_pop's sum-of-squares accumulates in f64, exact
while Σx² < 2^53 and last-ulp-exempt beyond (the documented REAL caveat).

Layouts are built once per (group columns, sort column) signature and pinned
on the ColumnBlockCache; queries whose partial fraction exceeds
``PARTIAL_FALLBACK`` hand back to the generic path (the layout buys nothing
when most tiles straddle a predicate boundary).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..analysis.sanitizer import note_blocking
from . import observatory as _obs
from .datatypes import EvalType
from .rpn import RpnExpression, eval_rpn

TILE_ROWS = 4096
PARTIAL_FALLBACK = 0.6  # > this fraction of partial tiles → generic path
_RIDX_INF = np.int32(2**31 - 1)

_ZONE_AGG_OPS = {"count", "sum", "avg", "min", "max", "var_pop"}
# null-preserving kernels: non-null operands can never produce a NULL result,
# so an expression's null mask is exactly the OR of its operands' — which lets
# has-null tiles be forced partial instead of tracked per row on full tiles
_NULLSAFE_OPS = {
    "plus", "minus", "multiply", "unary_minus", "abs",
    "bit_and", "bit_or", "bit_xor", "bit_neg",
    "lt", "le", "gt", "ge", "eq", "ne",
    "and", "or", "not", "is_not_null",
}


def _narrow_dtype(lo: int, hi: int):
    """Smallest signed int dtype that holds [lo, hi] (and 0, the null fill)."""
    lo, hi = min(lo, 0), max(hi, 0)
    for dt in (np.int8, np.int16, np.int32):
        info = np.iinfo(dt)
        if info.min <= lo and hi <= info.max:
            return dt
    return np.int64


def _stage_split(dtype, max_abs: int):
    """(inner_k, inner_dtype) for a two-stage tile sum that never overflows
    and never widens inside a vector reduction.  inner sums K elements in a
    dtype just wide enough; the outer reduce widens K× fewer elements."""
    for k in (64, 32, 16, 8):
        if TILE_ROWS % k:
            continue
        bound = k * max(max_abs, 1)
        for idt in (np.int16, np.int32):
            if np.iinfo(idt).min < -bound and bound < np.iinfo(idt).max and np.dtype(idt).itemsize >= np.dtype(dtype).itemsize:
                return k, idt
        if bound < np.iinfo(np.int64).max // 4:
            return k, np.int64
    return 1, np.int64


def _tile_sum(x2d, max_abs: int):
    """(T', L) → (T',) exact int64 tile sums, staged to keep reductions
    same-dtype (a widening reduce scalarizes on XLA-CPU)."""
    t, l = x2d.shape
    if x2d.dtype == jnp.int64:
        return x2d.sum(axis=1)
    k, idt = _stage_split(x2d.dtype.type, max_abs)
    if k == 1:
        return x2d.astype(jnp.int64).sum(axis=1)
    inner = x2d.reshape(t, l // k, k).sum(axis=-1, dtype=jnp.dtype(idt))
    return inner.sum(axis=1, dtype=jnp.int64)


# ---------------------------------------------------------------------------
# Conjunct recognition (interval arithmetic against tile zones)
# ---------------------------------------------------------------------------

def _rpn_sig(rpn: RpnExpression | None) -> tuple:
    if rpn is None:
        return ()
    return tuple(
        (n.kind, n.eval_type, n.frac, n.index, n.value, n.op, n.arity, tuple(n.scale_by or ()))
        for n in rpn.nodes
    )


def _plan_sig(ev) -> tuple:
    """Everything a zone device program depends on: selection RPNs (with
    constants), aggregate ops + argument RPNs, and whether grouping is on.
    Two evaluators with equal signatures compile to identical programs, so
    they share one cached jitted fn per layout instead of pinning one each."""
    return (
        tuple(_rpn_sig(r) for r in ev.sel_rpns),
        _agg_sig(ev),
    )


def _agg_sig(ev) -> tuple:
    """The aggregate/grouping part of the plan signature ALONE.  The
    full-tile program never evaluates selection row-wise — selection lives
    entirely in the tile classification, which arrives as the w_full
    argument — so keying its cache on the full _plan_sig made every distinct
    selection constant recompile an identical XLA program and churn the
    32-entry per-layout cache."""
    return (
        tuple((da.op, _rpn_sig(da.rpn)) for da in ev.device_aggs),
        bool(ev.group_rpns),
    )


_ZONE_FNS_MAX = 32  # distinct plan shapes cached per layout


def _layout_fn_cache(layout) -> dict:
    return layout.__dict__.setdefault("_zone_fns", {})


def _fn_cache_put(fns: dict, key, jfn):
    fns[key] = jfn
    while len(fns) > _ZONE_FNS_MAX:
        fns.pop(next(iter(fns)))
    return jfn


def _recognize_conjunct(rpn: RpnExpression):
    """(col_index, op, col_scale, const_value_scaled) for `cmp(col, const)` /
    `cmp(const, col)` RPNs, with the comparison flipped so the column is
    always on the left and both sides pre-multiplied by the node's static
    decimal-alignment factors (positive, so interval order is preserved);
    None for anything else (those classify every tile as partial)."""
    nodes = rpn.nodes
    if len(nodes) != 3 or nodes[2].kind != "fn":
        return None
    op = nodes[2].op
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}
    if op not in flip:
        return None
    a, b = nodes[0], nodes[1]
    sb = nodes[2].scale_by
    if a.kind == "col" and b.kind == "const":
        const = None if b.value is None else b.value * sb[1]
        return (a.index, op, sb[0], const)
    if a.kind == "const" and b.kind == "col":
        const = None if a.value is None else a.value * sb[0]
        return (b.index, flip[op], sb[1], const)
    return None




# ---------------------------------------------------------------------------
# Layout
# ---------------------------------------------------------------------------

class ZoneLayout:
    """Clustered, tiled, narrowed image of a filled block cache for one
    (group_cols, sort_col) signature.  Device arrays are flat over all tiles;
    zone stats stay host-side numpy."""

    def __init__(self, blocks, group_cols, dicts, sort_col, needed_cols, schema):
        self.group_cols = list(group_cols)
        self.sort_col = sort_col
        dict_lens = [len(d) for d in dicts]
        self.n_slots = 1
        for dl in dict_lens:
            self.n_slots *= dl + 1
        self.dicts = dicts
        self.dict_lens = dict_lens
        self.schema = schema

        perm_parts = []      # (block_index, original_positions) per run chunk
        valid_parts = []
        tile_gid_parts = []
        base = 0             # global valid-row offset of each block
        for blk in blocks:
            n_valid = blk.n_valid
            if self.n_slots > 1:
                gid = np.zeros(n_valid, dtype=np.int64)
                for ci, dl in zip(group_cols, dict_lens):
                    col = blk.cols[ci]
                    codes = np.asarray(col.data[:n_valid], dtype=np.int64)
                    nulls = np.asarray(col.nulls[:n_valid])
                    gid = gid * (dl + 1) + np.where(nulls, dl, codes)
            else:
                gid = np.zeros(n_valid, dtype=np.int64)
            if sort_col is not None:
                skey = np.asarray(blk.cols[sort_col].data[:n_valid])
                order = np.lexsort((skey, gid))
            else:
                order = np.argsort(gid, kind="stable")
            gs = gid[order]
            # run boundaries per slot present in this block
            boundaries = np.flatnonzero(np.diff(gs)) + 1
            starts = np.concatenate([[0], boundaries, [n_valid]])
            for s, e in zip(starts[:-1], starts[1:]):
                if s == e:
                    continue
                run = order[s:e]
                slot = int(gs[s])
                pad = (-len(run)) % TILE_ROWS
                perm_parts.append((blk, base, run, False))
                valid_parts.append(np.ones(len(run), dtype=bool))
                if pad:
                    perm_parts.append((blk, base, np.zeros(pad, dtype=run.dtype), True))
                    valid_parts.append(np.zeros(pad, dtype=bool))
                tile_gid_parts.append(np.full((len(run) + pad) // TILE_ROWS, slot, dtype=np.int32))
            base += n_valid

        valid = np.concatenate(valid_parts)
        self.n_rows = len(valid)
        self.tile_gid = np.concatenate(tile_gid_parts)
        self.n_tiles = len(self.tile_gid)
        assert self.n_tiles * TILE_ROWS == self.n_rows

        # gather the needed columns through the permutation, block by block
        ridx = np.empty(self.n_rows, dtype=np.int32)
        pos = 0
        gathered: dict[int, list] = {i: [] for i in needed_cols}
        nullable = set()
        for i in needed_cols:
            if any(np.asarray(b.cols[i].nulls[: b.n_valid]).any() for b in blocks):
                nullable.add(i)
        null_gathered: dict[int, list] = {i: [] for i in nullable}
        for blk, bbase, run, is_pad in perm_parts:
            m = len(run)
            if not is_pad:
                ridx[pos : pos + m] = (bbase + run).astype(np.int32)
                for i in needed_cols:
                    gathered[i].append(np.asarray(blk.cols[i].data)[run])
                for i in nullable:
                    null_gathered[i].append(np.asarray(blk.cols[i].nulls)[run])
            else:
                ridx[pos : pos + m] = _RIDX_INF
                for i in needed_cols:
                    gathered[i].append(np.zeros(m, dtype=np.asarray(blk.cols[i].data).dtype))
                for i in nullable:
                    null_gathered[i].append(np.ones(m, dtype=bool))
            pos += m

        self.valid = valid
        self.ridx = ridx
        self.nullable = nullable
        T = self.n_tiles
        self.cols_np: dict[int, np.ndarray] = {}
        self.nulls_np: dict[int, np.ndarray] = {}
        self.col_ranges: dict[int, tuple] = {}
        self.zone_lo: dict[int, np.ndarray] = {}
        self.zone_hi: dict[int, np.ndarray] = {}
        self.zone_has_null: dict[int, np.ndarray] = {}
        for i in needed_cols:
            arr = np.concatenate(gathered[i])
            nl = np.concatenate(null_gathered[i]) if i in nullable else None
            et = schema[i][0]
            if et == EvalType.REAL:
                data = np.where(~valid | (nl if nl is not None else False), 0.0, arr).astype(np.float64)
            else:
                a64 = arr.astype(np.int64)
                a64 = np.where(~valid | (nl if nl is not None else False), 0, a64)
                lo, hi = (int(a64.min()), int(a64.max())) if len(a64) else (0, 0)
                data = a64.astype(_narrow_dtype(lo, hi))
            self.cols_np[i] = data
            if nl is not None:
                self.nulls_np[i] = nl
            # zone stats over live (non-pad, non-null) rows only, in the
            # column's own dtype domain (float stats on int64 would round
            # above 2^53 and could misclassify a boundary tile as full)
            live = valid & (~nl if nl is not None else True)
            if et == EvalType.REAL:
                vals, pos_id, neg_id = arr.astype(np.float64), np.inf, -np.inf
            else:
                info = np.iinfo(np.int64)
                vals, pos_id, neg_id = arr.astype(np.int64), info.max, info.min
            self.zone_lo[i] = np.where(live, vals, pos_id).reshape(T, TILE_ROWS).min(axis=1)
            self.zone_hi[i] = np.where(live, vals, neg_id).reshape(T, TILE_ROWS).max(axis=1)
            self.zone_has_null[i] = (
                nl.reshape(T, TILE_ROWS).any(axis=1) if nl is not None else np.zeros(T, dtype=bool)
            )
            if et != EvalType.REAL:
                a = self.cols_np[i].astype(np.int64)
                self.col_ranges[i] = (int(a.min()) if len(a) else 0, int(a.max()) if len(a) else 0)
            else:
                self.col_ranges[i] = (0, 0)
        self.valid_count = valid.reshape(T, TILE_ROWS).sum(axis=1).astype(np.int32)
        self.has_pad = self.valid_count < TILE_ROWS

        # device pins
        self.dev = {
            "tile_gid": jnp.asarray(self.tile_gid),
            "valid_count": jnp.asarray(self.valid_count),
            "ridx": jnp.asarray(self.ridx),
            "valid": jnp.asarray(self.valid),
            "cols": {i: jnp.asarray(a) for i, a in self.cols_np.items()},
            "nulls": {i: jnp.asarray(a) for i, a in self.nulls_np.items()},
        }
        note_blocking("device.pin:zone_layout")
        for v in jax.tree.leaves(self.dev):
            v.block_until_ready()
        # classification needs only the per-tile stats; the full-size host
        # copies just fed the device pins — at bench scale they are GBs
        del self.cols_np, self.nulls_np, self.valid, self.ridx
        # encoded-resident images (docs/compressed_columns.md): the gathers
        # above materialized their decode caches — drop them, or the image
        # holds encoded payload + full decode while the budget counts only
        # the former
        for blk in blocks:
            for c in blk.cols:
                if hasattr(c, "purge_decoded"):
                    c.purge_decoded()



def build_layout(cache, group_cols, dicts, sort_col, needed_cols, schema):
    sig = ("zone_layout", tuple(group_cols), sort_col, tuple(sorted(needed_cols)), TILE_ROWS)
    blocks = cache.blocks

    def build(_blk):
        return ZoneLayout(blocks, group_cols, dicts, sort_col, sorted(needed_cols), schema)

    return cache.device_arrays(blocks[0], sig, build)


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------

class ZoneEvaluator:
    """Zone-path runner for one JaxDagEvaluator plan.  ``try_run`` returns the
    (state_np, n_slots, key_of) finalize inputs, or None to fall back."""

    def __init__(self, ev):
        self.ev = ev  # the owning JaxDagEvaluator
        import weakref

        # caches we already declined for (partial fraction too high): skip
        # the layout work on every later query against the same cache
        self._declined = weakref.WeakSet()
        self.served = 0  # queries answered by the zone path (observability)
        self.failed = 0  # zone-path crashes that fell through (observability)
        self.last_error: str | None = None

    # -- eligibility -------------------------------------------------------

    def eligible(self, blocks):
        from .tracker import count_path_fallback

        ev = self.ev
        if ev.plan.agg is None:
            return None
        stable = ev._stable_dict_group_cols(blocks)
        if stable is None:
            count_path_fallback("zone", "unstable_group_dicts")
            return None
        group_cols, dicts = stable
        for da in ev.device_aggs:
            if da.op not in _ZONE_AGG_OPS:
                count_path_fallback("zone", "agg_op")
                return None
            if da.rpn is not None:
                if da.rpn.eval_type == EvalType.REAL or da.input_type == EvalType.REAL:
                    # float sum order must match the CPU oracle — the
                    # VERDICT-weak-#6 decline that used to be invisible
                    count_path_fallback("zone", "real_arg")
                    return None
                for node in da.rpn.nodes:
                    if node.kind == "fn" and node.op not in _NULLSAFE_OPS:
                        count_path_fallback("zone", "non_nullsafe_fn")
                        return None
                    if node.kind == "const" and node.value is None:
                        # NULL literal breaks the null-safety rule
                        count_path_fallback("zone", "null_literal")
                        return None
        return group_cols, dicts

    # -- per-query host classification -------------------------------------

    def _classify_tiles(self, layout):
        """(full_mask, partial_idx) over tiles; empty tiles appear in
        neither.  Forced-partial: pad tiles and tiles with NULLs in any
        column referenced by selection or aggregate arguments."""
        ev = self.ev
        T = layout.n_tiles
        status_full = np.ones(T, dtype=bool)
        status_empty = np.zeros(T, dtype=bool)
        for rpn in ev.sel_rpns:
            rec = _recognize_conjunct(rpn)
            if rec is None:
                status_full[:] = False
                continue
            ci, op, cscale, const = rec
            if ci not in layout.zone_lo:
                status_full[:] = False
                continue
            if const is None:
                status_empty[:] = True
                status_full[:] = False
                continue
            lo, hi = layout.zone_lo[ci], layout.zone_hi[ci]
            if cscale != 1:
                # exact Python-int arithmetic: int64*scale may wrap in numpy,
                # and a wrapped bound could prove a tile "full" wrongly
                lo = lo.astype(object) * int(cscale)
                hi = hi.astype(object) * int(cscale)
            c = const
            if op == "lt":
                cf, ce = hi < c, lo >= c
            elif op == "le":
                cf, ce = hi <= c, lo > c
            elif op == "gt":
                cf, ce = lo > c, hi <= c
            elif op == "ge":
                cf, ce = lo >= c, hi < c
            elif op == "eq":
                cf, ce = (lo == c) & (hi == c), (c < lo) | (c > hi)
            else:  # ne
                cf, ce = (c < lo) | (c > hi), (lo == c) & (hi == c)
            # a NULL row fails every comparison: nulls block fullness
            cf = cf & ~layout.zone_has_null[ci]
            status_full &= cf
            status_empty |= ce
        forced = layout.has_pad.copy()
        for ci in self._referenced_cols():
            if ci in layout.zone_has_null:
                forced |= layout.zone_has_null[ci]
        full = status_full & ~status_empty & ~forced
        partial = ~full & ~status_empty
        # the tile-grained twin of the block-grained zone_maps counter:
        # proved-empty tiles are pruned work, same metric family
        from .zone_maps import count_prune

        count_prune("zone", "examined", T)
        count_prune("zone", "pruned", int(status_empty.sum()))
        return full, np.flatnonzero(partial).astype(np.int32)

    def _referenced_cols(self):
        ev = self.ev
        need = set()
        for r in ev.sel_rpns:
            need |= r.referenced_columns()
        for da in ev.device_aggs:
            if da.rpn is not None:
                need |= da.rpn.referenced_columns()
        return need

    # -- device programs ---------------------------------------------------

    def _full_fn(self, layout, capacity):
        """Full-tile contributions: pure tile reductions weighted by w_full."""
        # jitted fns live ON the layout: they close over it, so storing them
        # anywhere longer-lived would pin evicted layouts (and their device
        # arrays) forever; with the cache pin gone, layout + fns + compiled
        # programs all drop together.  Plan-signature keys let equivalent
        # evaluators share one compiled program (the endpoint's evaluator
        # LRU churns instances), and the dict is bounded.
        fns = _layout_fn_cache(layout)
        key = ("full", _agg_sig(self.ev), capacity)
        if key in fns:
            return fns[key]
        ev = self.ev
        T = layout.n_tiles
        track_first = bool(ev.group_rpns)
        ranges = layout.col_ranges

        def widen_cols(dev):
            cols = {}
            for i, a in dev["cols"].items():
                d = a.astype(jnp.int64) if a.dtype != jnp.float64 else a
                nl = dev["nulls"].get(i)
                cols[i] = (d, nl if nl is not None else jnp.zeros(layout.n_rows, dtype=bool))
            return cols

        def fn(dev, w_full):
            tg = dev["tile_gid"]
            wf = w_full
            seg = lambda x: jax.ops.segment_sum(x, tg, num_segments=capacity)
            vc = jnp.where(wf, dev["valid_count"].astype(jnp.int64), 0)
            counts = seg(vc)
            carries = []
            lazy_cols = None
            for da in ev.device_aggs:
                if da.op == "count":
                    # count(*) and count(expr) agree on full tiles: forced-
                    # partial removed every tile with NULLs in referenced
                    # columns, so all valid rows are live
                    carries.append((counts,))
                    continue
                bare = len(da.rpn.nodes) == 1 and da.rpn.nodes[0].kind == "col"
                if bare:
                    ci = da.rpn.nodes[0].index
                    arr2 = dev["cols"][ci].reshape(T, TILE_ROWS)
                    max_abs = max(abs(ranges[ci][0]), abs(ranges[ci][1]))
                else:
                    if lazy_cols is None:
                        lazy_cols = widen_cols(dev)
                    d, _nl = eval_rpn(da.rpn, lazy_cols, layout.n_rows, xp=jnp)
                    arr2 = d.reshape(T, TILE_ROWS)
                    max_abs = None  # already int64: _tile_sum sums directly
                if da.op in ("sum", "avg"):
                    ts = _tile_sum(arr2, max_abs if bare else 0)
                    carries.append((counts, seg(jnp.where(wf, ts, 0))))
                elif da.op == "var_pop":
                    # sumsq rides f64 (the CPU state's own dtype), fused
                    # square + same-dtype tile sum — vectorizes like the
                    # pure passes because nothing widens inside the reduce
                    ts = _tile_sum(arr2, max_abs if bare else 0)
                    f2 = arr2.astype(jnp.float64)
                    tsq = (f2 * f2).sum(axis=1)
                    carries.append((
                        counts,
                        seg(jnp.where(wf, ts, 0)),
                        seg(jnp.where(wf, tsq, 0.0)),
                    ))
                else:  # min / max — same-dtype tile reduce, then widen T-wise
                    red = (arr2.min(axis=1) if da.op == "min" else arr2.max(axis=1)).astype(jnp.int64)
                    info = np.iinfo(np.int64)
                    ident = info.max if da.op == "min" else info.min
                    red = jnp.where(wf, red, ident)
                    f = jax.ops.segment_min if da.op == "min" else jax.ops.segment_max
                    carries.append((counts, f(red, tg, num_segments=capacity)))
            if track_first:
                tmin = dev["ridx"].reshape(T, TILE_ROWS).min(axis=1)
                tmin = jnp.where(wf, tmin, _RIDX_INF)
                first = jax.ops.segment_min(tmin, tg, num_segments=capacity).astype(jnp.int64)
                first = jnp.where(first == int(_RIDX_INF), _NO_ROW_J, first)
            else:
                first = jnp.full(capacity, _NO_ROW_J, dtype=jnp.int64)
            return first, tuple(carries)

        return _fn_cache_put(
            fns, key,
            _obs.timed_jit(jax.jit(fn), "jax_zone.full", "zone",
                           self.ev.obs_sig))

    def _partial_fn(self, layout, capacity, pcap):
        """Gathered partial tiles: full row-level RPN evaluation over a
        (pcap, TILE_ROWS) bucket, padded entries weighted out."""
        fns = _layout_fn_cache(layout)
        key = ("partial", _plan_sig(self.ev), capacity, pcap)
        if key in fns:
            return fns[key]
        ev = self.ev
        T = layout.n_tiles
        track_first = bool(ev.group_rpns)
        n_sub = pcap * TILE_ROWS

        def fn(dev, pidx, pw):
            tg = dev["tile_gid"][pidx]
            tg = jnp.where(pw, tg, capacity - 1)  # scratch slot for padding
            cols = {}
            for i, a in dev["cols"].items():
                sub = a.reshape(T, TILE_ROWS)[pidx].reshape(n_sub)
                d = sub.astype(jnp.int64) if sub.dtype != jnp.float64 else sub
                nl = dev["nulls"].get(i)
                nl = (
                    nl.reshape(T, TILE_ROWS)[pidx].reshape(n_sub)
                    if nl is not None
                    else jnp.zeros(n_sub, dtype=bool)
                )
                cols[i] = (d, nl)
            valid = dev["valid"].reshape(T, TILE_ROWS)[pidx].reshape(n_sub)
            active = valid & jnp.broadcast_to(pw[:, None], (pcap, TILE_ROWS)).reshape(n_sub)
            for rpn in ev.sel_rpns:
                d, nl = eval_rpn(rpn, cols, n_sub, xp=jnp)
                active = active & (d != 0) & ~nl
            seg = lambda x: jax.ops.segment_sum(x, tg, num_segments=capacity)

            def tile_red(x, red):
                return red(x.reshape(pcap, TILE_ROWS), axis=1)

            carries = []
            for da in ev.device_aggs:
                if da.rpn is None:
                    live = active
                    data = None
                else:
                    data, dnl = eval_rpn(da.rpn, cols, n_sub, xp=jnp)
                    live = active & ~dnl
                cnt = seg(tile_red(live.astype(jnp.int64), jnp.sum))
                if da.op == "count":
                    carries.append((cnt,))
                elif da.op in ("sum", "avg"):
                    vals = jnp.where(live, data, 0)
                    carries.append((cnt, seg(tile_red(vals, jnp.sum))))
                elif da.op == "var_pop":
                    vals = jnp.where(live, data, 0)
                    f = jnp.where(live, data.astype(jnp.float64), 0.0)
                    carries.append((
                        cnt,
                        seg(tile_red(vals, jnp.sum)),
                        seg(tile_red(f * f, jnp.sum)),
                    ))
                else:
                    info = np.iinfo(np.int64)
                    ident = info.max if da.op == "min" else info.min
                    masked = jnp.where(live, data, ident)
                    red = tile_red(masked, jnp.min if da.op == "min" else jnp.max)
                    f = jax.ops.segment_min if da.op == "min" else jax.ops.segment_max
                    carries.append((cnt, f(red, tg, num_segments=capacity)))
            if track_first:
                ridx = dev["ridx"].reshape(T, TILE_ROWS)[pidx].reshape(n_sub)
                rm = jnp.where(active, ridx, _RIDX_INF)
                tmin = tile_red(rm, jnp.min)
                first = jax.ops.segment_min(tmin, tg, num_segments=capacity).astype(jnp.int64)
                first = jnp.where(first == int(_RIDX_INF), _NO_ROW_J, first)
            else:
                first = jnp.full(capacity, _NO_ROW_J, dtype=jnp.int64)
            return first, tuple(carries)

        return _fn_cache_put(
            fns, key,
            _obs.timed_jit(jax.jit(fn), "jax_zone.partial", "zone",
                           self.ev.obs_sig))

    # -- merge + run -------------------------------------------------------

    def try_run(self, cache):
        """Zone-serve the plan over ``cache``, or None to fall back.  A
        zone-path FAILURE (unexpected compiler/backend error — e.g. the
        first run on a new accelerator) is caught, recorded, and remembered
        per cache: the fast layer must never take down a query the slower
        layers can serve, and must not retry a crash on every request."""
        from .tracker import count_path_fallback

        breaker = getattr(self.ev, "breaker", None)
        if breaker is not None and not breaker.allow("zone"):
            count_path_fallback("zone", "breaker_open")
            return None
        try:
            out = self._try_run_inner(cache)
            if breaker is not None:
                if out is not None:
                    breaker.record_success("zone")
                else:
                    breaker.release_probe("zone")  # declined, didn't run
            return out
        except Exception as exc:  # noqa: BLE001 — generic path always serves
            self.failed += 1
            self.last_error = repr(exc)
            self._declined.add(cache)
            count_path_fallback("zone", "zone_error")
            if breaker is not None:
                breaker.record_failure("zone")
            return None

    def _try_run_inner(self, cache):
        from .tracker import count_path_fallback

        ev = self.ev
        blocks = cache.blocks
        if cache in self._declined:
            return None
        el = self.eligible(blocks)
        if el is None:
            return None
        group_cols, dicts = el
        if self.ev.sel_rpns and all(
            _recognize_conjunct(r) is None for r in self.ev.sel_rpns
        ):
            # no conjunct classifiable → 100% partial tiles: don't pay for a
            # layout the fallback check would immediately discard
            self._declined.add(cache)
            count_path_fallback("zone", "unclassifiable_selection")
            return None
        needed = self._referenced_cols()
        sort_col = None
        for rpn in ev.sel_rpns:
            rec = _recognize_conjunct(rpn)
            if rec is not None and rec[0] not in group_cols and ev.schema[rec[0]][0] != EvalType.REAL:
                sort_col = rec[0]
                break
        layout = build_layout(cache, group_cols, dicts, sort_col, needed, ev.schema)
        full, partial_idx = self._classify_tiles(layout)
        if layout.n_tiles and len(partial_idx) / layout.n_tiles > PARTIAL_FALLBACK:
            self._declined.add(cache)
            count_path_fallback("zone", "partial_fraction")
            return None
        n_slots = layout.n_slots
        capacity = 1
        while capacity < n_slots + 1:  # +1: scratch slot for partial padding
            capacity *= 2

        have_full = bool(full.any())
        have_partial = len(partial_idx) > 0
        states = []
        if have_full:
            fn = self._full_fn(layout, capacity)
            states.append(fn(layout.dev, jnp.asarray(full)))
        if have_partial:
            pcap = 64
            while pcap < len(partial_idx):
                pcap *= 2
            pidx = np.zeros(pcap, dtype=np.int32)
            pidx[: len(partial_idx)] = partial_idx
            pw = np.zeros(pcap, dtype=bool)
            pw[: len(partial_idx)] = True
            fn = self._partial_fn(layout, capacity, pcap)
            states.append(fn(layout.dev, jnp.asarray(pidx), jnp.asarray(pw)))
        if not states:
            # every tile proved empty: zero contributions
            states.append(
                self._full_fn(layout, capacity)(layout.dev, jnp.zeros(layout.n_tiles, dtype=bool))
            )
        merged = states[0] if len(states) == 1 else _merge_states(ev.device_aggs, states[0], states[1])
        state_np = jax.tree.map(np.asarray, merged)

        dict_lens = layout.dict_lens
        dicts_l = layout.dicts

        def key_of(slot: int) -> tuple:
            parts = []
            rem = int(slot)
            for d, dl in zip(reversed(dicts_l), reversed(dict_lens)):
                c = rem % (dl + 1)
                rem //= dl + 1
                parts.append(None if c == dl else bytes(d[c]))
            return tuple(reversed(parts))

        self.served += 1
        return state_np, n_slots, key_of


_NO_ROW_J = 1 << 62  # matches jax_eval._NO_ROW


def _merge_states(device_aggs, a, b):
    """Combine full-tile and partial-tile (first_row, carries) states."""
    first = jnp.minimum(a[0], b[0])
    carries = []
    for da, ca, cb in zip(device_aggs, a[1], b[1]):
        cnt = ca[0] + cb[0]
        if da.op == "count":
            carries.append((cnt,))
        elif da.op in ("sum", "avg"):
            carries.append((cnt, ca[1] + cb[1]))
        elif da.op == "var_pop":
            carries.append((cnt, ca[1] + cb[1], ca[2] + cb[2]))
        else:
            merge = jnp.minimum if da.op == "min" else jnp.maximum
            carries.append((cnt, merge(ca[1], cb[1])))
    return first, tuple(carries)
