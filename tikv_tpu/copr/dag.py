"""DAG request model + executor-chain runner + response encoding.

Re-expression of tipb's ``DagRequest``/executor descriptors and the
``BatchExecutorsRunner`` (``tidb_query_executors/src/runner.rs:41``):

* descriptors (dataclasses standing in for the tipb protos) describe the
  executor chain: scan leaf → selection/join/projection → aggregation/topN
  → limit (joins carry their build-side chain inline — docs/device_join.md)
* ``build_executors`` (runner.rs:150) assembles the chain
* ``handle_request`` (runner.rs:399) drives ``next_batch`` with the 32→×2→1024
  growing batch size and encodes output rows into datum-encoded chunks
  (``SelectResponse``-equivalent), chunked every 1024 rows

Response bytes are produced by a deterministic encoder so the CPU oracle and
the TPU path can be compared byte-for-byte (the BASELINE.json contract).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..analysis import bufsan as _bufsan
from ..server.wire import PASSTHROUGH_MIN as PART_MIN
from ..util import codec
from . import datum as datum_mod
from .aggr import AggDescriptor
from .datatypes import Chunk, Column, ColumnInfo, EvalType
from .executors import (
    BATCH_GROW_FACTOR,
    BATCH_INITIAL_SIZE,
    BATCH_MAX_SIZE,
    BatchExecutor,
    BatchHashAggregationExecutor,
    BatchIndexScanExecutor,
    BatchJoinExecutor,
    BatchLimitExecutor,
    BatchProjectionExecutor,
    BatchSelectionExecutor,
    BatchSimpleAggregationExecutor,
    BatchStreamAggregationExecutor,
    BatchTableScanExecutor,
    BatchTopNExecutor,
    FixtureScanSource,
    MvccScanSource,
    ScanSource,
)
from .rpn import Expr

# ---------------------------------------------------------------------------
# Executor descriptors (tipb::Executor equivalents)
# ---------------------------------------------------------------------------

@dataclass
class TableScan:
    table_id: int
    columns_info: list[ColumnInfo]


@dataclass
class IndexScan:
    table_id: int
    index_id: int
    columns_info: list[ColumnInfo]


@dataclass
class Selection:
    conditions: list[Expr]


@dataclass
class Aggregation:
    group_by: list[Expr]
    agg_funcs: list[AggDescriptor]
    streamed: bool = False


@dataclass
class TopN:
    order_by: list[tuple[Expr, bool]]  # (expr, desc)
    limit: int


@dataclass
class Limit:
    limit: int


@dataclass
class Projection:
    """Expression list over the child schema (tipb::Projection equivalent).

    Output columns are the evaluated expressions in order — the schema the
    downstream chain (and the response encoder) sees is
    ``[(expr.eval_type, expr.frac), ...]``."""

    exprs: list[Expr]


@dataclass
class Join:
    """Equi-join against a second executor chain (tipb::Join equivalent).

    The enclosing chain below this descriptor is the PROBE side; ``build``
    is the build side's own chain (a TableScan leaf plus optional
    Selections) scanned over ``build_ranges``.  Output schema is the probe
    schema followed by the build schema.  ``left_key``/``right_key`` are
    column offsets into the probe/build child schemas; ``join_type`` is
    ``"inner"`` or ``"left"`` (left-outer: unmatched probe rows emit build
    NULLs).  ``build_context`` optionally carries the build region's
    identity (region_id/region_epoch/apply_index) so the device rung can
    resolve the build side's warm image (docs/device_join.md)."""

    build: list
    build_ranges: list[tuple[bytes, bytes]]
    left_key: int
    right_key: int
    join_type: str = "inner"
    build_context: dict | None = None


ExecutorDescriptor = (TableScan | IndexScan | Selection | Aggregation | TopN
                      | Limit | Projection | Join)


#: response encodings (tipb EncodeType): datum rows are the default and the
#: compatibility oracle; TypeChunk ships whole column slabs with no row
#: materialization (docs/wire_path.md "Columnar chunk responses")
ENC_TYPE_DATUM = 0
ENC_TYPE_CHUNK = 1


@dataclass
class DagRequest:
    """The pushed-down plan (tipb::DagRequest equivalent)."""

    executors: list[ExecutorDescriptor]
    output_offsets: list[int] | None = None  # None = all columns
    chunk_rows: int = 1024
    # negotiated response encoding (tipb DagRequest.encode_type): clients
    # opt into ENC_TYPE_CHUNK per request; unsupported plans/field types
    # decline back to the datum codec (negotiate_encode_type)
    encode_type: int = ENC_TYPE_DATUM


@dataclass
class ExecSummary:
    """Per-executor execution summary (tidb_query_common/src/execute_stats.rs)."""

    num_produced_rows: int = 0
    num_iterations: int = 0


class SelectResponse:
    """The coprocessor DAG answer in either response encoding.

    Datum responses (the default) carry joined per-chunk row bytes in
    ``chunks`` exactly as before.  TypeChunk responses keep each chunk as a
    LIST of per-column slabs in ``chunk_parts`` — ``chunks`` joins lazily so
    the canonical ``encode()`` framing (and every byte-identity compare)
    stays one definition, while :meth:`encode_parts` hands the unjoined
    column slabs to the wire layer for the ``dumps_parts``/``sendmsg``
    gather write (docs/wire_path.md)."""

    def __init__(self, chunks: list[bytes] | None = None, exec_summaries=None,
                 warnings=None, encode_type: int = ENC_TYPE_DATUM,
                 chunk_parts: "list[list[bytes]] | None" = None,
                 field_types=None):
        assert chunks is not None or chunk_parts is not None
        self._chunks = chunks
        self.chunk_parts = chunk_parts
        self.exec_summaries: list[ExecSummary] = exec_summaries or []
        self.warnings: list[str] = warnings or []
        self.encode_type = encode_type
        # output schema for decoding TypeChunk columns — clients attach it
        # from their own plan (chunk_output_field_types); never on the wire
        self.field_types = field_types

    @property
    def chunks(self) -> list[bytes]:
        if self._chunks is None:
            self._chunks = [b"".join(map(bytes, p)) for p in self.chunk_parts]
        return self._chunks

    @chunks.setter
    def chunks(self, v: list[bytes]) -> None:
        self._chunks = v
        self.chunk_parts = None

    def encode(self) -> bytes:
        """Deterministic wire encoding — the byte-identity contract surface.
        Framing is shared across encode types; only chunk contents differ."""
        return b"".join(map(bytes, self.encode_parts()))

    def encode_parts(self) -> list:
        """The same bytes as :meth:`encode`, as a buffer list: each chunk's
        column slabs stay the encoder's own bytes objects (no join), so the
        wire layer's ``dumps_parts`` passthrough gather-writes them without
        a re-encoding copy.  Datum responses frame their joined chunks the
        same way."""
        per_chunk = (self.chunk_parts if self.chunk_parts is not None
                     else [[c] for c in self.chunks])
        parts: list = []
        head = bytearray()
        head += codec.encode_var_u64(len(per_chunk))
        for cols in per_chunk:
            head += codec.encode_var_u64(sum(len(c) for c in cols))
            for c in cols:
                # column slabs worth a gather iovec ride as their own part
                # (wire.PASSTHROUGH_MIN); small ones fold into the header.
                # From here the slab is an exposure: it must stay bit-stable
                # until the frame writer's send completes (bufsan tracks the
                # window under TIKV_TPU_SANITIZE=1)
                if len(c) >= PART_MIN:
                    if head:
                        parts.append(bytes(head))
                        head = bytearray()
                    _bufsan.export("encode_parts", c,
                                   site="dag.SelectResponse.encode_parts")
                    parts.append(c)
                else:
                    head += c
        head += codec.encode_var_u64(len(self.warnings))
        for w in self.warnings:
            wb = w.encode()
            head += codec.encode_var_u64(len(wb))
            head += wb
        if head:
            parts.append(bytes(head))
        return parts

    @classmethod
    def decode(cls, blob: bytes,
               encode_type: int = ENC_TYPE_DATUM) -> "SelectResponse":
        """Parse the wire encoding back (client-side partial merges and
        tests; the inverse of :meth:`encode`).  ``encode_type`` is the
        NEGOTIATED encoding the response rode (the response dict's
        ``encode_type`` key) — the framing itself is encoding-agnostic."""
        n, off = codec.decode_var_u64(blob, 0)
        chunks = []
        for _ in range(n):
            ln, off = codec.decode_var_u64(blob, off)
            chunks.append(bytes(blob[off:off + ln]))
            off += ln
        warnings = []
        if off < len(blob):
            nw, off = codec.decode_var_u64(blob, off)
            for _ in range(nw):
                ln, off = codec.decode_var_u64(blob, off)
                warnings.append(blob[off:off + ln].decode())
                off += ln
        return cls(chunks, warnings=warnings, encode_type=encode_type)

    def iter_rows(self, field_types=None) -> list[list]:
        """Decode all chunks back into python rows.  TypeChunk responses
        need the output schema (``field_types`` here, or attached by
        ``decode_wire_response``); values are identical to the datum path's
        row by row (the differential-test contract)."""
        if self.encode_type == ENC_TYPE_CHUNK:
            from . import chunk_codec

            fts = field_types or self.field_types
            if fts is None:
                raise ValueError("TypeChunk rows need the output field types")
            rows: list[list] = []
            for chunk in self.chunks:
                cols = chunk_codec.decode_chunk(chunk, fts)
                col_vals = [chunk_codec.column_values(c) for c in cols]
                rows.extend([list(r) for r in zip(*col_vals)] if col_vals
                            else [])
            return rows
        rows = []
        for chunk in self.chunks:
            off = 0
            while off < len(chunk):
                ncols, off = codec.decode_var_u64(chunk, off)
                row = []
                for _ in range(ncols):
                    d, off = datum_mod.decode_datum(chunk, off)
                    row.append(d.value)
                rows.append(row)
        return rows


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

def check_supported(dag: DagRequest) -> None:
    """Raise ValueError for plans the batch pipeline cannot run
    (runner.rs:75 check_supported).  Since the device-join work
    (docs/device_join.md) Join and Projection ARE coprocessor-side here —
    inner/left-outer equi-joins carry their build chain inline, Projections
    evaluate the scalar expression surface — so only Exchange (and other
    TiDB/TiFlash-only operators) remains out of the matrix."""
    if not dag.executors:
        raise ValueError("empty executor list")
    if not isinstance(dag.executors[0], (TableScan, IndexScan)):
        raise ValueError("first executor must be a scan")
    for e in dag.executors[1:]:
        if isinstance(e, (TableScan, IndexScan)):
            raise ValueError("scan executor must be the leaf")
        if isinstance(e, Join):
            _check_join(e)
        elif not isinstance(e, (Selection, Aggregation, TopN, Limit,
                                Projection)):
            raise ValueError(f"unsupported executor {type(e).__name__}")


def _check_join(j: Join) -> None:
    """Validate one Join descriptor's build chain: its own scan leaf plus
    optional Selections — no nested joins, no aggregates (the reference
    pushes only simple build sides to storage)."""
    if j.join_type not in ("inner", "left"):
        raise ValueError(f"unsupported join type {j.join_type!r}")
    if not j.build or not isinstance(j.build[0], TableScan):
        raise ValueError("join build chain must start with a TableScan")
    for e in j.build[1:]:
        if not isinstance(e, Selection):
            raise ValueError(
                f"unsupported build-side executor {type(e).__name__}")


def _attach(ex: BatchExecutor, desc, source: ScanSource | None,
            build_leaf: BatchExecutor | None = None) -> BatchExecutor:
    """Chain one non-leaf descriptor onto ``ex`` — the single
    descriptor→executor mapping the probe chain, join build chains and the
    device join rung's downstream finisher all share."""
    if isinstance(desc, Selection):
        return BatchSelectionExecutor(ex, desc.conditions)
    if isinstance(desc, Aggregation):
        if not desc.group_by:
            return BatchSimpleAggregationExecutor(ex, desc.agg_funcs)
        if desc.streamed:
            return BatchStreamAggregationExecutor(ex, desc.group_by, desc.agg_funcs)
        return BatchHashAggregationExecutor(ex, desc.group_by, desc.agg_funcs)
    if isinstance(desc, TopN):
        return BatchTopNExecutor(ex, desc.order_by, desc.limit)
    if isinstance(desc, Limit):
        return BatchLimitExecutor(ex, desc.limit)
    if isinstance(desc, Projection):
        return BatchProjectionExecutor(ex, desc.exprs)
    if isinstance(desc, Join):
        if build_leaf is not None:
            build_ex = build_leaf
        else:
            b_src = (source.fork(desc.build_ranges)
                     if source is not None else None)
            build_ex = BatchTableScanExecutor(b_src, desc.build[0].columns_info)
        for b in desc.build[1:]:
            build_ex = _attach(build_ex, b, None)
        return BatchJoinExecutor(ex, build_ex, desc.left_key, desc.right_key,
                                 desc.join_type)
    raise AssertionError(desc)


def build_executors(dag: DagRequest, source: ScanSource,
                    leaf: BatchExecutor | None = None,
                    build_leaf: BatchExecutor | None = None) -> BatchExecutor:
    """runner.rs:150 build_executors equivalent.  ``leaf`` overrides the scan
    executor (e.g. CachedBlocksExecutor for the warm block-cache path);
    ``build_leaf`` likewise overrides a Join descriptor's build-side scan.
    Without an override, a Join's build side scans a ``source.fork`` over
    its own ranges — the same snapshot, so both sides of the join read one
    consistent view.  Construction never touches the sources (drains are
    deferred to the first next_batch), so schema-only walks with
    ``source=None`` stay valid for plans with joins."""
    check_supported(dag)
    head = dag.executors[0]
    if leaf is not None:
        ex = leaf
    elif isinstance(head, TableScan):
        ex: BatchExecutor = BatchTableScanExecutor(source, head.columns_info)
    else:
        from .table import index_range

        prefix_len = len(index_range(head.table_id, head.index_id)[0])
        ex = BatchIndexScanExecutor(source, head.columns_info, prefix_len)
    for desc in dag.executors[1:]:
        ex = _attach(ex, desc, source, build_leaf=build_leaf)
    return ex


# ---------------------------------------------------------------------------
# TypeChunk negotiation (docs/wire_path.md "Columnar chunk responses")
# ---------------------------------------------------------------------------

# response schema for chunk columns, derived from the executor chain's
# (EvalType, frac) output schema: signed 8-byte ints mirror the datum value
# domain exactly (datum_at encodes INT signed, DATETIME as the packed u64,
# decimals as the fixed-point int64 + frac), so decoded chunk rows equal
# decoded datum rows by construction.  ENUM/SET have no datum-identical
# chunk mapping here and decline.
_CHUNK_TP = {
    EvalType.INT: "LONGLONG",
    EvalType.REAL: "DOUBLE",
    EvalType.DECIMAL: "NEW_DECIMAL",
    EvalType.BYTES: "VAR_STRING",
    EvalType.JSON: "JSON",
    EvalType.DATETIME: "DATETIME",
    EvalType.DURATION: "DURATION",
}

_UNSET = object()


def chunk_output_field_types(dag: DagRequest):
    """The response column FieldTypes a TypeChunk encoding of ``dag`` uses,
    or None when the plan declines to the datum codec (the decline cause is
    stashed as ``dag._chunk_decline``).  Derived from the SAME executor
    schema both pipelines serve (build_executors(dag, None).schema() — scan
    leaves never touch the source at construction), memoized per DagRequest
    object: plans are parse-memoized per (bytes, encode_type) by the
    service, so the walk runs once per distinct plan."""
    from .chunk_codec import MAX_VEC_DECIMAL_FRAC
    from .datatypes import FieldType, FieldTypeTp

    cached = getattr(dag, "_chunk_fts", _UNSET)
    if cached is not _UNSET:
        return cached
    try:
        schema = build_executors(dag, None).schema()
    except Exception:  # noqa: BLE001 — unbuildable plan: datum decides
        dag._chunk_decline = "plan"
        dag._chunk_fts = None
        return None
    offsets = dag.output_offsets
    try:
        out_schema = (schema if offsets is None
                      else [schema[i] for i in offsets])
    except IndexError:
        dag._chunk_decline = "plan"
        dag._chunk_fts = None
        return None
    fts = []
    for et, frac in out_schema:
        tp = _CHUNK_TP.get(et)
        if tp is None or (et == EvalType.DECIMAL
                          and frac > MAX_VEC_DECIMAL_FRAC):
            dag._chunk_decline = "field_type"
            dag._chunk_fts = None
            return None
        fts.append(FieldType(getattr(FieldTypeTp, tp), decimal=frac))
    if not fts:
        # zero output columns: datum rows still carry a per-row ncols
        # marker, but a chunk of no columns cannot carry a row count —
        # decline to the datum codec
        dag._chunk_decline = "field_type"
        dag._chunk_fts = None
        return None
    dag._chunk_fts = fts
    return fts


def datum_twin(dag: DagRequest) -> DagRequest:
    """The same plan with the datum encoding — what a declined TypeChunk
    request serves as.  Shares the executor descriptors (and therefore the
    endpoint's evaluator/memo entries keyed on the datum plan bytes)."""
    twin = getattr(dag, "_datum_twin", None)
    if twin is None:
        from dataclasses import replace

        twin = replace(dag, encode_type=ENC_TYPE_DATUM)
        dag._datum_twin = twin
    return twin


def negotiate_encode_type(dag: DagRequest) -> tuple[DagRequest, str | None]:
    """Resolve the request's effective encoding: ``(dag, None)`` when the
    requested encoding serves as-is, ``(datum twin, cause)`` when a
    TypeChunk request declines (unsupported field type, unbuildable plan) —
    a decline is a datum response, never an error."""
    if dag.encode_type != ENC_TYPE_CHUNK:
        return dag, None
    if chunk_output_field_types(dag) is not None:
        return dag, None
    return datum_twin(dag), getattr(dag, "_chunk_decline", "plan")


def response_data(resp: dict) -> bytes:
    """A wire response dict's payload bytes: joins ``data_parts`` (TypeChunk
    responses ship each large column slab as its own frame part) or returns
    ``data`` — the client-side inverse of ``encode_parts``."""
    parts = resp.get("data_parts")
    if parts is not None:
        return b"".join(bytes(p) for p in parts)
    return resp["data"]


def decode_wire_response(resp: dict, dag: DagRequest) -> SelectResponse:
    """Decode a coprocessor wire response dict against the plan the client
    sent: joins the frame parts, parses the shared framing, and attaches
    the TypeChunk output schema so ``iter_rows`` decodes either encoding."""
    sr = SelectResponse.decode(response_data(resp),
                               encode_type=resp.get("encode_type",
                                                    ENC_TYPE_DATUM))
    if sr.encode_type == ENC_TYPE_CHUNK:
        sr.field_types = chunk_output_field_types(dag)
    return sr


class ResponseEncoder:
    """Row-exact chunk framer: a new chunk starts every ``chunk_rows`` rows,
    independent of producer batch boundaries — so the CPU and device paths
    emit byte-identical framing for identical row streams.

    Large batches encode through the vectorized column codec
    (``datum_vec.encode_chunk_rows`` — numpy batch varints/fixed cells, one
    ragged scatter per column); tiny batches and exotic column types keep
    the scalar per-row loop.  Both paths emit identical bytes
    (tests/test_wire_path.py)."""

    encode_type = ENC_TYPE_DATUM

    def __init__(self, chunk_rows: int):
        self.chunk_rows = chunk_rows
        self.chunks: list[bytes] = []
        self._cur = bytearray()
        self._rows = 0

    def add_chunk(self, chunk: Chunk, output_offsets: list[int] | None) -> int:
        cols = (
            chunk.columns
            if output_offsets is None
            else [chunk.columns[i] for i in output_offsets]
        )
        from . import datum_vec

        n_rows = chunk.num_rows
        if n_rows >= datum_vec.VEC_MIN_ROWS and datum_vec.supported(cols):
            buf, row_ends = datum_vec.encode_chunk_rows(cols, chunk.logical_rows)
            start_row, start_byte = 0, 0
            take = self.chunk_rows - self._rows
            while start_row + take <= n_rows:
                end_byte = int(row_ends[start_row + take - 1])
                self._cur += buf[start_byte:end_byte]
                self.chunks.append(bytes(self._cur))
                self._cur = bytearray()
                self._rows = 0
                start_row += take
                start_byte = end_byte
                take = self.chunk_rows
            self._cur += buf[start_byte:]
            self._rows += n_rows - start_row
            return n_rows
        n = 0
        for row in chunk.logical_rows:
            self._cur += codec.encode_var_u64(len(cols))
            for c in cols:
                flag, value = c.datum_at(int(row))
                datum_mod.encode_datum(self._cur, flag, value)
            n += 1
            self._rows += 1
            if self._rows == self.chunk_rows:
                self.chunks.append(bytes(self._cur))
                self._cur = bytearray()
                self._rows = 0
        return n

    def finish(self) -> list[bytes]:
        if self._rows:
            self.chunks.append(bytes(self._cur))
            self._cur = bytearray()
            self._rows = 0
        return self.chunks

    # -- shared encoder surface (the runner/evaluators stay encoding-blind) --

    def to_response(self, **kw) -> SelectResponse:
        return SelectResponse(chunks=self.finish(), **kw)

    def pending_frames(self) -> int:
        return len(self.chunks)

    def flush_response(self, n: int) -> SelectResponse:
        """Pop the first ``n`` finished chunks as one streamed response
        frame (the streaming runner's flush unit)."""
        flushed, self.chunks = self.chunks[:n], self.chunks[n:]
        return SelectResponse(chunks=flushed)


class ChunkResponseEncoder:
    """The :class:`ResponseEncoder` twin for TypeChunk responses: the same
    row-exact framing (a new chunk every ``chunk_rows`` rows, independent of
    producer batch boundaries — so streamed flushes align with the datum
    path's), but each chunk is built as per-column slabs straight from the
    producer's numpy columns:

    * ``Column.take``/``EncodedColumn.take`` late-materializes only the
      selected rows (encoded-resident columns decode only survivors),
    * null bitmap / end-offset / cell assembly is one vectorized pass per
      column (``chunk_codec.encode_np_column``) — no per-row Python,
    * ``finish()`` returns ``list[list[bytes]]`` (per chunk, per column),
      which ``SelectResponse.encode_parts`` hands to the wire gather write
      without ever joining the slabs.

    Callers guarantee supportability up front (``chunk_output_field_types``
    — the same probe the negotiation decline uses), so an unsupported
    column type here is a programming error, not a client-visible one."""

    encode_type = ENC_TYPE_CHUNK

    def __init__(self, chunk_rows: int, field_types):
        assert field_types is not None, "chunk encoding needs the output schema"
        self.chunk_rows = chunk_rows
        self.field_types = field_types
        self.chunks: list[list[bytes]] = []
        self._segs: list[list] = []  # pending row-compacted Column segments
        self._rows = 0

    def add_chunk(self, chunk: Chunk, output_offsets: list[int] | None) -> int:
        cols = (chunk.columns if output_offsets is None
                else [chunk.columns[i] for i in output_offsets])
        logical = np.asarray(chunk.logical_rows)
        n = len(logical)
        if n == 0:
            return 0
        full = (cols and n == len(cols[0])
                and logical[0] == 0 and logical[-1] == n - 1
                and np.array_equal(logical, np.arange(n)))
        taken = list(cols) if full else [c.take(logical) for c in cols]
        self._segs.append(taken)
        self._rows += n
        while self._rows >= self.chunk_rows:
            self._emit(self.chunk_rows)
        return n

    def _emit(self, k: int) -> None:
        """Assemble one chunk of exactly ``k`` rows from the pending
        segments (splitting the boundary segment), one vectorized encode
        per column."""
        from . import chunk_codec, encoding as _encoding

        pieces: list[list] = []
        got = 0
        while got < k:
            seg = self._segs[0]
            seg_n = len(seg[0]) if seg else 0
            take = min(k - got, seg_n)
            if take == seg_n:
                pieces.append(self._segs.pop(0))
            else:
                pieces.append([c.slice(0, take) for c in seg])
                self._segs[0] = [c.slice(take, seg_n) for c in seg]
            got += take
        self._rows -= k
        out_cols: list[bytes] = []
        for j, ft in enumerate(self.field_types):
            parts = [p[j] for p in pieces]
            if len(parts) > 1 and any(p.dictionary is not None for p in parts):
                # mixed dict/plain segments: codes are only meaningful
                # per-segment — materialize before concatenating
                parts = [p.decoded() for p in parts]
            d = parts[0].dictionary if len(parts) == 1 else None
            # the no-cache accessors: a resident EncodedColumn must not be
            # left holding a full decode by response encoding (the budget
            # counts encoded bytes — docs/compressed_columns.md)
            if len(parts) == 1:
                data = np.asarray(_encoding.decoded_data(parts[0]))
                nulls = np.asarray(_encoding.decoded_nulls(parts[0]))
            else:
                data = np.concatenate(
                    [np.asarray(_encoding.decoded_data(p)) for p in parts])
                nulls = np.concatenate(
                    [np.asarray(_encoding.decoded_nulls(p)) for p in parts])
            out_cols.append(chunk_codec.encode_np_column(ft, data, nulls, d))
        self.chunks.append(out_cols)

    def finish(self) -> list[list[bytes]]:
        if self._rows:
            self._emit(self._rows)
        return self.chunks

    def to_response(self, **kw) -> SelectResponse:
        return SelectResponse(chunk_parts=self.finish(),
                              encode_type=ENC_TYPE_CHUNK,
                              field_types=self.field_types, **kw)

    def pending_frames(self) -> int:
        return len(self.chunks)

    def flush_response(self, n: int) -> SelectResponse:
        flushed, self.chunks = self.chunks[:n], self.chunks[n:]
        return SelectResponse(chunk_parts=flushed, encode_type=ENC_TYPE_CHUNK,
                              field_types=self.field_types)


def make_response_encoder(dag: DagRequest):
    """The one encoder-selection rule every serving path shares (CPU runner,
    unary/zone/fused/xregion/mesh device finalizers, streaming): TypeChunk
    when the plan negotiated it, else the datum framer.  Defensive: an
    unsupported chunk plan that slipped past the entry-gate negotiation
    still serves datum bytes rather than erroring."""
    if dag.encode_type == ENC_TYPE_CHUNK:
        fts = chunk_output_field_types(dag)
        if fts is not None:
            return ChunkResponseEncoder(dag.chunk_rows, fts)
    return ResponseEncoder(dag.chunk_rows)


class BatchExecutorsRunner:
    """Drive loop (runner.rs:399)."""

    def __init__(self, dag: DagRequest, source: ScanSource | None, leaf: BatchExecutor | None = None):
        self.dag = dag
        self.executor = build_executors(dag, source, leaf)
        self.summary = ExecSummary()

    def handle_request(self) -> SelectResponse:
        enc = make_response_encoder(self.dag)
        batch_size = BATCH_INITIAL_SIZE
        while True:
            r = self.executor.next_batch(batch_size)
            self.summary.num_iterations += 1
            if r.chunk.num_rows:
                enc.add_chunk(r.chunk, self.dag.output_offsets)
                self.summary.num_produced_rows += r.chunk.num_rows
            if r.is_drained:
                break
            if batch_size < BATCH_MAX_SIZE:
                batch_size = min(batch_size * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
        return enc.to_response(exec_summaries=[self.summary])

    def handle_streaming_request(self, rows_per_stream: int = 1024):
        """Streaming path (runner.rs:471 + endpoint.rs:508-584): yield one
        SelectResponse per ~rows_per_stream output rows so unbounded scans
        never buffer whole results.  Frames flush at whole response chunks
        in EITHER encoding — TypeChunk streams column-slab frames aligned
        with the same chunk_rows framing the datum stream uses."""
        enc = make_response_encoder(self.dag)
        batch_size = BATCH_INITIAL_SIZE
        while True:
            r = self.executor.next_batch(batch_size)
            self.summary.num_iterations += 1
            if r.chunk.num_rows:
                enc.add_chunk(r.chunk, self.dag.output_offsets)
                self.summary.num_produced_rows += r.chunk.num_rows
            # flush whole chunks as soon as a frame's worth accumulated
            per_frame = max(1, rows_per_stream // self.dag.chunk_rows)
            while enc.pending_frames() >= per_frame:
                yield enc.flush_response(per_frame)
            if r.is_drained:
                break
            if batch_size < BATCH_MAX_SIZE:
                batch_size = min(batch_size * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
        # final response always carries the exec summaries, like the unary path
        yield enc.to_response(exec_summaries=[self.summary])
