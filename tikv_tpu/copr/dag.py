"""DAG request model + executor-chain runner + response encoding.

Re-expression of tipb's ``DagRequest``/executor descriptors and the
``BatchExecutorsRunner`` (``tidb_query_executors/src/runner.rs:41``):

* descriptors (dataclasses standing in for the tipb protos) describe the
  executor chain: scan leaf → selection → aggregation/topN → limit
* ``build_executors`` (runner.rs:150) assembles the chain
* ``handle_request`` (runner.rs:399) drives ``next_batch`` with the 32→×2→1024
  growing batch size and encodes output rows into datum-encoded chunks
  (``SelectResponse``-equivalent), chunked every 1024 rows

Response bytes are produced by a deterministic encoder so the CPU oracle and
the TPU path can be compared byte-for-byte (the BASELINE.json contract).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..util import codec
from . import datum as datum_mod
from .aggr import AggDescriptor
from .datatypes import Chunk, Column, ColumnInfo, EvalType
from .executors import (
    BATCH_GROW_FACTOR,
    BATCH_INITIAL_SIZE,
    BATCH_MAX_SIZE,
    BatchExecutor,
    BatchHashAggregationExecutor,
    BatchIndexScanExecutor,
    BatchLimitExecutor,
    BatchSelectionExecutor,
    BatchSimpleAggregationExecutor,
    BatchStreamAggregationExecutor,
    BatchTableScanExecutor,
    BatchTopNExecutor,
    FixtureScanSource,
    MvccScanSource,
    ScanSource,
)
from .rpn import Expr

# ---------------------------------------------------------------------------
# Executor descriptors (tipb::Executor equivalents)
# ---------------------------------------------------------------------------

@dataclass
class TableScan:
    table_id: int
    columns_info: list[ColumnInfo]


@dataclass
class IndexScan:
    table_id: int
    index_id: int
    columns_info: list[ColumnInfo]


@dataclass
class Selection:
    conditions: list[Expr]


@dataclass
class Aggregation:
    group_by: list[Expr]
    agg_funcs: list[AggDescriptor]
    streamed: bool = False


@dataclass
class TopN:
    order_by: list[tuple[Expr, bool]]  # (expr, desc)
    limit: int


@dataclass
class Limit:
    limit: int


ExecutorDescriptor = TableScan | IndexScan | Selection | Aggregation | TopN | Limit


@dataclass
class DagRequest:
    """The pushed-down plan (tipb::DagRequest equivalent)."""

    executors: list[ExecutorDescriptor]
    output_offsets: list[int] | None = None  # None = all columns
    chunk_rows: int = 1024


@dataclass
class ExecSummary:
    """Per-executor execution summary (tidb_query_common/src/execute_stats.rs)."""

    num_produced_rows: int = 0
    num_iterations: int = 0


@dataclass
class SelectResponse:
    chunks: list[bytes]
    exec_summaries: list[ExecSummary] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)

    def encode(self) -> bytes:
        """Deterministic wire encoding — the byte-identity contract surface."""
        out = bytearray()
        out += codec.encode_var_u64(len(self.chunks))
        for c in self.chunks:
            out += codec.encode_var_u64(len(c))
            out += c
        out += codec.encode_var_u64(len(self.warnings))
        for w in self.warnings:
            wb = w.encode()
            out += codec.encode_var_u64(len(wb))
            out += wb
        return bytes(out)

    @classmethod
    def decode(cls, blob: bytes) -> "SelectResponse":
        """Parse the wire encoding back (client-side partial merges and
        tests; the inverse of :meth:`encode`)."""
        n, off = codec.decode_var_u64(blob, 0)
        chunks = []
        for _ in range(n):
            ln, off = codec.decode_var_u64(blob, off)
            chunks.append(bytes(blob[off:off + ln]))
            off += ln
        warnings = []
        if off < len(blob):
            nw, off = codec.decode_var_u64(blob, off)
            for _ in range(nw):
                ln, off = codec.decode_var_u64(blob, off)
                warnings.append(blob[off:off + ln].decode())
                off += ln
        return cls(chunks, warnings=warnings)

    def iter_rows(self) -> list[list]:
        """Decode all chunks back into python rows (test convenience)."""
        rows = []
        for chunk in self.chunks:
            off = 0
            while off < len(chunk):
                ncols, off = codec.decode_var_u64(chunk, off)
                row = []
                for _ in range(ncols):
                    d, off = datum_mod.decode_datum(chunk, off)
                    row.append(d.value)
                rows.append(row)
        return rows


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

def check_supported(dag: DagRequest) -> None:
    """Raise ValueError for plans the batch pipeline cannot run
    (runner.rs:75 check_supported; Join/Projection/Exchange unsupported there
    too — they are TiDB/TiFlash-side operators)."""
    if not dag.executors:
        raise ValueError("empty executor list")
    if not isinstance(dag.executors[0], (TableScan, IndexScan)):
        raise ValueError("first executor must be a scan")
    for e in dag.executors[1:]:
        if isinstance(e, (TableScan, IndexScan)):
            raise ValueError("scan executor must be the leaf")
        if not isinstance(e, (Selection, Aggregation, TopN, Limit)):
            raise ValueError(f"unsupported executor {type(e).__name__}")


def build_executors(dag: DagRequest, source: ScanSource, leaf: BatchExecutor | None = None) -> BatchExecutor:
    """runner.rs:150 build_executors equivalent.  ``leaf`` overrides the scan
    executor (e.g. CachedBlocksExecutor for the warm block-cache path)."""
    check_supported(dag)
    head = dag.executors[0]
    if leaf is not None:
        ex = leaf
    elif isinstance(head, TableScan):
        ex: BatchExecutor = BatchTableScanExecutor(source, head.columns_info)
    else:
        from .table import index_range

        prefix_len = len(index_range(head.table_id, head.index_id)[0])
        ex = BatchIndexScanExecutor(source, head.columns_info, prefix_len)
    for desc in dag.executors[1:]:
        if isinstance(desc, Selection):
            ex = BatchSelectionExecutor(ex, desc.conditions)
        elif isinstance(desc, Aggregation):
            if not desc.group_by:
                ex = BatchSimpleAggregationExecutor(ex, desc.agg_funcs)
            elif desc.streamed:
                ex = BatchStreamAggregationExecutor(ex, desc.group_by, desc.agg_funcs)
            else:
                ex = BatchHashAggregationExecutor(ex, desc.group_by, desc.agg_funcs)
        elif isinstance(desc, TopN):
            ex = BatchTopNExecutor(ex, desc.order_by, desc.limit)
        elif isinstance(desc, Limit):
            ex = BatchLimitExecutor(ex, desc.limit)
        else:
            raise AssertionError(desc)
    return ex


class ResponseEncoder:
    """Row-exact chunk framer: a new chunk starts every ``chunk_rows`` rows,
    independent of producer batch boundaries — so the CPU and device paths
    emit byte-identical framing for identical row streams.

    Large batches encode through the vectorized column codec
    (``datum_vec.encode_chunk_rows`` — numpy batch varints/fixed cells, one
    ragged scatter per column); tiny batches and exotic column types keep
    the scalar per-row loop.  Both paths emit identical bytes
    (tests/test_wire_path.py)."""

    def __init__(self, chunk_rows: int):
        self.chunk_rows = chunk_rows
        self.chunks: list[bytes] = []
        self._cur = bytearray()
        self._rows = 0

    def add_chunk(self, chunk: Chunk, output_offsets: list[int] | None) -> int:
        cols = (
            chunk.columns
            if output_offsets is None
            else [chunk.columns[i] for i in output_offsets]
        )
        from . import datum_vec

        n_rows = chunk.num_rows
        if n_rows >= datum_vec.VEC_MIN_ROWS and datum_vec.supported(cols):
            buf, row_ends = datum_vec.encode_chunk_rows(cols, chunk.logical_rows)
            start_row, start_byte = 0, 0
            take = self.chunk_rows - self._rows
            while start_row + take <= n_rows:
                end_byte = int(row_ends[start_row + take - 1])
                self._cur += buf[start_byte:end_byte]
                self.chunks.append(bytes(self._cur))
                self._cur = bytearray()
                self._rows = 0
                start_row += take
                start_byte = end_byte
                take = self.chunk_rows
            self._cur += buf[start_byte:]
            self._rows += n_rows - start_row
            return n_rows
        n = 0
        for row in chunk.logical_rows:
            self._cur += codec.encode_var_u64(len(cols))
            for c in cols:
                flag, value = c.datum_at(int(row))
                datum_mod.encode_datum(self._cur, flag, value)
            n += 1
            self._rows += 1
            if self._rows == self.chunk_rows:
                self.chunks.append(bytes(self._cur))
                self._cur = bytearray()
                self._rows = 0
        return n

    def finish(self) -> list[bytes]:
        if self._rows:
            self.chunks.append(bytes(self._cur))
            self._cur = bytearray()
            self._rows = 0
        return self.chunks


class BatchExecutorsRunner:
    """Drive loop (runner.rs:399)."""

    def __init__(self, dag: DagRequest, source: ScanSource | None, leaf: BatchExecutor | None = None):
        self.dag = dag
        self.executor = build_executors(dag, source, leaf)
        self.summary = ExecSummary()

    def handle_request(self) -> SelectResponse:
        enc = ResponseEncoder(self.dag.chunk_rows)
        batch_size = BATCH_INITIAL_SIZE
        while True:
            r = self.executor.next_batch(batch_size)
            self.summary.num_iterations += 1
            if r.chunk.num_rows:
                enc.add_chunk(r.chunk, self.dag.output_offsets)
                self.summary.num_produced_rows += r.chunk.num_rows
            if r.is_drained:
                break
            if batch_size < BATCH_MAX_SIZE:
                batch_size = min(batch_size * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
        return SelectResponse(chunks=enc.finish(), exec_summaries=[self.summary])

    def handle_streaming_request(self, rows_per_stream: int = 1024):
        """Streaming path (runner.rs:471 + endpoint.rs:508-584): yield one
        SelectResponse per ~rows_per_stream output rows so unbounded scans
        never buffer whole results."""
        enc = ResponseEncoder(self.dag.chunk_rows)
        batch_size = BATCH_INITIAL_SIZE
        emitted = 0
        while True:
            r = self.executor.next_batch(batch_size)
            self.summary.num_iterations += 1
            if r.chunk.num_rows:
                enc.add_chunk(r.chunk, self.dag.output_offsets)
                self.summary.num_produced_rows += r.chunk.num_rows
            # flush whole chunks as soon as a frame's worth accumulated
            per_frame = max(1, rows_per_stream // self.dag.chunk_rows)
            while len(enc.chunks) >= per_frame:
                flushed = enc.chunks[:per_frame]
                enc.chunks = enc.chunks[per_frame:]
                emitted += 1
                yield SelectResponse(chunks=flushed)
            if r.is_drained:
                break
            if batch_size < BATCH_MAX_SIZE:
                batch_size = min(batch_size * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
        # final response always carries the exec summaries, like the unary path
        yield SelectResponse(chunks=enc.finish(), exec_summaries=[self.summary])
