"""Coprocessor V2: pluggable raw-KV coprocessors.

Re-expression of ``src/coprocessor_v2`` + ``components/coprocessor_plugin_api``
(plugin_api.rs:20 ``CoprocessorPlugin``, storage_api.rs:21 ``RawStorage``,
plugin_registry.rs:74/:218 dylib registry with hot reload): plugins are
versioned handlers operating on raw KV through a narrow storage API, routed
by ``copr_name`` + a semver requirement.  The reference loads Rust dylibs;
here plugins are Python classes registered programmatically or loaded from a
plugin directory (one module per plugin, hot-reloadable by mtime).
"""

from __future__ import annotations

import importlib.util
import os
import threading


class PluginError(Exception):
    pass


class RawStorage:
    """The narrow storage surface handed to plugins (storage_api.rs:21)."""

    def __init__(self, storage, ctx: dict | None = None):
        self._storage = storage
        self._ctx = ctx

    def get(self, key: bytes) -> bytes | None:
        return self._storage.raw_get(key, self._ctx)

    def batch_get(self, keys: list[bytes]) -> list[tuple[bytes, bytes]]:
        return self._storage.raw_batch_get(keys, self._ctx)

    def scan(self, start: bytes, end: bytes | None, limit: int | None = None):
        return self._storage.raw_scan(start, end, limit, self._ctx)

    def put(self, key: bytes, value: bytes) -> None:
        self._storage.raw_put(key, value, self._ctx)

    def batch_put(self, pairs: list[tuple[bytes, bytes]]) -> None:
        self._storage.raw_batch_put(pairs, self._ctx)

    def delete(self, key: bytes) -> None:
        self._storage.raw_delete(key, self._ctx)

    def delete_range(self, start: bytes, end: bytes) -> None:
        self._storage.raw_delete_range(start, end, self._ctx)


class CoprocessorPlugin:
    """Plugin ABI (plugin_api.rs:20): subclass and implement on_request."""

    NAME: str = ""
    VERSION: tuple[int, int, int] = (0, 0, 0)

    def on_raw_coprocessor_request(self, ranges, request: bytes, storage: RawStorage) -> bytes:
        raise NotImplementedError


def _semver_match(version: tuple[int, int, int], req: str) -> bool:
    """Caret-style requirement: the leftmost NON-ZERO component is the
    compatibility boundary (semver caret: ^1.2 = >=1.2 <2; ^0.1 = 0.1.x;
    ^0.0.3 = exactly 0.0.3)."""
    if not req or req == "*":
        return True
    parts = [int(x) for x in req.split(".")]
    if parts[0] != version[0]:
        return False
    if parts[0] == 0:
        if len(parts) >= 2 and parts[1] != version[1]:
            return False
        if parts[0] == 0 and len(parts) >= 2 and parts[1] == 0:
            return len(parts) < 3 or parts[2] == version[2]
    return tuple(parts) <= version[: len(parts)]


class PluginRegistry:
    """Versioned registry + directory hot-reload (plugin_registry.rs:74)."""

    def __init__(self, plugin_dir: str | None = None):
        self._mu = threading.RLock()
        self._plugins: dict[str, CoprocessorPlugin] = {}
        self.plugin_dir = plugin_dir
        self._mtimes: dict[str, float] = {}
        self._path_names: dict[str, str] = {}
        self.load_errors: dict[str, str] = {}

    def register(self, plugin: CoprocessorPlugin) -> None:
        if not plugin.NAME:
            raise PluginError("plugin must define NAME")
        with self._mu:
            self._plugins[plugin.NAME] = plugin

    def unregister(self, name: str) -> None:
        with self._mu:
            self._plugins.pop(name, None)

    def get(self, name: str, version_req: str = "*") -> CoprocessorPlugin:
        self._maybe_reload()
        with self._mu:
            p = self._plugins.get(name)
        if p is None:
            raise PluginError(f"no such plugin {name!r}")
        if not _semver_match(p.VERSION, version_req):
            raise PluginError(
                f"plugin {name!r} version {'.'.join(map(str, p.VERSION))} "
                f"does not satisfy {version_req!r}"
            )
        return p

    def list_plugins(self) -> dict[str, tuple[int, int, int]]:
        self._maybe_reload()
        with self._mu:
            return {n: p.VERSION for n, p in self._plugins.items()}

    # -- directory loading (dylib hot-reload equivalent) --------------------

    def _maybe_reload(self) -> None:
        if self.plugin_dir is None or not os.path.isdir(self.plugin_dir):
            return
        present = set()
        for fn in os.listdir(self.plugin_dir):
            if not fn.endswith(".py") or fn.startswith("_"):
                continue
            path = os.path.join(self.plugin_dir, fn)
            present.add(path)
            mtime = os.path.getmtime(path)
            if self._mtimes.get(path) == mtime:
                continue
            self._mtimes[path] = mtime
            try:
                self._load_file(path)
                self.load_errors.pop(path, None)
            except Exception as e:  # noqa: BLE001 — one bad plugin file must
                # not break dispatch for the healthy ones (registry parity)
                self.load_errors[path] = repr(e)
        # deleted files unload their plugins (the reference unloads dylibs)
        for path in list(self._path_names):
            if path not in present:
                self.unregister(self._path_names.pop(path))
                self._mtimes.pop(path, None)

    def _load_file(self, path: str) -> None:
        name = "tikv_tpu_plugin_" + os.path.basename(path)[:-3]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # a plugin module exposes PLUGIN (instance) or declare_plugin()
        plugin = getattr(mod, "PLUGIN", None)
        if plugin is None and hasattr(mod, "declare_plugin"):
            plugin = mod.declare_plugin()
        if plugin is not None:
            self.register(plugin)
            self._path_names[path] = plugin.NAME


class CoprV2Endpoint:
    """Route RawCoprocessorRequests to plugins (src/coprocessor_v2/endpoint.rs:52)."""

    def __init__(self, storage, registry: PluginRegistry | None = None):
        self.storage = storage
        self.registry = registry or PluginRegistry()

    def handle_request(self, req: dict) -> dict:
        """req: {copr_name, copr_version_req, data, ranges, context}."""
        try:
            plugin = self.registry.get(req["copr_name"], req.get("copr_version_req", "*"))
            storage = RawStorage(self.storage, req.get("context"))
            ranges = [tuple(r) for r in req.get("ranges", [])]
            data = plugin.on_raw_coprocessor_request(ranges, req.get("data", b""), storage)
            return {"data": data}
        except PluginError as e:
            return {"error": {"other": str(e)}}
        except Exception as e:  # noqa: BLE001 — plugin faults stay contained
            return {"error": {"other": f"plugin error: {e!r}"}}
