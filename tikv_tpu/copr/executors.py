"""The vectorized (batch) executor pipeline — Volcano-style pull, columnar.

Re-expression of ``tidb_query_executors``: each executor implements
``next_batch(scan_rows) → BatchExecuteResult{chunk, is_drained}``
(``src/interface.rs:21,144-178``); the chain is TableScan/IndexScan at the
leaf, then Selection / Aggregation / TopN / Limit above
(``src/{table_scan,index_scan,selection,simple_aggr,fast_hash_aggr,
slow_hash_aggr,stream_aggr,top_n,limit}_executor.rs``).

Differences by design (TPU-first):

* Filtering updates ``Chunk.logical_rows`` (an index selection) exactly like
  the reference, so downstream executors and the device path both see
  fixed-shape physical columns + a selection.
* Aggregation states are segment reductions (see aggr.py), which are the
  mergeable shard states the mesh-parallel evaluator reduces with psum.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cmp_to_key

import numpy as np

from ..storage.mvcc import ForwardScanner, Statistics
from ..util import codec
from . import datum as datum_mod
from .aggr import AggDescriptor, AggState
from .datatypes import Chunk, Column, ColumnInfo, EvalType
from .groupby import GroupDict
from .rpn import Expr, RpnExpression, compile_expr, eval_rpn
from .table import RowBatchDecoder, decode_record_handles

BATCH_INITIAL_SIZE = 32
BATCH_MAX_SIZE = 1024
BATCH_GROW_FACTOR = 2


@dataclass
class BatchExecuteResult:
    chunk: Chunk
    is_drained: bool


def cols_for_eval(columns: list[Column], needed=None) -> dict:
    """(data, nulls) pairs for expression eval; dictionary-encoded bytes
    columns are materialized only when an expression actually references
    them."""
    out = {}
    for i, c in enumerate(columns):
        if needed is not None and i not in needed:
            continue
        c = c.decoded() if c.is_dict_encoded else c
        out[i] = (c.data, c.nulls)
    return out


class BatchExecutor:
    """Pull-based executor node."""

    def schema(self) -> list[tuple[EvalType, int]]:
        """(eval_type, frac) per output column."""
        raise NotImplementedError

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Scan sources (tidb_query_common Storage trait + RangesScanner equivalent)
# ---------------------------------------------------------------------------

class ScanSource:
    """Produces raw (key, value) pairs range by range."""

    def next_batch(self, n: int) -> tuple[list[bytes], list[bytes], bool]:
        """Returns (keys, values, drained)."""
        raise NotImplementedError

    def fork(self, ranges: list[tuple[bytes, bytes]]) -> "ScanSource":
        """A sibling source over different ranges off the SAME underlying
        view — how a Join descriptor's build side scans the build table
        consistently with the probe scan (docs/device_join.md)."""
        raise NotImplementedError(
            f"{type(self).__name__} cannot fork a build-side scan")


class MvccScanSource(ScanSource):
    """MVCC snapshot scan over raw-key ranges (SnapshotStore + RangesScanner)."""

    def __init__(
        self,
        snapshot,
        ts: int,
        ranges: list[tuple[bytes, bytes]],
        statistics: Statistics | None = None,
        **scan_kwargs,
    ):
        from ..storage.txn_types import Key

        self._snapshot = snapshot
        self._ts = ts
        self._scan_kwargs = scan_kwargs
        self.stats = statistics or Statistics()
        self._iters = [
            iter(
                ForwardScanner(
                    snapshot,
                    ts,
                    Key.from_raw(start),
                    Key.from_raw(end),
                    statistics=self.stats,
                    **scan_kwargs,
                )
            )
            for start, end in ranges
        ]
        self._cur = 0

    def next_batch(self, n: int) -> tuple[list[bytes], list[bytes], bool]:
        keys: list[bytes] = []
        vals: list[bytes] = []
        while len(keys) < n and self._cur < len(self._iters):
            it = self._iters[self._cur]
            try:
                k, v = next(it)
                keys.append(k)
                vals.append(v)
            except StopIteration:
                self._cur += 1
        return keys, vals, self._cur >= len(self._iters)

    def fork(self, ranges: list[tuple[bytes, bytes]]) -> "MvccScanSource":
        # same snapshot + read ts: the join's two sides see one consistent
        # view; scan statistics accumulate into the request's one ledger
        return MvccScanSource(self._snapshot, self._ts, ranges,
                              statistics=self.stats, **self._scan_kwargs)


class FixtureScanSource(ScanSource):
    """In-memory (key, value) fixture — test/bench leaf without MVCC."""

    def __init__(self, items: list[tuple[bytes, bytes]]):
        self.items = items
        self.pos = 0

    def next_batch(self, n: int) -> tuple[list[bytes], list[bytes], bool]:
        chunk = self.items[self.pos : self.pos + n]
        self.pos += len(chunk)
        return [k for k, _ in chunk], [v for _, v in chunk], self.pos >= len(self.items)

    def fork(self, ranges: list[tuple[bytes, bytes]]) -> "FixtureScanSource":
        return FixtureScanSource(
            [(k, v) for k, v in self.items
             if any(s <= k < e for s, e in ranges)])


# ---------------------------------------------------------------------------
# Leaf executors
# ---------------------------------------------------------------------------

class CachedBlocksExecutor(BatchExecutor):
    """Leaf serving pre-decoded column blocks from a ColumnBlockCache — the
    CPU pipeline's warm path (same cached data the device path reuses)."""

    def __init__(self, cache, columns_info: list[ColumnInfo]):
        self.cache = cache
        self.columns_info = columns_info
        self._idx = 0

    def schema(self) -> list[tuple[EvalType, int]]:
        return [(c.ftype.eval_type, c.ftype.decimal) for c in self.columns_info]

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        blocks = self.cache.blocks
        if self._idx >= len(blocks):
            return BatchExecuteResult(Chunk.full([]), True)
        blk = blocks[self._idx]
        self._idx += 1
        cols = [c.slice(0, blk.n_valid) for c in blk.cols]
        return BatchExecuteResult(Chunk.full(cols), self._idx >= len(blocks))


class BatchTableScanExecutor(BatchExecutor):  # noqa: E302
    """Decode record rows into columns (table_scan_executor.rs:20)."""

    def __init__(self, source: ScanSource, columns_info: list[ColumnInfo]):
        self.source = source
        self.columns_info = columns_info
        self.decoder = RowBatchDecoder(columns_info)

    def schema(self) -> list[tuple[EvalType, int]]:
        return [(c.ftype.eval_type, c.ftype.decimal) for c in self.columns_info]

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        keys, values, drained = self.source.next_batch(scan_rows)
        handles = decode_record_handles(keys)
        cols = self.decoder.decode(handles, values)
        return BatchExecuteResult(Chunk.full(cols), drained)


class BatchIndexScanExecutor(BatchExecutor):
    """Decode index entries (index_scan_executor.rs:29).

    Index key layout: prefix + datum values (for_key encodings); value is the
    8-byte handle.  Output columns: the indexed columns in order, then the
    handle if requested (pk handle column at the end, like the reference).
    """

    def __init__(self, source: ScanSource, columns_info: list[ColumnInfo], prefix_len: int):
        self.source = source
        self.columns_info = columns_info
        self.prefix_len = prefix_len
        self.handle_idx = [i for i, c in enumerate(columns_info) if c.is_pk_handle]

    def schema(self) -> list[tuple[EvalType, int]]:
        return [(c.ftype.eval_type, c.ftype.decimal) for c in self.columns_info]

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        keys, values, drained = self.source.next_batch(scan_rows)
        n = len(keys)
        non_handle = [c for c in self.columns_info if not c.is_pk_handle]
        per_col_values: list[list] = [[] for _ in non_handle]
        for k in keys:
            off = self.prefix_len
            for ci in range(len(non_handle)):
                d, off = datum_mod.decode_datum(k, off)
                if d.flag == datum_mod.DECIMAL_FLAG:
                    per_col_values[ci].append(d.value[0])
                elif d.flag == datum_mod.NIL_FLAG:
                    per_col_values[ci].append(None)
                else:
                    per_col_values[ci].append(d.value)
        cols: list[Column] = []
        vi = 0
        for c in self.columns_info:
            if c.is_pk_handle:
                handles = np.fromiter(
                    (codec.decode_u64(v) for v in values), dtype=np.int64, count=n
                ).astype(np.int64)
                cols.append(Column(EvalType.INT, handles, np.zeros(n, dtype=bool)))
            else:
                cols.append(
                    Column.from_values(c.ftype.eval_type, per_col_values[vi], c.ftype.decimal)
                )
                vi += 1
        return BatchExecuteResult(Chunk.full(cols), drained)


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------

class BatchSelectionExecutor(BatchExecutor):
    """Filter by conjunction of predicates (selection_executor.rs:18)."""

    def __init__(self, child: BatchExecutor, conditions: list[Expr]):
        self.child = child
        self._schema = child.schema()
        self.conditions = [compile_expr(c, self._schema) for c in conditions]

    def schema(self):
        return self._schema

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        r = self.child.next_batch(scan_rows)
        chunk = r.chunk
        if chunk.num_rows == 0:
            return r
        n = len(chunk.columns[0]) if chunk.columns else 0
        keep = np.ones(n, dtype=bool)
        needed = set()
        for rpn in self.conditions:
            needed |= rpn.referenced_columns()
        cols = cols_for_eval(chunk.columns, needed)
        for rpn in self.conditions:
            data, nulls = eval_rpn(rpn, cols, n)
            keep &= (np.asarray(data) != 0) & ~np.asarray(nulls)
        logical = chunk.logical_rows[keep[chunk.logical_rows]]
        return BatchExecuteResult(Chunk(chunk.columns, logical), r.is_drained)


# ---------------------------------------------------------------------------
# Projection + Join (the CPU oracle half of docs/device_join.md)
# ---------------------------------------------------------------------------

class BatchProjectionExecutor(BatchExecutor):
    """Evaluate an expression list over the child rows (tipb::Projection):
    output columns are the expressions in order, physically compacted.
    Reuses the same RPN/kernels scalar surface as Selection, so the device
    paths share its differential target by construction."""

    def __init__(self, child: BatchExecutor, exprs: list[Expr]):
        self.child = child
        self._child_schema = child.schema()
        self.exprs = [compile_expr(e, self._child_schema) for e in exprs]
        if not self.exprs:
            raise ValueError("projection needs at least one expression")
        self._needed = set()
        for rpn in self.exprs:
            self._needed |= rpn.referenced_columns()

    def schema(self) -> list[tuple[EvalType, int]]:
        return [(r.eval_type, r.frac) for r in self.exprs]

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        r = self.child.next_batch(scan_rows)
        chunk = r.chunk
        if chunk.num_rows == 0:
            return BatchExecuteResult(Chunk.full([]), r.is_drained)
        n = len(chunk.columns[0]) if chunk.columns else 0
        logical = chunk.logical_rows
        cols = cols_for_eval(chunk.columns, self._needed)
        out = []
        for rpn in self.exprs:
            data, nulls = eval_rpn(rpn, cols, n)
            out.append(Column(rpn.eval_type, np.asarray(data)[logical],
                              np.asarray(nulls)[logical], rpn.frac))
        return BatchExecuteResult(Chunk.full(out), r.is_drained)


# join keys are compared by VALUE (dictionary columns decode through their
# dictionaries), so shared-dict, disjoint-dict and plain columns all join
# consistently; NULL keys never match (SQL equi-join semantics)
_JOINABLE_KEY_TYPES = frozenset({
    EvalType.INT, EvalType.BYTES, EvalType.REAL, EvalType.DECIMAL,
    EvalType.DATETIME, EvalType.DURATION,
})


def _join_key_values(col: Column) -> list:
    """Hashable per-row key values for a (compacted, plain) column — None
    for NULL rows."""
    c = col.decoded() if col.is_dict_encoded else col
    data = np.asarray(c.data)
    nulls = np.asarray(c.nulls)
    out = []
    for i in range(len(data)):
        if nulls[i]:
            out.append(None)
            continue
        v = data[i]
        if isinstance(v, np.generic):
            v = v.item()
        if isinstance(v, (bytes, bytearray)):
            v = bytes(v)
        out.append(v)
    return out


def _concat_build_columns(parts: list[Column], et: EvalType,
                          frac: int) -> Column:
    """Concatenate the build side's per-batch compacted columns into one.
    Dictionary codes only concatenate when every part shares the SAME
    dictionary object; otherwise values materialize first."""
    if not parts:
        return Column.from_values(et, [], frac)
    if len(parts) == 1:
        return parts[0]
    d = parts[0].dictionary
    if any(p.dictionary is not d for p in parts):
        parts = [p.decoded() if p.is_dict_encoded else p for p in parts]
        d = None
    data = np.concatenate([np.asarray(p.data) for p in parts])
    nulls = np.concatenate([np.asarray(p.nulls) for p in parts])
    return Column(et, data, nulls, frac, d)


class BatchJoinExecutor(BatchExecutor):
    """Equi-join the child (probe) rows against a fully drained build chain
    (tipb::Join, inner + left-outer).

    Output row order is deterministic — probe stream order, with each probe
    row's matches in build-row order — which is exactly the order the device
    rank/hash kernels reproduce, so the two paths byte-compare at the wire
    (docs/device_join.md)."""

    def __init__(self, probe: BatchExecutor, build: BatchExecutor,
                 left_key: int, right_key: int, join_type: str = "inner"):
        self.probe = probe
        self.build = build
        self.left_key = left_key
        self.right_key = right_key
        self.join_type = join_type
        self._pschema = probe.schema()
        self._bschema = build.schema()
        if not 0 <= left_key < len(self._pschema):
            raise ValueError(f"join left key offset {left_key} out of range")
        if not 0 <= right_key < len(self._bschema):
            raise ValueError(f"join right key offset {right_key} out of range")
        for et in (self._pschema[left_key][0], self._bschema[right_key][0]):
            if et not in _JOINABLE_KEY_TYPES:
                raise ValueError(f"unsupported join key type {et}")
        self._table: dict | None = None  # key value -> build row id array
        self._bcols: list[Column] | None = None

    def schema(self) -> list[tuple[EvalType, int]]:
        return self._pschema + self._bschema

    def _ensure_build(self) -> None:
        if self._table is not None:
            return
        per_col: list[list[Column]] = [[] for _ in self._bschema]
        batch = BATCH_INITIAL_SIZE
        while True:
            r = self.build.next_batch(batch)
            if r.chunk.num_rows:
                cc = r.chunk.compact()
                for i, col in enumerate(cc.columns):
                    per_col[i].append(col)
            if r.is_drained:
                break
            batch = min(batch * BATCH_GROW_FACTOR, BATCH_MAX_SIZE)
        self._bcols = [
            _concat_build_columns(parts, et, frac)
            for parts, (et, frac) in zip(per_col, self._bschema)
        ]
        table: dict = {}
        for i, k in enumerate(_join_key_values(self._bcols[self.right_key])):
            if k is not None:
                table.setdefault(k, []).append(i)
        self._table = {k: np.asarray(v, dtype=np.int64)
                       for k, v in table.items()}

    def _gather_build(self, bidx: np.ndarray) -> list[Column]:
        missing = bidx < 0
        if not missing.any():
            return [c.take(bidx) for c in self._bcols]
        n_build = len(self._bcols[0]) if self._bcols else 0
        if n_build == 0:
            return [Column.from_values(et, [None] * len(bidx), frac)
                    for et, frac in self._bschema]
        safe = np.where(missing, 0, bidx)
        out = []
        for c, (et, frac) in zip(self._bcols, self._bschema):
            g = c.take(safe)
            nulls = np.asarray(g.nulls).copy()
            nulls[missing] = True
            out.append(Column(et, g.data, nulls, frac, g.dictionary))
        return out

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        self._ensure_build()
        r = self.probe.next_batch(scan_rows)
        chunk = r.chunk
        if chunk.num_rows == 0:
            return BatchExecuteResult(Chunk.full([]), r.is_drained)
        pc = chunk.compact()
        keys = _join_key_values(pc.columns[self.left_key])
        probe_parts: list[np.ndarray] = []
        build_parts: list[np.ndarray] = []
        left = self.join_type == "left"
        for i, k in enumerate(keys):
            rows = self._table.get(k) if k is not None else None
            if rows is not None:
                probe_parts.append(np.full(len(rows), i, dtype=np.int64))
                build_parts.append(rows)
            elif left:
                probe_parts.append(np.array([i], dtype=np.int64))
                build_parts.append(np.array([-1], dtype=np.int64))
        if not probe_parts:
            return BatchExecuteResult(Chunk.full([]), r.is_drained)
        pidx = np.concatenate(probe_parts)
        bidx = np.concatenate(build_parts)
        out = [c.take(pidx) for c in pc.columns]
        out.extend(self._gather_build(bidx))
        return BatchExecuteResult(Chunk.full(out), r.is_drained)


class ChunkFeedExecutor(BatchExecutor):
    """Leaf replaying prepared compact chunks — the device join rung's
    bridge into the CPU executor chain for descriptors ABOVE the Join
    (shared code keeps the finishing stages byte-identical by
    construction)."""

    def __init__(self, schema: list[tuple[EvalType, int]],
                 chunks: list[Chunk]):
        self._schema = schema
        self._chunks = chunks
        self._idx = 0

    def schema(self) -> list[tuple[EvalType, int]]:
        return self._schema

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._idx >= len(self._chunks):
            return BatchExecuteResult(Chunk.full([]), True)
        c = self._chunks[self._idx]
        self._idx += 1
        return BatchExecuteResult(c, self._idx >= len(self._chunks))


# ---------------------------------------------------------------------------
# Aggregations
# ---------------------------------------------------------------------------

class _AggBase(BatchExecutor):
    def __init__(self, child: BatchExecutor, aggs: list[AggDescriptor]):
        self.child = child
        self.child_schema = child.schema()
        self.aggs = aggs
        self.compiled: list[RpnExpression | None] = [
            compile_expr(a.expr, self.child_schema) if a.expr is not None else None
            for a in aggs
        ]
        self.states = [
            AggState(
                a.op,
                c.eval_type if c is not None else EvalType.INT,
                c.frac if c is not None else 0,
            )
            for a, c in zip(self.aggs, self.compiled)
        ]
        self._done = False

    def _agg_schema(self) -> list[tuple[EvalType, int]]:
        out = []
        for a, c in zip(self.aggs, self.compiled):
            it = c.eval_type if c is not None else EvalType.INT
            frac = c.frac if c is not None else 0
            if a.op == "count":
                out.append((EvalType.INT, 0))
            elif a.op == "avg":
                out.append((EvalType.INT, 0))
                out.append((it, frac))
            elif a.op == "var_pop":
                out.append((EvalType.INT, 0))
                out.append((EvalType.REAL, 0))
                out.append((EvalType.REAL, 0))
            elif a.op in ("bit_and", "bit_or", "bit_xor"):
                out.append((EvalType.INT, 0))
            else:
                out.append((it, frac))
        return out

    def _update_batch(self, chunk: Chunk, group_ids: np.ndarray, n_groups: int) -> None:
        logical = chunk.logical_rows
        needed = set()
        for rpn in self.compiled:
            if rpn is not None:
                needed |= rpn.referenced_columns()
        cols = cols_for_eval(chunk.columns, needed)
        n = len(chunk.columns[0]) if chunk.columns else 0
        for state, rpn in zip(self.states, self.compiled):
            state.grow(n_groups)
            if rpn is None:
                state.update(group_ids, None, None)
            else:
                data, nulls = eval_rpn(rpn, cols, n)
                state.update(group_ids, np.asarray(data)[logical], np.asarray(nulls)[logical])


class BatchSimpleAggregationExecutor(_AggBase):
    """All rows in one group (simple_aggr_executor.rs:22). Emits exactly one row."""

    def schema(self):
        return self._agg_schema()

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        drained = False
        while not drained:
            r = self.child.next_batch(scan_rows)
            drained = r.is_drained
            if r.chunk.num_rows:
                gids = np.zeros(r.chunk.num_rows, dtype=np.int64)
                self._update_batch(r.chunk, gids, 1)
            else:
                for s in self.states:
                    s.grow(1)
        self._done = True
        out: list[Column] = []
        for s in self.states:
            s.grow(1)
            out.extend(s.result_columns(1))
        return BatchExecuteResult(Chunk.full(out), True)


class BatchHashAggregationExecutor(_AggBase):
    """Hash group-by (fast/slow_hash_aggr_executor.rs merged: n group cols).

    Output columns: aggregate result columns first, then group-by columns —
    the reference's column order.
    """

    def __init__(self, child: BatchExecutor, group_by: list[Expr], aggs: list[AggDescriptor]):
        super().__init__(child, aggs)
        self.group_by = [compile_expr(g, self.child_schema) for g in group_by]
        self.groups = GroupDict()
        # group index → (eval_type, name dictionary) for ENUM/SET key columns
        self._group_dicts: dict[int, tuple[EvalType, np.ndarray]] = {}

    def schema(self):
        return self._agg_schema() + [(g.eval_type, g.frac) for g in self.group_by]

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        drained = False
        while not drained:
            r = self.child.next_batch(scan_rows)
            drained = r.is_drained
            chunk = r.chunk
            if not chunk.num_rows:
                continue
            n = len(chunk.columns[0]) if chunk.columns else 0
            logical = chunk.logical_rows
            for gi, g in enumerate(self.group_by):
                if len(g.nodes) == 1 and g.nodes[0].kind == "col":
                    c = chunk.columns[g.nodes[0].index]
                    if c.eval_type in (EvalType.ENUM, EvalType.SET) and c.dictionary is not None:
                        self._group_dicts.setdefault(gi, (c.eval_type, c.dictionary))
            gids = self._gids_for_chunk(chunk, n, logical)
            self._update_batch(chunk, gids, len(self.groups))
        self._done = True
        n_groups = len(self.groups)
        out: list[Column] = []
        for s in self.states:
            s.grow(n_groups)
            out.extend(s.result_columns(n_groups))
        # group-by key columns
        for gi, g in enumerate(self.group_by):
            vals = [self.groups.rows[r][gi] for r in range(n_groups)]
            col = Column.from_values(g.eval_type, vals, g.frac)
            if gi in self._group_dicts:
                et, d = self._group_dicts[gi]
                if et == g.eval_type:
                    col.dictionary = d
            out.append(col)
        return BatchExecuteResult(Chunk.full(out), True)

    def _gids_for_chunk(self, chunk: Chunk, n: int, logical: np.ndarray) -> np.ndarray:
        coded = _coded_group_parts(self.group_by, chunk.columns, logical)
        if coded is not None:
            if len(coded) == 1:
                return self.groups.assign_coded(*coded[0])
            return self.groups.assign_coded_multi(coded)
        needed = set()
        for g in self.group_by:
            needed |= g.referenced_columns()
        cols = cols_for_eval(chunk.columns, needed)
        key_parts = []
        for g in self.group_by:
            data, nulls = eval_rpn(g, cols, n)
            key_parts.append((np.asarray(data)[logical], np.asarray(nulls)[logical]))
        return self.groups.assign(key_parts)


class BatchStreamAggregationExecutor(_AggBase):
    """Group-by over input already sorted on the group key
    (stream_aggr_executor.rs:23).  Memory is bounded by ONE open group: each
    child batch is segmented at key-change boundaries (a vectorized adjacent
    compare, no per-row Python), completed segments are emitted immediately,
    and only the trailing segment's partial state carries to the next batch —
    the reason stream agg exists next to the hash path.
    """

    def __init__(self, child: BatchExecutor, group_by: list[Expr], aggs: list[AggDescriptor]):
        super().__init__(child, aggs)
        self.group_by = [compile_expr(g, self.child_schema) for g in group_by]
        # open-group carry: key as ((null, value), ...) or None when no group
        self._open_key: tuple | None = None
        # group index → (eval_type, name dictionary) for ENUM/SET key columns
        self._group_dicts: dict[int, tuple[EvalType, np.ndarray]] = {}

    def schema(self):
        return self._agg_schema() + [(g.eval_type, g.frac) for g in self.group_by]

    # -- carry management ---------------------------------------------------

    def _rebase_states(self, keep_idx: int | None) -> None:
        """Shrink every AggState to just the open group (or to empty) —
        emitted groups' state is dropped, keeping memory O(1) in groups."""
        for state in self.states:
            state.rebase(keep_idx)

    def _emit(self, n_groups: int, key_rows: list[tuple]) -> Chunk:
        out: list[Column] = []
        for s in self.states:
            out.extend(s.result_columns(n_groups))
        for gi, g in enumerate(self.group_by):
            vals = [None if key[gi][0] else key[gi][1] for key in key_rows]
            kcol = Column.from_values(g.eval_type, vals, g.frac)
            if gi in self._group_dicts:
                et, d = self._group_dicts[gi]
                if et == g.eval_type:
                    kcol.dictionary = d
            out.append(kcol)
        return Chunk.full(out)

    # -- drive --------------------------------------------------------------

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        r = self.child.next_batch(scan_rows)
        chunk = r.chunk
        if not chunk.num_rows:
            if r.is_drained:
                self._done = True
                if self._open_key is not None:
                    final = self._emit(1, [self._open_key])
                    self._open_key = None
                    return BatchExecuteResult(final, True)
                return BatchExecuteResult(self._emit(0, []), True)
            return BatchExecuteResult(self._emit(0, []), False)

        logical = chunk.logical_rows
        m = len(logical)
        n = len(chunk.columns[0])
        for gi, g in enumerate(self.group_by):
            if len(g.nodes) == 1 and g.nodes[0].kind == "col":
                c = chunk.columns[g.nodes[0].index]
                if c.eval_type in (EvalType.ENUM, EvalType.SET) and c.dictionary is not None:
                    self._group_dicts.setdefault(gi, (c.eval_type, c.dictionary))
        needed = set()
        for g in self.group_by:
            needed |= g.referenced_columns()
        cols = cols_for_eval(chunk.columns, needed)
        parts = []
        for g in self.group_by:
            data, nulls = eval_rpn(g, cols, n)
            parts.append((np.asarray(data)[logical], np.asarray(nulls)[logical]))

        # segment boundaries: adjacent-rows key change (NULLs group together)
        new_seg = np.zeros(m, dtype=bool)
        for d, nl in parts:
            if m > 1:
                diff = (nl[1:] != nl[:-1]) | (~nl[1:] & ~nl[:-1] & (d[1:] != d[:-1]))
                new_seg[1:] |= diff
        # NULL key cells canonicalize to (True, None): the data under a null
        # is whatever the kernel happened to compute and must not influence
        # group identity (GroupDict maps NULLs to None the same way)
        first_key = tuple(
            (True, None) if nl[0] else (False, _as_key_val(d[0])) for d, nl in parts
        )
        carried = self._open_key is not None
        continues = carried and first_key == self._open_key
        new_seg[0] = not continues

        # group id per logical row: the carried group (if any) keeps id 0;
        # each boundary opens the next id
        local = np.cumsum(new_seg.astype(np.int64))
        if not carried:
            local -= 1  # first chunk segment IS group 0
        n_local = int(local[-1]) + 1

        # per-group key tuples (carried first, then each segment start)
        key_rows: list[tuple] = []
        if carried:
            key_rows.append(self._open_key)
        for i in np.flatnonzero(new_seg):
            key_rows.append(
                tuple(
                    (True, None) if nl[i] else (False, _as_key_val(d[i]))
                    for d, nl in parts
                )
            )
        assert len(key_rows) == n_local, (len(key_rows), n_local)

        self._update_batch(Chunk(chunk.columns, logical), local, n_local)

        done = r.is_drained
        if done:
            self._done = True
            self._open_key = None
            out = self._emit(n_local, key_rows)
            self._rebase_states(None)
            return BatchExecuteResult(out, True)
        # hold back the trailing group, emit the rest
        emit_n = n_local - 1
        out = self._emit(emit_n, key_rows[:emit_n])
        self._open_key = key_rows[-1]
        self._rebase_states(n_local - 1)
        return BatchExecuteResult(out, False)


# ---------------------------------------------------------------------------
# TopN / Limit
# ---------------------------------------------------------------------------

class BatchTopNExecutor(BatchExecutor):
    """Bounded order-by (top_n_executor.rs:21): accumulate, prune to the best
    ``limit`` rows whenever the buffer doubles, final sort at drain."""

    def __init__(self, child: BatchExecutor, order_by: list[tuple[Expr, bool]], limit: int):
        self.child = child
        self._schema = child.schema()
        self.order_by = [(compile_expr(e, self._schema), desc) for e, desc in order_by]
        self.limit = limit
        self._done = False

    def schema(self):
        return self._schema

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self._done:
            return BatchExecuteResult(Chunk.full([]), True)
        key_fn = cmp_to_key(_row_cmp)
        # entries hold materialized row values so pruning releases the source
        # chunks — memory stays O(limit), not O(rows scanned)
        buf: list[tuple] = []  # (sort_key, seq, row_values)
        seq = 0
        drained = False
        enum_dicts: dict[int, np.ndarray] = {}
        while not drained:
            r = self.child.next_batch(scan_rows)
            drained = r.is_drained
            chunk = r.chunk
            if not chunk.num_rows:
                continue
            for ci, c in enumerate(chunk.columns):
                # ENUM/SET codes are only meaningful with their name table —
                # carry it through the row rebuild below
                if c.eval_type in (EvalType.ENUM, EvalType.SET) and c.dictionary is not None:
                    enum_dicts.setdefault(ci, c.dictionary)
            n = len(chunk.columns[0])
            needed = set()
            for rpn, _ in self.order_by:
                needed |= rpn.referenced_columns()
            cols = cols_for_eval(chunk.columns, needed)
            keys = []
            for rpn, desc in self.order_by:
                data, nulls = eval_rpn(rpn, cols, n)
                keys.append((np.asarray(data), np.asarray(nulls), desc))
            for row in chunk.logical_rows:
                row = int(row)
                values = tuple(
                    None if c.nulls[row] else _as_py(c, row) for c in chunk.columns
                )
                buf.append((_sort_key(keys, row), seq, values))
                seq += 1
            if len(buf) >= max(2 * self.limit, 4096):
                buf.sort(key=lambda it: (key_fn(it[0]), it[1]))
                del buf[self.limit :]
        self._done = True
        buf.sort(key=lambda it: (key_fn(it[0]), it[1]))
        del buf[self.limit :]
        out_cols: list[Column] = []
        for col_idx, (et, frac) in enumerate(self._schema):
            vals = [values[col_idx] for _, _, values in buf]
            col = Column.from_values(et, vals, frac)
            if col_idx in enum_dicts:
                col.dictionary = enum_dicts[col_idx]
            out_cols.append(col)
        return BatchExecuteResult(Chunk.full(out_cols), True)


def _coded_group_parts(group_rpns, columns, rows: np.ndarray):
    """If every group expr is a bare ref to a dictionary-encoded column (and
    the product capacity stays small), return [(codes, nulls, dictionary)]."""
    parts = []
    cap = 1
    for g in group_rpns:
        if len(g.nodes) != 1 or g.nodes[0].kind != "col":
            return None
        c = columns[g.nodes[0].index]
        if not c.is_dict_encoded:
            return None
        if c.eval_type in (EvalType.ENUM, EvalType.SET):
            # their dictionary is a name table, not a code table: ENUM codes
            # ARE the group value (generic int path), SET masks aren't codes
            return None
        cap *= len(c.dictionary) + 1
        if cap > (1 << 20):
            return None
        parts.append((np.asarray(c.data)[rows], np.asarray(c.nulls)[rows], c.dictionary))
    return parts or None


def _as_key_val(v):
    """Hashable python value for a group-key cell (numpy scalar or bytes)."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, np.generic):
        return v.item()
    return v


def _as_py(c: Column, row: int):
    v = c.data[row]
    if c.eval_type in (EvalType.BYTES, EvalType.JSON):
        if c.dictionary is not None:
            return bytes(c.dictionary[v])
        return bytes(v)
    if c.eval_type == EvalType.REAL:
        return float(v)
    return int(v)


def _sort_key(keys, row: int) -> tuple:
    parts = []
    for data, nulls, desc in keys:
        null = bool(nulls[row])
        v = None if null else (bytes(data[row]) if data.dtype == object else data[row].item())
        parts.append((null, v, desc))
    return tuple(parts)


def _row_cmp(a: tuple, b: tuple) -> int:
    """MySQL ORDER BY: NULLs first ascending, last descending."""
    for (n1, v1, desc), (n2, v2, _) in zip(a, b):
        if n1 or n2:
            if n1 == n2:
                continue
            r = -1 if n1 else 1
        elif v1 == v2:
            continue
        else:
            r = -1 if v1 < v2 else 1
        return -r if desc else r
    return 0


class BatchLimitExecutor(BatchExecutor):
    """Pass through the first N logical rows (limit_executor.rs:11)."""

    def __init__(self, child: BatchExecutor, limit: int):
        self.child = child
        self.remaining = limit

    def schema(self):
        return self.child.schema()

    def next_batch(self, scan_rows: int) -> BatchExecuteResult:
        if self.remaining <= 0:
            return BatchExecuteResult(Chunk.full([]), True)
        r = self.child.next_batch(scan_rows)
        chunk = r.chunk
        if chunk.num_rows >= self.remaining:
            logical = chunk.logical_rows[: self.remaining]
            self.remaining = 0
            return BatchExecuteResult(Chunk(chunk.columns, logical), True)
        self.remaining -= chunk.num_rows
        return r
