"""Table / index key-value codec.

Re-expression of ``tidb_query_datatype/src/codec/table.rs:22-29``:

* record key:  ``t{table_id:i64}_r{handle:i64}``   (both memcomparable i64)
* index key:   ``t{table_id:i64}_i{index_id:i64}{datum values for_key}``
* record value: datum-v1 row (col_id, value) pairs — see ``datum.py``

Plus the columnar **batch decoder** that turns a block of scanned MVCC rows
into ``Column`` vectors.  When every row in the block shares one fixed-width
layout (the overwhelmingly common case for numeric schemas — and detectable in
O(1) per row), decode is a numpy reshape + per-column slice; otherwise a
per-row datum walk is the fallback.  This is the host side of the host→TPU
pipeline, so it must not be a Python-per-row loop on the hot path.
"""

from __future__ import annotations

import numpy as np

from ..util import codec
from . import datatypes
from . import datum as datum_mod
from . import rowv2
from .datatypes import Column, ColumnInfo, EvalType

TABLE_PREFIX = b"t"
RECORD_PREFIX_SEP = b"_r"
INDEX_PREFIX_SEP = b"_i"


def record_key(table_id: int, handle: int) -> bytes:
    return TABLE_PREFIX + codec.encode_i64(table_id) + RECORD_PREFIX_SEP + codec.encode_i64(handle)


def record_range(table_id: int) -> tuple[bytes, bytes]:
    """[start, end) raw-key range covering all records of a table."""
    prefix = TABLE_PREFIX + codec.encode_i64(table_id) + RECORD_PREFIX_SEP
    return prefix, prefix[:-1] + bytes([prefix[-1] + 1])


def decode_record_key(key: bytes) -> tuple[int, int]:
    if len(key) != 19 or key[:1] != TABLE_PREFIX or key[9:11] != RECORD_PREFIX_SEP:
        raise ValueError(f"not a record key: {key!r}")
    return codec.decode_i64(key, 1), codec.decode_i64(key, 11)


def decode_record_handles(keys: list[bytes]) -> np.ndarray:
    """Batch handle decode: one reshape + byte-slice for the whole block."""
    n = len(keys)
    if n == 0:
        return np.empty(0, dtype=np.int64)
    lens = np.fromiter(map(len, keys), dtype=np.int64, count=n)
    if lens.min() != 19 or lens.max() != 19:
        # not uniformly record keys; per-key decode surfaces the bad one
        return np.array([decode_record_key(k)[1] for k in keys], dtype=np.int64)
    arr = np.frombuffer(b"".join(keys), dtype=np.uint8).reshape(n, 19)
    return codec.decode_i64_batch(arr[:, 11:19])


def index_key(table_id: int, index_id: int, values: list[tuple[int, object]]) -> bytes:
    out = bytearray(TABLE_PREFIX + codec.encode_i64(table_id) + INDEX_PREFIX_SEP + codec.encode_i64(index_id))
    for flag, value in values:
        datum_mod.encode_datum(out, flag, value, for_key=True)
    return bytes(out)


def index_range(table_id: int, index_id: int) -> tuple[bytes, bytes]:
    prefix = TABLE_PREFIX + codec.encode_i64(table_id) + INDEX_PREFIX_SEP + codec.encode_i64(index_id)
    return prefix, prefix[:-1] + bytes([prefix[-1] + 1])


def encode_row(columns: list[ColumnInfo], values: list) -> bytes:
    """Encode one row's non-handle columns as the record value."""
    out = bytearray()
    for info, v in zip(columns, values):
        datum_mod.encode_datum(out, datum_mod.INT_FLAG, info.col_id)
        if v is None:
            datum_mod.encode_datum(out, datum_mod.NIL_FLAG, None)
            continue
        et = info.ftype.eval_type
        if et == EvalType.INT:
            # fixed-width (for_key) int encoding: row blocks with stable
            # schemas become one reshape + vectorized byte-slice decode
            flag = datum_mod.UINT_FLAG if info.ftype.is_unsigned else datum_mod.INT_FLAG
            datum_mod.encode_datum(out, flag, v, for_key=True)
        elif et == EvalType.REAL:
            datum_mod.encode_datum(out, datum_mod.FLOAT_FLAG, v)
        elif et == EvalType.DECIMAL:
            datum_mod.encode_datum(out, datum_mod.DECIMAL_FLAG, (v, info.ftype.decimal))
        elif et == EvalType.BYTES:
            datum_mod.encode_datum(out, datum_mod.BYTES_FLAG, v)
        elif et == EvalType.JSON:
            datum_mod.encode_datum(out, datum_mod.JSON_FLAG, v)
        elif et in (EvalType.DATETIME, EvalType.DURATION):
            datum_mod.encode_datum(out, datum_mod.DURATION_FLAG, v)
        elif et in (EvalType.ENUM, EvalType.SET):
            # stored form is the index / bitmask (row::v2 stores the same)
            datum_mod.encode_datum(out, datum_mod.UINT_FLAG, int(v))
        else:
            raise ValueError(f"unsupported {et}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Batch row→column decode
# ---------------------------------------------------------------------------

class RowBatchDecoder:
    """Decode N record (handle, row_value) pairs into Columns for a schema.

    Column resolution per ``BatchTableScanExecutor`` (table_scan_executor.rs):
    a column marked ``is_pk_handle`` is filled from the key's handle; others
    come from the row value by col_id; missing col_id ⇒ default value / NULL.
    """

    def __init__(self, schema: list[ColumnInfo]):
        self.schema = schema
        self.handle_idx = [i for i, c in enumerate(schema) if c.is_pk_handle]
        # per-column cached dictionary (col_id → sorted uint64 keys + object
        # values): lets later blocks dictionary-encode with one searchsorted
        # instead of a fresh np.unique sort
        self._dict_cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def decode(self, handles: np.ndarray, row_values: list[bytes]) -> list[Column]:
        n = len(row_values)
        if row_values and all(rowv2.is_v2_row(rv) for rv in row_values):
            cols = rowv2.decode_rows_v2(self.schema, row_values)
        elif row_values and any(rowv2.is_v2_row(rv) for rv in row_values):
            cols = self._mixed_decode(row_values)
        else:
            fast = self._try_fast_decode(row_values)
            cols = fast if fast is not None else self._slow_decode(row_values)
        # fill handle columns
        for i in self.handle_idx:
            cols[i] = Column(EvalType.INT, handles.astype(np.int64), np.zeros(n, dtype=bool))
        return cols

    # -- fast path: single fixed layout across the block -------------------

    def _try_fast_decode(self, row_values: list[bytes]) -> list[Column] | None:
        if not row_values:
            return None
        first = row_values[0]
        nbytes = len(first)
        layout = self._parse_layout(first)
        if layout is None:
            return None
        for rv in row_values:
            if len(rv) != nbytes:
                return None
        buf = np.frombuffer(b"".join(row_values), dtype=np.uint8).reshape(len(row_values), nbytes)
        # verify every row matches the layout's fixed flag/colid bytes
        for off in layout["const_offsets"]:
            if not (buf[:, off] == first[off]).all():
                return None
        n = len(row_values)
        out: list[Column] = []
        for info in self.schema:
            if info.is_pk_handle:
                out.append(Column(EvalType.INT, np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool)))
                continue
            ent = layout["cols"].get(info.col_id)
            et = info.ftype.eval_type
            if ent is None:
                out.append(_default_column(info, n))
                continue
            kind, off = ent
            if kind == "i64":
                data = codec.decode_i64_batch(buf[:, off : off + 8])
                out.append(Column(et, data, np.zeros(n, dtype=bool), info.ftype.decimal))
            elif kind == "u64":
                data = codec.decode_u64_batch(buf[:, off : off + 8]).view(np.int64)
                out.append(Column(et, data, np.zeros(n, dtype=bool), info.ftype.decimal))
            elif kind == "f64":
                data = codec.decode_f64_batch(buf[:, off : off + 8])
                out.append(Column(et, data, np.zeros(n, dtype=bool)))
            elif isinstance(kind, tuple) and kind[0] == "bytes":
                blen = kind[1]
                codes, dictionary = self._dict_encode(info.col_id, buf, off, blen, n)
                out.append(Column(et, codes, np.zeros(n, dtype=bool), 0, dictionary))
            else:
                raise AssertionError(kind)
        return out

    def _parse_layout(self, row: bytes) -> dict | None:
        """Walk one row; return fixed offsets if every datum is fixed-width.

        Fixed-width means: INT/UINT/FLOAT/DURATION flags (8-byte payloads) and
        single-byte varint col-ids.  DECIMAL (1+varint) and BYTES are variable
        ⇒ fall back.  NULLs make a column's presence row-dependent ⇒ fall back.
        """
        cols: dict[int, tuple[str, int]] = {}
        const_offsets: list[int] = []
        off = 0
        while off < len(row):
            # col id datum: flag VARINT_FLAG + varint
            if row[off] != datum_mod.VARINT_FLAG:
                return None
            const_offsets.append(off)
            try:
                cid, noff = codec.decode_var_i64(row, off + 1)
            except ValueError:
                return None
            for o in range(off + 1, noff):
                const_offsets.append(o)
            off = noff
            if off >= len(row):
                return None
            flag = row[off]
            const_offsets.append(off)
            if flag == datum_mod.INT_FLAG:
                cols[cid] = ("i64", off + 1)
                off += 9
            elif flag == datum_mod.UINT_FLAG:
                cols[cid] = ("u64", off + 1)
                off += 9
            elif flag == datum_mod.FLOAT_FLAG:
                cols[cid] = ("f64", off + 1)
                off += 9
            elif flag == datum_mod.DURATION_FLAG:
                cols[cid] = ("i64", off + 1)
                off += 9
            elif flag == datum_mod.DECIMAL_FLAG:
                # frac byte is part of the constant layout; payload is fixed i64
                const_offsets.append(off + 1)
                cols[cid] = ("i64", off + 2)
                off += 10
            elif flag == datum_mod.COMPACT_BYTES_FLAG:
                # fixed-length bytes value: varint length must be 1 byte and
                # identical across the block (checked via const_offsets)
                try:
                    blen, noff2 = codec.decode_var_i64(row, off + 1)
                except ValueError:
                    return None
                if blen < 0 or noff2 != off + 2 or off + 2 + blen > len(row):
                    return None
                const_offsets.append(off + 1)
                cols[cid] = (("bytes", blen), off + 2)
                off += 2 + blen
            else:
                return None
        return {"cols": cols, "const_offsets": const_offsets}

    def _dict_encode(self, col_id: int, buf: np.ndarray, off: int, blen: int, n: int):
        """Dictionary-encode a fixed-width bytes column slice.

        Values ≤8 bytes pack into uint64 keys; a per-column cached dictionary
        turns steady-state blocks into one searchsorted (O(n log D)).  Wider
        values use the void-view np.unique path.
        """
        if blen == 0:
            return np.zeros(n, dtype=np.int64), np.array([b""], dtype=object)
        raw = np.ascontiguousarray(buf[:, off : off + blen])
        if blen <= 8:
            padded = np.zeros((n, 8), dtype=np.uint8)
            padded[:, :blen] = raw
            # big-endian packing: uint64 numeric order == lexicographic
            # bytes order, so the dictionary comes out SORTED — rank joins
            # and code-space range rewrites key on that
            keys = padded.view(np.uint64).reshape(n).byteswap()
            cached = self._dict_cache.get(col_id)
            if cached is not None:
                sorted_keys, values = cached
                pos = np.searchsorted(sorted_keys, keys)
                pos_c = np.minimum(pos, len(sorted_keys) - 1)
                if (sorted_keys[pos_c] == keys).all():
                    return pos_c.astype(np.int64), values
            uk, codes = np.unique(keys, return_inverse=True)
            values = np.empty(len(uk), dtype=object)
            kb = uk.byteswap().view(np.uint8).reshape(len(uk), 8)
            for j in range(len(uk)):
                values[j] = kb[j, :blen].tobytes()
            self._dict_cache[col_id] = (uk, values)
            return codes.astype(np.int64), values
        view = raw.view([("", np.uint8)] * blen).reshape(n)
        uniq, codes = np.unique(view, return_inverse=True)
        dictionary = np.empty(len(uniq), dtype=object)
        ub = uniq.view(np.uint8).reshape(len(uniq), blen)
        for j in range(len(uniq)):
            dictionary[j] = ub[j].tobytes()
        return codes.astype(np.int64), dictionary

    def _mixed_decode(self, row_values: list[bytes]) -> list[Column]:
        """A block mixing v1 and v2 rows (mid-migration): decode each format
        batch-wise, then interleave back into row order."""
        v2_idx = [i for i, rv in enumerate(row_values) if rowv2.is_v2_row(rv)]
        v1_idx = [i for i, rv in enumerate(row_values) if not rowv2.is_v2_row(rv)]
        v2_cols = rowv2.decode_rows_v2(self.schema, [row_values[i] for i in v2_idx])
        v1_cols = self._slow_decode([row_values[i] for i in v1_idx])
        n = len(row_values)
        order = np.empty(n, dtype=np.int64)
        order[np.array(v2_idx, dtype=np.int64)] = np.arange(len(v2_idx))
        order[np.array(v1_idx, dtype=np.int64)] = len(v2_idx) + np.arange(len(v1_idx))
        out = []
        for c2, c1 in zip(v2_cols, v1_cols):
            out.append(Column.concat([c2, c1]).take(order))
        return out

    # -- slow path: per-row datum walk -------------------------------------

    def _slow_decode(self, row_values: list[bytes]) -> list[Column]:
        n = len(row_values)
        rows = [datum_mod.decode_row_value(rv) for rv in row_values]
        out: list[Column] = []
        for info in self.schema:
            if info.is_pk_handle:
                out.append(Column(EvalType.INT, np.zeros(n, dtype=np.int64), np.zeros(n, dtype=bool)))
                continue
            et = info.ftype.eval_type
            values = []
            for r in rows:
                d = r.get(info.col_id)
                if d is None:
                    # column absent from the row (schema evolution) ⇒ default
                    values.append(info.default_value)
                elif d.flag == datum_mod.NIL_FLAG:
                    # explicitly stored NULL stays NULL (row v2 agrees)
                    values.append(None)
                elif d.flag == datum_mod.DECIMAL_FLAG:
                    values.append(d.value[0])
                else:
                    values.append(d.value)
            out.append(_typed_column(info, values))
        return out


_typed_column = datatypes.typed_column


def _default_column(info: ColumnInfo, n: int) -> Column:
    if info.default_value is not None:
        return _typed_column(info, [info.default_value] * n)
    return _typed_column(info, [None] * n)
