"""Scalar-function catalog extension: the remaining reference families.

Closes the gap toward the reference dispatch table
(``tidb_query_expr/src/lib.rs:300``, ~371 arms): conversion/cast breadth
(impl_cast.rs), CONVERT_TZ and the remaining time arithmetic
(impl_time.rs), string breadth (impl_string.rs), control (impl_control.rs),
math conv/log/round variants (impl_math.rs), compress/uncompress
(impl_encryption.rs), JSON datetime/search/merge-patch (impl_json.rs), and
miscellaneous IPv6/network helpers (impl_miscellaneous.rs).

Registered through the same ``KERNELS`` table — one backend-parameterized
definition per function, CPU/device semantics shared — imported from
kernels.py at the end of its own registrations.
"""

from __future__ import annotations

import datetime as _dt
import ipaddress as _ip
import struct as _struct
import zlib as _zlib

import numpy as _np

from .kernels import (
    KERNELS,
    _bytes_op,
    _int_bytes_op,
    _json_op,
    _reg,
    _reg_nullable_int,
)
from . import mysql_time as _mt


# -- conversion / cast family (impl_cast.rs) --------------------------------
#
# decimal values are scaled int64 (frac bookkeeping in rpn.py); these kernels
# implement the value transform, rpn.py routes frac metadata.

@_reg("cast_int_decimal", 1, "decimal")
def _cast_int_decimal(xp, a):
    ad, an = a
    return ad.astype("int64"), an


@_reg("cast_decimal_int", 1, "int")
def _cast_decimal_int(xp, a):
    # rpn.py divides by the scale before this kernel sees the value when the
    # operand's frac > 0; here we only materialize the int
    ad, an = a
    return ad.astype("int64"), an


@_reg("cast_real_decimal", 1, "decimal")
def _cast_real_decimal(xp, a):
    ad, an = a
    return xp.round(ad).astype("int64"), an


def _parse_num_prefix(s_: bytes) -> float:
    """MySQL string->number: longest numeric prefix, else 0."""
    t = s_.decode("utf-8", "replace").strip()
    n = len(t)
    for end in range(n, 0, -1):
        try:
            return float(t[:end])
        except ValueError:
            continue
    return 0.0


def _cast_string_real_impl(xp, a):
    ad, an = a
    out = _np.fromiter(
        (_parse_num_prefix(v) for v in ad), dtype=_np.float64, count=len(ad)
    )
    return out, _np.asarray(an)


KERNELS["cast_string_real"] = (1, "real", _cast_string_real_impl)


def _parse_int_prefix(s_: bytes) -> int:
    """Integer strings parse EXACTLY (no float round-trip: 2^53+ literals
    must not lose precision); non-integer numerics truncate via float."""
    t = s_.decode("utf-8", "replace").strip()
    import re as _re

    m = _re.match(r"[+-]?\d+", t)
    if m is not None and (len(m.group(0)) == len(t) or not t[len(m.group(0))] in ".eE"):
        v = int(m.group(0))
        return max(min(v, 2**63 - 1), -(2**63))  # MySQL clamps at int64 range
    return int(_parse_num_prefix(s_))


def _cast_string_int_impl(xp, a):
    ad, an = a
    out = _np.fromiter(
        (_parse_int_prefix(v) for v in ad), dtype=_np.int64, count=len(ad)
    )
    return out, _np.asarray(an)


KERNELS["cast_string_int"] = (1, "int", _cast_string_int_impl)

_bytes_op("cast_int_string", 1, "bytes")(lambda n: b"%d" % int(n))


def _fmt_real(x: float) -> bytes:
    if x == int(x) and abs(x) < 1e15:
        return b"%d" % int(x)
    return repr(float(x)).encode()


_bytes_op("cast_real_string", 1, "bytes")(_fmt_real)
_bytes_op("cast_datetime_string", 1, "bytes")(
    lambda p: _mt.format_datetime(int(p)).encode()
)
_bytes_op("cast_duration_string", 1, "bytes")(
    lambda n: _mt.format_duration(int(n)).encode()
)


def _cast_string_datetime(s_: bytes):
    try:
        return _mt.parse_datetime(s_.decode("utf-8", "replace"))
    except ValueError:
        return None


_reg_nullable_int("cast_string_datetime", 1, _cast_string_datetime)


def _cast_string_duration(s_: bytes):
    try:
        return _mt.parse_duration(s_.decode("utf-8", "replace"))
    except ValueError:
        return None


_reg_nullable_int("cast_string_duration", 1, _cast_string_duration)


# -- control (impl_control.rs) ----------------------------------------------

@_reg("null_eq", 2, "int")
def _null_eq(xp, a, b):
    """MySQL <=> : NULL-safe equality, never NULL itself."""
    (ad, an), (bd, bn) = a, b
    eq = (ad == bd) & ~an & ~bn
    both_null = an & bn
    data = (eq | both_null).astype("int64")
    return data, xp.zeros(data.shape, dtype=bool)


@_reg("nullif", 2, "same")
def _nullif(xp, a, b):
    """NULLIF(a, b): NULL when a == b, else a."""
    (ad, an), (bd, bn) = a, b
    eq = (ad == bd) & ~an & ~bn
    return ad, an | eq


@_reg("interval_int", -1, "int")
def _interval_int(xp, *args):
    """INTERVAL(N, N1, N2, ...): index of the last Ni <= N (impl_compare).
    NULL N -> -1 (MySQL quirk); NULL thresholds count as +inf."""
    (nd, nn) = args[0]
    big = xp.int64(2**62)
    count = xp.zeros(nd.shape, dtype="int64")
    for td, tn in args[1:]:
        t = xp.where(tn, big, td.astype("int64"))
        count = count + (t <= nd).astype("int64")
    data = xp.where(nn, xp.int64(-1), count)
    return data, xp.zeros(nd.shape, dtype=bool)


# -- math (impl_math.rs) ----------------------------------------------------

@_reg("log_base", 2, "real")
def _log_base(xp, a, b):
    """LOG(b, x): NULL for x <= 0 or b <= 0 or b == 1."""
    (bd, bn), (ad, an) = a, b
    base = bd.astype("float64")
    x = ad.astype("float64")
    bad = (x <= 0) | (base <= 0) | (base == 1.0)
    safe_x = xp.where(bad, 1.0, x)
    safe_b = xp.where(bad, 2.0, base)
    return xp.log(safe_x) / xp.log(safe_b), an | bn | bad


def _conv(s_: bytes, frm: int, to: int):
    frm, to = int(frm), int(to)
    if not (2 <= abs(frm) <= 36 and 2 <= abs(to) <= 36):
        return None
    t = s_.decode("utf-8", "replace").strip()
    neg = t.startswith("-")
    if neg:
        t = t[1:]
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"[: abs(frm)]
    val = 0
    for ch in t.lower():
        if ch not in digits:
            break
        val = val * abs(frm) + digits.index(ch)
    if neg:
        val = -val
    if val == 0:
        return b"0"
    if to < 0:
        v, sign = (abs(val), "-" if val < 0 else "")
    else:
        v, sign = (val & (2**64 - 1), "")
    out = ""
    alldig = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    while v:
        out = alldig[v % abs(to)] + out
        v //= abs(to)
    return (sign + out).encode()


_bytes_op("conv", 3, "bytes")(_conv)
_int_bytes_op("bit_count", 1)(lambda n: bin(int(n) & (2**64 - 1)).count("1"))


@_reg("round_int_frac", 2, "int")
def _round_int_frac(xp, a, b):
    """ROUND(int, frac): negative frac rounds to powers of ten (half away
    from zero, like MySQL)."""
    (ad, an), (fd, fn) = a, b
    frac = xp.clip(-fd.astype("int64"), 0, 18)
    p = xp.power(xp.int64(10), frac)
    half = p // 2
    sign = xp.where(ad < 0, xp.int64(-1), xp.int64(1))
    data = xp.where(frac > 0, ((xp.abs(ad) + half) // p) * p * sign, ad)
    return data.astype("int64"), an | fn


@_reg("truncate_int_frac", 2, "int")
def _truncate_int_frac(xp, a, b):
    (ad, an), (fd, fn) = a, b
    frac = xp.clip(-fd.astype("int64"), 0, 18)
    p = xp.power(xp.int64(10), frac)
    data = xp.where(frac > 0, (ad // p) * p + xp.where((ad % p != 0) & (ad < 0), p, 0), ad)
    return data.astype("int64"), an | fn


# -- string breadth (impl_string.rs) ----------------------------------------

def _insert_str(s_: bytes, pos: int, ln: int, new: bytes):
    pos, ln = int(pos), int(ln)
    if pos < 1 or pos > len(s_):
        return s_
    if ln < 0 or pos + ln - 1 > len(s_):
        ln = len(s_) - pos + 1
    return s_[: pos - 1] + new + s_[pos - 1 + ln :]


_bytes_op("insert_str", 4, "bytes")(_insert_str)
_int_bytes_op("ord", 1)(
    lambda s_: 0 if not s_ else int.from_bytes(
        s_[: max(1, _utf8_len(s_[0]))], "big"
    )
)


def _utf8_len(lead: int) -> int:
    if lead < 0x80:
        return 1
    if lead >> 5 == 0b110:
        return 2
    if lead >> 4 == 0b1110:
        return 3
    if lead >> 3 == 0b11110:
        return 4
    return 1


def _quote(s_: bytes) -> bytes:
    out = bytearray(b"'")
    for b in s_:
        if b in (0x27, 0x5C):  # ' and backslash
            out += b"\\" + bytes([b])
        elif b == 0:
            out += b"\\0"
        elif b == 0x1A:
            out += b"\\Z"
        else:
            out.append(b)
    out += b"'"
    return bytes(out)


_bytes_op("quote", 1, "bytes")(_quote)
_bytes_op("soundex", 1, "bytes")(lambda s_: _soundex(s_))


def _soundex(s_: bytes) -> bytes:
    codes = {
        **dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
        **dict.fromkeys("DT", "3"), "L": "4", **dict.fromkeys("MN", "5"), "R": "6",
    }
    t = "".join(c for c in s_.decode("utf-8", "replace").upper() if c.isalpha())
    if not t:
        return b""
    out = t[0]
    last = codes.get(t[0], "")
    for ch in t[1:]:
        c = codes.get(ch, "")
        if c and c != last:
            out += c
        last = c
    return (out + "000")[: max(4, len(out))].encode()


def _make_set(bits: int, *strs):
    out = [s for i, s in enumerate(strs) if s is not None and (int(bits) >> i) & 1]
    return b",".join(out)


def _make_set_wrapped(xp, *args):
    (bd, bn) = args[0]
    n = len(bd)
    out = _np.empty(n, dtype=object)
    rnull = _np.asarray(bn).copy()
    for i in range(n):
        if rnull[i]:
            out[i] = b""
            continue
        strs = [
            None if args[j][1][i] else args[j][0][i] for j in range(1, len(args))
        ]
        out[i] = _make_set(bd[i], *strs)
    return out, rnull


KERNELS["make_set"] = (-1, "bytes", _make_set_wrapped)


def _export_set(bits, on, off, sep, count):
    count = min(max(int(count), 0), 64)
    return sep.join((on if (int(bits) >> i) & 1 else off) for i in range(count))


_bytes_op("export_set5", 5, "bytes")(_export_set)
_bytes_op("export_set4", 4, "bytes")(lambda b, on, off, sep: _export_set(b, on, off, sep, 64))
_bytes_op("export_set3", 3, "bytes")(lambda b, on, off: _export_set(b, on, off, b",", 64))


def _char_fn(*codes):
    out = bytearray()
    for c in codes:
        if c is None:
            continue
        v = int(c) & 0xFFFFFFFF
        if v == 0:
            out.append(0)
            continue
        chunk = bytearray()
        while v:
            chunk.insert(0, v & 0xFF)
            v >>= 8
        out += chunk
    return bytes(out)


def _char_wrapped(xp, *args):
    n = len(args[0][0])
    out = _np.empty(n, dtype=object)
    rnull = _np.zeros(n, dtype=bool)  # CHAR() skips NULL args, never NULL itself
    for i in range(n):
        codes = [None if nl[i] else d[i] for d, nl in args]
        out[i] = _char_fn(*codes)
    return out, rnull


KERNELS["char_fn"] = (-1, "bytes", _char_wrapped)


def _format_number(x: float, d: int) -> bytes:
    d = min(max(int(d), 0), 30)
    s = f"{float(x):,.{d}f}"
    return s.encode()


_bytes_op("format", 2, "bytes")(_format_number)


def _locate3(sub: bytes, s_: bytes, pos: int):
    if int(pos) < 1:
        return 0  # MySQL LOCATE with pos < 1
    idx = s_.find(sub, int(pos) - 1)
    return idx + 1


_int_bytes_op("locate3", 3)(_locate3)
_bytes_op("mid", 3, "bytes")(
    lambda s_, pos, ln: _mid(s_, int(pos), int(ln))
)


def _mid(s_: bytes, pos: int, ln: int) -> bytes:
    if pos < 0:
        pos = len(s_) + pos + 1
    if pos < 1 or ln <= 0:
        return b""
    return s_[pos - 1 : pos - 1 + ln]


_bytes_op("lcase", 1, "bytes")(lambda s_: s_.decode("utf-8", "replace").lower().encode())
_bytes_op("ucase", 1, "bytes")(lambda s_: s_.decode("utf-8", "replace").upper().encode())


def _concat_ws(sep, *parts):
    return sep.join(p for p in parts if p is not None)


def _concat_ws_wrapped(xp, *args):
    (sd, sn) = args[0]
    n = len(sd)
    out = _np.empty(n, dtype=object)
    rnull = _np.asarray(sn).copy()  # NULL separator -> NULL; NULL parts skipped
    for i in range(n):
        if rnull[i]:
            out[i] = b""
            continue
        parts = [None if nl[i] else d[i] for d, nl in args[1:]]
        out[i] = _concat_ws(sd[i], *parts)
    return out, rnull


KERNELS["concat_ws"] = (-1, "bytes", _concat_ws_wrapped)


# -- encryption/compression (impl_encryption.rs) ----------------------------

def _compress(s_: bytes) -> bytes:
    if not s_:
        return b""
    return _struct.pack("<I", len(s_)) + _zlib.compress(s_)


def _uncompress(s_: bytes):
    if not s_:
        return b""
    if len(s_) < 4:
        return None
    (ln,) = _struct.unpack("<I", s_[:4])
    try:
        out = _zlib.decompress(s_[4:])
    except _zlib.error:
        return None
    return out if len(out) == ln else None


_bytes_op("compress", 1, "bytes")(_compress)
_bytes_op("uncompress", 1, "bytes")(_uncompress)


def _uncompressed_length(s_: bytes) -> int:
    if len(s_) < 4:
        return 0
    return _struct.unpack("<I", s_[:4])[0]


_int_bytes_op("uncompressed_length", 1)(_uncompressed_length)


# -- time breadth (impl_time.rs) --------------------------------------------

def _safe_dt(fn):
    def wrapped(*args):
        try:
            return fn(*args)
        except (ValueError, OverflowError):
            return None

    return wrapped


_reg_nullable_int(
    "makedate", 2,
    _safe_dt(lambda y, d: None if int(d) <= 0 else _mt.pack_datetime(
        *((_dt.date(int(y) if int(y) >= 100 else int(y) + (2000 if int(y) < 70 else 1900), 1, 1)
           + _dt.timedelta(days=int(d) - 1)).timetuple()[:3]), 0, 0, 0, 0
    )),
)
_reg_nullable_int(
    "maketime", 3,
    _safe_dt(lambda h, m, s: None if not (0 <= int(m) < 60 and 0 <= s < 60) else
             _mt.duration_nanos(abs(int(h)), int(m), int(s), neg=int(h) < 0)),
)
_reg_nullable_int("period_add", 2, _safe_dt(lambda p, n: _period_from_months(_period_to_months(int(p)) + int(n))))
_reg_nullable_int("period_diff", 2, _safe_dt(lambda a, b: _period_to_months(int(a)) - _period_to_months(int(b))))


def _period_to_months(p: int) -> int:
    if p == 0:
        return 0
    y, m = divmod(p, 100)
    if y < 70:
        y += 2000
    elif y < 100:
        y += 1900
    return y * 12 + m - 1


def _period_from_months(n: int) -> int:
    y, m = divmod(n, 12)
    return y * 100 + m + 1


_reg_nullable_int("time_to_sec", 1, lambda nanos: abs(int(nanos)) // _mt.NANOS_PER_SEC * (1 if int(nanos) >= 0 else -1))
_reg_nullable_int("sec_to_time", 1, lambda s: int(s) * _mt.NANOS_PER_SEC)
_reg_nullable_int(
    "to_seconds", 1,
    # +365: MySQL day counting from year 0 (same convention as to_days)
    _safe_dt(lambda p: (_mt._as_date(p).toordinal() + 365) * 86400
             + _mt.unpack_datetime(int(p))[3] * 3600
             + _mt.unpack_datetime(int(p))[4] * 60
             + _mt.unpack_datetime(int(p))[5]),
)
_reg_nullable_int("day_of_month", 1, _safe_dt(lambda p: _mt.unpack_datetime(int(p))[2]))
_reg_nullable_int(
    "week_of_year", 1, _safe_dt(lambda p: _mt._as_date(p).isocalendar()[1])
)
def _yearweek0(p: int) -> int:
    """YEARWEEK mode 0 (MySQL default): Sunday-first weeks counted from the
    year's first Sunday; dates before it belong to the PREVIOUS year's last
    week (week never 0 in YEARWEEK — it rolls back)."""
    d = _mt._as_date(p)
    for y in (d.year, d.year - 1):
        jan1 = _dt.date(y, 1, 1)
        offset = (jan1.weekday() + 1) % 7  # days from Sunday to jan1
        wk = ((d - jan1).days + offset) // 7
        if wk > 0 or y < d.year:
            return y * 100 + wk
    raise ValueError(p)


_reg_nullable_int("year_week", 1, _safe_dt(_yearweek0))
_reg_nullable_int(
    "timestamp_diff_days", 2,
    _safe_dt(lambda a, b: (_mt._as_date(b) - _mt._as_date(a)).days),
)


def _tz_offset_minutes(tz: bytes):
    """'+HH:MM' / '-HH:MM' offsets; named zones unsupported -> None (the
    reference resolves named zones through the tz database; offset syntax
    covers the wire-compatible subset)."""
    t = tz.decode("utf-8", "replace").strip()
    if len(t) >= 6 and t[0] in "+-" and t[3] == ":":
        try:
            sign = -1 if t[0] == "-" else 1
            hh, mm = int(t[1:3]), int(t[4:6])
            if hh > 13 or mm > 59:
                return None
            return sign * (hh * 60 + mm)
        except ValueError:
            return None
    if t.upper() in ("UTC", "GMT"):
        return 0
    return None


def _convert_tz(packed, from_tz: bytes, to_tz: bytes):
    f = _tz_offset_minutes(from_tz)
    t = _tz_offset_minutes(to_tz)
    if f is None or t is None:
        return None
    return _mt.date_add(int(packed), t - f, "MINUTE")


def _convert_tz_wrapped(xp, a, b, c):
    (pd, pn), (fd, fn), (td, tn) = a, b, c
    n = len(pd)
    out = _np.zeros(n, dtype=_np.int64)
    rnull = _np.asarray(pn | fn | tn).copy()
    for i in range(n):
        if rnull[i]:
            continue
        r = _convert_tz(pd[i], fd[i], td[i])
        if r is None:
            rnull[i] = True
        else:
            out[i] = r
    return out, rnull


KERNELS["convert_tz"] = (3, "int", _convert_tz_wrapped)

_bytes_op("time_format", 2, "bytes")(
    lambda nanos, fmt: _time_format(int(nanos), fmt)
)


def _time_format(nanos: int, fmt: bytes):
    # durations format through a synthetic datetime (hours may exceed 23:
    # %H shows the full count, like MySQL TIME_FORMAT)
    neg = nanos < 0
    nanos = abs(nanos)
    secs, sub = divmod(nanos, _mt.NANOS_PER_SEC)
    hh, rem = divmod(secs, 3600)
    mm, ss = divmod(rem, 60)
    t = fmt.decode("utf-8", "replace")
    out = (
        t.replace("%H", f"{hh:02d}").replace("%k", str(hh))
        .replace("%i", f"{mm:02d}").replace("%s", f"{ss:02d}")
        .replace("%S", f"{ss:02d}").replace("%f", f"{sub // 1000:06d}")
        .replace("%p", "AM" if hh % 24 < 12 else "PM")
    )
    return (("-" if neg else "") + out).encode()


def _get_format(kind: bytes, loc: bytes):
    table = {
        (b"DATE", b"USA"): b"%m.%d.%Y", (b"DATE", b"JIS"): b"%Y-%m-%d",
        (b"DATE", b"ISO"): b"%Y-%m-%d", (b"DATE", b"EUR"): b"%d.%m.%Y",
        (b"DATE", b"INTERNAL"): b"%Y%m%d",
        (b"DATETIME", b"USA"): b"%Y-%m-%d %H.%i.%s",
        (b"DATETIME", b"JIS"): b"%Y-%m-%d %H:%i:%s",
        (b"DATETIME", b"ISO"): b"%Y-%m-%d %H:%i:%s",
        (b"DATETIME", b"EUR"): b"%Y-%m-%d %H.%i.%s",
        (b"DATETIME", b"INTERNAL"): b"%Y%m%d%H%i%s",
        (b"TIME", b"USA"): b"%h:%i:%s %p", (b"TIME", b"JIS"): b"%H:%i:%s",
        (b"TIME", b"ISO"): b"%H:%i:%s", (b"TIME", b"EUR"): b"%H.%i.%s",
        (b"TIME", b"INTERNAL"): b"%H%i%s",
    }
    return table.get((kind.upper(), loc.upper()))


_bytes_op("get_format", 2, "bytes")(_get_format)


# -- JSON breadth (impl_json.rs) --------------------------------------------
#
# JSON values travel as the binary codec bytes; json_value decodes them into
# plain python values (dict / list / str / int / JsonU64 / float / bool /
# None) — the same representation the existing json kernels use.

from . import json_value as _jv


def _jd(b: bytes):
    return _jv.json_decode(bytes(b))


def _json_merge_patch_impl(a: bytes, b: bytes):
    def patch(x, y):
        if not isinstance(y, dict):
            return y
        out = dict(x) if isinstance(x, dict) else {}
        for k, v in y.items():
            if v is None:
                out.pop(k, None)
            else:
                out[k] = patch(out.get(k), v)
        return out

    return _jv.json_encode(patch(_jd(a), _jd(b)))


_json_op("json_merge_patch", 2, "bytes")(_json_merge_patch_impl)


def _json_pretty_impl(a: bytes):
    import json as _json

    v = _jd(a)

    def plain(x):
        if isinstance(x, dict):
            return {k: plain(v2) for k, v2 in x.items()}
        if isinstance(x, list):
            return [plain(e) for e in x]
        return x

    return _json.dumps(plain(v), indent=2).encode()


_json_op("json_pretty", 1, "bytes")(_json_pretty_impl)
_json_op("json_storage_size", 1, "int")(lambda a: len(a))


def _like_match(pat: str, s: str) -> bool:
    import re

    rx = "^" + "".join(
        ".*" if c == "%" else "." if c == "_" else re.escape(c) for c in pat
    ) + "$"
    return re.match(rx, s, re.S) is not None


def _json_search_impl(doc: bytes, one_all: bytes, target: bytes):
    v = _jd(doc)
    one = one_all.lower() == b"one"
    pat = target.decode("utf-8", "replace")
    found: list[str] = []

    def walk(node, path) -> bool:
        if isinstance(node, str):
            if _like_match(pat, node):
                found.append(path or "$")
                return not one
        elif isinstance(node, list):
            for i, el in enumerate(node):
                if not walk(el, f"{path}[{i}]"):
                    return False
        elif isinstance(node, dict):
            for k, el in node.items():
                if not walk(el, f"{path}.{k}"):
                    return False
        return True

    walk(v, "$")
    if not found:
        return None
    return _jv.json_encode(found[0] if len(found) == 1 else found)


_json_op("json_search", 3, "bytes")(_json_search_impl)


def _json_member_of(target: bytes, arr: bytes) -> int:
    va, vt = _jd(arr), _jd(target)
    if isinstance(va, list):
        return int(any(_jv._json_eq(el, vt) for el in va))
    return int(_jv._json_eq(va, vt))


_json_op("json_member_of", 2, "int")(_json_member_of)


def _json_overlaps(a: bytes, b: bytes) -> int:
    va, vb = _jd(a), _jd(b)
    aa = va if isinstance(va, list) else [va]
    bb = vb if isinstance(vb, list) else [vb]
    return int(any(_jv._json_eq(x, y) for x in aa for y in bb))


_json_op("json_overlaps", 2, "int")(_json_overlaps)


def _json_array_append(doc: bytes, path: bytes, val: bytes):
    v = _jd(doc)
    target = _jv.extract(v, [path.decode()])
    if target is _jv._NO_MATCH:
        return _jv.json_encode(v)
    new = target + [_jd(val)] if isinstance(target, list) else [target, _jd(val)]
    return _jv.json_encode(_jv.modify(v, [(path.decode(), new)], "set"))


_json_op("json_array_append", 3, "bytes")(_json_array_append)


# cast JSON <-> datetime/duration (opaque time values inside JSON)

_bytes_op("cast_datetime_json", 1, "bytes")(
    lambda p: _jv.json_encode(_mt.format_datetime(int(p)))
)
_bytes_op("cast_duration_json", 1, "bytes")(
    lambda n: _jv.json_encode(_mt.format_duration(int(n)))
)


# -- miscellaneous (impl_miscellaneous.rs) ----------------------------------

def _is_ipv4(s_: bytes) -> int:
    try:
        _ip.IPv4Address(s_.decode())
        return 1
    except (ValueError, UnicodeDecodeError):
        return 0


def _is_ipv6(s_: bytes) -> int:
    try:
        _ip.IPv6Address(s_.decode())
        return 1
    except (ValueError, UnicodeDecodeError):
        return 0


_int_bytes_op("is_ipv4", 1)(_is_ipv4)
_int_bytes_op("is_ipv6", 1)(_is_ipv6)


def _inet6_aton(s_: bytes):
    try:
        return _ip.ip_address(s_.decode()).packed
    except (ValueError, UnicodeDecodeError):
        return None


_bytes_op("inet6_aton", 1, "bytes")(_inet6_aton)


def _inet6_ntoa(b: bytes):
    try:
        if len(b) == 4:
            return str(_ip.IPv4Address(b)).encode()
        if len(b) == 16:
            return str(_ip.IPv6Address(b)).encode()
    except ValueError:
        pass
    return None


_bytes_op("inet6_ntoa", 1, "bytes")(_inet6_ntoa)
_int_bytes_op("is_ipv4_compat", 1)(
    lambda b: int(len(b) == 16 and b[:12] == b"\x00" * 12 and b[12:] != b"\x00" * 4)
)
_int_bytes_op("is_ipv4_mapped", 1)(
    lambda b: int(len(b) == 16 and b[:10] == b"\x00" * 10 and b[10:12] == b"\xff\xff")
)


@_reg("any_value", 1, "same")
def _any_value(xp, a):
    return a


@_reg("is_not_null", 1, "int")
def _is_not_null(xp, a):
    ad, an = a
    return (~an).astype("int64"), xp.zeros(an.shape, dtype=bool)


# -- trim family breadth (impl_string.rs TRIM(remstr FROM str)) -------------

def _trim_ends(s_: bytes, rem: bytes, leading: bool, trailing: bool) -> bytes:
    if not rem:
        return s_
    if leading:
        while s_.startswith(rem):
            s_ = s_[len(rem):]
    if trailing:
        while s_.endswith(rem):
            s_ = s_[: -len(rem)]
    return s_


_bytes_op("trim2", 2, "bytes")(lambda s_, rem: _trim_ends(s_, rem, True, True))
_bytes_op("trim_leading", 2, "bytes")(lambda s_, rem: _trim_ends(s_, rem, True, False))
_bytes_op("trim_trailing", 2, "bytes")(lambda s_, rem: _trim_ends(s_, rem, False, True))
_int_bytes_op("position", 2)(lambda sub, s_: s_.find(sub) + 1)


# -- utf8 character-based variants (byte-based siblings exist) --------------

def _u(s_: bytes) -> str:
    return s_.decode("utf-8", "replace")


_bytes_op("left_utf8", 2, "bytes")(lambda s_, n: _u(s_)[: max(int(n), 0)].encode())
_bytes_op("right_utf8", 2, "bytes")(
    lambda s_, n: _u(s_)[-int(n):].encode() if int(n) > 0 else b""
)
_bytes_op("reverse_utf8", 1, "bytes")(lambda s_: _u(s_)[::-1].encode())


def _substr_utf8(s_: bytes, pos: int, ln: int | None = None) -> bytes:
    t = _u(s_)
    pos = int(pos)
    if pos < 0:
        pos = len(t) + pos + 1
    if pos < 1:
        return b""
    sub = t[pos - 1 :]
    if ln is not None:
        if int(ln) <= 0:
            return b""
        sub = sub[: int(ln)]
    return sub.encode()


_bytes_op("substr_utf8_2", 2, "bytes")(lambda s_, p: _substr_utf8(s_, p))
_bytes_op("substr_utf8_3", 3, "bytes")(lambda s_, p, ln: _substr_utf8(s_, p, ln))


# -- greatest/least string + real variants (impl_compare.rs) ----------------

def _extreme_bytes(name, pick):
    def fn(xp, *args):
        n = len(args[0][0])
        out = _np.empty(n, dtype=object)
        nulls = args[0][1]
        for _, nl in args[1:]:
            nulls = nulls | nl
        rnull = _np.asarray(nulls).copy()
        for i in range(n):
            out[i] = b"" if rnull[i] else pick(d[i] for d, _ in args)
        return out, rnull

    KERNELS[name] = (-1, "bytes", fn)


_extreme_bytes("greatest_string", max)
_extreme_bytes("least_string", min)


@_reg("greatest_real", -1, "real")
def _greatest_real(xp, *args):
    data = args[0][0].astype("float64")
    nulls = args[0][1]
    for d, nl in args[1:]:
        data = xp.maximum(data, d.astype("float64"))
        nulls = nulls | nl
    return data, nulls


@_reg("least_real", -1, "real")
def _least_real(xp, *args):
    data = args[0][0].astype("float64")
    nulls = args[0][1]
    for d, nl in args[1:]:
        data = xp.minimum(data, d.astype("float64"))
        nulls = nulls | nl
    return data, nulls


# -- duration / datetime arithmetic (impl_time.rs add/sub family) -----------

@_reg("add_duration", 2, "int")
def _add_duration(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad.astype("int64") + bd.astype("int64"), an | bn


@_reg("sub_duration", 2, "int")
def _sub_duration(xp, a, b):
    (ad, an), (bd, bn) = a, b
    return ad.astype("int64") - bd.astype("int64"), an | bn


_reg_nullable_int(
    "add_datetime_duration", 2,
    _safe_dt(lambda p, nanos: _mt.date_add(int(p), int(nanos) // 1000, "MICROSECOND")),
)
_reg_nullable_int(
    "sub_datetime_duration", 2,
    _safe_dt(lambda p, nanos: _mt.date_add(int(p), -(int(nanos) // 1000), "MICROSECOND")),
)


# -- string-typed time arithmetic (impl_time.rs AddTime/SubTime string arms:
# ADDTIME/SUBTIME accept a time-or-datetime STRING on either side) ----------

def _parse_time_arg(s: bytes):
    """('dur', nanos) | ('dt', packed) | None — MySQL tries duration first
    unless the text looks like a date."""
    text = s.decode("utf-8", "replace").strip()
    if not text:
        return None
    if "-" in text.lstrip("-"):  # date separator (not a leading sign)
        try:
            return ("dt", _mt.parse_datetime(text))
        except ValueError:
            return None
    body, _, frac = text.lstrip("+-").partition(".")
    if body.isdigit() and ":" not in text:
        # bare numeric time is RIGHT-aligned HHMMSS: '123' = 00:01:23
        neg = text.lstrip().startswith("-")
        v = int(body)
        hh, rem = divmod(v, 10000)
        mm, ss = divmod(rem, 100)
        if mm > 59 or ss > 59:
            return None
        micro = int(frac.ljust(6, "0")[:6]) if frac and frac.isdigit() else 0
        return ("dur", _mt.duration_nanos(hh, mm, ss, micro, neg))
    try:
        return ("dur", _mt.parse_duration(text))
    except ValueError:
        return None


def _dt_plus_str(packed: int, s: bytes, sign: int):
    arg = _parse_time_arg(s)
    if arg is None or arg[0] != "dur":
        return None  # datetime + datetime-string is NULL in MySQL
    return _mt.date_add(int(packed), sign * (arg[1] // 1000), "MICROSECOND")


def _dur_plus_str(d: int, s: bytes):
    arg = _parse_time_arg(s)
    if arg is None or arg[0] != "dur":
        return None
    return int(d) + arg[1]


_reg_nullable_int("add_datetime_and_string", 2, lambda p, s: _dt_plus_str(p, s, 1))
_reg_nullable_int("sub_datetime_and_string", 2, lambda p, s: _dt_plus_str(p, s, -1))
_reg_nullable_int("add_duration_and_string", 2, _dur_plus_str)


def _str_plus_dur(s: bytes, nanos: int, sign: int):
    """string ADDTIME duration → string (MySQL's result type for this arm)."""
    arg = _parse_time_arg(s)
    if arg is None:
        return None
    if arg[0] == "dur":
        return _mt.format_duration(arg[1] + sign * int(nanos)).encode()
    packed = _mt.date_add(arg[1], sign * (int(nanos) // 1000), "MICROSECOND")
    if packed is None:
        return None
    return _mt.format_datetime(packed).encode()


_bytes_op("add_string_and_duration", 2, "bytes")(
    lambda s, d: _str_plus_dur(s, d, 1)
)
_bytes_op("sub_string_and_duration", 2, "bytes")(
    lambda s, d: _str_plus_dur(s, d, -1)
)
def _date_plus_str(p: int, s: bytes):
    r = _dt_plus_str(p, s, 1)
    return None if r is None else _mt.format_datetime(r).encode()


_bytes_op("add_date_and_string", 2, "bytes")(_date_plus_str)


@_reg("add_time_string_null", 2, "int")
def _add_time_string_null(xp, a, b):
    """The reference's *Null arm: statically NULL-typed result."""
    (ad, _), _b = a, b
    n = len(ad)
    return _np.zeros(n, dtype=_np.int64), _np.ones(n, dtype=bool)


def _timestamp_add(unit: bytes, n: int, packed: int):
    return _mt.date_add(int(packed), int(n), unit.decode().upper())


def _timestamp_add_wrapped(xp, a, b, c):
    (ud, un), (nd, nn), (pd, pn) = a, b, c
    n = len(nd)
    out = _np.zeros(n, dtype=_np.int64)
    rnull = _np.asarray(un | nn | pn).copy()
    for i in range(n):
        if rnull[i]:
            continue
        try:
            out[i] = _timestamp_add(ud[i], nd[i], pd[i])
        except (ValueError, KeyError, OverflowError):
            rnull[i] = True
    return out, rnull


KERNELS["timestamp_add"] = (3, "int", _timestamp_add_wrapped)

_EXTRACT_UNITS = {
    b"YEAR": lambda p: _mt.unpack_datetime(p)[0],
    b"QUARTER": lambda p: (_mt.unpack_datetime(p)[1] + 2) // 3,
    b"MONTH": lambda p: _mt.unpack_datetime(p)[1],
    b"DAY": lambda p: _mt.unpack_datetime(p)[2],
    b"HOUR": lambda p: _mt.unpack_datetime(p)[3],
    b"MINUTE": lambda p: _mt.unpack_datetime(p)[4],
    b"SECOND": lambda p: _mt.unpack_datetime(p)[5],
    b"MICROSECOND": lambda p: _mt.unpack_datetime(p)[6],
    b"YEAR_MONTH": lambda p: _mt.unpack_datetime(p)[0] * 100 + _mt.unpack_datetime(p)[1],
    b"DAY_HOUR": lambda p: _mt.unpack_datetime(p)[2] * 100 + _mt.unpack_datetime(p)[3],
}


def _extract_datetime_wrapped(xp, a, b):
    (ud, un), (pd, pn) = a, b
    n = len(pd)
    out = _np.zeros(n, dtype=_np.int64)
    rnull = _np.asarray(un | pn).copy()
    for i in range(n):
        if rnull[i]:
            continue
        fn = _EXTRACT_UNITS.get(bytes(ud[i]).upper())
        if fn is None:
            rnull[i] = True
        else:
            out[i] = fn(int(pd[i]))
    return out, rnull


KERNELS["extract_datetime"] = (2, "int", _extract_datetime_wrapped)

_reg_nullable_int(
    "timediff", 2,
    _safe_dt(
        lambda a, b: (
            (_mt._as_date(a).toordinal() - _mt._as_date(b).toordinal()) * 86400
            + (_mt.unpack_datetime(int(a))[3] - _mt.unpack_datetime(int(b))[3]) * 3600
            + (_mt.unpack_datetime(int(a))[4] - _mt.unpack_datetime(int(b))[4]) * 60
            + (_mt.unpack_datetime(int(a))[5] - _mt.unpack_datetime(int(b))[5])
        ) * _mt.NANOS_PER_SEC
    ),
)


def _week_mode(p: int, mode: int) -> int:
    d = _mt._as_date(p)
    mode = int(mode) & 7
    if mode in (1, 3):  # ISO-like: Monday first, week 1 has >3 days
        return d.isocalendar()[1]
    # Sunday-first variants: week 0..53, counted from the first Sunday
    jan1 = _dt.date(d.year, 1, 1)
    days = (d - jan1).days
    offset = (jan1.weekday() + 1) % 7  # days since Sunday
    return (days + offset) // 7 if mode in (0, 2, 4, 6) else d.isocalendar()[1]


_reg_nullable_int("week_with_mode", 2, _safe_dt(lambda p, m: _week_mode(int(p), m)))


# -- password / sha aliases (impl_encryption.rs) ----------------------------

import hashlib as _hl


def _password(s_: bytes) -> bytes:
    if not s_:
        return b""
    return b"*" + _hl.sha1(_hl.sha1(s_).digest()).hexdigest().upper().encode()


_bytes_op("password", 1, "bytes")(_password)
_bytes_op("sha", 1, "bytes")(lambda s_: _hl.sha1(s_).hexdigest().encode())


# -- uuid helpers (impl_miscellaneous.rs) -----------------------------------

import uuid as _uuid


def _is_uuid(s_: bytes) -> int:
    try:
        _uuid.UUID(s_.decode())
        return 1
    except (ValueError, UnicodeDecodeError):
        return 0


_int_bytes_op("is_uuid", 1)(_is_uuid)


def _uuid_to_bin(s_: bytes):
    try:
        return _uuid.UUID(s_.decode()).bytes
    except (ValueError, UnicodeDecodeError):
        return None


_bytes_op("uuid_to_bin", 1, "bytes")(_uuid_to_bin)


def _bin_to_uuid(b: bytes):
    if len(b) != 16:
        return None
    return str(_uuid.UUID(bytes=bytes(b))).encode()


_bytes_op("bin_to_uuid", 1, "bytes")(_bin_to_uuid)


# -- json path predicates (impl_json.rs) ------------------------------------

def _json_contains_path(xp, *args):
    (dd, dn), (od, on) = args[0], args[1]
    n = len(dd)
    out = _np.zeros(n, dtype=_np.int64)
    rnull = _np.asarray(dn | on).copy()
    for _, nl in args[2:]:
        rnull |= _np.asarray(nl)
    for i in range(n):
        if rnull[i]:
            continue
        v = _jd(dd[i])
        one = bytes(od[i]).lower() == b"one"
        hits = []
        for pd, _pn in args[2:]:
            r = _jv.extract(v, [bytes(pd[i]).decode()])
            hits.append(r is not _jv._NO_MATCH)
        out[i] = int(any(hits) if one else all(hits))
    return out, rnull


KERNELS["json_contains_path"] = (-1, "int", _json_contains_path)


def _json_array_insert(doc: bytes, path: bytes, val: bytes):
    p = path.decode()
    if not p.endswith("]"):
        return None
    v = _jd(doc)
    base, _, idx_part = p.rpartition("[")
    try:
        idx = int(idx_part[:-1])
    except ValueError:
        return None
    target = _jv.extract(v, [base]) if base != "$" else v
    if base != "$" and target is _jv._NO_MATCH:
        return _jv.json_encode(v)
    if not isinstance(target, list):
        return _jv.json_encode(v)
    new = list(target)
    new.insert(min(idx, len(new)), _jd(val))
    if base == "$":
        return _jv.json_encode(new)
    return _jv.json_encode(_jv.modify(v, [(base, new)], "set"))


_json_op("json_array_insert", 3, "bytes")(_json_array_insert)


# -- cast matrix completion (impl_cast.rs) ----------------------------------

def _identity_cast(name, rkind):
    @_reg(name, 1, rkind)
    def fn(xp, a):
        ad, an = a
        return ad, an

    return fn


_identity_cast("cast_int_int", "int")
_identity_cast("cast_real_real", "real")
_identity_cast("cast_decimal_decimal", "decimal")
_identity_cast("cast_duration_duration", "int")
_bytes_op("cast_string_string", 1, "bytes")(lambda s_: s_)
_bytes_op("cast_json_json", 1, "bytes")(lambda s_: s_)


def _num_to_datetime(n: int):
    """MySQL numeric datetime literal: YYYYMMDD or YYYYMMDDHHMMSS."""
    def fix_year(y: int) -> int:
        # MySQL 2-digit-year rule for YYMMDD-form literals
        if y < 70:
            return y + 2000
        if y < 100:
            return y + 1900
        return y

    n = int(n)
    if n == 0:
        return 0  # CAST(0 AS DATETIME) is the zero date '0000-00-00'
    if n < 10**8:
        y, md = divmod(n, 10**4)
        m, d = divmod(md, 100)
        if m == 0 or d == 0:
            raise ValueError("zero month/day in datetime literal")  # NULL
        return _mt.pack_datetime(fix_year(y), m, d)
    dpart, tpart = divmod(n, 10**6)
    y, md = divmod(dpart, 10**4)
    m, d = divmod(md, 100)
    hh, ms = divmod(tpart, 10**4)
    mm, ss = divmod(ms, 100)
    return _mt.pack_datetime(fix_year(y), m, d, hh, mm, ss)


_reg_nullable_int("cast_int_datetime", 1, _safe_dt(_num_to_datetime))
_reg_nullable_int("cast_real_datetime", 1, _safe_dt(lambda x: _num_to_datetime(int(round(x)))))
_reg_nullable_int("cast_decimal_datetime", 1, _safe_dt(_num_to_datetime))


def _num_to_duration(n: int):
    """MySQL numeric duration literal: [H]HMMSS (sign carried)."""
    n = int(n)
    neg = n < 0
    n = abs(n)
    hh, ms = divmod(n, 10**4)
    mm, ss = divmod(ms, 100)
    if mm >= 60 or ss >= 60:
        return None
    return _mt.duration_nanos(hh, mm, ss, neg=neg)


_reg_nullable_int("cast_int_duration", 1, _num_to_duration)
_reg_nullable_int("cast_real_duration", 1, lambda x: _num_to_duration(int(round(x))))
_reg_nullable_int("cast_decimal_duration", 1, _num_to_duration)


def _dt_to_num(p: int) -> int:
    y, m, d, hh, mm, ss, _us = _mt.unpack_datetime(int(p))
    return ((y * 100 + m) * 100 + d) * 10**6 + (hh * 100 + mm) * 100 + ss


_reg_nullable_int("cast_datetime_int", 1, _safe_dt(_dt_to_num))


@_reg("cast_datetime_real", 1, "real")
def _cast_datetime_real(xp, a):
    ad, an = a
    out = _np.fromiter(
        (float(_dt_to_num(v)) if not nl else 0.0 for v, nl in zip(ad, _np.asarray(an))),
        dtype=_np.float64, count=len(ad),
    )
    return out, an


_reg_nullable_int("cast_datetime_decimal", 1, _safe_dt(_dt_to_num))
_reg_nullable_int(
    "cast_datetime_duration", 1,
    _safe_dt(lambda p: _mt.duration_nanos(
        _mt.unpack_datetime(int(p))[3], _mt.unpack_datetime(int(p))[4],
        _mt.unpack_datetime(int(p))[5], _mt.unpack_datetime(int(p))[6],
    )),
)
_reg_nullable_int(
    "cast_datetime_date", 1,
    _safe_dt(lambda p: _mt.pack_datetime(*_mt.unpack_datetime(int(p))[:3])),
)


def _dur_to_num(nanos: int) -> int:
    neg = int(nanos) < 0
    secs = abs(int(nanos)) // _mt.NANOS_PER_SEC
    hh, rem = divmod(secs, 3600)
    mm, ss = divmod(rem, 60)
    v = (hh * 100 + mm) * 100 + ss
    return -v if neg else v


_reg_nullable_int("cast_duration_int", 1, _dur_to_num)


@_reg("cast_duration_real", 1, "real")
def _cast_duration_real(xp, a):
    ad, an = a
    out = _np.fromiter(
        (float(_dur_to_num(v)) for v in ad), dtype=_np.float64, count=len(ad)
    )
    return out, an


_reg_nullable_int("cast_duration_decimal", 1, _dur_to_num)


def _cast_string_decimal_impl(xp, a):
    # parses to REAL then lets rpn's frac scaling materialize the target
    # scale (same shape as cast_string_real; scaled-int64 decimals)
    return _cast_string_real_impl(xp, a)


KERNELS["cast_string_decimal"] = (1, "real", _cast_string_decimal_impl)

_bytes_op("cast_json_datetime", 1, "bytes")(lambda b: b)  # opaque passthrough


def _cast_json_duration_impl(xp, a):
    ad, an = a
    n = len(ad)
    out = _np.zeros(n, dtype=_np.int64)
    rnull = _np.asarray(an).copy()
    for i in range(n):
        if rnull[i]:
            continue
        v = _jd(ad[i])
        if isinstance(v, str):
            try:
                out[i] = _mt.parse_duration(v)
                continue
            except ValueError:
                pass
        rnull[i] = True
    return out, rnull


KERNELS["cast_json_duration"] = (1, "int", _cast_json_duration_impl)


def _cast_json_decimal_impl(xp, a):
    ad, an = a
    n = len(ad)
    out = _np.zeros(n, dtype=_np.float64)
    rnull = _np.asarray(an).copy()
    for i in range(n):
        if rnull[i]:
            continue
        v = _jd(ad[i])
        if isinstance(v, bool):
            out[i] = float(v)
        elif isinstance(v, (int, float)):
            out[i] = float(v)
        elif isinstance(v, str):
            out[i] = _parse_num_prefix(v.encode())
        else:
            out[i] = 0.0
    return out, rnull


KERNELS["cast_json_decimal"] = (1, "real", _cast_json_decimal_impl)


_identity_cast("cast_datetime_datetime", "int")


def _cast_decimal_json_impl(xp, a):
    # decimal rides as scaled int64; rpn's scale plumbing normalizes to the
    # unscaled value before a "real"-input kernel, so encode as number
    ad, an = a
    n = len(ad)
    out = _np.empty(n, dtype=object)
    rnull = _np.asarray(an).copy()
    for i in range(n):
        v = float(ad[i]) if not rnull[i] else 0.0
        out[i] = _jv.json_encode(int(v) if v == int(v) else v)
    return out, rnull


KERNELS["cast_decimal_json"] = (1, "bytes", _cast_decimal_json_impl)


def _cast_decimal_string_impl(xp, a):
    ad, an = a
    n = len(ad)
    out = _np.empty(n, dtype=object)
    rnull = _np.asarray(an).copy()
    for i in range(n):
        v = float(ad[i]) if not rnull[i] else 0.0
        out[i] = (b"%d" % int(v)) if v == int(v) else repr(v).encode()
    return out, rnull


KERNELS["cast_decimal_string"] = (1, "bytes", _cast_decimal_string_impl)

# duration -> datetime needs the session's current date (reference combines
# with ctx time); anchor on the epoch date like our duration-only pipeline
_reg_nullable_int(
    "cast_duration_datetime", 1,
    _safe_dt(lambda nanos: _mt.date_add(
        _mt.pack_datetime(1970, 1, 1), int(nanos) // 1000, "MICROSECOND"
    )),
)
