"""AST-based project linter for the concurrent + device hot paths.

Rules (docs/static_analysis.md has the full catalog and waiver syntax):

``lock-blocking-call``
    A blocking operation — ``engine.write`` / ``engine.snapshot`` round
    trips, device sync (``block_until_ready``), ``time.sleep``, socket I/O,
    event waits on foreign objects, ``scan_delta`` — executed while a
    cache/scheduler/latch lock is held (directly, or transitively through
    same-class/same-module calls).
``jit-nocache``
    ``jax.jit(...)`` called inside a function body with no visible caching
    idiom: every call re-traces and re-compiles — the dominant hidden cost
    on tensor runtimes ("Query Processing on Tensor Computation Runtimes").
``jit-static-args``
    ``static_argnums``/``static_argnames`` passed a non-literal value —
    value-varying or unhashable statics silently recompile per call.
``jit-host-sync``
    ``.item()`` / ``float(param)`` / ``int(param)`` / ``bool(param)``
    inside a jitted function: a trace-time host sync or value-dependent
    branch point.
``jit-shape-branch``
    ``if``/``while`` on a parameter's ``.shape``/``len()`` inside a jitted
    function: the branch specializes at trace time — each new shape
    recompiles silently.
``metric-drift-dashboard``
    A metric referenced by the Grafana dashboards / alert rules that no
    ``REGISTRY.counter/gauge/histogram`` call defines.
``metric-drift-code``
    A REGISTRY-defined metric never referenced by any dashboard or alert
    rule (dead telemetry — either chart it or waive it).
``failpoint-drift-test``
    A test configures (``cfg``) a failpoint name that no ``fail_point``
    site defines (neither in source nor locally in the test file).
``failpoint-drift-source``
    A ``fail_point`` site never exercised by any test.
``raw-lock-direct``
    A sanitizer-wired module creating ``threading.Lock/RLock/Condition``
    directly instead of through ``analysis.sanitizer.make_*`` — the lock
    would silently escape order tracking.
``buffer-inplace-export``
    An in-place numpy mutation (``x[...] = v``, ``+=``, ``np.copyto``,
    ``.sort()``/``.fill()``) on a name that flows into
    ``wire.dumps_parts`` / ``bufsan.export`` in the same function (directly
    or through a same-module call) — the zero-copy wire path holds that
    buffer until the send completes, so a later in-place write corrupts
    frames already handed to the kernel.
``buffer-export-unregistered``
    An exposure-boundary function (``dumps_parts``, ``write_frame_parts``,
    ``encode_parts``, the device-pin cache entry points) that doesn't route
    through ``analysis.bufsan`` export/release — the buffer would cross the
    zero-copy boundary invisible to the runtime sanitizer.
``view-escape``
    A public method returning a ``memoryview`` or slice of a cache-resident
    buffer attribute without ``.copy()``/``.tobytes()``/``.toreadonly()``
    or bufsan export registration: the caller holds an aliasing view into
    state a later fold mutates in place.

Waivers: ``# lint: allow(rule-name[, rule2]) -- reason`` on the flagged
line or the line directly above it.  Every waiver should carry a reason.

Limits (by design): the blocking-call analysis links ``self.method()`` and
bare same-module calls only — cross-object calls are invisible unless they
match a blocking pattern themselves; the runtime sanitizer
(``analysis/sanitizer.py``) covers that half dynamically.
"""

from __future__ import annotations

import argparse
import ast
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path

# --------------------------------------------------------------------------
# config
# --------------------------------------------------------------------------

# attribute names that smell like a mutex when assigned threading primitives
_LOCK_NAME_RE = re.compile(
    r"(^|_)(mu|mutex|lock|lk|cv|cond|conds|cvs|latch|latches)\d*$"
)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "make_lock", "make_rlock",
                   "make_condition"}

# attr-chain suffixes that are blocking by themselves
_BLOCKING_CHAIN_SUFFIXES = (
    ("engine", "write"),
    ("engine", "snapshot"),
)
_BLOCKING_ATTRS = {"block_until_ready"}
_SOCKET_ATTRS = {"accept", "connect", "recv", "recvfrom", "recv_into",
                 "sendall", "makefile", "create_connection"}
# project-specific expensive scans treated as blocking
_BLOCKING_NAMES = {"scan_delta"}

# modules that MUST create locks through analysis.sanitizer (tentpole wiring)
_SANITIZER_WIRED = {
    "tikv_tpu/storage/txn/latches.py",
    "tikv_tpu/storage/txn/scheduler.py",
    "tikv_tpu/storage/concurrency_manager.py",
    "tikv_tpu/copr/breaker.py",
    "tikv_tpu/copr/cache.py",
    "tikv_tpu/copr/dag.py",
    "tikv_tpu/copr/endpoint.py",
    "tikv_tpu/copr/jax_join.py",
    "tikv_tpu/copr/costmodel.py",
    "tikv_tpu/copr/encoding.py",
    "tikv_tpu/copr/integrity.py",
    "tikv_tpu/copr/observatory.py",
    "tikv_tpu/copr/overload.py",
    "tikv_tpu/copr/region_cache.py",
    "tikv_tpu/copr/scheduler.py",
    "tikv_tpu/raft/store.py",
    "tikv_tpu/raft/batch_system.py",
    "tikv_tpu/raft/fsm_system.py",
    "tikv_tpu/sidecar/resolved_ts.py",
    "tikv_tpu/server/read_plane.py",
    "tikv_tpu/server/wire.py",
    "tikv_tpu/util/chaos.py",
    "tikv_tpu/util/retry.py",
    "tikv_tpu/util/trace.py",
    "tikv_tpu/util/worker.py",
}

# files whose functions count as "device code" for the jit rules
_DEVICE_FILES = ("copr/jax_eval.py", "copr/jax_zone.py", "parallel/mesh.py")

# exposure-boundary functions that MUST route through analysis.bufsan
# (export at the boundary, release at the completion point) — the runtime
# half of the zero-copy contract (docs/static_analysis.md §bufsan)
_BUFSAN_BOUNDARY = {
    "tikv_tpu/server/wire.py": ("dumps_parts",),
    "tikv_tpu/server/server.py": ("write_frame_parts",),
    "tikv_tpu/copr/dag.py": ("encode_parts",),
    "tikv_tpu/copr/cache.py": ("device_arrays", "drop_device", "scatter_update"),
}
_BUFSAN_MODULES = ("bufsan", "_bufsan")
_BUFSAN_CALLS = {"export", "release", "release_parts", "note_mutation",
                 "verify_all"}
# in-place ndarray methods for the buffer-inplace-export rule (``.clear()``
# etc. would drown the rule in dict/list noise)
_INPLACE_METHODS = {"sort", "fill", "partition", "byteswap"}
# attribute names that smell like a shared buffer for the view-escape rule
_BUF_NAME_RE = re.compile(
    r"(^|_)(data|buf|buffer|raw|bytes|payload|arr|array|nulls|packed|slab|"
    r"frame|view|blob|chunk)s?\d*$"
)

_METRIC_REF_RE = re.compile(r"\btikv_[a-z0-9_]+")
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")

RULES = {
    "lock-blocking-call": "blocking call while holding a lock",
    "jit-nocache": "uncached jax.jit in a function body (recompiles per call)",
    "jit-static-args": "non-literal static_argnums/static_argnames",
    "jit-host-sync": "host sync / value branch inside a jitted function",
    "jit-shape-branch": "shape-dependent branch inside a jitted function",
    "metric-drift-dashboard": "dashboard references an undefined metric",
    "metric-drift-code": "metric defined in code but on no dashboard",
    "failpoint-drift-test": "test configures an unknown failpoint",
    "failpoint-drift-source": "failpoint site never exercised by tests",
    "raw-lock-direct": "wired module bypasses analysis.sanitizer lock factories",
    "buffer-inplace-export": "in-place mutation of a buffer that flows to the "
                             "zero-copy wire boundary",
    "buffer-export-unregistered": "exposure-boundary function bypasses "
                                  "analysis.bufsan export/release",
    "view-escape": "public method returns an aliasing view of a "
                   "cache-resident buffer",
}


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    waived: bool = False

    def format(self) -> str:
        w = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}]{w} {self.message}"


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _attr_chain(node: ast.AST) -> list[str]:
    """``self.store.engine.write`` -> ["self","store","engine","write"]."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Subscript):
        inner = _attr_chain(node.value)
        parts.append(inner[0] if inner else "?")
    else:
        parts.append("?")
    return list(reversed(parts))


def _expr_key(node: ast.AST) -> str:
    """Stable text for a with-target / call-base comparison."""
    return ".".join(_attr_chain(node))


def _is_lock_expr(node: ast.AST, known_locks: set[str]) -> bool:
    """Does this with-target look like a mutex?  Known (assigned from a lock
    factory in this file) or name-pattern matched; subscripts of lock-named
    containers (``self._cvs[i]``) count."""
    if isinstance(node, ast.Subscript):
        return _is_lock_expr(node.value, known_locks)
    if isinstance(node, ast.Call):  # with foo.acquire_timeout(...): etc
        return False
    chain = _attr_chain(node)
    if not chain:
        return False
    last = chain[-1]
    key = ".".join(chain)
    return key in known_locks or last in known_locks or bool(_LOCK_NAME_RE.search(last))


def _waivers_for(src_lines: list[str]) -> dict[int, set[str]]:
    """line -> waived rule names.  A waiver covers its own line (inline
    form) and the next CODE line — intervening comment-only lines (the
    reason text) don't break the reach."""
    out: dict[int, set[str]] = {}
    rx = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")
    for i, line in enumerate(src_lines, start=1):
        m = rx.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if not line.strip().startswith("#"):
            continue  # inline form covers ONLY its own line
        j = i + 1
        while j <= len(src_lines) and src_lines[j - 1].strip().startswith("#"):
            j += 1
        if j <= len(src_lines):
            out.setdefault(j, set()).update(rules)
    return out


def _apply_waivers(findings: list[Finding], waivers: dict[int, set[str]]) -> None:
    for f in findings:
        rules = waivers.get(f.line)
        if rules and (f.rule in rules or "*" in rules):
            f.waived = True


# --------------------------------------------------------------------------
# per-file analysis
# --------------------------------------------------------------------------

@dataclass
class _FuncInfo:
    qualname: str
    node: ast.AST
    cls: str | None
    # (lineno, description) of direct blocking calls in this function
    direct: list[tuple[int, str]] = field(default_factory=list)
    # local callees: ("self", name) for self.method, ("bare", name) for f()
    calls: set[tuple[str, str]] = field(default_factory=set)
    blocking: tuple[str, ...] | None = None  # chain of the reached blocker


class _FileLint(ast.NodeVisitor):
    """Single-module pass: lock inventory, function table, jit sites."""

    def __init__(self, path: str, tree: ast.Module, relpath: str):
        self.path = path
        self.relpath = relpath
        self.tree = tree
        self.findings: list[Finding] = []
        self.known_locks: set[str] = set()
        self.funcs: dict[str, _FuncInfo] = {}
        self._cls_stack: list[str] = []
        self._fn_stack: list[_FuncInfo] = []
        # fail_point()/REGISTRY sites for the project passes
        self.failpoint_sites: list[tuple[str, int]] = []
        self.failpoint_cfgs: list[tuple[str, int]] = []
        self.metric_defs: list[tuple[str, int]] = []

    # -- inventory ----------------------------------------------------------

    def _note_lock_assign(self, target: ast.AST, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        fn = value.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name in _LOCK_FACTORIES:
            chain = _attr_chain(target)
            if chain:
                self.known_locks.add(chain[-1])
                self.known_locks.add(".".join(chain))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_lock_assign(t, node.value)
            # list-of-locks: self._cvs = [make_condition(...) for ...]
            if isinstance(node.value, (ast.ListComp, ast.List)):
                elts = (node.value.elts if isinstance(node.value, ast.List)
                        else [node.value.elt])
                for e in elts:
                    self._note_lock_assign(t, e)
        self.generic_visit(node)

    # -- structure ----------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_func(self, node) -> None:
        cls = self._cls_stack[-1] if self._cls_stack else None
        qual = f"{cls}.{node.name}" if cls else node.name
        info = _FuncInfo(qual, node, cls)
        # nested defs shadow outer entries only if names collide; last wins
        self.funcs[qual] = info
        self._fn_stack.append(info)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- calls --------------------------------------------------------------

    def _blocking_desc(self, call: ast.Call) -> str | None:
        fn = call.func
        chain = _attr_chain(fn)
        if not chain:
            return None
        last = chain[-1]
        key = ".".join(chain)
        if last in _BLOCKING_ATTRS:
            return f"{key}() [device sync]"
        for a, b in _BLOCKING_CHAIN_SUFFIXES:
            if len(chain) >= 2 and chain[-2] == a and last == b:
                return f"{key}() [engine round trip]"
        if key in ("time.sleep", "sleep"):
            return f"{key}() [sleep]"
        if last in _SOCKET_ATTRS and chain[0] != "?":
            # str.join-style false positives have no resolvable base
            return f"{key}() [socket I/O]"
        if last == "wait" and len(chain) >= 2:
            return f"{key}() [wait]"
        if last == "join" and any("thread" in p.lower() for p in chain[:-1]):
            return f"{key}() [thread join]"
        if last in _BLOCKING_NAMES and len(chain) == 1:
            return f"{key}() [mvcc scan]"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        chain = _attr_chain(fn)
        if self._fn_stack:
            info = self._fn_stack[-1]
            desc = self._blocking_desc(node)
            if desc is not None:
                info.direct.append((node.lineno, desc))
            if chain:
                if len(chain) == 2 and chain[0] == "self":
                    info.calls.add(("self", chain[1]))
                elif len(chain) == 1:
                    info.calls.add(("bare", chain[0]))
        # project-pass inventory
        if chain:
            last = chain[-1]
            args = node.args
            if last == "fail_point" and args and isinstance(args[0], ast.Constant) \
                    and isinstance(args[0].value, str):
                self.failpoint_sites.append((args[0].value, node.lineno))
            if last == "cfg" and (len(chain) == 1 or "failpoint" in chain[-2].lower()
                                  or chain[-2] in ("fp", "fail")):
                if args and isinstance(args[0], ast.Constant) and isinstance(args[0].value, str):
                    self.failpoint_cfgs.append((args[0].value, node.lineno))
            if last in ("counter", "gauge", "histogram") and len(chain) >= 2 \
                    and "registry" in chain[-2].lower():
                if args and isinstance(args[0], ast.Constant) and isinstance(args[0].value, str):
                    self.metric_defs.append((args[0].value, node.lineno))
            # raw-lock-direct (wired modules only)
            if self.relpath in _SANITIZER_WIRED and len(chain) == 2 \
                    and chain[0] == "threading" and last in ("Lock", "RLock", "Condition"):
                self.findings.append(Finding(
                    self.path, node.lineno, "raw-lock-direct",
                    f"threading.{last}() in a sanitizer-wired module — use "
                    f"analysis.sanitizer.make_{last.lower().replace('rlock','rlock')} "
                    f"so the lock joins order tracking",
                ))
        self.generic_visit(node)

    # -- jit rules ----------------------------------------------------------

    def check_jit(self) -> None:
        if not self.relpath.startswith("tikv_tpu/"):
            return
        for info in self.funcs.values():
            body_src_has_cache = self._cacheish(info.node)
            jitted_local_fns: list[str] = []
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                chain = _attr_chain(call.func)
                if chain[-1:] != ["jit"] or (len(chain) > 1 and chain[-2] != "jax"):
                    continue
                if call.args and isinstance(call.args[0], ast.Name):
                    jitted_local_fns.append(call.args[0].id)
                for kw in call.keywords:
                    if kw.arg in ("static_argnums", "static_argnames") \
                            and not self._literal(kw.value):
                        self.findings.append(Finding(
                            self.path, call.lineno, "jit-static-args",
                            f"{kw.arg} is not a literal — a value-varying or "
                            f"unhashable static recompiles (or fails) per call",
                        ))
                if not body_src_has_cache:
                    self.findings.append(Finding(
                        self.path, call.lineno, "jit-nocache",
                        f"jax.jit inside {info.qualname}() with no caching "
                        f"idiom in sight — every invocation re-traces and "
                        f"re-compiles",
                    ))
            # rules inside the jitted local functions
            for fname in jitted_local_fns:
                target = None
                for n in ast.walk(info.node):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                            and n.name == fname:
                        target = n
                        break
                if target is None:
                    continue
                params = {a.arg for a in target.args.args}
                self._check_jitted_body(target, params)

    def _check_jitted_body(self, fn, params: set[str]) -> None:
        for n in ast.walk(fn):
            if isinstance(n, (ast.If, ast.While)):
                for sub in ast.walk(n.test):
                    if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                        base = _attr_chain(sub.value)
                        if base and base[0] in params:
                            self.findings.append(Finding(
                                self.path, n.lineno, "jit-shape-branch",
                                f"branch on {'.'.join(base)}.shape inside "
                                f"jitted {fn.name}() — specializes at trace "
                                f"time, every new shape recompiles silently",
                            ))
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                            and sub.func.id == "len" and sub.args \
                            and isinstance(sub.args[0], ast.Name) \
                            and sub.args[0].id in params:
                        self.findings.append(Finding(
                            self.path, n.lineno, "jit-shape-branch",
                            f"branch on len({sub.args[0].id}) inside jitted "
                            f"{fn.name}()",
                        ))
            if isinstance(n, ast.Call):
                chain = _attr_chain(n.func)
                if chain[-1:] == ["item"] and len(chain) >= 2:
                    self.findings.append(Finding(
                        self.path, n.lineno, "jit-host-sync",
                        f"{'.'.join(chain)}() inside jitted {fn.name}() — "
                        f"forces a host sync / concretization at trace time",
                    ))
                if isinstance(n.func, ast.Name) and n.func.id in ("float", "int", "bool") \
                        and n.args and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id in params:
                    self.findings.append(Finding(
                        self.path, n.lineno, "jit-host-sync",
                        f"{n.func.id}({n.args[0].id}) on a traced parameter "
                        f"inside jitted {fn.name}()",
                    ))

    @staticmethod
    def _literal(node: ast.AST) -> bool:
        try:
            ast.literal_eval(node)
            return True
        except (ValueError, SyntaxError):
            return False

    def _cacheish(self, fn) -> bool:
        try:
            src = ast.unparse(fn)
        except Exception:  # noqa: BLE001
            return True  # can't inspect: benefit of the doubt
        low = src.lower()
        return any(tok in low for tok in ("cache", "memo", "_fns", "lru"))

    # -- bufsan rules -------------------------------------------------------

    @staticmethod
    def _bufsan_call_name(call: ast.Call) -> str | None:
        """``bufsan.export`` / ``_bufsan.release_parts`` etc., else None."""
        chain = _attr_chain(call.func)
        if (len(chain) >= 2 and chain[-2] in _BUFSAN_MODULES
                and chain[-1] in _BUFSAN_CALLS):
            return chain[-1]
        return None

    def _bufsan_reach(self) -> set[str]:
        """Qualnames that touch analysis.bufsan, directly or through a
        same-class/same-module call (same fixpoint shape as blocking)."""
        reach = {
            q for q, info in self.funcs.items()
            if any(isinstance(n, ast.Call) and self._bufsan_call_name(n)
                   for n in ast.walk(info.node))
        }
        changed = True
        while changed:
            changed = False
            for q, info in self.funcs.items():
                if q in reach:
                    continue
                for kind, name in info.calls:
                    callee = self._resolve(info, kind, name)
                    if callee is not None and callee.qualname in reach:
                        reach.add(q)
                        changed = True
                        break
        return reach

    @staticmethod
    def _sink_args(call: ast.Call) -> list[ast.AST]:
        """Buffer-valued arguments of a direct export sink: the payload of
        ``dumps_parts(obj)`` or ``bufsan.export(kind, buf, ...)``."""
        chain = _attr_chain(call.func)
        if chain[-1:] == ["dumps_parts"] and call.args:
            return [call.args[0]]
        if (len(chain) >= 2 and chain[-2] in _BUFSAN_MODULES
                and chain[-1] == "export" and len(call.args) >= 2):
            return [call.args[1]]
        return []

    @staticmethod
    def _buf_key(node: ast.AST) -> str | None:
        """Taint key for a buffer expression: a dotted name chain, or the
        chain inside a trivial wrapper (``memoryview(x)``)."""
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "memoryview" and node.args):
            node = node.args[0]
        if not isinstance(node, (ast.Name, ast.Attribute)):
            return None
        chain = _attr_chain(node)
        if not chain or chain == ["self"] or chain[0] == "?":
            return None
        return ".".join(chain)

    def check_bufsan(self) -> None:
        if not self.relpath.startswith("tikv_tpu/"):
            return
        reach = self._bufsan_reach()
        self._check_export_unregistered(reach)
        self._check_inplace_export()
        self._check_view_escape(reach)

    def _check_export_unregistered(self, reach: set[str]) -> None:
        for fname in _BUFSAN_BOUNDARY.get(self.relpath, ()):
            for q, info in self.funcs.items():
                if q != fname and not q.endswith(f".{fname}"):
                    continue
                if q in reach:
                    continue
                self.findings.append(Finding(
                    self.path, info.node.lineno, "buffer-export-unregistered",
                    f"{q}() is an exposure boundary but never routes through "
                    f"analysis.bufsan export/release — buffers cross the "
                    f"zero-copy plane invisible to the sanitizer",
                ))

    def _check_inplace_export(self) -> None:
        # param indices each local function exports (fixpoint over direct
        # sinks, so taint follows ``f(buf)`` into f's own dumps_parts call)
        exported_params: dict[str, set[int]] = {q: set() for q in self.funcs}
        changed = True
        while changed:
            changed = False
            for q, info in self.funcs.items():
                params = [a.arg for a in info.node.args.args]
                keys = self._exported_keys(info, exported_params)
                for i, p in enumerate(params):
                    if p in keys and i not in exported_params[q]:
                        exported_params[q].add(i)
                        changed = True
        for info in self.funcs.values():
            exported = self._exported_keys(info, exported_params)
            if not exported:
                continue
            self._scan_mutations(info, exported)

    def _exported_keys(self, info: _FuncInfo,
                       exported_params: dict[str, set[int]]) -> dict[str, int]:
        """key -> line of the earliest export of that name inside ``info``:
        direct sink args plus positional args handed to local callees at
        positions those callees export."""
        out: dict[str, int] = {}

        def note(node: ast.AST, line: int) -> None:
            key = self._buf_key(node)
            if key is not None and (key not in out or line < out[key]):
                out[key] = line

        for call in ast.walk(info.node):
            if not isinstance(call, ast.Call):
                continue
            for arg in self._sink_args(call):
                note(arg, call.lineno)
            chain = _attr_chain(call.func)
            callee, bound = None, 0
            if len(chain) == 2 and chain[0] == "self":
                callee = self._resolve(info, "self", chain[1])
                bound = 1  # callee's args.args leads with self
            elif len(chain) == 1:
                callee = self._resolve(info, "bare", chain[0])
            if callee is not None:
                for i in exported_params.get(callee.qualname, ()):
                    j = i - bound
                    if 0 <= j < len(call.args):
                        note(call.args[j], call.lineno)
        return out

    def _scan_mutations(self, info: _FuncInfo, exported: dict[str, int]) -> None:
        """Flag in-place writes that land AFTER a name was exported — the
        window where the wire/pin layer may still hold the buffer."""
        def flag(line: int, key: str, what: str) -> None:
            exp_line = exported.get(key)
            if exp_line is None or line <= exp_line:
                return  # untainted, or fill-before-export (safe ordering)
            self.findings.append(Finding(
                self.path, line, "buffer-inplace-export",
                f"{what} after {key} flowed to the zero-copy export on line "
                f"{exp_line} — the wire/pin layer may still hold this "
                f"buffer; copy before export or defer the write",
            ))

        for n in ast.walk(info.node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        key = self._buf_key(t.value)
                        if key:
                            flag(n.lineno, key, f"{key}[...] = assignment")
            elif isinstance(n, ast.AugAssign):
                t = n.target
                base = t.value if isinstance(t, ast.Subscript) else t
                key = self._buf_key(base)
                if key:
                    flag(n.lineno, key, f"augmented assignment to {key}")
            elif isinstance(n, ast.Call):
                chain = _attr_chain(n.func)
                if chain[-1:] == ["copyto"] and n.args:
                    key = self._buf_key(n.args[0])
                    if key:
                        flag(n.lineno, key, f"np.copyto into {key}")
                elif (len(chain) >= 2 and chain[-1] in _INPLACE_METHODS):
                    key = ".".join(chain[:-1])
                    if chain[0] not in ("?",):
                        flag(n.lineno, key, f"in-place .{chain[-1]}() on {key}")

    def _check_view_escape(self, reach: set[str]) -> None:
        for q, info in self.funcs.items():
            name = q.rsplit(".", 1)[-1]
            if info.cls is None or name.startswith("_"):
                continue
            if q in reach:
                continue  # exposure is registered with bufsan
            for n in ast.walk(info.node):
                if not isinstance(n, ast.Return) or n.value is None:
                    continue
                v = n.value
                what = None
                if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                        and v.func.id == "memoryview" and v.args):
                    base = _attr_chain(v.args[0])
                    if base and base[0] == "self":
                        what = f"memoryview({'.'.join(base)})"
                elif isinstance(v, ast.Subscript) and isinstance(v.slice, ast.Slice):
                    base = _attr_chain(v.value)
                    if (base and base[0] == "self"
                            and any(_BUF_NAME_RE.search(p) for p in base[1:])):
                        what = f"slice of {'.'.join(base)}"
                if what is not None:
                    self.findings.append(Finding(
                        self.path, n.lineno, "view-escape",
                        f"{q}() returns {what} — an aliasing view of "
                        f"cache-resident state; .copy()/.tobytes() it, return "
                        f".toreadonly(), or register the exposure with "
                        f"bufsan.export",
                    ))

    # -- blocking-under-lock ------------------------------------------------

    def propagate_blocking(self) -> None:
        """Fixpoint: a function is blocking if it blocks directly or calls a
        local/same-class function that does.  ``blocking`` stores the chain
        for the report."""
        for info in self.funcs.values():
            if info.direct:
                info.blocking = (info.direct[0][1],)
        changed = True
        while changed:
            changed = False
            for info in self.funcs.values():
                if info.blocking is not None:
                    continue
                for kind, name in info.calls:
                    callee = self._resolve(info, kind, name)
                    if callee is not None and callee.blocking is not None:
                        info.blocking = (f"{callee.qualname}()",) + callee.blocking
                        changed = True
                        break

    def _resolve(self, caller: _FuncInfo, kind: str, name: str) -> _FuncInfo | None:
        if kind == "self" and caller.cls is not None:
            return self.funcs.get(f"{caller.cls}.{name}")
        if kind == "bare":
            return self.funcs.get(name)
        return None

    def check_with_regions(self) -> None:
        for info in self.funcs.values():
            for w in ast.walk(info.node):
                if not isinstance(w, ast.With):
                    continue
                held = [item.context_expr for item in w.items
                        if _is_lock_expr(item.context_expr, self.known_locks)]
                if not held:
                    continue
                held_keys = {_expr_key(h) for h in held}
                for stmt in w.body:
                    for call in ast.walk(stmt):
                        if not isinstance(call, ast.Call):
                            continue
                        self._check_call_under_lock(info, call, held_keys)

    def _check_call_under_lock(self, info: _FuncInfo, call: ast.Call,
                               held_keys: set[str]) -> None:
        desc = self._blocking_desc(call)
        chain = _attr_chain(call.func)
        locks = ", ".join(sorted(held_keys))
        if desc is not None:
            if "[wait]" in desc:
                base = ".".join(chain[:-1])
                if base in held_keys:
                    return  # normal condition wait on the held lock
            self.findings.append(Finding(
                self.path, call.lineno, "lock-blocking-call",
                f"{desc} while holding {locks}",
            ))
            return
        # transitive: self.foo()/bare foo() reaching a blocker
        callee = None
        if len(chain) == 2 and chain[0] == "self":
            callee = self._resolve(info, "self", chain[1])
        elif len(chain) == 1:
            callee = self._resolve(info, "bare", chain[0])
        if callee is not None and callee.blocking is not None:
            via = " -> ".join(callee.blocking)
            self.findings.append(Finding(
                self.path, call.lineno, "lock-blocking-call",
                f"{callee.qualname}() reaches {via} while holding {locks}",
            ))


# --------------------------------------------------------------------------
# project passes
# --------------------------------------------------------------------------

def _metric_drift(code_files: list[_FileLint], root: Path) -> list[Finding]:
    findings: list[Finding] = []
    defined: dict[str, tuple[str, int]] = {}
    for fl in code_files:
        if not fl.relpath.startswith("tikv_tpu/"):
            continue
        for name, line in fl.metric_defs:
            defined.setdefault(name, (fl.path, line))
    metrics_dir = root / "metrics"
    if not metrics_dir.is_dir():
        return findings
    refs: dict[str, tuple[str, int]] = {}
    for p in sorted(metrics_dir.rglob("*")):
        if p.suffix not in (".json", ".yml", ".yaml"):
            continue
        for i, line in enumerate(p.read_text().splitlines(), start=1):
            # only PromQL carriers — dashboard titles/uids also match the
            # name regex but reference nothing
            if not ("expr" in line or "query" in line):
                continue
            for m in _METRIC_REF_RE.finditer(line):
                refs.setdefault(m.group(0), (str(p), i))

    def base_of(ref: str) -> str:
        for suf in _HISTO_SUFFIXES:
            if ref.endswith(suf) and ref[: -len(suf)] in defined:
                return ref[: -len(suf)]
        return ref

    for ref, (path, line) in sorted(refs.items()):
        if base_of(ref) not in defined:
            findings.append(Finding(
                path, line, "metric-drift-dashboard",
                f"{ref} referenced here but defined by no REGISTRY call",
            ))
    ref_blob = set(refs)
    for name, (path, line) in sorted(defined.items()):
        used = name in ref_blob or any(name + s in ref_blob for s in _HISTO_SUFFIXES)
        if not used:
            findings.append(Finding(
                path, line, "metric-drift-code",
                f"metric {name} is exported but appears on no dashboard or "
                f"alert rule",
            ))
    return findings


def _failpoint_drift(code_files: list[_FileLint]) -> list[Finding]:
    findings: list[Finding] = []
    source_sites: dict[str, tuple[str, int]] = {}
    local_sites: dict[str, set[str]] = {}  # per test file
    cfgs: list[tuple[str, str, int]] = []
    for fl in code_files:
        if fl.relpath.startswith("tikv_tpu/"):
            for name, line in fl.failpoint_sites:
                source_sites.setdefault(name, (fl.path, line))
        else:
            for name, _line in fl.failpoint_sites:
                local_sites.setdefault(fl.path, set()).add(name)
            for name, line in fl.failpoint_cfgs:
                cfgs.append((name, fl.path, line))
    cfg_names = {n for n, _p, _l in cfgs}
    for name, path, line in cfgs:
        if name in source_sites or name in local_sites.get(path, ()):
            continue
        findings.append(Finding(
            path, line, "failpoint-drift-test",
            f"failpoint {name!r} configured here but no fail_point site "
            f"defines it (renamed or removed in a refactor?)",
        ))
    # the doc example in util/failpoint.py's docstring is code, not a site
    for name, (path, line) in sorted(source_sites.items()):
        if name == "name" and path.endswith("util/failpoint.py"):
            continue
        if name not in cfg_names:
            findings.append(Finding(
                path, line, "failpoint-drift-source",
                f"fail_point({name!r}) is never configured by any test — "
                f"dead injection site or missing coverage",
            ))
    return findings


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def _collect_py(paths: list[str], root: Path) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        pp = (root / p) if not Path(p).is_absolute() else Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        elif pp.suffix == ".py":
            out.append(pp)
    return out


def run(paths: list[str], root: Path | None = None,
        drift: bool = True) -> tuple[list[Finding], list[Finding]]:
    """Lint ``paths``; returns (active, waived) findings."""
    root = root or _repo_root()
    files = _collect_py(paths, root)
    file_lints: list[_FileLint] = []
    findings: list[Finding] = []
    waiver_maps: dict[str, dict[int, set[str]]] = {}
    for path in files:
        try:
            src = path.read_text()
            tree = ast.parse(src)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(str(path), getattr(e, "lineno", 1) or 1,
                                    "parse-error", str(e)))
            continue
        try:
            rel = str(path.resolve().relative_to(root))
        except ValueError:
            rel = str(path)
        fl = _FileLint(str(path), tree, rel)
        fl.visit(tree)
        fl.propagate_blocking()
        fl.check_with_regions()
        fl.check_jit()
        fl.check_bufsan()
        file_lints.append(fl)
        waiver_maps[str(path)] = _waivers_for(src.splitlines())
        # nested lock withs walk the same call once per enclosing region —
        # one finding per (line, rule) is enough
        seen: set[tuple[int, str]] = set()
        for f in fl.findings:
            if (f.line, f.rule) not in seen:
                seen.add((f.line, f.rule))
                findings.append(f)
    if drift:
        findings.extend(_metric_drift(file_lints, root))
        findings.extend(_failpoint_drift(file_lints))
    # waivers; findings in files we didn't parse (the metrics/ JSONs) have
    # no in-line waiver channel and stay active
    for f in findings:
        wmap = waiver_maps.get(f.path)
        if wmap:
            _apply_waivers([f], wmap)
    active = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    return active, waived


def _repo_root() -> Path:
    # tikv_tpu/analysis/lint.py -> repo root two levels above the package
    return Path(__file__).resolve().parents[2]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tikv-tpu-lint",
        description="Project linter: concurrency + device recompile hazards, "
                    "metric and failpoint drift.",
    )
    ap.add_argument("paths", nargs="*", default=["tikv_tpu", "tests"])
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-waived", action="store_true",
                    help="also print waived findings")
    ap.add_argument("--no-drift", action="store_true",
                    help="skip the project-wide metric/failpoint drift passes")
    args = ap.parse_args(argv)
    if args.list_rules:
        for name, desc in RULES.items():
            print(f"{name:26s} {desc}")
        return 0
    active, waived = run(args.paths or ["tikv_tpu", "tests"],
                         drift=not args.no_drift)
    for f in active:
        print(f.format())
    if args.show_waived:
        for f in waived:
            print(f.format())
    print(f"lint: {len(active)} finding(s), {len(waived)} waived", file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
