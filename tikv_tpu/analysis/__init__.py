"""Static + runtime correctness tooling for the concurrent hot paths.

Two halves (docs/static_analysis.md):

* :mod:`.lint` — AST-based project linter: blocking calls under
  cache/scheduler/latch locks, JAX recompile hazards, metric-name drift
  between code and the Grafana dashboards, failpoint drift between tests
  and source.  ``python scripts/lint.py tikv_tpu tests`` (console script
  ``tikv-tpu-lint``) gates CI at zero unwaived findings.
* :mod:`.sanitizer` — runtime lock-order race sanitizer: instrumented
  Lock/RLock/Condition wrappers (enabled by ``TIKV_TPU_SANITIZE=1``) that
  build a global lock-acquisition-order graph, report cycles (potential
  deadlocks) with the stacks of both conflicting acquisitions, and flag
  long holds and locks held across engine/device round trips.
"""
