"""Buffer-exposure sanitizer — alias/lifetime checking for the zero-copy plane.

The chunk wire path ships column slabs as zero-copy memoryview parts
(``wire.dumps_parts`` → ``server.write_frame_parts`` → ``sendmsg``) while
write-through delta folds and ``scatter_update`` mutate cached columns
concurrently.  That is a *buffer lifetime* property no lock can express: a
buffer handed to the kernel (or pinned on a device, or held for a shadow
compare) must stay bit-stable until the hand-off completes.  This module is
the third pillar of ``tikv_tpu/analysis`` next to the lint and the
lock-order sanitizer: a bounded ledger of every buffer crossing an exposure
boundary, verified at release and at every mutation choke point.

Mechanics (docs/static_analysis.md has the design note):

* :func:`export` registers ``(id(buffer), blake2b(sample), site, stack)``
  when a buffer crosses an exposure boundary — ``wire.dumps_parts``
  passthrough parts, ``SelectResponse.encode_parts`` slabs,
  ``ColumnBlockCache.device_arrays`` pins, shadow-read snapshots.
* :func:`release` pops the entry at the matching release boundary (send
  completion in ``write_frame_parts``, pin drop, shadow-compare finish) and
  re-hashes the sample: a mismatch means the buffer mutated while exposed.
* :func:`note_mutation` is called from the mutation choke points
  (``RegionImage._apply_updates``, block repack, ``scatter_update``) with
  the arrays about to be written; any byte overlap with a live exposed
  buffer is reported immediately — BEFORE the torn bytes can reach a
  client.

Reports carry BOTH stacks (export + mutation/release), ride the lock
sanitizer's report channel under kind ``buffer-mutation-while-exposed``,
and the same ``TIKV_TPU_SANITIZE=1`` / ``sanitizer.force()`` switches
enable everything.  Disabled, every entry point returns after one cheap
check — the hot paths pay nothing beyond the call.

False-positive policy: chunk column slabs are immutable ``bytes`` copies
(``chunk_codec.encode_np_column`` joins), so legitimate serving never
trips the verify; device pins are excluded from :func:`note_mutation`
overlap checks because ``_apply_updates`` → ``scatter_update`` is the
*coordinated* host-mutate-then-patch path (the pin sample is re-registered
when the patch lands).  A pin whose sample fails at drop therefore means a
host/device write bypassed the scatter path.
"""

from __future__ import annotations

import sys
import threading
from hashlib import blake2b

import numpy as np

from . import sanitizer as _san

REPORT_KIND = "buffer-mutation-while-exposed"

#: ledger bound: beyond this, the oldest entry is verified and evicted.
#: Entries hold a strong ref to their buffer (id() reuse after GC would
#: otherwise alias a dead entry onto a fresh buffer), so the bound also
#: caps how much memory sanitize mode can pin.
_MAX_LEDGER = 4096
_SAMPLE_BYTES = 64  # per probe point: head + middle + tail
_STACK_LIMIT = 20

_mu = threading.Lock()
_entries: list["_Entry"] = []  # FIFO for the bound
_by_key: dict[int, list["_Entry"]] = {}
_seen: set = set()  # report dedup, mirrors sanitizer._seen

_counter = None  # lazy: tikv_bufsan_total{event=export|release|violation}


def enabled() -> bool:
    """Shared switch with the lock-order sanitizer: ``TIKV_TPU_SANITIZE=1``
    or an enclosing ``sanitizer.force()``."""
    return _san.enabled()


def _count(event: str) -> None:
    global _counter
    if _counter is None:
        from ..util.metrics import REGISTRY

        _counter = REGISTRY.counter(
            "tikv_bufsan_total",
            "Buffer-exposure sanitizer events (export/release/violation)")
    _counter.inc(event=event)


def _stack(skip: int = 2) -> tuple[str, ...]:
    """Fast frame walk (no linecache I/O); frames inside this module are
    dropped so the exposure/mutation site tops the report."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    out = []
    while f is not None and len(out) < _STACK_LIMIT:
        co = f.f_code
        out.append(f"{co.co_filename}:{f.f_lineno} in {co.co_name}")
        f = f.f_back
    return tuple(out)


# ---------------------------------------------------------------------------
# buffer trees -> byte views
# ---------------------------------------------------------------------------

def _leaves(tree) -> list:
    """Flatten an exposure payload: nested lists/tuples/dicts and pin
    entries carrying their device arrays under a ``dev`` attribute (zone
    layouts) down to buffer-like leaves."""
    out: list = []
    stack = [tree]
    while stack:
        x = stack.pop()
        if x is None:
            continue
        if isinstance(x, (list, tuple)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
        elif not isinstance(x, (bytes, bytearray, memoryview, np.ndarray)) \
                and hasattr(x, "dev"):
            stack.append(x.dev)
        else:
            out.append(x)
    return out


def _as_u8(leaf) -> np.ndarray | None:
    """A flat uint8 view of the leaf's bytes.  numpy arrays view in place;
    bytes-likes wrap via the buffer protocol; device arrays pull to host
    (``np.asarray``) — a copy whose *hash* is still the truth, which is the
    sampling cost sanitize mode accepts.  ``None`` = nothing hashable."""
    try:
        if isinstance(leaf, (bytes, bytearray, memoryview)):
            a = np.frombuffer(leaf, dtype=np.uint8)
            return a if a.size else None
        a = np.asarray(leaf)
        if a.dtype == object or a.nbytes == 0:
            return None
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        return a.reshape(-1).view(np.uint8)
    except Exception:  # noqa: BLE001 — unhashable leaf: skip, don't break serving
        return None


def _span(u8: np.ndarray) -> tuple[int, int] | None:
    try:
        ptr = u8.__array_interface__["data"][0]
        return (ptr, ptr + u8.size)
    except Exception:  # noqa: BLE001
        return None


def _sample(u8s: list[np.ndarray]) -> bytes:
    """blake2b over head/middle/tail probes of each leaf plus its length —
    O(_SAMPLE_BYTES) per leaf regardless of slab size, so exporting a
    64 MiB column costs the same as a 4 KiB one."""
    h = blake2b(digest_size=16)
    for u8 in u8s:
        n = u8.size
        h.update(n.to_bytes(8, "little"))
        if n <= 3 * _SAMPLE_BYTES:
            h.update(u8.tobytes())
        else:
            h.update(u8[:_SAMPLE_BYTES].tobytes())
            mid = n // 2
            h.update(u8[mid:mid + _SAMPLE_BYTES].tobytes())
            h.update(u8[-_SAMPLE_BYTES:].tobytes())
    return h.digest()


def _key_of(buf) -> int:
    """Ledger key: the identity of the buffer's BASE object, so the bytes a
    slab was encoded into matches both its ``encode_parts`` registration
    and the memoryview ``dumps_parts`` wrapped around it."""
    if isinstance(buf, memoryview) and buf.obj is not None:
        return id(buf.obj)
    return id(buf)


class _Entry:
    __slots__ = ("key", "kind", "site", "leaves", "sample", "spans",
                 "stack", "thread", "buf", "violated")

    def __init__(self, key, kind, site, leaves, sample, spans, stack, buf):
        self.key = key
        self.kind = kind
        self.site = site
        self.leaves = leaves  # strong refs: re-hashed at verify time
        self.sample = sample
        self.spans = spans
        self.stack = stack
        self.thread = threading.current_thread().name
        self.buf = buf
        self.violated = False


def _violation(entry: _Entry, phase: str, site: str,
               stack: tuple[str, ...]) -> None:
    entry.violated = True
    dedup = (phase, entry.kind, entry.site, site,
             entry.stack[0] if entry.stack else "?",
             stack[0] if stack else "?")
    with _mu:
        if dedup in _seen:
            return
        _seen.add(dedup)
    _count("violation")
    _san._emit(_san.Report(
        REPORT_KIND,
        f"{entry.kind} buffer exported at {entry.site} "
        f"{'mutated while exposed' if phase == 'mutation' else 'changed between export and release'}"
        f" ({phase} at {site})",
        [(f"exposed at {entry.site} ({entry.kind}) by {entry.thread}", entry.stack),
         (f"{phase} at {site} by", stack)],
    ))


def _verify(entry: _Entry, phase: str, site: str) -> None:
    if entry.violated:
        return
    u8s = [u for u in (_as_u8(lf) for lf in entry.leaves) if u is not None]
    if _sample(u8s) != entry.sample:
        _violation(entry, phase, site, _stack(3))


# ---------------------------------------------------------------------------
# the boundary API
# ---------------------------------------------------------------------------

def export(kind: str, buf, site: str = "") -> None:
    """Register ``buf`` as exposed at ``site``.  Kinds in use: ``wire_part``
    (dumps_parts passthrough), ``encode_parts`` (response column slabs),
    ``device_pin`` (ColumnBlockCache pins), ``shadow_read`` (integrity
    snapshot compares).  No-op when the sanitizer is off."""
    if not _san.enabled():
        return
    leaves = _leaves(buf)
    u8s, spans = [], []
    for lf in leaves:
        u8 = _as_u8(lf)
        if u8 is None:
            continue
        u8s.append(u8)
        sp = _span(u8)
        if sp is not None:
            spans.append(sp)
    entry = _Entry(_key_of(buf), kind, site, leaves, _sample(u8s), spans,
                   _stack(2), buf)
    evicted = []
    with _mu:
        _entries.append(entry)
        _by_key.setdefault(entry.key, []).append(entry)
        while len(_entries) > _MAX_LEDGER:
            old = _entries.pop(0)
            peers = _by_key.get(old.key)
            if peers is not None:
                try:
                    peers.remove(old)
                except ValueError:
                    pass
                if not peers:
                    _by_key.pop(old.key, None)
            evicted.append(old)
    _count("export")
    for old in evicted:
        # evict-with-verify: a leaked exposure (a part list that never
        # reached the frame writer) still gets its mutation check here
        _verify(old, "release", "bufsan.evict")
        _count("release")


def release(buf, site: str = "") -> int:
    """Verify and drop every ledger entry for ``buf``; returns how many
    were released.  Quiet for unregistered buffers (frame headers, small
    parts)."""
    if not _san.enabled():
        return 0
    key = _key_of(buf)
    with _mu:
        popped = _by_key.pop(key, None)
        if not popped:
            return 0
        for e in popped:
            try:
                _entries.remove(e)
            except ValueError:
                pass
    for e in popped:
        _verify(e, "release", site)
        _count("release")
    return len(popped)


def release_parts(parts, site: str = "") -> None:
    """Release every buffer of a frame's part list at send completion."""
    if not _san.enabled():
        return
    for p in parts:
        release(p, site)


def note_mutation(bufs, site: str = "") -> None:
    """Mutation choke point: ``bufs`` are about to take in-place writes.
    Any byte overlap with a live exposed buffer (device pins excepted —
    scatter_update re-registers those after the coordinated patch) is a
    violation, reported with the export stack AND this mutation stack."""
    if not _san.enabled():
        return
    with _mu:
        candidates = [e for e in _entries
                      if e.kind != "device_pin" and not e.violated and e.spans]
    if not candidates:
        return
    spans = []
    for b in bufs:
        u8 = _as_u8(b)
        if u8 is None:
            continue
        sp = _span(u8)
        if sp is not None:
            spans.append(sp)
    if not spans:
        return
    stack = None
    for e in candidates:
        if any(lo < ehi and elo < hi
               for (lo, hi) in spans for (elo, ehi) in e.spans):
            if stack is None:
                stack = _stack(2)
            _violation(e, "mutation", site, stack)


def verify_all(site: str = "") -> None:
    """Re-hash every live entry without releasing (structural repack
    boundary + test/gate hook)."""
    if not _san.enabled():
        return
    with _mu:
        snap = list(_entries)
    for e in snap:
        _verify(e, "release", site)


# ---------------------------------------------------------------------------
# introspection + test plumbing
# ---------------------------------------------------------------------------

def reports() -> list:
    """This sanitizer's findings (they ride the lock sanitizer's channel)."""
    return _san.reports(REPORT_KIND)


def ledger_size() -> int:
    with _mu:
        return len(_entries)


def exposed_kinds() -> dict[str, int]:
    with _mu:
        out: dict[str, int] = {}
        for e in _entries:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out


def clear() -> None:
    """Drop the ledger and report dedup (test isolation; reports themselves
    clear via ``sanitizer.clear_reports``)."""
    with _mu:
        _entries.clear()
        _by_key.clear()
        _seen.clear()


def snapshot_state():
    """Pair with :func:`restore_state` — same contract as the lock
    sanitizer's, so seeded strike tests don't erase what a session-wide
    gate is accumulating."""
    with _mu:
        return (list(_entries), {k: list(v) for k, v in _by_key.items()},
                set(_seen))


def restore_state(state) -> None:
    entries, by_key, seen = state
    with _mu:
        _entries[:] = entries
        _by_key.clear()
        _by_key.update({k: list(v) for k, v in by_key.items()})
        _seen.clear()
        _seen.update(seen)
