"""Runtime lock-order race sanitizer (TSan/lockdep for the hot paths).

The serving/write stack is deeply concurrent — latch table, txn scheduler,
region column cache, coprocessor read scheduler, raft store, worker pools —
and example-based tests cannot prove the absence of lock-order inversions.
This module is the lockdep re-expression: instrumented ``Lock``/``RLock``/
``Condition`` wrappers that

* build a process-global **lock-acquisition-order graph** keyed by each
  lock's *order key* (a stable per-subsystem name, so every ``Worker``
  condition is one node, not thousands);
* report **cycles** (potential deadlocks) the moment the closing edge is
  observed, with the stacks of BOTH conflicting acquisitions — before any
  thread actually deadlocks (detection is at acquisition *attempt*, and two
  sequential threads A→B then B→A are enough, no timing window needed);
* flag **long holds** (a lock held longer than ``TIKV_TPU_SANITIZE_HOLD_MS``)
  and **locks held across engine/device round trips**
  (:func:`note_blocking` call sites in ``raft/raftkv.py`` and the device
  pull paths).

Enabling: set ``TIKV_TPU_SANITIZE=1`` before process start (the factories
read it when each lock is created), or wrap test code in
``with sanitizer.force():``.  Disabled, the factories return plain
``threading`` primitives — zero overhead on the hot paths.

Env vars:

=============================  =============================================
``TIKV_TPU_SANITIZE``          ``1`` enables the instrumented wrappers
``TIKV_TPU_SANITIZE_HOLD_MS``  long-hold threshold, default 500
``TIKV_TPU_SANITIZE_FATAL``    ``1`` raises on a detected cycle instead of
                               recording it (CI hard-stop mode)
=============================  =============================================

Reports accumulate in :func:`reports` (bounded, deduplicated) and are also
emitted through ``logging`` at WARNING.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading
import time

_log = logging.getLogger("tikv_tpu.sanitizer")

_FORCED: bool | None = None  # force() override for tests
_MAX_REPORTS = 256
_STACK_LIMIT = 20


def _enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("TIKV_TPU_SANITIZE", "").lower() in ("1", "true", "on", "yes")


def enabled() -> bool:
    """Public switch probe — the buffer-exposure sanitizer (bufsan) and
    other per-call instrumentation share this one gate."""
    return _enabled()


_hold_cache: float | None = None


def _hold_threshold_s() -> float:
    # cached: this runs on EVERY release — an os.environ read + float parse
    # there costs more than the rest of the release path combined.
    # clear_reports() invalidates (tests monkeypatch the env per scenario).
    global _hold_cache
    if _hold_cache is None:
        try:
            _hold_cache = float(
                os.environ.get("TIKV_TPU_SANITIZE_HOLD_MS", "500")) / 1000.0
        except ValueError:
            _hold_cache = 0.5
    return _hold_cache


def _fatal() -> bool:
    return os.environ.get("TIKV_TPU_SANITIZE_FATAL", "") == "1"


@contextlib.contextmanager
def force(enabled: bool = True):
    """Test hook: force the factories on (or off) regardless of the env.
    Wrappers created inside keep tracking after exit — create the subsystem
    under ``force()`` and exercise it anywhere."""
    global _FORCED
    prev = _FORCED
    _FORCED = enabled
    try:
        yield
    finally:
        _FORCED = prev


def _stack(skip: int = 2) -> tuple[str, ...]:
    """Fast frame walk — no linecache I/O, safe on every acquire.  Leading
    frames inside this module are dropped so user code tops the report."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    out = []
    while f is not None and len(out) < _STACK_LIMIT:
        co = f.f_code
        out.append(f"{co.co_filename}:{f.f_lineno} in {co.co_name}")
        f = f.f_back
    return tuple(out)


def _site(skip: int = 2) -> tuple[str, ...]:
    """One-frame acquire site: the cost the UNCONTENDED hot path pays on
    every acquisition.  Full walks (:func:`_stack`) run only for nested
    acquisitions and report emission — a raft cluster doing millions of
    flat lock round trips must not pay a 20-frame walk each time."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return ()
    co = f.f_code
    return (f"{co.co_filename}:{f.f_lineno} in {co.co_name}",)


class Report:
    """One sanitizer finding."""

    __slots__ = ("kind", "message", "stacks", "thread")

    def __init__(self, kind: str, message: str,
                 stacks: list[tuple[str, tuple[str, ...]]]):
        self.kind = kind
        self.message = message
        self.stacks = stacks
        self.thread = threading.current_thread().name

    def format(self) -> str:
        lines = [f"[{self.kind}] {self.message} (thread {self.thread})"]
        for title, frames in self.stacks:
            lines.append(f"  -- {title}:")
            lines.extend(f"     {fr}" for fr in frames)
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Report {self.kind}: {self.message}>"


class _Edge:
    __slots__ = ("held_stack", "acq_stack", "thread", "count")

    def __init__(self, held_stack, acq_stack, thread):
        self.held_stack = held_stack
        self.acq_stack = acq_stack
        self.thread = thread
        self.count = 1


class _Held:
    __slots__ = ("lock", "t0", "stack", "depth")

    def __init__(self, lock, t0, stack):
        self.lock = lock
        self.t0 = t0
        self.stack = stack
        self.depth = 1


# all sanitizer bookkeeping is guarded by ONE plain (untracked) mutex; the
# held-lists are thread-local so the common acquire touches _mu only to
# record graph edges (i.e. only for nested acquisitions)
_mu = threading.Lock()
_edges: dict[str, dict[str, _Edge]] = {}
_reports: list[Report] = []
_seen: set = set()  # dedup keys for every report kind
_tls = threading.local()


def _held_list() -> list:
    lst = getattr(_tls, "held", None)
    if lst is None:
        lst = _tls.held = []
    return lst


def _emit(report: Report) -> None:
    with _mu:
        # cycle/same-key reports bypass the cap: a flood of deduplicated
        # long-hold reports must never displace the one report the CI gate
        # exists to catch (cycles self-bound via node-set dedup anyway)
        if (len(_reports) < _MAX_REPORTS
                or report.kind in ("lock-order-cycle", "lock-order-same-key",
                                   "buffer-mutation-while-exposed")):
            _reports.append(report)
    _log.warning("%s", report.format())


def reports(kind: str | None = None) -> list[Report]:
    with _mu:
        snap = list(_reports)
    return snap if kind is None else [r for r in snap if r.kind == kind]


def clear_reports() -> None:
    """Reset findings AND the order graph (tests isolate scenarios)."""
    global _hold_cache
    with _mu:
        _reports.clear()
        _seen.clear()
        _edges.clear()
        _hold_cache = None


def snapshot_state():
    """Copy the global graph/report state — pair with :func:`restore_state`
    so a test can seed synthetic scenarios without erasing edges a
    session-wide gate (tests/conftest.py) is accumulating."""
    with _mu:
        return (
            {a: dict(bs) for a, bs in _edges.items()},
            list(_reports),
            set(_seen),
        )


def restore_state(state) -> None:
    edges, reports_, seen = state
    global _hold_cache
    with _mu:
        _edges.clear()
        _edges.update({a: dict(bs) for a, bs in edges.items()})
        _reports[:] = reports_
        _seen.clear()
        _seen.update(seen)
        _hold_cache = None


def lock_graph() -> dict[str, set[str]]:
    """The observed acquisition-order graph: key -> keys acquired under it."""
    with _mu:
        return {a: set(bs) for a, bs in _edges.items()}


def _find_path(src: str, dst: str) -> list[str] | None:
    """DFS over _edges from src to dst (caller holds _mu)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_acquire(lock: "_TrackedLock", stack: tuple[str, ...]) -> None:
    """Called on the outermost acquisition ATTEMPT: add order edges from
    every held lock and check each new edge for a closing cycle."""
    held = _held_list()
    if not held:
        return
    cycle_report = None
    for h in held:
        a, b = h.lock.order_key, lock.order_key
        if a == b:
            if h.lock is not lock:
                key = ("same-key", a)
                with _mu:
                    if key in _seen:
                        continue
                    _seen.add(key)
                _emit(Report(
                    "lock-order-same-key",
                    f"two distinct locks with order key {a!r} nested — "
                    f"instances of one subsystem lock acquired inside each "
                    f"other have no defined order",
                    [(f"outer {a} ({h.lock.label or 'unnamed'}) acquired at", h.stack),
                     (f"inner {b} ({lock.label or 'unnamed'}) acquired at", stack)],
                ))
            continue
        with _mu:
            row = _edges.setdefault(a, {})
            edge = row.get(b)
            if edge is not None:
                edge.count += 1
                continue
            row[b] = _Edge(h.stack, stack, threading.current_thread().name)
            path = _find_path(b, a)  # b ~> a plus the new a->b closes a cycle
            if path is None:
                continue
            key = ("cycle", frozenset(path))
            if key in _seen:
                continue
            _seen.add(key)
            stacks = [
                (f"this thread: {a} held at", h.stack),
                (f"this thread: {b} acquired under {a} at", stack),
            ]
            for u, v in zip(path, path[1:]):
                rev = _edges[u][v]
                stacks.append((
                    f"{rev.thread}: {v} acquired under {u} at "
                    f"(with {u} held at the stack above it)",
                    rev.held_stack + ("--- then acquired: ---",) + rev.acq_stack,
                ))
            cycle = " -> ".join([a, b] + path[1:])
            cycle_report = Report(
                "lock-order-cycle",
                f"lock-order inversion: {cycle} — potential deadlock",
                stacks,
            )
    if cycle_report is not None:
        _emit(cycle_report)
        if _fatal():
            raise RuntimeError("sanitizer: " + cycle_report.message)


def _push_held(lock: "_TrackedLock", stack: tuple[str, ...], depth: int = 1) -> _Held:
    h = _Held(lock, time.monotonic(), stack)
    h.depth = depth
    _held_list().append(h)
    return h


def _find_held(lock: "_TrackedLock") -> _Held | None:
    for h in reversed(_held_list()):
        if h.lock is lock:
            return h
    return None


def _pop_held(lock: "_TrackedLock") -> None:
    h = _find_held(lock)
    if h is None:
        return  # release of a lock acquired before tracking (shouldn't happen)
    h.depth -= 1
    if h.depth > 0:
        return
    _held_list().remove(h)
    dt = time.monotonic() - h.t0
    if dt > _hold_threshold_s():
        site = h.stack[0] if h.stack else "?"
        key = ("long-hold", lock.order_key, site)
        with _mu:
            if key in _seen:
                return
            _seen.add(key)
        _emit(Report(
            "long-hold",
            f"{lock.order_key} held for {dt * 1000:.0f}ms "
            f"(threshold {_hold_threshold_s() * 1000:.0f}ms)",
            [(f"{lock.order_key} acquired at", h.stack)],
        ))


def note_blocking(site: str) -> None:
    """Declare a blocking boundary (engine write/snapshot round trip, device
    sync/pull).  If the calling thread holds ANY sanitized lock here, that
    lock is held across a stall — report it with both stacks.  Call sites
    live in ``raft/raftkv.py``, ``copr/jax_eval.py``, ``copr/jax_zone.py``
    and ``parallel/mesh.py``; the call is a no-op when the sanitizer is off
    or nothing is held."""
    if not _enabled():
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    stack = _stack(2)
    names = ", ".join(h.lock.order_key for h in held)
    site_frame = stack[0] if stack else "?"
    key = ("blocking", site, tuple(h.lock.order_key for h in held), site_frame)
    with _mu:
        if key in _seen:
            return
        _seen.add(key)
    stacks = [(f"{h.lock.order_key} acquired at", h.stack) for h in held]
    stacks.append((f"blocking call {site} at", stack))
    _emit(Report(
        "blocking-under-lock",
        f"{site} entered while holding [{names}]",
        stacks,
    ))


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------

class _TrackedLock:
    """Instrumented lock.  ``order_key`` names the graph node (one per
    subsystem lock class); ``label`` carries per-instance detail for
    reports."""

    _reentrant = False

    def __init__(self, order_key: str, label: str | None = None, real=None):
        self.order_key = order_key
        self.label = label
        self._real = real if real is not None else (
            threading.RLock() if self._reentrant else threading.Lock()
        )

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        h = _find_held(self) if self._reentrant else None
        if h is not None:  # reentrant re-acquire: no new ordering event
            got = self._real.acquire(blocking, timeout)
            if got:
                h.depth += 1
            return got
        if _held_list():
            # nested acquisition: an ordering event worth a full stack.
            # Edges record the *attempt* — a cycle is reported before this
            # thread can actually park on the inverted lock.
            stack = _stack(2)
            _record_acquire(self, stack)
        else:
            stack = _site(2)  # flat fast path: one frame for hold reports
        got = self._real.acquire(blocking, timeout)
        if got:
            _push_held(self, stack)
        return got

    def release(self) -> None:
        self._real.release()
        _pop_held(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "RLock" if self._reentrant else "Lock"
        return f"<San{kind} {self.order_key} ({self.label or 'unnamed'})>"


class _TrackedRLock(_TrackedLock):
    _reentrant = True


class _TrackedCondition:
    """Condition over a tracked lock.  ``wait`` releases the lock — the
    held-record is parked for the duration so hold-time and order tracking
    stay truthful."""

    def __init__(self, order_key: str, lock: _TrackedLock | None = None,
                 label: str | None = None):
        if lock is None:
            lock = _TrackedRLock(order_key, label)
        self._lock = lock
        self._cond = threading.Condition(lock._real)

    # lock facade ------------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()

    # condition facade --------------------------------------------------------
    def wait(self, timeout: float | None = None) -> bool:
        h = _find_held(self._lock)
        depth = h.depth if h is not None else 1
        if h is not None:
            # the real Condition releases the lock for the wait: park the
            # record (hold time restarts on wake — the wait is not a hold)
            h.depth = 1
            _pop_held(self._lock)
        try:
            return self._cond.wait(timeout)
        finally:
            if h is not None:
                _push_held(self._lock, _site(2), depth)

    def wait_for(self, predicate, timeout: float | None = None):
        result = predicate()
        if result:
            return result
        endtime = None if timeout is None else time.monotonic() + timeout
        while not result:
            t = None if endtime is None else max(endtime - time.monotonic(), 0)
            if t == 0:
                break
            self.wait(t)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<SanCondition over {self._lock!r}>"


# ---------------------------------------------------------------------------
# factories — the ONLY api the wired modules use
# ---------------------------------------------------------------------------

def make_lock(order_key: str, label: str | None = None):
    """A mutex participating in order tracking when sanitize is on, else a
    plain ``threading.Lock``."""
    if _enabled():
        return _TrackedLock(order_key, label)
    return threading.Lock()


def make_rlock(order_key: str, label: str | None = None):
    if _enabled():
        return _TrackedRLock(order_key, label)
    return threading.RLock()


def make_condition(order_key: str, lock=None, label: str | None = None):
    """A condition variable; pass ``lock`` (from :func:`make_lock`) to share
    one mutex between direct ``with lock:`` sections and the condition —
    tracking stays consistent across both."""
    if isinstance(lock, _TrackedLock):
        return _TrackedCondition(order_key, lock, label)
    if _enabled() and lock is None:
        return _TrackedCondition(order_key, None, label)
    return threading.Condition(lock)


def held_locks() -> list[str]:
    """Order keys this thread currently holds (debugging/tests)."""
    return [h.lock.order_key for h in getattr(_tls, "held", [])]
