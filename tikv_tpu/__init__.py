"""tikv_tpu — a TPU-native distributed transactional KV framework.

Re-expresses the capabilities of TiKV (multi-Raft regions, Percolator MVCC
transactions, raw KV, and a pushdown coprocessor) with the coprocessor's
vectorized columnar execution compiled by XLA onto TPU.  See SURVEY.md at the
repo root for the layer map this package follows.
"""

__version__ = "0.1.0"
