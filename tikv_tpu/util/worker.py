"""Worker framework + unified multilevel read pool.

Re-expression of ``tikv_util/src/worker`` (LazyWorker/Runnable: a named
single-thread worker draining a channel of tasks, with optional periodic
timer) and the yatp multilevel pool behind the unified read pool
(``tikv_util/src/yatp_pool/mod.rs:12`` — queue levels, per-task-group
elapsed accounting, demotion; ``src/read_pool.rs`` build_yatp_read_pool).

Scheduling model (yatp's multilevel queue, re-derived):

* Three levels.  New task groups start at L0.  A group is demoted as its
  *accumulated* CPU time crosses thresholds (default 5ms → L1, 100ms → L2),
  so cheap point-gets never sit behind a long analytical scan — the exact
  property the reference's unified read pool exists for.
* Workers prefer L0 but visit lower levels on a fixed ratio so nothing
  starves (level_time_ratio in yatp; a deterministic 8:2:1 cycle here).
* ``TaskPriority.HIGH`` pins a task to L0 regardless of history (the
  reference's resource-control override).
"""

from __future__ import annotations

import enum
import threading
import time
from collections import deque

from ..analysis.sanitizer import make_condition


class Runnable:
    """Task handler for a Worker (worker/mod.rs Runnable)."""

    def run(self, task) -> None:
        raise NotImplementedError

    def on_timeout(self) -> None:
        """Periodic tick (RunnableWithTimer)."""

    def shutdown(self) -> None:
        """Called once when the worker stops."""


class Worker:
    """Named single-thread worker: schedule() enqueues, the thread drains.

    ``LazyWorker`` semantics: created stopped; ``start(runnable)`` spins the
    thread; schedule() before start() buffers.
    """

    def __init__(self, name: str, timer_interval: float | None = None):
        self.name = name
        self._queue: deque = deque()
        self._cv = make_condition("util.worker", label=name)
        self._runnable: Runnable | None = None
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._timer_interval = timer_interval
        self.handled = 0

    def start(self, runnable: Runnable) -> None:
        assert self._thread is None, "worker already started"
        self._runnable = runnable
        self._thread = threading.Thread(target=self._loop, name=self.name, daemon=True)
        self._thread.start()

    def schedule(self, task) -> bool:
        with self._cv:
            if self._stopped:
                return False
            self._queue.append(task)
            self._cv.notify()
        return True

    def _loop(self) -> None:
        interval = self._timer_interval
        next_tick = time.monotonic() + interval if interval else None
        while True:
            # the tick is checked on EVERY iteration so a continuously-fed
            # queue cannot starve the periodic flush/heartbeat
            if next_tick is not None and time.monotonic() >= next_tick:
                try:
                    self._runnable.on_timeout()
                except Exception:  # noqa: BLE001
                    pass
                next_tick = time.monotonic() + interval
            with self._cv:
                while not self._queue and not self._stopped:
                    timeout = 0.5
                    if next_tick is not None:
                        timeout = max(0.0, min(timeout, next_tick - time.monotonic()))
                        if timeout == 0.0:
                            break
                    self._cv.wait(timeout)
                if self._stopped and not self._queue:
                    break
                task = self._queue.popleft() if self._queue else None
            if task is None:
                continue  # woke for a tick; handled at loop top
            try:
                self._runnable.run(task)
            except Exception:  # noqa: BLE001 — a task must not kill the worker
                pass
            self.handled += 1

    def stop(self, wait: bool = True) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        if wait and self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                # A wedged task still owns run(); calling shutdown() now would
                # race with it.  Leave the runnable alive and let the daemon
                # thread die with the process.
                return
        if self._runnable is not None:
            self._runnable.shutdown()

    def pending(self) -> int:
        with self._cv:
            return len(self._queue)


class TaskPriority(enum.IntEnum):
    HIGH = 0
    NORMAL = 1


class _Future:
    __slots__ = ("_ev", "_result", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._result = None
        self._exc: BaseException | None = None

    def set(self, result=None, exc: BaseException | None = None) -> None:
        self._result, self._exc = result, exc
        self._ev.set()

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("read pool task timed out")
        if self._exc is not None:
            raise self._exc
        return self._result

    def done(self) -> bool:
        return self._ev.is_set()


# demotion thresholds: accumulated group CPU seconds crossing these moves the
# group down a level (yatp multilevel defaults are 5ms/100ms task-elapsed)
_LEVEL_THRESHOLDS = (0.005, 0.100)
# deterministic visit cycle — 8 L0 slots, 2 L1, 1 L2 (≈ yatp level_time_ratio)
_VISIT_CYCLE = (0, 0, 1, 0, 0, 2, 0, 1, 0, 0, 0)


class UnifiedReadPool:
    """The unified read pool: N workers over one 3-level queue.

    ``submit(fn, group=...)`` → future.  ``group`` identifies the logical
    request stream (e.g. a txn's start_ts or a connection id); the group's
    accumulated elapsed time decides its level, so one heavy consumer sinks
    to L2 while light traffic keeps L0 latency.
    """

    def __init__(self, workers: int = 4, name: str = "unified-read-pool"):
        self._levels: tuple[deque, deque, deque] = (deque(), deque(), deque())
        self._cv = make_condition("util.read_pool", label=name)
        # group → (accumulated elapsed seconds, last activity monotonic time)
        self._group_elapsed: dict[object, tuple[float, float]] = {}
        self._stopped = False
        self.name = name
        self._threads = [
            threading.Thread(target=self._worker_loop, args=(i,), name=f"{name}-{i}", daemon=True)
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    # -- submission ---------------------------------------------------------

    def level_of(self, group) -> int:
        e, _ = self._group_elapsed.get(group, (0.0, 0.0))
        if e < _LEVEL_THRESHOLDS[0]:
            return 0
        if e < _LEVEL_THRESHOLDS[1]:
            return 1
        return 2

    def submit(self, fn, *args, group=None, priority: TaskPriority = TaskPriority.NORMAL):
        fut = _Future()
        with self._cv:
            if self._stopped:
                raise RuntimeError("read pool is stopped")
            level = 0 if priority == TaskPriority.HIGH else self.level_of(group)
            self._levels[level].append((fn, args, group, fut))
            self._cv.notify()
        return fut

    # -- workers ------------------------------------------------------------

    def _pick_locked(self, slot: int):
        preferred = _VISIT_CYCLE[slot % len(_VISIT_CYCLE)]
        for lvl in (preferred, 0, 1, 2):
            if self._levels[lvl]:
                return self._levels[lvl].popleft()
        return None

    def _worker_loop(self, seed: int) -> None:
        slot = seed
        while True:
            with self._cv:
                task = self._pick_locked(slot)
                while task is None and not self._stopped:
                    self._cv.wait(0.5)
                    task = self._pick_locked(slot)
                if task is None:
                    return
            slot += 1
            fn, args, group, fut = task
            start = time.monotonic()
            try:
                fut.set(fn(*args))
            except BaseException as e:  # noqa: BLE001 — delivered via future
                fut.set(exc=e)
            if group is not None:
                now = time.monotonic()
                elapsed = now - start
                with self._cv:
                    prev, _ = self._group_elapsed.get(group, (0.0, 0.0))
                    self._group_elapsed[group] = (prev + elapsed, now)
                    # bound the stats map by evicting *idle* groups only — a
                    # wholesale clear would re-promote still-running heavy
                    # groups to L0 (yatp recycles idle records the same way)
                    if len(self._group_elapsed) > 4096:
                        cutoff = now - 30.0
                        evict = [g for g, (_, last) in self._group_elapsed.items() if last < cutoff]
                        if not evict:
                            # all recent: drop the *cheapest* half — losing a
                            # light group's record is free (it re-enters at
                            # L0 anyway), while a heavy group's demotion
                            # state is exactly what must survive
                            by_cost = sorted(self._group_elapsed.items(), key=lambda kv: kv[1][0])
                            evict = [g for g, _ in by_cost[: len(by_cost) // 2]]
                        for g in evict:
                            del self._group_elapsed[g]

    # -- introspection ------------------------------------------------------

    def queue_depths(self) -> tuple[int, int, int]:
        with self._cv:
            return tuple(len(q) for q in self._levels)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
