"""Stable error codes for the wire boundary.

Re-expression of ``error_code/src/`` in the reference: every user-visible
error carries a spec-stable code ``KV:<Module>:<Name>`` so clients, logs, and
dashboards can match on codes instead of message strings.  The reference
generates a ``error_code.toml`` spec from the registered codes
(``error_code/src/lib.rs:87`` define_error_codes!); ``spec()`` here serves the
same artifact.

Codes attach to exceptions two ways:

* by *type*: ``register(exc_type, code)`` — used for the framework's own
  exception classes, resolved via ``code_of`` (walks the MRO so subclasses
  inherit their family's code);
* by *instance*: exceptions may set ``.error_code`` to override.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ErrorCode:
    code: str  # "KV:Raftstore:NotLeader"
    description: str

    @property
    def module(self) -> str:
        return self.code.split(":")[1]


_CODES: dict[str, ErrorCode] = {}
_BY_TYPE: dict[type, ErrorCode] = {}

UNKNOWN = ErrorCode("KV:Unknown", "unclassified error")


def define(code: str, description: str = "") -> ErrorCode:
    ec = ErrorCode(code, description)
    if code in _CODES:
        return _CODES[code]
    _CODES[code] = ec
    return ec


def register(exc_type: type, ec: ErrorCode) -> None:
    _BY_TYPE[exc_type] = ec


def code_of(exc: BaseException) -> str:
    override = getattr(exc, "error_code", None)
    if isinstance(override, ErrorCode):
        return override.code
    if isinstance(override, str):
        return override
    for klass in type(exc).__mro__:
        ec = _BY_TYPE.get(klass)
        if ec is not None:
            return ec.code
    return UNKNOWN.code


def spec() -> dict[str, str]:
    """code → description, the error_code.toml equivalent artifact."""
    return {c.code: c.description for c in _CODES.values()}


# --- the registry (error_code/src/{raftstore,storage,coprocessor}.rs) -------

RAFTSTORE_NOT_LEADER = define("KV:Raftstore:NotLeader", "peer is not the region leader")
RAFTSTORE_EPOCH_NOT_MATCH = define("KV:Raftstore:EpochNotMatch", "region epoch is stale")
RAFTSTORE_KEY_NOT_IN_REGION = define("KV:Raftstore:KeyNotInRegion", "key outside region range")
RAFTSTORE_DATA_NOT_READY = define("KV:Raftstore:DataIsNotReady", "safe-ts not advanced for stale read")
STORAGE_KEY_IS_LOCKED = define("KV:Storage:KeyIsLocked", "key locked by another transaction")
STORAGE_WRITE_CONFLICT = define("KV:Storage:WriteConflict", "write conflict at commit ts")
STORAGE_TXN_LOCK_NOT_FOUND = define("KV:Storage:TxnLockNotFound", "lock vanished before commit")
STORAGE_ALREADY_EXISTS = define("KV:Storage:AlreadyExist", "insert found an existing key")
STORAGE_COMMIT_EXPIRED = define("KV:Storage:CommitTsExpired", "commit ts below lock min_commit_ts")
STORAGE_PESSIMISTIC_LOCK_NOT_FOUND = define(
    "KV:Storage:PessimisticLockNotFound", "pessimistic lock missing at prewrite"
)
STORAGE_DEADLOCK = define("KV:Storage:Deadlock", "waits-for cycle detected")
COPR_PLUGIN = define("KV:Coprocessor:Plugin", "coprocessor plugin failure")
COPR_DEADLINE = define(
    "KV:Coprocessor:DeadlineExceeded", "request deadline expired before serving"
)
SERVER_IS_BUSY = define("KV:Server:IsBusy", "server shed the request under load")
ENGINE_FAILPOINT = define("KV:Engine:Failpoint", "injected failure")
CLOUD_IO = define("KV:Cloud:Io", "external storage failure")


def register_builtin() -> None:
    """Bind the framework's exception families to their codes (idempotent)."""
    from ..copr.plugin import PluginError
    from ..raft.region import EpochError, KeyNotInRegionError, NotLeaderError
    from ..server.lock_manager import DeadlockError
    from ..sidecar.cloud import CloudError
    from ..storage.mvcc.reader import KeyIsLockedError, WriteConflictError
    from ..storage.mvcc.txn import (
        AlreadyExistsError,
        CommitTsExpiredError,
        PessimisticLockNotFoundError,
        TxnLockNotFoundError,
    )
    from .failpoint import FailpointError
    from .retry import DeadlineExceeded, ServerBusyError

    register(DeadlineExceeded, COPR_DEADLINE)
    register(ServerBusyError, SERVER_IS_BUSY)
    register(NotLeaderError, RAFTSTORE_NOT_LEADER)
    register(EpochError, RAFTSTORE_EPOCH_NOT_MATCH)
    register(KeyNotInRegionError, RAFTSTORE_KEY_NOT_IN_REGION)
    register(KeyIsLockedError, STORAGE_KEY_IS_LOCKED)
    register(WriteConflictError, STORAGE_WRITE_CONFLICT)
    register(TxnLockNotFoundError, STORAGE_TXN_LOCK_NOT_FOUND)
    register(AlreadyExistsError, STORAGE_ALREADY_EXISTS)
    register(CommitTsExpiredError, STORAGE_COMMIT_EXPIRED)
    register(PessimisticLockNotFoundError, STORAGE_PESSIMISTIC_LOCK_NOT_FOUND)
    register(DeadlockError, STORAGE_DEADLOCK)
    register(PluginError, COPR_PLUGIN)
    register(FailpointError, ENGINE_FAILPOINT)
    register(CloudError, CLOUD_IO)
