"""IO rate limiting with priorities.

Re-expression of ``components/file_system`` (rate_limiter.rs:425
``IORateLimiter``: priority token budget with periodic refill; IO-type
tagging): callers request bytes before doing IO; high-priority requests are
served first, low priority waits when the epoch's budget is exhausted.
"""

from __future__ import annotations

import enum
import threading
import time


class IoPriority(enum.IntEnum):
    LOW = 0
    MEDIUM = 1
    HIGH = 2


class IoType(enum.Enum):
    FOREGROUND_READ = "foreground_read"
    FOREGROUND_WRITE = "foreground_write"
    FLUSH = "flush"
    COMPACTION = "compaction"
    REPLICATION = "replication"
    GC = "gc"
    IMPORT = "import"
    EXPORT = "export"


_DEFAULT_PRIORITY = {
    IoType.FOREGROUND_READ: IoPriority.HIGH,
    IoType.FOREGROUND_WRITE: IoPriority.HIGH,
    IoType.REPLICATION: IoPriority.HIGH,
    IoType.FLUSH: IoPriority.MEDIUM,
    IoType.COMPACTION: IoPriority.LOW,
    IoType.GC: IoPriority.LOW,
    IoType.IMPORT: IoPriority.MEDIUM,
    IoType.EXPORT: IoPriority.LOW,
}

_tls = threading.local()


def set_io_type(io_type: IoType) -> None:
    """Per-thread IO tag (the reference's set_io_type TLS)."""
    _tls.io_type = io_type


def get_io_type() -> IoType:
    return getattr(_tls, "io_type", IoType.FOREGROUND_WRITE)


class IoRateLimiter:
    """Token bucket refilled per epoch; HIGH priority is never throttled
    (foreground traffic), lower priorities wait for budget."""

    def __init__(self, bytes_per_sec: int = 0, refill_period: float = 0.05):
        self.bytes_per_sec = bytes_per_sec  # 0 = unlimited
        self.refill_period = refill_period
        self._mu = threading.Condition()
        self._budget = self._epoch_budget()
        self._epoch_start = time.monotonic()
        self.stats: dict[IoType, int] = {}

    def _epoch_budget(self) -> int:
        return int(self.bytes_per_sec * self.refill_period)

    def set_rate(self, bytes_per_sec: int) -> None:
        with self._mu:
            self.bytes_per_sec = bytes_per_sec
            self._budget = self._epoch_budget()
            self._mu.notify_all()

    def request(self, nbytes: int, io_type: IoType | None = None, timeout: float = 5.0) -> int:
        """Block until ``nbytes`` of budget is granted (or HIGH priority).
        Returns the granted bytes."""
        io_type = io_type or get_io_type()
        with self._mu:
            self.stats[io_type] = self.stats.get(io_type, 0) + nbytes
            if self.bytes_per_sec <= 0:
                return nbytes
            if _DEFAULT_PRIORITY[io_type] == IoPriority.HIGH:
                # high priority consumes budget but never blocks
                self._refill_locked()
                self._budget -= nbytes
                return nbytes
            deadline = time.monotonic() + timeout
            while True:
                self._refill_locked()
                # debt model (RocksDB-style): a request only needs the bucket
                # to be non-negative, then takes the whole grant — the bucket
                # goes into debt and later refills pay it back, so requests
                # larger than one epoch's budget still flow at the target rate
                if self._budget > 0:
                    self._budget -= nbytes
                    return nbytes
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # timed out: grant anyway (the reference degrades rather
                    # than starving background work forever)
                    self._budget -= nbytes
                    return nbytes
                self._mu.wait(min(self.refill_period, remaining))

    def _refill_locked(self) -> None:
        now = time.monotonic()
        if now - self._epoch_start >= self.refill_period:
            epochs = int((now - self._epoch_start) / self.refill_period)
            self._epoch_start += epochs * self.refill_period
            # refills pay back debt; credit caps at one epoch's budget
            self._budget = min(
                self._budget + epochs * self._epoch_budget(), self._epoch_budget()
            )
            self._mu.notify_all()
