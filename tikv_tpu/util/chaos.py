"""Chaos nemesis: seeded, deterministic fault injection over the raft plane.

Re-expression of the reference's nemesis layer — ``transport_simulate.rs``
filters composed into Jepsen-style schedules (the ``tests/failpoints/cases/``
suite drives the same machinery through the ``fail`` crate).  One
:class:`Nemesis` wraps a cluster's raft transport and injects:

* message **drop** (rate-based, optionally scoped to a region or an
  (src, dst) direction),
* message **delay** (held and re-injected later),
* message **duplication** and **reorder** (windowed shuffle),
* **asymmetric partitions** (A→B dropped while B→A flows) and symmetric
  ones,
* node **crash/restart** (delegating to the cluster harness),
* **disk stall** (the apply path wedged through the existing failpoints),

plus :meth:`heal`, which ends every fault, flushes held traffic, lifts
failpoints, and restarts crashed nodes — so every scenario ends in a state
the test can verify convergence from.

Works over BOTH cluster harnesses through their shared ``Filter`` API:

* :class:`~tikv_tpu.raft.cluster.Cluster` (in-memory ChannelTransport):
  fully deterministic.  Delays are measured in nemesis *steps*; the test
  pumps :meth:`Nemesis.advance` alongside ``cluster.tick()``.
* :class:`~tikv_tpu.server.cluster.ServerCluster` (framed TCP through
  ``RaftClient``): delays are wall-clock seconds, re-injection runs on a
  background delivery thread.  The *schedule* stays seeded/deterministic;
  thread interleaving is not (that is the point of the networked suite).

Determinism contract: every random decision (drop coin, delay draw, shuffle
order, schedule composition) comes from ONE ``random.Random(seed)``, so a
channel-mode scenario replays identically from its seed.

Re-injected (delayed/duplicated/reordered) messages bypass the filter stack
on purpose: a delay fault must not re-capture its own release, and raft
tolerates the resulting at-least-once delivery by design.

See ``docs/robustness.md`` for the scenario catalog.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..analysis.sanitizer import make_condition, make_lock
from . import failpoint
from .metrics import REGISTRY


def _count(fault: str) -> None:
    REGISTRY.counter(
        "tikv_chaos_injected_total", "Nemesis fault injections, by fault kind"
    ).inc(fault=fault)


# ---------------------------------------------------------------------------
# Faults
# ---------------------------------------------------------------------------

@dataclass
class Fault:
    """One active transport fault.  ``src``/``dst`` (store-id sets) scope
    directional faults; ``region_id`` scopes to one region's traffic."""

    kind: str                      # drop | delay | dup | reorder | partition
    rate: float = 1.0
    region_id: int | None = None
    src: frozenset | None = None   # match: from_peer.store_id in src
    dst: frozenset | None = None   # match: to_peer.store_id in dst
    delay: tuple[float, float] = (0.0, 0.0)  # seconds (server) / steps (channel)
    window: int = 4                # reorder shuffle window
    buf: list = field(default_factory=list, repr=False)  # reorder holding pen

    def matches(self, rmsg) -> bool:
        if self.region_id is not None and rmsg.region_id != self.region_id:
            return False
        if self.src is not None and rmsg.from_peer.store_id not in self.src:
            return False
        if self.dst is not None and rmsg.to_peer.store_id not in self.dst:
            return False
        return True


@dataclass
class _Held:
    due: float          # step count (channel) or monotonic seconds (server)
    seq: int
    to_store: int
    rmsg: object


class _NemesisFilter:
    """The transport-facing shim: one instance attached to every wrapped
    transport's ``filters`` list, delegating to the owning Nemesis."""

    def __init__(self, nemesis: "Nemesis"):
        self.nemesis = nemesis

    def before(self, rmsg) -> bool:
        return self.nemesis._on_send(rmsg)


# ---------------------------------------------------------------------------
# Cluster adapters
# ---------------------------------------------------------------------------

class _ChannelAdapter:
    """raft.cluster.Cluster: one shared ChannelTransport, logical time.

    Attaching also hooks every store's ``process_messages`` so each pump
    round advances the nemesis' step clock and re-injects due held
    messages — harness loops that pump internally (``RaftKv`` write/read
    barriers, admin waits, pre-existing ``pump=`` references) then make
    progress under delay faults without knowing a nemesis exists.  One
    step elapses per store-process call, so a delay of K steps spans
    roughly K/n_stores pump rounds — still fully deterministic.  Explicit
    :meth:`Nemesis.advance` remains available for hand-driven time."""

    realtime = False

    def __init__(self, cluster):
        self.cluster = cluster
        self._orig_pm: dict[int, object] = {}

    def attach(self, filt) -> None:
        self.cluster.transport.filters.append(filt)
        nemesis = filt.nemesis
        for sid, store in self.cluster.stores.items():
            orig = store.process_messages
            self._orig_pm[sid] = orig

            def pm(_orig=orig):
                nemesis.advance(1)
                return _orig()

            store.process_messages = pm

    def detach(self, filt) -> None:
        if filt in self.cluster.transport.filters:
            self.cluster.transport.filters.remove(filt)
        for sid, orig in self._orig_pm.items():
            store = self.cluster.stores.get(sid)
            if store is not None:
                store.process_messages = orig
        self._orig_pm.clear()

    def store_ids(self) -> list[int]:
        return list(self.cluster.stores)

    def reinject(self, to_store: int, rmsg) -> None:
        if to_store in self.cluster.stopped:
            return
        store = self.cluster.stores.get(to_store)
        if store is not None:
            store.enqueue_message(rmsg)

    def crash(self, store_id: int) -> None:
        self.cluster.stop_node(store_id)

    def restart(self, store_id: int) -> None:
        self.cluster.restart_node(store_id)


class _ServerAdapter:
    """server.cluster.ServerCluster: per-node RemoteTransports, wall clock."""

    realtime = True

    def __init__(self, cluster):
        self.cluster = cluster
        self._filter = None
        self._attached: list = []

    def attach(self, filt) -> None:
        self._filter = filt
        for node in self.cluster.nodes.values():
            node.transport.filters.append(filt)
            self._attached.append(node.transport)

    def detach(self, filt) -> None:
        for tr in self._attached:
            if filt in tr.filters:
                tr.filters.remove(filt)
        self._attached.clear()
        self._filter = None

    def store_ids(self) -> list[int]:
        return list(self.cluster.nodes)

    def reinject(self, to_store: int, rmsg) -> None:
        # below the filter stack: straight into the SENDER's connection pool
        frm = rmsg.from_peer.store_id
        node = self.cluster.nodes.get(frm)
        if node is None or not node.running:
            return
        node.transport.client.send(to_store, rmsg)

    def crash(self, store_id: int) -> None:
        self.cluster.stop_node(store_id)

    def restart(self, store_id: int) -> None:
        # a server-mode restart builds a NEW StoreNode (fresh transport):
        # the nemesis filter must follow it or the rebooted node's outbound
        # traffic would escape injection
        self.cluster.restart_node(store_id)
        if self._filter is not None:
            tr = self.cluster.nodes[store_id].transport
            tr.filters.append(self._filter)
            self._attached.append(tr)


class _NullAdapter:
    """No transport: a nemesis over ``cluster=None`` injects LOAD-shaped
    faults only (hot_tenant / slow_consumer / memory_squeeze) — useful for
    single-endpoint overload scenarios with no raft plane at all."""

    realtime = True

    def attach(self, filt) -> None:
        pass

    def detach(self, filt) -> None:
        pass

    def store_ids(self) -> list[int]:
        return []

    def reinject(self, to_store: int, rmsg) -> None:
        pass

    def crash(self, store_id: int) -> None:
        raise ValueError("no cluster attached to this nemesis")

    restart = crash


def _adapter_for(cluster):
    if cluster is None:
        return _NullAdapter()
    if hasattr(cluster, "nodes"):
        return _ServerAdapter(cluster)
    if hasattr(cluster, "transport"):
        return _ChannelAdapter(cluster)
    raise TypeError(f"unsupported cluster harness: {type(cluster).__name__}")


# ---------------------------------------------------------------------------
# The nemesis
# ---------------------------------------------------------------------------

_STALL_POINT = "apply_before_exec"  # the raft apply path's write gate


class Nemesis:
    def __init__(self, cluster, seed: int = 0):
        import random

        self.adapter = _adapter_for(cluster)
        self.rng = random.Random(seed)
        self.seed = seed
        self._mu = make_condition("util.chaos", make_lock("util.chaos"))
        self._faults: list[Fault] = []
        self._held: list[_Held] = []
        self._seq = 0
        self._step = 0              # logical clock (channel mode)
        self._crashed: set[int] = set()
        self._stalled: str | None = None
        # load-shaped faults (docs/robustness.md "Overload"): seeded flood
        # threads + squeezed cache budgets, all undone by heal()
        self._load_stop = threading.Event()
        self._load_threads: list[threading.Thread] = []
        self._squeezed: list[tuple[object, int, dict]] = []
        self._closed = False
        self._deliverer: threading.Thread | None = None
        self._filter = _NemesisFilter(self)
        self.adapter.attach(self._filter)
        # observability for test debugging
        self.stats = {"dropped": 0, "delayed": 0, "duplicated": 0,
                      "reordered": 0, "delivered_late": 0}

    # -- fault surface ------------------------------------------------------

    def _add(self, f: Fault) -> Fault:
        _count(f.kind)
        with self._mu:
            self._faults.append(f)
            self._mu.notify_all()
        return f

    def drop(self, rate: float = 1.0, region_id: int | None = None,
             src=None, dst=None) -> Fault:
        return self._add(Fault("drop", rate=rate, region_id=region_id,
                               src=_fset(src), dst=_fset(dst)))

    def delay(self, lo: float, hi: float, rate: float = 1.0,
              region_id: int | None = None, src=None, dst=None) -> Fault:
        """Hold matching messages for uniform(lo, hi) — seconds in server
        mode, :meth:`advance` steps in channel mode."""
        return self._add(Fault("delay", rate=rate, delay=(lo, hi),
                               region_id=region_id, src=_fset(src), dst=_fset(dst)))

    def duplicate(self, rate: float = 0.2, region_id: int | None = None) -> Fault:
        return self._add(Fault("dup", rate=rate, region_id=region_id))

    def reorder(self, window: int = 4, rate: float = 1.0,
                region_id: int | None = None) -> Fault:
        """Capture matching messages; every ``window`` captures release the
        pen in a seeded shuffle (at latest on heal/advance)."""
        return self._add(Fault("reorder", rate=rate, window=window,
                               region_id=region_id))

    def partition(self, side_a, side_b, symmetric: bool = True) -> list[Fault]:
        """Cut side_a → side_b (and the reverse when symmetric).  With
        ``symmetric=False`` this is the nasty half-open link: A's messages
        die while B still reaches A."""
        a, b = _fset(side_a), _fset(side_b)
        faults = [self._add(Fault("partition", src=a, dst=b))]
        if symmetric:
            faults.append(self._add(Fault("partition", src=b, dst=a)))
        return faults

    def isolate(self, store_id: int, incoming: bool = True,
                outgoing: bool = True) -> list[Fault]:
        others = [s for s in self.adapter.store_ids() if s != store_id]
        faults = []
        if outgoing:
            faults += self.partition({store_id}, others, symmetric=False)
        if incoming:
            faults += self.partition(others, {store_id}, symmetric=False)
        return faults

    def remove(self, fault) -> None:
        faults = fault if isinstance(fault, list) else [fault]
        with self._mu:
            for f in faults:
                if f in self._faults:
                    self._faults.remove(f)
                self._flush_reorder_locked(f)
            self._mu.notify_all()

    def crash(self, store_id: int) -> None:
        _count("crash")
        with self._mu:
            self._crashed.add(store_id)
        self.adapter.crash(store_id)

    def restart(self, store_id: int) -> None:
        _count("restart")
        with self._mu:
            self._crashed.discard(store_id)
        self.adapter.restart(store_id)

    def corrupt_image(self, cache, region_id: int | None = None,
                      mode: str | None = None, bits: int = 1):
        """Silent-data-corruption fault (docs/integrity.md): flip bits in a
        warm region image's DERIVED state — decoded cached block columns
        (``mode="block"``: the post-decode plane the device serves, caught
        by shadow reads and the deep scrub), the ENCODED payload of a
        compressed-resident column (``mode="encoded"``: bitpacked lanes /
        RLE run values, docs/compressed_columns.md — proves detection
        covers the encoded plane), or a buffered write-through
        pending delta (``mode="pending"``: a bad fold input, caught by the
        fingerprint-vs-oracle hash scrub).  Direct-injection like
        :meth:`disk_stall` — it targets a cache, not the transport — so it
        composes with any transport schedule.  Seeded off the nemesis rng;
        returns a description of what was corrupted, or None when nothing
        matched."""
        _count("corrupt_image")
        info = corrupt_image(cache, self.rng, region_id=region_id,
                             mode=mode, bits=bits)
        if info is not None:
            self.stats["corrupted"] = self.stats.get("corrupted", 0) + 1
        return info

    # -- load-shaped faults (docs/robustness.md "Overload") ------------------

    def hot_tenant(self, submit, qps: float = 200.0, tenant: str = "hot",
                   threads: int = 2, hold_s: float = 0.0,
                   fault: str = "hot_tenant") -> None:
        """One tenant floods the serving plane: seeded threads call
        ``submit(i, tenant)`` at ~``qps`` total until :meth:`heal` (every
        outcome — served, shed, error — is counted, never raised; the
        overload plane under test decides which it is).  Pacing draws from
        a per-thread rng DERIVED from the nemesis seed, so the schedule
        replays while live threads stay independent."""
        import random

        _count(fault)
        self.stats.setdefault(f"{fault}_requests", 0)
        self.stats.setdefault(f"{fault}_errors", 0)
        interval = threads / max(qps, 0.001)
        stop = self._load_stop

        def flood(idx: int):
            rng = random.Random(f"{self.seed}:{fault}:{idx}")
            i = 0
            while not stop.is_set():
                try:
                    submit(i, tenant)
                except Exception:  # noqa: BLE001 — shed/busy IS the point
                    with self._mu:
                        self.stats[f"{fault}_errors"] += 1
                else:
                    with self._mu:
                        self.stats[f"{fault}_requests"] += 1
                i += 1
                if hold_s:
                    # slow consumer: sit on the response/stream slot before
                    # asking for more — the client that drains too slowly
                    stop.wait(hold_s)
                stop.wait(interval * rng.uniform(0.5, 1.5))

        for idx in range(max(threads, 1)):
            t = threading.Thread(target=flood, args=(idx,), daemon=True,
                                 name=f"chaos-{fault}-{idx}")
            with self._mu:
                self._load_threads.append(t)
            t.start()

    def slow_consumer(self, submit, qps: float = 20.0, hold_s: float = 0.05,
                      tenant: str = "slow", threads: int = 1) -> None:
        """A tenant that consumes responses slowly: each ``submit`` is
        followed by a ``hold_s`` pause modelling a client sitting on its
        response before requesting more (the stream-backpressure shape)."""
        self.hot_tenant(submit, qps=qps, tenant=tenant, threads=threads,
                        hold_s=hold_s, fault="slow_consumer")

    def memory_squeeze(self, cache, fraction: float = 0.5) -> None:
        """Shrink a region column cache's byte budget (and every tenant
        partition) to ``fraction`` of its current value — memory pressure
        without traffic.  Enforcement (and the per-tenant degradation
        ladder) runs immediately; :meth:`heal` restores the budgets."""
        _count("memory_squeeze")
        with self._mu:
            self._squeezed.append((cache, cache.byte_budget,
                                   dict(cache._tenant_budgets)))
            self.stats["squeezed"] = self.stats.get("squeezed", 0) + 1
        cache.set_tenant_budgets({
            t: max(int(b * fraction), 1)
            for t, b in cache._tenant_budgets.items()
        })
        cache.resize_budget(max(int(cache.byte_budget * fraction), 1))

    def _stop_load_locked(self):
        threads, self._load_threads = self._load_threads, []
        squeezed, self._squeezed = self._squeezed, []
        return threads, squeezed

    def disk_stall(self, ms: float | None = None, count: int | None = None) -> None:
        """Wedge the apply path through the existing ``apply_before_exec``
        failpoint: ``ms`` → every apply sleeps that long (slow disk);
        ``ms=None`` → a hard pause until heal.  Process-global (failpoints
        are), so this models a cluster-wide slow/stuck disk."""
        _count("stall")
        action = "pause" if ms is None else f"sleep({ms})"
        if count is not None:
            action = f"{count}*{action}"
        with self._mu:
            self._stalled = _STALL_POINT
        failpoint.cfg(_STALL_POINT, action)

    # -- heal ---------------------------------------------------------------

    def heal(self) -> None:
        """End EVERY fault: clear the fault set, release held/penned
        messages, lift the disk stall, and restart crashed nodes.  After
        heal the transport is transparent again — convergence asserts run
        from here."""
        _count("heal")
        with self._mu:
            for f in self._faults:
                self._flush_reorder_locked(f)
            self._faults.clear()
            for h in self._held:
                h.due = 0.0  # everything is due now
            self._mu.notify_all()
            crashed = sorted(self._crashed)
            self._crashed.clear()
            stalled = self._stalled
            self._stalled = None
            # load faults end with everything else: flood threads stop,
            # squeezed budgets restore
            threads, squeezed = self._stop_load_locked()
            stop_evt = self._load_stop
            self._load_stop = threading.Event()
        stop_evt.set()
        for t in threads:
            t.join(timeout=2.0)
        # restore NEWEST-first: stacked squeezes of one cache snapshot the
        # already-squeezed budgets, so the earliest (true original)
        # snapshot must win
        for cache, byte_budget, tenant_budgets in reversed(squeezed):
            cache.set_tenant_budgets(tenant_budgets)
            cache.resize_budget(byte_budget)
        if stalled is not None:
            failpoint.remove(stalled)
        self._deliver_due(float("inf"))
        for sid in crashed:
            self.adapter.restart(sid)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()
            threads, _squeezed = self._stop_load_locked()
        self._load_stop.set()
        for t in threads:
            t.join(timeout=2.0)
        self.adapter.detach(self._filter)
        if self._deliverer is not None:
            self._deliverer.join(timeout=2.0)
            self._deliverer = None

    # -- logical time (channel mode) ----------------------------------------

    def advance(self, steps: int = 1) -> int:
        """Advance the nemesis' logical clock (channel mode): deliver held
        messages whose step came due, and flush any filled/stale reorder
        pens.  Returns how many messages were re-injected."""
        with self._mu:
            self._step += steps
            for f in self._faults:
                self._flush_reorder_locked(f)
            now = float(self._step)
        return self._deliver_due(now)

    # -- schedules ----------------------------------------------------------

    def random_steps(self, n: int, ops=("drop", "delay", "partition",
                                        "crash_restart", "dup", "reorder")):
        """A seeded schedule: n (op, kwargs) tuples drawn from ``ops``.
        Pure data — the caller applies them via :meth:`apply_step` with
        whatever pacing its harness needs — so a failing scenario replays
        from (seed, n, ops) alone."""
        import random

        # a DERIVED rng: the schedule must replay from (seed, n, ops) even
        # when live traffic has already consumed draws from self.rng
        rng = random.Random(f"{self.seed}:{n}:{sorted(ops)}")
        sids = self.adapter.store_ids()
        steps = []
        for _ in range(n):
            op = rng.choice(list(ops))
            if op == "drop":
                steps.append(("drop", {"rate": rng.uniform(0.1, 0.6)}))
            elif op == "delay":
                lo = rng.uniform(0.001, 0.01)
                steps.append(("delay", {"lo": lo, "hi": lo * 4,
                                        "rate": rng.uniform(0.2, 0.8)}))
            elif op == "dup":
                steps.append(("dup", {"rate": rng.uniform(0.1, 0.5)}))
            elif op == "reorder":
                steps.append(("reorder", {"window": rng.randint(2, 6)}))
            elif op == "partition":
                k = max(1, len(sids) // 2)
                side = rng.sample(sids, k)
                steps.append(("partition", {
                    "side_a": side,
                    "side_b": [s for s in sids if s not in side],
                    "symmetric": rng.random() < 0.5,
                }))
            elif op == "crash_restart":
                steps.append(("crash_restart", {"store_id": rng.choice(sids)}))
        return steps

    def apply_step(self, op: str, kw: dict):
        if op == "crash_restart":
            sid = kw["store_id"]
            if sid in self._crashed:
                self.restart(sid)
            else:
                self.crash(sid)
            return None
        if op == "dup":
            return self.duplicate(**kw)
        return getattr(self, op)(**kw)

    # -- the filter path ----------------------------------------------------

    def _on_send(self, rmsg) -> bool:
        """True = let the transport deliver; False = we dropped or took it."""
        with self._mu:
            if self._closed:
                return True
            for f in self._faults:
                if not f.matches(rmsg):
                    continue
                if f.kind == "partition":
                    self.stats["dropped"] += 1
                    _count("partition_drop")
                    return False
                if f.rate < 1.0 and self.rng.random() >= f.rate:
                    continue
                if f.kind == "drop":
                    self.stats["dropped"] += 1
                    _count("drop")
                    return False
                if f.kind == "dup":
                    self.stats["duplicated"] += 1
                    _count("dup")
                    self._hold_locked(rmsg, 0.0)
                    return True  # original delivers now, the copy follows
                if f.kind == "delay":
                    self.stats["delayed"] += 1
                    _count("delay")
                    self._hold_locked(rmsg, self.rng.uniform(*f.delay))
                    return False
                if f.kind == "reorder":
                    self.stats["reordered"] += 1
                    _count("reorder")
                    f.buf.append(rmsg)
                    if len(f.buf) >= f.window:
                        self._flush_reorder_locked(f)
                    return False
            return True

    # -- held-message plumbing ----------------------------------------------

    def _hold_locked(self, rmsg, delay: float) -> None:
        now = float(self._step) if not self.adapter.realtime else time.monotonic()
        self._seq += 1
        self._held.append(_Held(now + delay, self._seq,
                                rmsg.to_peer.store_id, rmsg))
        if self.adapter.realtime:
            self._ensure_deliverer_locked()
            self._mu.notify_all()

    def _flush_reorder_locked(self, f: Fault) -> None:
        if f.kind != "reorder" or not f.buf:
            return
        pen, f.buf = f.buf, []
        self.rng.shuffle(pen)
        for rmsg in pen:
            self._hold_locked(rmsg, 0.0)

    def _deliver_due(self, now: float) -> int:
        with self._mu:
            due = [h for h in self._held if h.due <= now]
            self._held = [h for h in self._held if h.due > now]
            due.sort(key=lambda h: (h.due, h.seq))
        for h in due:
            # outside the lock: re-injection walks the receiving store's
            # enqueue path (channel) or the sender's socket pool (server)
            self.adapter.reinject(h.to_store, h.rmsg)
            self.stats["delivered_late"] += 1
        return len(due)

    def _ensure_deliverer_locked(self) -> None:
        if self._deliverer is not None or self._closed:
            return
        self._deliverer = threading.Thread(
            target=self._deliver_loop, daemon=True, name="chaos-deliver"
        )
        self._deliverer.start()

    def _deliver_loop(self) -> None:
        while True:
            with self._mu:
                if self._closed:
                    return
                if not self._held:
                    self._mu.wait(0.5)
                    continue
                next_due = min(h.due for h in self._held)
                wait = next_due - time.monotonic()
                if wait > 0:
                    self._mu.wait(min(wait, 0.05))
                    continue
            self._deliver_due(time.monotonic())


def corrupt_image(cache, rng, region_id: int | None = None,
                  mode: str | None = None, bits: int = 1):
    """Flip bits in a resident region image (SDC injection core; see
    :meth:`Nemesis.corrupt_image`).  Mutates under the cache's manager lock
    and drops the image's device pins so the next warm serve re-pins the
    corrupted host state — modelling decode/fold/device corruption that the
    serving path would actually return."""
    import numpy as np

    with cache._mu:
        imgs = [(k, img) for k, img in cache._images.items()
                if region_id is None or k[0] == region_id]
        if not imgs:
            return None
        key, img = imgs[rng.randrange(len(imgs))]
        has_pending = bool(img.wt_pending and img.wt_pending["changed"])
        if mode is None:
            mode = "pending" if has_pending and rng.random() < 0.5 else "block"
        if mode == "pending":
            if not has_pending:
                return None
            pend = img.wt_pending
            handles = sorted(pend["changed"])
            h = handles[rng.randrange(len(handles))]
            v, cts = pend["changed"][h]
            if not v:
                return None
            ba = bytearray(v)
            for _ in range(max(bits, 1)):
                i = rng.randrange(len(ba))
                ba[i] ^= 1 << rng.randrange(8)
            pend["changed"][h] = (bytes(ba), cts)
            return {"mode": "pending", "region_id": key[0], "handle": int(h)}
        blocks = img.block_cache.blocks
        if not blocks:
            return None
        from ..copr.encoding import EncodedColumn

        for _ in range(64):  # retry until a corruptible cell is found
            bi = rng.randrange(len(blocks))
            blk = blocks[bi]
            if blk.n_valid == 0:
                continue
            ci = rng.randrange(len(blk.cols))
            col = blk.cols[ci]
            if mode == "encoded" and not isinstance(col, EncodedColumn):
                continue
            r = rng.randrange(blk.n_valid)
            if bool(np.asarray(col.nulls)[r]):
                continue
            if isinstance(col, EncodedColumn):
                # flip the ENCODED payload bytes — the resident form the
                # device actually serves (docs/compressed_columns.md); the
                # materialized decode cache is purged so host consumers
                # (deep scrub, late-materialize gathers) see the flip too
                if col.kind == "bp":
                    arr = col.packed
                    arr[r] ^= np.asarray(
                        1 << rng.randrange(max(arr.dtype.itemsize * 8 - 1, 1)),
                        dtype=arr.dtype)
                else:
                    run = int(np.searchsorted(col.run_ends, r, side="right"))
                    col.run_values[run] ^= np.int64(1) << np.int64(
                        rng.randrange(63))
                col.purge_decoded()
                blk.zones = None  # zone maps rebuild from the flipped bytes
                img.block_cache.drop_device()
                # mode="block" over an encoded column IS an encoded flip —
                # the payload is that column's resident block plane
                return {"mode": mode, "region_id": key[0], "block": bi,
                        "column": ci, "row": r, "kind": col.kind}
            data = col.data
            if col.is_dict_encoded:
                dlen = len(col.dictionary)
                if dlen < 2:
                    continue
                data[r] = (int(data[r]) + 1 + rng.randrange(dlen - 1)) % dlen
            elif isinstance(data, np.ndarray) and data.dtype == object:
                v = data[r]
                if not isinstance(v, (bytes, bytearray)) or len(v) == 0:
                    continue
                ba = bytearray(v)
                i = rng.randrange(len(ba))
                ba[i] ^= 1 << rng.randrange(8)
                data[r] = bytes(ba)
            else:
                arr = np.asarray(data)
                if arr.dtype.itemsize != 8:
                    continue
                # bit-flip through a u64 view (int64 and float64 alike);
                # bit 63 excluded so int corruption stays value-level, not
                # a sign explosion that might overflow downstream casts
                arr.view(np.uint64)[r] ^= np.uint64(1) << np.uint64(
                    rng.randrange(63))
            blk.zones = None  # zone maps rebuild from the flipped bytes
            img.block_cache.drop_device()
            return {"mode": "block", "region_id": key[0], "block": bi,
                    "column": ci, "row": r}
        return None


def _fset(v) -> frozenset | None:
    if v is None:
        return None
    if isinstance(v, (int,)):
        return frozenset((v,))
    return frozenset(v)
