"""Metrics registry with Prometheus text exposition.

Re-expression of the reference's prometheus-static-metric usage (every module
has a metrics.rs; served at /metrics by the status server): counters, gauges,
and histograms with labels, rendered in the Prometheus text format.
"""

from __future__ import annotations

import threading

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10)


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._mu = threading.Lock()


class Counter(_Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_, "counter")
        self._values: dict[tuple, float] = {}

    def inc(self, value: float = 1, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            self._values[key] = self._values.get(key, 0) + value

    def get(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._mu:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_fmt_labels(key)} {v}")
        if not items:
            lines.append(f"{self.name} 0")
        return "\n".join(lines)


class Gauge(Counter):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self.kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            self._values[key] = value


class Histogram(_Metric):
    def __init__(self, name, help_="", buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = {}
        self._n: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._mu:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sum[key] = self._sum.get(key, 0) + value
            self._n[key] = self._n.get(key, 0) + 1

    def count(self, **labels) -> int:
        """Observation count for a label set (the _count series)."""
        with self._mu:
            return self._n.get(tuple(sorted(labels.items())), 0)

    def percentile(self, q: float, **labels) -> float:
        """Bucket-interpolated percentile for a label set (``q`` in [0, 1]):
        the same estimate PromQL's histogram_quantile computes, locally.
        Returns 0.0 for an empty histogram; observations past the last
        finite bucket clamp to that bucket's bound (the +Inf bucket has no
        upper edge to interpolate toward)."""
        key = tuple(sorted(labels.items()))
        with self._mu:
            counts = list(self._counts.get(key, ()))
            n = self._n.get(key, 0)
        return percentile_from_buckets(self.buckets, counts, n, q)

    def total(self, **labels) -> float:
        """Accumulated observed value for a label set (the _sum series)."""
        with self._mu:
            return self._sum.get(tuple(sorted(labels.items())), 0.0)

    def label_sets(self) -> list[dict]:
        """The label sets observed so far (debug summaries)."""
        with self._mu:
            return [dict(key) for key in self._n]

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._mu:
            snapshot = [
                (key, list(counts), self._sum[key], self._n[key])
                for key, counts in sorted(self._counts.items())
            ]
        for key, counts, _s, _n in snapshot:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lines.append(f'{self.name}_bucket{_fmt_labels(key, le=str(b))} {cum}')
            cum += counts[-1]
            lines.append(f'{self.name}_bucket{_fmt_labels(key, le="+Inf")} {cum}')
            lines.append(f"{self.name}_sum{_fmt_labels(key)} {_s}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {_n}")
        return "\n".join(lines)


def percentile_from_buckets(buckets, counts, n: int, q: float) -> float:
    """Shared bucket-interpolation core behind :meth:`Histogram.percentile`
    and the observatory's windowed p50/p95/p99 accessors
    (copr/observatory.py): ``buckets`` are the finite upper bounds,
    ``counts`` the per-bucket (non-cumulative) counts with the +Inf
    overflow last, ``n`` the total observation count."""
    if n <= 0 or not counts:
        return 0.0
    q = min(max(q, 0.0), 1.0)
    rank = q * n
    cum = 0.0
    lower = 0.0
    for i, b in enumerate(buckets):
        c = counts[i] if i < len(counts) else 0
        if cum + c >= rank and c > 0:
            frac = (rank - cum) / c
            return lower + (b - lower) * frac
        cum += c
        lower = b
    # rank lands in the +Inf bucket: clamp to the last finite bound
    return float(buckets[-1]) if buckets else 0.0


def _fmt_labels(key: tuple, **extra) -> str:
    items = list(key) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets))

    def _get_or_create(self, name, factory):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def render(self) -> str:
        with self._mu:
            return "\n".join(m.render() for m in self._metrics.values()) + "\n"


REGISTRY = Registry()
