"""Shared backoff-retry policy for every cluster-facing client loop.

Re-expression of the reference's client retry machinery (client-go's
``retry/backoff.go`` and TiKV's own ``ServerIsBusy``/``NotLeader`` handling):
one policy object — exponential backoff with jitter, bounded attempts,
error-CLASS routing — replaces the divergent ad-hoc ``time.sleep`` loops that
grew in ``server/cluster.py``, ``raft/cluster.py`` and the raft-client
reconnect path.

Error classes (routed by exception type NAME so util never imports the
subsystems it serves):

``not_leader`` / ``epoch``
    Leadership moved / the region epoch is stale — always retryable; the
    next attempt re-routes.
``busy``
    ``ServerIsBusy``-style load shedding (``SchedTooBusy``,
    :class:`ServerBusyError`).  Retryable; when the exception carries a
    ``retry_after_s`` hint the retrier sleeps AT LEAST that long — the
    server knows its own drain time better than our backoff curve does.
``timeout``
    A bounded wait elapsed (no leader yet, admin command stalled).
    Retryable: partitions heal and elections finish.
``data_not_ready``
    ``RaftKv.DataNotReadyError`` — a follower stale read above the region's
    resolved-ts watermark (docs/stale_reads.md).  Retryable: the watermark
    only ever advances.  The backoff is WATERMARK-AWARE: the exception
    carries the ``resolved`` ts it was refused against, and the sleep grows
    with the lag (``read_ts - resolved``) so a barely-behind replica is
    re-probed quickly while a far-behind one is not hammered.
``suspect``
    ``AssertionError`` / ``KeyError`` — historically retried wholesale by
    the cluster clients, which masked real bugs.  Still retryable (routing
    races genuinely raise them) but under a SEPARATE, tighter attempt bound,
    and the final failure is logged with the exception chain.
``deadline``
    :class:`DeadlineExceeded` — never retried: the caller's budget is gone.
``permanent``
    Everything unrouted.  Never retried.

See ``docs/robustness.md``.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field

logger = logging.getLogger("tikv_tpu.retry")


class DeadlineExceeded(Exception):
    """The request's deadline expired before it could be served.  Carried
    end-to-end: admission control and the copr scheduler lanes raise it for
    already-expired work instead of wasting a device dispatch."""


class ServerBusyError(Exception):
    """ServerIsBusy analog: the server shed this request under load.  The
    optional ``retry_after_s`` hint tells clients when capacity is expected
    back (honored by :class:`Retrier`)."""

    def __init__(self, msg: str = "server is busy", retry_after_s: float | None = None):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


# exception type name -> error class (name-based: no subsystem imports; an
# exception may override with an explicit ``retry_class`` attribute)
ROUTES: dict[str, str] = {
    "NotLeaderError": "not_leader",
    "EpochError": "epoch",
    "EpochNotMatchError": "epoch",
    "SchedTooBusy": "busy",
    "ServerBusyError": "busy",
    "TimeoutError": "timeout",
    "DeadlineExceeded": "deadline",
    "AssertionError": "suspect",
    "KeyError": "suspect",
    # a stale read refused above the watermark is a WAIT, not a failure:
    # before PR 7 this fell through to "permanent" and clients never
    # retried a read the next advance round would have served
    "DataNotReadyError": "data_not_ready",
}

RETRYABLE_CLASSES = {"not_leader", "epoch", "busy", "timeout", "suspect",
                     "data_not_ready"}

#: physical TSO encoding (TiKV composes ms<<18 | logical); a lag with any
#: bit at/above the shift is wall-clock milliseconds, a small integer lag is
#: a logical test clock
TSO_PHYSICAL_SHIFT = 18


def data_not_ready_hint(exc: BaseException) -> float | None:
    """A ``retry_after_s``-style sleep derived from the watermark lag the
    refusal reported.  Physical TSO lags convert exactly (the watermark
    trails real time, so the wait IS the lag); logical-clock lags (unit
    test TSOs) pace at ~1ms per unit.  Both are capped — the exponential
    curve still provides the long-tail growth."""
    read_ts = getattr(exc, "read_ts", None)
    resolved = getattr(exc, "resolved", None)
    if read_ts is None or resolved is None:
        return None
    lag = max(int(read_ts) - int(resolved), 0)
    if lag >> TSO_PHYSICAL_SHIFT:
        return min((lag >> TSO_PHYSICAL_SHIFT) / 1000.0, 1.0)
    return min(0.001 * lag, 0.1)


def classify(exc: BaseException) -> str:
    """The error class an exception routes to (``permanent`` if unrouted)."""
    override = getattr(exc, "retry_class", None)
    if isinstance(override, str):
        return override
    for klass in type(exc).__mro__:
        cls = ROUTES.get(klass.__name__)
        if cls is not None:
            return cls
    return "permanent"


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with decorrelating jitter and bounded attempts.

    ``max_attempts`` bounds the TOTAL failures absorbed (0 = unbounded, the
    deadline is then the only stop); ``class_attempts`` tightens individual
    classes — by default the ``suspect`` class (AssertionError/KeyError,
    which can mask real bugs) gets a much shorter leash."""

    base_s: float = 0.02
    max_s: float = 1.0
    multiplier: float = 2.0
    jitter: float = 0.2          # +/- fraction of the computed backoff
    max_attempts: int = 0        # 0 = unbounded (deadline-bound only)
    class_attempts: dict = field(default_factory=lambda: {"suspect": 16})

    def backoff(self, attempt: int, rng: random.Random | None = None) -> float:
        """Sleep before retry number ``attempt`` (1-based)."""
        raw = min(self.base_s * (self.multiplier ** max(attempt - 1, 0)),
                  self.max_s)
        # jitter AFTER the ceiling clamp: once the curve saturates, clamping
        # a jittered value collapses every caller to exactly max_s — N
        # stores probing one restarted peer would reconnect in lockstep,
        # which is the scenario the jitter exists to break up
        if self.jitter and rng is not None:
            raw *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(raw, 0.0)


#: the project default: ~20ms..1s exponential, suspect errors capped at 16
DEFAULT_POLICY = RetryPolicy()

#: reconnect flavor for the raft client's per-store connections: quicker
#: first probe than the old constant 0.5s, exponential toward a bounded
#: ceiling so a dead store is probed, not hammered — and a restarted one is
#: re-reached within one ceiling interval
RECONNECT_POLICY = RetryPolicy(base_s=0.1, max_s=2.0, jitter=0.25)


class Retrier:
    """Per-operation retry state: feed it failures, it answers with the
    sleep before the next attempt or ``None`` for "stop, re-raise".

    ``deadline`` is absolute ``time.monotonic()`` seconds; sleeps are
    clipped to the remaining budget and a spent budget stops retrying."""

    def __init__(
        self,
        policy: RetryPolicy = DEFAULT_POLICY,
        deadline: float | None = None,
        rng: random.Random | None = None,
        site: str = "",
        clock=time.monotonic,
    ):
        self.policy = policy
        self.deadline = deadline
        self.rng = rng or random.Random()
        self.site = site
        self.clock = clock
        self.attempts = 0
        self.by_class: dict[str, int] = {}
        self.last_exc: BaseException | None = None

    def should_retry(self, exc: BaseException) -> float | None:
        """None = give up (caller re-raises); else seconds to sleep."""
        cls = classify(exc)
        self.last_exc = exc
        self.attempts += 1
        self.by_class[cls] = self.by_class.get(cls, 0) + 1
        self._count(cls)
        if cls not in RETRYABLE_CLASSES:
            return None
        cap = self.policy.class_attempts.get(cls, 0)
        if cap and self.by_class[cls] > cap:
            if cls == "suspect":
                logger.warning(
                    "retry[%s]: giving up after %d suspect failures "
                    "(AssertionError/KeyError may mask a real bug): %r",
                    self.site, self.by_class[cls], exc,
                )
            return None
        if self.policy.max_attempts and self.attempts >= self.policy.max_attempts:
            return None
        delay = self.policy.backoff(self.attempts, self.rng)
        hint = getattr(exc, "retry_after_s", None)
        if hint is None and cls == "data_not_ready":
            # no explicit hint: derive one from the watermark lag the
            # refusal carried (the ``resolved`` ts on the exception)
            hint = data_not_ready_hint(exc)
        if hint is not None:
            # the server's own drain estimate dominates our curve
            delay = max(delay, float(hint))
        if self.deadline is not None:
            remaining = self.deadline - self.clock()
            if remaining <= 0:
                return None
            delay = min(delay, remaining)
        return delay

    def _count(self, cls: str) -> None:
        from .metrics import REGISTRY

        REGISTRY.counter(
            "tikv_client_retry_total",
            "Client retry-loop failures absorbed, by call site and error class",
        ).inc(site=self.site or "unknown", error_class=cls)


def call(
    fn,
    *,
    policy: RetryPolicy = DEFAULT_POLICY,
    timeout: float | None = None,
    site: str = "",
    sleep=time.sleep,
    rng: random.Random | None = None,
    clock=time.monotonic,
):
    """Run ``fn()`` under the retry policy until it succeeds, the error is
    non-retryable, attempts exhaust, or ``timeout`` seconds elapse.  The
    LAST exception re-raises — never a synthetic wrapper, so callers keep
    matching on the real error types."""
    deadline = None if timeout is None else clock() + timeout
    r = Retrier(policy, deadline=deadline, rng=rng, site=site, clock=clock)
    while True:
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classify() routes
            delay = r.should_retry(exc)
            if delay is None:
                raise
            sleep(delay)


def wait_until(
    pred,
    timeout: float,
    interval: float = 0.02,
    desc: str = "condition",
    sleep=time.sleep,
    clock=time.monotonic,
):
    """Poll ``pred()`` until it returns a truthy value; raise TimeoutError
    after ``timeout`` seconds.  The ONE wait-for-condition loop the cluster
    harnesses share (wait_leader / wait_applied / wait_get...)."""
    deadline = clock() + timeout
    while True:
        v = pred()
        if v:
            return v
        if clock() >= deadline:
            raise TimeoutError(f"{desc} not reached within {timeout}s")
        sleep(min(interval, max(deadline - clock(), 0.0)))


def deadline_from_context(ctx: dict | None, clock=time.monotonic) -> float | None:
    """Resolve a request context's deadline to absolute monotonic seconds.

    Two spellings: ``deadline`` (absolute monotonic — in-process callers) and
    ``timeout_ms`` (relative budget — wire clients can't share our clock;
    the service layer stamps the absolute deadline at parse time)."""
    if not ctx:
        return None
    d = ctx.get("deadline")
    if d is not None:
        return float(d)
    t = ctx.get("timeout_ms")
    if t is not None:
        return clock() + float(t) / 1000.0
    return None
